"""Measure the MCTS design bets (VERDICT.md round-2 task 6).

Three deliberate departures from the reference's C++ search are
quantified here on the tiny board (fast enough for CPU; the relative
signals, not absolute scores, are what the bets are about):

(a) **No subtree reuse** (reference re-roots the previous tree each
    move, `alphatriangle/rl/self_play/worker.py:273-280`; we re-search
    from scratch with fresh root priors, `mcts/search.py` module doc).
    Measured as the score-vs-simulation-budget curve: the value of
    reuse is bounded by the marginal value of extra simulations, so a
    flat curve past the operating point (64 sims) means reuse would buy
    little; a steep curve means it matters.

(b) **Root-value bootstrap** (we bootstrap the n-step window with the
    search's backed-up root value; the reference queries the raw
    network, `worker.py:418`). Measured as MSE of each predictor
    against the realized discounted return-to-go of the played games.

(c) **Orphan node slots** (duplicate edges inside a wave burn a slot,
    `mcts/search.py` `wasted_slots`). Measured as the wasted-simulation
    fraction per wave size at the 64-sim budget.

Usage:  JAX_PLATFORMS=cpu python benchmarks/mcts_design.py
Writes benchmarks/mcts_design_results.json and prints a summary; the
prose writeup lives in docs/MCTS_DESIGN.md.
"""

import json
import os
import sys
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from alphatriangle_tpu.config import (
    AlphaTriangleMCTSConfig,
    EnvConfig,
    ModelConfig,
    expected_other_features_dim,
)
from alphatriangle_tpu.env.engine import TriangleEnv
from alphatriangle_tpu.features.core import get_feature_extractor
from alphatriangle_tpu.mcts import BatchedMCTS
from alphatriangle_tpu.nn.network import NeuralNetwork

B = 64  # games per condition
MAX_MOVES = 60
SEEDS = (0, 1)


def tiny_world():
    env_cfg = EnvConfig(
        ROWS=3,
        COLS=4,
        PLAYABLE_RANGE_PER_ROW=[(0, 4), (0, 4), (0, 4)],
        NUM_SHAPE_SLOTS=1,
    )
    model_cfg = ModelConfig(
        GRID_INPUT_CHANNELS=1,
        CONV_FILTERS=[8],
        CONV_KERNEL_SIZES=[3],
        CONV_STRIDES=[1],
        NUM_RESIDUAL_BLOCKS=1,
        RESIDUAL_BLOCK_FILTERS=8,
        USE_TRANSFORMER=False,
        FC_DIMS_SHARED=[16],
        POLICY_HEAD_DIMS=[16],
        VALUE_HEAD_DIMS=[16],
        NUM_VALUE_ATOMS=21,
        OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(env_cfg),
    )
    env = TriangleEnv(env_cfg)
    fe = get_feature_extractor(env, model_cfg)
    net = NeuralNetwork(model_cfg, env_cfg, seed=0)
    return env, fe, net, model_cfg


def rollout(env, fe, net, mcts, seed, record_values=False, b=B, max_moves=MAX_MOVES):
    """Play B games to completion with greedy-from-visits moves.

    Returns (mean_score, wasted_fraction, value_records) where
    value_records rows are (root_value, raw_value, reward, done) per
    (move, game) for the bootstrap comparison.
    """
    states = env.reset_batch(jax.random.split(jax.random.PRNGKey(seed), b))
    total_sims = 0
    total_wasted = 0
    recs = []
    for move in range(max_moves):
        done = np.asarray(states.done)
        if done.all():
            break
        out = mcts.search(
            net.variables, states, jax.random.PRNGKey(seed * 1000 + move)
        )
        counts = np.asarray(out.visit_counts)
        live = ~done
        total_sims += int(live.sum()) * mcts.config.max_simulations
        total_wasted += int(np.asarray(out.wasted_slots)[live].sum())
        actions = np.where(counts.sum(axis=1) > 0, counts.argmax(axis=1), 0)
        if record_values:
            _, raw_values, _ = mcts._evaluate(net.variables, states)
            root_v = np.asarray(out.root_value)
            raw_v = np.asarray(raw_values)
        states, rewards, _ = env.step_batch(
            states, jnp.asarray(actions, dtype=jnp.int32)
        )
        if record_values:
            recs.append(
                np.stack(
                    [root_v, raw_v, np.asarray(rewards), live.astype(float)],
                    axis=1,
                )
            )
    scores = float(np.asarray(states.score).mean())
    wasted_frac = total_wasted / max(total_sims, 1)
    return scores, wasted_frac, recs


def bootstrap_mse(recs):
    """MSE of root-value vs raw-value predictions of return-to-go."""
    arr = np.stack(recs)  # (T, B, 4): root_v, raw_v, reward, live
    t_len = arr.shape[0]
    g = np.zeros(arr.shape[1])
    returns = np.zeros((t_len, arr.shape[1]))
    for t in range(t_len - 1, -1, -1):
        g = arr[t, :, 2] + g  # discount=1.0 in these runs
        returns[t] = g
    live = arr[:, :, 3] > 0
    root_err = ((arr[:, :, 0] - returns) ** 2)[live]
    raw_err = ((arr[:, :, 1] - returns) ** 2)[live]
    return float(root_err.mean()), float(raw_err.mean()), int(live.sum())


def main() -> None:
    env, fe, net, model_cfg = tiny_world()
    results: dict = {"board": "3x4/1-slot", "games_per_condition": B * len(SEEDS)}

    # (a) score vs simulation budget (no-reuse bet).
    curve = {}
    for sims in (8, 16, 32, 64, 128):
        cfg = AlphaTriangleMCTSConfig(
            max_simulations=sims, max_depth=8, mcts_batch_size=32
        )
        mcts = BatchedMCTS(env, fe, net.model, cfg, net.support)
        t0 = time.time()
        scores = [rollout(env, fe, net, mcts, s)[0] for s in SEEDS]
        curve[sims] = {
            "mean_score": round(float(np.mean(scores)), 3),
            "per_seed": [round(s, 3) for s in scores],
            "seconds": round(time.time() - t0, 1),
        }
        print(f"(a) sims={sims}: {curve[sims]}", flush=True)
    results["score_vs_sims"] = curve

    # (b) bootstrap quality: root value vs raw network value.
    cfg = AlphaTriangleMCTSConfig(
        max_simulations=64, max_depth=8, mcts_batch_size=32
    )
    mcts = BatchedMCTS(env, fe, net.model, cfg, net.support)
    root_mses, raw_mses = [], []
    for s in SEEDS:
        _, _, recs = rollout(env, fe, net, mcts, 100 + s, record_values=True)
        root_mse, raw_mse, n = bootstrap_mse(recs)
        root_mses.append(root_mse)
        raw_mses.append(raw_mse)
        print(f"(b) seed={s}: root_mse={root_mse:.3f} raw_mse={raw_mse:.3f} n={n}", flush=True)
    results["bootstrap_mse"] = {
        "root_value": round(float(np.mean(root_mses)), 3),
        "raw_network": round(float(np.mean(raw_mses)), 3),
    }

    # (c) wasted-slot fraction by wave size at the 64-sim budget.
    # NOTE: on the tiny board the reachable tree under a root often has
    # fewer nodes than the simulation budget, so most sims necessarily
    # revisit (tree exhausted) — the flagship section below is the
    # honest operating-point number.
    waste = {}
    for wave in (1, 8, 16, 32, 64):
        cfg = AlphaTriangleMCTSConfig(
            max_simulations=64, max_depth=8, mcts_batch_size=wave
        )
        mcts = BatchedMCTS(env, fe, net.model, cfg, net.support)
        fracs = [rollout(env, fe, net, mcts, 200 + s)[1] for s in SEEDS]
        waste[wave] = round(float(np.mean(fracs)), 4)
        print(f"(c) wave={wave}: wasted_frac={waste[wave]}", flush=True)
    results["wasted_slot_fraction_by_wave_tiny"] = waste

    # (c') flagship board (8x15, 3 slots, action_dim 360): the real
    # operating point. Smaller B and a move cap keep CPU time sane.
    if os.environ.get("DESIGN_FLAGSHIP", "1") == "1":
        f_env_cfg = EnvConfig()
        f_model_cfg = ModelConfig(
            GRID_INPUT_CHANNELS=1,
            CONV_FILTERS=[8],
            CONV_KERNEL_SIZES=[3],
            CONV_STRIDES=[1],
            NUM_RESIDUAL_BLOCKS=1,
            RESIDUAL_BLOCK_FILTERS=8,
            USE_TRANSFORMER=False,
            FC_DIMS_SHARED=[16],
            POLICY_HEAD_DIMS=[16],
            VALUE_HEAD_DIMS=[16],
            NUM_VALUE_ATOMS=21,
            OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(
                f_env_cfg
            ),
        )
        f_env = TriangleEnv(f_env_cfg)
        f_fe = get_feature_extractor(f_env, f_model_cfg)
        f_net = NeuralNetwork(f_model_cfg, f_env_cfg, seed=0)
        fwaste = {}
        for wave in (1, 32):
            cfg = AlphaTriangleMCTSConfig(
                max_simulations=64, max_depth=8, mcts_batch_size=wave
            )
            mcts = BatchedMCTS(f_env, f_fe, f_net.model, cfg, f_net.support)
            t0 = time.time()
            _, frac, _ = rollout(
                f_env, f_fe, f_net, mcts, 300, b=8, max_moves=12
            )
            fwaste[wave] = {
                "wasted_frac": round(frac, 4),
                "seconds": round(time.time() - t0, 1),
            }
            print(f"(c') flagship wave={wave}: {fwaste[wave]}", flush=True)
        results["wasted_slot_fraction_by_wave_flagship"] = fwaste

        # (c'') wave_noise_scale sweep at wave=32: the knob that trades
        # descent diversity (fewer duplicate edges) against PUCT
        # fidelity (noise perturbs the argmax).
        nsweep = {}
        for noise in (0.0, 0.1, 0.25, 0.5, 1.0):
            cfg = AlphaTriangleMCTSConfig(
                max_simulations=64,
                max_depth=8,
                mcts_batch_size=32,
                wave_noise_scale=noise,
            )
            mcts = BatchedMCTS(f_env, f_fe, f_net.model, cfg, f_net.support)
            score, frac, _ = rollout(
                f_env, f_fe, f_net, mcts, 400, b=8, max_moves=12
            )
            nsweep[str(noise)] = {
                "wasted_frac": round(frac, 4),
                "mean_score_12_moves": round(score, 2),
            }
            print(f"(c'') noise={noise}: {nsweep[str(noise)]}", flush=True)
        results["flagship_noise_sweep_wave32"] = nsweep

    out_path = Path(__file__).parent / "mcts_design_results.json"
    out_path.write_text(json.dumps(results, indent=2))
    print(json.dumps(results))


if __name__ == "__main__":
    main()
