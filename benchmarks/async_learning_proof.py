"""Learning proof for the OVERLAPPED topology: the pipelined async
loop (producer threads + replay-ratio-gated, double-buffered learner)
doesn't just run — it learns.

The round-3 learning A/Bs (benchmarks/learning_curve.py) drove the
engine and trainer directly, synchronously. This harness trains the
same 4x6/2-slot small-board world through the REAL `TrainingLoop` in
overlapped mode — `ASYNC_ROLLOUTS` + `PIPELINE_LEARNER` + fused groups
+ 2 rollout streams + the flagship Gumbel+PCR search recipe — then
scores the trained net against the untrained baseline with the same
fixed greedy-PUCT evaluator the round-3 curves used.

Usage:  python benchmarks/async_learning_proof.py   (CPU harness: the
        platform is forced to CPU — like learning_curve.py — so the
        numbers stay comparable across hosts)
Env:    PROOF_STEPS=N (default 1500), PROOF_EVAL_GAMES=N (default 256)
Writes benchmarks/async_learning_results.json.
"""

import json
import os
import sys
import time
from pathlib import Path

import jax

jax.config.update("jax_platforms", "cpu")
# XLA:CPU persistent-cache RELOADS of donating programs silently return
# unchanged outputs in this image (see tests/conftest.py) — a cached
# learner step here would fake a flat learning curve; never enable it.

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "benchmarks"))

# Shared with learning_curve.py: evaluator AND world configs, so this
# row stays locked to the round-3 curves' yardstick.
from learning_curve import (  # noqa: E402
    curve_model,
    greedy_eval,
    small_board_env,
)

from alphatriangle_tpu.config import (  # noqa: E402
    AlphaTriangleMCTSConfig,
    PersistenceConfig,
    TrainConfig,
)
from alphatriangle_tpu.mcts import BatchedMCTS  # noqa: E402
from alphatriangle_tpu.training import (  # noqa: E402
    LoopStatus,
    TrainingLoop,
    setup_training_components,
)


def run_proof(
    topology: str,
    out_name: str,
    run_name: str,
    default_root: str,
    train_overrides: "dict | None" = None,
    mesh_config=None,
    post_setup=None,
    extra_payload=None,
) -> dict:
    """Shared proof scaffolding: one world, one recipe, ONE fixed
    evaluator for every topology variant — the 'apples-to-apples'
    claim across learning_curve.py, this file and
    sharded_learning_proof.py holds exactly because this is the single
    copy of the configs and the before/after protocol.

    `train_overrides` parameterizes the topology under test;
    `post_setup(c)` asserts the intended components were built;
    `extra_payload(c, loop)` adds topology-specific result fields.
    """
    steps = int(os.environ.get("PROOF_STEPS", "1500"))
    eval_games = int(os.environ.get("PROOF_EVAL_GAMES", "256"))

    env_cfg = small_board_env()
    model_cfg = curve_model(env_cfg)
    # The measured flagship recipe at small-board scale (matches the
    # winning LEARN_GUMBEL=1 LEARN_PCR=1 arm in BASELINE.md).
    mcts_cfg = AlphaTriangleMCTSConfig(
        max_simulations=16,
        max_depth=6,
        mcts_batch_size=8,
        root_selection="gumbel",
        gumbel_m=8,
        fast_simulations=4,
    )
    train_kw = dict(
        SELF_PLAY_BATCH_SIZE=32,
        ROLLOUT_CHUNK_MOVES=4,
        BATCH_SIZE=64,
        BUFFER_CAPACITY=20_000,
        MIN_BUFFER_SIZE_TO_TRAIN=512,
        MAX_TRAINING_STEPS=steps,
        WORKER_UPDATE_FREQ_STEPS=10,
        LEARNING_RATE=1e-3,
        N_STEP_RETURNS=3,
        TEMPERATURE_ANNEAL_MOVES=8,
        # The overlapped topology under test (variants override).
        ASYNC_ROLLOUTS=True,
        PIPELINE_LEARNER=True,
        FUSED_LEARNER_STEPS=4,
        NUM_SELF_PLAY_WORKERS=2,
        REPLAY_RATIO=1.0,
        AUTO_RESUME_LATEST=False,
        CHECKPOINT_SAVE_FREQ_STEPS=100_000,  # not under test
        RUN_NAME=run_name,
    )
    train_kw.update(train_overrides or {})
    train_cfg = TrainConfig(**train_kw)
    root = Path(os.environ.get("PROOF_ROOT", default_root))
    c = setup_training_components(
        train_config=train_cfg,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        mesh_config=mesh_config,
        persistence_config=PersistenceConfig(
            ROOT_DATA_DIR=str(root), RUN_NAME=run_name
        ),
        use_tensorboard=False,
    )
    if post_setup is not None:
        post_setup(c)

    # Fixed evaluator: greedy PUCT-16, 60-move games averaged over
    # seeds 11 and 22 — EXACTLY learning_curve.py's run_eval, so every
    # proof row is apples-to-apples with the round-3 curves in
    # BASELINE.md.
    eval_mcts_cfg = AlphaTriangleMCTSConfig(
        max_simulations=16, max_depth=6, mcts_batch_size=8,
        dirichlet_epsilon=0.0,
    )

    def evaluate(net) -> float:
        mcts = BatchedMCTS(
            c.env, c.extractor, net.model, eval_mcts_cfg, net.support
        )
        return float(
            sum(
                greedy_eval(c.env, net, mcts, eval_games, 60, s)
                for s in (11, 22)
            )
            / 2
        )

    # Baseline = the SAME net the loop will train (seeded by
    # TrainConfig.RANDOM_SEED), evaluated before any training — the
    # before/after delta measures training, not an init lottery.
    before = evaluate(c.net)
    print(f"untrained greedy eval: {before:.2f}", flush=True)

    t0 = time.time()
    loop = TrainingLoop(c)
    status = loop.run()
    train_seconds = time.time() - t0
    assert status == LoopStatus.COMPLETED, status
    c.trainer.sync_to_network()

    after = evaluate(c.net)
    print(f"trained greedy eval: {after:.2f}", flush=True)

    payload = {
        "topology": topology,
        "steps": loop.global_step,
        "train_seconds": round(train_seconds, 1),
        "steps_per_sec": round(loop.global_step / train_seconds, 2),
        "episodes_played": loop.episodes_played,
        "experiences": loop.experiences_added,
        "achieved_replay_ratio": round(
            loop._steps_this_run
            * train_cfg.BATCH_SIZE
            / max(loop.experiences_added, 1),
            3,
        ),
        "tuned_chunk_moves": loop._tuned_chunk_moves,
        "eval_games": eval_games,
        "untrained_eval": round(before, 2),
        "trained_eval": round(after, 2),
        "improvement_pct": round(100 * (after - before) / max(before, 1e-9), 1),
    }
    if extra_payload is not None:
        payload.update(extra_payload(c, loop))
    out = REPO / "benchmarks" / out_name
    out.write_text(json.dumps(payload, indent=2))
    print(json.dumps(payload))
    c.stats.close()
    c.checkpoints.close()
    return payload


def main() -> int:
    run_proof(
        topology="overlapped: pipelined learner + auto-chunk + "
        "2 streams + fused groups + Gumbel+PCR",
        out_name="async_learning_results.json",
        run_name="async_proof",
        default_root="/tmp/async_proof",
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
