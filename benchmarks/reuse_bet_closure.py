"""Close the subtree-reuse bet at flagship scale (round-5 VERDICT #6).

`docs/MCTS_DESIGN.md` §a dropped the reference's subtree reuse
(`alphatriangle/rl/self_play/worker.py:273-280`) on a measured
argument: the value of reuse is bounded by the marginal value of extra
simulations, and the score-vs-sims curve was flat past the 64-sim
operating point. That measurement was CPU, tiny-board, UNTRAINED net —
and the doc's own criterion says to revisit if a trained net steepens
the curve. This harness reruns the curve with a TRAINED checkpoint on
the run's own (flagship) board.

Reading the result: reuse can at best make an S-sim search as strong
as an (S + carried) sim search. If score(128) ~ score(64) with the
trained net, reuse still buys nothing at the operating point and the
no-reuse design stands; a steep 64->128 slope reopens it.

Usage (healthy-chip window, after the training run):
    python benchmarks/reuse_bet_closure.py \
        --run-name tpu_flagship_r5 --root-dir /tmp/tpu_r5_train
Writes benchmarks/reuse_bet_results.json.
"""

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from alphatriangle_tpu.utils.helpers import enforce_platform  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--run-name", default=None)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--root-dir", default=None)
    ap.add_argument("--games", type=int, default=64)
    ap.add_argument("--max-moves", type=int, default=200)
    ap.add_argument("--sims", default="16,32,64,128")
    ap.add_argument("--seeds", default="0,1")
    ap.add_argument("--device", default=None)
    args = ap.parse_args()
    if not (args.run_name or args.checkpoint):
        ap.error("need --run-name or --checkpoint (a TRAINED net)")

    enforce_platform(args.device or "auto")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from alphatriangle_tpu.config import (
        AlphaTriangleMCTSConfig,
        PersistenceConfig,
        TrainConfig,
    )
    from alphatriangle_tpu.config.run_configs import (
        load_run_configs_or_default,
    )
    from alphatriangle_tpu.env.engine import TriangleEnv
    from alphatriangle_tpu.features.core import get_feature_extractor
    from alphatriangle_tpu.mcts import BatchedMCTS
    from alphatriangle_tpu.nn.network import NeuralNetwork
    from alphatriangle_tpu.rl import Trainer
    from alphatriangle_tpu.stats.persistence import CheckpointManager
    from alphatriangle_tpu.utils.helpers import (
        enable_persistent_compilation_cache,
    )

    enable_persistent_compilation_cache(backend=jax.default_backend())

    # The run's OWN board/net configs (cli eval pattern).
    if args.run_name:
        persistence = PersistenceConfig(RUN_NAME=args.run_name)
        if args.root_dir:
            persistence = persistence.model_copy(
                update={"ROOT_DATA_DIR": args.root_dir}
            )
        cfg_dir = persistence.get_run_base_dir()
    else:
        cfg_dir = Path(args.checkpoint).resolve().parent.parent
        persistence = PersistenceConfig(RUN_NAME="reuse_bet")
    env_cfg, model_cfg = load_run_configs_or_default(cfg_dir)
    env = TriangleEnv(env_cfg)
    extractor = get_feature_extractor(env, model_cfg)
    net = NeuralNetwork(model_cfg, env_cfg, seed=0)
    trainer = Trainer(net, TrainConfig(RUN_NAME="reuse_bet"))
    mgr = CheckpointManager(persistence)
    loaded = (
        mgr.restore_path(args.checkpoint, trainer.state)
        if args.checkpoint
        else mgr.restore(trainer.state)
    )
    if loaded.train_state is None:
        print("no checkpoint found — the bet needs a TRAINED net",
              file=sys.stderr)
        return 1
    trainer.set_state(loaded.train_state)
    trainer.sync_to_network()
    print(f"restored step {loaded.global_step} from {cfg_dir}", flush=True)

    def rollout(mcts, seed: int) -> float:
        """B games to completion, greedy-from-visits (exploit)."""
        states = env.reset_batch(
            jax.random.split(jax.random.PRNGKey(seed), args.games)
        )
        for move in range(args.max_moves):
            done = np.asarray(states.done)
            if done.all():
                break
            out = mcts.search(
                net.variables, states,
                jax.random.PRNGKey(seed * 1000 + move),
            )
            counts = np.asarray(out.visit_counts)
            actions = np.where(
                counts.sum(axis=1) > 0, counts.argmax(axis=1), 0
            )
            states, _, _ = env.step_batch(
                states, jnp.asarray(actions, dtype=jnp.int32)
            )
        return float(np.asarray(states.score).mean())

    seeds = [int(s) for s in args.seeds.split(",")]
    curve = {}
    for sims in (int(s) for s in args.sims.split(",")):
        cfg = AlphaTriangleMCTSConfig(
            max_simulations=sims,
            max_depth=8,
            mcts_batch_size=min(32, sims),
            dirichlet_epsilon=0.0,  # exploit: the strength probe
        )
        mcts = BatchedMCTS(env, extractor, net.model, cfg, net.support)
        t0 = time.time()
        scores = [rollout(mcts, s) for s in seeds]
        curve[sims] = {
            "mean_score": round(float(np.mean(scores)), 3),
            "per_seed": [round(s, 3) for s in scores],
            "seconds": round(time.time() - t0, 1),
        }
        print(f"sims={sims}: {curve[sims]}", flush=True)

    sims_sorted = sorted(curve)
    op = 64 if 64 in curve else sims_sorted[-2]
    top = sims_sorted[-1]
    gain_past_op = (
        curve[top]["mean_score"] - curve[op]["mean_score"]
        if top != op
        else 0.0
    )
    rel = gain_past_op / max(abs(curve[op]["mean_score"]), 1e-9)
    payload = {
        "board": f"{env_cfg.ROWS}x{env_cfg.COLS}",
        "checkpoint_step": loaded.global_step,
        "backend": jax.default_backend(),
        "games_per_condition": args.games * len(seeds),
        "max_moves": args.max_moves,
        "curve": curve,
        "gain_past_operating_point": round(gain_past_op, 3),
        "gain_relative": round(rel, 4),
        # MCTS_DESIGN.md §a's own criterion, applied to the trained net.
        "verdict": (
            "no-reuse design stands (curve flat past the operating "
            "point with a trained net)"
            if rel < 0.02
            else "REVISIT: trained net steepened the sims curve — "
            "subtree reuse could buy real strength"
        ),
    }
    out = REPO / "benchmarks" / "reuse_bet_results.json"
    out.write_text(json.dumps(payload, indent=2))
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main())
