"""Elo ladder over a run's checkpoints: paired round-robin arena.

Restores every checkpoint of a run (or an explicit list), plays each
pair head-to-head on the SAME paired hands (identical reset keys +
step-indexed shape draws, so hand luck cancels — the property the
`eval` command's arena also leans on), and fits Elo ratings to the
pairwise win rates by logistic regression (simple iterative update).

Usage:
  JAX_PLATFORMS=cpu python benchmarks/elo_ladder.py --run-name my_run \
      [--root-dir DIR] [--games 64] [--sims 32] [--max-moves 120]

Writes benchmarks/elo_ladder_<run>.json and prints the table.
"""

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from alphatriangle_tpu.utils.helpers import enforce_platform  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--run-name", required=True)
    ap.add_argument("--root-dir", default=None)
    ap.add_argument("--games", type=int, default=64)
    ap.add_argument("--sims", type=int, default=32)
    ap.add_argument("--max-moves", type=int, default=120)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--device", default=None, choices=["auto", "tpu", "cpu"]
    )
    ap.add_argument(
        "--max-checkpoints",
        type=int,
        default=6,
        help="Evenly subsample to at most this many rungs.",
    )
    args = ap.parse_args()
    enforce_platform(args.device or "auto")

    import jax

    from alphatriangle_tpu.utils.helpers import (  # noqa: E402
        enable_persistent_compilation_cache,
    )

    # Re-call with the resolved backend: the unpinned-auto case defers
    # (utils/helpers.py), and the ladder compiles the flagship search
    # programs repeatedly across rungs.
    enable_persistent_compilation_cache(backend=jax.default_backend())

    import numpy as np

    from alphatriangle_tpu.arena import play_service
    from alphatriangle_tpu.config import (
        AlphaTriangleMCTSConfig,
        PersistenceConfig,
        TrainConfig,
    )
    from alphatriangle_tpu.env.engine import TriangleEnv
    from alphatriangle_tpu.features.core import get_feature_extractor
    from alphatriangle_tpu.mcts import BatchedMCTS
    from alphatriangle_tpu.nn.network import NeuralNetwork
    from alphatriangle_tpu.rl import Trainer
    from alphatriangle_tpu.stats.persistence import CheckpointManager

    persistence = PersistenceConfig(RUN_NAME=args.run_name)
    if args.root_dir:
        persistence = persistence.model_copy(
            update={"ROOT_DATA_DIR": args.root_dir}
        )

    # Rebuild the run's own board/net from its configs.json dump.
    from alphatriangle_tpu.config.run_configs import (
        load_run_configs_or_default,
    )

    env_cfg, model_cfg = load_run_configs_or_default(
        persistence.get_run_base_dir()
    )
    mcts_cfg = AlphaTriangleMCTSConfig(max_simulations=args.sims)
    train_cfg = TrainConfig(RUN_NAME=args.run_name)
    env = TriangleEnv(env_cfg)
    extractor = get_feature_extractor(env, model_cfg)
    ckpt_dir = persistence.get_checkpoint_dir()
    mgr = CheckpointManager(persistence)
    steps = mgr.list_steps()
    if len(steps) < 2:
        raise SystemExit(f"Need >=2 checkpoints under {ckpt_dir}; found {steps}")
    if len(steps) > args.max_checkpoints:
        idx = np.linspace(0, len(steps) - 1, args.max_checkpoints)
        steps = [steps[int(i)] for i in idx]
    print(f"ladder rungs (steps): {steps}")

    # One net + trainer + ONE policy service for the whole ladder:
    # every rung is a hot weight reload into the same compiled
    # `serve/b<games>` search (the service reads net.variables at
    # dispatch time), so the heavy search program compiles once and
    # ladder traffic runs the same session API served "human" traffic
    # does (serving/service.py, docs/SERVING.md).
    from alphatriangle_tpu.serving import PolicyService

    net = NeuralNetwork(model_cfg, env_cfg, seed=0)
    trainer = Trainer(net, train_cfg)
    mcts = BatchedMCTS(env, extractor, net.model, mcts_cfg, net.support)
    service = PolicyService(
        env, extractor, net, mcts, slots=args.games
    )

    # Scores are deterministic per rung given the fixed keys, so the
    # full round-robin needs one playout per rung.
    scores = {}
    for step in steps:
        loaded = mgr.restore_path(
            str(ckpt_dir / f"step_{step:08d}"), trainer.state
        )
        assert loaded.train_state is not None, step
        trainer.set_state(loaded.train_state)
        trainer.sync_to_network()
        service.reload_weights()  # counted hot swap, zero recompiles
        scores[step], _, _ = play_service(
            service, args.games, args.max_moves, args.seed
        )

    # Win-rate matrix + Elo fit via the league subsystem's shared
    # rating math (league/pool.py) — the ladder is a thin client of it.
    from alphatriangle_tpu.league import fit_elo, pairwise_win_fraction

    n = len(steps)
    wins = np.zeros((n, n))
    # Clip away 0/1 winrates: the Bradley-Terry MLE is unbounded for a
    # never-lost pairing, so an unclipped fit would just ride the
    # iteration cap instead of the data.
    eps = 1.0 / (2.0 * args.games)
    for i, a in enumerate(steps):
        for j, b in enumerate(steps):
            if i == j:
                continue
            # paired=True: both rungs played the SAME hands, so the
            # element-wise comparison cancels hand luck.
            w = pairwise_win_fraction(scores[a], scores[b], paired=True)
            wins[i, j] = min(max(w, eps), 1.0 - eps)

    elo = fit_elo(wins)

    table = [
        {
            "step": steps[i],
            "elo": round(float(elo[i]), 1),
            "mean_score": round(float(scores[steps[i]].mean()), 3),
            "mean_winrate": round(
                float(wins[i].sum() / max(n - 1, 1)), 3
            ),
        }
        for i in range(n)
    ]
    table.sort(key=lambda r: -r["elo"])
    out = {
        "run": args.run_name,
        "games": args.games,
        "sims": args.sims,
        "ladder": table,
    }
    out_path = Path(__file__).parent / f"elo_ladder_{args.run_name}.json"
    out_path.write_text(json.dumps(out, indent=2))
    for row in table:
        print(row)


if __name__ == "__main__":
    main()
