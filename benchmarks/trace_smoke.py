"""CI distributed-tracing gate: one causal timeline, router to chip.

`make trace-smoke` runs this. On a CPU-only box it proves the
cross-process tracing + SLO contract (docs/OBSERVABILITY.md
"Distributed tracing & SLOs") end to end:

1. the storm: `cli fleet` drives episode requests through 2 replicas
   with an aggressive hedge trigger while a `hang-serve` fault wedges
   one replica mid-storm — so the ledger is guaranteed to hold BOTH
   router recovery paths: hedged dispatches (slow primary, hedge fired)
   and retried dispatches (dead primary, rerouted), each stamped with
   its request's trace_id;
2. the merge: `cli trace --fleet` (run under the same jax import
   guard as the fleet parent — the merge is a reader for dead fleets)
   fuses fleet.jsonl + the parent's route brackets + every replica's
   flight ring and trace.json into trace_fleet.json; the merged trace
   must contain flow arrows for >= 1 hedged and >= 1 retried request,
   and every flow's trace_id must appear consistently in fleet.jsonl,
   in a replica flight ring, and in the merged trace;
3. the SLO exit-code contract: `cli slo` (jax-guarded) returns 0 on a
   healthy window, 1 on a brownout window burning its availability
   budget, 2 on a run dir with no data — pinned against synthetic
   ledgers so the contract can't drift with storm noise.

Exit 0 when every stage passes; the first failing stage's code
otherwise.
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPLICAS = 2
SLOTS = 8
REQUESTS = 64
MAX_MOVES = 6
#: Aggressive hedge trigger: any dispatch slower than this (queue wait
#: behind the wedged replica, compile warm-up stragglers) hedges onto
#: the peer — guaranteeing hedge/hedge-win events in the ledger.
HEDGE_AFTER_S = 0.3
#: The wedge: hang-serve freezes the first replica to reach this many
#: dispatches. Requests queued behind the frozen dispatch wait past
#: the hedge trigger long before the watchdog's ~2s deadline fires —
#: the wedge GUARANTEES hedges.
HANG_AFTER_DISPATCH = 8
#: Mid-storm SIGKILL: the victim's in-flight requests EOF instantly —
#: faster than the hedge trigger — so they fail outright and get
#: RETRIED onto the peer. (A wedge alone can't pin retries: its
#: requests hedge at 0.3s and complete via hedge-win, never retrying.)
KILL_AFTER = 32

# Same import-guard preamble as fleet_smoke.py: any jax import in the
# guarded subprocess raises. The whole observability readout — fleet
# parent, merge, slo — must work beside a dead or wedged accelerator.
_NO_JAX_PREAMBLE = (
    "import builtins, sys;"
    "_real = builtins.__import__;\n"
    "def _guard(name, *a, **k):\n"
    "    if name == 'jax' or name.startswith('jax.'):\n"
    "        raise ImportError('tracing readers must not import jax: ' + name)\n"
    "    return _real(name, *a, **k)\n"
    "builtins.__import__ = _guard\n"
)


def tiny_configs():
    """fleet_smoke's tiny board/net (fast compile, fast moves)."""
    from alphatriangle_tpu.config import (
        EnvConfig,
        ModelConfig,
        expected_other_features_dim,
    )

    env_cfg = EnvConfig(
        ROWS=3,
        COLS=4,
        PLAYABLE_RANGE_PER_ROW=[(0, 4), (0, 4), (0, 4)],
        NUM_SHAPE_SLOTS=1,
        MAX_SHAPE_TRIANGLES=3,
        LINE_MIN_LENGTH=3,
    )
    model_cfg = ModelConfig(
        GRID_INPUT_CHANNELS=1,
        CONV_FILTERS=[4],
        CONV_KERNEL_SIZES=[3],
        CONV_STRIDES=[1],
        NUM_RESIDUAL_BLOCKS=0,
        RESIDUAL_BLOCK_FILTERS=4,
        USE_TRANSFORMER=False,
        FC_DIMS_SHARED=[16],
        POLICY_HEAD_DIMS=[16],
        VALUE_HEAD_DIMS=[16],
        OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(env_cfg),
        NUM_VALUE_ATOMS=11,
        COMPUTE_DTYPE="float32",
    )
    return env_cfg, model_cfg


def run_dir_for(root: str, run_name: str) -> Path:
    from alphatriangle_tpu.config import PersistenceConfig

    return PersistenceConfig(
        ROOT_DATA_DIR=root, RUN_NAME=run_name
    ).get_run_base_dir()


def fleet_events(ledger: Path) -> list:
    events = []
    if not ledger.exists():
        return events
    for line in ledger.read_text().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("kind") == "fleet":
            events.append(rec)
    return events


def _guarded_cli(argv: list, timeout: float = 300.0):
    """Run `cli <argv>` in a jax-import-guarded subprocess."""
    code = (
        _NO_JAX_PREAMBLE
        + "from alphatriangle_tpu.cli import main\n"
        + f"sys.exit(main({argv!r}))\n"
    )
    return subprocess.run(
        [sys.executable, "-c", code],
        cwd=str(REPO),
        env={**os.environ, "PYTHONPATH": str(REPO)},
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def _fail(msg: str) -> int:
    print(f"trace-smoke: {msg}", file=sys.stderr)
    return 2


class _ArmedFaults:
    def __init__(self, spec: str, state_dir: Path) -> None:
        self.spec = spec
        self.state_dir = state_dir

    def __enter__(self):
        self.state_dir.mkdir(parents=True, exist_ok=True)
        os.environ["ALPHATRIANGLE_FAULTS"] = self.spec
        os.environ["ALPHATRIANGLE_FAULT_STATE_DIR"] = str(self.state_dir)
        return self

    def __exit__(self, *exc):
        os.environ.pop("ALPHATRIANGLE_FAULTS", None)
        os.environ.pop("ALPHATRIANGLE_FAULT_STATE_DIR", None)
        return False


def stage_storm(root: Path) -> "tuple[int, Path]":
    """2-replica storm with a mid-storm wedge: hedges fire off the
    aggressive trigger, retries off the wedge death."""
    run = "trace_smoke"
    run_dir = run_dir_for(str(root), run)
    run_dir.mkdir(parents=True, exist_ok=True)
    env_cfg, model_cfg = tiny_configs()
    (run_dir / "configs.json").write_text(
        json.dumps(
            {"env": env_cfg.model_dump(), "model": model_cfg.model_dump()}
        )
    )
    argv = [
        "fleet",
        "--smoke",
        "--run-name",
        run,
        "--root-dir",
        str(root),
        "--replicas",
        str(REPLICAS),
        "--slots",
        str(SLOTS),
        "--sims",
        "2",
        "--requests",
        str(REQUESTS),
        "--concurrency",
        "8",
        "--max-moves",
        str(MAX_MOVES),
        "--timeout",
        "60",
        "--retries",
        "2",
        "--route-backoff-base",
        "0.1",
        "--route-backoff-max",
        "1.0",
        "--hedge-after",
        str(HEDGE_AFTER_S),
        "--max-queue",
        "64",
        "--probe-deadline",
        "10",
        "--poll",
        "0.25",
        "--settle",
        "90",
        "--backoff-base",
        "0.5",
        "--backoff-max",
        "4.0",
        "--quarantine-after",
        "1",
        "--max-restarts",
        "8",
        "--circuit-breaker",
        "6",
        "--replica-health-interval",
        "1.0",
        "--replica-dispatch-min-deadline",
        "2.0",
        "--replica-dispatch-first-deadline",
        "120",
        "--replica-watchdog-poll",
        "0.25",
        "--tick-every",
        "4",
        "--chaos-kill-after",
        str(KILL_AFTER),
    ]
    with _ArmedFaults(
        f"hang-serve@after={HANG_AFTER_DISPATCH}", root / "faults_trace"
    ):
        proc = _guarded_cli(argv, timeout=900.0)
    report = None
    for line in proc.stdout.splitlines():
        if line.strip().startswith("{"):
            try:
                report = json.loads(line)
            except json.JSONDecodeError:
                pass
    if proc.returncode != 0 or report is None:
        tail = "\n".join(proc.stderr.splitlines()[-30:])
        return (
            _fail(
                f"cli fleet failed (rc={proc.returncode}, "
                f"report={'yes' if report else 'no'})\nstderr tail:\n{tail}"
            ),
            run_dir,
        )
    if report["lost"] != 0 or report["completed"] <= 0:
        return _fail(f"storm accounting broke: {report}"), run_dir

    events = fleet_events(run_dir / "fleet.jsonl")
    hedged = [e for e in events if e.get("event") == "hedge"]
    retried = [e for e in events if e.get("event") == "retry"]
    if not hedged:
        return _fail("no hedge events — hedge trigger never fired"), run_dir
    if not retried:
        return _fail("no retry events — the wedge never forced a reroute"), run_dir
    untraced = [
        e for e in hedged + retried if not e.get("trace_id")
    ]
    if untraced:
        return _fail(f"router decisions without trace_id: {untraced[:3]}"), run_dir
    print(
        f"trace-smoke: storm ok — {report['completed']}/{report['requests']} "
        f"served, {len(hedged)} hedges, {len(retried)} retries, "
        f"slo={report.get('slo')}"
    )
    return 0, run_dir


def stage_merge(root: Path, run_dir: Path) -> int:
    """`cli trace --fleet` under the jax guard; the merged trace must
    hold flow arrows for >= 1 hedged and >= 1 retried trace_id, with
    ids consistent across fleet.jsonl, the replica flight rings, and
    the merged trace itself."""
    proc = _guarded_cli(
        ["trace", run_dir.name, "--fleet", "--root-dir", str(root)],
        timeout=300.0,
    )
    if proc.returncode != 0:
        return _fail(
            f"cli trace --fleet failed (rc={proc.returncode})\n"
            f"stderr: {proc.stderr[-2000:]}"
        )
    merged_path = run_dir / "trace_fleet.json"
    if not merged_path.exists():
        return _fail(f"{merged_path} not written")
    payload = json.loads(merged_path.read_text())
    trace_events = payload.get("traceEvents", [])
    flow_ids = {
        e.get("id")
        for e in trace_events
        if e.get("cat") == "fleet-flow" and e.get("ph") in ("s", "t", "f")
    }
    if not flow_ids:
        return _fail("merged trace holds no flow arrows")
    # Causal order: within each flow id, the step timestamps must be
    # non-decreasing, and no merged span may have a negative duration.
    by_id: dict = {}
    for e in trace_events:
        if e.get("cat") == "fleet-flow":
            by_id.setdefault(e["id"], []).append(e)
        if e.get("ph") == "X" and (e.get("dur") or 0) < 0:
            return _fail(f"negative-duration span in merged trace: {e}")
    for fid, steps in by_id.items():
        ts = [s["ts"] for s in sorted(steps, key=lambda s: s["ts"])]
        if ts != sorted(ts):
            return _fail(f"flow {fid} steps out of causal order: {ts}")

    events = fleet_events(run_dir / "fleet.jsonl")
    hedged_ids = {
        e["trace_id"] for e in events
        if e.get("event") == "hedge" and e.get("trace_id")
    }
    retried_ids = {
        e["trace_id"] for e in events
        if e.get("event") == "retry" and e.get("trace_id")
    }
    if not (hedged_ids & flow_ids):
        return _fail(
            f"no hedged request has a flow arrow "
            f"(hedged={len(hedged_ids)}, flows={len(flow_ids)})"
        )
    if not (retried_ids & flow_ids):
        return _fail(
            f"no retried request has a flow arrow "
            f"(retried={len(retried_ids)}, flows={len(flow_ids)})"
        )
    # Consistency: each checked trace_id must also appear in at least
    # one replica flight ring (the chip end of the causal chain).
    ring_text = ""
    for rdir in sorted(run_dir.glob("replica_*")):
        ring = rdir / "flight.jsonl"
        if ring.exists():
            ring_text += ring.read_text()
    for tid in list(hedged_ids & flow_ids)[:1] + list(retried_ids & flow_ids)[:1]:
        if tid not in ring_text:
            return _fail(f"trace_id {tid} missing from replica flight rings")
    print(
        f"trace-smoke: merge ok — {len(flow_ids)} flow trace ids, "
        f"hedged+retried both causally linked router->replica"
    )
    return 0


def _write_slo_fixture(run_dir: Path, *, sheds: int) -> None:
    """Synthetic fleet run dir: 100 served requests over 60s, p95 well
    under threshold, ok dispatch seals — plus `sheds` availability
    failures. sheds=0 is a healthy window; sheds=50 burns the 1%
    availability budget at x~33 (>= both default thresholds)."""
    now = time.time()
    run_dir.mkdir(parents=True, exist_ok=True)
    with (run_dir / "metrics.jsonl").open("w") as f:
        for i in range(6):
            f.write(
                json.dumps(
                    {
                        "kind": "util",
                        "time": now - 50 + i * 10,
                        "step": i,
                        "window_s": 10.0,
                        "serve_requests_per_sec": 100.0 / 60.0,
                    }
                )
                + "\n"
            )
    with (run_dir / "fleet.jsonl").open("w") as f:
        f.write(
            json.dumps(
                {"kind": "fleet", "event": "fleet-start", "time": now - 55}
            )
            + "\n"
        )
        for i in range(sheds):
            f.write(
                json.dumps(
                    {
                        "kind": "fleet",
                        "event": "shed",
                        "rejection": "queue-full",
                        "time": now - 40 + (i % 30),
                    }
                )
                + "\n"
            )
        f.write(
            json.dumps(
                {"kind": "fleet", "event": "fleet-stop", "time": now}
            )
            + "\n"
        )
    rdir = run_dir / "replica_r0"
    rdir.mkdir(exist_ok=True)
    with (rdir / "metrics.jsonl").open("w") as f:
        for i in range(6):
            f.write(
                json.dumps(
                    {
                        "kind": "util",
                        "time": now - 50 + i * 10,
                        "step": i,
                        "window_s": 10.0,
                        "serve_move_latency_ms_p95": 20.0,
                        "serve_window_requests": 16,
                    }
                )
                + "\n"
            )
    with (rdir / "flight.jsonl").open("w") as f:
        for i in range(10):
            f.write(
                json.dumps(
                    {
                        "kind": "flight",
                        "phase": "seal",
                        "family": "serve",
                        "program": "serve/b8",
                        "seq": i,
                        "ok": True,
                        "time": now - 45 + i * 4,
                    }
                )
                + "\n"
            )


def stage_slo_contract(root: Path) -> int:
    """`cli slo` exit codes, pinned: healthy -> 0, brownout -> 1,
    no data -> 2 (all jax-guarded)."""
    healthy = root / "slo_healthy"
    brownout = root / "slo_brownout"
    empty = root / "slo_empty"
    _write_slo_fixture(healthy, sheds=0)
    _write_slo_fixture(brownout, sheds=50)
    empty.mkdir(parents=True, exist_ok=True)
    for run_dir, want in ((healthy, 0), (brownout, 1), (empty, 2)):
        proc = _guarded_cli(["slo", str(run_dir), "--json"], timeout=120.0)
        if proc.returncode != want:
            return _fail(
                f"cli slo {run_dir.name}: exit {proc.returncode}, "
                f"want {want}\nstdout: {proc.stdout[-1500:]}\n"
                f"stderr: {proc.stderr[-500:]}"
            )
        if want != 2:
            report = json.loads(proc.stdout.strip().splitlines()[-1])
            if report["schema"] != "alphatriangle.slo.v1":
                return _fail(f"bad slo schema: {report['schema']}")
    print("trace-smoke: slo exit contract ok (healthy=0 brownout=1 empty=2)")
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--root-dir", default=None)
    args = parser.parse_args()

    root = Path(args.root_dir or tempfile.mkdtemp(prefix="at_trace_smoke_"))
    t0 = time.monotonic()
    try:
        rc, run_dir = stage_storm(root)
        if rc != 0:
            return rc
        rc = stage_merge(root, run_dir)
        if rc != 0:
            return rc
        rc = stage_slo_contract(root)
        if rc != 0:
            return rc
    finally:
        if args.root_dir is None:
            shutil.rmtree(root, ignore_errors=True)
    print(f"trace-smoke: OK ({time.monotonic() - t0:.0f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
