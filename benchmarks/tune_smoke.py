"""CI autotuner gate: `cli tune` offline search -> consumable preset.

`make tune-smoke` runs this. It proves, on any machine with no
accelerator, the full fit-driven autotune loop (docs/AUTOTUNE.md) end
to end:

1. `cli tune cpu --smoke --limit-gb <host cap>` searches the smoke
   lattice with the REAL `estimate_fit` oracle (a couple of AOT
   compiles, nothing executed) and must exit 0 with a
   `tuned_preset.json` artifact;
2. `cli fit <artifact>` re-runs the OOM pre-flight against the emitted
   preset with the same limit and must exit 0 — the tuner's feasibility
   claim is independently confirmed by the fit gate;
3. the artifact's search table must show the winner's predicted games/h
   >= every other feasible candidate's (the acceptance invariant the
   pruned search guarantees structurally);
4. `cli train --preset <artifact> --dry-setup` must construct every
   training component from the preset and exit 0 — the preset is
   runnable, not just well-formed;
5. optionally (--train-steps N, default 2) a real N-step training run
   consumes the preset and must append a `kind:"tune_outcome"`
   predicted-vs-observed record to its metrics ledger — the calibration
   feedback loop `cli tune --calibrate` reads.

Exit 0 when every stage passes; the first failing stage's code
otherwise.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
RUN_NAME = "tune_smoke"

if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

# Must precede any jax import: the smoke must not wake an accelerator,
# and a pinned peak makes predicted-vs-observed MFU comparable.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ALPHATRIANGLE_PEAK_TFLOPS", "1.0")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--limit-gb",
        type=float,
        default=4.0,
        help="Host-RAM stand-in for the per-device byte limit "
        "(default 4 GiB — far below any CI host's actual RAM, so the "
        "gate also proves the search respects a cap).",
    )
    parser.add_argument(
        "--root-dir",
        default=None,
        help="Runs root for the smoke (default: a temp dir).",
    )
    parser.add_argument(
        "--train-steps",
        type=int,
        default=2,
        help="Learner steps for the outcome-ledger stage (0 skips it).",
    )
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    from alphatriangle_tpu.cli import main as cli_main

    root = args.root_dir or tempfile.mkdtemp(prefix="at_tune_smoke_")
    artifact = Path(root) / "tuned_preset.json"

    print(
        f"tune-smoke: cli tune cpu --smoke (limit {args.limit_gb} GiB) "
        f"-> {artifact} ...",
        flush=True,
    )
    rc = cli_main(
        [
            "tune",
            "cpu",
            "--smoke",
            "--limit-gb",
            str(args.limit_gb),
            "--out",
            str(artifact),
            "--root-dir",
            root,
            "--run-name",
            RUN_NAME,
        ]
    )
    if rc != 0:
        print(f"tune-smoke: cli tune failed (rc={rc})", file=sys.stderr)
        return rc
    if not artifact.is_file():
        print(
            f"tune-smoke: tune exited 0 but {artifact} was not written",
            file=sys.stderr,
        )
        return 2
    payload = json.loads(artifact.read_text())

    print("tune-smoke: winner-beats-feasible invariant...", flush=True)
    best = (payload.get("predicted") or {}).get("games_per_hour")
    if not isinstance(best, (int, float)) or best <= 0:
        print(
            f"tune-smoke: artifact has no positive predicted games/h "
            f"({best!r})",
            file=sys.stderr,
        )
        return 2
    for row in (payload.get("search") or {}).get("rows", []):
        pred = row.get("predicted") or {}
        gph = pred.get("games_per_hour")
        if (
            row.get("status") in ("fit", "dominated")
            and isinstance(gph, (int, float))
            and gph > best + 1e-9
        ):
            print(
                f"tune-smoke: feasible candidate {row} predicts "
                f"{gph:.1f} games/h > winner's {best:.1f}",
                file=sys.stderr,
            )
            return 2

    print("tune-smoke: cli fit <artifact> (independent confirm)...", flush=True)
    rc = cli_main(
        ["fit", str(artifact), "--limit-gb", str(args.limit_gb)]
    )
    if rc != 0:
        print(
            f"tune-smoke: cli fit rejected the tuned preset (rc={rc}) — "
            "the tuner's feasibility claim did not hold",
            file=sys.stderr,
        )
        return rc

    print("tune-smoke: cli train --preset <artifact> --dry-setup...", flush=True)
    rc = cli_main(
        [
            "train",
            "--preset",
            str(artifact),
            "--dry-setup",
            "--run-name",
            f"{RUN_NAME}_dry",
            "--root-dir",
            root,
            "--no-tensorboard",
            "--no-auto-resume",
            "--log-level",
            "WARNING",
        ]
    )
    if rc != 0:
        print(
            f"tune-smoke: dry component setup from the preset failed "
            f"(rc={rc})",
            file=sys.stderr,
        )
        return rc

    if args.train_steps > 0:
        print(
            f"tune-smoke: {args.train_steps}-step run for the "
            "tune_outcome ledger...",
            flush=True,
        )
        obs_run = f"{RUN_NAME}_obs"
        rc = cli_main(
            [
                "train",
                "--preset",
                str(artifact),
                "--max-steps",
                str(args.train_steps),
                "--min-buffer",
                "16",
                "--run-name",
                obs_run,
                "--root-dir",
                root,
                "--no-tensorboard",
                "--no-auto-resume",
                "--log-level",
                "WARNING",
            ]
        )
        if rc != 0:
            print(
                f"tune-smoke: tuned training run failed (rc={rc})",
                file=sys.stderr,
            )
            return rc
        from alphatriangle_tpu.config import PersistenceConfig

        ledger = (
            PersistenceConfig(ROOT_DATA_DIR=root, RUN_NAME=obs_run)
            .get_run_base_dir()
            / "metrics.jsonl"
        )
        outcomes = [
            r
            for line in ledger.read_text().splitlines()
            for r in [json.loads(line)]
            if r.get("kind") == "tune_outcome"
        ]
        if not outcomes:
            print(
                f"tune-smoke: {ledger} holds no tune_outcome record — "
                "the calibration feedback loop broke",
                file=sys.stderr,
            )
            return 2
        print(
            "tune-smoke: outcome ledgered "
            f"(predicted {outcomes[-1].get('predicted_games_per_hour')}, "
            f"observed {outcomes[-1].get('observed_games_per_hour')})"
        )

    if args.root_dir is None:
        shutil.rmtree(root, ignore_errors=True)
    print("tune-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
