#!/bin/bash
# Round-5 on-chip measurement sweep (run only in a healthy-chip window;
# probe first: timeout 60 python -c "import jax; print(jax.devices())").
# Each section appends its JSON line to benchmarks/tpu_r5_results.jsonl.
set -u
cd "$(dirname "$0")/.."
out=benchmarks/tpu_r5_results.jsonl
run() {
  label="$1"; shift
  # ORCH_END_BY (epoch seconds, exported by the orchestrator): re-check
  # the hard deadline BETWEEN sections — a section launched with too
  # little runway would hold the chip past the deadline and collide
  # with the round driver's own bench (the contention the deadline
  # contract exists to prevent). 120s floor: less than that cannot fit
  # even a probe, let alone a measurement.
  if [ "${ORCH_END_BY:-0}" -gt 0 ] && \
     [ $(( ORCH_END_BY - $(date +%s) )) -lt 120 ]; then
    echo "sweep: out of runway before $label; stopping cleanly" >&2
    exit 0
  fi
  # BENCH_SECTIONS="a b c": run only the named sections (the
  # orchestrator uses this to land the highest-priority numbers before
  # handing the chip to the hours-long training run).
  if [ -n "${BENCH_SECTIONS:-}" ] && \
     ! printf ' %s ' "$BENCH_SECTIONS" | grep -q " $label "; then
    return 0
  fi
  # Resumable: a section already recorded (an earlier run before a
  # mid-sweep wedge) is skipped, so the watcher can relaunch the whole
  # script until every section lands.
  if grep -q "\"label\": \"$label\"" "$out" 2>/dev/null; then
    echo "=== $label === already recorded; skipping" >&2
    return 0
  fi
  echo "=== $label ===" >&2
  # BENCH_NO_CPU_FALLBACK: a wedge mid-attempt aborts fast with an
  # error line instead of burning minutes on a CPU run this sweep
  # would refuse to record anyway. Outer timeout is a backstop above
  # the supervisor's own probe (300s) + attempt (900s) budgets.
  line=$(env "$@" BENCH_INIT_TIMEOUT=90 BENCH_INIT_BUDGET=300 \
    BENCH_NO_CPU_FALLBACK=1 timeout 1500 python bench.py)
  if [ -z "$line" ]; then
    echo "$label: bench produced no JSON (killed?); aborting sweep" >&2
    exit 1
  fi
  # A section that fell back to CPU means the chip wedged mid-sweep:
  # every further section would burn its probe budget and record
  # CPU-scale numbers under a TPU label. Abort WITHOUT recording the
  # line — the resume-skip would otherwise pin the mislabeled row
  # forever — and rerun in a new window.
  if ! printf '%s' "$line" | grep -q '"backend": "tpu"'; then
    echo "$label: backend != tpu (chip wedged?); aborting sweep" >&2
    exit 1
  fi
  # Same rule for a wedge-truncated PARTIAL snapshot (some sections
  # missing): recording it would pin the incomplete row against the
  # resume-skip forever; abort and re-measure in the next window.
  if printf '%s' "$line" | grep -q '"partial":'; then
    echo "$label: partial result (wedge mid-section?); aborting sweep" >&2
    exit 1
  fi
  echo "{\"label\": \"$label\", \"result\": $line}" >> "$out"
}
# Section order = round-5 VERDICT priority: the flagship device-replay
# learner + overlapped numbers first (item 1), then the unmeasured
# BASELINE presets (item 4), then the A/Bs (item 7) — healthy windows
# last ~20-30 min, so later sections may wait for another window.
# 1. Flagship, new default recipe (gumbel+PCR) + pipelined overlap + MFU.
run flagship_gumbel_pcr BENCH_SECONDS=75
# 2. Reference-parity PUCT for comparison.
run flagship_puct BENCH_RECIPE=puct BENCH_SECONDS=60
# 3. BASELINE presets 2-5 (2 and 4 are the VERDICT's named gaps).
run preset2 BENCH_CONFIG=2 BENCH_SECONDS=60
run preset4 BENCH_CONFIG=4 BENCH_SECONDS=60
run preset3 BENCH_CONFIG=3 BENCH_SECONDS=60
run preset5 BENCH_CONFIG=5 BENCH_SECONDS=60
# 4. Gather lowering A/B (short windows).
run gather_pallas BENCH_GATHER=pallas BENCH_SECONDS=45
run gather_take BENCH_GATHER=take BENCH_SECONDS=45
# 5. Multi-stream overlap.
run flagship_workers2 BENCH_WORKERS=2 BENCH_SECONDS=60
# 6. Lane-count A/B: lanes are the direct lever on self-play MFU
# (B=512 measured 1.4%); B=1024/2048 double/quadruple every wave's
# MXU batch at the same program shape.
run flagship_b1024 BENCH_BATCH=1024 BENCH_SECONDS=60
run flagship_b2048 BENCH_BATCH=2048 BENCH_SECONDS=60
# 7. Wave-size A/B (MXU batch per eval = lanes x wave). PUCT recipe:
# under gumbel_pcr the fast searches clamp the wave anyway and a
# 64-wave 64-sim gumbel collapses sequential halving to one phase —
# the A/B would change the algorithm, not just the batching.
run wave16 BENCH_WAVE=16 BENCH_RECIPE=puct BENCH_SECONDS=45
run wave64 BENCH_WAVE=64 BENCH_RECIPE=puct BENCH_SECONDS=45
# 8. XLA trace of the flagship self-play (not a headline number — the
# MFU diagnosis input for the next optimization round).
run flagship_profile BENCH_PROFILE=1 BENCH_SECONDS=30
echo "sweep complete" >&2
