"""CI fleet gate: a loadgen storm survives replica murder, end to end.

`make fleet-smoke` runs this. It proves, on any machine with no
accelerator, that the serve fleet (docs/SERVING.md "Fleet") actually
delivers its robustness contract:

1. the fleet control plane (router + supervisor) imports and routes
   with jax imports hard-blocked — the parent must outlive a wedged
   replica, same contract as `cli supervise`;
2. the storm: `cli fleet --smoke` drives N episode requests through
   2 replicas while the chaos schedule fires —
     - a rolling weight reload drains one replica at a time with
       traffic flowing (asserted zero recompiles from the reply),
     - a `hang-serve` fault (supervise/faults.py) wedges one replica's
       dispatch mid-storm: its watchdog exits 113, `diagnose` reads
       the unsealed `serve/b<B>` intent as `dispatch-hung`, the
       quarantine policy respawns it onto a HALVED serve bucket, and
       the probe re-admits it — the death -> verdict -> respawn ->
       re-admission chain lands in order on fleet.jsonl,
     - a SIGKILL takes a live replica late-storm (`chaos-kill`),
   and the zero-lost invariant must hold regardless of interleaving:
   `completed + shed == terminal == requests`, with p95 move latency
   for the completed window inside a (generous, CPU) SLO.

Exit 0 when every stage passes; the first failing stage's code
otherwise. The fleet parent subprocess runs under the same jax import
guard as stage 1 — jax may only live in the replica children.
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPLICAS = 2
SLOTS = 8
REQUESTS = 96
MAX_MOVES = 6
#: p95 per-move latency SLO for the surviving window. Deliberately
#: loose: the tiny CPU net serves moves in tens of ms, but CI boxes
#: run 3+ python processes here — the gate is about not WEDGING.
SLO_P95_MS = 2000.0

# Chaos schedule (see the timeline note in stage_storm): reload first
# on a stable fleet, hang mid-storm, SIGKILL late-storm. Calibrated
# against the tiny board: games end naturally in ~3 moves, so a
# replica completes ~1.3 episodes per dispatch wave — dispatch 12
# lands around terminal ~30 fleet-wide, comfortably between the
# reload and the kill even when the load skews.
RELOAD_AFTER = 2
HANG_AFTER_DISPATCH = 12
KILL_AFTER = 72

# Same import-guard preamble as chaos_smoke.py: any jax import in the
# guarded subprocess raises.
_NO_JAX_PREAMBLE = (
    "import builtins, sys;"
    "_real = builtins.__import__;\n"
    "def _guard(name, *a, **k):\n"
    "    if name == 'jax' or name.startswith('jax.'):\n"
    "        raise ImportError('fleet parent must not import jax: ' + name)\n"
    "    return _real(name, *a, **k)\n"
    "builtins.__import__ = _guard\n"
)


def tiny_configs():
    """chaos_smoke's tiny board/net (fast compile, fast moves); the
    replica-side watchdog knobs ride the `cli fleet --replica-*`
    flags instead of a TelemetryConfig."""
    from alphatriangle_tpu.config import (
        EnvConfig,
        ModelConfig,
        expected_other_features_dim,
    )

    env_cfg = EnvConfig(
        ROWS=3,
        COLS=4,
        PLAYABLE_RANGE_PER_ROW=[(0, 4), (0, 4), (0, 4)],
        NUM_SHAPE_SLOTS=1,
        MAX_SHAPE_TRIANGLES=3,
        LINE_MIN_LENGTH=3,
    )
    model_cfg = ModelConfig(
        GRID_INPUT_CHANNELS=1,
        CONV_FILTERS=[4],
        CONV_KERNEL_SIZES=[3],
        CONV_STRIDES=[1],
        NUM_RESIDUAL_BLOCKS=0,
        RESIDUAL_BLOCK_FILTERS=4,
        USE_TRANSFORMER=False,
        FC_DIMS_SHARED=[16],
        POLICY_HEAD_DIMS=[16],
        VALUE_HEAD_DIMS=[16],
        OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(env_cfg),
        NUM_VALUE_ATOMS=11,
        COMPUTE_DTYPE="float32",
    )
    return env_cfg, model_cfg


def run_dir_for(root: str, run_name: str) -> Path:
    from alphatriangle_tpu.config import PersistenceConfig

    return PersistenceConfig(
        ROOT_DATA_DIR=root, RUN_NAME=run_name
    ).get_run_base_dir()


def fleet_events(ledger: Path) -> list:
    events = []
    if not ledger.exists():
        return events
    for line in ledger.read_text().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("kind") == "fleet":
            events.append(rec)
    return events


class _ArmedFaults:
    """chaos_smoke's context manager: arm the fault env (replica
    children inherit os.environ) with a fresh sentinel state dir so
    each fault fires exactly once across respawns."""

    def __init__(self, spec: str, state_dir: Path) -> None:
        self.spec = spec
        self.state_dir = state_dir

    def __enter__(self):
        self.state_dir.mkdir(parents=True, exist_ok=True)
        os.environ["ALPHATRIANGLE_FAULTS"] = self.spec
        os.environ["ALPHATRIANGLE_FAULT_STATE_DIR"] = str(self.state_dir)
        return self

    def __exit__(self, *exc):
        os.environ.pop("ALPHATRIANGLE_FAULTS", None)
        os.environ.pop("ALPHATRIANGLE_FAULT_STATE_DIR", None)
        return False


def stage_jax_free_router(root: Path) -> int:
    """The fleet control plane must import + route with jax blocked."""
    code = (
        _NO_JAX_PREAMBLE
        + "from alphatriangle_tpu.serving.router import (\n"
        + "    REJECT_NO_HEALTHY, ReplicaRouter)\n"
        + "from alphatriangle_tpu.serving.fleet import FleetSupervisor\n"
        + "from alphatriangle_tpu.serving import run_fleet_load\n"
        + "router = ReplicaRouter([], timeout_s=1.0, retries=0)\n"
        + "res = router.route({'kind': 'episode', 'seed': 0})\n"
        + "assert not res.ok and res.rejection == REJECT_NO_HEALTHY, res\n"
        + "assert router.backoff_delay(3) > 0\n"
        + "print('fleet routed jax-free:', res.rejection)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=str(REPO),
        env={**os.environ, "PYTHONPATH": str(REPO)},
        capture_output=True,
        text=True,
        timeout=120,
    )
    if proc.returncode != 0:
        print(
            f"fleet-smoke: jax-free router gate failed "
            f"(rc={proc.returncode})\nstdout: {proc.stdout}\n"
            f"stderr: {proc.stderr}",
            file=sys.stderr,
        )
        return 2
    print("fleet-smoke: router + supervisor import/route with jax blocked")
    return 0


def _fail(msg: str) -> int:
    print(f"fleet-smoke: {msg}", file=sys.stderr)
    return 2


def stage_storm(root: Path) -> int:
    """The chaos storm via `cli fleet --smoke` (jax-guarded parent).

    Chaos timeline (calibrated against the tiny run: completions start
    within a couple of waves; dispatch counters only reset on a
    respawn, and nothing dies before the hang itself fires):
      n >= RELOAD_AFTER (early)  rolling reload while both replicas
                                 are still healthy — zero recompiles;
      dispatch >= HANG_AFTER     hang-serve wedges the first replica
                                 to reach it, mid-storm, while its
                                 peer is serving;
      n >= KILL_AFTER (late)     SIGKILL the first live replica.
    The zero-lost accounting must close no matter how these overlap
    with respawn warm-up windows (overlap windows shed, never lose).
    """
    run = "fleet_smoke"
    run_dir = run_dir_for(str(root), run)
    run_dir.mkdir(parents=True, exist_ok=True)
    env_cfg, model_cfg = tiny_configs()
    (run_dir / "configs.json").write_text(
        json.dumps(
            {"env": env_cfg.model_dump(), "model": model_cfg.model_dump()}
        )
    )

    argv = [
        "fleet",
        "--smoke",
        "--run-name",
        run,
        "--root-dir",
        str(root),
        "--replicas",
        str(REPLICAS),
        "--slots",
        str(SLOTS),
        "--sims",
        "2",
        "--requests",
        str(REQUESTS),
        "--concurrency",
        "8",
        "--max-moves",
        str(MAX_MOVES),
        "--timeout",
        "60",
        "--retries",
        "2",
        "--route-backoff-base",
        "0.1",
        "--route-backoff-max",
        "1.0",
        "--hedge-after",
        "2.0",
        "--max-queue",
        "64",
        "--probe-deadline",
        "10",
        "--poll",
        "0.25",
        "--settle",
        "90",
        "--backoff-base",
        "0.5",
        "--backoff-max",
        "4.0",
        "--quarantine-after",
        "1",
        "--max-restarts",
        "8",
        "--circuit-breaker",
        "6",
        "--replica-health-interval",
        "1.0",
        "--replica-dispatch-min-deadline",
        "2.0",
        "--replica-dispatch-first-deadline",
        "120",
        "--replica-watchdog-poll",
        "0.25",
        "--tick-every",
        "4",
        "--chaos-kill-after",
        str(KILL_AFTER),
        "--reload-after",
        str(RELOAD_AFTER),
    ]
    code = (
        _NO_JAX_PREAMBLE
        + "from alphatriangle_tpu.cli import main\n"
        + f"sys.exit(main({argv!r}))\n"
    )
    with _ArmedFaults(
        f"hang-serve@after={HANG_AFTER_DISPATCH}", root / "faults_fleet"
    ):
        proc = subprocess.run(
            [sys.executable, "-c", code],
            cwd=str(REPO),
            env={**os.environ, "PYTHONPATH": str(REPO)},
            capture_output=True,
            text=True,
            timeout=900,
        )
    report = None
    for line in proc.stdout.splitlines():
        if line.strip().startswith("{"):
            try:
                report = json.loads(line)
            except json.JSONDecodeError:
                pass
    if proc.returncode != 0 or report is None:
        tail = "\n".join(proc.stderr.splitlines()[-30:])
        return _fail(
            f"cli fleet --smoke failed (rc={proc.returncode}, "
            f"report={'yes' if report else 'no'})\nstderr tail:\n{tail}"
        )

    # Zero-lost invariant (the --smoke gate checked it too; re-assert
    # from the report so a gate regression can't pass silently).
    if report["lost"] != 0:
        return _fail(f"lost requests: {report['lost']} ({report})")
    if report["completed"] <= 0:
        return _fail(f"nothing completed: {report}")
    if report["completed"] + report["shed"] != report["terminal"] or (
        report["terminal"] != report["requests"]
    ):
        return _fail(
            f"accounting leak: completed={report['completed']} "
            f"shed={report['shed']} terminal={report['terminal']} "
            f"requests={report['requests']}"
        )
    p95 = report.get("move_latency_ms_p95")
    if p95 is None or p95 > SLO_P95_MS:
        return _fail(
            f"p95 move latency {p95}ms outside the {SLO_P95_MS:g}ms SLO"
        )

    events = fleet_events(Path(report["ledger"]))
    deaths = [e for e in events if e.get("event") == "death"]
    if len(deaths) < 2:
        return _fail(
            f"expected >= 2 deaths (hang + chaos kill), saw "
            f"{[(d.get('replica'), d.get('rc')) for d in deaths]}"
        )
    if not any(e.get("event") == "chaos-kill" for e in events):
        return _fail("no chaos-kill event on fleet.jsonl")
    wedges = [
        d
        for d in deaths
        if d.get("rc") == 113
        and d.get("verdict") == "dispatch-hung"
        and d.get("family") == "serve"
    ]
    if not wedges:
        return _fail(
            f"no watchdog wedge death (rc=113, dispatch-hung/serve): "
            f"{[(d.get('rc'), d.get('verdict')) for d in deaths]}"
        )

    # The death -> verdict -> respawn -> re-admission chain, in ledger
    # order, for the wedged replica.
    victim = wedges[0].get("replica")
    i_death = events.index(wedges[0])
    i_respawn = next(
        (
            i
            for i, e in enumerate(events)
            if i > i_death
            and e.get("event") == "respawn"
            and e.get("replica") == victim
        ),
        None,
    )
    if i_respawn is None:
        return _fail(f"wedged replica {victim} never respawned")
    if not any(
        e.get("event") == "readmit" and e.get("replica") == victim
        for e in events[i_respawn:]
    ):
        return _fail(f"respawned replica {victim} never re-admitted")

    # Quarantined respawn = graceful degradation onto a halved bucket.
    respawn = events[i_respawn]
    if not (respawn.get("slots") or SLOTS) < SLOTS:
        return _fail(
            f"wedged replica respawned at full bucket: {respawn}"
        )

    # Rolling weight swap with traffic flowing: zero recompiles.
    reloaded = [e for e in events if e.get("event") == "replica-reloaded"]
    if not reloaded:
        return _fail("no replica-reloaded event (rolling swap skipped)")
    hot = [e for e in reloaded if e.get("recompiles") not in (0, None)]
    if hot:
        return _fail(f"weight reload recompiled: {hot}")
    if not any(e.get("event") == "reload-done" for e in events):
        return _fail("rolling reload never completed")

    print(
        f"fleet-smoke: {report['completed']}/{report['requests']} served "
        f"(+{report['shed']} shed, 0 lost) through "
        f"{len(deaths)} deaths [{victim} wedge -> 113 -> dispatch-hung -> "
        f"respawn@b{respawn.get('slots')} -> readmit], "
        f"{len(reloaded)} hot reloads (0 recompiles), "
        f"p95 {p95:.0f}ms, {report['elapsed_s']:.0f}s storm"
    )
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--root-dir", default=None)
    args = parser.parse_args()

    root = Path(args.root_dir or tempfile.mkdtemp(prefix="at_fleet_smoke_"))
    t0 = time.monotonic()
    try:
        for stage in (stage_jax_free_router, stage_storm):
            rc = stage(root)
            if rc != 0:
                return rc
    finally:
        if args.root_dir is None:
            shutil.rmtree(root, ignore_errors=True)
    print(f"fleet-smoke: OK ({time.monotonic() - t0:.0f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
