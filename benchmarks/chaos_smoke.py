"""CI chaos gate: injected faults -> supervised recovery, end to end.

`make chaos-smoke` runs this. It proves, on any machine with no
accelerator, that the self-healing story (docs/ROBUSTNESS.md) actually
closes: the fault injector (supervise/faults.py) kills real training
children in the exact ways TPU runs die, and the `cli supervise`
machinery must bring every scenario home:

1. the supervisor package imports and decides with jax imports hard-
   blocked — the parent must outlive a wedged chip, so this is a
   contract, not a style preference;
2. wedge drill: a dispatch hung mid-run (hang-dispatch fault) dies by
   the real watchdog's exit 113, the `Supervisor` classifies it
   dispatch-hung, restarts from the latest committed checkpoint with
   backoff, and the run completes (exit 0) with step loss bounded by
   one checkpoint cadence — `supervisor.jsonl` carries the full
   death -> verdict -> restart chain;
3. preemption drill: SIGTERM at a mid-run step is absorbed as an
   emergency checkpoint + exit 114, `cli doctor` reads the preempt
   report as verdict `preempted` (exit 7), and a bare rerun resumes
   from the emergency checkpoint and completes;
4. torn-checkpoint drill: SIGKILL in the middle of a checkpoint save
   (after the tree dispatch + meta write, before the commit marker)
   leaves an uncommitted step dir; the supervised restart must resume
   from the prior COMMITTED step, skip the torn one, and complete.

Exit 0 when every stage passes; the first failing stage's code
otherwise. Scenario children run `--child` below (a tiny CPU training
run with a fast dispatch watchdog); the parent stays jax-free.
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

MAX_STEPS = 8
CKPT_CADENCE = 2

# Same import-guard preamble as doctor_smoke.py: any jax import in the
# guarded subprocess raises.
_NO_JAX_PREAMBLE = (
    "import builtins, sys;"
    "_real = builtins.__import__;\n"
    "def _guard(name, *a, **k):\n"
    "    if name == 'jax' or name.startswith('jax.'):\n"
    "        raise ImportError('supervisor must not import jax: ' + name)\n"
    "    return _real(name, *a, **k)\n"
    "builtins.__import__ = _guard\n"
)


def tiny_configs(run_name: str):
    """perf_smoke's tiny world plus a fast dispatch watchdog: small
    deadlines so an injected hang dies in seconds, not minutes."""
    from alphatriangle_tpu.config import (
        AlphaTriangleMCTSConfig,
        EnvConfig,
        ModelConfig,
        TelemetryConfig,
        TrainConfig,
        expected_other_features_dim,
    )

    env_cfg = EnvConfig(
        ROWS=3,
        COLS=4,
        PLAYABLE_RANGE_PER_ROW=[(0, 4), (0, 4), (0, 4)],
        NUM_SHAPE_SLOTS=1,
        MAX_SHAPE_TRIANGLES=3,
        LINE_MIN_LENGTH=3,
    )
    model_cfg = ModelConfig(
        GRID_INPUT_CHANNELS=1,
        CONV_FILTERS=[4],
        CONV_KERNEL_SIZES=[3],
        CONV_STRIDES=[1],
        NUM_RESIDUAL_BLOCKS=0,
        RESIDUAL_BLOCK_FILTERS=4,
        USE_TRANSFORMER=False,
        FC_DIMS_SHARED=[16],
        POLICY_HEAD_DIMS=[16],
        VALUE_HEAD_DIMS=[16],
        OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(env_cfg),
        NUM_VALUE_ATOMS=11,
        COMPUTE_DTYPE="float32",
    )
    mcts_cfg = AlphaTriangleMCTSConfig(max_simulations=4, max_depth=4)
    train_cfg = TrainConfig(
        RUN_NAME=run_name,
        AUTO_RESUME_LATEST=False,
        MAX_TRAINING_STEPS=MAX_STEPS,
        SELF_PLAY_BATCH_SIZE=4,
        ROLLOUT_CHUNK_MOVES=4,
        BATCH_SIZE=8,
        BUFFER_CAPACITY=2000,
        MIN_BUFFER_SIZE_TO_TRAIN=16,
        USE_PER=True,
        PER_BETA_ANNEAL_STEPS=8,
        N_STEP_RETURNS=2,
        WORKER_UPDATE_FREQ_STEPS=2,
        CHECKPOINT_SAVE_FREQ_STEPS=CKPT_CADENCE,
        MAX_EPISODE_MOVES=30,
        RANDOM_SEED=5,
        DEVICE="cpu",
    )
    tele_cfg = TelemetryConfig(
        # Calibrated dispatches wedge after ~2s of silence; a program's
        # first dispatch (its compile) keeps a generous allowance.
        DISPATCH_MIN_DEADLINE_S=2.0,
        DISPATCH_FIRST_DEADLINE_S=120.0,
        DISPATCH_WATCHDOG_POLL_S=0.25,
        HEALTH_WRITE_INTERVAL_S=1.0,
    )
    return env_cfg, model_cfg, mcts_cfg, train_cfg, tele_cfg


def child(args) -> int:
    """One tiny supervised-training child (runs in a subprocess; the
    armed ALPHATRIANGLE_FAULTS env decides how it dies)."""
    from alphatriangle_tpu.config import PersistenceConfig
    from alphatriangle_tpu.training import run_training

    env_cfg, model_cfg, mcts_cfg, train_cfg, tele_cfg = tiny_configs(
        args.run_name
    )
    pc = PersistenceConfig(ROOT_DATA_DIR=args.root_dir, RUN_NAME=args.run_name)
    return run_training(
        train_config=train_cfg,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        persistence_config=pc,
        telemetry_config=tele_cfg,
        use_tensorboard=False,
        log_level="WARNING",
    )


def run_dir_for(root: str, run_name: str) -> Path:
    from alphatriangle_tpu.config import PersistenceConfig

    return PersistenceConfig(
        ROOT_DATA_DIR=root, RUN_NAME=run_name
    ).get_run_base_dir()


def child_argv(root: str, run_name: str) -> list:
    return [
        sys.executable,
        str(Path(__file__).resolve()),
        "--child",
        "--root-dir",
        root,
        "--run-name",
        run_name,
    ]


def committed_steps(run_dir: Path) -> list:
    ckpts = run_dir / "checkpoints"
    if not ckpts.is_dir():
        return []
    steps = []
    for p in ckpts.glob("step_*.commit"):
        stem = p.name[len("step_"):-len(".commit")]
        if stem.isdigit():
            steps.append(int(stem))
    return sorted(steps)


def supervisor_events(run_dir: Path) -> list:
    events = []
    path = run_dir / "supervisor.jsonl"
    if not path.exists():
        return events
    for line in path.read_text().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("kind") == "supervisor":
            events.append(rec)
    return events


class _ArmedFaults:
    """Context manager arming the fault env for children the Supervisor
    spawns (it inherits os.environ), with a fresh sentinel state dir so
    each fault fires exactly once per scenario across restarts."""

    def __init__(self, spec: str, state_dir: Path) -> None:
        self.spec = spec
        self.state_dir = state_dir

    def __enter__(self):
        self.state_dir.mkdir(parents=True, exist_ok=True)
        os.environ["ALPHATRIANGLE_FAULTS"] = self.spec
        os.environ["ALPHATRIANGLE_FAULT_STATE_DIR"] = str(self.state_dir)
        return self

    def __exit__(self, *exc):
        os.environ.pop("ALPHATRIANGLE_FAULTS", None)
        os.environ.pop("ALPHATRIANGLE_FAULT_STATE_DIR", None)
        return False


def stage_jax_free_supervisor(root: Path) -> int:
    """The supervisor parent must import + decide with jax blocked."""
    code = (
        _NO_JAX_PREAMBLE
        + "from alphatriangle_tpu.supervise import (\n"
        + "    RecoveryPolicy, Supervisor, diagnose, latest_committed_step)\n"
        + "policy = RecoveryPolicy(backoff_base_s=1.0)\n"
        + "action = policy.decide(verdict='dispatch-hung', exit_code=113,\n"
        + "                       family='rollout')\n"
        + "assert action.kind == 'restart', action\n"
        + f"assert latest_committed_step({str(root)!r}) is None\n"
        + f"verdict = diagnose({str(root)!r})\n"
        + "assert verdict['verdict'] == 'never-started', verdict\n"
        + "print('supervise decided jax-free:', action.kind)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=str(REPO),
        env={**os.environ, "PYTHONPATH": str(REPO)},
        capture_output=True,
        text=True,
        timeout=120,
    )
    if proc.returncode != 0:
        print(
            f"chaos-smoke: jax-free supervisor gate failed "
            f"(rc={proc.returncode})\nstdout: {proc.stdout}\n"
            f"stderr: {proc.stderr}",
            file=sys.stderr,
        )
        return 2
    print("chaos-smoke: supervise package imports + decides with jax blocked")
    return 0


def stage_wedge_restart(root: Path) -> int:
    """Injected mid-run hang -> watchdog 113 -> supervised restart from
    the latest committed checkpoint -> completion, chain on disk."""
    from alphatriangle_tpu.supervise import RecoveryPolicy, Supervisor

    run = "chaos_wedge"
    run_dir = run_dir_for(str(root), run)
    # Threshold calibrated against the tiny run's dispatch timeline:
    # ~13 dispatches total, the step-2 checkpoint commits by seq ~5 and
    # step-4 by seq ~8 — seq 9 is mid-run with committed progress.
    with _ArmedFaults("hang-dispatch@after=9", root / "faults_wedge"):
        policy = RecoveryPolicy(backoff_base_s=0.2, backoff_max_s=2.0)
        rc = Supervisor(child_argv(str(root), run), run_dir, policy).run()
    if rc != 0:
        print(
            f"chaos-smoke: supervised wedge run did not complete (rc={rc})",
            file=sys.stderr,
        )
        return 2
    events = supervisor_events(run_dir)
    deaths = [e for e in events if e.get("event") == "death"]
    spawns = [e for e in events if e.get("event") == "spawn"]
    completes = [e for e in events if e.get("event") == "complete"]
    if not deaths or len(spawns) < 2 or not completes:
        print(
            f"chaos-smoke: supervisor.jsonl chain incomplete: "
            f"{len(spawns)} spawns, {len(deaths)} deaths, "
            f"{len(completes)} completes",
            file=sys.stderr,
        )
        return 2
    death = deaths[0]
    if (
        death.get("rc") != 113
        or death.get("verdict") != "dispatch-hung"
        or death.get("action") != "restart"
        or not death.get("program")
        or death.get("delay_s", 0) <= 0
    ):
        print(
            f"chaos-smoke: death event misclassified: {death}",
            file=sys.stderr,
        )
        return 2
    progress = death.get("progress_step")
    if progress is None or progress < CKPT_CADENCE:
        print(
            f"chaos-smoke: no committed checkpoint at death "
            f"(progress_step={progress}) — the wedge fired before the "
            "first commit; raise the hang-dispatch threshold",
            file=sys.stderr,
        )
        return 2
    # Step loss <= one checkpoint cadence: the step the dead child had
    # reached (its last ledger record before the death event) minus the
    # committed step the restart resumed from.
    death_t = float(death.get("time") or 0.0)
    last_step = 0
    ledger = run_dir / "metrics.jsonl"
    if ledger.exists():
        for line in ledger.read_text().splitlines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            step = rec.get("step")
            if (
                isinstance(step, (int, float))
                and float(rec.get("time") or 0.0) < death_t
            ):
                last_step = max(last_step, int(step))
    if last_step - progress > CKPT_CADENCE:
        print(
            f"chaos-smoke: step loss {last_step - progress} exceeds the "
            f"checkpoint cadence {CKPT_CADENCE} (died at ~{last_step}, "
            f"resumed from {progress})",
            file=sys.stderr,
        )
        return 2
    final = committed_steps(run_dir)
    if not final or final[-1] != MAX_STEPS:
        print(
            f"chaos-smoke: run did not finish at a committed step "
            f"{MAX_STEPS} (committed: {final})",
            file=sys.stderr,
        )
        return 2
    print(
        f"chaos-smoke: wedge -> 113 -> {death['verdict']} "
        f"({death['program']}) -> restart from step {progress} after "
        f"{death['delay_s']:.1f}s -> completed at step {final[-1]} "
        f"(lost <= {CKPT_CADENCE} steps)"
    )
    return 0


def stage_preempt(root: Path) -> int:
    """SIGTERM mid-run -> emergency checkpoint + exit 114, doctor says
    `preempted`, a bare rerun resumes and completes."""
    run = "chaos_preempt"
    run_dir = run_dir_for(str(root), run)
    argv = child_argv(str(root), run)
    with _ArmedFaults("sigterm@step=3", root / "faults_preempt") as armed:
        env = {**os.environ, "PYTHONPATH": str(REPO)}
        first = subprocess.run(argv, cwd=str(REPO), env=env, timeout=600)
        if first.returncode != 114:
            print(
                f"chaos-smoke: preempted child exited {first.returncode}, "
                "expected 114",
                file=sys.stderr,
            )
            return 2
        report_path = run_dir / "preempt_report.json"
        try:
            report = json.loads(report_path.read_text())
        except (OSError, ValueError) as exc:
            print(
                f"chaos-smoke: no parseable {report_path}: {exc}",
                file=sys.stderr,
            )
            return 2
        ckpt_step = report.get("checkpointed_step")
        if ckpt_step is None or ckpt_step < 3:
            print(
                f"chaos-smoke: emergency checkpoint missing from the "
                f"preempt report: {report}",
                file=sys.stderr,
            )
            return 2
        if ckpt_step not in committed_steps(run_dir):
            print(
                f"chaos-smoke: emergency checkpoint step {ckpt_step} has "
                f"no commit marker (committed: {committed_steps(run_dir)})",
                file=sys.stderr,
            )
            return 2
        # The doctor invocation tpu_watch.sh makes must read the report.
        code = (
            _NO_JAX_PREAMBLE
            + "from alphatriangle_tpu.cli import main\n"
            + f"sys.exit(main(['doctor', {str(run_dir)!r}, '--json']))\n"
        )
        doc = subprocess.run(
            [sys.executable, "-c", code],
            cwd=str(REPO),
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        verdict = None
        for line in doc.stdout.splitlines():
            if line.strip().startswith("{"):
                try:
                    verdict = json.loads(line)
                except json.JSONDecodeError:
                    pass
        if (
            doc.returncode != 7
            or verdict is None
            or verdict.get("verdict") != "preempted"
        ):
            print(
                f"chaos-smoke: doctor on a preempted run gave "
                f"rc={doc.returncode}, verdict={verdict}\n"
                f"stderr: {doc.stderr}",
                file=sys.stderr,
            )
            return 2
        # Rerun (sentinel already claimed: the fault cannot refire) and
        # require completion from the emergency checkpoint.
        assert armed  # env still armed: the sentinel is what protects us
        second = subprocess.run(argv, cwd=str(REPO), env=env, timeout=600)
    if second.returncode != 0:
        print(
            f"chaos-smoke: preempt resume failed (rc={second.returncode})",
            file=sys.stderr,
        )
        return 2
    final = committed_steps(run_dir)
    if not final or final[-1] != MAX_STEPS:
        print(
            f"chaos-smoke: preempt resume did not reach a committed "
            f"step {MAX_STEPS} (committed: {final})",
            file=sys.stderr,
        )
        return 2
    print(
        f"chaos-smoke: SIGTERM@step3 -> exit 114 + emergency checkpoint "
        f"at step {ckpt_step} (committed) -> doctor 'preempted' (exit 7) "
        f"-> resume completed at step {final[-1]}"
    )
    return 0


def stage_torn_checkpoint(root: Path) -> int:
    """SIGKILL mid-checkpoint-save -> the uncommitted step dir is
    skipped and the supervised restart resumes from the prior committed
    step."""
    from alphatriangle_tpu.supervise import RecoveryPolicy, Supervisor

    run = "chaos_torn"
    run_dir = run_dir_for(str(root), run)
    with _ArmedFaults("sigkill-save@step=4", root / "faults_torn"):
        policy = RecoveryPolicy(backoff_base_s=0.2, backoff_max_s=2.0)
        rc = Supervisor(child_argv(str(root), run), run_dir, policy).run()
    if rc != 0:
        print(
            f"chaos-smoke: supervised torn-checkpoint run did not "
            f"complete (rc={rc})",
            file=sys.stderr,
        )
        return 2
    deaths = [
        e for e in supervisor_events(run_dir) if e.get("event") == "death"
    ]
    if not deaths:
        print(
            "chaos-smoke: no death event after the sigkill-save fault",
            file=sys.stderr,
        )
        return 2
    death = deaths[0]
    progress = death.get("progress_step")
    # Killed DURING the step-4 save, before its commit marker: the
    # supervisor must report the prior committed step as the restart
    # point, never the torn step-4 directory.
    if progress != 4 - CKPT_CADENCE:
        print(
            f"chaos-smoke: expected restart from the prior committed "
            f"step {4 - CKPT_CADENCE}, supervisor saw "
            f"progress_step={progress} ({death})",
            file=sys.stderr,
        )
        return 2
    final = committed_steps(run_dir)
    if not final or final[-1] != MAX_STEPS:
        print(
            f"chaos-smoke: torn-checkpoint run did not finish at a "
            f"committed step {MAX_STEPS} (committed: {final})",
            file=sys.stderr,
        )
        return 2
    print(
        f"chaos-smoke: SIGKILL mid-save at step 4 -> torn dir skipped, "
        f"restart from committed step {progress} -> completed at step "
        f"{final[-1]}"
    )
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--root-dir", default=None)
    parser.add_argument("--run-name", default="chaos_child")
    args = parser.parse_args()

    if args.child:
        return child(args)

    root = Path(args.root_dir or tempfile.mkdtemp(prefix="at_chaos_smoke_"))
    t0 = time.monotonic()
    try:
        for stage in (
            stage_jax_free_supervisor,
            stage_wedge_restart,
            stage_preempt,
            stage_torn_checkpoint,
        ):
            rc = stage(root)
            if rc != 0:
                return rc
    finally:
        if args.root_dir is None:
            shutil.rmtree(root, ignore_errors=True)
    print(f"chaos-smoke: OK ({time.monotonic() - t0:.0f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
