"""Round-4 on-hardware training run: the flagship recipe, end to end.

Drives the real CLI (separate processes, exactly what a user runs):

1. Train the flagship preset (8x15 board, 4-layer transformer,
   Gumbel+PCR search) in overlapped mode until `--kill-at` learner
   steps, then deliver SIGINT mid-run — the reference's ctrl-C path.
2. Resume the SAME run (auto-resume) to `--steps`, proving
   checkpoint/resume under real device timing.
3. Post-hoc strength curve: arena-eval every checkpoint (paired
   hands vs the random baseline, Gumbel exploit search) and write
   `benchmarks/tpu_training_curve.json`.

Wedge resilience: the TPU behind the tunnel oscillates between healthy
and wedged. Every phase watches checkpoint progress; a phase that
makes no progress for --stall-minutes is killed and retried (resume
picks up from the latest checkpoint), up to --retries times.

Usage (healthy-chip window):
    python benchmarks/tpu_training_run.py --steps 2000 --kill-at 600
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def log(msg: str) -> None:
    print(f"[tpu_training_run] {msg}", file=sys.stderr, flush=True)


def checkpoint_dir(root: str, run_name: str) -> Path:
    return Path(root) / "AlphaTriangleTPU" / "runs" / run_name / "checkpoints"


def completed_steps(ckpt_dir: Path) -> list[int]:
    """Step numbers of COMPLETED checkpoints (orbax writes
    `step_XXXX.orbax-checkpoint-tmp-*` staging dirs first; skip any
    name whose suffix isn't purely numeric)."""
    steps = []
    for p in ckpt_dir.glob("step_*"):
        suffix = p.name.split("_", 1)[1]
        if p.is_dir() and suffix.isdigit():
            steps.append(int(suffix))
    return sorted(steps)


def latest_step(ckpt_dir: Path) -> int:
    return max(completed_steps(ckpt_dir), default=0)


def train_phase(
    args, target_steps: int, kill_at: int | None, attempt: int
) -> str:
    """One training subprocess. Returns 'done', 'killed', or 'stalled'."""
    cmd = [
        sys.executable,
        "-m",
        "alphatriangle_tpu.cli",
        "train",
        "--preset",
        "3",
        "--async-rollouts",
        "--workers",
        str(args.workers),
        "--fused-learner-steps",
        str(args.fused),
        "--max-steps",
        str(target_steps),
        "--run-name",
        args.run_name,
        "--root-dir",
        args.root_dir,
        "--checkpoint-freq",
        str(args.checkpoint_freq),
        "--min-buffer",
        str(args.min_buffer),
        "--keep-checkpoints",
        "10000",  # keep everything: phase 3 evals the WHOLE curve
        "--no-tensorboard",
    ]
    if args.smoke:
        # Tiny CPU shakeout of THIS DRIVER's orchestration (kill,
        # resume, stall watch, eval sweep) — not a performance run.
        cmd += [
            "--self-play-batch",
            "8",
            "--batch-size",
            "8",
            "--rollout-chunk",
            "2",
            "--buffer-capacity",
            "2000",
            "--device",
            "cpu",
        ]
    log(f"attempt {attempt}: {' '.join(cmd[2:])}")
    t_launch = time.time()
    proc = subprocess.Popen(cmd, cwd=REPO)
    ckpts = checkpoint_dir(args.root_dir, args.run_name)
    last_progress = time.time()
    last_seen = latest_step(ckpts)
    killed = False
    while True:
        rc = proc.poll()
        if rc is not None:
            if killed:
                return "killed"
            if rc == 0:
                return "done"
            # A nonzero exit with zero checkpoint progress in the
            # first couple of minutes is a deterministic crash (bad
            # config, import error) — retrying the identical command
            # is pointless and a retry loop would mask the failure.
            if (
                latest_step(ckpts) == last_seen
                and time.time() - t_launch < 180
            ):
                return "crashed"
            return "stalled"
        step = latest_step(ckpts)
        if step > last_seen:
            last_seen = step
            last_progress = time.time()
            log(f"checkpoint at step {step}")
        if kill_at is not None and step >= kill_at and not killed:
            log(f"delivering SIGINT at step {step} (kill/resume exercise)")
            proc.send_signal(signal.SIGINT)
            killed = True
        if time.time() - last_progress > args.stall_minutes * 60:
            log(
                f"no checkpoint progress in {args.stall_minutes} min; "
                "killing this attempt (chip wedge?)"
            )
            proc.kill()
            proc.wait(timeout=60)
            return "stalled"
        time.sleep(10.0)


def eval_checkpoint(args, step: int | None) -> dict | None:
    """Arena-eval one checkpoint (None = untrained net)."""
    cmd = [
        sys.executable,
        "-m",
        "alphatriangle_tpu.cli",
        "eval",
        "--games",
        str(args.eval_games),
        "--sims",
        str(args.eval_sims),
        "--gumbel",
        "--max-moves",
        str(args.eval_max_moves),
        "--root-dir",
        args.root_dir,
    ]
    if args.smoke:
        cmd += ["--device", "cpu"]
    if step is not None:
        ckpt = checkpoint_dir(args.root_dir, args.run_name) / f"step_{step:08d}"
        cmd += ["--run-name", args.run_name, "--checkpoint", str(ckpt)]
    try:
        out = subprocess.run(
            cmd,
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=args.stall_minutes * 60,
        )
    except subprocess.TimeoutExpired:
        log(f"eval of step {step} timed out")
        return None
    if out.returncode != 0:
        log(f"eval of step {step} failed rc={out.returncode}")
        return None
    for line in reversed(out.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    return None


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=2000)
    ap.add_argument("--kill-at", type=int, default=600)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--fused", type=int, default=16)
    ap.add_argument("--checkpoint-freq", type=int, default=250)
    ap.add_argument("--min-buffer", type=int, default=25_000)
    ap.add_argument("--eval-games", type=int, default=64)
    ap.add_argument("--eval-sims", type=int, default=32)
    ap.add_argument("--eval-max-moves", type=int, default=200)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--run-name", default="tpu_flagship_r4")
    ap.add_argument("--root-dir", default="/tmp/tpu_r4_train")
    ap.add_argument("--stall-minutes", type=float, default=25.0)
    ap.add_argument("--retries", type=int, default=6)
    ap.add_argument(
        "--out", default=str(REPO / "benchmarks" / "tpu_training_curve.json")
    )
    args = ap.parse_args()

    t_start = time.time()
    events = []
    # Phase 1+2: train to --steps with one deliberate mid-run SIGINT.
    kill_pending = args.kill_at if args.kill_at > 0 else None
    for attempt in range(1, args.retries + 1):
        status = train_phase(args, args.steps, kill_pending, attempt)
        step = latest_step(checkpoint_dir(args.root_dir, args.run_name))
        events.append(
            {"attempt": attempt, "status": status, "latest_step": step}
        )
        log(f"attempt {attempt}: {status} at step {step}")
        if status == "crashed":
            log("deterministic startup crash; aborting (not a chip wedge)")
            return 1
        if status == "killed":
            kill_pending = None  # the resume that follows proves the path
        if status == "done" and step >= args.steps:
            break
    else:
        log("retries exhausted before reaching target steps")

    # Phase 3: strength curve over every checkpoint.
    ckpts = completed_steps(checkpoint_dir(args.root_dir, args.run_name))
    curve = []
    base = eval_checkpoint(args, None)
    if base is not None:
        curve.append({"step": 0, **base})
    for step in ckpts:
        r = eval_checkpoint(args, step)
        if r is not None:
            curve.append({"step": step, **r})
            log(
                f"step {step}: mean {r.get('mcts_mean_score')} "
                f"(vs random x{r.get('score_vs_random')})"
            )

    payload = {
        "recipe": "preset 3 (flagship): Gumbel+PCR, overlapped, "
        f"workers={args.workers}, fused={args.fused}",
        "target_steps": args.steps,
        "kill_at": args.kill_at,
        "wall_seconds": round(time.time() - t_start, 1),
        "train_events": events,
        "curve": curve,
    }
    Path(args.out).write_text(json.dumps(payload, indent=2))
    log(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())


