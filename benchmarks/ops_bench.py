"""Kernel-library micro-benchmark: backend x shape grid with parity gate.

Times every interchangeable lowering of the three hot kernels in
alphatriangle_tpu/ops/ (docs/KERNELS.md) against each other:

- gather_rows:    einsum | take | pallas   (MCTS descent row gather)
- backup_update:  xla | pallas             (fused insertion + backup)
- per_sample:     xla | pallas             (stratified PER draw)

Every row is correctness-gated before it is timed: each backend's
output must match the reference backend bit-for-bit (all three kernels
are exact-parity by construction — see the module docstrings in ops/).
A parity failure raises, so `make ops-smoke` is a CPU regression gate
for the kernel library, not just a stopwatch. On CPU the Pallas rows
run in interpret mode — their timings measure the interpreter, not the
mosaic lowering; run on a TPU host for decision-grade numbers.

Usage: JAX_PLATFORMS=cpu python benchmarks/ops_bench.py
Env:   OPS_BENCH_FULL=1  adds flagship-sized shapes (TPU hosts)
Writes benchmarks/ops_bench_results.json.
"""

import json
import os
import sys
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from alphatriangle_tpu.ops import backup_update, gather_rows, per_sample

FULL = os.environ.get("OPS_BENCH_FULL") == "1"

# (B, N, A, W, D) tree shapes: smoke rows stay interpreter-friendly on
# CPU; FULL adds the flagship self-play geometry (bench.py tpu tier).
TREE_SHAPES = [(8, 65, 12, 8, 6), (16, 129, 24, 16, 8)] + (
    [(256, 801, 72, 32, 12)] if FULL else []
)
# (cap, K, b) replay shapes: off- and on-tile-boundary capacities.
PER_SHAPES = [(700, 2, 32), (4096, 4, 64)] + (
    [(200_000, 16, 1024)] if FULL else []
)


def timed(fn, *args):
    jax.block_until_ready(fn(*args))  # compile
    reps = 5
    t0 = time.time()
    for _ in range(reps):
        jax.block_until_ready(fn(*args))
    return (time.time() - t0) / reps


def assert_same(ref, got, label):
    for r, g in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g), label)


def bench_gather(rows):
    rng = np.random.default_rng(0)
    for b, n, a, w, _ in TREE_SHAPES:
        k = a + 3  # stat row width: per-action stats + scalars
        stats = jnp.asarray(rng.standard_normal((b, n, k)), jnp.float32)
        idx = jnp.asarray(rng.integers(0, n, (b, w)), jnp.int32)
        fns = {
            m: jax.jit(lambda s, i, m=m: gather_rows(s, i, mode=m))
            for m in ("einsum", "take", "pallas")
        }
        ref = fns["einsum"](stats, idx)
        for mode, fn in fns.items():
            assert_same(ref, fn(stats, idx), f"gather_rows[{mode}]")
            rows.append(
                {
                    "kernel": "gather_rows",
                    "backend": mode,
                    "shape": {"B": b, "N": n, "K": k, "W": w},
                    "mean_s": round(timed(fn, stats, idx), 5),
                }
            )
            print(json.dumps(rows[-1]), flush=True)


def bench_backup(rows):
    rng = np.random.default_rng(1)
    for b, n, a, w, d in TREE_SHAPES:
        ops = (
            jnp.asarray(rng.standard_normal((b, n, a)), jnp.float32),
            jnp.asarray(rng.standard_normal((b, n, a)), jnp.float32),
            jnp.asarray(
                rng.integers(-1, n, (b, n, a)).astype(np.float32)
            ),
            jnp.asarray(rng.standard_normal((b, n, a)), jnp.float32),
            jnp.asarray(rng.integers(0, n, (b, w)), jnp.int32),
            jnp.asarray(rng.integers(0, a, (b, w)), jnp.int32),
            jnp.asarray(
                np.where(
                    rng.random((b, w)) < 0.5,
                    rng.integers(0, n, (b, w)),
                    -1,
                ).astype(np.float32)
            ),
            jnp.asarray(rng.standard_normal((b, w)), jnp.float32),
            # narrow index ranges force duplicate edges, the ordering-
            # sensitive case the parity gate must hold on:
            jnp.asarray(rng.integers(-1, n // 2, (b, w, d)), jnp.int32),
            jnp.asarray(rng.integers(-1, a // 2, (b, w, d)), jnp.int32),
            jnp.asarray(
                (rng.random((b, w, d)) < 0.7).astype(np.int32)
            ),
            jnp.asarray(rng.standard_normal((b, w, d)), jnp.float32),
        )
        fns = {
            m: jax.jit(lambda *o, m=m: backup_update(*o, mode=m))
            for m in ("xla", "pallas")
        }
        ref = fns["xla"](*ops)
        for mode, fn in fns.items():
            assert_same(ref, fn(*ops), f"backup_update[{mode}]")
            rows.append(
                {
                    "kernel": "backup_update",
                    "backend": mode,
                    "shape": {"B": b, "N": n, "A": a, "W": w, "D": d},
                    "mean_s": round(timed(fn, *ops), 5),
                }
            )
            print(json.dumps(rows[-1]), flush=True)


def bench_per_sample(rows):
    rng = np.random.default_rng(2)
    for cap, k, b in PER_SHAPES:
        pri = jnp.asarray(rng.random(cap), jnp.float32)
        key = jax.random.PRNGKey(7)
        fns = {
            m: jax.jit(
                lambda p, kk, m=m: per_sample(p, cap, k, b, kk, mode=m)
            )
            for m in ("xla", "pallas")
        }
        ref = fns["xla"](pri, key)
        for mode, fn in fns.items():
            assert_same(ref, fn(pri, key), f"per_sample[{mode}]")
            rows.append(
                {
                    "kernel": "per_sample",
                    "backend": mode,
                    "shape": {"cap": cap, "K": k, "b": b},
                    "mean_s": round(timed(fn, pri, key), 5),
                }
            )
            print(json.dumps(rows[-1]), flush=True)


def main() -> None:
    rows: list[dict] = []
    bench_gather(rows)
    bench_backup(rows)
    bench_per_sample(rows)
    report = {
        "backend": jax.default_backend(),
        "interpret_pallas": jax.default_backend() != "tpu",
        "full": FULL,
        "rows": rows,
    }
    out_path = Path(__file__).parent / "ops_bench_results.json"
    out_path.write_text(json.dumps(report, indent=2))
    print(f"parity gate passed for all {len(rows)} rows -> {out_path}")


if __name__ == "__main__":
    main()
