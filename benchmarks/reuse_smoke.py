"""CI reuse-smoke gate: MCTS subtree reuse end to end on CPU.

`make reuse-smoke` runs this. It proves, on any machine with no
accelerator, that the subtree-reuse path (ops/subtree_reuse.py,
`MCTSConfig.tree_reuse` — docs/KERNELS.md) holds its three contracts:

1. **Promotion parity.** `subtree_promote` over a REAL search tree must
   match an eager NumPy BFS reference node for node (order, budget
   truncation, children remap, freed-row fills, state gather plan), and
   the `"pallas"` lowering must be bit-identical to `"xla"`. This is
   the semantic pin the jitted scatter-min/argsort plan is held to.
2. **Throughput + telemetry.** Reuse ON at equal sims must deliver
   >= 1.15x leaf-evals/s over the fresh-root engine (the ISSUE 17
   acceptance ratio), and a short reuse training run must land
   `leaf_evals_per_sec` + `mcts_reused_visit_fraction` (> 0) on the
   ledger's util records and in `cli perf --json`.
3. **Strength.** A fixed-seed paired arena (arena.play_service, the
   full PolicyService queue/dispatch path) of reuse at REDUCED sims vs
   fresh-root at full sims must be score-neutral-or-better — the bet
   that carried visits buy back search budget, gated deterministically.

Exit 0 when every stage passes; the first failing stage's code
otherwise.
"""

import argparse
import contextlib
import io
import json
import os
import shutil
import sys
import tempfile
import time
from collections import deque
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
RUN_NAME = "reuse_smoke"

if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

# Must precede any jax import: the smoke must not wake (or wedge on) an
# accelerator, and the peak override is what makes CPU MFU non-null.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ALPHATRIANGLE_PEAK_TFLOPS", "1.0")

import numpy as np  # noqa: E402

SPEEDUP_BAR = 1.15  # ISSUE 17 acceptance: reuse leaf-evals/s multiple
FULL_SIMS = 8
REDUCED_SIMS = 6  # arena gate: reuse must not lose strength here
ARENA_GAMES = 16
ARENA_MAX_MOVES = 30


def eager_promote(planes, terminal, actions, max_retained):
    """Pure-NumPy reference for `subtree_promote`: literal BFS from the
    chosen child, depth-major stable order, budget truncation with
    parent-before-child consistency, children remapped to new ids (edges
    to dropped nodes -> -1), freed rows zeroed (children -1), terminal
    masked, state_index mirroring the root broadcast on freed rows."""
    ev, eq, er, ch, pr, va = [np.asarray(p, np.float32) for p in planes]
    term = np.asarray(terminal, bool)
    acts = np.asarray(actions, np.int64)
    b_n, n, a_dim = ev.shape
    outs = [np.zeros_like(p) for p in (ev, eq, er, ch, pr, va)]
    outs[3][:] = -1.0
    term_out = np.zeros_like(term)
    state_index = np.zeros((b_n, n), np.int32)
    promo_valid = np.zeros(b_n, bool)
    retained = np.zeros(b_n, np.int32)
    for b in range(b_n):
        c0 = int(ch[b, 0, acts[b]])
        if c0 < 0:
            continue  # invalid lane: zeroed planes, state_index -> 0
        promo_valid[b] = True
        depth = {c0: 0}
        dq = deque([c0])
        while dq:
            u = dq.popleft()
            for act in range(a_dim):
                v = int(ch[b, u, act])
                if v >= 0 and v not in depth:
                    depth[v] = depth[u] + 1
                    dq.append(v)
        order = sorted(depth, key=lambda u: (depth[u], u))
        rank = {u: r for r, u in enumerate(order)}
        ret = min(len(order), max_retained)
        retained[b] = ret
        for r, u in enumerate(order[:ret]):
            for i, plane in enumerate((ev, eq, er, None, pr, va)):
                if plane is not None:
                    outs[i][b, r] = plane[b, u]
            for act in range(a_dim):
                v = int(ch[b, u, act])
                kept = v >= 0 and v in rank and rank[v] < max_retained
                outs[3][b, r, act] = float(rank[v]) if kept else -1.0
            term_out[b, r] = term[b, u]
        state_index[b, :ret] = order[:ret]
        state_index[b, ret:] = c0
    return outs, term_out, state_index, promo_valid, retained


def tiny_world():
    """The training perf smoke's tiny world with a PUCT search config
    sized so reuse has subtree to carry."""
    from perf_smoke import tiny_configs

    from alphatriangle_tpu.config import AlphaTriangleMCTSConfig

    env_cfg, model_cfg, _mcts, train_cfg = tiny_configs()
    mcts_cfg = AlphaTriangleMCTSConfig(
        max_simulations=FULL_SIMS, max_depth=5, mcts_batch_size=4
    )
    return env_cfg, model_cfg, mcts_cfg, train_cfg


def build_world():
    from alphatriangle_tpu.env.engine import TriangleEnv
    from alphatriangle_tpu.features.core import get_feature_extractor
    from alphatriangle_tpu.nn.network import NeuralNetwork

    env_cfg, model_cfg, mcts_cfg, train_cfg = tiny_world()
    env = TriangleEnv(env_cfg)
    extractor = get_feature_extractor(env, model_cfg)
    net = NeuralNetwork(model_cfg, env_cfg, seed=0)
    return env_cfg, model_cfg, mcts_cfg, train_cfg, env, extractor, net


def stage_parity() -> int:
    """Stage 1: subtree_promote vs the eager reference, xla == pallas."""
    import jax
    import jax.numpy as jnp

    from alphatriangle_tpu.mcts.search import BatchedMCTS
    from alphatriangle_tpu.ops import subtree_promote

    (_env_cfg, _model_cfg, mcts_cfg, _train_cfg, env, extractor, net) = (
        build_world()
    )
    reuse_cfg = mcts_cfg.model_copy(update={"tree_reuse": True})
    mcts = BatchedMCTS(env, extractor, net.model, reuse_cfg, net.support)

    states = jax.vmap(env.reset)(jax.random.split(jax.random.PRNGKey(3), 8))
    carried = mcts.zero_carried(states)
    _out, tree, _reused = mcts._search_carried(
        net.variables, states, jax.random.PRNGKey(17), carried
    )
    planes = (
        tree.e_visits, tree.e_value, tree.e_reward,
        tree.children, tree.prior, tree.valid,
    )
    # Chosen actions: the visit argmax for most lanes, plus one lane
    # forced onto a (likely) never-visited action to cover the
    # invalid-promotion path.
    counts = np.asarray(tree.e_visits[:, 0, :])
    actions = counts.argmax(axis=1).astype(np.int32)
    actions[0] = int(counts[0].argmin())
    actions_d = jnp.asarray(actions)

    failures = 0
    for max_retained in (mcts.reuse_slots, 3):
        ref_planes, ref_term, ref_sidx, ref_pv, ref_ret = eager_promote(
            planes, tree.terminal, actions, max_retained
        )
        for mode in ("xla", "pallas"):
            got = subtree_promote(
                *planes, tree.terminal, actions_d,
                max_retained=max_retained,
                bfs_rounds=reuse_cfg.max_depth,
                mode=mode,
            )
            names = (
                "e_visits", "e_value", "e_reward", "children", "prior",
                "valid", "terminal", "state_index", "promo_valid",
                "retained",
            )
            refs = list(ref_planes) + [ref_term, ref_sidx, ref_pv, ref_ret]
            for name, g, r in zip(names, got, refs):
                if not np.array_equal(np.asarray(g), np.asarray(r)):
                    print(
                        f"reuse-smoke: {mode} promotion plane {name} "
                        f"diverges from the eager reference "
                        f"(max_retained={max_retained})",
                        file=sys.stderr,
                    )
                    failures += 1
    if failures:
        return 1
    print(
        "reuse-smoke: promotion parity OK (xla+pallas vs eager NumPy "
        f"reference, budgets {mcts.reuse_slots} and 3, one invalid lane)"
    )
    return 0


def stage_speedup() -> int:
    """Stage 2a: reuse ON >= SPEEDUP_BAR x leaf-evals/s at equal sims."""
    from alphatriangle_tpu.rl.self_play import SelfPlayEngine

    (_env_cfg, _model_cfg, mcts_cfg, train_cfg, env, extractor, net) = (
        build_world()
    )

    def build_engine(reuse: bool) -> SelfPlayEngine:
        cfg = mcts_cfg.model_copy(update={"tree_reuse": reuse})
        engine = SelfPlayEngine(
            env, extractor, net, cfg, train_cfg, seed=123
        )
        engine.play_chunk()  # compile + warm
        engine.harvest()
        return engine

    fresh_eng = build_engine(False)
    reuse_eng = build_engine(True)
    # Interleave the two engines chunk by chunk and score each by its
    # MEDIAN per-chunk time: on a shared CI box a transient load spike
    # then taxes both sides (and the median discards it) instead of
    # sinking whichever phase it happened to land on.
    chunks = 8
    fresh_times: list[float] = []
    reuse_times: list[float] = []
    for _ in range(chunks):
        t0 = time.perf_counter()
        fresh_eng.play_chunk()
        fresh_times.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        reuse_eng.play_chunk()
        reuse_times.append(time.perf_counter() - t0)
    fresh_res = fresh_eng.harvest()
    reuse_res = reuse_eng.harvest()
    fresh_leafs = fresh_res.total_simulations + fresh_res.total_reused_visits
    reuse_leafs = reuse_res.total_simulations + reuse_res.total_reused_visits
    fresh_rate = (fresh_leafs / chunks) / float(np.median(fresh_times))
    reuse_rate = (reuse_leafs / chunks) / float(np.median(reuse_times))
    reuse_frac = reuse_res.total_reused_visits / max(1, reuse_leafs)
    speedup = reuse_rate / fresh_rate
    print(
        f"reuse-smoke: leaf-evals/s fresh {fresh_rate:.0f} vs reuse "
        f"{reuse_rate:.0f} -> {speedup:.2f}x "
        f"(reused fraction {reuse_frac:.2f}; bar {SPEEDUP_BAR}x)"
    )
    if reuse_frac <= 0.0:
        print("reuse-smoke: reuse never carried a visit", file=sys.stderr)
        return 1
    if speedup < SPEEDUP_BAR:
        print(
            f"reuse-smoke: speedup {speedup:.2f}x below the "
            f"{SPEEDUP_BAR}x acceptance bar",
            file=sys.stderr,
        )
        return 1
    return 0


def stage_telemetry(root: str) -> int:
    """Stage 2b: reuse run -> ledger util fields -> cli perf --json."""
    from alphatriangle_tpu.cli import main as cli_main
    from alphatriangle_tpu.config import PersistenceConfig, TrainConfig
    from alphatriangle_tpu.training import run_training

    env_cfg, model_cfg, mcts_cfg, train_cfg = tiny_world()
    reuse_cfg = mcts_cfg.model_copy(update={"tree_reuse": True})
    run_cfg = TrainConfig(
        **{
            **train_cfg.model_dump(),
            "RUN_NAME": RUN_NAME,
            "MAX_TRAINING_STEPS": 6,
        }
    )
    pc = PersistenceConfig(ROOT_DATA_DIR=root, RUN_NAME=RUN_NAME)
    rc = run_training(
        train_config=run_cfg,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=reuse_cfg,
        persistence_config=pc,
        use_tensorboard=False,
        log_level="WARNING",
    )
    if rc != 0:
        print(
            f"reuse-smoke: reuse training run failed (rc={rc})",
            file=sys.stderr,
        )
        return rc

    ledger = pc.get_run_base_dir() / "metrics.jsonl"
    utils = []
    for line in ledger.read_text().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("kind") == "util" and isinstance(
            rec.get("leaf_evals_per_sec"), (int, float)
        ):
            utils.append(rec)
    if not utils:
        print(
            f"reuse-smoke: {ledger} holds no util record with "
            "leaf_evals_per_sec — the telemetry schema broke",
            file=sys.stderr,
        )
        return 2
    fracs = [
        r.get("mcts_reused_visit_fraction")
        for r in utils
        if isinstance(r.get("mcts_reused_visit_fraction"), (int, float))
    ]
    if not fracs or max(fracs) <= 0.0:
        print(
            "reuse-smoke: ledger never recorded a positive "
            f"mcts_reused_visit_fraction (got {fracs})",
            file=sys.stderr,
        )
        return 2
    print(
        f"reuse-smoke: {len(utils)} ledger util record(s); peak reused "
        f"fraction {max(fracs):.2f}"
    )

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(["perf", RUN_NAME, "--root-dir", root, "--json"])
    if rc != 0:
        print(f"reuse-smoke: cli perf failed (rc={rc})", file=sys.stderr)
        return rc
    summary = json.loads(buf.getvalue())
    for key in ("leaf_evals_per_sec", "mcts_reused_visit_fraction"):
        if not isinstance(summary.get(key), (int, float)):
            print(
                f"reuse-smoke: cli perf --json lacks {key}",
                file=sys.stderr,
            )
            return 2
    if summary["mcts_reused_visit_fraction"] <= 0.0:
        print(
            "reuse-smoke: cli perf --json reused fraction is zero",
            file=sys.stderr,
        )
        return 2
    print(
        "reuse-smoke: cli perf --json leaf-evals/s "
        f"{summary['leaf_evals_per_sec']:.0f}, reused fraction "
        f"{summary['mcts_reused_visit_fraction']:.2f}"
    )
    return 0


def stage_arena() -> int:
    """Stage 3: fixed-seed paired arena — reuse at REDUCED_SIMS must be
    score-neutral-or-better vs fresh-root at FULL_SIMS, both through
    the PolicyService dispatch path (arena.play_service)."""
    from alphatriangle_tpu.arena import play_service
    from alphatriangle_tpu.mcts.search import BatchedMCTS
    from alphatriangle_tpu.serving.service import PolicyService

    (_env_cfg, _model_cfg, mcts_cfg, _train_cfg, env, extractor, net) = (
        build_world()
    )

    def arena_mean(sims: int, reuse: bool) -> float:
        cfg = mcts_cfg.model_copy(
            update={"max_simulations": sims, "tree_reuse": reuse}
        )
        mcts = BatchedMCTS(env, extractor, net.model, cfg, net.support)
        service = PolicyService(
            env, extractor, net, mcts, slots=ARENA_GAMES
        )
        scores, _lengths, _done = play_service(
            service, ARENA_GAMES, ARENA_MAX_MOVES, seed=11
        )
        return float(np.mean(scores))

    fresh = arena_mean(FULL_SIMS, reuse=False)
    reduced = arena_mean(REDUCED_SIMS, reuse=True)
    print(
        f"reuse-smoke: arena mean score fresh@{FULL_SIMS} {fresh:.3f} "
        f"vs reuse@{REDUCED_SIMS} {reduced:.3f} "
        f"({ARENA_GAMES} paired hands, seed 11)"
    )
    if reduced < fresh:
        print(
            "reuse-smoke: reuse at reduced sims LOST strength "
            f"({reduced:.3f} < {fresh:.3f})",
            file=sys.stderr,
        )
        return 1
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--root-dir",
        default=None,
        help="Runs root for the telemetry stage (default: a temp dir).",
    )
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    rc = stage_parity()
    if rc != 0:
        return rc
    rc = stage_speedup()
    if rc != 0:
        return rc
    root = args.root_dir or tempfile.mkdtemp(prefix="at_reuse_smoke_")
    try:
        rc = stage_telemetry(root)
    finally:
        if args.root_dir is None:
            shutil.rmtree(root, ignore_errors=True)
    if rc != 0:
        return rc
    rc = stage_arena()
    if rc != 0:
        return rc
    print("reuse-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
