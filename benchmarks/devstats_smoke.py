"""CI device-telemetry gate: stat-packs in the one fetch + beacons.

`make devstats-smoke` runs this. It proves, on any machine with no
accelerator, that the device telemetry plane
(alphatriangle_tpu/telemetry/device_stats.py, docs/OBSERVABILITY.md
"Device telemetry plane") closes end to end:

1. stat-pack ledger gate: a short FUSED_MEGASTEP CPU training run with
   `TelemetryConfig.DEVICE_STATS` on (the default) must land
   `kind: "device_stats"` records in metrics.jsonl carrying the search
   leg (root entropy / occupancy / depth histogram), and
   `cli perf --json` must fold them into `ds_*` summary fields — while
   the one-dispatch-per-iteration gauge still reads exactly 1.0;
2. overhead gate: the SAME megastep program timed with stat-packs OFF
   vs ON (in-process, warmup excluded, medians) must show <3% added
   wall per iteration, with the runner's dispatch counter advancing
   exactly once per megastep in both modes — the stats ride the
   existing fetch, they do not buy extra dispatches or host syncs;
3. wedge-phase forensics gate: a training child with beacons armed by
   env (`ALPHATRIANGLE_BEACONS=1`) and an injected mid-run dispatch
   hang (`hang-dispatch` fault) must die by the real watchdog's exit
   113 leaving crash-safe beacons.jsonl rows, a wedge_report.json whose
   frozen `last_beacon` names the phase, and a `cli doctor` dispatch-
   hung verdict (run with jax imports hard-blocked, exactly as
   tpu_watch.sh invokes it) that carries that same beacon.

Exit 0 when every stage passes; the first failing stage's code
otherwise.
"""

import json
import os
import shutil
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

# Must precede any jax import: the smoke must not wake an accelerator.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ALPHATRIANGLE_PEAK_TFLOPS", "1.0")

OVERHEAD_BUDGET = 0.03  # stat-pack wall overhead bound (3%)

# Hard import-guard preamble for the doctor subprocess: any jax import
# on the doctor path raises, same contract as doctor_smoke.py.
_NO_JAX_PREAMBLE = (
    "import builtins, sys;"
    "_real = builtins.__import__;\n"
    "def _guard(name, *a, **k):\n"
    "    if name == 'jax' or name.startswith('jax.'):\n"
    "        raise ImportError('cli doctor must not import jax: ' + name)\n"
    "    return _real(name, *a, **k)\n"
    "builtins.__import__ = _guard\n"
)


def run_doctor(run_dir: Path) -> "tuple[int, dict | None]":
    """`cli doctor <run_dir> --json` in a subprocess with jax imports
    blocked — the exact invocation tpu_watch.sh's archive step makes."""
    code = (
        _NO_JAX_PREAMBLE
        + "from alphatriangle_tpu.cli import main\n"
        + f"sys.exit(main(['doctor', {str(run_dir)!r}, '--json']))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=str(REPO),
        env={**os.environ, "PYTHONPATH": str(REPO)},
        capture_output=True,
        text=True,
        timeout=120,
    )
    verdict = None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                verdict = json.loads(line)
            except json.JSONDecodeError:
                pass
    if verdict is None:
        print(
            f"devstats-smoke: no JSON verdict from cli doctor "
            f"(rc={proc.returncode})\nstdout: {proc.stdout}\n"
            f"stderr: {proc.stderr}",
            file=sys.stderr,
        )
    return proc.returncode, verdict


def tiny_configs(run_name: str):
    """perf_smoke's tiny world in FUSED_MEGASTEP mode, plus a fast
    dispatch watchdog so the injected hang in stage 3 dies in seconds."""
    from alphatriangle_tpu.config import (
        AlphaTriangleMCTSConfig,
        EnvConfig,
        ModelConfig,
        TelemetryConfig,
        TrainConfig,
        expected_other_features_dim,
    )

    env_cfg = EnvConfig(
        ROWS=3,
        COLS=4,
        PLAYABLE_RANGE_PER_ROW=[(0, 4), (0, 4), (0, 4)],
        NUM_SHAPE_SLOTS=1,
        MAX_SHAPE_TRIANGLES=3,
        LINE_MIN_LENGTH=3,
    )
    model_cfg = ModelConfig(
        GRID_INPUT_CHANNELS=1,
        CONV_FILTERS=[4],
        CONV_KERNEL_SIZES=[3],
        CONV_STRIDES=[1],
        NUM_RESIDUAL_BLOCKS=0,
        RESIDUAL_BLOCK_FILTERS=4,
        USE_TRANSFORMER=False,
        FC_DIMS_SHARED=[16],
        POLICY_HEAD_DIMS=[16],
        VALUE_HEAD_DIMS=[16],
        OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(env_cfg),
        NUM_VALUE_ATOMS=11,
        COMPUTE_DTYPE="float32",
    )
    mcts_cfg = AlphaTriangleMCTSConfig(max_simulations=4, max_depth=4)
    train_cfg = TrainConfig(
        RUN_NAME=run_name,
        AUTO_RESUME_LATEST=False,
        MAX_TRAINING_STEPS=8,
        SELF_PLAY_BATCH_SIZE=4,
        ROLLOUT_CHUNK_MOVES=4,
        BATCH_SIZE=8,
        BUFFER_CAPACITY=2000,
        MIN_BUFFER_SIZE_TO_TRAIN=16,
        USE_PER=True,
        PER_BETA_ANNEAL_STEPS=8,
        N_STEP_RETURNS=2,
        WORKER_UPDATE_FREQ_STEPS=2,
        CHECKPOINT_SAVE_FREQ_STEPS=4,
        MAX_EPISODE_MOVES=30,
        RANDOM_SEED=5,
        DEVICE="cpu",
        FUSED_MEGASTEP=True,
        DEVICE_REPLAY="on",
        FUSED_LEARNER_STEPS=2,
    )
    tele_cfg = TelemetryConfig(
        DISPATCH_MIN_DEADLINE_S=2.0,
        DISPATCH_FIRST_DEADLINE_S=120.0,
        DISPATCH_WATCHDOG_POLL_S=0.25,
        HEALTH_WRITE_INTERVAL_S=1.0,
    )
    return env_cfg, model_cfg, mcts_cfg, train_cfg, tele_cfg


def read_records(ledger: Path) -> list:
    records = []
    if not ledger.exists():
        return records
    for line in ledger.read_text().splitlines():
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return records


def stage_statpack_ledger(root: Path) -> int:
    """Short megastep run -> device_stats records -> `cli perf --json`
    ds_* fields, with the one-dispatch gauge untouched."""
    import contextlib
    import io

    from alphatriangle_tpu.cli import main as cli_main
    from alphatriangle_tpu.config import PersistenceConfig
    from alphatriangle_tpu.training import run_training

    run = "devstats_ledger"
    env_cfg, model_cfg, mcts_cfg, train_cfg, _tele = tiny_configs(run)
    pc = PersistenceConfig(ROOT_DATA_DIR=str(root), RUN_NAME=run)
    rc = run_training(
        train_config=train_cfg,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        persistence_config=pc,
        use_tensorboard=False,
        log_level="WARNING",
    )
    if rc != 0:
        print(
            f"devstats-smoke: megastep run failed (rc={rc})",
            file=sys.stderr,
        )
        return 2

    records = read_records(pc.get_run_base_dir() / "metrics.jsonl")
    ds_records = [r for r in records if r.get("kind") == "device_stats"]
    search_legs = [
        r["search"] for r in ds_records if isinstance(r.get("search"), dict)
    ]
    if not ds_records or not search_legs:
        print(
            f"devstats-smoke: ledger holds {len(ds_records)} device_stats "
            f"record(s), {len(search_legs)} with a search leg — the "
            "stat-pack plumbing came unwired",
            file=sys.stderr,
        )
        return 2
    leg = search_legs[-1]
    missing = [
        k
        for k in ("root_entropy", "occupancy", "depth_hist", "value_abs_max")
        if leg.get(k) is None
    ]
    if missing:
        print(
            f"devstats-smoke: search leg lacks {missing}: {leg}",
            file=sys.stderr,
        )
        return 2

    # Stat-packs must NOT buy extra dispatches: the megastep gauge still
    # reads exactly one host dispatch per iteration with stats on.
    dpi = [
        r.get("dispatches_per_iteration")
        for r in records
        if r.get("kind") == "util"
        and isinstance(r.get("dispatches_per_iteration"), (int, float))
    ]
    if not dpi or abs(dpi[-1] - 1.0) > 1e-6:
        print(
            f"devstats-smoke: dispatches_per_iteration "
            f"{dpi[-1] if dpi else None} != 1.0 with stat-packs on",
            file=sys.stderr,
        )
        return 2

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(["perf", run, "--root-dir", str(root), "--json"])
    if rc != 0:
        print(
            f"devstats-smoke: cli perf failed (rc={rc})", file=sys.stderr
        )
        return rc
    summary = json.loads(buf.getvalue())
    if not summary.get("ds_records") or not isinstance(
        summary.get("ds_root_entropy"), (int, float)
    ):
        print(
            "devstats-smoke: cli perf --json lacks ds_* fields: "
            f"ds_records={summary.get('ds_records')} "
            f"ds_root_entropy={summary.get('ds_root_entropy')}",
            file=sys.stderr,
        )
        return 2
    print(
        f"devstats-smoke: {len(ds_records)} device_stats record(s); "
        f"perf summary entropy {summary['ds_root_entropy']} nats, "
        f"occupancy {summary.get('ds_tree_occupancy')}, "
        f"dispatches/iteration {dpi[-1]:.1f}"
    )
    return 0


def _make_runner(run_name: str):
    """A bare MegastepRunner over the tiny world (no training loop, no
    telemetry) — the unit the overhead gate times."""
    from alphatriangle_tpu.env.engine import TriangleEnv
    from alphatriangle_tpu.features.core import get_feature_extractor
    from alphatriangle_tpu.nn.network import NeuralNetwork
    from alphatriangle_tpu.rl import MegastepRunner, SelfPlayEngine, Trainer
    from alphatriangle_tpu.rl.device_buffer import DeviceReplayBuffer

    env_cfg, model_cfg, mcts_cfg, train_cfg, _tele = tiny_configs(run_name)
    env = TriangleEnv(env_cfg)
    extractor = get_feature_extractor(env, model_cfg)
    net = NeuralNetwork(model_cfg, env_cfg, seed=0)
    engine = SelfPlayEngine(env, extractor, net, mcts_cfg, train_cfg, seed=0)
    trainer = Trainer(net, train_cfg)
    buf = DeviceReplayBuffer(
        train_cfg,
        grid_shape=(
            model_cfg.GRID_INPUT_CHANNELS,
            env_cfg.ROWS,
            env_cfg.COLS,
        ),
        other_dim=extractor.other_dim,
        action_dim=env_cfg.action_dim,
    )
    return MegastepRunner(engine, trainer, buf, train_cfg)


def _time_megasteps(runner, warmup: int, timed: int) -> list:
    """Per-iteration wall times, warmup (compile + cache fill) excluded.
    The dispatch counter must advance exactly once per megastep."""
    before = runner.dispatch_count
    for _ in range(warmup):
        runner.run_megastep(2, 2)
    times = []
    for _ in range(timed):
        t0 = time.perf_counter()
        runner.run_megastep(2, 2)
        times.append(time.perf_counter() - t0)
    dispatched = runner.dispatch_count - before
    assert dispatched == warmup + timed, (
        f"{dispatched} dispatches for {warmup + timed} megasteps — the "
        "one-dispatch contract broke"
    )
    return times


def stage_overhead(root: Path) -> int:
    """Stat-packs OFF vs ON on the same megastep shape: <3% added wall,
    one dispatch per iteration in both modes."""
    from alphatriangle_tpu.telemetry.device_stats import (
        reset_device_stats_state,
        set_device_stats,
    )

    warmup, timed = 3, 12
    try:
        reset_device_stats_state()
        set_device_stats(False)
        runner_off = _make_runner("devstats_off")
        off = _time_megasteps(runner_off, warmup, timed)
        if runner_off.last_device_stats is not None:
            print(
                "devstats-smoke: stats-off runner produced "
                "last_device_stats — the gate is not gating",
                file=sys.stderr,
            )
            return 2

        reset_device_stats_state()
        set_device_stats(True)
        runner_on = _make_runner("devstats_on")
        on = _time_megasteps(runner_on, warmup, timed)
        if not (runner_on.last_device_stats or {}).get("search"):
            print(
                "devstats-smoke: stats-on runner has no search leg in "
                f"last_device_stats: {runner_on.last_device_stats}",
                file=sys.stderr,
            )
            return 2
    finally:
        reset_device_stats_state()

    med_off = statistics.median(off)
    med_on = statistics.median(on)
    overhead = (med_on - med_off) / med_off if med_off > 0 else 0.0
    print(
        f"devstats-smoke: megastep median {med_off * 1e3:.2f}ms off / "
        f"{med_on * 1e3:.2f}ms on -> {overhead:+.1%} stat-pack overhead "
        f"(budget {OVERHEAD_BUDGET:.0%}); one dispatch per iteration in "
        "both modes"
    )
    if overhead > OVERHEAD_BUDGET:
        print(
            f"devstats-smoke: stat-pack overhead {overhead:.1%} exceeds "
            f"the {OVERHEAD_BUDGET:.0%} budget — the pack left the "
            "device program",
            file=sys.stderr,
        )
        return 2
    return 0


def wedge_child(args) -> int:
    """Stage-3 child: tiny megastep run with a fast watchdog; the armed
    hang-dispatch fault wedges it mid-run and the watchdog exits 113."""
    from alphatriangle_tpu.config import PersistenceConfig
    from alphatriangle_tpu.training import run_training

    env_cfg, model_cfg, mcts_cfg, train_cfg, tele_cfg = tiny_configs(
        args.run_name
    )
    pc = PersistenceConfig(ROOT_DATA_DIR=args.root_dir, RUN_NAME=args.run_name)
    return run_training(
        train_config=train_cfg,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        persistence_config=pc,
        telemetry_config=tele_cfg,
        use_tensorboard=False,
        log_level="WARNING",
    )


def stage_wedge_beacon(root: Path) -> int:
    """Beacons armed by env + injected dispatch hang -> watchdog 113 ->
    wedge report + doctor verdict naming the beacon phase."""
    from alphatriangle_tpu.config import PersistenceConfig

    run = "devstats_wedge"
    run_dir = PersistenceConfig(
        ROOT_DATA_DIR=str(root), RUN_NAME=run
    ).get_run_base_dir()
    child_env = {
        **os.environ,
        "PYTHONPATH": str(REPO),
        "JAX_PLATFORMS": "cpu",
        # Arm beacons the way an operator (or `cli supervise`'s
        # TELEMETRY__BEACONS respawn override) would: by env, every
        # wave, so the beacon trail is dense around the wedge.
        "ALPHATRIANGLE_BEACONS": "1",
        "ALPHATRIANGLE_BEACON_EVERY": "1",
        # Wedge mid-run: past the first compiles, with beacon rows from
        # completed dispatches already durable on disk.
        "ALPHATRIANGLE_FAULTS": "hang-dispatch@after=6",
        "ALPHATRIANGLE_FAULT_STATE_DIR": str(root / "faults_wedge"),
    }
    proc = subprocess.run(
        [
            sys.executable,
            str(Path(__file__).resolve()),
            "--wedge-child",
            "--root-dir",
            str(root),
            "--run-name",
            run,
        ],
        cwd=str(REPO),
        env=child_env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    if proc.returncode != 113:
        print(
            f"devstats-smoke: wedge child exited {proc.returncode}, "
            f"expected the watchdog's 113\nstdout: {proc.stdout[-2000:]}\n"
            f"stderr: {proc.stderr[-2000:]}",
            file=sys.stderr,
        )
        return 2

    beacons = read_records(run_dir / "beacons.jsonl")
    if not beacons or not all(
        b.get("phase") and isinstance(b.get("index"), int) for b in beacons
    ):
        print(
            f"devstats-smoke: {run_dir}/beacons.jsonl holds "
            f"{len(beacons)} well-formed beacon row(s) — the armed "
            "beacon channel wrote nothing durable",
            file=sys.stderr,
        )
        return 2

    wedge_path = run_dir / "wedge_report.json"
    try:
        wedge = json.loads(wedge_path.read_text())
    except (OSError, json.JSONDecodeError) as exc:
        print(
            f"devstats-smoke: unreadable {wedge_path}: {exc}",
            file=sys.stderr,
        )
        return 2
    frozen = wedge.get("last_beacon")
    if not isinstance(frozen, dict) or not frozen.get("phase"):
        print(
            f"devstats-smoke: wedge report froze no beacon: {frozen}",
            file=sys.stderr,
        )
        return 2

    rc, verdict = run_doctor(run_dir)
    if verdict is None:
        return 2
    if verdict.get("verdict") not in ("dispatch-hung", "compile-hung"):
        print(
            f"devstats-smoke: doctor verdict {verdict.get('verdict')!r}, "
            "expected a hung classification",
            file=sys.stderr,
        )
        return 2
    doc_beacon = verdict.get("last_beacon")
    if (
        not isinstance(doc_beacon, dict)
        or doc_beacon.get("phase") != frozen["phase"]
        or "last beacon" not in str(verdict.get("detail"))
    ):
        print(
            "devstats-smoke: doctor verdict does not carry the frozen "
            f"beacon: verdict {verdict}",
            file=sys.stderr,
        )
        return 2
    print(
        f"devstats-smoke: wedge died by watchdog 113; {len(beacons)} "
        f"beacon row(s); doctor {verdict['verdict']} at phase "
        f"{doc_beacon['phase']}#{doc_beacon.get('index')} "
        f"(program {verdict.get('program')})"
    )
    return 0


def main() -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--root-dir", default=None)
    parser.add_argument("--run-name", default="devstats_wedge")
    parser.add_argument(
        "--wedge-child",
        action="store_true",
        help=argparse.SUPPRESS,  # internal: the stage-3 training child
    )
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    if args.wedge_child:
        return wedge_child(args)

    root = Path(args.root_dir or tempfile.mkdtemp(prefix="at_devstats_"))
    stages = [
        ("stat-pack ledger", stage_statpack_ledger),
        ("overhead", stage_overhead),
        ("wedge beacon", stage_wedge_beacon),
    ]
    try:
        for name, stage in stages:
            print(f"devstats-smoke: {name} gate...", flush=True)
            rc = stage(root)
            if rc != 0:
                return rc
    finally:
        if args.root_dir is None:
            shutil.rmtree(root, ignore_errors=True)
    print("devstats-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
