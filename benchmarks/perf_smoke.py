"""CI perf-smoke gate: short CPU training run -> `cli perf`/`cli compare`.

`make perf-smoke` runs this. It proves, on any machine with no
accelerator, that the metrics-ledger pipeline end to end still works:

1. a tiny CPU training run (test-sized world, ~8 learner steps) writes
   `metrics.jsonl` with utilization records (non-null MFU via the
   ALPHATRIANGLE_PEAK_TFLOPS override this script sets);
2. the run's ledger carries memory observability records
   (docs/OBSERVABILITY.md "Memory"): `kind: "memory"` attribution
   lines (train state / replay ring / AOT program analysis) and
   `mem_bytes_in_use` on the utilization records;
3. `cli perf <run>` summarizes it — exit 2 there means the ledger
   schema broke;
4. `cli fit cpu` composes the CPU-scale static memory budget against
   the host byte limit and must exit 0 (the OOM pre-flight gate);
5. `cli compare <run> benchmarks/perf_reference_cpu_smoke.json`
   gates against the checked-in reference summary. The threshold is
   deliberately generous (default 0.9: fail only on a >90% collapse)
   because CI hosts vary wildly in speed — the hard signal here is
   schema alignment plus "not catastrophically slower", not a tight
   perf bar (that's what `cli compare` against same-hardware runs is
   for).

Exit 0 when every stage passes; the first failing stage's code
otherwise. Regenerate the reference with --write-reference after an
intentional schema change.
"""

import argparse
import os
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
REFERENCE = Path(__file__).resolve().parent / "perf_reference_cpu_smoke.json"
RUN_NAME = "perf_smoke"

# Runnable as `python benchmarks/perf_smoke.py` without installing the
# package: the repo root is the import root.
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

# Must precede any jax import: the smoke must not wake (or wedge on) an
# accelerator, and the peak override is what makes CPU MFU non-null.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ALPHATRIANGLE_PEAK_TFLOPS", "1.0")


def tiny_configs():
    """The test suite's tiny world (tests/conftest.py), inlined so the
    smoke needs no pytest machinery."""
    from alphatriangle_tpu.config import (
        AlphaTriangleMCTSConfig,
        EnvConfig,
        ModelConfig,
        TrainConfig,
        expected_other_features_dim,
    )

    env_cfg = EnvConfig(
        ROWS=3,
        COLS=4,
        PLAYABLE_RANGE_PER_ROW=[(0, 4), (0, 4), (0, 4)],
        NUM_SHAPE_SLOTS=1,
        MAX_SHAPE_TRIANGLES=3,
        LINE_MIN_LENGTH=3,
    )
    model_cfg = ModelConfig(
        GRID_INPUT_CHANNELS=1,
        CONV_FILTERS=[4],
        CONV_KERNEL_SIZES=[3],
        CONV_STRIDES=[1],
        NUM_RESIDUAL_BLOCKS=0,
        RESIDUAL_BLOCK_FILTERS=4,
        USE_TRANSFORMER=False,
        FC_DIMS_SHARED=[16],
        POLICY_HEAD_DIMS=[16],
        VALUE_HEAD_DIMS=[16],
        OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(env_cfg),
        NUM_VALUE_ATOMS=11,
        COMPUTE_DTYPE="float32",
        # The smokes run with the bf16 inference path ON (nn/precision.py,
        # docs/KERNELS.md): rollout + serve forwards consume bf16-cast
        # params while the learner keeps updating the f32 originals —
        # this gate proves the cast path end to end on CPU, not speed.
        INFERENCE_PRECISION="bfloat16",
    )
    mcts_cfg = AlphaTriangleMCTSConfig(max_simulations=4, max_depth=4)
    train_cfg = TrainConfig(
        RUN_NAME=RUN_NAME,
        AUTO_RESUME_LATEST=False,
        MAX_TRAINING_STEPS=8,
        SELF_PLAY_BATCH_SIZE=4,
        ROLLOUT_CHUNK_MOVES=4,
        BATCH_SIZE=8,
        BUFFER_CAPACITY=2000,
        MIN_BUFFER_SIZE_TO_TRAIN=16,
        USE_PER=True,
        PER_BETA_ANNEAL_STEPS=8,
        N_STEP_RETURNS=2,
        WORKER_UPDATE_FREQ_STEPS=2,
        CHECKPOINT_SAVE_FREQ_STEPS=4,
        MAX_EPISODE_MOVES=30,
        RANDOM_SEED=5,
        DEVICE="cpu",
    )
    return env_cfg, model_cfg, mcts_cfg, train_cfg


def dp_child(args) -> int:
    """The 2-device dp-sharded megastep stage (runs in a subprocess).

    The parent spawns this module with
    `XLA_FLAGS=--xla_force_host_platform_device_count=2` so the CPU
    backend presents two devices — the flag must be set before the
    process's first jax import, hence a child process rather than a
    stage in the parent. Runs a 4-step FUSED_MEGASTEP training loop
    sharded over dp=2 and gates on the ledger's mesh-level dispatch
    gauge: one host dispatch per iteration regardless of mesh width.
    """
    import json

    import jax

    if jax.device_count() < 2:
        print(
            f"perf-smoke[dp]: expected >=2 devices, got "
            f"{jax.device_count()} — XLA_FLAGS not applied?",
            file=sys.stderr,
        )
        return 2

    from alphatriangle_tpu.config import (
        MeshConfig,
        PersistenceConfig,
        TrainConfig,
    )
    from alphatriangle_tpu.training import run_training

    env_cfg, model_cfg, mcts_cfg, train_cfg = tiny_configs()
    dp_run = f"{RUN_NAME}_megastep_dp2"
    dp_cfg = TrainConfig(
        **{
            **train_cfg.model_dump(),
            "RUN_NAME": dp_run,
            "FUSED_MEGASTEP": True,
            "DEVICE_REPLAY": "on",
            "FUSED_LEARNER_STEPS": 2,
            "MAX_TRAINING_STEPS": 4,
        }
    )
    dp_pc = PersistenceConfig(ROOT_DATA_DIR=args.root_dir, RUN_NAME=dp_run)
    rc = run_training(
        train_config=dp_cfg,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        persistence_config=dp_pc,
        mesh_config=MeshConfig(DP_SIZE=2),
        use_tensorboard=False,
        log_level="WARNING",
    )
    if rc != 0:
        print(
            f"perf-smoke[dp]: dp=2 megastep run failed (rc={rc})",
            file=sys.stderr,
        )
        return rc
    ledger = dp_pc.get_run_base_dir() / "metrics.jsonl"
    utils = [
        r
        for line in ledger.read_text().splitlines()
        for r in [json.loads(line)]
        if r.get("kind") == "util"
        and isinstance(r.get("dispatches_per_iteration"), (int, float))
    ]
    if not utils:
        print(
            f"perf-smoke[dp]: {ledger} has no util record with "
            "dispatches_per_iteration",
            file=sys.stderr,
        )
        return 2
    dpi = utils[-1]["dispatches_per_iteration"]
    mesh_devices = utils[-1].get("mesh_devices")
    # The gauge counts mesh-level program launches: a dp=2 iteration is
    # still exactly ONE dispatch. mesh_devices is recorded beside it so
    # readers can recover per-device executions.
    if abs(dpi - 1.0) > 1e-6 or mesh_devices != 2:
        print(
            f"perf-smoke[dp]: expected dispatches_per_iteration=1.0 "
            f"with mesh_devices=2, got {dpi} / {mesh_devices}",
            file=sys.stderr,
        )
        return 2
    print(
        f"perf-smoke[dp]: dp=2 megastep ran; dispatches/iteration "
        f"{dpi:.1f} across {mesh_devices} devices"
    )
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--threshold",
        type=float,
        default=0.9,
        help="compare tolerance vs the checked-in reference "
        "(generous by design: CI hosts vary in speed).",
    )
    parser.add_argument(
        "--root-dir",
        default=None,
        help="Runs root for the smoke run (default: a temp dir).",
    )
    parser.add_argument(
        "--write-reference",
        action="store_true",
        help=f"Regenerate {REFERENCE.name} from this run's summary.",
    )
    parser.add_argument(
        "--dp-child",
        action="store_true",
        help=argparse.SUPPRESS,  # internal: the 2-device megastep stage
    )
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    if args.dp_child:
        return dp_child(args)

    from alphatriangle_tpu.cli import main as cli_main
    from alphatriangle_tpu.config import PersistenceConfig
    from alphatriangle_tpu.training import run_training

    root = args.root_dir or tempfile.mkdtemp(prefix="at_perf_smoke_")
    env_cfg, model_cfg, mcts_cfg, train_cfg = tiny_configs()
    pc = PersistenceConfig(ROOT_DATA_DIR=root, RUN_NAME=RUN_NAME)
    print(f"perf-smoke: training {RUN_NAME} under {root}...", flush=True)
    rc = run_training(
        train_config=train_cfg,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        persistence_config=pc,
        use_tensorboard=False,
        log_level="WARNING",
    )
    if rc != 0:
        print(f"perf-smoke: training run failed (rc={rc})", file=sys.stderr)
        return rc

    print("perf-smoke: memory records gate...", flush=True)
    import json as _json

    ledger = pc.get_run_base_dir() / "metrics.jsonl"
    records = []
    for line in ledger.read_text().splitlines():
        try:
            records.append(_json.loads(line))
        except _json.JSONDecodeError:
            continue
    mem_records = [r for r in records if r.get("kind") == "memory"]
    mem_utils = [
        r
        for r in records
        if r.get("kind") == "util"
        and isinstance(r.get("mem_bytes_in_use"), (int, float))
    ]
    if not mem_records or not mem_utils:
        print(
            f"perf-smoke: {ledger} holds {len(mem_records)} memory "
            f"record(s) and {len(mem_utils)} util record(s) with "
            "mem_bytes_in_use — memory observability broke",
            file=sys.stderr,
        )
        return 2
    print(
        f"perf-smoke: {len(mem_records)} memory record(s), "
        f"{len(mem_utils)} util record(s) with live accounting"
    )

    print("perf-smoke: flight recorder gate...", flush=True)
    # The dispatch flight recorder (telemetry/flight.py) must have
    # sealed real dispatches from this run's hot sites — an empty ring
    # means the instrumentation came unwired — and its measured
    # bookkeeping overhead must stay under ~1% of the run's wall time
    # (compared against total wall, not sealed dispatch wall: tiny CPU
    # dispatches make that ratio meaningless).
    from alphatriangle_tpu.telemetry.flight import read_flight
    from alphatriangle_tpu.telemetry.ledger import iter_jsonl_records

    flight_path = pc.get_run_base_dir() / "flight.jsonl"
    flight = read_flight(flight_path)
    seals = [r for r in flight if r.get("phase") == "seal" and r.get("ok")]
    families = {r.get("family") for r in seals}
    if not seals or not {"rollout", "learner"} <= families:
        print(
            f"perf-smoke: {flight_path} holds {len(seals)} sealed "
            f"dispatch(es) across families {sorted(families)} — the "
            "flight recorder came unwired from the hot dispatch sites",
            file=sys.stderr,
        )
        return 2
    run_wall = sum(
        r["window_s"]
        for r in records
        if r.get("kind") == "util"
        and isinstance(r.get("window_s"), (int, float))
    )
    overhead = next(
        (
            r.get("overhead_s")
            for r in reversed(list(iter_jsonl_records(flight_path)))
            if r.get("kind") == "flight_overhead"
        ),
        None,
    )
    if not isinstance(overhead, (int, float)):
        print(
            f"perf-smoke: {flight_path} has no flight_overhead summary "
            "record (FlightRecorder.close never ran?)",
            file=sys.stderr,
        )
        return 2
    if run_wall > 0 and overhead > 0.01 * run_wall:
        print(
            f"perf-smoke: flight overhead {overhead:.3f}s exceeds 1% of "
            f"the run's {run_wall:.1f}s wall — the recorder is on the "
            "hot path",
            file=sys.stderr,
        )
        return 2
    print(
        f"perf-smoke: {len(seals)} sealed dispatch(es) "
        f"({', '.join(sorted(f for f in families if f))}); overhead "
        f"{overhead:.4f}s of {run_wall:.1f}s wall"
    )

    print("perf-smoke: cli perf (schema gate)...", flush=True)
    rc = cli_main(["perf", RUN_NAME, "--root-dir", root])
    if rc != 0:
        print(f"perf-smoke: cli perf failed (rc={rc})", file=sys.stderr)
        return rc

    print("perf-smoke: cli fit cpu (OOM pre-flight gate)...", flush=True)
    rc = cli_main(["fit", "cpu"])
    if rc != 0:
        print(f"perf-smoke: cli fit cpu failed (rc={rc})", file=sys.stderr)
        return rc

    print("perf-smoke: fused-megastep mode gate...", flush=True)
    # A second, even shorter run in FUSED_MEGASTEP mode: the whole
    # iteration (rollout + ingest + on-device sampling + K learner
    # steps) is one device program, and its ledger must carry the
    # dispatches-per-iteration gauge that makes the win measurable.
    from alphatriangle_tpu.config import TrainConfig

    mega_run = f"{RUN_NAME}_megastep"
    mega_cfg = TrainConfig(
        **{
            **train_cfg.model_dump(),
            "RUN_NAME": mega_run,
            "FUSED_MEGASTEP": True,
            "DEVICE_REPLAY": "on",
            "FUSED_LEARNER_STEPS": 2,
            "MAX_TRAINING_STEPS": 4,
        }
    )
    mega_pc = PersistenceConfig(ROOT_DATA_DIR=root, RUN_NAME=mega_run)
    rc = run_training(
        train_config=mega_cfg,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        persistence_config=mega_pc,
        use_tensorboard=False,
        log_level="WARNING",
    )
    if rc != 0:
        print(
            f"perf-smoke: megastep run failed (rc={rc})", file=sys.stderr
        )
        return rc
    mega_ledger = mega_pc.get_run_base_dir() / "metrics.jsonl"
    mega_dpi = [
        r.get("dispatches_per_iteration")
        for line in mega_ledger.read_text().splitlines()
        for r in [_json.loads(line)]
        if r.get("kind") == "util"
        and isinstance(r.get("dispatches_per_iteration"), (int, float))
    ]
    if not mega_dpi:
        print(
            f"perf-smoke: {mega_ledger} has no util record with "
            "dispatches_per_iteration — the megastep gauge broke",
            file=sys.stderr,
        )
        return 2
    print(
        f"perf-smoke: megastep ran; dispatches/iteration "
        f"{mega_dpi[-1]:.1f} (last tick)"
    )

    print("perf-smoke: dp-sharded megastep gate (2 devices)...", flush=True)
    # The dp-sharded variant needs a 2-device backend, and
    # --xla_force_host_platform_device_count only takes effect before a
    # process's first jax import — so the stage runs in a child process
    # (dp_child above) with its own XLA_FLAGS.
    import subprocess

    child_env = {
        **os.environ,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
    }
    child = subprocess.run(
        [
            sys.executable,
            str(Path(__file__).resolve()),
            "--dp-child",
            "--root-dir",
            root,
        ],
        cwd=str(REPO),
        env=child_env,
        timeout=600,
    )
    if child.returncode != 0:
        print(
            f"perf-smoke: dp-sharded gate failed (rc={child.returncode})",
            file=sys.stderr,
        )
        return child.returncode

    if args.write_reference:
        import contextlib
        import io
        import json

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli_main(["perf", RUN_NAME, "--root-dir", root, "--json"])
        if rc != 0:
            return rc
        summary = json.loads(buf.getvalue())
        summary["source"] = "benchmarks/perf_smoke.py --write-reference"
        # The serve smoke (benchmarks/serve_smoke.py) merges its
        # serve_* SLO rows into this same reference file; preserve
        # them across training-side regenerations.
        if REFERENCE.exists():
            try:
                old = json.loads(REFERENCE.read_text())
                summary.update(
                    {
                        k: v
                        for k, v in old.items()
                        if k.startswith("serve_")
                    }
                )
            except json.JSONDecodeError:
                pass
        REFERENCE.write_text(json.dumps(summary, indent=2) + "\n")
        print(f"perf-smoke: reference written to {REFERENCE}")
        return 0

    print(
        f"perf-smoke: cli compare vs {REFERENCE.name} "
        f"(threshold {args.threshold:.0%})...",
        flush=True,
    )
    rc = cli_main(
        [
            "compare",
            RUN_NAME,
            str(REFERENCE),
            "--root-dir",
            root,
            "--threshold",
            str(args.threshold),
        ]
    )
    if rc != 0:
        print(f"perf-smoke: cli compare failed (rc={rc})", file=sys.stderr)
        return rc
    if args.root_dir is None:
        shutil.rmtree(root, ignore_errors=True)
    print("perf-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
