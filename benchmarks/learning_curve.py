"""Learning proof: the full pipeline improves play on the tiny board.

Trains the real stack end to end — batched self-play (wave MCTS +
temperature schedule + n-step windows) -> PER buffer -> sharded-jit
learner with periodic weight sync — on the 3x4/1-slot board, tracking
the mean self-play episode score per bucket of learner steps. Rising
scores validate the whole loop: experience plumbing, policy targets,
C51 value learning, and the search's use of the improving net.

Usage:  JAX_PLATFORMS=cpu python benchmarks/learning_curve.py
Env:    LEARN_STEPS=N (default 400), LEARN_EVAL_GAMES=N (default 64)
Writes benchmarks/learning_curve_results.json.
"""

import json
import os
import sys
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")
# XLA:CPU persistent-cache RELOADS of donating programs silently return
# unchanged outputs in this image (see tests/conftest.py) — a cached
# learner step here would fake a flat learning curve; never enable it.

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from alphatriangle_tpu.config import (
    AlphaTriangleMCTSConfig,
    EnvConfig,
    ModelConfig,
    TrainConfig,
    expected_other_features_dim,
)
from alphatriangle_tpu.env.engine import TriangleEnv
from alphatriangle_tpu.features.core import get_feature_extractor
from alphatriangle_tpu.nn.network import NeuralNetwork
from alphatriangle_tpu.rl import ExperienceBuffer, SelfPlayEngine, Trainer


def small_board_env() -> EnvConfig:
    """The 4x6/2-slot 'small' learning board — a meaningfully larger
    decision space than the luck-bounded 3x4 (action_dim 48 vs 12,
    two-slot choice), still CPU-tractable. Shared with
    `async_learning_proof.py` so its BASELINE.md row stays
    apples-to-apples with the curves measured here."""
    return EnvConfig(
        ROWS=4,
        COLS=6,
        PLAYABLE_RANGE_PER_ROW=[(0, 6)] * 4,
        NUM_SHAPE_SLOTS=2,
    )


def curve_model(env_cfg: EnvConfig) -> ModelConfig:
    """The learning-harness net (shared with async_learning_proof.py)."""
    return ModelConfig(
        GRID_INPUT_CHANNELS=1,
        CONV_FILTERS=[16],
        CONV_KERNEL_SIZES=[3],
        CONV_STRIDES=[1],
        NUM_RESIDUAL_BLOCKS=1,
        RESIDUAL_BLOCK_FILTERS=16,
        USE_TRANSFORMER=False,
        FC_DIMS_SHARED=[32],
        POLICY_HEAD_DIMS=[32],
        VALUE_HEAD_DIMS=[32],
        NUM_VALUE_ATOMS=21,
        VALUE_MIN=-5.0,
        VALUE_MAX=30.0,
        OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(env_cfg),
    )


def build():
    if os.environ.get("LEARN_BOARD") == "small":
        env_cfg = small_board_env()
    else:
        env_cfg = EnvConfig(
            ROWS=3,
            COLS=4,
            PLAYABLE_RANGE_PER_ROW=[(0, 4), (0, 4), (0, 4)],
            NUM_SHAPE_SLOTS=1,
        )
    model_cfg = curve_model(env_cfg)
    mcts_cfg = AlphaTriangleMCTSConfig(
        max_simulations=16,
        max_depth=6,
        mcts_batch_size=8,
        # LEARN_GUMBEL=1 A/Bs the Gumbel sequential-halving root
        # (mcts/gumbel.py) against reference-parity PUCT.
        root_selection=(
            "gumbel" if os.environ.get("LEARN_GUMBEL") == "1" else "puct"
        ),
        gumbel_m=8,
        # Follows the config default (paper c_scale=1.0); override to
        # reproduce the sweep rows in docs/MCTS_DESIGN.md §d.
        gumbel_c_scale=float(os.environ.get("LEARN_GUMBEL_CSCALE", "1.0")),
        # LEARN_PCR=1 A/Bs playout cap randomization: 4-sim fast
        # searches for 75% of moves (policy targets only from the
        # 16-sim full searches).
        fast_simulations=(4 if os.environ.get("LEARN_PCR") == "1" else None),
    )
    train_cfg = TrainConfig(
        SELF_PLAY_BATCH_SIZE=32,
        ROLLOUT_CHUNK_MOVES=4,
        BATCH_SIZE=64,
        BUFFER_CAPACITY=20_000,
        MIN_BUFFER_SIZE_TO_TRAIN=512,
        MAX_TRAINING_STEPS=10_000,
        WORKER_UPDATE_FREQ_STEPS=10,
        LEARNING_RATE=1e-3,
        N_STEP_RETURNS=3,
        TEMPERATURE_ANNEAL_MOVES=8,
        RUN_NAME="learning_curve",
    )
    env = TriangleEnv(env_cfg)
    extractor = get_feature_extractor(env, model_cfg)
    net = NeuralNetwork(model_cfg, env_cfg, seed=0)
    engine = SelfPlayEngine(env, extractor, net, mcts_cfg, train_cfg, seed=0)
    buffer = ExperienceBuffer(train_cfg, action_dim=env_cfg.action_dim)
    trainer = Trainer(net, train_cfg)
    return env_cfg, train_cfg, net, engine, buffer, trainer


def greedy_eval(env, net, mcts, games: int, max_moves: int, seed: int) -> float:
    """Mean final score of `games` greedy-from-visits games."""
    import jax.numpy as jnp

    states = env.reset_batch(
        jax.random.split(jax.random.PRNGKey(seed), games)
    )
    for move in range(max_moves):
        done = np.asarray(states.done)
        if done.all():
            break
        out = mcts.search(
            net.variables, states, jax.random.PRNGKey(seed * 999 + move)
        )
        counts = np.asarray(out.visit_counts)
        actions = np.where(counts.sum(axis=1) > 0, counts.argmax(axis=1), 0)
        states, _, _ = env.step_batch(
            states, jnp.asarray(actions, dtype=jnp.int32)
        )
    return float(np.asarray(states.score).mean())


def main() -> None:
    max_steps = int(os.environ.get("LEARN_STEPS", "400"))
    eval_games = int(os.environ.get("LEARN_EVAL_GAMES", "256"))
    bucket = max(1, max_steps // 8)
    env_cfg, train_cfg, net, engine, buffer, trainer = build()

    # Greedy strength probe: same search config as self-play but
    # deterministic play, evaluated at fixed trainer steps.
    from alphatriangle_tpu.mcts import BatchedMCTS

    eval_mcts = BatchedMCTS(
        engine.env,
        engine.extractor,
        net.model,
        engine.mcts_config.model_copy(update={"dirichlet_epsilon": 0.0}),
        net.support,
    )
    eval_points: list[tuple[int, float]] = []

    def run_eval(step):
        score = np.mean(
            [
                greedy_eval(engine.env, net, eval_mcts, eval_games, 60, s)
                for s in (11, 22)
            ]
        )
        eval_points.append((step, round(float(score), 3)))
        print(f"greedy eval @ step {step}: {score:.3f}", flush=True)

    t_start = time.time()
    run_eval(0)
    scores: list[tuple[int, float, int]] = []  # (step, mean_score, n)
    bucket_scores: list[float] = []
    steps = 0
    while steps < max_steps:
        engine.play_chunk()
        result = engine.harvest()
        bucket_scores.extend(result.episode_scores)
        if result.num_experiences:
            buffer.add_dense(
                result.grid,
                result.other_features,
                result.policy_target,
                result.value_target,
            )
        if len(buffer) < train_cfg.MIN_BUFFER_SIZE_TO_TRAIN:
            continue
        # Replay ratio ~2 samples per produced experience at this scale.
        n_train = max(
            1, (2 * result.num_experiences) // train_cfg.BATCH_SIZE
        )
        for _ in range(n_train):
            if steps >= max_steps:
                break
            sample = buffer.sample(
                train_cfg.BATCH_SIZE, current_train_step=steps
            )
            if sample is None:
                break
            out = trainer.train_step(sample["batch"])
            metrics, td = out
            buffer.update_priorities(sample["indices"], td)
            steps += 1
            if steps % train_cfg.WORKER_UPDATE_FREQ_STEPS == 0:
                trainer.sync_to_network()
            if steps % bucket == 0:
                mean = (
                    float(np.mean(bucket_scores)) if bucket_scores else None
                )
                scores.append((steps, mean, len(bucket_scores)))
                print(
                    f"step {steps}: mean_score={mean} "
                    f"({len(bucket_scores)} episodes, "
                    f"loss={metrics['total_loss']:.3f}, "
                    f"{time.time() - t_start:.0f}s)",
                    flush=True,
                )
                bucket_scores = []
                if steps in (max_steps // 2, max_steps):
                    trainer.sync_to_network()
                    run_eval(steps)

    results = {
        "board": (
            "4x6/2-slot"
            if os.environ.get("LEARN_BOARD") == "small"
            else "3x4/1-slot"
        ),
        "max_steps": max_steps,
        "eval_games_per_point": eval_games * 2,
        "self_play_curve": [
            {"step": s, "mean_score": m, "episodes": n}
            for s, m, n in scores
        ],
        "greedy_eval_curve": [
            {"step": s, "mean_score": m} for s, m in eval_points
        ],
        "seconds": round(time.time() - t_start, 1),
    }
    if len(eval_points) >= 2:
        results["greedy_initial"] = eval_points[0][1]
        results["greedy_final"] = eval_points[-1][1]
        results["improved"] = eval_points[-1][1] > eval_points[0][1]
    suffix = "_gumbel" if os.environ.get("LEARN_GUMBEL") == "1" else ""
    if os.environ.get("LEARN_BOARD") == "small":
        suffix += "_small"
    if os.environ.get("LEARN_PCR") == "1":
        suffix += "_pcr"
    if suffix.startswith("_gumbel"):
        results["gumbel_c_scale"] = float(
            os.environ.get("LEARN_GUMBEL_CSCALE", "1.0")
        )
        if os.environ.get("LEARN_GUMBEL_CSCALE"):
            suffix += f"_cs{os.environ['LEARN_GUMBEL_CSCALE']}"
    results["root_selection"] = (
        "gumbel" if os.environ.get("LEARN_GUMBEL") == "1" else "puct"
    )
    results["playout_cap_randomization"] = (
        os.environ.get("LEARN_PCR") == "1"
    )
    out_path = Path(__file__).parent / f"learning_curve_results{suffix}.json"
    out_path.write_text(json.dumps(results, indent=2))
    print(json.dumps(results))


if __name__ == "__main__":
    main()
