"""CI serve-smoke gate: `cli serve --smoke` -> `cli perf`/`cli compare`.

`make serve-smoke` runs this. It proves, on any machine with no
accelerator, that the policy-serving front end (docs/SERVING.md) works
end to end:

1. a run dir with the test-sized world's configs.json is staged —
   with int8 weight-only inference ON (`INFERENCE_PRECISION="int8"`,
   nn/precision.py) — and `cli serve --smoke` storms the serve-shape
   ladder (`--buckets 16,32,64`, serving/buckets.py): the burst of
   96 sessions against a 16-slot base rung drives the micro-batcher
   up >= 1 rung (to 64 concurrent at the top) and the drain walks it
   back down; sessions admit AND retire mid-run, AOT warm start (every
   rung) and the OOM pre-flight (every rung) on the way up. Gates:
   every rung switch is zero-recompile (the compile-cache event count
   stays at exactly one entry per rung — the warm), and zero requests
   are lost (every session serves to completion);
2. the serve run's `metrics.jsonl` must carry `kind: "util"` records
   with per-request latency SLO fields (`serve_move_latency_ms_p50/
   p95`, `serve_queue_wait_ms_*`, `serve_requests_per_sec`) plus the
   ladder gauges (`serve_bucket`, `serve_fill`), and the folded
   buckets must show the walk (max above the base rung, final below
   the max);
3. `cli perf <serve_run> --json` must summarize them, serve_bucket /
   serve_fill included (exit 2 = the ledger schema broke);
4. `cli compare <serve_run> benchmarks/perf_reference_cpu_smoke.json
   --metrics serve_move_latency_ms_p95,serve_requests_per_sec` gates
   the serve SLO rows against the checked-in reference. The threshold
   is deliberately generous (default 3.0: fail only on a 4x latency
   blowup) because CI hosts vary wildly in speed — the hard signal is
   schema alignment plus "not catastrophically slower".

Exit 0 when every stage passes; the first failing stage's code
otherwise. `--write-reference` merges this run's `serve_*` summary
fields into perf_reference_cpu_smoke.json (preserving the training
smoke's fields — the two smokes share one reference file).
"""

import argparse
import contextlib
import io
import json
import os
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
REFERENCE = Path(__file__).resolve().parent / "perf_reference_cpu_smoke.json"
RUN_NAME = "serve_smoke"

if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

# Must precede any jax import: the smoke must not wake (or wedge on) an
# accelerator.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ALPHATRIANGLE_PEAK_TFLOPS", "1.0")

SERVE_METRICS = "serve_move_latency_ms_p95,serve_requests_per_sec"
BASE_RUNG = 16  # starting serve shape — the burst must outgrow it
BUCKETS = "16,32,64"  # the ladder the storm walks (serving/buckets.py)
SLOTS = 64  # top rung: >= 64 concurrent sessions (the acceptance bar)
SESSIONS = 96  # > SLOTS forces admit/retire churn mid-run


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--threshold",
        type=float,
        default=3.0,
        help="compare tolerance vs the checked-in serve reference "
        "(generous by design: CI hosts vary in speed).",
    )
    parser.add_argument(
        "--root-dir",
        default=None,
        help="Runs root for the smoke (default: a temp dir).",
    )
    parser.add_argument(
        "--write-reference",
        action="store_true",
        help=f"Merge this run's serve_* summary into {REFERENCE.name}.",
    )
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    from alphatriangle_tpu.cli import main as cli_main
    from alphatriangle_tpu.config import PersistenceConfig

    # The training perf smoke's tiny world — one definition, reused.
    from perf_smoke import tiny_configs  # noqa: E402

    root = args.root_dir or tempfile.mkdtemp(prefix="at_serve_smoke_")
    env_cfg, model_cfg, _mcts_cfg, _train_cfg = tiny_configs()
    # int8 weight-only inference ON (nn/precision.py): the smoke
    # proves the quantized serve path end to end on CPU — per-channel
    # int8 weights + f32 scales dispatch through every ladder rung.
    model_cfg = model_cfg.model_copy(
        update={"INFERENCE_PRECISION": "int8"}
    )

    # Stage a run dir whose configs.json pins the tiny world, so
    # `cli serve --run-name` serves it instead of the flagship net.
    src_pc = PersistenceConfig(ROOT_DATA_DIR=root, RUN_NAME=RUN_NAME)
    src_dir = src_pc.get_run_base_dir()
    src_dir.mkdir(parents=True, exist_ok=True)
    (src_dir / "configs.json").write_text(
        json.dumps(
            {"env": env_cfg.model_dump(), "model": model_cfg.model_dump()}
        )
    )

    print(
        f"serve-smoke: storming {SESSIONS} sessions over the "
        f"{{{BUCKETS}}} ladder (base rung {BASE_RUNG}, int8) "
        f"under {root}...",
        flush=True,
    )
    from alphatriangle_tpu.compile_cache import get_compile_cache

    events_before = len(get_compile_cache().stats()["events"])
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(
            [
                "serve",
                "--smoke",
                "--run-name", RUN_NAME,
                "--root-dir", root,
                "--slots", str(BASE_RUNG),
                "--buckets", BUCKETS,
                "--sessions", str(SESSIONS),
                "--sims", "4",
                "--max-moves", "40",
                "--tick-every", "4",
                "--seed", "0",
            ]
        )
    sys.stdout.write(buf.getvalue())
    if rc != 0:
        print(f"serve-smoke: cli serve failed (rc={rc})", file=sys.stderr)
        return rc
    report = json.loads(buf.getvalue().strip().splitlines()[-1])
    # Zero lost requests: every session of the burst served to
    # completion despite the mid-stream rung switches.
    if report["sessions_served"] < SESSIONS:
        print(
            f"serve-smoke: only {report['sessions_served']} of "
            f"{SESSIONS} sessions served",
            file=sys.stderr,
        )
        return 1
    # Churn proof: more sessions than slots can only complete by
    # retiring finished sessions and admitting replacements mid-run.
    if report["sessions_served"] <= SLOTS:
        print("serve-smoke: no churn exercised", file=sys.stderr)
        return 1
    # Ladder walk proof, part 1 (the service's own counter): the burst
    # must force at least one walk-up and the drain one walk-down.
    if report.get("rung_switches", 0) < 2:
        print(
            f"serve-smoke: only {report.get('rung_switches')} rung "
            "switch(es) — the storm never walked the ladder",
            file=sys.stderr,
        )
        return 1
    # Zero-recompile gate: after the up-front all-rung warm, rung
    # switches must never touch the compiler — the compile-cache event
    # log (one entry per compile/deserialize, never per dispatch) may
    # hold exactly one entry per serve rung for this run.
    serve_events = [
        e
        for e in get_compile_cache().stats()["events"][events_before:]
        if str(e.get("program", "")).startswith("serve/b")
    ]
    rungs = len(BUCKETS.split(","))
    if len(serve_events) != rungs:
        print(
            f"serve-smoke: {len(serve_events)} serve compile events for "
            f"{rungs} rungs — a rung switch recompiled: {serve_events}",
            file=sys.stderr,
        )
        return 1
    print(
        f"serve-smoke: {report['rung_switches']} rung switches, "
        f"{len(serve_events)} compiles for {rungs} rungs (zero "
        "recompiles after warm)"
    )

    serve_run = f"serve_{RUN_NAME}"
    serve_pc = PersistenceConfig(ROOT_DATA_DIR=root, RUN_NAME=serve_run)
    ledger = serve_pc.get_run_base_dir() / "metrics.jsonl"
    lat_records = []
    for line in ledger.read_text().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("kind") == "util" and isinstance(
            rec.get("serve_move_latency_ms_p95"), (int, float)
        ):
            lat_records.append(rec)
    if not lat_records:
        print(
            f"serve-smoke: {ledger} holds no util record with serve "
            "latency fields — the SLO ledger broke",
            file=sys.stderr,
        )
        return 2
    # Ladder walk proof, part 2 (the ledger's view): every util record
    # carries the serve_bucket/serve_fill gauges, the folded buckets
    # climb above the base rung, and the final record sits below the
    # max (the drain walked back down).
    buckets_seen = [
        r.get("serve_bucket")
        for r in lat_records
        if isinstance(r.get("serve_bucket"), int)
    ]
    fills_seen = [
        r.get("serve_fill")
        for r in lat_records
        if isinstance(r.get("serve_fill"), (int, float))
    ]
    if not buckets_seen or not fills_seen:
        print(
            "serve-smoke: ledger util records lack serve_bucket/"
            "serve_fill gauges",
            file=sys.stderr,
        )
        return 2
    if max(buckets_seen) <= BASE_RUNG:
        print(
            f"serve-smoke: ledger never saw a rung above the base "
            f"({sorted(set(buckets_seen))})",
            file=sys.stderr,
        )
        return 1
    if buckets_seen[-1] >= max(buckets_seen):
        print(
            f"serve-smoke: final rung {buckets_seen[-1]} never walked "
            f"back down from the max {max(buckets_seen)}",
            file=sys.stderr,
        )
        return 1
    print(
        f"serve-smoke: {len(lat_records)} ledger record(s) with "
        f"per-request latency fields; rungs {sorted(set(buckets_seen))}, "
        f"final {buckets_seen[-1]}"
    )

    print("serve-smoke: cli perf --json (schema gate)...", flush=True)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(["perf", serve_run, "--root-dir", root, "--json"])
    if rc != 0:
        print(f"serve-smoke: cli perf failed (rc={rc})", file=sys.stderr)
        return rc
    summary = json.loads(buf.getvalue())
    for key in (
        "serve_move_latency_ms_p50",
        "serve_move_latency_ms_p95",
        "serve_requests_per_sec",
        "serve_bucket",
        "serve_fill",
    ):
        if not isinstance(summary.get(key), (int, float)):
            print(
                f"serve-smoke: cli perf --json lacks {key}",
                file=sys.stderr,
            )
            return 2
    print(
        "serve-smoke: move latency p50 "
        f"{summary['serve_move_latency_ms_p50']:.1f}ms, p95 "
        f"{summary['serve_move_latency_ms_p95']:.1f}ms, "
        f"{summary['serve_requests_per_sec']:.0f} req/s"
    )

    if args.write_reference:
        reference = (
            json.loads(REFERENCE.read_text()) if REFERENCE.exists() else {}
        )
        reference.update(
            {
                k: v
                for k, v in summary.items()
                if k.startswith("serve_")
            }
        )
        reference.setdefault("schema", summary["schema"])
        REFERENCE.write_text(json.dumps(reference, indent=2) + "\n")
        print(f"serve-smoke: serve rows merged into {REFERENCE}")
        return 0

    print(
        f"serve-smoke: cli compare vs {REFERENCE.name} "
        f"(serve SLO rows, threshold {args.threshold:.0%})...",
        flush=True,
    )
    rc = cli_main(
        [
            "compare",
            serve_run,
            str(REFERENCE),
            "--root-dir", root,
            "--threshold", str(args.threshold),
            "--metrics", SERVE_METRICS,
        ]
    )
    if rc != 0:
        print(f"serve-smoke: cli compare failed (rc={rc})", file=sys.stderr)
        return rc
    if args.root_dir is None:
        shutil.rmtree(root, ignore_errors=True)
    print("serve-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
