"""CI serve-smoke gate: `cli serve --smoke` -> `cli perf`/`cli compare`.

`make serve-smoke` runs this. It proves, on any machine with no
accelerator, that the policy-serving front end (docs/SERVING.md) works
end to end:

1. a run dir with the test-sized world's configs.json is staged, and
   `cli serve --smoke` serves >= 64 concurrent simulated sessions
   through batched search dispatches — sessions admitted AND retired
   mid-run (total sessions > slot count forces churn), AOT warm start
   and the OOM pre-flight on the way up;
2. the serve run's `metrics.jsonl` must carry `kind: "util"` records
   with per-request latency SLO fields (`serve_move_latency_ms_p50/
   p95`, `serve_queue_wait_ms_*`, `serve_requests_per_sec`);
3. `cli perf <serve_run> --json` must summarize them (exit 2 = the
   ledger schema broke);
4. `cli compare <serve_run> benchmarks/perf_reference_cpu_smoke.json
   --metrics serve_move_latency_ms_p95,serve_requests_per_sec` gates
   the serve SLO rows against the checked-in reference. The threshold
   is deliberately generous (default 3.0: fail only on a 4x latency
   blowup) because CI hosts vary wildly in speed — the hard signal is
   schema alignment plus "not catastrophically slower".

Exit 0 when every stage passes; the first failing stage's code
otherwise. `--write-reference` merges this run's `serve_*` summary
fields into perf_reference_cpu_smoke.json (preserving the training
smoke's fields — the two smokes share one reference file).
"""

import argparse
import contextlib
import io
import json
import os
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
REFERENCE = Path(__file__).resolve().parent / "perf_reference_cpu_smoke.json"
RUN_NAME = "serve_smoke"

if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

# Must precede any jax import: the smoke must not wake (or wedge on) an
# accelerator.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ALPHATRIANGLE_PEAK_TFLOPS", "1.0")

SERVE_METRICS = "serve_move_latency_ms_p95,serve_requests_per_sec"
SLOTS = 64  # >= 64 concurrent sessions (the acceptance bar)
SESSIONS = 96  # > SLOTS forces admit/retire churn mid-run


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--threshold",
        type=float,
        default=3.0,
        help="compare tolerance vs the checked-in serve reference "
        "(generous by design: CI hosts vary in speed).",
    )
    parser.add_argument(
        "--root-dir",
        default=None,
        help="Runs root for the smoke (default: a temp dir).",
    )
    parser.add_argument(
        "--write-reference",
        action="store_true",
        help=f"Merge this run's serve_* summary into {REFERENCE.name}.",
    )
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    from alphatriangle_tpu.cli import main as cli_main
    from alphatriangle_tpu.config import PersistenceConfig

    # The training perf smoke's tiny world — one definition, reused.
    from perf_smoke import tiny_configs  # noqa: E402

    root = args.root_dir or tempfile.mkdtemp(prefix="at_serve_smoke_")
    env_cfg, model_cfg, _mcts_cfg, _train_cfg = tiny_configs()

    # Stage a run dir whose configs.json pins the tiny world, so
    # `cli serve --run-name` serves it instead of the flagship net.
    src_pc = PersistenceConfig(ROOT_DATA_DIR=root, RUN_NAME=RUN_NAME)
    src_dir = src_pc.get_run_base_dir()
    src_dir.mkdir(parents=True, exist_ok=True)
    (src_dir / "configs.json").write_text(
        json.dumps(
            {"env": env_cfg.model_dump(), "model": model_cfg.model_dump()}
        )
    )

    print(
        f"serve-smoke: serving {SESSIONS} sessions over {SLOTS} slots "
        f"under {root}...",
        flush=True,
    )
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(
            [
                "serve",
                "--smoke",
                "--run-name", RUN_NAME,
                "--root-dir", root,
                "--slots", str(SLOTS),
                "--sessions", str(SESSIONS),
                "--sims", "4",
                "--max-moves", "40",
                "--tick-every", "4",
                "--seed", "0",
            ]
        )
    sys.stdout.write(buf.getvalue())
    if rc != 0:
        print(f"serve-smoke: cli serve failed (rc={rc})", file=sys.stderr)
        return rc
    report = json.loads(buf.getvalue().strip().splitlines()[-1])
    if report["sessions_served"] < SESSIONS:
        print(
            f"serve-smoke: only {report['sessions_served']} of "
            f"{SESSIONS} sessions served",
            file=sys.stderr,
        )
        return 1
    # Churn proof: more sessions than slots can only complete by
    # retiring finished sessions and admitting replacements mid-run.
    if report["sessions_served"] <= SLOTS:
        print("serve-smoke: no churn exercised", file=sys.stderr)
        return 1

    serve_run = f"serve_{RUN_NAME}"
    serve_pc = PersistenceConfig(ROOT_DATA_DIR=root, RUN_NAME=serve_run)
    ledger = serve_pc.get_run_base_dir() / "metrics.jsonl"
    lat_records = []
    for line in ledger.read_text().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("kind") == "util" and isinstance(
            rec.get("serve_move_latency_ms_p95"), (int, float)
        ):
            lat_records.append(rec)
    if not lat_records:
        print(
            f"serve-smoke: {ledger} holds no util record with serve "
            "latency fields — the SLO ledger broke",
            file=sys.stderr,
        )
        return 2
    print(
        f"serve-smoke: {len(lat_records)} ledger record(s) with "
        "per-request latency fields"
    )

    print("serve-smoke: cli perf --json (schema gate)...", flush=True)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(["perf", serve_run, "--root-dir", root, "--json"])
    if rc != 0:
        print(f"serve-smoke: cli perf failed (rc={rc})", file=sys.stderr)
        return rc
    summary = json.loads(buf.getvalue())
    for key in (
        "serve_move_latency_ms_p50",
        "serve_move_latency_ms_p95",
        "serve_requests_per_sec",
    ):
        if not isinstance(summary.get(key), (int, float)):
            print(
                f"serve-smoke: cli perf --json lacks {key}",
                file=sys.stderr,
            )
            return 2
    print(
        "serve-smoke: move latency p50 "
        f"{summary['serve_move_latency_ms_p50']:.1f}ms, p95 "
        f"{summary['serve_move_latency_ms_p95']:.1f}ms, "
        f"{summary['serve_requests_per_sec']:.0f} req/s"
    )

    if args.write_reference:
        reference = (
            json.loads(REFERENCE.read_text()) if REFERENCE.exists() else {}
        )
        reference.update(
            {
                k: v
                for k, v in summary.items()
                if k.startswith("serve_")
            }
        )
        reference.setdefault("schema", summary["schema"])
        REFERENCE.write_text(json.dumps(reference, indent=2) + "\n")
        print(f"serve-smoke: serve rows merged into {REFERENCE}")
        return 0

    print(
        f"serve-smoke: cli compare vs {REFERENCE.name} "
        f"(serve SLO rows, threshold {args.threshold:.0%})...",
        flush=True,
    )
    rc = cli_main(
        [
            "compare",
            serve_run,
            str(REFERENCE),
            "--root-dir", root,
            "--threshold", str(args.threshold),
            "--metrics", SERVE_METRICS,
        ]
    )
    if rc != 0:
        print(f"serve-smoke: cli compare failed (rc={rc})", file=sys.stderr)
        return rc
    if args.root_dir is None:
        shutil.rmtree(root, ignore_errors=True)
    print("serve-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
