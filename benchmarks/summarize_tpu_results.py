"""Render a tpu_r{N}_results.jsonl sweep as a BASELINE.md-ready table.

`benchmarks/tpu_round{N}.sh` appends one labeled bench JSON per sweep
section; this prints a markdown table (games/h, leaf-evals/s, learner
steps/s, MFU, overlapped combined rates) plus the gather-lowering A/B
verdict, so the measured numbers drop straight into BASELINE.md.
Default input: the newest tpu_r*_results*.jsonl next to this script.
"""

import json
import sys
from pathlib import Path


def main() -> int:
    if len(sys.argv) > 1:
        path = Path(sys.argv[1])
    else:
        candidates = sorted(
            Path(__file__).parent.glob("tpu_r*_results*.jsonl"),
            key=lambda p: p.stat().st_mtime,
        )
        path = (
            candidates[-1]
            if candidates
            else Path(__file__).parent / "tpu_r5_results.jsonl"
        )
    if not path.is_file():
        print(f"no results at {path}", file=sys.stderr)
        return 1
    # Always say which sweep is being rendered: the mtime default can
    # legitimately resolve to an older round's file (e.g. before the
    # current round's first section lands), and a table with no
    # provenance invites pasting stale numbers into BASELINE.md.
    print(f"reading {path}", file=sys.stderr)
    rows = []
    for i, line in enumerate(path.read_text().splitlines(), 1):
        if not line.strip():
            continue
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError as exc:
            # A killed bench (wedged chip mid-sweep) appends a
            # malformed line; report it, keep the measured rows.
            print(f"skipping malformed line {i}: {exc}", file=sys.stderr)

    print(
        "| label | backend | games/h | leaf-evals/s | learner steps/s "
        "(fused) | device-replay steps/s | self-play MFU | "
        "overlapped g/h (vs serial) | overlapped steps/s |"
    )
    print("|---|---|---|---|---|---|---|---|---|")
    gather = {}
    for row in rows:
        r = row["result"]
        e = r.get("extra", {})
        o = e.get("overlapped", {})
        f = e.get("flops", {})
        mfu = f.get("self_play_mfu")
        print(
            f"| {row['label']} | {e.get('backend')} | {r.get('value'):,} | "
            f"{e.get('mcts_leaf_evals_per_sec')} | "
            f"{e.get('learner_steps_per_sec_fused')} | "
            f"{e.get('learner_steps_per_sec_device_replay')} | "
            f"{mfu if mfu is None else f'{100 * mfu:.1f}%'} | "
            f"{o.get('games_per_hour')} ({o.get('vs_serialized_self_play')}) | "
            f"{o.get('learner_steps_per_sec')} |"
        )
        # Only rows that actually recorded their lowering enter the
        # A/B (an errored bench emits no descent_gather; defaulting it
        # would overwrite a real einsum row with the failure's 0.0).
        if (
            row["label"].startswith("gather_")
            or row["label"] == "flagship_gumbel_pcr"
        ) and e.get("descent_gather"):
            gather[e["descent_gather"]] = r.get("value")
    if len(gather) > 1:
        best = max(gather, key=lambda k: gather[k] or 0)
        print(
            f"\ngather A/B (games/h): "
            + ", ".join(f"{k}={v}" for k, v in gather.items())
            + f" -> best: {best}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
