#!/bin/bash
# Round-5 healthy-window orchestrator (run by benchmarks/tpu_watch.sh).
#
# Priority order per the round-5 plan:
#   1. The four headline sweep sections (flagship device-replay learner
#      + overlapped numbers, presets 2 and 4) — minutes each, resumable.
#   2. The on-hardware training run (hours; checkpoint-stall watchdog
#      inside tpu_training_run.py survives mid-run wedges).
#   3. The remaining sweep sections (A/Bs, presets 3/5, profile).
#
# Every phase is resumable/idempotent, so the watcher can relaunch this
# script across as many healthy windows as it takes.
set -u
cd "$(dirname "$0")/.."

KEY="flagship_gumbel_pcr flagship_puct preset2 preset4"
BENCH_SECTIONS="$KEY" bash benchmarks/tpu_round5.sh || exit 1
python benchmarks/tpu_training_run.py --steps 2000 --kill-at 600 \
  --run-name tpu_flagship_r5 --root-dir /tmp/tpu_r5_train || exit 1
# Close the subtree-reuse bet with the just-trained checkpoint
# (docs/MCTS_DESIGN.md §a's revisit criterion; VERDICT r5 item 6).
if [ ! -f benchmarks/reuse_bet_results.json ]; then
  timeout 2400 python benchmarks/reuse_bet_closure.py \
    --run-name tpu_flagship_r5 --root-dir /tmp/tpu_r5_train || true
fi
bash benchmarks/tpu_round5.sh
