#!/bin/bash
# Round-5 healthy-window orchestrator (run by benchmarks/tpu_watch.sh).
#
# Priority order per the round-5 plan:
#   1. The four headline sweep sections (flagship device-replay learner
#      + overlapped numbers, presets 2 and 4) — minutes each, resumable.
#   2. The on-hardware training run (hours; checkpoint-stall watchdog
#      inside tpu_training_run.py survives mid-run wedges).
#   3. The subtree-reuse bet closure on the trained checkpoint.
#   4. The remaining sweep sections (A/Bs, presets 3/5, profile).
#
# ORCH_END_BY (epoch seconds, optional): hard runway limit — phases
# that might not fit are skipped/capped so the chip is FREE by then
# (the round driver runs its own bench at round end; two processes
# contending for the single chip would turn its attempt into a CPU
# fallback). Every phase is resumable/idempotent, so the watcher can
# relaunch this script across as many healthy windows as it takes.
set -u
cd "$(dirname "$0")/.."

end_by=${ORCH_END_BY:-0}
runway() {
  if [ "$end_by" -le 0 ]; then echo 999999; else
    echo $(( end_by - $(date +%s) )); fi
}

KEY="flagship_gumbel_pcr flagship_puct preset2 preset4"
[ "$(runway)" -gt 600 ] || { echo "orchestrator: out of runway" >&2; exit 1; }
# Capped by the remaining runway like every other phase: even the
# "minutes each" key sections can stack past ORCH_END_BY when several
# retry their probe budgets back to back (ADVICE round-5). The sweep
# also re-checks ORCH_END_BY between sections, so TERM here is a
# backstop, not the usual exit path.
BENCH_SECTIONS="$KEY" timeout $(( $(runway) - 60 )) \
  bash benchmarks/tpu_round5.sh || exit 1

r=$(runway)
if [ "$r" -gt 1800 ]; then
  # Cap the training run so the chip is free 10 min before end_by.
  timeout $(( r - 600 )) python benchmarks/tpu_training_run.py \
    --steps 2000 --kill-at 600 \
    --run-name tpu_flagship_r5 --root-dir /tmp/tpu_r5_train || exit 1
else
  echo "orchestrator: skipping training run (runway ${r}s)" >&2
fi

# Close the subtree-reuse bet with the just-trained checkpoint
# (docs/MCTS_DESIGN.md §a's revisit criterion; VERDICT r5 item 6).
if [ ! -f benchmarks/reuse_bet_results.json ] && [ "$(runway)" -gt 1500 ] \
   && ls /tmp/tpu_r5_train/AlphaTriangleTPU/runs/tpu_flagship_r5/checkpoints/step_* >/dev/null 2>&1; then
  timeout $(( $(runway) - 300 )) python benchmarks/reuse_bet_closure.py \
    --run-name tpu_flagship_r5 --root-dir /tmp/tpu_r5_train || true
fi

r=$(runway)
[ "$r" -gt 600 ] || exit 0
# Cap the final full sweep with the remaining runway: an uncapped
# sweep could hold the chip straight through ORCH_END_BY, turning the
# round driver's own bench attempt into a CPU fallback — the exact
# contention the hard-deadline contract exists to prevent. timeout's
# TERM propagates to the sweep's children (each section is resumable,
# so a cut-off sweep just resumes in the next healthy window).
timeout $(( r - 60 )) bash benchmarks/tpu_round5.sh
