"""CI window-forensics gate: torn flight ring -> `cli doctor` verdict.

`make doctor-smoke` runs this. It proves, with no accelerator and no
training run, that the postmortem pipeline the chip watcher depends on
(benchmarks/tpu_watch.sh, docs/OBSERVABILITY.md "Flight recorder &
forensics") still closes end to end:

1. a synthetic run dir with sealed flight records, a final UNSEALED
   intent and byte-torn trailing junk — the exact artifact a SIGKILLed
   run leaves — must classify as dispatch-hung naming the hung program,
   via the `cli doctor` subprocess tpu_watch.sh invokes, with JAX
   imports hard-blocked in that subprocess;
2. a simulated over-deadline dispatch (real `FlightRecorder` +
   `DispatchWatchdog` with a frozen clock and exit-on-wedge off) must
   dump stacks, write `wedge_report.json`, and doctor to the same
   verdict with the wedge report as evidence;
3. sealed flight records beside a minimal metrics ledger must surface
   as per-program device-time rows in `cli perf --json`.

Exit 0 when every stage passes; the first failing stage's code
otherwise.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# Hard import-guard preamble for the doctor subprocess: any jax import
# on the doctor path raises, exactly like tests/test_flight.py's guard.
_NO_JAX_PREAMBLE = (
    "import builtins, sys;"
    "_real = builtins.__import__;\n"
    "def _guard(name, *a, **k):\n"
    "    if name == 'jax' or name.startswith('jax.'):\n"
    "        raise ImportError('cli doctor must not import jax: ' + name)\n"
    "    return _real(name, *a, **k)\n"
    "builtins.__import__ = _guard\n"
)


def run_doctor(run_dir: Path) -> "tuple[int, dict | None]":
    """`cli doctor <run_dir> --json` in a subprocess with jax imports
    blocked — the exact invocation tpu_watch.sh's archive step makes."""
    code = (
        _NO_JAX_PREAMBLE
        + "from alphatriangle_tpu.cli import main\n"
        + f"sys.exit(main(['doctor', {str(run_dir)!r}, '--json']))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=str(REPO),
        env={**os.environ, "PYTHONPATH": str(REPO)},
        capture_output=True,
        text=True,
        timeout=120,
    )
    verdict = None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                verdict = json.loads(line)
            except json.JSONDecodeError:
                pass
    if verdict is None:
        print(
            f"doctor-smoke: no JSON verdict from cli doctor "
            f"(rc={proc.returncode})\nstdout: {proc.stdout}\n"
            f"stderr: {proc.stderr}",
            file=sys.stderr,
        )
    return proc.returncode, verdict


def flight_line(**fields) -> str:
    return json.dumps({"kind": "flight", **fields}) + "\n"


def stage_torn_ring(root: Path) -> int:
    """A SIGKILLed run's artifact, synthesized byte for byte: sealed
    history, one unsealed intent, a torn trailing line."""
    run_dir = root / "torn_ring"
    run_dir.mkdir(parents=True)
    now = time.time()
    lines = [
        flight_line(
            phase="intent", seq=1, program="self_play_chunk/t4",
            family="rollout", avals="B4xT4", expected_s=None,
            deadline_s=900.0, t_mono=10.0, time=now - 120, pid=4242,
        ),
        flight_line(
            phase="seal", seq=1, program="self_play_chunk/t4",
            family="rollout", wall_s=2.5, ok=True, t_mono=12.5,
            time=now - 117,
        ),
        flight_line(
            phase="intent", seq=2, program="self_play_chunk/t4",
            family="rollout", avals="B4xT4", expected_s=2.5,
            deadline_s=60.0, t_mono=13.0, time=now - 110, pid=4242,
        ),
        # Unsealed: the process was SIGKILLed inside this dispatch.
    ]
    torn = '{"kind": "flight", "phase": "seal", "seq": 2, "wal'
    (run_dir / "flight.jsonl").write_text("".join(lines) + torn)
    # A stale heartbeat (no stall flag — the process just vanished).
    (run_dir / "health.json").write_text(
        json.dumps(
            {"time": now - 110, "stalled": False, "learner_step": 0,
             "watchdog_deadline_s": 300.0}
        )
    )
    rc, verdict = run_doctor(run_dir)
    if verdict is None:
        return 2
    if (
        verdict.get("verdict") != "dispatch-hung"
        or verdict.get("program") != "self_play_chunk/t4"
        or verdict.get("family") != "rollout"
        or rc != 4
    ):
        print(
            f"doctor-smoke: torn ring misclassified: rc={rc}, "
            f"verdict={verdict}",
            file=sys.stderr,
        )
        return 2
    print(
        f"doctor-smoke: torn ring -> {verdict['verdict']} "
        f"({verdict['program']}), exit {rc}, no jax imported"
    )
    return 0


def stage_wedge_watchdog(root: Path) -> int:
    """A live over-deadline dispatch: real recorder + watchdog, frozen
    clock, exit-on-wedge off so the report is observable in-process."""
    from alphatriangle_tpu.telemetry.flight import (
        WEDGE_REPORT_FILENAME,
        WEDGE_STACKS_FILENAME,
        DispatchWatchdog,
        FlightRecorder,
        read_wedge_report,
    )

    run_dir = root / "wedged"
    run_dir.mkdir(parents=True)
    clock = {"t": 1000.0}
    watchdog = DispatchWatchdog(
        run_dir, on_wedge=None, exit_on_wedge=False,
        clock=lambda: clock["t"],
    )
    recorder = FlightRecorder(
        run_dir / "flight.jsonl", watchdog=watchdog,
        min_deadline_s=5.0, first_deadline_s=30.0,
    )
    # One healthy dispatch seals and calibrates the expected duration.
    recorder.begin("megastep", "megastep/t4_k2", avals="B4xT4xK2").seal()
    # The second never seals; advance the frozen clock past deadline.
    recorder.begin("megastep", "megastep/t4_k2", avals="B4xT4xK2")
    if watchdog.check() is not None:
        print(
            "doctor-smoke: watchdog fired before the deadline",
            file=sys.stderr,
        )
        return 2
    clock["t"] += 1e6
    report = watchdog.check()
    if report is None or report.get("program") != "megastep/t4_k2":
        print(
            f"doctor-smoke: watchdog did not fire past deadline "
            f"(report={report})",
            file=sys.stderr,
        )
        return 2
    on_disk = read_wedge_report(run_dir / WEDGE_REPORT_FILENAME)
    stacks = run_dir / WEDGE_STACKS_FILENAME
    if on_disk is None or not stacks.exists() or not stacks.read_text():
        print(
            "doctor-smoke: wedge_report.json or stacks missing",
            file=sys.stderr,
        )
        return 2
    rc, verdict = run_doctor(run_dir)
    if verdict is None:
        return 2
    if (
        verdict.get("verdict") != "dispatch-hung"
        or verdict.get("program") != "megastep/t4_k2"
        or not verdict.get("evidence", {}).get("wedge_report")
        or rc != 4
    ):
        print(
            f"doctor-smoke: wedged run misclassified: rc={rc}, "
            f"verdict={verdict}",
            file=sys.stderr,
        )
        return 2
    print(
        f"doctor-smoke: simulated wedge -> wedge_report.json + stacks, "
        f"doctor {verdict['verdict']} ({verdict['program']}), exit {rc}"
    )
    return 0


def stage_perf_programs(root: Path) -> int:
    """Sealed flight records + a minimal util ledger must yield
    per-program rows in `cli perf --json` (the calibrate feed)."""
    import contextlib
    import io

    from alphatriangle_tpu.cli import main as cli_main

    run_dir = root / "perf_programs"
    run_dir.mkdir(parents=True)
    now = time.time()
    utils = [
        json.dumps(
            {"kind": "util", "step": i, "time": now - 60 + i,
             "window_s": 1.0, "learner_steps_per_sec": 1.0,
             "mfu": 0.01, "tflops_per_sec": 0.01,
             "device_kind": "cpu", "step_time_ms": 10.0}
        )
        for i in range(1, 4)
    ]
    (run_dir / "metrics.jsonl").write_text("\n".join(utils) + "\n")
    lines = []
    for seq, wall in enumerate([0.9, 1.1, 1.0], start=1):
        lines.append(
            flight_line(
                phase="intent", seq=seq, program="learner_fused_steps",
                family="learner", avals="K2xB8", expected_s=None,
                deadline_s=900.0, t_mono=float(seq), time=now - 60 + seq,
                pid=1,
            )
        )
        lines.append(
            flight_line(
                phase="seal", seq=seq, program="learner_fused_steps",
                family="learner", wall_s=wall, ok=True,
                t_mono=float(seq) + wall, time=now - 59 + seq,
            )
        )
    (run_dir / "flight.jsonl").write_text("".join(lines))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(["perf", str(run_dir), "--json"])
    if rc != 0:
        print(f"doctor-smoke: cli perf failed (rc={rc})", file=sys.stderr)
        return 2
    summary = json.loads(buf.getvalue())
    programs = summary.get("programs")
    if not programs:
        print(
            "doctor-smoke: cli perf --json has no programs rows",
            file=sys.stderr,
        )
        return 2
    row = programs[0]
    if (
        row.get("program") != "learner_fused_steps"
        or row.get("count") != 3
        or not isinstance(row.get("wall_s_p50"), (int, float))
        or not isinstance(row.get("wall_s_p95"), (int, float))
    ):
        print(
            f"doctor-smoke: malformed programs row: {row}",
            file=sys.stderr,
        )
        return 2
    print(
        f"doctor-smoke: cli perf --json programs -> "
        f"{row['program']} x{row['count']} "
        f"p50 {row['wall_s_p50']:.2f}s p95 {row['wall_s_p95']:.2f}s"
    )
    return 0


def main() -> int:
    root = Path(tempfile.mkdtemp(prefix="at_doctor_smoke_"))
    try:
        for stage in (stage_torn_ring, stage_wedge_watchdog, stage_perf_programs):
            rc = stage(root)
            if rc != 0:
                return rc
    finally:
        shutil.rmtree(root, ignore_errors=True)
    print("doctor-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
