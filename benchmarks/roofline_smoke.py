"""CI roofline-smoke gate: CPU run -> cost sidecars -> `cli roofline`.

`make roofline-smoke` runs this. It proves, on any machine with no
accelerator, that the roofline attribution plane end to end still works
(docs/OBSERVABILITY.md "Roofline & gap attribution"):

1. a tiny CPU training run + a fused-megastep run + a serve-program
   analysis leave `.cost.json` sidecars (XLA `cost_analysis()` FLOPs /
   bytes-accessed, compile_cache.py) covering the rollout, learner,
   megastep and serve program families;
2. `cli roofline <run>` (JAX-free) classifies every hot family in the
   training run compute- vs memory-bound with a roofline fraction
   (non-null via the ALPHATRIANGLE_PEAK_TFLOPS / _PEAK_HBM_GBPS
   overrides this script sets) and attributes >= 95% of the run's wall
   across dispatch + named gap categories;
3. the chip-idle gauge rides util records into `cli perf --json`
   (`chip_idle_fraction`, `roofline_*` fields) while the flight
   recorder's measured bookkeeping overhead stays under 1% of wall and
   `dispatches_per_iteration` still lands;
4. `cli compare <run> benchmarks/perf_reference_cpu_smoke.json` holds
   against the checked-in reference (regenerate it with
   `python benchmarks/perf_smoke.py --write-reference` after an
   intentional schema change — the roofline fields ride that file).

Exit 0 when every stage passes; the first failing stage's code
otherwise.
"""

import argparse
import os
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
REFERENCE = Path(__file__).resolve().parent / "perf_reference_cpu_smoke.json"
RUN_NAME = "roofline_smoke"

# Runnable as `python benchmarks/roofline_smoke.py` without installing
# the package: the repo root is the import root, and perf_smoke's tiny
# world is importable from the benchmarks dir.
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
if str(REPO / "benchmarks") not in sys.path:
    sys.path.insert(0, str(REPO / "benchmarks"))

# Must precede any jax import. The peak overrides are what make CPU
# MFU / machine balance non-null; the cache-dir override makes the
# sidecar gate hermetic (a fresh dir, so every `.cost.json` found was
# written by THIS process).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ALPHATRIANGLE_PEAK_TFLOPS", "1.0")
os.environ.setdefault("ALPHATRIANGLE_PEAK_HBM_GBPS", "1.0")
# The smoke's whole point is cost coverage for the AOT-bypassed
# learner family — force the setup pre-capture on even when invoked
# from a shell that inherited the test suite's opt-out.
os.environ["ALPHATRIANGLE_COST_PRECAPTURE"] = "1"
_CACHE_DIR = tempfile.mkdtemp(prefix="at_roofline_cache_")
os.environ["JAX_COMPILATION_CACHE_DIR"] = _CACHE_DIR


def _roofline_json(cli_main, run: str, root: str) -> "dict | None":
    """One `cli roofline --json` invocation's parsed summary."""
    import contextlib
    import io
    import json

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(["roofline", run, "--root-dir", root, "--json"])
    if rc != 0:
        return None
    try:
        return json.loads(buf.getvalue())
    except json.JSONDecodeError:
        return None


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--threshold",
        type=float,
        default=0.9,
        help="compare tolerance vs the checked-in reference "
        "(generous by design: CI hosts vary in speed).",
    )
    parser.add_argument(
        "--root-dir",
        default=None,
        help="Runs root for the smoke runs (default: a temp dir).",
    )
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    from perf_smoke import tiny_configs

    from alphatriangle_tpu.cli import main as cli_main
    from alphatriangle_tpu.config import PersistenceConfig, TrainConfig
    from alphatriangle_tpu.training import run_training

    root = args.root_dir or tempfile.mkdtemp(prefix="at_roofline_smoke_")
    env_cfg, model_cfg, mcts_cfg, train_cfg = tiny_configs()
    train_cfg = TrainConfig(
        **{**train_cfg.model_dump(), "RUN_NAME": RUN_NAME}
    )
    pc = PersistenceConfig(ROOT_DATA_DIR=root, RUN_NAME=RUN_NAME)
    print(f"roofline-smoke: training {RUN_NAME} under {root}...", flush=True)
    rc = run_training(
        train_config=train_cfg,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        persistence_config=pc,
        use_tensorboard=False,
        log_level="WARNING",
    )
    if rc != 0:
        print(
            f"roofline-smoke: training run failed (rc={rc})",
            file=sys.stderr,
        )
        return rc

    print("roofline-smoke: fused-megastep run...", flush=True)
    mega_run = f"{RUN_NAME}_megastep"
    mega_cfg = TrainConfig(
        **{
            **train_cfg.model_dump(),
            "RUN_NAME": mega_run,
            "FUSED_MEGASTEP": True,
            "DEVICE_REPLAY": "on",
            "FUSED_LEARNER_STEPS": 2,
            "MAX_TRAINING_STEPS": 4,
        }
    )
    mega_pc = PersistenceConfig(ROOT_DATA_DIR=root, RUN_NAME=mega_run)
    rc = run_training(
        train_config=mega_cfg,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        persistence_config=mega_pc,
        use_tensorboard=False,
        log_level="WARNING",
    )
    if rc != 0:
        print(
            f"roofline-smoke: megastep run failed (rc={rc})",
            file=sys.stderr,
        )
        return rc

    print("roofline-smoke: serve-program cost analysis...", flush=True)
    # The serve family never dispatches in a training run; its cost
    # record comes from the same AOT-analysis leg `cli serve`'s
    # pre-flight uses (analyze -> capture_cost, persist=True).
    from alphatriangle_tpu.env.engine import TriangleEnv
    from alphatriangle_tpu.features.core import get_feature_extractor
    from alphatriangle_tpu.nn.network import NeuralNetwork
    from alphatriangle_tpu.rl.self_play import SelfPlayEngine
    from alphatriangle_tpu.serving import PolicyService

    env = TriangleEnv(env_cfg)
    extractor = get_feature_extractor(env, model_cfg)
    net = NeuralNetwork(model_cfg, env_cfg, seed=0)
    engine = SelfPlayEngine(
        env, extractor, net, mcts_cfg, train_cfg, seed=0
    )
    service = PolicyService(env, extractor, net, engine.mcts, slots=4)
    if service.analyze(persist=True) is None:
        print(
            "roofline-smoke: serve program analysis returned no record",
            file=sys.stderr,
        )
        return 2

    print("roofline-smoke: cost sidecar gate...", flush=True)
    import json as _json

    from alphatriangle_tpu.compile_cache import get_compile_cache
    from alphatriangle_tpu.telemetry.flight import program_family

    cache_dir = get_compile_cache().cache_dir
    sidecar_families: dict = {}
    for sidecar in Path(cache_dir).glob("*.cost.json"):
        try:
            rec = _json.loads(sidecar.read_text())
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict) and rec.get("kind") == "cost":
            fam = program_family(str(rec.get("program", "")))
            sidecar_families.setdefault(fam, []).append(sidecar.name)
    wanted = {"rollout", "learner", "megastep", "serve"}
    missing = wanted - set(sidecar_families)
    if missing:
        print(
            f"roofline-smoke: {cache_dir} is missing .cost.json "
            f"sidecars for families {sorted(missing)} (found: "
            f"{ {f: len(n) for f, n in sidecar_families.items()} })",
            file=sys.stderr,
        )
        return 2
    print(
        "roofline-smoke: sidecars cover "
        + ", ".join(
            f"{f} x{len(sidecar_families[f])}" for f in sorted(wanted)
        )
    )

    print("roofline-smoke: cli roofline attribution gate...", flush=True)
    rc = cli_main(["roofline", RUN_NAME, "--root-dir", root])
    if rc != 0:
        print(
            f"roofline-smoke: cli roofline failed (rc={rc})",
            file=sys.stderr,
        )
        return rc
    roof = _roofline_json(cli_main, RUN_NAME, root)
    if roof is None:
        print(
            "roofline-smoke: cli roofline --json unparseable",
            file=sys.stderr,
        )
        return 2
    attrib = roof.get("attribution") or {}
    attributed = attrib.get("attributed_fraction")
    if not isinstance(attributed, (int, float)) or attributed < 0.95:
        print(
            f"roofline-smoke: attributed_fraction {attributed} < 0.95 "
            f"(gaps: {attrib.get('gaps')})",
            file=sys.stderr,
        )
        return 2
    rows = roof.get("programs") or []
    hot = {
        r.get("family")
        for r in rows
        if isinstance(r.get("count"), (int, float)) and r["count"] > 0
    }
    unclassified = [
        r["program"]
        for r in rows
        if r.get("bound") is None or r.get("roofline_fraction") is None
    ]
    if not {"rollout", "learner"} <= hot or unclassified:
        print(
            f"roofline-smoke: hot families {sorted(f for f in hot if f)} "
            f"(need rollout+learner); unclassified rows: {unclassified}",
            file=sys.stderr,
        )
        return 2
    print(
        f"roofline-smoke: attributed {attributed:.1%} of "
        f"{attrib.get('wall_s')}s wall "
        f"(idle {attrib.get('chip_idle_fraction'):.1%}); "
        f"{len(rows)} program row(s) classified"
    )

    mega_roof = _roofline_json(cli_main, mega_run, root)
    if mega_roof is None:
        print(
            "roofline-smoke: cli roofline --json failed on the "
            "megastep run",
            file=sys.stderr,
        )
        return 2
    mega_rows = [
        r
        for r in mega_roof.get("programs") or []
        if r.get("family") == "megastep" and r.get("bound") is not None
    ]
    if not mega_rows:
        print(
            "roofline-smoke: megastep run has no classified megastep "
            "row",
            file=sys.stderr,
        )
        return 2

    print("roofline-smoke: flight overhead gate (<1% wall)...", flush=True)
    from alphatriangle_tpu.telemetry.ledger import iter_jsonl_records

    ledger = pc.get_run_base_dir() / "metrics.jsonl"
    flight_path = pc.get_run_base_dir() / "flight.jsonl"
    utils = [
        r
        for r in iter_jsonl_records(ledger)
        if r.get("kind") == "util"
    ]
    run_wall = sum(
        r["window_s"]
        for r in utils
        if isinstance(r.get("window_s"), (int, float))
    )
    overhead = next(
        (
            r.get("overhead_s")
            for r in reversed(list(iter_jsonl_records(flight_path)))
            if r.get("kind") == "flight_overhead"
        ),
        None,
    )
    if not isinstance(overhead, (int, float)) or (
        run_wall > 0 and overhead > 0.01 * run_wall
    ):
        print(
            f"roofline-smoke: flight overhead {overhead} vs "
            f"{run_wall:.1f}s wall — telemetry cost regressed past 1%",
            file=sys.stderr,
        )
        return 2
    idle_utils = [
        r
        for r in utils
        if isinstance(r.get("chip_idle_fraction"), (int, float))
    ]
    dpi_utils = [
        r
        for r in utils
        if isinstance(r.get("dispatches_per_iteration"), (int, float))
    ]
    if not idle_utils or not dpi_utils:
        print(
            f"roofline-smoke: {ledger} carries {len(idle_utils)} util "
            f"record(s) with chip_idle_fraction and {len(dpi_utils)} "
            "with dispatches_per_iteration — a gauge came unwired",
            file=sys.stderr,
        )
        return 2

    print("roofline-smoke: cli perf --json roofline fields...", flush=True)
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(["perf", RUN_NAME, "--root-dir", root, "--json"])
    if rc != 0:
        print(
            f"roofline-smoke: cli perf failed (rc={rc})", file=sys.stderr
        )
        return rc
    perf = _json.loads(buf.getvalue())
    perf_missing = [
        k
        for k in (
            "chip_idle_fraction",
            "roofline_attributed_fraction",
            "roofline_chip_idle_fraction",
            "dispatches_per_iteration",
        )
        if not isinstance(perf.get(k), (int, float))
    ]
    if perf_missing:
        print(
            f"roofline-smoke: cli perf --json is missing {perf_missing}",
            file=sys.stderr,
        )
        return 2

    print(
        f"roofline-smoke: cli compare vs {REFERENCE.name} "
        f"(threshold {args.threshold:.0%})...",
        flush=True,
    )
    rc = cli_main(
        [
            "compare",
            RUN_NAME,
            str(REFERENCE),
            "--root-dir",
            root,
            "--threshold",
            str(args.threshold),
        ]
    )
    if rc != 0:
        print(
            f"roofline-smoke: cli compare failed (rc={rc})",
            file=sys.stderr,
        )
        return rc
    if args.root_dir is None:
        shutil.rmtree(root, ignore_errors=True)
    shutil.rmtree(_CACHE_DIR, ignore_errors=True)
    print("roofline-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
