#!/bin/bash
# Chip-health watcher: probe until the TPU init succeeds, then run the
# round-4 sweep (benchmarks/tpu_round4.sh — resumable per section);
# if the sweep aborts on a mid-run wedge, go back to probing. The chip
# behind the tunnel oscillates healthy<->wedged on a timescale of
# minutes-to-hours (observed across rounds 2-4), so unattended
# persistence is the only way to land a full sweep.
#
# Every window ends in forensics: on an aborted command or a failed
# probe the newest run's flight ring + heartbeat + wedge report +
# trace are archived under runs/_windows/<ts>/ and `cli doctor`
# (JAX-free — safe beside the wedged chip) classifies how the window
# died, appending one verdict line per window to runs/_windows/
# windows.jsonl. A command exiting with the dispatch watchdog's code
# (113) is a detected wedge, not a crash: the watchdog already wrote
# wedge_report.json and the window is reclassified in minutes instead
# of being silently eaten (docs/OBSERVABILITY.md "Flight recorder").
#
#   WATCH_BUDGET_S  total wall budget (default 6h)
#   WATCH_CMD       command to run in a healthy window
#                   (default: bash benchmarks/tpu_round4.sh)
#   WATCH_RUN       when set (and WATCH_CMD is not), the window runs a
#                   SUPERVISED training run of this name instead of the
#                   sweep: `cli supervise --run-name $WATCH_RUN -- train`
#                   (docs/ROBUSTNESS.md). The supervisor self-heals
#                   in-window deaths (verdict-driven restarts from the
#                   latest committed checkpoint); only exhausted budgets
#                   (exit 115) or preemption (114) end the window.
#   WATCH_FLEET     when set (and WATCH_CMD/WATCH_RUN are not), the
#                   window runs a serve FLEET of this run name instead:
#                   `cli fleet --run-name $WATCH_FLEET` (docs/SERVING.md
#                   "Fleet"). The fleet parent self-heals replica
#                   deaths (doctor-classified respawns, probe-gated
#                   re-admission); fleet.jsonl is archived per window.
#   WATCH_WARM_S    budget for the post-probe compile-cache warm
#                   (default 900; 0 disables warming)
#   WATCH_TUNE_S    budget for the offline autotune step (default 600;
#                   0 disables). Runs `cli tune auto` — AOT memory
#                   analysis only, no chip execution beyond compiles —
#                   and, when it lands a tuned_preset.json, warms THAT
#                   config's shapes too so a tuned run launched in the
#                   same window starts hot (docs/AUTOTUNE.md).
set -u
cd "$(dirname "$0")/.."
deadline=$(( $(date +%s) + ${WATCH_BUDGET_S:-21600} ))
if [ -n "${WATCH_RUN:-}" ]; then
  default_cmd="python -m alphatriangle_tpu.cli supervise --run-name ${WATCH_RUN} -- train"
elif [ -n "${WATCH_FLEET:-}" ]; then
  default_cmd="python -m alphatriangle_tpu.cli fleet --run-name ${WATCH_FLEET}"
else
  default_cmd="bash benchmarks/tpu_round4.sh"
fi
cmd=${WATCH_CMD:-"$default_cmd"}
warm_s=${WATCH_WARM_S:-900}
tune_s=${WATCH_TUNE_S:-600}
runs_root=.alphatriangle_data/AlphaTriangleTPU/runs

# Lint preflight (docs/ANALYSIS.md): a chip window is too expensive to
# spend discovering a host-sync regression or a torn donation at
# runtime, so a window NEVER launches with dirty lint. graftlint is
# JAX-free (same contract as `cli doctor` below) — safe to run even
# while the chip is wedged. The JSON verdict is kept and folded into
# every windows.jsonl line so postmortems record what static state the
# window launched from.
lint_row=$(timeout 60 python -m alphatriangle_tpu.cli lint --json 2>/dev/null)
lint_rc=$?
[ -n "$lint_row" ] || lint_row='{"schema": "alphatriangle.lint.v1", "verdict": "unavailable", "exit_code": null}'
if [ "$lint_rc" -ne 0 ]; then
  echo "graftlint preflight FAILED (rc=$lint_rc); refusing to launch a chip window:" >&2
  timeout 60 python -m alphatriangle_tpu.cli lint >&2
  exit 1
fi
echo "$(date +%T) graftlint preflight clean" >&2

# Archive the newest run's postmortem artifacts and record a doctor
# verdict for this window. $1 labels why the window ended (probe-failed
# / cmd-aborted / cmd-wedged). Best-effort throughout: forensics must
# never take down the watcher.
archive_window() {
  local why=$1 ts run_dir dest verdict rc
  ts=$(date +%Y%m%d_%H%M%S)
  run_dir=$(ls -1dt "$runs_root"/*/ 2>/dev/null | grep -v "_windows" | head -1)
  [ -n "$run_dir" ] || return 0
  dest="$runs_root/_windows/$ts"
  mkdir -p "$dest"
  for f in flight.jsonl flight.jsonl.1 health.json wedge_report.json \
           wedge_stacks.txt stall_stacks.txt trace.json \
           supervisor.jsonl preempt_report.json fleet.jsonl \
           beacons.jsonl; do
    [ -f "$run_dir/$f" ] && cp "$run_dir/$f" "$dest/" 2>/dev/null
  done
  # Per-attempt report archives a supervised window's restarts left
  # behind (wedge_report.json.attempt2, ...): the death->verdict->
  # restart chain's evidence, kept beside supervisor.jsonl.
  for f in "$run_dir"/*.attempt*; do
    [ -f "$f" ] && cp "$f" "$dest/" 2>/dev/null
  done
  # JAX-free postmortem: names the program the window died inside.
  verdict=$(timeout 60 python -m alphatriangle_tpu.cli doctor "$run_dir" --json 2>/dev/null)
  rc=$?
  [ -n "$verdict" ] || verdict='{"verdict": "unreadable", "exit_code": null}'
  # Device-stats presence bit: did the window's ledger carry any
  # in-program stat-pack records (telemetry/device_stats.py)?
  device_stats=0
  grep -q '"kind": *"device_stats"' "$run_dir/metrics.jsonl" 2>/dev/null && device_stats=1
  # Roofline verdict (also JAX-free): where this window's wall went —
  # compute- vs memory-bound families + chip-idle gap attribution.
  roofline=$(timeout 60 python -m alphatriangle_tpu.cli roofline "$run_dir" --json 2>/dev/null)
  [ -n "$roofline" ] || roofline='{"verdict": "unreadable"}'
  printf '{"ts": "%s", "why": "%s", "run_dir": "%s", "device_stats": %s, "doctor": %s, "roofline": %s, "lint": %s}\n' \
    "$ts" "$why" "$run_dir" "$device_stats" "$verdict" "$roofline" "$lint_row" >> "$runs_root/_windows/windows.jsonl"
  echo "$verdict" > "$dest/doctor.json"
  echo "$roofline" > "$dest/roofline.json"
  echo "$(date +%T) window archived: $dest ($why, doctor rc=$rc)" >&2
}

while [ "$(date +%s)" -lt "$deadline" ]; do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    # Probe passed: warm the compile caches (XLA persistent + AOT
    # executables, docs/COMPILE_CACHE.md) for the bench/sweep shapes
    # BEFORE spending the window on the real command — on a warm cache
    # this is seconds; cold, it front-loads the ~minute-per-program
    # compiles so the sweep's sections start measuring immediately.
    # `warm auto` covers the policy-serving shapes too: with
    # BENCH_SERVE_BUCKETS set, EVERY rung of the serve-shape ladder
    # (`serve/b<rung>` per rung, serving/buckets.py) is warmed for the
    # active inference precision, so both a `cli serve` startup and
    # its mid-stream micro-batcher rung switches are zero-recompile in
    # the window (docs/SERVING.md). Best-effort: a warm failure (or a
    # wedge mid-warm) must not stop the sweep attempt.
    if [ "$warm_s" -gt 0 ]; then
      rung_note=${BENCH_SERVE_BUCKETS:+" serve rungs {$BENCH_SERVE_BUCKETS}"}
      echo "$(date +%T) chip healthy; warming compile caches (<=${warm_s}s)$rung_note" >&2
      timeout "$warm_s" python -m alphatriangle_tpu.cli warm auto >&2 \
        || echo "$(date +%T) warm incomplete (continuing)" >&2
    fi
    # Offline autotune: spends HBM analysis (AOT compiles), not the
    # chip window — the tuned preset is the config the next real run
    # should use, so pre-warm its shapes while the chip is healthy.
    # Best-effort like the warm: never blocks the sweep attempt.
    if [ "$tune_s" -gt 0 ]; then
      tuned=$runs_root/tune_auto/tuned_preset.json
      echo "$(date +%T) chip healthy; autotuning (<=${tune_s}s)" >&2
      if timeout "$tune_s" python -m alphatriangle_tpu.cli tune auto \
           --run-name tune_auto >&2 && [ -f "$tuned" ]; then
        timeout "$warm_s" python -m alphatriangle_tpu.cli warm "$tuned" >&2 \
          || echo "$(date +%T) tuned warm incomplete (continuing)" >&2
      else
        echo "$(date +%T) tune incomplete (continuing)" >&2
      fi
    fi
    echo "$(date +%T) chip healthy; running: $cmd" >&2
    eval "$cmd"
    rc=$?
    if [ "$rc" -eq 0 ]; then
      echo "$(date +%T) command complete" >&2
      exit 0
    fi
    if [ "$rc" -eq 113 ]; then
      # The dispatch watchdog detected an over-deadline dispatch,
      # dumped stacks + wedge_report.json and exited: a DETECTED
      # wedge, reclassified here instead of lost to a silent hang.
      echo "$(date +%T) command wedged (dispatch watchdog, exit 113); back to probing" >&2
      archive_window "cmd-wedged"
    elif [ "$rc" -eq 114 ]; then
      # Preemption absorbed: the loop emergency-checkpointed and exited
      # on purpose (docs/ROBUSTNESS.md). The next healthy window's
      # restart resumes from that checkpoint.
      echo "$(date +%T) command preempted (exit 114, emergency checkpoint on disk); back to probing" >&2
      archive_window "cmd-preempted"
    elif [ "$rc" -eq 115 ]; then
      # `cli supervise` exhausted its restart budget / tripped the
      # circuit breaker: the chip (or config) is persistently sick.
      # Back to probing — a later window may find a healthy chip.
      echo "$(date +%T) supervisor gave up (exit 115); back to probing" >&2
      archive_window "supervisor-gave-up"
    else
      echo "$(date +%T) command aborted (rc=$rc); back to probing" >&2
      archive_window "cmd-aborted"
    fi
  else
    echo "$(date +%T) probe failed (chip wedged)" >&2
    archive_window "probe-failed"
  fi
  sleep 120
done
echo "watch budget exhausted" >&2
exit 1
