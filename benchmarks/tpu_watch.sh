#!/bin/bash
# Chip-health watcher: probe until the TPU init succeeds, then run the
# round-4 sweep (benchmarks/tpu_round4.sh — resumable per section);
# if the sweep aborts on a mid-run wedge, go back to probing. The chip
# behind the tunnel oscillates healthy<->wedged on a timescale of
# minutes-to-hours (observed across rounds 2-4), so unattended
# persistence is the only way to land a full sweep.
#
#   WATCH_BUDGET_S  total wall budget (default 6h)
#   WATCH_CMD       command to run in a healthy window
#                   (default: bash benchmarks/tpu_round4.sh)
#   WATCH_WARM_S    budget for the post-probe compile-cache warm
#                   (default 900; 0 disables warming)
#   WATCH_TUNE_S    budget for the offline autotune step (default 600;
#                   0 disables). Runs `cli tune auto` — AOT memory
#                   analysis only, no chip execution beyond compiles —
#                   and, when it lands a tuned_preset.json, warms THAT
#                   config's shapes too so a tuned run launched in the
#                   same window starts hot (docs/AUTOTUNE.md).
set -u
cd "$(dirname "$0")/.."
deadline=$(( $(date +%s) + ${WATCH_BUDGET_S:-21600} ))
cmd=${WATCH_CMD:-"bash benchmarks/tpu_round4.sh"}
warm_s=${WATCH_WARM_S:-900}
tune_s=${WATCH_TUNE_S:-600}
while [ "$(date +%s)" -lt "$deadline" ]; do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    # Probe passed: warm the compile caches (XLA persistent + AOT
    # executables, docs/COMPILE_CACHE.md) for the bench/sweep shapes
    # BEFORE spending the window on the real command — on a warm cache
    # this is seconds; cold, it front-loads the ~minute-per-program
    # compiles so the sweep's sections start measuring immediately.
    # `warm auto` covers the policy-serving shape too (`serve/b<B>`,
    # reported alongside megastep/t·_k· in the warm summary), so a
    # `cli serve` brought up in the same window starts answering in
    # ~0.5s instead of burning it on a search compile (docs/SERVING.md).
    # Best-effort: a warm failure (or a wedge mid-warm) must not stop
    # the sweep attempt.
    if [ "$warm_s" -gt 0 ]; then
      echo "$(date +%T) chip healthy; warming compile caches (<=${warm_s}s)" >&2
      timeout "$warm_s" python -m alphatriangle_tpu.cli warm auto >&2 \
        || echo "$(date +%T) warm incomplete (continuing)" >&2
    fi
    # Offline autotune: spends HBM analysis (AOT compiles), not the
    # chip window — the tuned preset is the config the next real run
    # should use, so pre-warm its shapes while the chip is healthy.
    # Best-effort like the warm: never blocks the sweep attempt.
    if [ "$tune_s" -gt 0 ]; then
      tuned=.alphatriangle_data/AlphaTriangleTPU/runs/tune_auto/tuned_preset.json
      echo "$(date +%T) chip healthy; autotuning (<=${tune_s}s)" >&2
      if timeout "$tune_s" python -m alphatriangle_tpu.cli tune auto \
           --run-name tune_auto >&2 && [ -f "$tuned" ]; then
        timeout "$warm_s" python -m alphatriangle_tpu.cli warm "$tuned" >&2 \
          || echo "$(date +%T) tuned warm incomplete (continuing)" >&2
      else
        echo "$(date +%T) tune incomplete (continuing)" >&2
      fi
    fi
    echo "$(date +%T) chip healthy; running: $cmd" >&2
    if eval "$cmd"; then
      echo "$(date +%T) command complete" >&2
      exit 0
    fi
    echo "$(date +%T) command aborted (wedge?); back to probing" >&2
  else
    echo "$(date +%T) probe failed (chip wedged)" >&2
  fi
  sleep 120
done
echo "watch budget exhausted" >&2
exit 1
