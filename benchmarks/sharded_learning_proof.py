"""Learning proof for the MULTI-CHIP data path: dp-sharded rollout
lanes + dp-sharded device replay ring + dp-sharded learner, overlapped
— the fully device-local experience path doesn't just run, it learns.

Same protocol as benchmarks/async_learning_proof.py (whose `run_proof`
scaffolding this parameterizes: same 4x6/2-slot world, same Gumbel+PCR
recipe, same fixed greedy-PUCT evaluator, before/after on the same
net), but through a virtual 8-device CPU mesh with `DEVICE_REPLAY=on`:
rollouts shard 32 lanes 8 ways, every chunk's experiences
shard_map-scatter into per-device ring shards, batches are
stratified-sampled per shard and gathered device-locally. A matching
improvement over the untrained baseline proves the stratified
per-shard PER + sharded ingest/gather semantics train correctly end
to end.

Measured 2026-07-31 (single-core host, so the virtual mesh adds
overhead rather than speed — the point is semantics, not throughput):
21.69 -> 23.75 greedy eval (+9.5%) in 4000 steps at replay ratio 0.45
— near-parity with the single-device reference (24.00, +10.7%) at the
same step count despite half the gradient updates per experience; a
1200-step run measured +6.4% en route.

Usage:  python benchmarks/sharded_learning_proof.py
Env:    PROOF_STEPS=N (default 1500), PROOF_EVAL_GAMES=N (default 256)
Writes benchmarks/sharded_learning_results.json.
"""

import os
import sys

# 8 virtual devices BEFORE any jax import (conftest pattern).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# XLA:CPU async dispatch deadlocks under the device-replay thread
# topology (rl/device_buffer.py module docstring); latched at client
# creation, so set before any backend touch.
jax.config.update("jax_cpu_enable_async_dispatch", False)

# Shares run_proof (and through it the world/recipe/evaluator) with the
# single-device overlapped proof; also re-asserts the CPU platform +
# compile cache at its own import time.
from async_learning_proof import run_proof  # noqa: E402

from alphatriangle_tpu.config import MeshConfig  # noqa: E402
from alphatriangle_tpu.rl.sharded_device_buffer import (  # noqa: E402
    ShardedDeviceReplayBuffer,
)

DP = 8


def main() -> int:
    def post_setup(c):
        assert isinstance(c.buffer, ShardedDeviceReplayBuffer), type(
            c.buffer
        )
        assert c.self_play.mesh is not None

    run_proof(
        topology="dp-sharded (8 virtual devices): sharded rollout "
        "lanes + sharded device replay ring + sharded learner, "
        "overlapped + pipelined + fused + Gumbel+PCR",
        out_name="sharded_learning_results.json",
        run_name="sharded_proof",
        default_root="/tmp/sharded_proof",
        train_overrides={"DEVICE_REPLAY": "on"},
        mesh_config=MeshConfig(DP_SIZE=DP),
        post_setup=post_setup,
        extra_payload=lambda c, loop: {
            "ring_shard_sizes": [int(s) for s in c.buffer._sizes],
            "single_device_reference": "async_learning_results.json: "
            "21.69 -> 24.00 (+10.7%) in 4000 steps",
        },
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
