"""Long-context evidence: ring attention vs dense at growing sequence length.

Dense attention materializes an (S, S) score matrix per head; ring
attention (parallel/ring_attention.py) holds only per-shard blocks, so
its per-device memory scales with S/n instead of S^2. This benchmark
runs both on the virtual 8-device CPU mesh at growing S and records
wall time plus the analytical score-matrix footprint, demonstrating the
framework's long-context path end to end (forward + gradient).

Usage: JAX_PLATFORMS=cpu python benchmarks/ring_attention_bench.py
Env:   RING_MAX_LOG2=N  largest S = 2**N (default 13 -> 8192)
Writes benchmarks/ring_attention_results.json.
"""

import json
import os
import sys
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
# XLA:CPU persistent-cache RELOADS of donating programs silently return
# unchanged outputs in this image (see tests/conftest.py) — a cached
# learner step here would fake a flat learning curve; never enable it.

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from alphatriangle_tpu.config import MeshConfig
from alphatriangle_tpu.parallel import make_sp_attention

B, H, D = 1, 4, 64


def dense_attention(q, k, v):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def timed(fn, *args):
    jax.block_until_ready(fn(*args))  # compile
    t0 = time.time()
    for _ in range(3):
        jax.block_until_ready(fn(*args))
    return (time.time() - t0) / 3


def main() -> None:
    mesh = MeshConfig(DP_SIZE=1, SP_SIZE=8).build_mesh()
    ring = make_sp_attention(mesh, kind="ring")
    max_log2 = int(os.environ.get("RING_MAX_LOG2", "13"))
    rows = []
    rng = np.random.default_rng(0)

    grad_ring = jax.jit(jax.grad(lambda q, k, v: (ring(q, k, v) ** 2).sum()))
    ring_jit = jax.jit(ring)
    dense_jit = jax.jit(dense_attention)

    for log2 in range(9, max_log2 + 1):
        s = 1 << log2
        q, k, v = (
            jnp.asarray(
                rng.standard_normal((B, s, H, D)), jnp.float32
            )
            for _ in range(3)
        )
        row = {
            "seq_len": s,
            # per-head f32 score matrix, the dense memory driver:
            "dense_scores_mb_per_head": round(s * s * 4 / 2**20, 1),
            "ring_block_mb_per_head": round(
                (s // 8) * (s // 8) * 4 / 2**20, 2
            ),
        }
        row["ring_fwd_s"] = round(timed(ring_jit, q, k, v), 3)
        row["ring_grad_s"] = round(timed(grad_ring, q, k, v), 3)
        # Dense comparison only while the score matrix is sane on CPU.
        if s <= 4096:
            row["dense_fwd_s"] = round(timed(dense_jit, q, k, v), 3)
            out_r = ring_jit(q, k, v)
            out_d = dense_jit(q, k, v)
            np.testing.assert_allclose(
                np.asarray(out_r), np.asarray(out_d), rtol=3e-4, atol=3e-4
            )
            row["matches_dense"] = True
        rows.append(row)
        print(json.dumps(row), flush=True)

    out_path = Path(__file__).parent / "ring_attention_results.json"
    out_path.write_text(json.dumps(rows, indent=2))


if __name__ == "__main__":
    main()
