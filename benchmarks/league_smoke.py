"""CI league-smoke gate: the experience flywheel end to end on CPU.

`make league-smoke` runs this. On a machine with no accelerator it
proves the league subsystem (docs/LEAGUE.md) still closes the loop:

1. a tiny CPU training run (`perf_smoke`-sized world) leaves >=2
   checkpoints — the seed population;
2. `cli league --pool-from <that run>` runs the flywheel: the learner
   trains while a PolicyService plays matchmade games against the
   pool, served trajectories flow into the replay ring interleaved
   with self-play, and a permissive promotion gate lets the live net
   earn at least one pool seat;
3. the flywheel run's `league.jsonl` replays cleanly and its rating
   events are monotonically consistent with its result events (the
   incremental Elo fold reproduces every persisted rating);
4. the run's ledger carries `kind: "league"` records proving
   service-played moves actually reached the ring (moves_ingested,
   buffer growth, staleness tags);
5. `cli perf --json` summarizes the league fields and `cli compare
   --metrics league_ingested_moves_per_sec` aligns them;
6. the flywheel run's checkpoint resumes under plain training — a
   flywheel run is an ordinary run that also served games.

Exit 0 when every stage passes; the first failing stage's code
otherwise.
"""

import argparse
import contextlib
import io
import json
import os
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SRC_RUN = "league_smoke_src"
FLY_RUN = "league_smoke_fly"

if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

# Must precede any jax import: the smoke must not wake an accelerator.
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def fail(msg: str, code: int = 2) -> int:
    print(f"league-smoke: {msg}", file=sys.stderr)
    return code


def check_rating_consistency(league_path: Path) -> "str | None":
    """Replay league.jsonl's result events through the incremental Elo
    fold and require every persisted rating event to match, in order —
    the monotonic-consistency gate on the crash-safe store."""
    from alphatriangle_tpu.league import LeaguePool

    shadow = LeaguePool(league_path.parent / "_shadow.jsonl")
    checked = 0
    for line in league_path.read_text().splitlines():
        try:
            r = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn tail tolerance, same as the reader
        kind = r.get("kind")
        if kind == "member":
            shadow.add_member(
                r["member_id"], r.get("checkpoint", ""), r.get("step") or 0,
                elo=float(r.get("elo", 0.0)),
            )
        elif kind == "result":
            shadow._fold_result(r["a"], r["b"], float(r["score_a"]), persist=False)
        elif kind == "rating":
            got = shadow.ratings.get(r["member_id"])
            if got is None or abs(got - float(r["elo"])) > 1e-2:
                return (
                    f"rating event for {r['member_id']} says {r['elo']} "
                    f"but the result replay gives {got}"
                )
            checked += 1
    if checked == 0:
        return "no rating events to check"
    print(f"league-smoke: {checked} rating event(s) replay-consistent")
    return None


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--root-dir",
        default=None,
        help="Runs root for the smoke runs (default: a temp dir).",
    )
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    from alphatriangle_tpu.cli import main as cli_main
    from alphatriangle_tpu.config import PersistenceConfig
    from alphatriangle_tpu.league import LEAGUE_FILENAME
    from alphatriangle_tpu.training import run_training

    # The seed run reuses the perf smoke's tiny world so both smokes
    # exercise the same geometry.
    from benchmarks.perf_smoke import tiny_configs

    root = args.root_dir or tempfile.mkdtemp(prefix="at_league_smoke_")
    env_cfg, model_cfg, mcts_cfg, train_cfg = tiny_configs()
    train_cfg = train_cfg.model_copy(update={"RUN_NAME": SRC_RUN})
    src_pc = PersistenceConfig(ROOT_DATA_DIR=root, RUN_NAME=SRC_RUN)

    print(f"league-smoke: seeding pool run {SRC_RUN} under {root}...", flush=True)
    rc = run_training(
        train_config=train_cfg,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        persistence_config=src_pc,
        use_tensorboard=False,
        log_level="WARNING",
    )
    if rc != 0:
        return fail(f"seed training run failed (rc={rc})", rc)

    from alphatriangle_tpu.stats.persistence import CheckpointManager

    mgr = CheckpointManager(src_pc)
    steps = mgr.list_steps()
    mgr.close()
    if len(steps) < 2:
        return fail(f"seed run left {steps} checkpoint(s); need >=2")
    print(f"league-smoke: seed checkpoints at steps {steps}")

    print("league-smoke: flywheel run (cli league)...", flush=True)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(
            [
                "league",
                "--pool-from", SRC_RUN,
                "--root-dir", root,
                "--run-name", FLY_RUN,
                "--steps", "6",
                "--seed", "5",
                "--device", "cpu",
                "--sims", "4",
                "--self-play-batch", "4",
                "--batch-size", "8",
                "--buffer-capacity", "2000",
                "--min-buffer", "16",
                "--rollout-chunk", "4",
                "--checkpoint-freq", "2",
                "--max-moves", "20",
                "--slots", "4",
                "--games", "2",
                "--mix", "0.5",
                "--reload-every", "1",
                # Permissive gate: the smoke proves the promotion
                # machinery, not playing strength.
                "--promotion-games", "1",
                "--promotion-win-rate", "0.0",
            ]
        )
    sys.stdout.write(buf.getvalue())
    if rc != 0:
        return fail(f"cli league failed (rc={rc})", rc)
    report_lines = [
        ln for ln in buf.getvalue().splitlines() if ln.startswith("{")
    ]
    if not report_lines:
        return fail("cli league printed no JSON report line")
    report = json.loads(report_lines[-1])
    print(f"league-smoke: report {report}")

    fly_pc = PersistenceConfig(ROOT_DATA_DIR=root, RUN_NAME=FLY_RUN)
    league_path = fly_pc.get_run_base_dir() / LEAGUE_FILENAME
    if not league_path.exists():
        return fail(f"{league_path} missing")
    if report.get("pool_size", 0) < 2:
        return fail(f"pool has {report.get('pool_size')} member(s); expected >=2")
    if report.get("promotions", 0) < 1:
        return fail("no promotion happened under the permissive gate")
    err = check_rating_consistency(league_path)
    if err:
        return fail(f"league.jsonl inconsistent: {err}")

    print("league-smoke: ledger league records...", flush=True)
    ledger = fly_pc.get_run_base_dir() / "metrics.jsonl"
    league_records = []
    for line in ledger.read_text().splitlines():
        try:
            r = json.loads(line)
        except json.JSONDecodeError:
            continue
        if r.get("kind") == "league":
            league_records.append(r)
    if not league_records:
        return fail(f"{ledger} has no kind='league' records")
    ingested = sum(int(r.get("moves_ingested", 0)) for r in league_records)
    grew = any(
        r.get("buffer_size_after", 0) > r.get("buffer_size_before", 0)
        for r in league_records
    )
    tagged = any(
        isinstance(r.get("mean_staleness"), (int, float))
        for r in league_records
    )
    if ingested <= 0 or not grew:
        return fail(
            f"league records show {ingested} ingested move(s), "
            f"buffer growth={grew} — served trajectories never reached "
            "the replay ring"
        )
    if not tagged:
        return fail("no league record carries a mean_staleness tag")
    print(
        f"league-smoke: {len(league_records)} round(s), {ingested} "
        f"service-played move(s) into the ring, staleness tags present"
    )

    print("league-smoke: cli perf --json league fields...", flush=True)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(["perf", FLY_RUN, "--root-dir", root, "--json"])
    if rc != 0:
        return fail(f"cli perf failed (rc={rc})", rc)
    summary = json.loads(buf.getvalue())
    for key in (
        "league_rounds",
        "league_pool_size",
        "league_moves_ingested",
        "league_ingested_moves_per_sec",
        "league_promotions",
    ):
        if key not in summary:
            return fail(f"cli perf --json summary lacks {key}")
    print(
        f"league-smoke: perf summary rounds={summary['league_rounds']} "
        f"ingest={summary['league_ingested_moves_per_sec']} moves/s"
    )

    print("league-smoke: cli compare league metric alignment...", flush=True)
    snapshot = Path(root) / "league_smoke_reference.json"
    summary["source"] = "benchmarks/league_smoke.py"
    snapshot.write_text(json.dumps(summary, indent=2))
    rc = cli_main(
        [
            "compare",
            FLY_RUN,
            str(snapshot),
            "--root-dir", root,
            "--metrics", "league_ingested_moves_per_sec",
            "--threshold", "0.9",
        ]
    )
    if rc != 0:
        return fail(f"cli compare on the league metric failed (rc={rc})", rc)

    print("league-smoke: flywheel checkpoint resumes under plain train...", flush=True)
    # A flywheel run is an ordinary run: its checkpoint (weights +
    # counters + mixed-source replay spill) must restore under the
    # standard training entrypoint and keep stepping.
    resume_cfg = train_cfg.model_copy(
        update={"RUN_NAME": FLY_RUN, "MAX_TRAINING_STEPS": 8}
    )
    rc = run_training(
        train_config=resume_cfg,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        persistence_config=fly_pc,
        use_tensorboard=False,
        log_level="WARNING",
    )
    if rc != 0:
        return fail(f"plain-train resume of {FLY_RUN} failed (rc={rc})", rc)
    mgr = CheckpointManager(fly_pc)
    final = mgr.latest_step()
    mgr.close()
    if final is None or final < 8:
        return fail(f"resume ended at step {final}; expected >=8")
    print(f"league-smoke: resumed to step {final}")

    if args.root_dir is None:
        shutil.rmtree(root, ignore_errors=True)
    print("league-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
