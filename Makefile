# Quality gate (reference: .github/workflows/ci_cd.yml:18-100 runs
# ruff + mypy + pytest + coverage fail_under=40).
#
# `make check` is the one command that fails the build on a lint, type,
# syntax or test regression. Tools missing from the current image
# (ruff/mypy/pytest-cov are not baked into the TPU image and installs
# are disallowed there) degrade to the strongest available check and
# SAY SO; the test suite itself is mandatory and never skipped.

PY ?= python

.PHONY: check lint type test bench-smoke perf-smoke serve-smoke tune-smoke doctor-smoke ops-smoke league-smoke chaos-smoke fleet-smoke trace-smoke reuse-smoke devstats-smoke roofline-smoke

check: lint type test

lint:
	@if $(PY) -c "import ruff" 2>/dev/null; then \
		echo "== ruff check =="; \
		$(PY) -m ruff check alphatriangle_tpu tests bench.py; \
	else \
		echo "== ruff unavailable; syntax gate via compileall =="; \
		$(PY) -m compileall -q alphatriangle_tpu tests bench.py __graft_entry__.py; \
	fi
	@echo "== graftlint (docs/ANALYSIS.md) =="
	@$(PY) -m alphatriangle_tpu.cli lint

type:
	@if $(PY) -c "import mypy" 2>/dev/null; then \
		echo "== mypy =="; \
		$(PY) -m mypy alphatriangle_tpu; \
	else \
		echo "== mypy unavailable in this image; skipping type gate =="; \
	fi

test:
	@if $(PY) -c "import pytest_cov" 2>/dev/null; then \
		echo "== pytest + coverage (fail_under from pyproject) =="; \
		$(PY) -m pytest tests/ -q --cov --cov-fail-under=40; \
	else \
		echo "== pytest (coverage plugin unavailable) =="; \
		$(PY) -m pytest tests/ -q; \
	fi

bench-smoke:
	BENCH_SMOKE=1 JAX_PLATFORMS=cpu $(PY) bench.py

# Metrics-ledger pipeline gate: a short CPU training run must produce a
# parseable metrics.jsonl carrying memory-attribution + live-memory
# records, `cli perf` must summarize it (exit 2 = the ledger schema
# broke), `cli fit cpu` must compose the static memory budget and exit
# 0 (the OOM pre-flight gate), and `cli compare` must hold against the
# checked-in reference summary (generous threshold — CI hosts vary in
# speed; the hard signal is schema alignment + "not catastrophically
# slower"). Regenerate the reference after intentional schema changes:
#   $(PY) benchmarks/perf_smoke.py --write-reference
perf-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/perf_smoke.py

# Policy-serving pipeline gate (docs/SERVING.md): `cli serve --smoke`
# must storm 96 simulated sessions on CPU over the {16,32,64}
# serve-shape ladder with int8 inference ON — the micro-batcher walks
# up >= 1 rung (64 concurrent at the top) and back down on the drain,
# zero recompiles after the all-rung warm, zero lost requests,
# admit/retire churn mid-run — land per-request p50/p95 move-latency
# records plus the serve_bucket/serve_fill gauges in the serve run's
# metrics ledger, summarize them via `cli perf --json`, and hold the
# serve SLO rows of `cli compare` against the checked-in reference.
# Regenerate the serve rows after intentional schema changes:
#   $(PY) benchmarks/serve_smoke.py --write-reference
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/serve_smoke.py

# Experience-flywheel gate (docs/LEAGUE.md): seed a league pool from a
# tiny CPU run's checkpoints, then `cli league` must train the learner
# while a PolicyService plays matchmade pool games whose trajectories
# verifiably reach the replay ring (ledger `kind:"league"` records with
# ingest counts + staleness tags), promote the live net at least once
# under a permissive gate, keep league.jsonl's rating events consistent
# with its result events, surface the league fields through `cli perf
# --json` / `cli compare`, and leave a checkpoint that resumes under
# plain training.
league-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/league_smoke.py

# Window-forensics gate (docs/OBSERVABILITY.md "Flight recorder"):
# a synthetic torn flight ring must classify as dispatch-hung naming
# the exact program, a simulated over-deadline dispatch (frozen clock,
# exit-on-wedge off) must land wedge_report.json + stacks and doctor
# the same way, and sealed flight records must surface as per-program
# device-time rows in `cli perf --json`. Runs the doctor CLI in
# subprocesses exactly as tpu_watch.sh does — JAX is never imported on
# that path.
doctor-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/doctor_smoke.py

# Self-healing gate (docs/ROBUSTNESS.md): injected faults against real
# training children — a mid-run dispatch hang must die by the watchdog's
# exit 113 and be restarted by the supervisor from the latest committed
# checkpoint (completing with step loss <= one checkpoint cadence, the
# death->verdict->restart chain in supervisor.jsonl); SIGTERM must be
# absorbed as an emergency checkpoint + exit 114 that doctor reads as
# `preempted` and a rerun resumes; SIGKILL mid-checkpoint-save must
# leave a torn step dir that restore skips for the prior committed one.
# The supervisor parent runs with jax imports hard-blocked.
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/chaos_smoke.py

# Serve-fleet gate (docs/SERVING.md "Fleet"): a loadgen storm through
# `cli fleet --smoke` (2 replica subprocesses behind the least-queue-
# depth router, jax-free parent) must survive a mid-storm SIGKILL, an
# injected hang-serve wedge (watchdog 113 -> dispatch-hung -> respawn
# on a halved bucket -> re-admission, the chain in fleet.jsonl), and a
# rolling weight reload with zero recompiles — with ZERO lost requests
# (completed + shed == requests) and p95 move latency inside the SLO.
fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/fleet_smoke.py

# Distributed-tracing + SLO gate (docs/OBSERVABILITY.md "Distributed
# tracing & SLOs"): a 2-replica CPU storm with an aggressive hedge
# trigger and an injected hang-serve wedge must leave trace_ids
# consistent across fleet.jsonl, the replica flight rings, and the
# `cli trace --fleet` merged Perfetto timeline — with flow arrows for
# >= 1 hedged and >= 1 retried request in causal order — and the
# `cli slo` exit-code contract (0 within budget / 1 burning / 2 no
# data) must hold on pinned healthy/brownout/empty windows. Every
# reader runs with jax imports hard-blocked.
trace-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/trace_smoke.py

# Kernel-library gate (docs/KERNELS.md): every interchangeable lowering
# in alphatriangle_tpu/ops/ (gather_rows, backup_update, per_sample)
# must match its reference backend bit-for-bit across a shape grid
# before it is timed; a parity break fails the target. CPU runs the
# Pallas rows in interpret mode — set OPS_BENCH_FULL=1 on a TPU host
# for decision-grade timings at flagship shapes.
ops-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/ops_bench.py

# Subtree-reuse gate (docs/KERNELS.md "subtree_promote"): the batched
# root-promotion pass over a REAL search tree must match an eager NumPy
# BFS reference node for node with the Pallas lowering bit-identical to
# XLA; reuse ON at equal sims must deliver >= 1.15x leaf-evals/s over
# fresh-root; a short reuse training run must land leaf_evals_per_sec +
# mcts_reused_visit_fraction (> 0) on the ledger and in `cli perf
# --json`; and a fixed-seed paired arena through the PolicyService path
# must show reuse at REDUCED sims score-neutral-or-better vs fresh-root
# at full sims.
reuse-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/reuse_smoke.py

# Device-telemetry gate (docs/OBSERVABILITY.md "Device telemetry
# plane"): a short megastep CPU run with stat-packs on must land
# `kind:"device_stats"` ledger records surfaced as ds_* fields by
# `cli perf --json` while the one-dispatch gauge still reads 1.0;
# stat-packs timed OFF vs ON on the same megastep shape must cost <3%
# wall (they ride the existing fetch — no extra dispatches); and a
# beacon-armed child with an injected dispatch hang must die by the
# watchdog's 113 leaving beacons.jsonl + a wedge report whose frozen
# last_beacon the jax-blocked `cli doctor` verdict names.
devstats-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/devstats_smoke.py

# Roofline-attribution gate (docs/OBSERVABILITY.md "Roofline & gap
# attribution"): a short CPU training run must leave `.cost.json`
# sidecars + ledger `kind:"cost"` records for the chunk/learner/
# megastep/serve program families, `cli roofline` (jax-free) must
# classify every hot family and attribute >= 95% of the run's wall
# across dispatch + named gap categories, the chip-idle gauge must
# ride util records into `cli perf --json`/`cli compare`, and the
# perf reference must still hold with dispatches_per_iteration
# unchanged. Regenerate the reference after intentional changes:
#   $(PY) benchmarks/perf_smoke.py --write-reference
roofline-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/roofline_smoke.py

# Fit-driven autotuner gate (docs/AUTOTUNE.md): `cli tune cpu --smoke`
# under a host-RAM byte limit must emit a tuned_preset.json that
# `cli fit` independently confirms fits, whose winner out-predicts every
# feasible rejected candidate, that `cli train --preset <artifact>
# --dry-setup` can construct components from, and whose short real run
# ledgers the predicted-vs-observed tune_outcome record the next
# search's --calibrate reads.
tune-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/tune_smoke.py
