"""Benchmark: batched self-play throughput on the available accelerator.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ..., "extra": {...}}

Primary metric: **self-play games/hour**, measured directly (episodes
completed / wall-clock) with the flagship configuration — default 8x15
board, conv+residual+transformer net, 64-sim batched MCTS — on one
chip. `vs_baseline` divides by the BASELINE.json north star (10,000
games/hour on v4-8 with a 4-layer transformer net); the reference
itself publishes no numbers (BASELINE.md).

`extra` carries the secondary BASELINE metrics: MCTS leaf-evals/sec
(per chip) and learner steps/sec on a 256 batch.

Env knobs: BENCH_SMOKE=1 shrinks everything for a fast CPU sanity run;
BENCH_SECONDS overrides the self-play measurement window.
"""

import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from alphatriangle_tpu.config import (
        AlphaTriangleMCTSConfig,
        EnvConfig,
        ModelConfig,
        TrainConfig,
        expected_other_features_dim,
    )
    from alphatriangle_tpu.env.engine import TriangleEnv
    from alphatriangle_tpu.features.core import get_feature_extractor
    from alphatriangle_tpu.nn.network import NeuralNetwork
    from alphatriangle_tpu.rl import SelfPlayEngine, Trainer

    smoke = os.environ.get("BENCH_SMOKE") == "1"
    seconds = float(os.environ.get("BENCH_SECONDS", "8" if smoke else "75"))
    backend = jax.default_backend()
    device = jax.devices()[0]
    log(f"bench: backend={backend} device={device.device_kind if hasattr(device, 'device_kind') else device}")

    env_cfg = EnvConfig()
    model_cfg = ModelConfig(
        OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(env_cfg),
        COMPUTE_DTYPE="float32" if backend == "cpu" else "bfloat16",
    )
    mcts_cfg = AlphaTriangleMCTSConfig(
        max_simulations=8 if smoke else 64, max_depth=4 if smoke else 8
    )
    sp_batch = 16 if smoke else 512
    train_cfg = TrainConfig(
        SELF_PLAY_BATCH_SIZE=sp_batch,
        BATCH_SIZE=32 if smoke else 256,
        BUFFER_CAPACITY=10_000,
        MIN_BUFFER_SIZE_TO_TRAIN=1_000,
        MAX_TRAINING_STEPS=1_000,
        RUN_NAME="bench",
    )

    env = TriangleEnv(env_cfg)
    extractor = get_feature_extractor(env, model_cfg)
    net = NeuralNetwork(model_cfg, env_cfg, seed=0)
    engine = SelfPlayEngine(
        env, extractor, net, mcts_cfg, train_cfg, seed=0
    )

    # --- self-play games/hour (primary) --------------------------------
    log("bench: compiling self-play move (first dispatch)...")
    t0 = time.time()
    engine.play_move()
    compile_s = time.time() - t0
    log(f"bench: first move (compile) {compile_s:.1f}s; measuring {seconds:.0f}s...")
    engine.harvest()  # reset counters after warmup

    t0 = time.time()
    moves = 0
    while time.time() - t0 < seconds:
        engine.play_move()
        moves += 1
    elapsed = time.time() - t0
    result = engine.harvest()
    episodes = result.num_episodes
    games_per_hour = episodes / elapsed * 3600.0
    sims = mcts_cfg.max_simulations
    leaf_evals_per_sec = moves * sp_batch * (sims + 1) / elapsed
    moves_per_sec = moves * sp_batch / elapsed
    log(
        f"bench: {moves} lockstep moves x {sp_batch} games in {elapsed:.1f}s "
        f"-> {episodes} episodes, {games_per_hour:.0f} games/h, "
        f"{leaf_evals_per_sec:.0f} leaf-evals/s"
    )

    # --- learner steps/sec (secondary) ----------------------------------
    trainer = Trainer(net, train_cfg)
    b = train_cfg.BATCH_SIZE
    rng = np.random.default_rng(0)
    policy = rng.random((b, env_cfg.action_dim)).astype(np.float32)
    policy /= policy.sum(axis=1, keepdims=True)
    batch = {
        "grid": rng.integers(-1, 2, size=(b, 1, env_cfg.ROWS, env_cfg.COLS)).astype(
            np.float32
        ),
        "other_features": rng.random(
            (b, model_cfg.OTHER_NN_INPUT_FEATURES_DIM)
        ).astype(np.float32),
        "policy_target": policy,
        "value_target": rng.uniform(-5, 5, b).astype(np.float32),
        "weights": np.ones(b, np.float32),
    }
    trainer.train_step(batch)  # compile
    n_steps = 5 if smoke else 30
    t0 = time.time()
    for _ in range(n_steps):
        trainer.train_step(batch)
    jax.block_until_ready(trainer.state.params)
    learner_steps_per_sec = n_steps / (time.time() - t0)
    log(f"bench: learner {learner_steps_per_sec:.2f} steps/s (batch {b})")

    north_star = 10_000.0  # games/hour, BASELINE.json north star (v4-8)
    out = {
        "metric": "self_play_games_per_hour",
        "value": round(games_per_hour, 1),
        "unit": "games/hour",
        "vs_baseline": round(games_per_hour / north_star, 4),
        "extra": {
            "backend": backend,
            "self_play_batch": sp_batch,
            "mcts_simulations": sims,
            "episodes_completed": episodes,
            "measure_seconds": round(elapsed, 1),
            "mean_episode_length": (
                round(float(np.mean(result.episode_lengths)), 1)
                if result.episode_lengths
                else None
            ),
            "moves_per_sec": round(moves_per_sec, 1),
            "mcts_leaf_evals_per_sec": round(leaf_evals_per_sec, 1),
            "learner_steps_per_sec": round(learner_steps_per_sec, 2),
            "learner_batch": b,
            "first_move_compile_seconds": round(compile_s, 1),
        },
    }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
