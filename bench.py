"""Benchmark: batched self-play throughput on the available accelerator.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ..., "extra": {...}}

(The supervised measurement CHILD additionally streams cumulative
snapshot lines tagged `extra.partial` as each section completes; the
supervisor consumes those internally — keeping the newest if the chip
wedges mid-run — and still prints exactly one line.)

Primary metric: **self-play games/hour**, measured directly (episodes
completed / wall-clock) with the flagship configuration - default 8x15
board, conv+residual+transformer net, 64-sim batched MCTS - on one
chip. `vs_baseline` divides by the BASELINE.json north star (10,000
games/hour on v4-8 with a 4-layer transformer net); the reference
itself publishes no numbers (BASELINE.md).

`extra` carries the secondary BASELINE metrics: MCTS leaf-evals/sec
(per chip) and learner steps/sec on a 256 batch.

Resilience: the accelerator is probed in a SUBPROCESS with a hard
timeout before this process touches JAX at all - a wedged TPU init
hangs uninterruptibly in-process (observed >570s in round 2), so a
watchdog thread cannot recover from it; a child process can simply be
killed. On probe failure the bench falls back to CPU and STILL emits
its one JSON line, with `extra.backend` recording what actually ran.
Any later crash also emits the JSON line (value 0, error recorded).

The chip behind the tunnel oscillates between healthy and wedged
(observed healthy->wedged->healthy within one hour in rounds 2-3), so a
single probe attempt throws away the round's TPU evidence whenever the
driver happens to land in a wedged window. The probe therefore RETRIES
with backoff across a total budget: first success wins.

Env knobs:
  BENCH_SMOKE=1         shrink everything for a fast CPU sanity run
  BENCH_TUNED_PRESET=P  bench the shapes from a tuned_preset.json
                        emitted by `cli tune` (wins over every other
                        shape knob; docs/AUTOTUNE.md)
  BENCH_SECONDS=N       override the self-play measurement window
  BENCH_INIT_TIMEOUT=N  per-attempt probe timeout in seconds (default 120)
  BENCH_INIT_BUDGET=N   total probe budget across retries (default 900)
  BENCH_TPU_BUDGET=N    wall budget for the supervised accelerator attempt
                        (default max(900, 4*BENCH_SECONDS+600))
  BENCH_CPU_BUDGET=N    wall budget for the CPU fallback run (default 3600)
  BENCH_NO_CPU_FALLBACK=1  emit the error line instead of a CPU run when
                        the accelerator attempt fails (sweep mode; an
                        explicit JAX_PLATFORMS=cpu request still runs)
  BENCH_PROFILE=1       capture an XLA trace of the first ~3 measured
                        chunks (BENCH_PROFILE_DIR, default
                        benchmarks/bench_profile); read with cli analyze
  BENCH_TREE_REUSE=0    skip the subtree-reuse A/B section (the headline
                        sections always measure fresh-root either way)
  JAX_PLATFORMS=cpu     skip the probe, run straight on CPU
  BENCH_CHILD=1         internal: marks the supervised measurement child
"""

import json
import os
import subprocess
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


# Children the supervisor currently has in flight, so a SIGTERM/SIGINT
# to the supervisor (the sweep's `timeout`, the watcher killing the
# sweep) can be forwarded instead of orphaning a JAX process that keeps
# holding — or wedging — the chip for every later attempt.
_live_children: "list[subprocess.Popen]" = []


def install_signal_forwarding() -> None:
    import signal

    def _forward(signum, frame):
        # TERM first: the child's own SIGTERM handler converts it to a
        # clean interpreter exit, giving PJRT its chip teardown — the
        # orphan-wedge scenario this forwarding exists to mitigate.
        # Only escalate to KILL after a short bounded wait.
        for child in list(_live_children):
            try:
                child.terminate()
            except Exception:
                pass
        deadline = time.time() + 10.0
        for child in list(_live_children):
            try:
                child.wait(timeout=max(0.1, deadline - time.time()))
            except Exception:
                try:
                    child.kill()
                except Exception:
                    pass
        raise SystemExit(128 + signum)

    signal.signal(signal.SIGTERM, _forward)
    signal.signal(signal.SIGINT, _forward)


def spawn_registered(args: list, **popen_kw) -> subprocess.Popen:
    """Popen + _live_children registration, atomic w.r.t. signals.

    A SIGTERM landing between Popen() returning and the append would
    orphan the just-spawned JAX child — exactly the chip-holding orphan
    the forwarding exists to prevent. Block TERM/INT across the pair.
    """
    import signal

    mask = {signal.SIGTERM, signal.SIGINT}
    old = signal.pthread_sigmask(signal.SIG_BLOCK, mask)
    try:
        proc = subprocess.Popen(args, **popen_kw)
        _live_children.append(proc)
    finally:
        signal.pthread_sigmask(signal.SIG_SETMASK, old)
    return proc


def probe_accelerator(timeout_s: float) -> "str | None":
    """Initialize JAX in a child process; return its backend name or None.

    The child inherits the ambient environment (including any accelerator
    plugin sitecustomize), so it exercises exactly the init path this
    process would take. Timeout or nonzero exit -> None (accelerator sick).
    A CPU answer that comes with a backend-init failure warning is ALSO
    None: that is a present-but-sick accelerator plugin falling back, not
    a cpu-only host, and it deserves the retry budget.
    """
    code = "import jax; print('BACKEND=' + jax.default_backend())"
    proc = spawn_registered(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        log(f"bench: accelerator probe timed out after {timeout_s:.0f}s")
        proc.kill()
        try:
            # Per subprocess docs: after the kill, re-invoke
            # communicate() to reap the process AND release the PIPE
            # fds + reader threads — a wedged chip retries this path
            # up to BENCH_INIT_BUDGET/BENCH_INIT_TIMEOUT times per
            # run, so each leak would compound.
            proc.communicate(timeout=30)
        except Exception:
            for stream in (proc.stdout, proc.stderr):
                try:
                    if stream:
                        stream.close()
                except Exception:
                    pass
        return None
    finally:
        _live_children.remove(proc)
    if proc.returncode != 0:
        tail = (stderr or "").strip().splitlines()[-3:]
        log(f"bench: accelerator probe failed rc={proc.returncode}: {tail}")
        return None
    backend = None
    for line in (stdout or "").splitlines():
        if line.startswith("BACKEND="):
            backend = line.split("=", 1)[1].strip()
    if backend == "cpu" and "Unable to initialize backend" in (stderr or ""):
        log("bench: probe fell back to CPU (plugin init failed) — retryable")
        return None
    return backend


def resolve_backend() -> "tuple[str, str | None]":
    """Decide the platform BEFORE importing jax; return (decision, probe_error).

    decision is "default" (let the plugin pick, probe passed) or "cpu".
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return "cpu", None
    timeout_s = float(os.environ.get("BENCH_INIT_TIMEOUT", "120"))
    budget_s = float(os.environ.get("BENCH_INIT_BUDGET", "900"))
    t0 = time.time()
    attempt = 0
    while True:
        remaining = budget_s - (time.time() - t0)
        if remaining < 30.0:
            # Too little budget left for a meaningful init attempt.
            return (
                "cpu",
                f"accelerator init probe failed {attempt}x over "
                f"{time.time() - t0:.0f}s budget",
            )
        attempt += 1
        this_timeout = min(timeout_s, remaining)
        log(
            f"bench: probing accelerator init (attempt {attempt}, "
            f"timeout {this_timeout:.0f}s, budget {remaining:.0f}s left)..."
        )
        backend = probe_accelerator(this_timeout)
        if backend == "cpu":
            # CPU-only host (no accelerator plugin): there is no
            # accelerator attempt to budget — go straight to the CPU
            # path, with a note so sweep mode can abort fast.
            log(f"bench: probe found cpu-only backend ({time.time() - t0:.1f}s)")
            return "cpu", "probe found cpu-only backend (no accelerator)"
        if backend is not None:
            log(f"bench: probe OK ({backend}, {time.time() - t0:.1f}s total)")
            return "default", None
        # A wedged chip often recovers within minutes; pause before the
        # next attempt so the probes sample distinct windows.
        remaining = budget_s - (time.time() - t0)
        if remaining >= 60.0:
            time.sleep(30.0)


def run_bench(smoke: bool, seconds: float) -> dict:
    import jax
    import numpy as np

    from alphatriangle_tpu.bench_config import resolve_bench_plan
    from alphatriangle_tpu.compile_cache import get_compile_cache
    from alphatriangle_tpu.env.engine import TriangleEnv
    from alphatriangle_tpu.features.core import get_feature_extractor
    from alphatriangle_tpu.nn.network import NeuralNetwork
    from alphatriangle_tpu.rl import SelfPlayEngine, Trainer
    from alphatriangle_tpu.utils.helpers import (
        enable_persistent_compilation_cache,
    )

    backend = jax.default_backend()
    # The flagship programs cost ~70s each to compile on the tunneled
    # chip; sweep sections repeat them. Cache executables across runs.
    # The backend is resolved at this point, so pass it: the helper
    # must skip CPU (XLA:CPU AOT reloads carry a SIGILL risk) even when
    # an auto run landed there without a pinned platform.
    enable_persistent_compilation_cache(backend=backend)
    # The AOT executable cache (compile_cache.py) covers the gap the
    # XLA persistent cache leaves: it works on CPU too, skips tracing/
    # lowering bookkeeping inside the window on a hit, and `cli warm`
    # (run by benchmarks/tpu_watch.sh on every successful probe) fills
    # it BEFORE a healthy window opens.
    compile_cache = get_compile_cache()
    device = jax.devices()[0]
    log(
        "bench: backend="
        f"{backend} device={getattr(device, 'device_kind', device)}"
    )

    # The plan is shared with `cli warm` so the warmer precompiles
    # exactly the shapes measured here (alphatriangle_tpu/bench_config.py).
    plan = resolve_bench_plan(smoke, backend)
    env_cfg, model_cfg = plan.env, plan.model
    mcts_cfg, train_cfg = plan.mcts, plan.train
    scale, sims = plan.scale, plan.sims
    sp_batch, chunk, lbatch = plan.sp_batch, plan.chunk, plan.lbatch
    if os.environ.get("BENCH_CONFIG"):
        log(f"bench: {scale}: {plan.description}")
    log(f"bench: scale={scale} sims={sims} batch={sp_batch} chunk={chunk}")

    env = TriangleEnv(env_cfg)
    extractor = get_feature_extractor(env, model_cfg)
    net = NeuralNetwork(model_cfg, env_cfg, seed=0)
    engine = SelfPlayEngine(env, extractor, net, mcts_cfg, train_cfg, seed=0)

    # --- self-play games/hour (primary) --------------------------------
    log("bench: compiling self-play chunk (first dispatch)...")
    t0 = time.time()
    engine.play_chunk()
    compile_s = time.time() - t0
    log(f"bench: first chunk (compile) {compile_s:.1f}s; measuring {seconds:.0f}s...")
    engine.harvest()  # reset counters after warmup

    # BENCH_PROFILE=1: capture a jax.profiler (XLA) trace of the first
    # few measured chunks — the ground truth for where self-play MFU
    # goes (tree ops vs network matmuls vs dispatch gaps). Kept out of
    # the headline sections; `cli analyze <dir>` reads the result.
    profile_dir = None
    if os.environ.get("BENCH_PROFILE") == "1":
        profile_dir = os.environ.get(
            "BENCH_PROFILE_DIR", "benchmarks/bench_profile"
        )
        jax.profiler.start_trace(profile_dir)

    def stop_profile() -> None:
        nonlocal profile_dir
        if profile_dir is not None:
            jax.profiler.stop_trace()
            log(f"bench: profiler trace written to {profile_dir}")
            profile_dir = None

    t0 = time.time()
    moves = 0
    try:
        while time.time() - t0 < seconds:
            engine.play_chunk()
            moves += chunk
            if moves >= 3 * chunk:
                # ~3 chunks of trace is plenty; tracing is not free, so
                # stop before it skews the rest of the window.
                stop_profile()
    finally:
        # Flush the trace even if a chunk raises (chip wedge mid-run):
        # the partial capture is exactly the diagnosis data we want.
        stop_profile()
    elapsed = time.time() - t0
    result = engine.harvest()
    episodes = result.num_episodes
    games_per_hour = episodes / elapsed * 3600.0
    # Engine-reported sims (exact under playout cap randomization too)
    # + visits inherited through subtree reuse (0 on the fresh-root
    # default plan) + one root eval per move.
    leaf_evals_per_sec = (
        result.total_simulations
        + result.total_reused_visits
        + moves * sp_batch
    ) / elapsed
    reused_fraction = result.total_reused_visits / max(
        1, result.total_simulations + result.total_reused_visits
    )
    moves_per_sec = moves * sp_batch / elapsed
    log(
        f"bench: {moves} lockstep moves x {sp_batch} games in {elapsed:.1f}s "
        f"-> {episodes} episodes, {games_per_hour:.0f} games/h, "
        f"{leaf_evals_per_sec:.0f} leaf-evals/s"
    )

    # Result assembled incrementally; after each completed section the
    # child emits a cumulative SNAPSHOT line tagged extra.partial, so a
    # chip that wedges mid-run still leaves the sections that finished
    # on the supervisor's pipe (the supervisor keeps the LAST parseable
    # line; it only early-stops on a final, untagged one). The flagship
    # games/h — the headline — therefore lands ~BENCH_SECONDS after
    # first compile no matter what the later sections do.
    north_star = 10_000.0  # games/hour, BASELINE.json north star (v4-8)
    from alphatriangle_tpu.utils.flops import (
        forward_flops,
        mfu,
        peak_bf16_tflops_info,
        train_step_flops,
    )

    device_kind = str(getattr(device, "device_kind", backend))
    # Explicit "unknown" beats a null nobody can distinguish from a
    # missing field; ALPHATRIANGLE_PEAK_TFLOPS (peak_source "env") lets
    # CPU/smoke runs still publish an MFU ratio.
    peak_tflops, peak_source = peak_bf16_tflops_info(device_kind)
    fwd = forward_flops(model_cfg, env_cfg, env_cfg.action_dim)
    sp_flops_s = leaf_evals_per_sec * fwd
    extra = {
        "backend": backend,
        "scale": scale,
        "search_recipe": {
            "root_selection": mcts_cfg.root_selection,
            "fast_simulations": mcts_cfg.fast_simulations,
            "full_search_prob": mcts_cfg.full_search_prob,
        },
        "descent_gather": mcts_cfg.descent_gather,
        # Kernel-library provenance (docs/KERNELS.md): which lowering
        # of each hot kernel + the rollout inference precision this
        # measurement ran with — a bench row without these would be a
        # mislabeled A/B the moment a non-default backend is flipped on.
        "kernels": {
            "descent_gather": mcts_cfg.descent_gather,
            "backup_update": mcts_cfg.backup_update,
            "per_sample": train_cfg.PER_SAMPLE_BACKEND,
            "inference_precision": model_cfg.INFERENCE_PRECISION,
            "tree_reuse": mcts_cfg.tree_reuse,
            "tree_reuse_backend": mcts_cfg.tree_reuse_backend,
        },
        "self_play_batch": sp_batch,
        "mcts_simulations": sims,
        "rollout_chunk_moves": chunk,
        "episodes_completed": episodes,
        "measure_seconds": round(elapsed, 1),
        "mean_episode_length": (
            round(float(np.mean(result.episode_lengths)), 1)
            if result.episode_lengths
            else None
        ),
        "moves_per_sec": round(moves_per_sec, 1),
        "mcts_leaf_evals_per_sec": round(leaf_evals_per_sec, 1),
        # Compare-facing aliases (telemetry/perf.py _summary_from_bench
        # reads these into the `cli compare` rows).
        "leaf_evals_per_sec": round(leaf_evals_per_sec, 1),
        "mcts_reused_visit_fraction": round(reused_fraction, 4),
        "first_chunk_compile_seconds": round(compile_s, 1),
        "device_kind": device_kind,
        "flops": {
            "forward_flops_per_eval": fwd,
            "peak_bf16_tflops": (
                peak_tflops if peak_tflops is not None else "unknown"
            ),
            "peak_source": peak_source,
            "self_play_tflops_per_sec": round(sp_flops_s / 1e12, 3),
            "self_play_mfu": (
                round(m, 4) if (m := mfu(sp_flops_s, device_kind)) else None
            ),
        },
    }
    # Device-stats plane (telemetry/device_stats.py): when the engine
    # compiled stat-packs in (ALPHATRIANGLE_DEVICE_STATS / config), the
    # newest in-program search/rollout fold rides the BENCH snapshot.
    ds_legs = getattr(engine, "last_device_stats", None)
    if ds_legs:
        from alphatriangle_tpu.telemetry.device_stats import (
            device_stats_json,
            device_stats_record,
        )

        ds_rec = device_stats_record(moves, **ds_legs)
        if ds_rec is not None:
            extra["device_stats"] = device_stats_json([ds_rec])

    def snapshot(partial: "str | None") -> dict:
        global _last_partial
        # Refreshed at every snapshot: later sections (learner, fused,
        # device-replay, overlapped) add their own compiles/hits — and
        # their own program memory records + device memory high water.
        extra["compile_cache"] = compile_cache.stats()
        from alphatriangle_tpu.telemetry.health import device_memory_stats

        extra["memory"] = {
            "device": device_memory_stats(),
            "programs": compile_cache.memory_summary(),
        }
        # Compiler cost attribution (telemetry/roofline.py): every
        # program's FLOPs/bytes-accessed next to its memory record, so
        # a BENCH snapshot carries the roofline inputs too.
        extra["roofline"] = {"programs": compile_cache.cost_summary()}
        r = {
            "metric": "self_play_games_per_hour",
            "value": round(games_per_hour, 1),
            "unit": "games/hour",
            "vs_baseline": round(games_per_hour / north_star, 4),
            "extra": json.loads(json.dumps(extra)),  # deep copy
        }
        if partial:
            r["extra"]["partial"] = partial
            _last_partial = r
        return r

    emit(snapshot("self_play"))

    # --- subtree-reuse A/B (MCTSConfig.tree_reuse) ----------------------
    # Same plan with reuse flipped on: the carried-tree engine measures
    # its own leaf-evals/s window against a matched fresh-root rate.
    # The headline sections always run fresh-root, so BENCH_TREE_REUSE=0
    # (skip) and =1 (run the extra section) emit identical headline
    # numbers — the A/B only ADDS extra["tree_reuse"]. Skipped under
    # recipes reuse cannot compose with (gumbel roots, playout cap
    # randomization — config/mcts_config.py validators).
    reuse_compatible = (
        mcts_cfg.root_selection != "gumbel"
        and mcts_cfg.fast_simulations is None
    )
    if os.environ.get("BENCH_TREE_REUSE", "1") != "0" and reuse_compatible:
        # A single-wave plan (wave >= sims) builds a depth-1 tree whose
        # promoted child has no expanded edges — nothing to carry. The
        # A/B then drops to a 2-wave geometry on BOTH sides and measures
        # its own matched fresh-root baseline; otherwise the headline
        # rate above is already the matched comparator.
        reuse_wave = mcts_cfg.mcts_batch_size
        fresh_comparator = leaf_evals_per_sec
        if reuse_wave >= sims:
            reuse_wave = max(1, sims // 2)
            match_cfg = mcts_cfg.model_copy(
                update={"mcts_batch_size": reuse_wave}
            )
            match_engine = SelfPlayEngine(
                env, extractor, net, match_cfg, train_cfg, seed=0
            )
            log("bench: compiling matched fresh-root chunk (2-wave)...")
            match_engine.play_chunk()
            match_engine.harvest()
            m_seconds = min(seconds, 15.0)
            t0 = time.time()
            m_moves = 0
            while time.time() - t0 < m_seconds:
                match_engine.play_chunk()
                m_moves += chunk
            m_elapsed = time.time() - t0
            m_result = match_engine.harvest()
            fresh_comparator = (
                m_result.total_simulations
                + m_result.total_reused_visits
                + m_moves * sp_batch
            ) / m_elapsed
        reuse_cfg = mcts_cfg.model_copy(
            update={"tree_reuse": True, "mcts_batch_size": reuse_wave}
        )
        reuse_engine = SelfPlayEngine(
            env, extractor, net, reuse_cfg, train_cfg, seed=0
        )
        log("bench: compiling reuse self-play chunk (first dispatch)...")
        t0 = time.time()
        reuse_engine.play_chunk()
        reuse_compile_s = time.time() - t0
        reuse_engine.harvest()
        reuse_seconds = min(seconds, 15.0)
        t0 = time.time()
        r_moves = 0
        while time.time() - t0 < reuse_seconds:
            reuse_engine.play_chunk()
            r_moves += chunk
        r_elapsed = time.time() - t0
        r_result = reuse_engine.harvest()
        r_leaf = (
            r_result.total_simulations
            + r_result.total_reused_visits
            + r_moves * sp_batch
        ) / r_elapsed
        r_fraction = r_result.total_reused_visits / max(
            1,
            r_result.total_simulations + r_result.total_reused_visits,
        )
        extra["tree_reuse"] = {
            "backend": reuse_cfg.tree_reuse_backend,
            "wave": reuse_wave,
            "seconds": round(r_elapsed, 1),
            "compile_seconds": round(reuse_compile_s, 1),
            "moves_per_sec": round(r_moves * sp_batch / r_elapsed, 1),
            "leaf_evals_per_sec": round(r_leaf, 1),
            "reused_visit_fraction": round(r_fraction, 4),
            # The acceptance ratio: reuse-on leaf-equivalent search
            # effort per wall second over the matched fresh-root rate
            # at equal sims and wave.
            "speedup_vs_fresh": (
                round(r_leaf / fresh_comparator, 3)
                if fresh_comparator > 0
                else None
            ),
        }
        log(f"bench: tree_reuse {extra['tree_reuse']}")
        emit(snapshot("tree_reuse"))

    # --- learner steps/sec (secondary) ----------------------------------
    trainer = Trainer(net, train_cfg)
    b = train_cfg.BATCH_SIZE
    rng = np.random.default_rng(0)
    policy = rng.random((b, env_cfg.action_dim)).astype(np.float32)
    policy /= policy.sum(axis=1, keepdims=True)
    batch = {
        "grid": rng.integers(-1, 2, size=(b, 1, env_cfg.ROWS, env_cfg.COLS)).astype(
            np.float32
        ),
        "other_features": rng.random(
            (b, model_cfg.OTHER_NN_INPUT_FEATURES_DIM)
        ).astype(np.float32),
        "policy_target": policy,
        "value_target": rng.uniform(-5, 5, b).astype(np.float32),
        "weights": np.ones(b, np.float32),
    }
    trainer.train_step(batch)  # compile
    n_steps = 5 if smoke else 30
    t0 = time.time()
    for _ in range(n_steps):
        trainer.train_step(batch)
    jax.block_until_ready(trainer.state.params)
    learner_steps_per_sec = n_steps / (time.time() - t0)
    log(f"bench: learner {learner_steps_per_sec:.2f} steps/s (batch {b})")

    # Fused groups: K steps per dispatch (one round trip per group) —
    # the FUSED_LEARNER_STEPS path the loop uses on tunneled chips.
    # CPU unrolls the group (see Trainer._train_steps_impl), so keep K
    # small there to bound compile time. (K values live in the shared
    # plan so `cli warm` precompiles the same fused programs.)
    fused_k = plan.fused_k
    fused_batches = [batch] * fused_k
    trainer.train_steps(fused_batches)  # compile
    n_groups = 2 if smoke else 5
    t0 = time.time()
    for _ in range(n_groups):
        trainer.train_steps(fused_batches)
    jax.block_until_ready(trainer.state.params)
    fused_steps_per_sec = n_groups * fused_k / (time.time() - t0)
    log(
        f"bench: fused learner {fused_steps_per_sec:.2f} steps/s "
        f"(batch {b}, K={fused_k})"
    )
    step_flops = train_step_flops(model_cfg, env_cfg, env_cfg.action_dim, b)
    ln_flops_s = fused_steps_per_sec * step_flops
    extra.update(
        {
            "learner_steps_per_sec": round(learner_steps_per_sec, 2),
            "learner_steps_per_sec_fused": round(fused_steps_per_sec, 2),
            "fused_group_size": fused_k,
            "learner_batch": b,
        }
    )
    extra["flops"].update(
        {
            "train_flops_per_step": step_flops,
            "learner_tflops_per_sec": round(ln_flops_s / 1e12, 3),
            "learner_mfu": (
                round(m, 4) if (m := mfu(ln_flops_s, device_kind)) else None
            ),
        }
    )
    emit(snapshot("learner"))

    # Device-resident replay (rl/device_buffer.py): batches are gathered
    # on device from sampled indices, so a fused group uploads ~K*B*4
    # bytes of indices instead of K full batches — the difference
    # between link-bound and compute-bound on a tunneled/PCIe-fed chip.
    # Measured on every backend except CPU (where host and "device"
    # memory are the same RAM and the comparison is meaningless).
    device_replay = plan.device_replay
    dev_buffer = None
    dev_steps_per_sec = None
    if device_replay:
        from alphatriangle_tpu.rl.device_buffer import DeviceReplayBuffer

        dev_buffer = DeviceReplayBuffer(
            train_cfg,
            grid_shape=(
                model_cfg.GRID_INPUT_CHANNELS,
                env_cfg.ROWS,
                env_cfg.COLS,
            ),
            other_dim=extractor.other_dim,
            action_dim=env_cfg.action_dim,
        )
        fill = batch["grid"].astype(np.int8).astype(np.float32)
        for _ in range(max(1, (train_cfg.MIN_BUFFER_SIZE_TO_TRAIN // b) + 1)):
            dev_buffer.add_dense(
                fill,
                batch["other_features"],
                batch["policy_target"],
                batch["value_target"],
            )

        def dev_samples(k: int) -> list:
            return [
                dev_buffer.sample(b, current_train_step=trainer.global_step)
                for _ in range(k)
            ]

        trainer.train_steps_from(dev_buffer, dev_samples(fused_k))  # compile
        t0 = time.time()
        for _ in range(n_steps // fused_k + 1):
            trainer.train_steps_from(dev_buffer, dev_samples(fused_k))
        jax.block_until_ready(trainer.state.params)
        dev_steps_per_sec = (
            (n_steps // fused_k + 1) * fused_k / (time.time() - t0)
        )
        log(
            f"bench: device-replay learner {dev_steps_per_sec:.2f} steps/s "
            f"(batch {b}, K={fused_k}, index-only uploads)"
        )
        extra["learner_steps_per_sec_device_replay"] = round(
            dev_steps_per_sec, 2
        )
        extra["flops"]["learner_device_replay_mfu"] = (
            round(m, 4)
            if (m := mfu(dev_steps_per_sec * step_flops, device_kind))
            else None
        )
        emit(snapshot("device_replay"))
    else:
        extra["learner_steps_per_sec_device_replay"] = None

    # --- overlapped producer/consumer (combined rates) ------------------
    # The phases above run each side alone; this measures both at once
    # (the training loop's ASYNC_ROLLOUTS topology): producer thread(s)
    # drive self-play chunks while the main thread trains. Two devices-
    # share mechanisms from the training loop are reproduced here:
    #   * async chunk auto-sizing — producer dispatches are shrunk to
    #     ~BENCH_ASYNC_CHUNK_SECONDS of device time each, bounding how
    #     long a learner dispatch queues behind a rollout program;
    #   * the pipelined learner — fused group N+1 is dispatched before
    #     group N's results are fetched, so the learner always has a
    #     program in the device FIFO and never idles a tunnel round
    #     trip per group.
    # BENCH_WORKERS > 1 measures the multi-stream topology
    # (NUM_SELF_PLAY_WORKERS).
    import threading

    overlap_seconds = 5.0 if smoke else min(40.0, seconds)
    per_move_s = elapsed / max(moves, 1)
    async_target_s = float(os.environ.get("BENCH_ASYNC_CHUNK_SECONDS", "2.0"))
    async_chunk = max(1, min(chunk, round(async_target_s / per_move_s)))
    # Larger fused groups amortize the producer interleave: the learner
    # runs K steps per time slice between rollout chunks.
    overlap_k = plan.overlap_k
    overlap_batches = [batch] * overlap_k
    if device_replay:
        # Warm the K-sized device-gather program OUTSIDE the timed
        # window (the host-path program is never dispatched here).
        if overlap_k != fused_k:
            assert dev_buffer is not None
            trainer.train_steps_from(dev_buffer, dev_samples(overlap_k))
    elif overlap_k != fused_k:
        trainer.train_steps(overlap_batches)  # compile
    if async_chunk != chunk:
        log(
            f"bench: overlap auto-chunk {async_chunk} moves/dispatch "
            f"(~{per_move_s:.2f}s/move, target {async_target_s:.1f}s)"
        )
        engine.play_chunk(async_chunk)  # compile the tuned size
    n_streams = max(1, int(os.environ.get("BENCH_WORKERS", "1")))
    engines = [engine]
    for i in range(1, n_streams):
        engines.append(
            SelfPlayEngine(
                env,
                extractor,
                net,
                mcts_cfg,
                train_cfg,
                seed=100 + i,
                share_compiled=engine,
            )
        )
    for e in engines:
        e.harvest()  # reset counters
    stop = threading.Event()
    produced = {"moves": 0, "episodes": 0, "errors": []}
    lock = threading.Lock()
    payloads: "queue.Queue | None" = None
    import queue

    if device_replay:
        # Mirror the real overlapped loop's device-replay topology:
        # producers enqueue device-resident payloads (no bulk fetch),
        # the learner thread ingests them into the on-device ring and
        # trains from index-only samples.
        payloads = queue.Queue(maxsize=4)

    def producer(e) -> None:
        try:
            while not stop.is_set():
                if payloads is not None:
                    stats, payload = e.play_moves_device(async_chunk)
                    with lock:
                        produced["moves"] += async_chunk
                        produced["episodes"] += stats.num_episodes
                    while not stop.is_set():
                        try:
                            payloads.put(payload, timeout=0.2)
                            break
                        except queue.Full:
                            continue
                else:
                    e.play_chunk(async_chunk)
                    with lock:
                        produced["moves"] += async_chunk
        except Exception as exc:  # surface, don't hang the bench
            with lock:
                produced["errors"].append(f"{type(exc).__name__}: {exc}")

    def dispatch_total(*comps) -> int:
        """Cumulative device-program dispatches across components (the
        per-mode dispatches-per-iteration counter; rl components each
        count their own dispatches)."""
        seen = {}
        for comp in comps:
            if comp is not None:
                seen[id(comp)] = comp
        return sum(
            int(getattr(x, "dispatch_count", 0)) for x in seen.values()
        )

    threads = [
        threading.Thread(target=producer, args=(e,), daemon=True)
        for e in engines
    ]
    for th in threads:
        th.start()
    t0 = time.time()
    o_steps = 0
    o_ingested = 0
    o_iters = 0
    o_disp0 = dispatch_total(trainer, dev_buffer, *engines)
    pending = None
    while time.time() - t0 < overlap_seconds:
        o_iters += 1
        if payloads is not None:
            assert dev_buffer is not None
            while True:
                try:
                    o_ingested += dev_buffer.ingest_payload(
                        payloads.get_nowait()
                    )
                except queue.Empty:
                    break
            nxt = trainer.train_steps_from_begin(
                dev_buffer, dev_samples(overlap_k)
            )
        else:
            nxt = trainer.train_steps_begin(overlap_batches)
        if pending is not None:
            o_steps += len(trainer.train_steps_finish(pending))
        pending = nxt
    if pending is not None:
        o_steps += len(trainer.train_steps_finish(pending))
    jax.block_until_ready(trainer.state.params)
    stop.set()
    for th in threads:
        th.join(timeout=120)
    o_elapsed = time.time() - t0
    if payloads is not None:
        o_episodes = produced["episodes"]
    else:
        o_episodes = sum(e.harvest().num_episodes for e in engines)
    o_games_per_hour = o_episodes / o_elapsed * 3600.0
    o_moves_per_sec = produced["moves"] * sp_batch / o_elapsed
    o_dpi = (
        dispatch_total(trainer, dev_buffer, *engines) - o_disp0
    ) / max(o_iters, 1)
    overlapped = {
        "seconds": round(o_elapsed, 1),
        "streams": n_streams,
        "chunk_moves": async_chunk,
        "fused_group": overlap_k,
        # Device dispatches per consumer pump beat — the host-round-
        # trip count the fused megastep collapses to 1.
        "dispatches_per_iteration": round(o_dpi, 2),
        "games_per_hour": round(o_games_per_hour, 1),
        "vs_serialized_self_play": round(
            o_games_per_hour / games_per_hour, 3
        )
        if games_per_hour
        else None,
        "moves_per_sec": round(
            produced["moves"] * sp_batch / o_elapsed, 1
        ),
        "learner_steps_per_sec": round(o_steps / o_elapsed, 2),
    }
    if device_replay:
        overlapped["device_replay"] = True
        overlapped["experiences_ingested_per_sec"] = round(
            o_ingested / o_elapsed, 1
        )
    if produced["errors"]:
        overlapped["producer_errors"] = produced["errors"]
    log(f"bench: overlapped {overlapped}")
    extra["overlapped"] = overlapped
    emit(snapshot("overlapped"))

    # --- fused megastep (Anakin): the whole iteration as ONE program ----
    # rollout chunk + ring ingest + on-device PER sampling + K learner
    # steps in a single jitted dispatch (rl/megastep.py) — the loop's
    # FUSED_MEGASTEP mode. vs_overlapped is the headline: the round-5
    # overlapped mode ran at 0.774x of serialized self-play because
    # every phase paid a host round trip; the megastep removes them.
    # BENCH_MEGASTEP=0 skips the section (compile-budget escape hatch).
    if os.environ.get("BENCH_MEGASTEP", "1") != "0":
        from alphatriangle_tpu.rl.device_buffer import DeviceReplayBuffer
        from alphatriangle_tpu.rl.megastep import MegastepRunner

        mega_buffer = dev_buffer
        if mega_buffer is None:
            # CPU/smoke path: the device-replay learner section didn't
            # run, so build + prefill the ring here (DEVICE_REPLAY="on"
            # works on the CPU backend; this section is single-threaded
            # so the XLA:CPU async-dispatch caveat does not apply).
            mega_buffer = DeviceReplayBuffer(
                train_cfg,
                grid_shape=(
                    model_cfg.GRID_INPUT_CHANNELS,
                    env_cfg.ROWS,
                    env_cfg.COLS,
                ),
                other_dim=extractor.other_dim,
                action_dim=env_cfg.action_dim,
            )
            fill = batch["grid"].astype(np.int8).astype(np.float32)
            for _ in range(
                max(1, (train_cfg.MIN_BUFFER_SIZE_TO_TRAIN // b) + 1)
            ):
                mega_buffer.add_dense(
                    fill,
                    batch["other_features"],
                    batch["policy_target"],
                    batch["value_target"],
                )
        runner = MegastepRunner(engine, trainer, mega_buffer, train_cfg)
        mega_k = fused_k
        engine.harvest()  # drop pre-section episode stats
        log(
            f"bench: compiling megastep t{chunk}_k{mega_k} "
            "(first dispatch)..."
        )
        t0 = time.time()
        runner.run_megastep(chunk, mega_k)
        mega_compile_s = time.time() - t0
        engine.harvest()
        mega_seconds = 5.0 if smoke else min(30.0, seconds)
        m_disp0 = dispatch_total(
            trainer, dev_buffer, mega_buffer, runner, *engines
        )
        t0 = time.time()
        m_moves = 0
        m_steps = 0
        m_iters = 0
        while time.time() - t0 < mega_seconds:
            runner.run_megastep(chunk, mega_k)
            m_moves += chunk
            m_steps += mega_k
            m_iters += 1
        m_elapsed = time.time() - t0
        m_dpi = (
            dispatch_total(
                trainer, dev_buffer, mega_buffer, runner, *engines
            )
            - m_disp0
        ) / max(m_iters, 1)
        m_result = engine.harvest()
        m_games_per_hour = m_result.num_episodes / m_elapsed * 3600.0
        m_moves_per_sec = m_moves * sp_batch / m_elapsed
        m_steps_per_sec = m_steps / m_elapsed
        # vs_overlapped: games/h when both windows completed episodes,
        # else the exact moves/s ratio (short smoke windows may finish
        # zero episodes; the ratio must still land — acceptance bar).
        if o_games_per_hour > 0 and m_games_per_hour > 0:
            vs_overlapped = m_games_per_hour / o_games_per_hour
            vs_basis = "games_per_hour"
        else:
            vs_overlapped = (
                m_moves_per_sec / o_moves_per_sec
                if o_moves_per_sec > 0
                else None
            )
            vs_basis = "moves_per_sec"
        megastep_section = {
            "seconds": round(m_elapsed, 1),
            "iterations": m_iters,
            "chunk_moves": chunk,
            "learner_steps_per_iteration": mega_k,
            "compile_seconds": round(mega_compile_s, 1),
            "games_per_hour": round(m_games_per_hour, 1),
            "moves_per_sec": round(m_moves_per_sec, 1),
            "learner_steps_per_sec": round(m_steps_per_sec, 2),
            "leaf_evals_per_sec": round(
                (
                    m_result.total_simulations
                    + m_result.total_reused_visits
                    + m_moves * sp_batch
                )
                / m_elapsed,
                1,
            ),
            "vs_overlapped": (
                round(vs_overlapped, 3) if vs_overlapped else None
            ),
            "vs_overlapped_basis": vs_basis,
            # All three loop modes' host-round-trip gauges side by
            # side (the overlapped/megastep values are measured; the
            # sync loop's is fixed by construction: rollout + ingest +
            # one fused learner group per iteration).
            "dispatches_per_iteration": {
                "sync": 3.0,
                "overlapped": round(o_dpi, 2),
                "megastep": round(m_dpi, 2),
            },
        }
        log(f"bench: megastep {megastep_section}")
        extra["megastep"] = megastep_section
        emit(snapshot("megastep"))

        # --- dp-sharded megastep scaling (megastep/dp<D>_t<T>_k<K>) -
        # The same fused program sharded over the mesh's dp axis: each
        # device runs its rollout lanes, scatters into its ring shard,
        # samples its PER stratum and psums gradients in-program.
        # Measures games/h + learner steps/s at 1 vs N devices and the
        # vs_single_device ratio against the window just measured.
        # BENCH_MEGASTEP_DP=0 skips (compile-budget escape hatch).
        from alphatriangle_tpu.telemetry.memory import (
            sharded_megastep_dp,
        )

        mega_dp = sharded_megastep_dp(train_cfg)
        if (
            os.environ.get("BENCH_MEGASTEP_DP", "1") != "0"
            and mega_dp > 1
        ):
            from alphatriangle_tpu.config import MeshConfig
            from alphatriangle_tpu.rl import SelfPlayEngine, Trainer
            from alphatriangle_tpu.rl.sharded_device_buffer import (
                ShardedDeviceReplayBuffer,
            )

            mesh = MeshConfig(DP_SIZE=mega_dp).build_mesh()
            dp_engine = SelfPlayEngine(
                env, extractor, net, mcts_cfg, train_cfg, seed=11,
                mesh=mesh,
            )
            dp_trainer = Trainer(net, train_cfg, mesh=mesh)
            dp_ring = ShardedDeviceReplayBuffer(
                train_cfg,
                grid_shape=(
                    model_cfg.GRID_INPUT_CHANNELS,
                    env_cfg.ROWS,
                    env_cfg.COLS,
                ),
                other_dim=extractor.other_dim,
                action_dim=env_cfg.action_dim,
                mesh=mesh,
            )
            fill = batch["grid"].astype(np.int8).astype(np.float32)
            for _ in range(
                max(1, (train_cfg.MIN_BUFFER_SIZE_TO_TRAIN // b) + 1)
            ):
                dp_ring.add_dense(
                    fill,
                    batch["other_features"],
                    batch["policy_target"],
                    batch["value_target"],
                )
            dp_runner = MegastepRunner(
                dp_engine, dp_trainer, dp_ring, train_cfg
            )
            log(
                f"bench: compiling megastep dp{mega_dp}_t{chunk}"
                f"_k{mega_k} (first dispatch)..."
            )
            t0 = time.time()
            dp_runner.run_megastep(chunk, mega_k)
            s_compile_s = time.time() - t0
            dp_engine.harvest()
            s_disp0 = dispatch_total(dp_trainer, dp_ring, dp_runner)
            t0 = time.time()
            s_moves = 0
            s_steps = 0
            s_iters = 0
            while time.time() - t0 < mega_seconds:
                dp_runner.run_megastep(chunk, mega_k)
                s_moves += chunk
                s_steps += mega_k
                s_iters += 1
            s_elapsed = time.time() - t0
            s_dpi = (
                dispatch_total(dp_trainer, dp_ring, dp_runner)
                - s_disp0
            ) / max(s_iters, 1)
            s_result = dp_engine.harvest()
            s_games_per_hour = (
                s_result.num_episodes / s_elapsed * 3600.0
            )
            s_moves_per_sec = s_moves * sp_batch / s_elapsed
            s_steps_per_sec = s_steps / s_elapsed
            if m_games_per_hour > 0 and s_games_per_hour > 0:
                vs_single = s_games_per_hour / m_games_per_hour
                vs_single_basis = "games_per_hour"
            else:
                vs_single = (
                    s_moves_per_sec / m_moves_per_sec
                    if m_moves_per_sec > 0
                    else None
                )
                vs_single_basis = "moves_per_sec"
            scaling_section = {
                "devices": mega_dp,
                "seconds": round(s_elapsed, 1),
                "iterations": s_iters,
                "compile_seconds": round(s_compile_s, 1),
                "games_per_hour": {
                    "1": round(m_games_per_hour, 1),
                    str(mega_dp): round(s_games_per_hour, 1),
                },
                "learner_steps_per_sec": {
                    "1": round(m_steps_per_sec, 2),
                    str(mega_dp): round(s_steps_per_sec, 2),
                },
                "moves_per_sec": round(s_moves_per_sec, 1),
                "vs_single_device": (
                    round(vs_single, 3) if vs_single else None
                ),
                "vs_single_device_basis": vs_single_basis,
                "dispatches_per_iteration": round(s_dpi, 2),
            }
            log(f"bench: megastep scaling {scaling_section}")
            megastep_section["scaling"] = scaling_section
            emit(snapshot("megastep_scaling"))
        elif mega_dp > 1:
            log("bench: megastep scaling skipped (BENCH_MEGASTEP_DP=0)")
        else:
            log(
                "bench: megastep scaling skipped (single device or "
                "geometry does not divide the mesh)"
            )

    # --- policy-serving latency (serving/service.py) --------------------
    # The serving front end's SLO numbers next to the training numbers:
    # simulated concurrent sessions with admit/retire churn through the
    # continuous batcher at the plan's `serve/b<B>` shape (the shape
    # `cli warm` precompiles). Overall p50/p95 per-move latency,
    # requests/s and batch fill land in extra["serve"] — the same
    # metrics `cli perf` summarizes from a real serve run's ledger and
    # `cli compare` gates. BENCH_SERVE=0 skips.
    if os.environ.get("BENCH_SERVE", "1") != "0":
        from alphatriangle_tpu.nn.precision import (
            cast_params_for_inference,
            quantized_param_bytes,
        )
        from alphatriangle_tpu.serving import (
            PolicyService,
            run_simulated_load,
        )

        def serve_param_bytes(cfg) -> int:
            """Bytes of weights one serve wave reads from HBM under
            `cfg`'s inference precision policy (nn/precision.py)."""
            return int(
                quantized_param_bytes(
                    cast_params_for_inference(net.variables, cfg)
                )
            )

        serve_slots = plan.serve_batch
        serve_gumbel = (
            getattr(mcts_cfg, "root_selection", "puct") == "gumbel"
        )
        if serve_gumbel:
            # Mirror `cli warm`'s construction exactly: serving
            # dispatches the deterministic exploit-mode Gumbel arm.
            from alphatriangle_tpu.mcts import GumbelMCTS

            serve_mcts = GumbelMCTS(
                env, extractor, net.model, mcts_cfg, net.support,
                exploit=True,
            )
        else:
            serve_mcts = engine.mcts
        serve_service = PolicyService(
            env, extractor, net, serve_mcts,
            slots=serve_slots, use_gumbel=serve_gumbel,
            ladder=plan.serve_buckets,
        )
        log(f"bench: warming serve/b{serve_slots}...")
        t0 = time.time()
        serve_service.warm()
        serve_compile_s = time.time() - t0
        serve_stats = run_simulated_load(
            serve_service,
            total_sessions=serve_slots + max(8, serve_slots // 2),
            max_moves=8 if smoke else 32,
            seed=0,
            max_dispatches=4000,
        )
        # No telemetry ticks drained the service's windows, so these
        # percentiles cover every request of the section.
        slo = serve_service.serve_stats(drain=False)
        serve_section = {
            "slots": serve_slots,
            "sessions_served": serve_stats["sessions_served"],
            "moves_served": serve_stats["moves_served"],
            "seconds": serve_stats["seconds"],
            "compile_seconds": round(serve_compile_s, 1),
            "requests_per_sec": serve_stats["moves_per_sec"],
            # Device search effort per wall second: full-array sims +
            # reused visits (0 unless the plan serves with tree_reuse)
            # + one root eval per dispatched lane.
            "leaf_evals_per_sec": (
                round(
                    (
                        serve_service.simulations_total
                        + serve_service.reused_visits_total
                        + serve_service.dispatch_count * serve_slots
                    )
                    / serve_stats["seconds"],
                    1,
                )
                if serve_stats["seconds"]
                else None
            ),
            "move_latency_ms_p50": slo["serve_move_latency_ms_p50"],
            "move_latency_ms_p95": slo["serve_move_latency_ms_p95"],
            "queue_wait_ms_p95": slo["serve_queue_wait_ms_p95"],
            "batch_ms_p50": slo["serve_batch_ms_p50"],
            "batch_fill": slo["serve_batch_fill"],
            "precision": model_cfg.INFERENCE_PRECISION,
            "buckets": list(serve_service.ladder.rungs),
            "rung_switches": serve_service.rung_switches,
            "param_bytes": serve_param_bytes(model_cfg),
        }
        log(f"bench: serve {serve_section}")

        def serve_arm(precision: str, ladder_spec) -> dict:
            """One alternate serve arm: same weights and traffic shape
            as the main section, different inference precision and/or
            bucket ladder — the paired-measurement A/B the serve
            speedup/fill ratios are computed from."""
            from alphatriangle_tpu.features.core import (
                get_feature_extractor,
            )
            from alphatriangle_tpu.nn.network import NeuralNetwork

            model_arm = model_cfg.model_copy(
                update={"INFERENCE_PRECISION": precision}
            )
            extractor_arm = get_feature_extractor(env, model_arm)
            net_arm = NeuralNetwork(
                model_arm, env_cfg, seed=0, variables=net.variables
            )
            if serve_gumbel:
                from alphatriangle_tpu.mcts import GumbelMCTS

                mcts_arm = GumbelMCTS(
                    env, extractor_arm, net_arm.model, mcts_cfg,
                    net_arm.support, exploit=True,
                )
            else:
                from alphatriangle_tpu.mcts import BatchedMCTS

                mcts_arm = BatchedMCTS(
                    env, extractor_arm, net_arm.model, mcts_cfg,
                    net_arm.support,
                )
            svc = PolicyService(
                env, extractor_arm, net_arm, mcts_arm,
                slots=serve_slots, use_gumbel=serve_gumbel,
                ladder=ladder_spec,
            )
            svc.warm()
            stats = run_simulated_load(
                svc,
                total_sessions=serve_slots + max(8, serve_slots // 2),
                max_moves=8 if smoke else 32,
                seed=0,
                max_dispatches=4000,
            )
            arm_slo = svc.serve_stats(drain=False)
            return {
                "precision": precision,
                "buckets": list(svc.ladder.rungs),
                "requests_per_sec": stats["moves_per_sec"],
                "batch_fill": arm_slo["serve_batch_fill"],
                "rung_switches": svc.rung_switches,
                "param_bytes": serve_param_bytes(model_arm),
            }

        # Precision A/B (BENCH_SERVE_PRECISION=int8): the named
        # precision arm against a bf16 baseline arm on identical
        # weights and traffic — speedup_vs_bf16 is the serve fast
        # path's headline, param_bytes_ratio the HBM-read reduction
        # the int8 weight tensors buy.
        ab_precision = os.environ.get("BENCH_SERVE_PRECISION")
        if ab_precision:
            arm = serve_arm(ab_precision, plan.serve_buckets)
            base = (
                serve_section
                if model_cfg.INFERENCE_PRECISION == "bfloat16"
                else serve_arm("bfloat16", plan.serve_buckets)
            )
            serve_section["precision_ab"] = {
                "arm": arm,
                "baseline_precision": "bfloat16",
                "baseline_requests_per_sec": base["requests_per_sec"],
                "speedup_vs_bf16": (
                    round(
                        arm["requests_per_sec"]
                        / base["requests_per_sec"],
                        3,
                    )
                    if base["requests_per_sec"]
                    else None
                ),
                "param_bytes_ratio": (
                    round(arm["param_bytes"] / base["param_bytes"], 3)
                    if base["param_bytes"]
                    else None
                ),
            }
            log(f"bench: serve precision A/B {serve_section['precision_ab']}")
        # Bucket-ladder A/B (BENCH_SERVE_BUCKETS=...): the laddered
        # main section against a fixed single-rung arm — fill_vs_fixed
        # > 1 means the micro-batcher's rung walking kept waves fuller
        # than the fixed flagship shape under the same churn.
        if plan.serve_buckets:
            fixed = serve_arm(model_cfg.INFERENCE_PRECISION, None)
            serve_section["buckets_ab"] = {
                "fixed": fixed,
                "fill_vs_fixed": (
                    round(
                        serve_section["batch_fill"]
                        / fixed["batch_fill"],
                        3,
                    )
                    if fixed["batch_fill"]
                    else None
                ),
            }
            log(f"bench: serve buckets A/B {serve_section['buckets_ab']}")
        extra["serve"] = serve_section
    log(f"bench: flops/mfu {extra['flops']}")
    return snapshot(None)


# Most recent partial snapshot emitted by run_bench (child process
# only): the crash path must finish with the best real measurement,
# not bury it under a zero-value error line.
_last_partial: "dict | None" = None


def error_result(extra: dict) -> dict:
    """The one-JSON-line shape for a run that produced no measurement."""
    return {
        "metric": "self_play_games_per_hour",
        "value": 0.0,
        "unit": "games/hour",
        "vs_baseline": 0.0,
        "extra": extra,
    }


def child_main() -> None:
    """Run the measurement on whatever platform the environment dictates
    and emit the one JSON line. Invoked by the supervisor (BENCH_CHILD=1);
    a crash still emits, but a WEDGE here simply hangs — the supervisor's
    wall-clock budget is the recovery path."""
    import signal

    # Python's default SIGTERM disposition kills the process without
    # running atexit — the supervisor's graceful-kill rung (terminate
    # before kill) only buys a clean PJRT/chip teardown if we convert
    # the signal into a normal interpreter exit.
    signal.signal(signal.SIGTERM, lambda signum, frame: sys.exit(143))

    smoke = os.environ.get("BENCH_SMOKE") == "1"
    seconds = float(os.environ.get("BENCH_SECONDS", "8" if smoke else "75"))
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # Site hooks may force the platform config value at interpreter
        # start, overriding the env var; re-assert before any backend
        # initializes (conftest.py pattern).
        import jax

        jax.config.update("jax_platforms", "cpu")
    try:
        out = run_bench(smoke, seconds)
    except Exception as exc:  # always emit the one JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        if _last_partial is not None:
            # Sections that completed before the crash are a real
            # measurement; re-emit the newest snapshot (still tagged
            # extra.partial) with the crash recorded beside it, so the
            # LAST line the supervisor parses is the best one.
            out = _last_partial
            out["extra"]["error_after_partial"] = (
                f"{type(exc).__name__}: {exc}"
            )
        else:
            out = error_result({"error": f"{type(exc).__name__}: {exc}"})
    emit(out)


def run_child(platform: "str | None", timeout_s: float) -> "dict | None":
    """Run the whole bench in a killable child; return its parsed JSON
    line, or None on hang/crash/garbage.

    The round-3->4 lesson: the init PROBE can pass and the chip wedge
    seconds later inside the first compile (observed 2026-07-31: probe OK
    in 13.5s, then NeuralNetwork init hung >19 min). A wedged XLA call
    blocks uninterruptibly in C++, so in-process supervision (signals,
    watchdog threads) cannot recover — only a child process the parent
    can kill. stderr is inherited so progress streams live.
    """
    import select

    env = dict(os.environ, BENCH_CHILD="1")
    if platform:
        env["JAX_PLATFORMS"] = platform
    proc = spawn_registered(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE,
        env=env,
    )

    # Incremental select/os.read drain instead of communicate(): a child
    # that emitted its JSON line and then wedged in an uninterruptible
    # XLA teardown call never reaches EOF (its fds stay open), so
    # communicate() would time out and discard the already-buffered
    # result. Reading the pipe directly keeps whatever the child
    # managed to flush, whatever its fate.
    fd = proc.stdout.fileno()
    buf = bytearray()

    def drain(deadline: float, stop_on_result: bool) -> str:
        """Read until deadline/EOF — or, when stop_on_result, until the
        buffer already holds the complete result line (stdout's contract
        is ONE JSON line emitted as the child's last act; waiting out
        the rest of the budget on an emit-then-wedge child wastes it)."""
        while True:
            remaining = deadline - time.time()
            if remaining <= 0:
                return "deadline"
            ready, _, _ = select.select(
                [proc.stdout], [], [], min(remaining, 5.0)
            )
            if not ready:
                if proc.poll() is not None:
                    return "exit"  # child gone and pipe idle
                continue
            data = os.read(fd, 65536)
            if not data:
                return "eof"
            buf.extend(data)
            if (
                stop_on_result
                and buf.endswith(b"\n")
                and is_final_result(parse_last_json_line(buf))
            ):
                return "result"

    try:
        reason = drain(time.time() + timeout_s, stop_on_result=True)
        grace = 30.0 if reason in ("result", "eof") else 5.0
        try:
            # Grace for the finish->exit race. After a clean result/EOF
            # the child is presumably in JAX/TPU runtime teardown — give
            # it long enough to shut the chip down cleanly rather than
            # SIGKILLing a correctly-exiting process every run.
            proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            pass
        hung = proc.poll() is None
        if hung:
            if reason == "deadline":
                log(f"bench: attempt exceeded {timeout_s:.0f}s budget; killing")
            else:
                log(f"bench: child stalled after {reason}; killing")
            # SIGTERM first (lets atexit/PJRT teardown run), then KILL.
            proc.terminate()
            drain(time.time() + 10.0, stop_on_result=False)  # salvage pipe
            try:
                proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    # A child blocked in an uninterruptible (D-state)
                    # XLA call survives even SIGKILL until the kernel
                    # releases it; don't let the zombie stop the
                    # supervisor from emitting its line.
                    log("bench: child unkillable (D-state?); abandoning it")
    finally:
        _live_children.remove(proc)
    # Parse regardless of exit status: a child that emitted its JSON
    # line and THEN died or hung still produced a real measurement.
    rc = proc.returncode
    parsed = parse_last_json_line(buf)
    if parsed is not None:
        if rc is None or rc != 0:
            log(
                f"bench: attempt ended abnormally (rc={rc}) after "
                "emitting its result; keeping the measurement"
            )
        return parsed
    if reason != "deadline":
        log(f"bench: attempt ended ({reason}, rc={rc}) with no JSON")
    return None


def parse_last_json_line(buf: bytes) -> "dict | None":
    """Last parseable '{'-line in a (possibly truncated) stdout capture."""
    for line in reversed(buf.decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue  # stray '{'-line after the real one; keep looking
    return None


def is_final_result(parsed: "dict | None") -> bool:
    """True when `parsed` is a COMPLETE result line.

    The child emits a cumulative snapshot after each section, tagged
    `extra.partial`, so a mid-run wedge still leaves every completed
    section's numbers on the pipe; the supervisor must keep draining
    past those and only early-stop on the final, untagged line."""
    return parsed is not None and not parsed.get("extra", {}).get("partial")


def main() -> None:
    if os.environ.get("BENCH_CHILD") == "1":
        child_main()
        return

    # Supervisor: never touches JAX itself, so it can always emit the
    # JSON line no matter what the accelerator does. Signals are
    # forwarded to whichever probe/measurement child is in flight.
    install_signal_forwarding()
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    seconds = float(os.environ.get("BENCH_SECONDS", "8" if smoke else "75"))
    decision, probe_error = resolve_backend()

    out = None
    if decision == "default":
        # Accelerator attempt under a hard wall budget: measurement
        # windows (self-play + overlapped ≈ 2x seconds) + compiles
        # (~70s/program on the tunneled chip, several programs).
        budget = float(
            os.environ.get("BENCH_TPU_BUDGET", max(900.0, seconds * 4 + 600))
        )
        out = run_child(None, budget)
        child_error = out.get("extra", {}).get("error") if out else None
        if child_error:
            # A Python-visible crash inside the accelerator child (e.g.
            # RESOURCE_EXHAUSTED on a sick chip) deserves the same CPU
            # fallback a segfault or hang gets — and the real exception
            # text must survive into the emitted line, not a made-up
            # "killed at budget" story.
            log(f"bench: attempt errored: {child_error}")
            out = None
            probe_error = f"accelerator attempt errored: {child_error}"
        elif out is None:
            probe_error = (
                "accelerator attempt hung/crashed after passing the init "
                f"probe (killed at {budget:.0f}s budget)"
            )
        elif (
            os.environ.get("BENCH_NO_CPU_FALLBACK") == "1"
            and out.get("extra", {}).get("backend") == "cpu"
        ):
            # The plugin passed the probe but the measurement child
            # silently fell back to CPU (plugin init failed inside the
            # child). In sweep mode that row must NOT land: it would
            # record a cpu-backend measurement under a TPU section
            # label AND burn the minutes sweep mode exists to avoid.
            log(
                "bench: child completed on cpu backend under "
                "BENCH_NO_CPU_FALLBACK; discarding the measurement"
            )
            out = None
            probe_error = (
                "accelerator probe passed but the measurement child "
                "resolved to the cpu backend"
            )
        if out is None:
            log(f"bench: {probe_error}")

    # resolve_backend already recognized an explicit CPU request: it is
    # the only way to get decision "cpu" with no probe error.
    explicit_cpu = decision == "cpu" and probe_error is None
    if out is None:
        if os.environ.get("BENCH_NO_CPU_FALLBACK") == "1" and not explicit_cpu:
            # Sweep mode: a CPU number under a TPU section label is
            # worse than no number — emit the error line immediately.
            out = error_result({"backend": "none", "error": probe_error})
        else:
            if probe_error:
                log(f"bench: FALLING BACK TO CPU ({probe_error})")
            out = run_child(
                "cpu", float(os.environ.get("BENCH_CPU_BUDGET", "3600"))
            )
            if out is None:
                out = error_result(
                    {"backend": "cpu", "error": "CPU fallback also failed"}
                )

    if probe_error:
        out.setdefault("extra", {})["probe_error"] = probe_error
    if out.get("extra", {}).get("partial"):
        # Killed/crashed mid-run after >=1 completed section: the kept
        # snapshot is real, but the record says which sections ran.
        log(
            "bench: keeping PARTIAL result (completed through "
            f"'{out['extra']['partial']}' section)"
        )
    if out.get("extra", {}).get("backend") != "tpu":
        # A CPU-fallback number is not the TPU story; point at the
        # newest preserved on-hardware measurement for comparison.
        out.setdefault("extra", {})["tpu_measurement_on_record"] = (
            latest_tpu_record()
        )
    emit(out)


def latest_tpu_record(base_dir: "str | None" = None) -> str:
    """Newest on-chip flagship measurement preserved in the repo —
    cited on CPU-fallback lines so the round's official record always
    carries the real TPU story even when the driver's window lands on
    a wedged chip. Prefers the sweep jsonl artifacts (watcher-captured,
    freshest first), falls back to the static round-3 artifact."""
    import glob
    import re

    here = base_dir or os.path.dirname(os.path.abspath(__file__))

    def round_key(path: str) -> tuple:
        # Order by the round number IN the filename (durable across
        # git checkouts, which flatten mtimes), mtime as tie-breaker.
        m = re.search(r"tpu_r(\d+)", os.path.basename(path))
        return (int(m.group(1)) if m else -1, os.path.getmtime(path))

    for path in sorted(
        glob.glob(os.path.join(here, "benchmarks", "tpu_r*_results*.jsonl")),
        key=round_key,
        reverse=True,
    ):
        try:
            with open(path) as f:
                rows = [
                    json.loads(line)
                    for line in f.read().splitlines()
                    if line.strip()
                ]
        except (OSError, json.JSONDecodeError):
            continue
        for row in rows:
            if not str(row.get("label", "")).startswith("flagship"):
                continue
            res = row.get("result", {})
            value = res.get("value")
            # Only a real on-chip number may be cited as the TPU
            # record — the sweep can legitimately contain CPU-fallback
            # or zero-value error rows from wedge windows.
            if (
                res.get("extra", {}).get("backend") != "tpu"
                or not isinstance(value, (int, float))
                or value <= 0
            ):
                continue
            return (
                f"{os.path.relpath(path, here)} [{row['label']}]: "
                f"{value:,.0f} games/hour on one chip (backend tpu)"
            )
    return (
        "benchmarks/bench_flagship_tpu_20260730.json: 211,771 "
        "games/hour on one v5 lite chip (2026-07-30)"
    )


if __name__ == "__main__":
    main()
