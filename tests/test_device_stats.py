"""Device telemetry plane unit tests (telemetry/device_stats.py):
enable-state + cache signatures, the crash-safe beacon channel (armed
emit -> beacons.jsonl rows -> JAX-free readers), the host-side folds
feeding `kind:"device_stats"` ledger records, RunTelemetry wiring, the
dispatch watchdog's near-deadline warning (the in-process beacon armer),
anomaly latches on search health, and the supervisor's
`TELEMETRY__BEACONS` respawn directive end to end (policy -> runner)."""

import json

import pytest

from alphatriangle_tpu.telemetry.device_stats import (
    BEACONS_FILENAME,
    arm_beacons,
    attach_beacon_run_dir,
    beacon_every,
    beacon_signature,
    beacons_armed,
    describe_beacon,
    device_stats_enabled,
    device_stats_json,
    device_stats_record,
    device_stats_signature,
    disarm_beacons,
    emit_beacon,
    fold_search_stats,
    last_beacon,
    merge_search_folds,
    note_dispatch,
    read_beacons,
    reset_device_stats_state,
    rollout_chunk_stats,
    set_device_stats,
    summarize_device_stats,
)


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch):
    """Every test starts from import-time defaults with the env arming
    knobs cleared, and leaves no armed state behind for the suite."""
    for var in (
        "ALPHATRIANGLE_DEVICE_STATS",
        "ALPHATRIANGLE_BEACONS",
        "ALPHATRIANGLE_BEACON_EVERY",
    ):
        monkeypatch.delenv(var, raising=False)
    reset_device_stats_state()
    yield
    reset_device_stats_state()


class TestEnableState:
    def test_defaults_off(self):
        assert device_stats_enabled() is False
        assert beacons_armed() is False
        assert device_stats_signature() == ""
        assert beacon_signature() == ""

    def test_set_device_stats_and_signature(self):
        set_device_stats(True)
        assert device_stats_enabled() is True
        assert device_stats_signature() == "|devstats1"

    def test_env_override_wins_over_setter(self, monkeypatch):
        set_device_stats(True)
        monkeypatch.setenv("ALPHATRIANGLE_DEVICE_STATS", "0")
        assert device_stats_enabled() is False
        monkeypatch.setenv("ALPHATRIANGLE_DEVICE_STATS", "1")
        set_device_stats(False)
        assert device_stats_enabled() is True

    def test_env_arms_beacons(self, monkeypatch):
        monkeypatch.setenv("ALPHATRIANGLE_BEACONS", "1")
        monkeypatch.setenv("ALPHATRIANGLE_BEACON_EVERY", "3")
        reset_device_stats_state()
        assert beacons_armed() is True
        assert beacon_every() == 3
        assert beacon_signature() == "|beacons3"

    def test_arm_and_disarm(self):
        arm_beacons(every=5)
        assert beacons_armed() is True
        assert beacon_every() == 5
        disarm_beacons()
        assert beacons_armed() is False
        assert beacon_signature() == ""

    def test_bad_beacon_every_env_falls_back(self, monkeypatch):
        monkeypatch.setenv("ALPHATRIANGLE_BEACON_EVERY", "banana")
        reset_device_stats_state()
        assert beacon_every() == 8  # DEFAULT_BEACON_EVERY


class TestBeaconChannel:
    def test_unarmed_emit_is_pure_noop(self, tmp_path):
        attach_beacon_run_dir(tmp_path)
        emit_beacon("search_wave", 3)
        assert not (tmp_path / BEACONS_FILENAME).exists()

    def test_armed_emit_writes_subsampled_rows(self, tmp_path):
        arm_beacons()
        attach_beacon_run_dir(tmp_path)
        note_dispatch("megastep/t4_k2")
        for k in range(7):
            emit_beacon("search_wave", k, every=3)
        rows = read_beacons(tmp_path / BEACONS_FILENAME)
        assert [r["index"] for r in rows] == [0, 3, 6]
        assert all(r["phase"] == "search_wave" for r in rows)
        assert all(r["program"] == "megastep/t4_k2" for r in rows)

    def test_emit_inside_jit(self, tmp_path):
        """The traced form: `jax.debug.callback` rows land after the
        dispatch completes (async callbacks drained by block_until_ready)."""
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp

        arm_beacons()
        attach_beacon_run_dir(tmp_path)
        note_dispatch("test/jit")

        @jax.jit
        def f(x):
            emit_beacon("learner_step", jnp.int32(4))
            return x * 2

        f(jnp.ones(3)).block_until_ready()
        jax.effects_barrier()
        rows = read_beacons(tmp_path / BEACONS_FILENAME)
        assert rows and rows[-1]["phase"] == "learner_step"
        assert rows[-1]["index"] == 4

    def test_last_beacon_reads_run_dir(self, tmp_path):
        arm_beacons()
        attach_beacon_run_dir(tmp_path)
        emit_beacon("ring_scatter", 2)
        emit_beacon("learner_step", 9)
        newest = last_beacon(tmp_path)
        assert newest["phase"] == "learner_step"
        assert newest["index"] == 9
        assert "phase=learner_step" in describe_beacon(newest)

    def test_describe_beacon_legacy(self):
        assert describe_beacon(None) is None
        assert describe_beacon("junk") is None


class TestFolds:
    def test_fold_search_stats_reduces_stacked(self):
        np = pytest.importorskip("numpy")

        stats = {
            "root_entropy": np.array([1.0, 3.0]),
            "occupancy": np.array([0.25, 0.75]),
            "value_abs_max": np.array([0.5, 2.0]),
            "depth_hist": np.array([[1.0, 0.0], [2.0, 4.0]]),
        }
        fold = fold_search_stats(stats)
        assert fold["root_entropy"] == pytest.approx(2.0)
        assert fold["occupancy"] == pytest.approx(0.5)
        assert fold["value_abs_max"] == pytest.approx(2.0)  # max, not mean
        assert fold["depth_hist"] == [3.0, 4.0]

    def test_fold_empty_is_none(self):
        assert fold_search_stats(None) is None
        assert fold_search_stats({}) is None

    def test_merge_search_folds(self):
        merged = merge_search_folds(
            [
                {"root_entropy": 1.0, "value_abs_max": 0.5,
                 "depth_hist": [1.0, 1.0]},
                None,
                {"root_entropy": 3.0, "value_abs_max": 2.5,
                 "depth_hist": [2.0, 0.0, 4.0]},
            ]
        )
        assert merged["root_entropy"] == pytest.approx(2.0)
        assert merged["value_abs_max"] == pytest.approx(2.5)
        assert merged["depth_hist"] == [3.0, 1.0, 4.0]
        assert merge_search_folds([]) is None

    def test_rollout_chunk_stats(self):
        np = pytest.importorskip("numpy")

        endings = np.array([[0, 1, 0], [0, 0, 1]])  # (T, B)
        rewards = np.array([[0.1, -0.4, 0.0], [2.0, 0.0, 0.3]])
        leg = rollout_chunk_stats(endings, rewards)
        assert leg["terminations_per_step"] == [1, 1]
        assert leg["reward_min"] == pytest.approx(-0.4)
        assert leg["reward_max"] == pytest.approx(2.0)

    def test_record_and_summary_roundtrip(self):
        rec = device_stats_record(
            7,
            program="megastep/t4_k2",
            search={"root_entropy": 1.5, "occupancy": 0.4,
                    "value_abs_max": 0.9},
            learner={"grad_norm_max": 3.0},
            now=123.0,
        )
        assert rec["kind"] == "device_stats"
        assert rec["step"] == 7 and rec["program"] == "megastep/t4_k2"
        summary = summarize_device_stats([rec, rec])
        assert summary["ds_records"] == 2
        assert summary["ds_root_entropy"] == pytest.approx(1.5)
        assert summary["ds_tree_occupancy"] == pytest.approx(0.4)
        assert summary["ds_grad_norm_max"] == pytest.approx(3.0)
        assert summary["ds_reuse_frac"] is None  # leg absent, not invented

    def test_record_all_empty_is_none(self):
        assert device_stats_record(3) is None
        assert device_stats_record(3, search=None, per={}) is None

    def test_device_stats_json_carries_last_record(self):
        rec = device_stats_record(9, search={"root_entropy": 0.8}, now=5.0)
        block = device_stats_json([rec])
        assert block["ds_records"] == 1
        assert block["last_record"]["step"] == 9
        block["last_record"]["step"] = 0  # deep copy: caller may mutate
        assert rec["step"] == 9


class TestRunTelemetryWiring:
    def test_record_device_stats_ledgers_and_detects(self, tmp_path, caplog):
        from alphatriangle_tpu.telemetry import RunTelemetry, TelemetryConfig
        from alphatriangle_tpu.telemetry.ledger import read_ledger

        tel = RunTelemetry(
            TelemetryConfig(WATCHDOG_ENABLED=False), run_dir=tmp_path
        )
        with caplog.at_level("WARNING", logger="alphatriangle_tpu.telemetry"):
            rec = tel.record_device_stats(
                4,
                program="megastep/t4_k2",
                search={"root_entropy": 0.0, "occupancy": 1.0,
                        "value_abs_max": 0.5},
            )
        assert rec is not None
        rows = read_ledger(tmp_path / "metrics.jsonl", kinds={"device_stats"})
        assert len(rows) == 1 and rows[0]["step"] == 4
        # entropy collapse + occupancy saturation escalated as anomalies
        text = caplog.text
        assert "collapse" in text and "saturation" in text
        tel.close()

    def test_disabled_record_is_none(self, tmp_path):
        from alphatriangle_tpu.telemetry import RunTelemetry, TelemetryConfig

        tel = RunTelemetry(
            TelemetryConfig(ENABLED=False), run_dir=tmp_path
        )
        assert tel.record_device_stats(1, search={"root_entropy": 1.0}) is None
        assert not (tmp_path / "metrics.jsonl").exists()

    def test_ctor_attaches_beacon_run_dir(self, tmp_path):
        from alphatriangle_tpu.telemetry import RunTelemetry, TelemetryConfig

        tel = RunTelemetry(
            TelemetryConfig(WATCHDOG_ENABLED=False), run_dir=tmp_path
        )
        arm_beacons()
        emit_beacon("search_wave", 0)
        assert last_beacon(tmp_path)["phase"] == "search_wave"
        tel.close()


class TestWatchdogWarning:
    def _pair(self, tmp_path, **kw):
        from alphatriangle_tpu.telemetry.flight import (
            FLIGHT_FILENAME,
            DispatchWatchdog,
            FlightRecorder,
        )

        clock = {"t": 0.0}
        wd = DispatchWatchdog(
            tmp_path, exit_on_wedge=False, clock=lambda: clock["t"], **kw
        )
        rec = FlightRecorder(
            tmp_path / FLIGHT_FILENAME, watchdog=wd,
            min_deadline_s=5.0, first_deadline_s=10.0,
        )
        return clock, wd, rec

    def test_warn_fires_once_before_wedge(self, tmp_path):
        warned = []
        clock, wd, rec = self._pair(
            tmp_path, warn_fraction=0.5, on_warn=warned.append
        )
        rec.begin("learner", "learner_step")
        clock["t"] += 4.0  # 40% of the 10s first deadline: quiet
        assert wd.check() is None
        assert not warned
        clock["t"] += 2.0  # 60%: past the warn fraction, under deadline
        assert wd.check() is None
        assert len(warned) == 1 and warned[0]["program"] == "learner_step"
        clock["t"] += 1.0
        assert wd.check() is None  # warn latched per dispatch
        assert len(warned) == 1
        assert wd.warn_count == 1
        clock["t"] += 5.0  # past the deadline: the wedge still fires
        assert wd.check() is not None

    def test_no_warn_without_fraction(self, tmp_path):
        clock, wd, rec = self._pair(tmp_path)
        rec.begin("learner", "learner_step")
        clock["t"] += 9.0
        assert wd.check() is None
        assert wd.warn_count == 0

    def test_warn_hook_error_never_raises(self, tmp_path):
        def boom(info):
            raise RuntimeError("hook exploded")

        clock, wd, rec = self._pair(
            tmp_path, warn_fraction=0.5, on_warn=boom
        )
        rec.begin("learner", "learner_step")
        clock["t"] += 6.0
        assert wd.check() is None
        assert wd.warn_count == 1

    def test_telemetry_warn_arms_beacons(self, tmp_path):
        from alphatriangle_tpu.telemetry import RunTelemetry, TelemetryConfig

        tel = RunTelemetry(
            TelemetryConfig(
                WATCHDOG_ENABLED=False, BEACON_EVERY_N_WAVES=2
            ),
            run_dir=tmp_path,
        )
        assert beacons_armed() is False
        tel._on_dispatch_warn(
            {"program": "megastep/t4_k2", "elapsed_s": 3.0,
             "deadline_s": 5.0, "family": "megastep", "seq": 1}
        )
        assert beacons_armed() is True
        assert beacon_every() == 2
        tel.close()


class TestAnomalySearchHealth:
    def test_collapse_and_saturation_latch_once(self):
        from alphatriangle_tpu.telemetry.anomaly import AnomalyDetector

        det = AnomalyDetector()
        first = det.observe_search(
            {"root_entropy": 0.0, "occupancy": 1.0, "value_abs_max": 1.0}, 5
        )
        assert {a.kind for a in first} == {"collapse", "saturation"}
        again = det.observe_search(
            {"root_entropy": 0.0, "occupancy": 1.0}, 6
        )
        assert again == []

    def test_healthy_leg_is_quiet(self):
        from alphatriangle_tpu.telemetry.anomaly import AnomalyDetector

        det = AnomalyDetector()
        for step in range(12):
            assert (
                det.observe_search(
                    {"root_entropy": 1.4, "occupancy": 0.3,
                     "value_abs_max": 0.9},
                    step,
                )
                == []
            )

    def test_value_explosion_screened(self):
        from alphatriangle_tpu.telemetry.anomaly import AnomalyDetector

        det = AnomalyDetector(warmup=4, z_threshold=4.0)
        for step in range(30):
            det.observe_search(
                {"value_abs_max": 1.0 + 0.01 * (step % 3)}, step
            )
        hits = det.observe_search({"value_abs_max": 500.0}, 30)
        assert any(a.kind == "spike" for a in hits)


class TestSupervisorDirective:
    def test_policy_arms_beacons_on_wedge(self):
        from alphatriangle_tpu.supervise import RecoveryPolicy

        policy = RecoveryPolicy(backoff_base_s=0.1)
        action = policy.decide(
            verdict="dispatch-hung", exit_code=113, family="megastep"
        )
        assert action.kind == "restart"
        assert action.overrides.get("TELEMETRY__BEACONS") is True
        assert "beacons" in action.reason
        # Second wedge keeps the override without re-announcing it.
        again = policy.decide(
            verdict="dispatch-hung", exit_code=113, family="megastep",
            progress_step=4,
        )
        assert again.overrides.get("TELEMETRY__BEACONS") is True
        assert "arming progress beacons" not in again.reason

    def test_clean_crash_does_not_arm(self):
        from alphatriangle_tpu.supervise import RecoveryPolicy

        policy = RecoveryPolicy(backoff_base_s=0.1)
        action = policy.decide(verdict="crashed", exit_code=1)
        assert "TELEMETRY__BEACONS" not in (action.overrides or {})

    def test_runner_pops_directive_and_arms(self, monkeypatch):
        from alphatriangle_tpu.config import TrainConfig
        from alphatriangle_tpu.training.runner import (
            SUPERVISE_OVERRIDES_ENV,
            _apply_supervise_overrides,
        )

        tc = TrainConfig(RUN_NAME="directive_probe")
        monkeypatch.setenv(
            SUPERVISE_OVERRIDES_ENV,
            json.dumps({"TELEMETRY__BEACONS": True}),
        )
        out = _apply_supervise_overrides(tc)
        # The reserved key is NOT a TrainConfig field: it must be popped
        # (no validation error) and the config returned unchanged.
        assert out.RUN_NAME == "directive_probe"
        assert beacons_armed() is True

    def test_runner_mixes_directive_with_real_overrides(self, monkeypatch):
        from alphatriangle_tpu.config import TrainConfig
        from alphatriangle_tpu.training.runner import (
            SUPERVISE_OVERRIDES_ENV,
            _apply_supervise_overrides,
        )

        tc = TrainConfig(RUN_NAME="directive_mix", FUSED_LEARNER_STEPS=4)
        monkeypatch.setenv(
            SUPERVISE_OVERRIDES_ENV,
            json.dumps(
                {"TELEMETRY__BEACONS": True, "FUSED_LEARNER_STEPS": 1}
            ),
        )
        out = _apply_supervise_overrides(tc)
        assert out.FUSED_LEARNER_STEPS == 1
        assert beacons_armed() is True
