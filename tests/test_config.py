"""Config-model validation tests (reference analog: config validators)."""

import pytest
from pydantic import ValidationError

from alphatriangle_tpu.config import (
    AlphaTriangleMCTSConfig,
    EnvConfig,
    MeshConfig,
    ModelConfig,
    PersistenceConfig,
    TrainConfig,
    expected_other_features_dim,
    print_config_info_and_validate,
)


def test_default_configs_validate_together():
    cfgs = print_config_info_and_validate()
    assert cfgs["env"].action_dim == 3 * 8 * 15
    assert cfgs["model"].OTHER_NN_INPUT_FEATURES_DIM == 30


def test_expected_other_features_dim_matches_reference_layout():
    # 3 slots: 3*7 shape + 3 availability + 6 scalars = 30
    assert expected_other_features_dim(EnvConfig()) == 30


def test_env_config_rejects_bad_playable_range():
    with pytest.raises(ValidationError):
        EnvConfig(ROWS=2, COLS=3, PLAYABLE_RANGE_PER_ROW=[(0, 3)])
    with pytest.raises(ValidationError):
        EnvConfig(ROWS=1, COLS=3, PLAYABLE_RANGE_PER_ROW=[(2, 2)])
    with pytest.raises(ValidationError):
        EnvConfig(ROWS=1, COLS=3, PLAYABLE_RANGE_PER_ROW=[(0, 9)])


def test_model_config_conv_consistency():
    with pytest.raises(ValidationError):
        ModelConfig(CONV_FILTERS=[8, 16], CONV_KERNEL_SIZES=[3], CONV_STRIDES=[1, 1])


def test_model_config_transformer_divisibility():
    with pytest.raises(ValidationError):
        ModelConfig(TRANSFORMER_DIM=10, TRANSFORMER_HEADS=4, TRANSFORMER_LAYERS=1)


def test_model_config_value_support():
    with pytest.raises(ValidationError):
        ModelConfig(VALUE_MIN=1.0, VALUE_MAX=-1.0)


def test_train_config_derives_schedules():
    cfg = TrainConfig(MAX_TRAINING_STEPS=1234)
    assert cfg.LR_SCHEDULER_T_MAX == 1234
    assert cfg.PER_BETA_ANNEAL_STEPS == 1234


def test_train_config_buffer_invariants():
    with pytest.raises(ValidationError):
        TrainConfig(MIN_BUFFER_SIZE_TO_TRAIN=100, BUFFER_CAPACITY=10)
    with pytest.raises(ValidationError):
        TrainConfig(BATCH_SIZE=1000, BUFFER_CAPACITY=100, MIN_BUFFER_SIZE_TO_TRAIN=50)


def test_train_config_beta_ordering():
    with pytest.raises(ValidationError):
        TrainConfig(PER_BETA_INITIAL=0.9, PER_BETA_FINAL=0.5)


def test_mcts_config_defaults_match_reference():
    cfg = AlphaTriangleMCTSConfig()
    assert cfg.max_simulations == 64
    assert cfg.max_depth == 8
    assert cfg.cpuct == 1.5
    assert cfg.mcts_batch_size == 32


def test_mesh_config_builds_8_device_cpu_mesh():
    import jax

    mesh = MeshConfig(DP_SIZE=-1, MDL_SIZE=2).build_mesh(jax.devices("cpu"))
    assert mesh.shape == {"dp": 4, "mdl": 2, "sp": 1}

    sp_mesh = MeshConfig(DP_SIZE=2, MDL_SIZE=2, SP_SIZE=2).build_mesh(
        jax.devices("cpu")
    )
    assert sp_mesh.shape == {"dp": 2, "mdl": 2, "sp": 2}


def test_baseline_presets_valid():
    """All five BASELINE presets produce mutually-consistent configs
    (feature dims match the env, transformer dims divide heads, etc.)."""
    from alphatriangle_tpu.config import baseline_preset
    from alphatriangle_tpu.config.validation import (
        expected_other_features_dim,
    )

    for n in range(1, 6):
        b = baseline_preset(n)
        assert b["model"].OTHER_NN_INPUT_FEATURES_DIM == (
            expected_other_features_dim(b["env"])
        )
        assert b["train"].SELF_PLAY_BATCH_SIZE >= 16
    assert baseline_preset(1)["model"].USE_TRANSFORMER is False
    assert baseline_preset(3)["model"].TRANSFORMER_LAYERS == 4
    # The flagship preset carries the measured-best search recipe
    # (Gumbel + playout cap randomization); the others stay PUCT so
    # the BASELINE table remains comparable config-for-config.
    p3_mcts = baseline_preset(3)["mcts"]
    assert p3_mcts.root_selection == "gumbel"
    assert p3_mcts.fast_simulations == 16
    assert p3_mcts.full_search_prob == 0.25
    assert baseline_preset(2)["mcts"].root_selection == "puct"
    assert baseline_preset(4)["mcts"].max_simulations == 400
    p5 = baseline_preset(5)
    assert p5["env"].ROWS == 12 and p5["model"].TRANSFORMER_LAYERS == 8
    with pytest.raises(ValueError):
        baseline_preset(6)


def test_preset_overrides_revalidate_and_rederive():
    """CLI overrides on a preset must go through the constructor:
    schedule lengths re-derive from a new horizon and invalid combos
    raise instead of being silently accepted."""
    from alphatriangle_tpu.cli import merge_train_overrides
    from alphatriangle_tpu.config import baseline_preset

    base = baseline_preset(3)["train"]
    merged = merge_train_overrides(base, {"MAX_TRAINING_STEPS": 5000})
    assert merged.LR_SCHEDULER_T_MAX == 5000
    assert merged.PER_BETA_ANNEAL_STEPS == 5000
    with pytest.raises(ValueError):
        merge_train_overrides(base, {"BUFFER_CAPACITY": 100})
    assert baseline_preset(1)["train"].DEVICE == "cpu"


def test_mesh_config_rejects_indivisible():
    import jax

    with pytest.raises(ValueError):
        MeshConfig(MDL_SIZE=3).build_mesh(jax.devices("cpu"))


def test_persistence_config_layout(tmp_path):
    p = PersistenceConfig(ROOT_DATA_DIR=str(tmp_path), RUN_NAME="r1")
    p.create_run_dirs()
    assert (tmp_path / "AlphaTriangleTPU" / "runs" / "r1" / "checkpoints").is_dir()
    assert (tmp_path / "AlphaTriangleTPU" / "runs" / "r1" / "tensorboard").is_dir()


def test_validation_catches_feature_dim_mismatch():
    env = EnvConfig()
    model = ModelConfig(OTHER_NN_INPUT_FEATURES_DIM=13)
    with pytest.raises(ValueError, match="OTHER_NN_INPUT_FEATURES_DIM"):
        print_config_info_and_validate(env=env, model=model)
