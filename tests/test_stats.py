"""Stats collector + Orbax persistence tests (trieye-equivalent surface;
VERDICT.md #10 'Done =' bar: kill a run mid-training, rerun, resume)."""

import numpy as np
import pytest

from alphatriangle_tpu.config import PersistenceConfig, TrainConfig
from alphatriangle_tpu.nn.network import NeuralNetwork
from alphatriangle_tpu.rl import ExperienceBuffer, Trainer
from alphatriangle_tpu.stats import (
    CheckpointManager,
    RawMetricEvent,
    StatsCollector,
)


class TestCollector:
    def test_aggregates_means_per_tick(self, tmp_path):
        col = StatsCollector(log_dir=tmp_path / "tb")
        col.log_scalar("Loss/Total", 4.0, step=1)
        col.log_scalar("Loss/Total", 2.0, step=1)
        col.log_event(RawMetricEvent(name="score", value=7.0, global_step=1))
        means = col.process_and_log(1)
        assert means["Loss/Total"] == pytest.approx(3.0)
        assert means["score"] == pytest.approx(7.0)
        # Window cleared after the tick.
        assert col.process_and_log(2) == {}
        assert col.get_series("Loss/Total") == [(1, 3.0)]
        assert col.latest("score") == 7.0
        col.close()

    def test_nonfinite_dropped_counted_not_silent(self, tmp_path, caplog):
        import logging

        col = StatsCollector(log_dir=tmp_path / "tb")
        with caplog.at_level(logging.WARNING):
            col.log_scalar("x", float("nan"))
            col.log_scalar("x", float("inf"))
            col.log_scalar("y", float("nan"))
        # Dropped from aggregation, but surfaced: cumulative count as a
        # scalar on each tick, per-name counts introspectable, and one
        # warning per metric name (not one per value, not silence).
        means = col.process_and_log(0)
        assert means == {"Stats/nonfinite_dropped": 3.0}
        assert col.nonfinite_dropped() == {"x": 2, "y": 1}
        warnings = [
            r for r in caplog.records if "Non-finite" in r.getMessage()
        ]
        assert len(warnings) == 2  # once for x, once for y
        # Counter is cumulative and keeps appearing on later ticks.
        col.log_scalar("z", 1.0, step=1)
        means = col.process_and_log(1)
        assert means["Stats/nonfinite_dropped"] == 3.0
        assert means["z"] == 1.0
        col.close()

    def test_no_drops_no_counter_metric(self, tmp_path):
        col = StatsCollector(log_dir=tmp_path / "tb")
        col.log_scalar("x", 1.0)
        assert "Stats/nonfinite_dropped" not in col.process_and_log(0)
        col.close()

    def test_close_flushes_pending_events(self, tmp_path):
        """Trailing sub-interval metrics must not be silently lost at
        shutdown: close() runs a final process_and_log at the newest
        step seen, and the tick sink receives it."""
        col = StatsCollector(log_dir=tmp_path / "tb")
        sink_calls = []
        col.set_tick_sink(lambda step, means: sink_calls.append((step, means)))
        col.log_scalar("m", 1.0, step=3)
        col.log_scalar("late", 9.0, step=7)  # never ticked
        col.close()
        assert col.latest("late") == 9.0
        assert col.get_series("late") == [(7, 9.0)]
        assert sink_calls and sink_calls[-1][0] == 7
        assert sink_calls[-1][1]["late"] == 9.0
        # Idempotent: a second close neither flushes nor raises.
        n = len(sink_calls)
        col.close()
        assert len(sink_calls) == n

    def test_tick_sink_receives_every_tick_and_never_raises(self, tmp_path):
        col = StatsCollector(log_dir=tmp_path / "tb")

        def bad_sink(step, means):
            raise RuntimeError("sink down")

        col.set_tick_sink(bad_sink)
        col.log_scalar("m", 1.0, step=1)
        # A failing sink must not break the tick.
        assert col.process_and_log(1)["m"] == 1.0
        col.close()

    def test_atexit_registration_cleared_on_close(self, tmp_path):
        import atexit

        col = StatsCollector(log_dir=tmp_path / "tb")
        col.close()
        # Unregistered: atexit must not re-run close on a closed
        # collector at interpreter exit (would resurrect the writer).
        atexit.unregister(col._atexit_cb)  # no-op if already done

    def test_tensorboard_files_written(self, tmp_path):
        col = StatsCollector(log_dir=tmp_path / "tb")
        col.log_scalar("m", 1.0, 0)
        col.process_and_log(0)
        col.close()
        assert list((tmp_path / "tb").glob("events.out.tfevents.*"))

    def test_history_bounded(self, tmp_path):
        col = StatsCollector(log_dir=tmp_path / "tb", history_limit=4)
        for step in range(10):
            col.log_scalar("m", float(step), step)
            col.process_and_log(step)
        series = col.get_series("m")
        assert len(series) == 4
        assert series == [(6, 6.0), (7, 7.0), (8, 8.0), (9, 9.0)]
        col.close()

    def test_log_params_writes_text(self, tmp_path, tiny_env_config):
        col = StatsCollector(log_dir=tmp_path / "tb")
        col.log_params({"env": tiny_env_config, "plain": {"k": 1}})
        col.close()
        files = list((tmp_path / "tb").glob("events.out.tfevents.*"))
        assert files and files[0].stat().st_size > 0

    def test_mlflow_mirroring_when_available(self, tmp_path, monkeypatch):
        """With a tracking URI configured and mlflow importable, metrics
        and params mirror to it (absent mlflow degrades to TB-only)."""
        import sys
        import types

        calls = {"metrics": [], "params": [], "runs": 0, "ended": 0}
        fake = types.ModuleType("mlflow")
        fake.set_tracking_uri = lambda uri: calls.setdefault("uri", uri)
        fake.start_run = lambda run_name=None: (
            calls.__setitem__("runs", calls["runs"] + 1) or object()
        )
        fake.log_metrics = lambda m, step=None: calls["metrics"].append(
            (m, step)
        )
        fake.log_params = lambda p: calls["params"].append(p)
        fake.end_run = lambda: calls.__setitem__(
            "ended", calls["ended"] + 1
        )
        monkeypatch.setitem(sys.modules, "mlflow", fake)

        cfg = PersistenceConfig(
            ROOT_DATA_DIR=str(tmp_path),
            RUN_NAME="ml_run",
            MLFLOW_TRACKING_URI="file:///tmp/mlruns",
        )
        col = StatsCollector(cfg)
        assert calls["runs"] == 1 and calls["uri"] == "file:///tmp/mlruns"
        col.log_scalar("Loss/Total", 2.0, step=3)
        col.process_and_log(3)
        assert calls["metrics"] == [({"Loss.Total": 2.0}, 3)]
        col.log_params({"train": {"BATCH_SIZE": 8}})
        assert calls["params"] == [{"train.BATCH_SIZE": "8"}]
        col.close()
        assert calls["ended"] == 1


def per_cfg(tmp_path, run="run_a") -> PersistenceConfig:
    return PersistenceConfig(ROOT_DATA_DIR=str(tmp_path), RUN_NAME=run)


class TestCheckpointManager:
    def test_train_state_roundtrip(
        self, tmp_path, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        trainer = Trainer(net, tiny_train_config)
        from tests.test_trainer import make_batch

        trainer.train_step(make_batch())
        mgr = CheckpointManager(per_cfg(tmp_path))
        counters = {"episodes_played": 5, "total_simulations": 99}
        mgr.save(1, trainer.state, counters=counters)
        mgr.wait_until_finished()

        # Fresh process-equivalent: new net/trainer, restore by template.
        net2 = NeuralNetwork(tiny_model_config, tiny_env_config, seed=123)
        trainer2 = Trainer(net2, tiny_train_config)
        loaded = mgr.restore(trainer2.state)
        assert loaded.global_step == 1
        assert loaded.counters["episodes_played"] == 5
        trainer2.set_state(loaded.train_state)
        import jax

        for a, b in zip(
            jax.tree_util.tree_leaves(trainer.state.params),
            jax.tree_util.tree_leaves(trainer2.state.params),
            strict=True,
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(trainer2.state.step) == 1

    def test_checkpoint_retention_prunes_oldest(
        self, tmp_path, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        trainer = Trainer(net, tiny_train_config)
        cfg = per_cfg(tmp_path).model_copy(
            update={"KEEP_LAST_CHECKPOINTS": 2, "KEEP_LAST_BUFFERS": 1}
        )
        mgr = CheckpointManager(cfg)
        for step in (1, 2, 3, 4):
            mgr.save(step, trainer.state)
        mgr.wait_until_finished()
        kept = sorted(
            p.name
            for p in cfg.get_checkpoint_dir().iterdir()
            if p.is_dir()
        )
        assert kept == ["step_00000003", "step_00000004"]
        # Meta files pruned alongside their checkpoint dirs.
        metas = sorted(
            p.name for p in cfg.get_checkpoint_dir().glob("*.meta.json")
        )
        assert metas == ["step_00000003.meta.json", "step_00000004.meta.json"]
        # Restore still lands on the newest survivor.
        assert mgr.latest_step() == 4

        tc = TrainConfig(
            BATCH_SIZE=4, BUFFER_CAPACITY=64, MIN_BUFFER_SIZE_TO_TRAIN=8,
            MAX_TRAINING_STEPS=10, RUN_NAME="t",
        )
        from tests.test_buffer import make_dense

        buf = ExperienceBuffer(tc)
        buf.add_dense(*make_dense(4))
        for step in (1, 2, 3):
            mgr.save_buffer(step, buf)
        spills = sorted(p.name for p in cfg.get_buffer_dir().iterdir())
        assert spills == ["buffer_00000003.npz"]

    def test_retention_zero_keeps_everything(
        self, tmp_path, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        trainer = Trainer(net, tiny_train_config)
        cfg = per_cfg(tmp_path).model_copy(
            update={"KEEP_LAST_CHECKPOINTS": 0}
        )
        mgr = CheckpointManager(cfg)
        for step in (1, 2, 3):
            mgr.save(step, trainer.state)
        mgr.wait_until_finished()
        dirs = [p for p in cfg.get_checkpoint_dir().iterdir() if p.is_dir()]
        assert len(dirs) == 3

    def test_restore_empty_run(self, tmp_path, tiny_model_config, tiny_env_config, tiny_train_config):
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        trainer = Trainer(net, tiny_train_config)
        mgr = CheckpointManager(per_cfg(tmp_path))
        loaded = mgr.restore(trainer.state)
        assert loaded.train_state is None
        assert loaded.global_step == 0

    def test_buffer_spill_roundtrip(self, tmp_path):
        tc = TrainConfig(
            BATCH_SIZE=4, BUFFER_CAPACITY=64, MIN_BUFFER_SIZE_TO_TRAIN=8,
            USE_PER=True, PER_BETA_ANNEAL_STEPS=10, MAX_TRAINING_STEPS=10,
            RUN_NAME="t",
        )
        from tests.test_buffer import make_dense

        buf = ExperienceBuffer(tc)
        buf.add_dense(*make_dense(20, value=2.5))
        buf.update_priorities(np.arange(20), np.linspace(0.5, 3.0, 20))
        mgr = CheckpointManager(per_cfg(tmp_path))
        mgr.save_buffer(7, buf)

        buf2 = ExperienceBuffer(tc)
        assert mgr.restore_buffer(buf2)
        assert len(buf2) == 20
        np.testing.assert_array_equal(
            buf2._storage["value_target"][:20],
            buf._storage["value_target"][:20],
        )
        np.testing.assert_allclose(
            buf2.tree.tree[buf2.tree._cap2 : buf2.tree._cap2 + 20],
            buf.tree.tree[buf.tree._cap2 : buf.tree._cap2 + 20],
        )

    def test_restore_explicit_path(
        self, tmp_path, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        trainer = Trainer(net, tiny_train_config)
        mgr = CheckpointManager(per_cfg(tmp_path))
        mgr.save(5, trainer.state, counters={"episodes_played": 2})
        mgr.wait_until_finished()
        path = per_cfg(tmp_path).get_checkpoint_dir() / "step_00000005"

        net2 = NeuralNetwork(tiny_model_config, tiny_env_config, seed=9)
        trainer2 = Trainer(net2, tiny_train_config)
        mgr2 = CheckpointManager(per_cfg(tmp_path, "other_run"))
        loaded = mgr2.restore_path(path, trainer2.state)
        assert loaded.global_step == 5
        assert loaded.counters["episodes_played"] == 2
        with pytest.raises(FileNotFoundError):
            mgr2.restore_path(tmp_path / "nope", trainer2.state)

    def test_restore_buffer_explicit_path(self, tmp_path):
        tc = TrainConfig(
            BATCH_SIZE=4, BUFFER_CAPACITY=64, MIN_BUFFER_SIZE_TO_TRAIN=8,
            USE_PER=False, MAX_TRAINING_STEPS=10, RUN_NAME="t",
        )
        from tests.test_buffer import make_dense

        buf = ExperienceBuffer(tc)
        buf.add_dense(*make_dense(10))
        mgr = CheckpointManager(per_cfg(tmp_path))
        spill = mgr.save_buffer(3, buf)
        buf2 = ExperienceBuffer(tc)
        assert CheckpointManager.restore_buffer_path(buf2, spill)
        assert len(buf2) == 10
        with pytest.raises(FileNotFoundError):
            CheckpointManager.restore_buffer_path(buf2, tmp_path / "nope.npz")

    def test_latest_step_and_multiple_saves(
        self, tmp_path, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        trainer = Trainer(net, tiny_train_config)
        mgr = CheckpointManager(per_cfg(tmp_path))
        mgr.save(3, trainer.state)
        mgr.save(12, trainer.state)
        mgr.wait_until_finished()
        assert mgr.latest_step() == 12

    def test_find_latest_run(
        self, tmp_path, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        trainer = Trainer(net, tiny_train_config)
        mgr_a = CheckpointManager(per_cfg(tmp_path, "run_a"))
        mgr_a.save(1, trainer.state)
        mgr_a.wait_until_finished()
        import time

        time.sleep(0.05)
        mgr_b = CheckpointManager(per_cfg(tmp_path, "run_b"))
        mgr_b.save(2, trainer.state)
        mgr_b.wait_until_finished()
        # run_c has dirs but no checkpoints -> ignored.
        CheckpointManager(per_cfg(tmp_path, "run_c"))
        assert CheckpointManager.find_latest_run(per_cfg(tmp_path)) == "run_b"

    def test_save_configs(self, tmp_path, tiny_env_config):
        mgr = CheckpointManager(per_cfg(tmp_path))
        mgr.save_configs({"env": tiny_env_config, "note": "x"})
        import json

        data = json.loads(
            (per_cfg(tmp_path).get_run_base_dir() / "configs.json").read_text()
        )
        assert data["env"]["ROWS"] == 3
        assert data["note"] == "x"


class TestCheckpointIntegrity:
    """Crash-integrity contract (docs/ROBUSTNESS.md): commit markers
    certify fully-landed Orbax trees; restore never trusts a torn one."""

    def _trainer(self, tiny_model_config, tiny_env_config, tiny_train_config):
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        return Trainer(net, tiny_train_config)

    def test_commit_marker_lands_without_explicit_wait(
        self, tmp_path, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        """The background flusher commits a save as soon as the async
        write finishes — `cli supervise` reads the markers at death
        time, so they must not wait for the NEXT save to settle them."""
        import time

        trainer = self._trainer(
            tiny_model_config, tiny_env_config, tiny_train_config
        )
        cfg = per_cfg(tmp_path)
        mgr = CheckpointManager(cfg)
        mgr.save(1, trainer.state)
        marker = cfg.get_checkpoint_dir() / "step_00000001.commit"
        deadline = time.monotonic() + 30.0
        while not marker.exists() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert marker.exists(), "commit marker never flushed in background"
        mgr.close()

    def test_restore_skips_step_without_commit_marker(
        self, tmp_path, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        """A SIGKILL mid-save leaves a step dir with no marker: restore
        must fall back to the previous committed step, not crash and
        not trust the torn tree."""
        import json

        trainer = self._trainer(
            tiny_model_config, tiny_env_config, tiny_train_config
        )
        cfg = per_cfg(tmp_path)
        mgr = CheckpointManager(cfg)
        mgr.save(1, trainer.state)
        mgr.save(2, trainer.state)
        mgr.wait_until_finished()
        # Forge the torn artifact: a half-written step-3 tree + meta,
        # killed before its commit marker.
        ckpts = cfg.get_checkpoint_dir()
        torn = ckpts / "step_00000003"
        torn.mkdir()
        (torn / "partial_array").write_bytes(b"\x00\x01garbage")
        (ckpts / "step_00000003.meta.json").write_text(
            json.dumps({"global_step": 3})
        )
        assert mgr.valid_steps() == [1, 2]
        assert mgr.latest_step() == 2
        loaded = mgr.restore(trainer.state)
        assert loaded.global_step == 2
        mgr.close()

    def test_restore_skips_unparseable_meta(
        self, tmp_path, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        trainer = self._trainer(
            tiny_model_config, tiny_env_config, tiny_train_config
        )
        cfg = per_cfg(tmp_path)
        mgr = CheckpointManager(cfg)
        mgr.save(1, trainer.state)
        mgr.save(2, trainer.state)
        mgr.wait_until_finished()
        (cfg.get_checkpoint_dir() / "step_00000002.meta.json").write_text(
            "{torn mid-write"
        )
        assert mgr.valid_steps() == [1]
        loaded = mgr.restore(trainer.state)
        assert loaded.global_step == 1
        mgr.close()

    def test_restore_falls_back_when_committed_tree_unreadable(
        self, tmp_path, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        """Belt and braces: even a MARKED step whose tree turns out
        unreadable (disk fault) costs one cadence, not the run. An
        explicitly requested step still raises."""
        import shutil

        trainer = self._trainer(
            tiny_model_config, tiny_env_config, tiny_train_config
        )
        cfg = per_cfg(tmp_path)
        mgr = CheckpointManager(cfg)
        mgr.save(1, trainer.state)
        mgr.save(2, trainer.state)
        mgr.wait_until_finished()
        step2 = cfg.get_checkpoint_dir() / "step_00000002"
        shutil.rmtree(step2)
        step2.mkdir()  # marker present, tree gutted
        loaded = mgr.restore(trainer.state)
        assert loaded.global_step == 1
        with pytest.raises(Exception):
            mgr.restore(trainer.state, step=2)
        mgr.close()

    def test_restore_buffer_falls_back_past_torn_spill(self, tmp_path):
        from tests.test_buffer import make_dense

        tc = TrainConfig(
            BATCH_SIZE=4, BUFFER_CAPACITY=64, MIN_BUFFER_SIZE_TO_TRAIN=8,
            USE_PER=False, MAX_TRAINING_STEPS=10, RUN_NAME="t",
        )
        buf = ExperienceBuffer(tc)
        buf.add_dense(*make_dense(10))
        cfg = per_cfg(tmp_path)
        mgr = CheckpointManager(cfg)
        mgr.save_buffer(3, buf)
        # A newer spill torn by a kill mid-write (pre-atomic artifact).
        (cfg.get_buffer_dir() / "buffer_00000009.npz").write_bytes(
            b"PK\x03\x04 torn"
        )
        buf2 = ExperienceBuffer(tc)
        assert mgr.restore_buffer(buf2)
        assert len(buf2) == 10

    def test_find_latest_run_ignores_torn_only_runs(
        self, tmp_path, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        import time

        trainer = self._trainer(
            tiny_model_config, tiny_env_config, tiny_train_config
        )
        mgr_a = CheckpointManager(per_cfg(tmp_path, "run_good"))
        mgr_a.save(1, trainer.state)
        mgr_a.close()
        time.sleep(0.05)
        # run_torn is NEWER but its only step dir has no commit marker
        # (its single marker names a step whose dir is gone).
        cfg_t = per_cfg(tmp_path, "run_torn")
        cfg_t.create_run_dirs()
        ckpts = cfg_t.get_checkpoint_dir()
        (ckpts / "step_00000002").mkdir()
        (ckpts / "step_00000001.commit").write_text('{"global_step": 1}')
        assert (
            CheckpointManager.find_latest_run(per_cfg(tmp_path)) == "run_good"
        )


class _MlflowStub:
    """In-memory mlflow facade: records every mirror call the collector
    makes, so the MLflow channel is pinned even on images where mlflow
    itself cannot be installed (reference treats MLflow as the primary
    tracker, its `training/logging_utils.py:13-35`)."""

    def __init__(self):
        self.tracking_uri = None
        self.run_name = None
        self.metrics: list[tuple[dict, int]] = []
        self.params: dict = {}
        self.ended = False

    def set_tracking_uri(self, uri):
        self.tracking_uri = uri

    def start_run(self, run_name=None):
        self.run_name = run_name
        return object()

    def log_metrics(self, metrics, step=None):
        self.metrics.append((dict(metrics), step))

    def log_params(self, params):
        self.params.update(params)

    def end_run(self):
        self.ended = True


class TestMlflowMirror:
    def _collector(self, tmp_path, monkeypatch, stub):
        import alphatriangle_tpu.stats.collector as collector_mod

        monkeypatch.setattr(
            collector_mod, "_import_mlflow", lambda: stub
        )
        pc = PersistenceConfig(
            ROOT_DATA_DIR=str(tmp_path),
            RUN_NAME="ml_run",
            MLFLOW_TRACKING_URI=f"file://{tmp_path}/mlruns",
        )
        return StatsCollector(pc, use_tensorboard=False)

    def test_metrics_and_params_mirrored(self, tmp_path, monkeypatch):
        stub = _MlflowStub()
        stats = self._collector(tmp_path, monkeypatch, stub)
        assert stub.tracking_uri == f"file://{tmp_path}/mlruns"
        assert stub.run_name == "ml_run"

        stats.log_scalar("Loss/total_loss", 1.5, step=3)
        stats.log_scalar("Loss/total_loss", 2.5, step=3)
        stats.process_and_log(3)
        # Mean of the tick, MLflow-legal metric name ('/' -> '.').
        assert stub.metrics == [({"Loss.total_loss": 2.0}, 3)]

        stats.log_params({"train": TrainConfig(RUN_NAME="ml_run")})
        assert stub.params["train.RUN_NAME"] == "ml_run"
        assert "train.BATCH_SIZE" in stub.params

        stats.close()
        assert stub.ended

    def test_mirror_failure_never_fatal(self, tmp_path, monkeypatch):
        stub = _MlflowStub()

        def boom(metrics, step=None):
            raise RuntimeError("tracking server down")

        stub.log_metrics = boom
        stats = self._collector(tmp_path, monkeypatch, stub)
        stats.log_scalar("Loss/x", 1.0, step=1)
        means = stats.process_and_log(1)  # must not raise
        assert means == {"Loss/x": 1.0}
        stats.close()

    @pytest.mark.skipif(
        __import__("importlib").util.find_spec("mlflow") is None,
        reason="mlflow not installed in this image",
    )
    def test_real_mlflow_file_store(self, tmp_path):
        """End-to-end against a real file-backed mlflow store (runs
        automatically wherever mlflow is importable, e.g. CI with the
        dev extra installed)."""
        pc = PersistenceConfig(
            ROOT_DATA_DIR=str(tmp_path),
            RUN_NAME="ml_real",
            MLFLOW_TRACKING_URI=f"file://{tmp_path}/mlruns",
        )
        stats = StatsCollector(pc, use_tensorboard=False)
        stats.log_scalar("Loss/total_loss", 1.0, step=1)
        stats.process_and_log(1)
        stats.log_params({"train": TrainConfig(RUN_NAME="ml_real")})
        stats.close()
        assert (tmp_path / "mlruns").exists()
