"""Profiling subsystem tests (reference `worker.py:549-566` +
`analyze_profiles.py:41-78` equivalents)."""

import json
import time

import pytest

from alphatriangle_tpu.profiling import PhaseTimers, ProfileSession


class TestPhaseTimers:
    def test_accumulates_and_reports(self):
        t = PhaseTimers()
        for _ in range(3):
            with t.phase("work"):
                time.sleep(0.002)
        with t.phase("other"):
            pass
        m = t.metrics()
        assert m["Profile/work_ms"] >= 2.0
        s = t.summary()
        assert s["work"]["count"] == 3
        assert s["other"]["count"] == 1

    def test_dump(self, tmp_path):
        t = PhaseTimers()
        with t.phase("x"):
            pass
        t.dump(tmp_path / "sub" / "phase_timers.json")
        data = json.loads((tmp_path / "sub" / "phase_timers.json").read_text())
        assert data["x"]["count"] == 1

    def test_exception_safe(self):
        t = PhaseTimers()
        try:
            with t.phase("boom"):
                raise RuntimeError
        except RuntimeError:
            pass
        assert t.summary()["boom"]["count"] == 1


class TestProfileSession:
    def test_disabled_is_inert(self, tmp_path):
        s = ProfileSession(enabled=False, profile_dir=tmp_path / "p")
        s.on_iteration(0)
        s.on_iteration(1)
        with s.phase("rollout"):
            pass
        s.close()
        assert not (tmp_path / "p").exists()

    def test_trace_window_and_dump(self, tmp_path):
        import jax
        import jax.numpy as jnp

        s = ProfileSession(
            enabled=True,
            profile_dir=tmp_path / "p",
            trace_start=1,
            trace_stop=2,
        )
        for i in range(3):
            s.on_iteration(i)
            with s.phase("rollout"):
                jnp.square(jnp.arange(8.0)).block_until_ready()
        s.close()
        assert (tmp_path / "p" / "phase_timers.json").exists()
        # jax.profiler writes an xplane trace under plugins/profile/.
        traces = list((tmp_path / "p").glob("**/*.xplane.pb"))
        assert traces, "no device trace written"
        del jax

    def test_close_stops_open_trace(self, tmp_path):
        s = ProfileSession(
            enabled=True, profile_dir=tmp_path / "p", trace_start=0,
            trace_stop=99,
        )
        s.on_iteration(0)  # starts trace; stop never reached
        s.close()  # must stop it and dump timers
        assert (tmp_path / "p" / "phase_timers.json").exists()
        assert list((tmp_path / "p").glob("**/*.xplane.pb"))

    def test_close_dumps_timers_even_when_stop_trace_fails(
        self, tmp_path, monkeypatch
    ):
        import jax

        s = ProfileSession(enabled=True, profile_dir=tmp_path / "p")
        with s.phase("rollout"):
            pass
        s._tracing = True  # as if on_iteration had started a trace

        def boom():
            raise RuntimeError("profiler wedged")

        monkeypatch.setattr(jax.profiler, "stop_trace", boom)
        s.close()  # must not raise
        assert not s._tracing
        data = json.loads(
            (tmp_path / "p" / "phase_timers.json").read_text()
        )
        assert data["rollout"]["count"] == 1

    def test_invalid_trace_window_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="trace_stop"):
            ProfileSession(
                enabled=True, profile_dir=tmp_path / "p",
                trace_start=3, trace_stop=3,
            )

    def test_phase_records_spans_on_attached_tracer(self, tmp_path):
        from alphatriangle_tpu.telemetry import SpanTracer

        tracer = SpanTracer()
        s = ProfileSession(
            enabled=False, profile_dir=tmp_path / "p", tracer=tracer
        )
        with s.phase("rollout"):
            pass
        with s.phase("rollout"):
            pass
        # Both surfaces see the phase: whole-run mean AND per-occurrence
        # spans (disabled device profiling doesn't gate the tracer).
        assert s.timers.summary()["rollout"]["count"] == 2
        assert tracer.summary()["rollout"]["count"] == 2


class TestXplaneSummary:
    def test_summarize_real_trace(self, tmp_path, capsys):
        """The in-terminal top-ops table parses a real jax trace (the
        tensorboard profile plugin can't load this TF build, so the
        raw-XSpace path is the only analysis surface)."""
        pytest.importorskip("tensorflow.tsl.profiler.protobuf")
        import jax
        import jax.numpy as jnp

        from alphatriangle_tpu.profiling import summarize_xplane_trace

        jax.profiler.start_trace(str(tmp_path / "t"))
        jax.jit(lambda x: x @ x)(jnp.ones((64, 64))).block_until_ready()
        jax.profiler.stop_trace()
        traces = list((tmp_path / "t").glob("**/*.xplane.pb"))
        assert traces
        summarize_xplane_trace(traces[0], top=5)
        out = capsys.readouterr().out
        assert "plane" in out and "total ms" in out

    def test_unreadable_trace_degrades(self, tmp_path, capsys):
        from alphatriangle_tpu.profiling import summarize_xplane_trace

        bad = tmp_path / "x.xplane.pb"
        bad.write_bytes(b"\x01\x02not a proto")
        summarize_xplane_trace(bad, top=5)
        out = capsys.readouterr().out
        assert "unreadable trace" in out or "unavailable" in out
