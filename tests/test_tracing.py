"""Cross-process distributed tracing + fleet SLO engine
(alphatriangle_tpu/telemetry/tracectx.py, merge.py, slo.py;
docs/OBSERVABILITY.md "Distributed tracing & SLOs").

Covers the context seam (mint/child/traceparent/env round trips with
legacy id-less tolerance), router trace propagation through fake
replicas, flight-ring trace stamping, the Perfetto fleet merge under
DELIBERATE clock skew (two replicas with offset monotonic epochs must
still produce causally ordered flow arrows and zero negative-duration
spans), the `cli slo` exit-code contract, the fleet Prometheus
aggregation, and the fleet-parent doctor verdicts. JAX never loads on
any of these paths — every reader runs beside a dead fleet.
"""

import json
import os

from alphatriangle_tpu.serving.fleet import classify_fleet
from alphatriangle_tpu.serving.router import (
    REJECT_QUEUE_FULL,
    ReplicaRouter,
)
from alphatriangle_tpu.stats.watch import (
    FleetWatchState,
    fleet_line,
    tail_fleet,
)
from alphatriangle_tpu.telemetry import tracectx
from alphatriangle_tpu.telemetry.flight import FlightRecorder, flight_span
from alphatriangle_tpu.telemetry.merge import (
    FLOW_CAT,
    MERGED_TRACE_FILENAME,
    merge_fleet_trace,
)
from alphatriangle_tpu.telemetry.slo import (
    SLO_EXIT_CODES,
    evaluate_slos,
    slo_status_line,
    write_fleet_prometheus,
)
from alphatriangle_tpu.telemetry.tracectx import (
    TRACEPARENT_ENV,
    TraceContext,
)

# --- trace context -------------------------------------------------------


class TestTraceContext:
    def test_mint_and_child_share_the_trace(self):
        root = tracectx.mint()
        child = root.child()
        assert child.trace_id == root.trace_id
        assert child.span_id != root.span_id
        assert child.parent_id == root.span_id

    def test_traceparent_round_trip(self):
        ctx = tracectx.mint()
        parsed = TraceContext.from_traceparent(ctx.to_traceparent())
        assert parsed is not None
        assert parsed.trace_id == ctx.trace_id
        assert parsed.span_id == ctx.span_id

    def test_malformed_traceparent_is_none_not_a_crash(self):
        for junk in ("", "garbage", "00-zz-xx-01", "99-" + "a" * 32, None):
            assert TraceContext.from_traceparent(junk) is None

    def test_env_seam_round_trip(self):
        ctx = tracectx.mint()
        env = tracectx.child_env(ctx, environ={})
        assert TRACEPARENT_ENV in env
        back = tracectx.from_env(environ=env)
        assert back is not None
        assert back.trace_id == ctx.trace_id
        # child_env(None) POPS the var: a child spawned outside any
        # trace must not inherit a stale one.
        cleared = tracectx.child_env(None, environ=env)
        assert TRACEPARENT_ENV not in cleared

    def test_from_fields_tolerates_legacy_records(self):
        # Pre-tracing records carry no ids at all.
        assert TraceContext.from_fields({}) is None
        assert TraceContext.from_fields({"event": "shed"}) is None
        # trace_id without span_id (partial legacy) gets a fresh span.
        ctx = TraceContext.from_fields({"trace_id": "a" * 32})
        assert ctx is not None and ctx.span_id

    def test_fields_and_trace_fields_extraction(self):
        ctx = tracectx.mint().child()
        record = {"event": "retry", "replica": "r0", **ctx.fields()}
        extracted = tracectx.trace_fields(record)
        assert extracted["trace_id"] == ctx.trace_id
        assert extracted["parent_id"] == ctx.parent_id
        assert "event" not in extracted
        assert tracectx.trace_fields({"event": "retry"}) == {}


# --- router propagation --------------------------------------------------


class _Pending:
    def __init__(self, value):
        self.value = value
        self.error = None

    def done(self):
        return True

    def wait(self, timeout=None):
        return True

    def cancel(self):
        pass


class _Replica:
    routable = True
    queue_depth = 0
    bucket = 8

    def __init__(self, name):
        self.name = name
        self.submits = []

    def submit(self, payload):
        self.submits.append(payload)
        return _Pending({"ok": True})


class TestRouterTracing:
    def test_route_mints_and_propagates_a_context(self):
        events = []
        replica = _Replica("r0")
        router = ReplicaRouter(
            [replica], timeout_s=5.0, retries=0, on_event=events.append
        )
        result = router.route({"kind": "episode", "seed": 0})
        assert result.ok and result.trace_id
        # The replica-bound payload carries the request's trace fields.
        sent = replica.submits[0]
        assert sent["trace_id"] == result.trace_id
        assert sent["span_id"]

    def test_route_continues_a_caller_context(self):
        parent = tracectx.mint()
        replica = _Replica("r0")
        router = ReplicaRouter([replica], timeout_s=5.0, retries=0)
        result = router.route(
            {"kind": "episode", "seed": 0, **parent.fields()}
        )
        assert result.trace_id == parent.trace_id
        # but with a fresh per-request span under the caller's.
        assert replica.submits[0]["span_id"] != parent.span_id

    def test_queue_full_shed_carries_the_trace_id(self):
        events = []
        router = ReplicaRouter(
            [_Replica("r0")],
            timeout_s=5.0,
            retries=0,
            max_inflight=0,
            on_event=events.append,
        )
        result = router.route({"kind": "episode"})
        assert not result.ok and result.rejection == REJECT_QUEUE_FULL
        assert result.trace_id
        shed = [e for e in events if e["event"] == "shed"][0]
        assert shed["trace_id"] == result.trace_id
        assert isinstance(shed["inflight"], int)


# --- flight-ring stamping ------------------------------------------------


class TestFlightTracing:
    def test_trace_fields_land_on_intent_and_seal(self, tmp_path):
        rec = FlightRecorder(tmp_path / "flight.jsonl")
        ctx = tracectx.mint()
        with flight_span(rec, "serve", "serve/b8", trace=ctx.fields()):
            pass
        rec.close()
        records = [
            json.loads(line)
            for line in (tmp_path / "flight.jsonl").read_text().splitlines()
        ]
        intent = [r for r in records if r.get("phase") == "intent"][0]
        seal = [r for r in records if r.get("phase") == "seal"][0]
        assert intent["trace_id"] == ctx.trace_id
        assert seal["trace_id"] == ctx.trace_id

    def test_base_trace_is_the_env_seam_default(self, tmp_path):
        parent = tracectx.mint().fields()
        rec = FlightRecorder(tmp_path / "flight.jsonl", base_trace=parent)
        with flight_span(rec, "train", "train/step"):
            pass
        rec.close()
        intent = json.loads(
            (tmp_path / "flight.jsonl").read_text().splitlines()[0]
        )
        assert intent["trace_id"] == parent["trace_id"]


# --- merge under clock skew ----------------------------------------------

WALL = 1_700_000_000.0
PARENT_PID, R0_PID, R1_PID = 100, 200, 300
# Deliberately skewed monotonic epochs: replica r0 booted "recently"
# (small monotonic), r1 has a huge uptime — naive mono comparison
# across processes would be wildly acausal.
MONO_EPOCH = {PARENT_PID: 1_000.0, R0_PID: 50.0, R1_PID: 90_000.0}


def _mono(pid: int, wall_offset: float) -> float:
    return MONO_EPOCH[pid] + wall_offset


def _jl(path, records):
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


def _skewed_fleet_dir(tmp_path, trace_ids=("t" * 31 + "1", "t" * 31 + "2")):
    """Fleet-parent run dir with two fake replicas on offset monotonic
    clocks: request 0 served by r0 at wall+1..+2, request 1 by r1 at
    wall+3..+4, each route bracket opening slightly earlier."""
    t1, t2 = trace_ids
    run = tmp_path / "run"
    _jl(
        run / "flight.jsonl",
        [
            {
                "kind": "flight", "phase": "intent", "seq": 1,
                "program": "fleet/route", "family": "fleet",
                "pid": PARENT_PID, "trace_id": t1,
                "t_mono": _mono(PARENT_PID, 0.5), "time": WALL + 0.5,
            },
            {
                "kind": "flight", "phase": "seal", "seq": 1,
                "program": "fleet/route", "family": "fleet", "ok": True,
                "trace_id": t1, "wall_s": 1.7,
                "t_mono": _mono(PARENT_PID, 2.2), "time": WALL + 2.2,
            },
            {
                "kind": "flight", "phase": "intent", "seq": 2,
                "program": "fleet/route", "family": "fleet",
                "pid": PARENT_PID, "trace_id": t2,
                "t_mono": _mono(PARENT_PID, 2.5), "time": WALL + 2.5,
            },
            {
                "kind": "flight", "phase": "seal", "seq": 2,
                "program": "fleet/route", "family": "fleet", "ok": True,
                "trace_id": t2, "wall_s": 1.8,
                "t_mono": _mono(PARENT_PID, 4.3), "time": WALL + 4.3,
            },
        ],
    )
    _jl(
        run / "fleet.jsonl",
        [
            {
                "kind": "fleet", "event": "fleet-start",
                "time": WALL, "pid": PARENT_PID,
            },
            {
                "kind": "fleet", "event": "replica-ready",
                "replica": "r0", "replica_pid": R0_PID,
                "t_mono": _mono(R0_PID, 0.2),
                "replica_time": WALL + 0.2,
                "time": WALL + 0.2, "pid": PARENT_PID,
            },
            {
                "kind": "fleet", "event": "replica-ready",
                "replica": "r1", "replica_pid": R1_PID,
                "t_mono": _mono(R1_PID, 0.3),
                "replica_time": WALL + 0.3,
                "time": WALL + 0.3, "pid": PARENT_PID,
            },
            {
                "kind": "fleet", "event": "fleet-stop",
                "time": WALL + 5.0, "pid": PARENT_PID,
            },
        ],
    )
    _jl(
        run / "replica_r0" / "flight.jsonl",
        [
            {
                "kind": "flight", "phase": "intent", "seq": 1,
                "program": "serve/b8", "family": "serve",
                "pid": R0_PID, "trace_ids": [t1],
                "t_mono": _mono(R0_PID, 1.0), "time": WALL + 1.0,
            },
            {
                "kind": "flight", "phase": "seal", "seq": 1,
                "program": "serve/b8", "family": "serve", "ok": True,
                "wall_s": 1.0,
                "t_mono": _mono(R0_PID, 2.0), "time": WALL + 2.0,
            },
        ],
    )
    _jl(
        run / "replica_r1" / "flight.jsonl",
        [
            {
                "kind": "flight", "phase": "intent", "seq": 1,
                "program": "serve/b8", "family": "serve",
                "pid": R1_PID, "trace_ids": [t2],
                "t_mono": _mono(R1_PID, 3.0), "time": WALL + 3.0,
            },
            {
                "kind": "flight", "phase": "seal", "seq": 1,
                "program": "serve/b8", "family": "serve", "ok": True,
                "wall_s": 1.0,
                "t_mono": _mono(R1_PID, 4.0), "time": WALL + 4.0,
            },
        ],
    )
    return run, (t1, t2)


class TestMergeClockSkew:
    def test_skewed_clocks_yield_causal_flows_and_no_negative_spans(
        self, tmp_path
    ):
        run, (t1, t2) = _skewed_fleet_dir(tmp_path)
        result = merge_fleet_trace(run)
        assert sorted(result["flow_trace_ids"]) == sorted([t1, t2])
        payload = json.loads((run / MERGED_TRACE_FILENAME).read_text())
        events = payload["traceEvents"]
        # Calibration found all three processes.
        assert set(result["clock_offsets"]) == {
            str(PARENT_PID), str(R0_PID), str(R1_PID)
        }
        # No span anywhere has a negative duration, despite the skew.
        for e in events:
            if e.get("ph") == "X":
                assert e["dur"] >= 0, e
        # Every span sits on the SHARED wall timeline: the replica
        # serve span for t1 must start inside its route bracket even
        # though r0's raw monotonic clock is ~950s behind the parent's.
        route_1 = next(
            e for e in events
            if e.get("ph") == "X" and e.get("args", {}).get("trace_id") == t1
        )
        serve_1 = next(
            e for e in events
            if e.get("ph") == "X"
            and e.get("pid") == R0_PID
            and t1 in (e.get("args", {}).get("trace_ids") or [])
        )
        assert route_1["ts"] <= serve_1["ts"] <= route_1["ts"] + route_1["dur"]
        # Flow arrows: per trace_id, source then targets, ts
        # non-decreasing (the causal-order contract).
        for tid in (t1, t2):
            steps = [
                e for e in events
                if e.get("cat") == FLOW_CAT and e.get("id") == tid
            ]
            assert steps and steps[0]["ph"] == "s"
            ts = [s["ts"] for s in steps]
            assert ts == sorted(ts), steps
            assert steps[-1]["ph"] == "f"
        # Per-process lanes: one process_name per pid.
        names = {
            m["pid"]: m["args"]["name"]
            for m in events
            if m.get("name") == "process_name"
        }
        assert "fleet parent" in names[PARENT_PID]
        assert "replica_r0" in names[R0_PID]
        assert "replica_r1" in names[R1_PID]

    def test_legacy_idless_records_merge_without_arrows(self, tmp_path):
        run = tmp_path / "legacy"
        _jl(
            run / "fleet.jsonl",
            [{"kind": "fleet", "event": "fleet-start", "time": WALL}],
        )
        _jl(
            run / "flight.jsonl",
            [
                {
                    "kind": "flight", "phase": "intent", "seq": 1,
                    "program": "fleet/route", "family": "fleet",
                    "pid": PARENT_PID,
                    "t_mono": 1.0, "time": WALL + 1.0,
                },
                {
                    "kind": "flight", "phase": "seal", "seq": 1,
                    "program": "fleet/route", "family": "fleet",
                    "ok": True, "t_mono": 2.0, "time": WALL + 2.0,
                },
            ],
        )
        result = merge_fleet_trace(run)
        assert result["flows"] == 0
        assert result["route_spans"] == 0  # no trace ids to index
        payload = json.loads((run / MERGED_TRACE_FILENAME).read_text())
        assert any(
            e.get("ph") == "X" for e in payload["traceEvents"]
        )  # the span still draws

    def test_missing_fleet_ledger_raises(self, tmp_path):
        try:
            merge_fleet_trace(tmp_path)
        except FileNotFoundError:
            return
        raise AssertionError("expected FileNotFoundError")


# --- SLO engine ----------------------------------------------------------

def _slo_fixture(root, *, sheds=0, p95=20.0, bad_seals=0, now=WALL + 60):
    """Synthetic fleet run dir: ~100 requests over the last minute."""
    run = root / f"slo_{sheds}_{p95}_{bad_seals}"
    _jl(
        run / "metrics.jsonl",
        [
            {
                "kind": "util", "time": now - 50 + i * 10, "step": i,
                "window_s": 10.0, "serve_requests_per_sec": 100.0 / 60.0,
            }
            for i in range(6)
        ],
    )
    _jl(
        run / "fleet.jsonl",
        [{"kind": "fleet", "event": "fleet-start", "time": now - 55}]
        + [
            {
                "kind": "fleet", "event": "shed",
                "rejection": "queue-full", "time": now - 40 + (i % 30),
            }
            for i in range(sheds)
        ]
        + [{"kind": "fleet", "event": "fleet-stop", "time": now}],
    )
    _jl(
        run / "replica_r0" / "metrics.jsonl",
        [
            {
                "kind": "util", "time": now - 50 + i * 10, "step": i,
                "window_s": 10.0, "serve_move_latency_ms_p95": p95,
                "serve_window_requests": 16,
            }
            for i in range(6)
        ],
    )
    _jl(
        run / "replica_r0" / "flight.jsonl",
        [
            {
                "kind": "flight", "phase": "seal", "family": "serve",
                "program": "serve/b8", "seq": i, "ok": i >= bad_seals,
                "time": now - 45 + i * 4,
            }
            for i in range(10)
        ],
    )
    return run


class TestSLO:
    def test_healthy_window_is_ok_exit_0(self, tmp_path):
        report = evaluate_slos(_slo_fixture(tmp_path))
        assert report["status"] == "ok"
        assert report["exit_code"] == SLO_EXIT_CODES["ok"] == 0
        assert {s["name"] for s in report["slos"]} == {
            "availability", "move-latency-p95", "dispatch-success"
        }

    def test_brownout_burns_the_availability_budget_exit_1(self, tmp_path):
        report = evaluate_slos(_slo_fixture(tmp_path, sheds=50))
        assert report["status"] == "burning"
        assert report["exit_code"] == 1
        avail = next(
            s for s in report["slos"] if s["name"] == "availability"
        )
        assert avail["status"] == "burning"
        # err = 50/150, budget 1% -> burn x33, past both thresholds.
        assert all(w["burning"] for w in avail["windows"])
        assert avail["windows"][0]["burn_rate"] > 14.4

    def test_no_data_exit_2(self, tmp_path):
        report = evaluate_slos(tmp_path)
        assert report["status"] == "no-data"
        assert report["exit_code"] == 2

    def test_latency_threshold_flips_the_latency_slo(self, tmp_path):
        run = _slo_fixture(tmp_path, p95=600.0)
        burning = evaluate_slos(run)  # default threshold 500ms
        lat = next(
            s for s in burning["slos"] if s["name"] == "move-latency-p95"
        )
        assert lat["status"] == "burning"
        ok = evaluate_slos(run, latency_threshold_ms=1000.0)
        lat = next(
            s for s in ok["slos"] if s["name"] == "move-latency-p95"
        )
        assert lat["status"] == "ok"

    def test_dispatch_failures_count_against_dispatch_success(
        self, tmp_path
    ):
        report = evaluate_slos(_slo_fixture(tmp_path, bad_seals=5))
        disp = next(
            s for s in report["slos"] if s["name"] == "dispatch-success"
        )
        assert disp["status"] == "burning"

    def test_now_replays_the_alert_state(self, tmp_path):
        # Evaluated 2h after the brownout, the 300s window is empty and
        # the 1h window no longer covers the bad minute -> no data.
        run = _slo_fixture(tmp_path, sheds=50)
        later = evaluate_slos(run, now=WALL + 60 + 7200)
        assert later["status"] == "no-data"

    def test_status_line_is_one_line(self, tmp_path):
        line = slo_status_line(evaluate_slos(_slo_fixture(tmp_path)))
        assert "\n" not in line and "availability" in line

    def test_prometheus_aggregation(self, tmp_path):
        report = evaluate_slos(_slo_fixture(tmp_path, sheds=3))
        path = tmp_path / "fleet.prom"
        ok = write_fleet_prometheus(
            path,
            {
                "fleet_sheds": 3,
                "fleet_shed_queue_full": 3,
                "fleet_shed_no_healthy": 0,
                "fleet_shed_retries_exhausted": 0,
                "fleet_requests_per_sec": 12.5,
            },
            report,
            run_name="r1",
        )
        assert ok
        text = path.read_text()
        # Rejection codes are DISTINCT counter series.
        assert (
            "# TYPE alphatriangle_fleet_shed_queue_full_total counter"
            in text
        )
        assert 'alphatriangle_fleet_shed_queue_full_total{run="r1"} 3' in text
        assert (
            "# TYPE alphatriangle_fleet_shed_no_healthy_replica_total "
            "counter" in text
        )
        assert "# TYPE alphatriangle_fleet_requests_per_sec gauge" in text
        assert 'slo="availability"' in text
        assert "alphatriangle_slo_burn_rate" in text


# --- fleet-parent doctor -------------------------------------------------


class TestClassifyFleet:
    def test_empty_ledger_is_never_started(self, tmp_path):
        (tmp_path / "fleet.jsonl").write_text("")
        v = classify_fleet(tmp_path)
        assert v["verdict"] == "never-started" and v["exit_code"] == 2

    def test_torn_route_intent_is_dispatch_hung(self, tmp_path):
        _jl(
            tmp_path / "fleet.jsonl",
            [{"kind": "fleet", "event": "fleet-start", "time": WALL}],
        )
        _jl(
            tmp_path / "flight.jsonl",
            [
                {
                    "kind": "flight", "phase": "intent", "seq": 7,
                    "program": "fleet/route", "family": "fleet",
                    "pid": 1, "trace_id": "f" * 32,
                    "t_mono": 1.0, "time": WALL + 1.0,
                }
            ],
        )
        v = classify_fleet(tmp_path)
        assert v["verdict"] == "dispatch-hung" and v["exit_code"] == 4
        assert "f" * 32 in v["detail"]

    def test_death_without_stop_inherits_the_replica_verdict(
        self, tmp_path
    ):
        _jl(
            tmp_path / "fleet.jsonl",
            [
                {"kind": "fleet", "event": "fleet-start", "time": WALL},
                {
                    "kind": "fleet", "event": "death", "replica": "r0",
                    "rc": 137, "verdict": "oom", "program": "serve/b8",
                    "family": "serve", "time": WALL + 2,
                },
            ],
        )
        v = classify_fleet(tmp_path)
        assert v["verdict"] == "oom" and v["exit_code"] == 6
        assert v["program"] == "serve/b8"

    def test_fleet_stop_is_clean_despite_healed_deaths(self, tmp_path):
        _jl(
            tmp_path / "fleet.jsonl",
            [
                {"kind": "fleet", "event": "fleet-start", "time": WALL},
                {
                    "kind": "fleet", "event": "death", "replica": "r0",
                    "rc": 113, "verdict": "dispatch-hung", "time": WALL + 2,
                },
                {
                    "kind": "fleet", "event": "respawn", "replica": "r0",
                    "time": WALL + 3,
                },
                {"kind": "fleet", "event": "fleet-stop", "time": WALL + 9},
            ],
        )
        v = classify_fleet(tmp_path)
        assert v["verdict"] == "clean" and v["exit_code"] == 0
        assert v["evidence"]["deaths"] == 1


# --- watch fleet line ----------------------------------------------------


class TestFleetWatch:
    def test_fold_and_render(self, tmp_path):
        path = tmp_path / "fleet.jsonl"
        ctx = tracectx.mint()
        _jl(
            path,
            [
                {"kind": "fleet", "event": "fleet-start", "time": WALL},
                {
                    "kind": "fleet", "event": "replica-ready",
                    "replica": "r0", "time": WALL + 1,
                },
                {
                    "kind": "fleet", "event": "replica-ready",
                    "replica": "r1", "time": WALL + 1,
                },
                {
                    "kind": "fleet", "event": "shed",
                    "rejection": "queue-full", "inflight": 64,
                    "time": WALL + 2, **ctx.fields(),
                },
                {
                    "kind": "fleet", "event": "death", "replica": "r1",
                    "time": WALL + 3,
                },
                # Legacy id-less router event folds fine.
                {
                    "kind": "fleet", "event": "retry", "replica": "r0",
                    "attempt": 1, "time": WALL + 4,
                },
            ],
        )
        state = FleetWatchState()
        offset = tail_fleet(path, state, 0)
        assert offset > 0
        assert state.routable == 1 and len(state.replicas) == 2
        assert state.sheds == 1 and state.deaths == 1
        assert state.retries == 1
        assert state.inflight == 64
        assert state.shed_per_min > 0
        line = fleet_line(state)
        assert "1/2 routable" in line
        assert "last retry" in line  # newest decision wins
        # The shed carried a trace id; the retry (legacy) did not, and
        # rendering must not crash either way.
        state2 = FleetWatchState()
        state2.fold_fleet_line(
            json.dumps(
                {
                    "kind": "fleet", "event": "shed",
                    "rejection": "queue-full", "time": WALL,
                    **ctx.fields(),
                }
            )
        )
        assert ctx.trace_id[:8] in fleet_line(state2)

    def test_junk_and_foreign_lines_are_rejected(self):
        state = FleetWatchState()
        assert not state.fold_fleet_line("")
        assert not state.fold_fleet_line("{torn")
        assert not state.fold_fleet_line(json.dumps({"kind": "util"}))
        assert fleet_line(state) is None


# --- the whole package stays importable without JAX ----------------------


def test_tracing_stack_is_jax_free():
    import subprocess
    import sys

    code = (
        "import sys\n"
        "import alphatriangle_tpu.telemetry.tracectx\n"
        "import alphatriangle_tpu.telemetry.merge\n"
        "import alphatriangle_tpu.telemetry.slo\n"
        "import alphatriangle_tpu.stats.watch\n"
        "assert 'jax' not in sys.modules, 'jax leaked into the readers'\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        env={**os.environ, "PYTHONPATH": "."},
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
