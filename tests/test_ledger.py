"""Metrics ledger + utilization accounting + perf/compare CLI tests:
append/rotate round-trips, crash-mid-write (torn line) recovery, meter
math under a frozen clock, golden `cli perf`/`cli compare` outputs on
synthetic runs with threshold exit codes, Prometheus export, and the
`cli watch` utilization line fed from the ledger tail."""

import json

import pytest

from alphatriangle_tpu.cli import main as cli_main
from alphatriangle_tpu.telemetry.ledger import (
    MetricsLedger,
    ledger_paths,
    read_ledger,
    resolve_ledger_path,
    tick_record,
    write_prometheus_textfile,
)
from alphatriangle_tpu.telemetry.perf import (
    SUMMARY_SCHEMA,
    UtilizationMeter,
    compare_summaries,
    load_comparable,
    summarize_utilization,
)


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_meter(clock, peak_env=None, monkeypatch=None, **kw):
    if peak_env is not None:
        monkeypatch.setenv("ALPHATRIANGLE_PEAK_TFLOPS", str(peak_env))
    defaults = dict(
        forward_flops=1_000_000,
        train_step_flops=50_000_000,
        device_kind="cpu",
        buffer_capacity=1000,
        clock=clock,
    )
    defaults.update(kw)
    return UtilizationMeter(**defaults)


def synthetic_run(tmp_path, name="run_a", scale=1.0, ticks=6):
    """A run dir holding a metrics.jsonl of synthetic util records."""
    run_dir = tmp_path / name
    clock = FakeClock()
    meter = UtilizationMeter(
        forward_flops=1_000_000,
        train_step_flops=50_000_000,
        device_kind="TPU v4",
        buffer_capacity=1000,
        clock=clock,
    )
    ledger = MetricsLedger(run_dir / "metrics.jsonl")
    for i in range(ticks):
        rec = meter.tick(
            step=int(i * 10 * scale),
            episodes=int(i * 5 * scale),
            experiences=int(i * 100 * scale),
            simulations=int(i * 5000 * scale),
            buffer_size=min(1000, i * 100),
            transfer_h2d_s=i * 0.01,
            transfer_d2h_s=i * 0.02,
            compile_hits=3,
            compile_misses=1,
        )
        clock.advance(2.0)
        if rec is not None:
            ledger.append(rec)
    return run_dir


class TestLedger:
    def test_append_read_roundtrip(self, tmp_path):
        led = MetricsLedger(tmp_path / "metrics.jsonl")
        for i in range(5):
            assert led.append(tick_record(i, {"Loss/total_loss": 0.5 + i}))
        recs = read_ledger(tmp_path / "metrics.jsonl")
        assert [r["step"] for r in recs] == list(range(5))
        assert all(r["kind"] == "tick" for r in recs)
        # Kind filter.
        assert read_ledger(tmp_path / "metrics.jsonl", kinds={"util"}) == []

    def test_rotation_keeps_recent_generations(self, tmp_path):
        path = tmp_path / "metrics.jsonl"
        led = MetricsLedger(path, max_bytes=400, keep=2)
        for i in range(50):
            led.append({"kind": "tick", "step": i, "means": {"m": i}})
        paths = ledger_paths(path)
        assert path in paths
        assert path.with_name("metrics.jsonl.1") in paths
        # Bounded: never more than keep rotations + live file.
        assert len(paths) <= 3
        assert not path.with_name("metrics.jsonl.3").exists()
        recs = read_ledger(path)
        # Reads span rotations in order; the newest record is last.
        steps = [r["step"] for r in recs]
        assert steps == sorted(steps)
        assert steps[-1] == 49

    def test_torn_last_line_recovery(self, tmp_path):
        """Crash mid-write: the torn tail is skipped, later appends and
        reads keep working."""
        path = tmp_path / "metrics.jsonl"
        led = MetricsLedger(path)
        led.append({"kind": "tick", "step": 1, "means": {"m": 1.0}})
        with path.open("a") as f:
            f.write('{"kind": "tick", "step": 2, "mea')  # torn: no newline
        # Reader skips the torn line.
        assert [r["step"] for r in read_ledger(path)] == [1]
        # A restarted process (fresh ledger over the same file) detects
        # the torn tail and terminates it before its first append — its
        # record must not glue onto the scar and vanish with it.
        led2 = MetricsLedger(path)
        led2.append({"kind": "tick", "step": 3, "means": {"m": 3.0}})
        steps = [r["step"] for r in read_ledger(path)]
        assert steps == [1, 3]

    def test_junk_bytes_never_raise(self, tmp_path):
        path = tmp_path / "metrics.jsonl"
        path.write_bytes(b"\xff\xfe garbage\n[1,2]\n" + b'{"kind":"tick","step":7,"means":{}}\n')
        assert [r["step"] for r in read_ledger(path)] == [7]

    def test_resolve_ledger_path(self, tmp_path):
        run = synthetic_run(tmp_path)
        assert resolve_ledger_path(run) == run / "metrics.jsonl"
        assert resolve_ledger_path(run / "metrics.jsonl") is not None
        assert resolve_ledger_path(tmp_path / "nope") is None


class TestUtilizationMeter:
    def test_first_tick_baselines_then_derives(self, monkeypatch):
        clock = FakeClock()
        meter = make_meter(clock, peak_env=2.0, monkeypatch=monkeypatch)
        assert meter.tick(step=0) is None  # baseline
        clock.advance(2.0)
        rec = meter.tick(
            step=10,
            episodes=5,
            experiences=100,
            simulations=5000,
            buffer_size=100,
            transfer_h2d_s=0.01,
            transfer_d2h_s=0.02,
            compile_hits=3,
            compile_misses=1,
        )
        assert rec["kind"] == "util"
        assert rec["learner_steps_per_sec"] == pytest.approx(5.0)
        assert rec["step_time_ms"] == pytest.approx(200.0)
        assert rec["moves_per_sec"] == pytest.approx(50.0)
        assert rec["games_per_hour"] == pytest.approx(9000.0)
        assert rec["sims_per_sec"] == pytest.approx(2500.0)
        # FLOPs: 5 steps/s * 50e6 + (2500 + 50) evals/s * 1e6.
        expected_tflops = (5 * 50e6 + 2550 * 1e6) / 1e12
        assert rec["tflops_per_sec"] == pytest.approx(
            expected_tflops, rel=1e-3
        )
        assert rec["mfu"] == pytest.approx(expected_tflops / 2.0, rel=1e-3)
        assert rec["peak_source"] == "env"
        assert rec["buffer_fill"] == pytest.approx(0.1)
        assert rec["transfer_h2d_ms"] == pytest.approx(10.0)
        assert rec["transfer_d2h_ms"] == pytest.approx(20.0)
        assert rec["compile_cache_hit_rate"] == pytest.approx(0.75)

    def test_unknown_peak_yields_null_mfu_with_marker(self, monkeypatch):
        monkeypatch.delenv("ALPHATRIANGLE_PEAK_TFLOPS", raising=False)
        clock = FakeClock()
        meter = make_meter(clock, device_kind="NPU weird9000")
        assert meter.peak_tflops is None
        assert meter.peak_source == "unknown"
        meter.tick(step=0)
        clock.advance(1.0)
        rec = meter.tick(step=5, experiences=10)
        assert rec["mfu"] is None
        assert rec["peak_bf16_tflops"] is None
        assert rec["peak_source"] == "unknown"

    def test_known_chip_uses_table(self, monkeypatch):
        monkeypatch.delenv("ALPHATRIANGLE_PEAK_TFLOPS", raising=False)
        meter = make_meter(FakeClock(), device_kind="TPU v4")
        assert meter.peak_tflops == 275.0
        assert meter.peak_source == "table"

    def test_zero_width_tick_skipped(self, monkeypatch):
        clock = FakeClock()
        meter = make_meter(clock)
        meter.tick(step=0)
        assert meter.tick(step=1) is None  # same clock instant


class TestSummarize:
    def test_summary_fields(self, tmp_path):
        run = synthetic_run(tmp_path)
        recs = read_ledger(run / "metrics.jsonl", kinds={"util"})
        s = summarize_utilization(recs)
        assert s["schema"] == SUMMARY_SCHEMA
        assert s["ticks"] == len(recs)
        assert s["learner_steps_per_sec"] == pytest.approx(5.0)
        assert s["games_per_hour"] == pytest.approx(9000.0)
        assert s["step_time_ms_p50"] == pytest.approx(200.0)
        assert s["step_time_ms_p95"] == pytest.approx(200.0)
        assert s["mfu"] is not None
        assert s["throughput_trend"] == pytest.approx(0.0)
        assert s["device_kind"] == "TPU v4"

    def test_window_limits_records(self, tmp_path):
        run = synthetic_run(tmp_path, ticks=10)
        recs = read_ledger(run / "metrics.jsonl", kinds={"util"})
        s = summarize_utilization(recs, window=3)
        assert s["ticks"] == 3
        assert s["ticks_total"] == len(recs)

    def test_no_records_is_none(self):
        assert summarize_utilization([]) is None
        assert summarize_utilization([{"kind": "tick", "step": 1}]) is None


class TestCompare:
    def test_parity_and_regression(self, tmp_path):
        a = synthetic_run(tmp_path, "run_a")
        sa, _ = load_comparable(str(a))
        rows, reg = compare_summaries(sa, sa, threshold=0.1)
        assert reg == []
        assert all(r[4] in ("ok", "n/a") for r in rows)
        # 20% slower candidate vs baseline: regression.
        slower = dict(sa, games_per_hour=sa["games_per_hour"] * 0.8)
        rows, reg = compare_summaries(slower, sa, threshold=0.1)
        assert "games_per_hour" in reg

    def test_load_comparable_bench_json(self, tmp_path):
        bench = {
            "metric": "self_play_games_per_hour",
            "value": 12000.0,
            "unit": "games/hour",
            "extra": {
                "moves_per_sec": 900.0,
                "learner_steps_per_sec": 4.0,
                "learner_steps_per_sec_fused": 9.5,
                "device_kind": "TPU v5 lite",
                "flops": {"self_play_mfu": 0.11},
            },
        }
        path = tmp_path / "BENCH_x.json"
        path.write_text(json.dumps(bench))
        s, label = load_comparable(str(path))
        assert s["games_per_hour"] == 12000.0
        assert s["learner_steps_per_sec"] == 9.5  # fused preferred
        assert s["mfu"] == 0.11

    def test_load_comparable_missing(self, tmp_path):
        s, reason = load_comparable(str(tmp_path / "ghost"))
        assert s is None and "ghost" in reason


class TestCliPerf:
    def test_golden_summary_on_synthetic_run(self, tmp_path, capsys):
        run = synthetic_run(tmp_path)
        rc = cli_main(["perf", str(run)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "steps 10" in out and "TPU v4" in out
        assert "step p50 200.0ms" in out and "p95 200.0ms" in out
        assert "9,000.0 games/h" in out
        assert "MFU" in out and "trend" in out
        assert "[table]" in out  # peak source surfaced

    def test_json_summary_feeds_compare(self, tmp_path, capsys):
        run = synthetic_run(tmp_path)
        rc = cli_main(["perf", str(run), "--json"])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["schema"] == SUMMARY_SCHEMA
        ref = tmp_path / "ref.json"
        ref.write_text(json.dumps(summary))
        assert cli_main(["compare", str(run), str(ref)]) == 0

    def test_missing_ledger_exits_2(self, tmp_path, capsys):
        empty = tmp_path / "empty_run"
        empty.mkdir()
        assert cli_main(["perf", str(empty)]) == 2

    def test_tick_only_ledger_exits_2(self, tmp_path, capsys):
        run = tmp_path / "tickrun"
        MetricsLedger(run / "metrics.jsonl").append(
            tick_record(1, {"m": 1.0})
        )
        assert cli_main(["perf", str(run)]) == 2


class TestCliCompare:
    def test_parity_exit_0(self, tmp_path, capsys):
        a = synthetic_run(tmp_path, "run_a")
        b = synthetic_run(tmp_path, "run_b")
        rc = cli_main(["compare", str(a), str(b)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "parity" in out

    def test_injected_20pct_regression_exits_1(self, tmp_path, capsys):
        a = synthetic_run(tmp_path, "run_a", scale=0.8)  # 20% slower
        b = synthetic_run(tmp_path, "run_b", scale=1.0)
        rc = cli_main(["compare", str(a), str(b)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "REGRESSION" in out

    def test_threshold_is_respected(self, tmp_path):
        a = synthetic_run(tmp_path, "run_a", scale=0.8)
        b = synthetic_run(tmp_path, "run_b", scale=1.0)
        assert cli_main(["compare", str(a), str(b), "--threshold", "0.3"]) == 0

    def test_unreadable_side_exits_2(self, tmp_path, capsys):
        a = synthetic_run(tmp_path, "run_a")
        assert cli_main(["compare", str(a), str(tmp_path / "ghost")]) == 2

    def test_json_report(self, tmp_path, capsys):
        a = synthetic_run(tmp_path, "run_a", scale=0.5)
        b = synthetic_run(tmp_path, "run_b")
        rc = cli_main(["compare", str(a), str(b), "--json"])
        report = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert "games_per_hour" in report["regressions"]
        assert any(r["status"] == "regression" for r in report["rows"])


class TestPrometheus:
    def test_textfile_gauges(self, tmp_path):
        rec = {
            "kind": "util",
            "step": 42,
            "mfu": 0.125,
            "games_per_hour": 9000.0,
            "learner_steps_per_sec": 5.0,
            "device_kind": "TPU v4",  # non-numeric: skipped
            "step_time_ms": None,  # missing: skipped
        }
        path = tmp_path / "metrics.prom"
        assert write_prometheus_textfile(path, rec, run_name="r1")
        text = path.read_text()
        assert 'alphatriangle_mfu{run="r1"} 0.125' in text
        assert 'alphatriangle_step{run="r1"} 42' in text
        assert "# TYPE alphatriangle_games_per_hour gauge" in text
        assert "device_kind" not in text
        assert "step_time_ms" not in text
        assert not path.with_suffix(".prom.tmp").exists()


class TestWatchUtilization:
    def test_tail_and_render_util_line(self, tmp_path):
        from alphatriangle_tpu.stats.watch import (
            WatchState,
            render_frame,
            tail_ledger_utils,
        )

        run = synthetic_run(tmp_path)
        state = WatchState()
        offset = tail_ledger_utils(run / "metrics.jsonl", state, 0)
        assert offset > 0
        assert state.util["kind"] == "util"
        frame = render_frame(state, "run_a")
        assert "utilization" in frame
        assert "TFLOP/s" in frame and "MFU" in frame

    def test_torn_ledger_tail_survives(self, tmp_path):
        from alphatriangle_tpu.stats.watch import WatchState, tail_ledger_utils

        path = tmp_path / "metrics.jsonl"
        path.write_text('{"kind": "util", "step": 3, "mfu": 0.5}\n{"kind": "ut')
        state = WatchState()
        offset = tail_ledger_utils(path, state, 0)
        assert state.util["step"] == 3
        # Torn tail not consumed; completing it folds on the next tail.
        with path.open("a") as f:
            f.write('il", "step": 4, "mfu": 0.6}\n')
        tail_ledger_utils(path, state, offset)
        assert state.util["step"] == 4

    def test_no_util_no_line(self):
        from alphatriangle_tpu.stats.watch import WatchState, render_frame

        frame = render_frame(WatchState(), "r")
        assert "utilization" not in frame


class TestRunTelemetryLedger:
    def test_on_util_tick_appends_and_updates_health(self, tmp_path, monkeypatch):
        monkeypatch.setenv("ALPHATRIANGLE_PEAK_TFLOPS", "1.0")
        from alphatriangle_tpu.telemetry import RunTelemetry, TelemetryConfig

        clock = FakeClock()
        meter = make_meter(clock)
        tel = RunTelemetry(
            TelemetryConfig(WATCHDOG_ENABLED=False),
            run_dir=tmp_path,
            run_name="r",
            clock=clock,
            perf=meter,
        )
        assert tel.on_util_tick(0, compile_hits=0, compile_misses=0) is None
        clock.advance(2.0)
        rec = tel.on_util_tick(
            10, experiences=100, compile_hits=1, compile_misses=1
        )
        assert rec is not None
        utils = read_ledger(tmp_path / "metrics.jsonl", kinds={"util"})
        assert len(utils) == 1 and utils[0]["step"] == 10
        tel.close(10)
        health = json.loads((tmp_path / "health.json").read_text())
        assert health["device_kind"] == "cpu"
        assert health["peak_bf16_tflops"] == 1.0
        assert health["utilization"]["step"] == 10

    def test_record_metrics_sink(self, tmp_path):
        from alphatriangle_tpu.telemetry import RunTelemetry, TelemetryConfig

        tel = RunTelemetry(
            TelemetryConfig(WATCHDOG_ENABLED=False), run_dir=tmp_path
        )
        tel.record_metrics(5, {"Loss/total_loss": 0.3})
        ticks = read_ledger(tmp_path / "metrics.jsonl", kinds={"tick"})
        assert ticks[0]["step"] == 5
        assert ticks[0]["means"]["Loss/total_loss"] == 0.3
        tel.close()

    def test_disabled_writes_nothing(self, tmp_path):
        from alphatriangle_tpu.telemetry import RunTelemetry, TelemetryConfig

        tel = RunTelemetry(
            TelemetryConfig(ENABLED=False), run_dir=tmp_path, perf=make_meter(FakeClock())
        )
        tel.record_metrics(1, {"m": 1.0})
        assert tel.on_util_tick(1) is None
        assert not (tmp_path / "metrics.jsonl").exists()

    def test_prometheus_opt_in(self, tmp_path, monkeypatch):
        monkeypatch.setenv("ALPHATRIANGLE_PEAK_TFLOPS", "1.0")
        from alphatriangle_tpu.telemetry import RunTelemetry, TelemetryConfig

        clock = FakeClock()
        tel = RunTelemetry(
            TelemetryConfig(WATCHDOG_ENABLED=False, PROMETHEUS_TEXTFILE=True),
            run_dir=tmp_path,
            run_name="promrun",
            clock=clock,
            perf=make_meter(clock),
        )
        tel.on_util_tick(0, compile_hits=0, compile_misses=0)
        clock.advance(1.0)
        tel.on_util_tick(5, experiences=10, compile_hits=0, compile_misses=0)
        text = (tmp_path / "metrics.prom").read_text()
        assert 'alphatriangle_step{run="promrun"} 5' in text
        tel.close()


class TestFlopsPeakOverride:
    def test_env_override_wins(self, monkeypatch):
        from alphatriangle_tpu.utils.flops import (
            mfu,
            peak_bf16_tflops,
            peak_bf16_tflops_info,
        )

        monkeypatch.setenv("ALPHATRIANGLE_PEAK_TFLOPS", "2.5")
        assert peak_bf16_tflops_info("TPU v4") == (2.5, "env")
        assert peak_bf16_tflops("whatever") == 2.5
        assert mfu(2.5e12, "cpu") == pytest.approx(1.0)

    def test_invalid_override_ignored(self, monkeypatch):
        from alphatriangle_tpu.utils.flops import peak_bf16_tflops_info

        monkeypatch.setenv("ALPHATRIANGLE_PEAK_TFLOPS", "not-a-number")
        assert peak_bf16_tflops_info("TPU v4") == (275.0, "table")
        monkeypatch.setenv("ALPHATRIANGLE_PEAK_TFLOPS", "-3")
        assert peak_bf16_tflops_info("nope") == (None, "unknown")

    def test_table_and_unknown(self, monkeypatch):
        from alphatriangle_tpu.utils.flops import peak_bf16_tflops_info

        monkeypatch.delenv("ALPHATRIANGLE_PEAK_TFLOPS", raising=False)
        assert peak_bf16_tflops_info("TPU v5 lite") == (394.0, "table")
        assert peak_bf16_tflops_info("TPU v5litepod-8") == (394.0, "table")
        assert peak_bf16_tflops_info("Quantum Q1") == (None, "unknown")


class TestLegacyDeviceStatsTolerance:
    """Runs recorded BEFORE the device-telemetry plane existed (no
    `kind:"device_stats"` records, no stat-pack gauges on the util
    ticks) must keep reading exactly as they always did: no ds_* keys
    invented, no search-health line printed, compare still clean."""

    def test_perf_json_has_no_ds_fields(self, tmp_path, capsys):
        run = synthetic_run(tmp_path)
        rc = cli_main(["perf", str(run), "--json"])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        assert not [k for k in summary if k.startswith("ds_")]
        assert "root_visit_entropy" not in summary
        assert "tree_occupancy" not in summary

    def test_perf_text_has_no_search_health_line(self, tmp_path, capsys):
        run = synthetic_run(tmp_path)
        rc = cli_main(["perf", str(run)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "entropy" not in out
        assert "ingest/per" not in out

    def test_summarize_device_stats_none_on_legacy(self, tmp_path):
        from alphatriangle_tpu.telemetry.device_stats import (
            summarize_device_stats,
        )

        run = synthetic_run(tmp_path)
        recs = read_ledger(run / "metrics.jsonl", kinds={"device_stats"})
        assert recs == []
        assert summarize_device_stats(recs) is None

    def test_compare_legacy_run_vs_ds_reference_clean(self, tmp_path, capsys):
        """A reference regenerated WITH ds_* fields must not regress a
        legacy run: ds_* keys are not in COMPARE_METRICS, so the rows
        stay absent unless --metrics names them explicitly."""
        run = synthetic_run(tmp_path)
        rc = cli_main(["perf", str(run), "--json"])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        ref = dict(
            summary,
            ds_records=12,
            ds_root_entropy=1.2,
            ds_tree_occupancy=0.4,
            root_visit_entropy=1.2,
        )
        ref_path = tmp_path / "ref_ds.json"
        ref_path.write_text(json.dumps(ref))
        assert cli_main(["compare", str(run), str(ref_path)]) == 0

    def test_watch_renders_no_devstats_line_on_legacy(self):
        from alphatriangle_tpu.stats.watch import device_stats_line

        assert device_stats_line({}) is None
        assert device_stats_line({"mfu": 0.5, "steps_per_sec": 1.0}) is None
