"""Model + evaluator contract tests.

Mirrors the reference test matrix (`tests/nn/test_model.py:31-131`,
`tests/nn/test_network.py:61-322`): forward shapes/dtypes with the
transformer on and off, eval contracts (probs sum to 1, full action
mapping, finite values), weight get/set round trip, NaN-input guard.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphatriangle_tpu.config import ModelConfig, expected_other_features_dim
from alphatriangle_tpu.env import GameState
from alphatriangle_tpu.nn import (
    AlphaTriangleNet,
    NetworkEvaluationError,
    NeuralNetwork,
    count_parameters,
    expected_value_from_logits,
    sinusoidal_positional_encoding,
    value_support,
)


def _model_cfg(base: ModelConfig, **overrides) -> ModelConfig:
    return ModelConfig(**{**base.model_dump(), **overrides})


@pytest.fixture(scope="module", params=[False, True], ids=["cnn", "transformer"])
def model_variant(request, tiny_model_config):
    return _model_cfg(
        tiny_model_config,
        USE_TRANSFORMER=request.param,
        TRANSFORMER_LAYERS=1 if request.param else 0,
        NUM_RESIDUAL_BLOCKS=1,
    )


def test_forward_shapes_and_dtype(model_variant, tiny_env_config):
    net = AlphaTriangleNet(model_variant, tiny_env_config.action_dim)
    b = 3
    grid = jnp.zeros((b, 1, tiny_env_config.ROWS, tiny_env_config.COLS))
    other = jnp.zeros((b, model_variant.OTHER_NN_INPUT_FEATURES_DIM))
    variables = net.init(jax.random.PRNGKey(0), grid, other, train=False)
    pol, val = jax.jit(lambda v, g, o: net.apply(v, g, o, train=False))(
        variables, grid, other
    )
    assert pol.shape == (b, tiny_env_config.action_dim)
    assert val.shape == (b, model_variant.NUM_VALUE_ATOMS)
    assert pol.dtype == jnp.float32 and val.dtype == jnp.float32
    assert np.all(np.isfinite(np.asarray(pol)))


def test_bfloat16_compute_path(tiny_model_config, tiny_env_config):
    cfg = _model_cfg(tiny_model_config, COMPUTE_DTYPE="bfloat16")
    net = AlphaTriangleNet(cfg, tiny_env_config.action_dim)
    grid = jnp.zeros((2, 1, tiny_env_config.ROWS, tiny_env_config.COLS))
    other = jnp.zeros((2, cfg.OTHER_NN_INPUT_FEATURES_DIM))
    variables = net.init(jax.random.PRNGKey(0), grid, other)
    pol, val = net.apply(variables, grid, other)
    # Params stay f32, outputs are f32 despite bf16 internals.
    leaf = jax.tree_util.tree_leaves(variables["params"])[0]
    assert leaf.dtype == jnp.float32
    assert pol.dtype == jnp.float32 and val.dtype == jnp.float32


def test_batch_norm_variant_has_batch_stats(tiny_model_config, tiny_env_config):
    cfg = _model_cfg(tiny_model_config, NORM_TYPE="batch")
    net = AlphaTriangleNet(cfg, tiny_env_config.action_dim)
    grid = jnp.zeros((2, 1, tiny_env_config.ROWS, tiny_env_config.COLS))
    other = jnp.zeros((2, cfg.OTHER_NN_INPUT_FEATURES_DIM))
    variables = net.init(jax.random.PRNGKey(0), grid, other, train=True)
    assert "batch_stats" in variables
    out, mutated = net.apply(
        variables, grid, other, train=True,
        mutable=["batch_stats"], rngs={"dropout": jax.random.PRNGKey(1)},
    )
    assert "batch_stats" in mutated


def test_positional_encoding_table():
    pe = sinusoidal_positional_encoding(10, 8)
    assert pe.shape == (10, 8)
    # Row 0 is sin(0)=0 interleaved with cos(0)=1.
    np.testing.assert_allclose(pe[0, 0::2], 0.0, atol=1e-7)
    np.testing.assert_allclose(pe[0, 1::2], 1.0, atol=1e-7)
    assert np.all(np.abs(pe) <= 1.0)


def test_value_support_and_expectation(tiny_model_config):
    support = value_support(tiny_model_config)
    assert support.shape == (tiny_model_config.NUM_VALUE_ATOMS,)
    assert float(support[0]) == tiny_model_config.VALUE_MIN
    assert float(support[-1]) == tiny_model_config.VALUE_MAX
    # A one-hot distribution on atom k has expected value z_k.
    logits = jnp.full((1, tiny_model_config.NUM_VALUE_ATOMS), -1e9)
    logits = logits.at[0, 3].set(0.0)
    ev = expected_value_from_logits(logits, support)
    assert float(ev[0]) == pytest.approx(float(support[3]), rel=1e-5)


@pytest.fixture(scope="module")
def network(tiny_model_config, tiny_env_config) -> NeuralNetwork:
    return NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)


@pytest.fixture()
def game(tiny_env_config) -> GameState:
    return GameState(tiny_env_config, initial_seed=4)


def test_evaluate_state_contract(network, game, tiny_env_config):
    policy, value = network.evaluate_state(game)
    assert len(policy) == tiny_env_config.action_dim
    assert sum(policy.values()) == pytest.approx(1.0, abs=1e-4)
    assert all(p >= 0 for p in policy.values())
    assert network.v_min <= value <= network.v_max
    assert np.isfinite(value)


def test_evaluate_batch_contract(network, tiny_env_config):
    states = [GameState(tiny_env_config, initial_seed=s) for s in range(5)]
    results = network.evaluate_batch(states)
    assert len(results) == 5
    for policy, value in results:
        assert sum(policy.values()) == pytest.approx(1.0, abs=1e-4)
        assert np.isfinite(value)
    assert network.evaluate_batch([]) == []


def test_evaluate_batch_matches_single(network, tiny_env_config):
    state = GameState(tiny_env_config, initial_seed=7)
    single_policy, single_value = network.evaluate_state(state)
    [(batch_policy, batch_value)] = network.evaluate_batch([state])
    assert single_value == pytest.approx(batch_value, abs=1e-5)
    np.testing.assert_allclose(
        np.array(list(single_policy.values())),
        np.array(list(batch_policy.values())),
        atol=1e-5,
    )


def test_weights_roundtrip_and_version(network, game):
    w = network.get_weights()
    policy_before, value_before = network.evaluate_state(game)
    v0 = network.weights_version
    # Perturb weights -> output changes; restore -> output matches.
    perturbed = jax.tree_util.tree_map(lambda a: a + 0.5, w)
    network.set_weights(perturbed)
    assert network.weights_version == v0 + 1
    _, value_perturbed = network.evaluate_state(game)
    network.set_weights(w)
    policy_after, value_after = network.evaluate_state(game)
    assert value_after == pytest.approx(value_before, abs=1e-5)
    assert value_perturbed != pytest.approx(value_before, abs=1e-6)
    np.testing.assert_allclose(
        np.array(list(policy_before.values())),
        np.array(list(policy_after.values())),
        atol=1e-6,
    )


def test_nan_features_raise(network, game, monkeypatch):
    import alphatriangle_tpu.nn.network as netmod

    def bad_extract(gs, mc):
        feats = extract_real(gs, mc)
        feats["other_features"] = np.full_like(feats["other_features"], np.nan)
        return feats

    extract_real = netmod.extract_state_features
    monkeypatch.setattr(netmod, "extract_state_features", bad_extract)
    with pytest.raises(NetworkEvaluationError):
        network.evaluate_state(game)


def test_count_parameters(network):
    n = count_parameters(network.params)
    assert n > 0
    total = sum(
        int(np.prod(p.shape))
        for p in jax.tree_util.tree_leaves(network.variables["params"])
    )
    assert n == total
