"""Batched MCTS tests: helper contracts, search invariants on the tiny
env, and the VERDICT.md #7 'Done =' bar — MCTS with an untrained net
beats uniform-random play on average score."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphatriangle_tpu.config import AlphaTriangleMCTSConfig
from alphatriangle_tpu.env.engine import TriangleEnv
from alphatriangle_tpu.features.core import get_feature_extractor
from alphatriangle_tpu.mcts import (
    BatchedMCTS,
    PolicyGenerationError,
    policy_target_from_visits,
    select_action_from_visits,
)
from alphatriangle_tpu.mcts.helpers import select_action_from_visits_dict
from alphatriangle_tpu.nn.network import NeuralNetwork


class TestHelpers:
    def test_policy_target_normalizes(self):
        counts = jnp.array([[4.0, 0.0, 12.0, 0.0]])
        target = policy_target_from_visits(counts)
        np.testing.assert_allclose(
            np.asarray(target[0]), [0.25, 0.0, 0.75, 0.0], rtol=1e-6
        )

    def test_policy_target_zero_visits_fallback(self):
        counts = jnp.zeros((1, 4))
        mask = jnp.array([[True, False, True, False]])
        target = policy_target_from_visits(counts, mask)
        np.testing.assert_allclose(np.asarray(target[0]), [0.5, 0, 0.5, 0])

    def test_greedy_selection(self):
        counts = jnp.array([[1.0, 7.0, 2.0, 0.0]])
        a = select_action_from_visits(counts, 0.0, jax.random.PRNGKey(0))
        assert int(a[0]) == 1

    def test_sampling_never_picks_zero_count(self):
        counts = jnp.array([[0.0, 5.0, 5.0, 0.0]])
        for seed in range(20):
            a = select_action_from_visits(
                counts, 1.5, jax.random.PRNGKey(seed)
            )
            assert int(a[0]) in (1, 2)

    def test_low_temperature_concentrates(self):
        counts = jnp.array([[1.0, 10.0, 2.0, 1.0]])
        picks = [
            int(
                select_action_from_visits(
                    counts, 0.1, jax.random.PRNGKey(s)
                )[0]
            )
            for s in range(25)
        ]
        assert picks.count(1) >= 23

    def test_all_zero_row_yields_sentinel(self):
        counts = jnp.array([[0.0, 0.0], [3.0, 1.0]])
        a = select_action_from_visits(counts, 0.0, jax.random.PRNGKey(0))
        assert a.tolist() == [-1, 0]

    def test_per_game_temperature_vector(self):
        counts = jnp.array([[1.0, 9.0], [9.0, 1.0]])
        a = select_action_from_visits(
            counts, jnp.array([0.0, 0.0]), jax.random.PRNGKey(0)
        )
        assert a.tolist() == [1, 0]

    def test_dict_adapter(self):
        assert select_action_from_visits_dict({3: 10, 1: 1}, 6, 0.0) == 3
        with pytest.raises(PolicyGenerationError):
            select_action_from_visits_dict({}, 6, 0.0)
        with pytest.raises(PolicyGenerationError):
            select_action_from_visits_dict({9: 3}, 6, 0.0)


@pytest.fixture(scope="module")
def mcts_world(tiny_env_config, tiny_model_config, tiny_mcts_config):
    env = TriangleEnv(tiny_env_config)
    fe = get_feature_extractor(env, tiny_model_config)
    net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
    mcts = BatchedMCTS(env, fe, net.model, tiny_mcts_config, net.support)
    return env, fe, net, mcts


class TestSearch:
    B = 8

    def _roots(self, env, seed=0):
        return env.reset_batch(jax.random.split(jax.random.PRNGKey(seed), self.B))

    def test_visit_counts_invariants(self, mcts_world, tiny_mcts_config):
        env, _, net, mcts = mcts_world
        roots = self._roots(env)
        out = mcts.search(net.variables, roots, jax.random.PRNGKey(1))
        counts = np.asarray(out.visit_counts)
        assert counts.shape == (self.B, env.action_dim)
        # Every simulation backs up through exactly one root child.
        np.testing.assert_allclose(
            counts.sum(axis=1), tiny_mcts_config.max_simulations
        )
        # Visits only on valid root actions.
        valid = np.asarray(env.valid_mask_batch(roots))
        assert np.all(counts[~valid] == 0)

    def test_root_value_finite(self, mcts_world):
        env, _, net, mcts = mcts_world
        out = mcts.search(
            net.variables, self._roots(env), jax.random.PRNGKey(2)
        )
        rv = np.asarray(out.root_value)
        assert np.all(np.isfinite(rv))

    def test_deterministic_given_rng(self, mcts_world):
        env, _, net, mcts = mcts_world
        roots = self._roots(env)
        o1 = mcts.search(net.variables, roots, jax.random.PRNGKey(7))
        o2 = mcts.search(net.variables, roots, jax.random.PRNGKey(7))
        np.testing.assert_array_equal(
            np.asarray(o1.visit_counts), np.asarray(o2.visit_counts)
        )

    def test_noise_changes_with_rng(self, mcts_world):
        env, _, net, mcts = mcts_world
        roots = self._roots(env)
        o1 = mcts.search(net.variables, roots, jax.random.PRNGKey(7))
        o2 = mcts.search(net.variables, roots, jax.random.PRNGKey(8))
        assert not np.array_equal(
            np.asarray(o1.root_prior), np.asarray(o2.root_prior)
        )

    def test_mcts_beats_random(
        self, mcts_world, tiny_env_config, tiny_mcts_config
    ):
        """VERDICT #7 bar: untrained-net MCTS > uniform random play."""
        env, _, net, mcts = mcts_world
        B, max_moves = 16, 40
        rng = np.random.default_rng(0)

        def play(policy_fn, seed):
            states = env.reset_batch(
                jax.random.split(jax.random.PRNGKey(seed), B)
            )
            for move in range(max_moves):
                done = np.asarray(states.done)
                if done.all():
                    break
                actions = policy_fn(states, move)
                states, _, _ = env.step_batch(
                    states, jnp.asarray(actions, dtype=jnp.int32)
                )
            return float(np.asarray(states.score).mean())

        def random_policy(states, move):
            masks = np.asarray(env.valid_mask_batch(states))
            logits = np.where(masks, rng.random(masks.shape), -np.inf)
            # Finished games have all-False masks; action 0 is a no-op.
            return np.where(masks.any(axis=1), logits.argmax(axis=1), 0)

        def mcts_policy(states, move):
            out = mcts.search(
                net.variables, states, jax.random.PRNGKey(1000 + move)
            )
            counts = np.asarray(out.visit_counts)
            return np.where(
                counts.sum(axis=1) > 0, counts.argmax(axis=1), 0
            )

        random_score = np.mean([play(random_policy, s) for s in (11, 22)])
        mcts_score = np.mean([play(mcts_policy, s) for s in (11, 22)])
        assert mcts_score > random_score


class TestWaves:
    """Wave-parallel mechanics: size clamp, duplicate canonicalization,
    wasted-slot accounting, and exact PUCT at wave_size=1."""

    def test_wave_size_clamped_to_divisor(
        self, mcts_world, tiny_mcts_config
    ):
        env, fe, net, _ = mcts_world
        cfg = tiny_mcts_config.model_copy(
            update={"max_simulations": 10, "mcts_batch_size": 4}
        )
        mcts = BatchedMCTS(env, fe, net.model, cfg, net.support)
        assert mcts.wave_size == 2 and mcts.num_waves == 5
        cfg = tiny_mcts_config.model_copy(
            update={"max_simulations": 8, "mcts_batch_size": 7}
        )
        mcts = BatchedMCTS(env, fe, net.model, cfg, net.support)
        assert mcts.wave_size == 4 and mcts.num_waves == 2

    def test_wave_duplicates_share_one_child_slot(self, mcts_world):
        """After one wave: distinct edges own distinct child slots, and
        the number of live (non-orphan) slots matches wasted_slots."""
        env, fe, net, mcts = mcts_world
        B = 4
        roots = env.reset_batch(jax.random.split(jax.random.PRNGKey(5), B))
        rng = jax.random.PRNGKey(6)
        tree = mcts._init_tree(net.variables, roots, rng)
        tree, wasted, base = mcts._wave(
            net.variables,
            B,
            (tree, jnp.zeros((B,), jnp.int32), jnp.int32(1)),
            jax.random.fold_in(rng, 0),
        )
        assert int(base) == 1 + mcts.wave_size
        children = np.asarray(tree.children)
        wasted = np.asarray(wasted)
        for b in range(B):
            kids = children[b][children[b] >= 0]
            # No slot is shared across different edges.
            assert len(kids) == len(set(kids.tolist()))
            # Live slots + orphans tile the wave exactly.
            assert len(kids) == mcts.wave_size - int(wasted[b])
            assert 0 <= wasted[b] < mcts.wave_size

    def test_wasted_slots_bounded_full_search(
        self, mcts_world, tiny_mcts_config
    ):
        env, _, net, mcts = mcts_world
        roots = env.reset_batch(jax.random.split(jax.random.PRNGKey(9), 8))
        out = mcts.search(net.variables, roots, jax.random.PRNGKey(10))
        wasted = np.asarray(out.wasted_slots)
        assert np.all(wasted >= 0)
        assert np.all(wasted <= tiny_mcts_config.max_simulations)

    def test_wave_size_one_is_noise_free(self, mcts_world, tiny_mcts_config):
        """W=1 must reproduce exact sequential PUCT: identical visit
        counts for different wave RNG streams (no Gumbel perturbation)."""
        env, fe, net, _ = mcts_world
        cfg = tiny_mcts_config.model_copy(
            update={"mcts_batch_size": 1, "dirichlet_epsilon": 0.0}
        )
        mcts = BatchedMCTS(env, fe, net.model, cfg, net.support)
        assert mcts.wave_size == 1
        roots = env.reset_batch(jax.random.split(jax.random.PRNGKey(3), 4))
        o1 = mcts.search(net.variables, roots, jax.random.PRNGKey(1))
        o2 = mcts.search(net.variables, roots, jax.random.PRNGKey(2))
        np.testing.assert_array_equal(
            np.asarray(o1.visit_counts), np.asarray(o2.visit_counts)
        )
