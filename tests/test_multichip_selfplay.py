"""Multi-chip self-play: lockstep lanes sharded over the mesh.

The TPU counterpart of the reference fanning self-play actors across
hardware (`alphatriangle/training/worker_manager.py:39-75`): B games
shard B/n per device over the mesh's data axes, one jitted chunk
program spans the mesh. Lanes are independent, so the sharded engine
must produce exactly the same games as the single-device engine with
the same seed — that bit-parity is the core assertion here.
"""

import jax
import numpy as np
import pytest

from alphatriangle_tpu.config import MeshConfig, TrainConfig
from alphatriangle_tpu.env.engine import TriangleEnv
from alphatriangle_tpu.features.core import get_feature_extractor
from alphatriangle_tpu.nn.network import NeuralNetwork
from alphatriangle_tpu.rl import SelfPlayEngine


@pytest.fixture(scope="module")
def world(tiny_env_config, tiny_model_config, tiny_mcts_config):
    env = TriangleEnv(tiny_env_config)
    fe = get_feature_extractor(env, tiny_model_config)
    net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
    return env, fe, net, tiny_mcts_config


def _train_cfg(**kw):
    base = dict(
        BATCH_SIZE=8,
        BUFFER_CAPACITY=5000,
        MIN_BUFFER_SIZE_TO_TRAIN=8,
        USE_PER=False,
        N_STEP_RETURNS=3,
        GAMMA=0.9,
        MAX_EPISODE_MOVES=50,
        SELF_PLAY_BATCH_SIZE=8,
        MAX_TRAINING_STEPS=100,
        RUN_NAME="mc_sp_test",
    )
    base.update(kw)
    return TrainConfig(**base)


def _make(world, mesh=None, data_axes=("dp",), seed=7, **cfg_kw):
    env, fe, net, mcts_cfg = world
    tc = _train_cfg(**cfg_kw)
    return SelfPlayEngine(
        env,
        fe,
        net,
        mcts_cfg,
        tc,
        seed=seed,
        mesh=mesh,
        data_axes=data_axes,
    )


class TestShardedRollout:
    def test_lanes_span_every_device(self, world):
        mesh = MeshConfig(DP_SIZE=8).build_mesh()
        engine = _make(world, mesh=mesh)
        # Initial carry already sharded: 8 lanes -> 1 per device.
        shards = engine.states.step_count.addressable_shards
        devices = {s.device for s in shards}
        assert len(devices) == 8
        assert all(s.data.shape == (1,) for s in shards)
        engine.play_chunk(4)
        # Sharding survives the donated chunk dispatch.
        shards = engine.states.step_count.addressable_shards
        assert {s.device for s in shards} == devices

    @pytest.mark.slow
    def test_parity_with_unsharded_engine(self, world):
        mesh = MeshConfig(DP_SIZE=8).build_mesh()
        sharded = _make(world, mesh=mesh, seed=11)
        plain = _make(world, mesh=None, seed=11)
        rs = sharded.play_moves(8)
        rp = plain.play_moves(8)
        # Lane math is device-local; the sharded program must play the
        # exact same games (same seeds, same kernels, no collectives).
        assert rs.num_experiences == rp.num_experiences
        np.testing.assert_allclose(rs.grid, rp.grid, atol=0, rtol=0)
        np.testing.assert_allclose(
            rs.policy_target, rp.policy_target, atol=1e-6
        )
        np.testing.assert_allclose(
            rs.value_target, rp.value_target, atol=1e-5
        )
        assert rs.episode_scores == rp.episode_scores
        assert rs.episode_lengths == rp.episode_lengths

    def test_dp_sp_axes_compose(self, world):
        # Lanes ride (dp, sp) when the mesh has a real sp axis: rollouts
        # must not leave sp-axis devices idle (setup.py wires this).
        mesh = MeshConfig(DP_SIZE=2, MDL_SIZE=2, SP_SIZE=2).build_mesh()
        engine = _make(world, mesh=mesh, data_axes=("dp", "sp"))
        engine.play_chunk(4)
        result = engine.harvest()
        assert result.num_experiences >= 0
        shards = engine.states.step_count.addressable_shards
        # 4-way lane sharding (dp*sp), each shard replicated over mdl:
        # every one of the 8 devices holds lanes and steps games.
        assert len({s.device for s in shards}) == 8
        assert all(s.data.shape == (2,) for s in shards)

    def test_indivisible_batch_rejected(self, world):
        mesh = MeshConfig(DP_SIZE=8).build_mesh()
        with pytest.raises(ValueError, match="divide"):
            _make(world, mesh=mesh, SELF_PLAY_BATCH_SIZE=6, BATCH_SIZE=6)

    def test_share_compiled_requires_same_mesh(self, world):
        mesh = MeshConfig(DP_SIZE=8).build_mesh()
        primary = _make(world, mesh=mesh)
        env, fe, net, mcts_cfg = world
        with pytest.raises(ValueError, match="mesh"):
            SelfPlayEngine(
                env,
                fe,
                net,
                mcts_cfg,
                primary.config,
                seed=8,
                share_compiled=primary,
                mesh=None,
            )

    def test_stream_shares_program_on_same_mesh(self, world):
        mesh = MeshConfig(DP_SIZE=8).build_mesh()
        primary = _make(world, mesh=mesh)
        env, fe, net, mcts_cfg = world
        stream = SelfPlayEngine(
            env,
            fe,
            net,
            mcts_cfg,
            primary.config,
            seed=8,
            share_compiled=primary,
            mesh=mesh,
        )
        assert stream._chunk_fn is primary._chunk_fn
        stream.play_chunk(2)
        assert stream.harvest() is not None

    def test_mesh_sharded_variables_pass_through(self, world):
        # Trainer-sharded (replicated-on-mesh) weights must ride as-is:
        # _place_variables may not reshard them (zero-copy sync path).
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = MeshConfig(DP_SIZE=8).build_mesh()
        engine = _make(world, mesh=mesh)
        rep = NamedSharding(mesh, PartitionSpec())
        placed_in = jax.device_put(engine.net.variables, rep)
        placed_out = engine._place_variables(placed_in, version=0)
        assert placed_out is placed_in

    def test_unsharded_variables_replicated_once_per_version(self, world):
        # A pre-first-sync run must not re-upload the full network
        # every chunk: the replicated copy is memoized per version.
        mesh = MeshConfig(DP_SIZE=8).build_mesh()
        engine = _make(world, mesh=mesh)
        placed_a = engine._place_variables(engine.net.variables, version=0)
        placed_b = engine._place_variables(engine.net.variables, version=0)
        assert placed_b is placed_a
        placed_c = engine._place_variables(engine.net.variables, version=1)
        assert placed_c is not placed_a


class TestSetupWiring:
    def test_setup_shards_when_divisible(self, tmp_path, tiny_env_config,
                                         tiny_model_config, tiny_mcts_config):
        from alphatriangle_tpu.config import PersistenceConfig
        from alphatriangle_tpu.training import setup_training_components

        c = setup_training_components(
            train_config=_train_cfg(RUN_NAME="mc_setup"),
            env_config=tiny_env_config,
            model_config=tiny_model_config,
            mcts_config=tiny_mcts_config,
            persistence_config=PersistenceConfig(
                ROOT_DATA_DIR=str(tmp_path), RUN_NAME="mc_setup"
            ),
            use_tensorboard=False,
        )
        # 8 lanes over the default dp=8 mesh of the 8 virtual devices.
        assert c.self_play.mesh is not None
        assert len(
            {s.device for s in c.self_play.states.done.addressable_shards}
        ) == 8
        c.stats.close()
        c.checkpoints.close()

    def test_setup_falls_back_when_indivisible(
        self, tmp_path, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        from alphatriangle_tpu.config import PersistenceConfig
        from alphatriangle_tpu.training import setup_training_components

        c = setup_training_components(
            train_config=_train_cfg(
                RUN_NAME="mc_setup2", SELF_PLAY_BATCH_SIZE=6
            ),
            env_config=tiny_env_config,
            model_config=tiny_model_config,
            mcts_config=tiny_mcts_config,
            persistence_config=PersistenceConfig(
                ROOT_DATA_DIR=str(tmp_path), RUN_NAME="mc_setup2"
            ),
            use_tensorboard=False,
        )
        assert c.self_play.mesh is None  # warned + single-device
        c.stats.close()
        c.checkpoints.close()


class TestPlacedVariablesMemo:
    def test_streams_share_one_replicated_copy(self, world):
        mesh = MeshConfig(DP_SIZE=8).build_mesh()
        primary = _make(world, mesh=mesh)
        env, fe, net, mcts_cfg = world
        stream = SelfPlayEngine(
            env, fe, net, mcts_cfg, primary.config, seed=9,
            share_compiled=primary, mesh=mesh,
        )
        a = primary._place_variables(net.variables, version=0)
        b = stream._place_variables(net.variables, version=0)
        assert b is a  # one upload for all streams

    def test_memo_dropped_once_weights_are_mesh_sharded(self, world):
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = MeshConfig(DP_SIZE=8).build_mesh()
        engine = _make(world, mesh=mesh)
        engine._place_variables(engine.net.variables, version=0)
        assert engine._placed_owner._placed_variables is not None
        sharded = jax.device_put(
            engine.net.variables, NamedSharding(mesh, PartitionSpec())
        )
        out = engine._place_variables(sharded, version=1)
        assert out is sharded
        # The pre-sync replicated copy must not stay pinned in HBM.
        assert engine._placed_owner._placed_variables is None
