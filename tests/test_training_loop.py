"""Loop/runner integration tests (reference
`tests/training/test_loop_integration.py:328-428` — but with REAL
components instead of mocks, as VERDICT.md #9 demands: a tiny-config
end-to-end run on CPU, then kill + resume)."""

import numpy as np
import pytest

from alphatriangle_tpu.config import (
    PersistenceConfig,
    TrainConfig,
    expected_other_features_dim,
)
from alphatriangle_tpu.config.env_config import EnvConfig
from alphatriangle_tpu.config.mcts_config import AlphaTriangleMCTSConfig
from alphatriangle_tpu.config.model_config import ModelConfig
from alphatriangle_tpu.training import (
    LoopStatus,
    TrainingLoop,
    run_training,
    setup_training_components,
)


@pytest.fixture(scope="module")
def tiny_world_configs(tiny_env_config, tiny_model_config, tiny_mcts_config):
    return tiny_env_config, tiny_model_config, tiny_mcts_config


def make_train_cfg(run_name: str, root: str, **kw) -> TrainConfig:
    base = dict(
        RUN_NAME=run_name,
        AUTO_RESUME_LATEST=False,
        MAX_TRAINING_STEPS=8,
        SELF_PLAY_BATCH_SIZE=4,
        ROLLOUT_CHUNK_MOVES=4,
        BATCH_SIZE=8,
        BUFFER_CAPACITY=2000,
        MIN_BUFFER_SIZE_TO_TRAIN=16,
        USE_PER=True,
        PER_BETA_ANNEAL_STEPS=8,
        N_STEP_RETURNS=2,
        WORKER_UPDATE_FREQ_STEPS=2,
        CHECKPOINT_SAVE_FREQ_STEPS=4,
        MAX_EPISODE_MOVES=30,
        RANDOM_SEED=5,
    )
    base.update(kw)
    return TrainConfig(**base)


def build(tmp_path, cfgs, run_name="loop_run", **kw):
    env_cfg, model_cfg, mcts_cfg = cfgs
    tc = make_train_cfg(run_name, str(tmp_path), **kw)
    pc = PersistenceConfig(ROOT_DATA_DIR=str(tmp_path), RUN_NAME=run_name)
    return setup_training_components(
        train_config=tc,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        persistence_config=pc,
        use_tensorboard=False,
    )


class TestLoop:
    def test_end_to_end_tiny_run(self, tmp_path, tiny_world_configs, monkeypatch):
        # Peak override: CPU has no table entry, so without it the
        # utilization records would carry mfu null (acceptance bar:
        # a smoke run produces a non-null MFU via the override).
        monkeypatch.setenv("ALPHATRIANGLE_PEAK_TFLOPS", "1.0")
        c = build(tmp_path, tiny_world_configs)
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        assert loop.global_step == 8
        assert loop.episodes_played > 0
        # Weight sync cadence honored (every 2 steps -> 4 updates).
        assert loop.weight_updates == 4
        assert c.net.weights_version == 4
        # Metrics flowed through the collector.
        assert c.stats.latest("Loss/total_loss") is not None
        assert c.stats.latest("Buffer/Size") > 0
        # The stats value is a per-tick mean and an iteration can cover
        # several learner steps; the anneal endpoint itself must be exact.
        assert c.stats.latest("PER/Beta") == pytest.approx(1.0, abs=0.1)
        assert c.buffer.beta(loop.global_step) == pytest.approx(1.0)
        # Checkpoints: cadence (step 4) + final (step 8).
        assert c.checkpoints.latest_step() == 8
        steps = sorted(
            int(p.name.split("_")[1])
            for p in c.persistence_config.get_checkpoint_dir().iterdir()
            if p.is_dir()
        )
        assert 4 in steps and 8 in steps
        # Metrics ledger (docs/OBSERVABILITY.md "Ledger"): the run dir
        # holds a parseable metrics.jsonl whose tick records advance
        # and whose utilization records carry a non-null MFU.
        import json

        ledger = c.persistence_config.get_run_base_dir() / "metrics.jsonl"
        assert ledger.exists()
        records = [
            json.loads(line) for line in ledger.read_text().splitlines()
        ]
        ticks = [r for r in records if r["kind"] == "tick"]
        utils = [r for r in records if r["kind"] == "util"]
        assert ticks and utils
        tick_steps = [r["step"] for r in ticks]
        assert tick_steps == sorted(tick_steps)
        assert tick_steps[-1] > tick_steps[0]  # the ledger advanced
        assert any("Loss/total_loss" in r["means"] for r in ticks)
        for r in utils:
            assert r["mfu"] is not None
            assert r["peak_source"] == "env"
            assert r["learner_steps_per_sec"] >= 0
        assert utils[-1]["step"] == 8
        # Health heartbeat records the device identity + utilization.
        health = json.loads(
            (
                c.persistence_config.get_run_base_dir() / "health.json"
            ).read_text()
        )
        assert health["device_kind"] == "cpu"
        assert health["peak_bf16_tflops"] == 1.0
        assert health["utilization"] is not None
        c.stats.close()
        c.checkpoints.close()

    @pytest.mark.slow
    def test_fused_learner_steps_run(self, tmp_path, tiny_world_configs):
        """FUSED_LEARNER_STEPS>1 completes the same run; cadences use
        crossing checks because steps advance by the group size."""
        c = build(
            tmp_path, tiny_world_configs, run_name="fused_run",
            FUSED_LEARNER_STEPS=3,
        )
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        assert loop.global_step == 8
        # Weight sync: one sync per group that crosses a freq-2
        # multiple (group boundaries depend on harvest sizes, so the
        # count is bounded, not exact: 8 steps in groups of <=3 means
        # at least ceil(8/3)=3 boundary checks, at most the per-step 4).
        assert 2 <= loop.weight_updates <= 4
        assert c.net.weights_version == loop.weight_updates
        # Checkpoint crossing (freq 4) + final save at 8.
        steps = sorted(
            int(p.name.split("_")[1])
            for p in c.persistence_config.get_checkpoint_dir().iterdir()
            if p.is_dir()
        )
        assert steps[-1] == 8
        assert any(4 <= s <= 8 for s in steps)
        assert c.stats.latest("Loss/total_loss") is not None
        c.stats.close()
        c.checkpoints.close()

    def test_sp_mesh_end_to_end(self, tmp_path, tiny_world_configs):
        """setup wires ring attention automatically when the mesh has a
        real sp axis; the whole loop (self-play search included) runs
        sequence-sharded on (dp=4, sp=2)."""
        from alphatriangle_tpu.config import MeshConfig

        env_cfg, model_cfg, mcts_cfg = tiny_world_configs
        tc = make_train_cfg("sp_run", str(tmp_path), MAX_TRAINING_STEPS=2)
        pc = PersistenceConfig(ROOT_DATA_DIR=str(tmp_path), RUN_NAME="sp_run")
        c = setup_training_components(
            train_config=tc,
            env_config=env_cfg,
            model_config=model_cfg,
            mcts_config=mcts_cfg,
            mesh_config=MeshConfig(DP_SIZE=4, SP_SIZE=2),
            persistence_config=pc,
            use_tensorboard=False,
        )
        assert c.net.model.attention_fn is not None
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        assert loop.global_step == 2
        assert loop.episodes_played >= 0
        c.stats.close()
        c.checkpoints.close()

    def test_stop_event(self, tmp_path, tiny_world_configs):
        c = build(
            tmp_path, tiny_world_configs, run_name="stop_run",
            MAX_TRAINING_STEPS=1000, BUFFER_CAPACITY=200_000,
            MIN_BUFFER_SIZE_TO_TRAIN=100_000,
        )
        loop = TrainingLoop(c)
        loop.stop_event.set()
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        assert loop.global_step == 0
        c.stats.close()
        c.checkpoints.close()


class TestAsyncLoop:
    def test_async_end_to_end(self, tmp_path, tiny_world_configs):
        """Overlapped mode reaches MAX_TRAINING_STEPS with the same
        cadence guarantees as the synchronous loop."""
        c = build(
            tmp_path, tiny_world_configs, run_name="async_run",
            ASYNC_ROLLOUTS=True, REPLAY_RATIO=1.0,
        )
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        assert loop.global_step == 8
        # Weight sync cadence pinned in async mode too (every 2 -> 4).
        assert loop.weight_updates == 4
        assert c.net.weights_version == 4
        assert c.stats.latest("Loss/total_loss") is not None
        # Async gauges exported.
        assert c.stats.latest("System/Rollout_Queue_Depth") is not None
        # Checkpoints: cadence (step 4) + final (step 8).
        assert c.checkpoints.latest_step() == 8
        # Producer thread shut down cleanly.
        import threading

        assert not any(
            t.name == "self-play-producer" and t.is_alive()
            for t in threading.enumerate()
        )
        c.stats.close()
        c.checkpoints.close()

    @pytest.mark.slow
    def test_multi_stream_producers(self, tmp_path, tiny_world_configs):
        """NUM_SELF_PLAY_WORKERS=2 runs two independent rollout
        streams into the shared queue (the reference's worker fan-out,
        worker_manager.py:39-75, as producer threads)."""
        c = build(
            tmp_path, tiny_world_configs, run_name="multi_stream",
            ASYNC_ROLLOUTS=True, NUM_SELF_PLAY_WORKERS=2,
            MAX_TRAINING_STEPS=4,
        )
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        assert loop.global_step == 4
        assert loop.experiences_added > 0
        assert len(c.buffer) > 0
        c.stats.close()
        c.checkpoints.close()

    @pytest.mark.slow
    def test_all_features_compose(self, tmp_path, tiny_world_configs):
        """Cross-feature integration: Gumbel root search + playout cap
        randomization + fused learner groups + overlapped multi-stream
        + PER, all in one run. Guards against pairwise-tested features
        breaking in combination."""
        env_cfg, model_cfg, mcts_cfg = tiny_world_configs
        pcr_gumbel_cfg = type(mcts_cfg)(
            **{
                **mcts_cfg.model_dump(),
                "root_selection": "gumbel",
                "gumbel_m": 4,
                "fast_simulations": 2,
                "full_search_prob": 0.5,
            }
        )
        c = build(
            tmp_path,
            (env_cfg, model_cfg, pcr_gumbel_cfg),
            run_name="combo_run",
            ASYNC_ROLLOUTS=True,
            NUM_SELF_PLAY_WORKERS=2,
            FUSED_LEARNER_STEPS=2,
            MAX_TRAINING_STEPS=4,
        )
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        assert loop.global_step == 4
        assert loop.experiences_added > 0
        # PCR default drops fast rows: everything in the buffer is
        # policy-trainable.
        sample = c.buffer.sample(4, current_train_step=4)
        assert sample is not None
        assert np.all(sample["batch"]["policy_weight"] == 1.0)
        c.stats.close()
        c.checkpoints.close()

    @pytest.mark.slow
    def test_replay_ratio_gate(self, tmp_path, tiny_world_configs):
        """The learner never consumes more than REPLAY_RATIO allows."""
        ratio = 0.5
        c = build(
            tmp_path, tiny_world_configs, run_name="ratio_run",
            ASYNC_ROLLOUTS=True, REPLAY_RATIO=ratio,
        )
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        consumed = loop._steps_this_run * c.train_config.BATCH_SIZE
        assert consumed <= loop.experiences_added * ratio + 1e-9
        assert loop.experiences_added > 0
        c.stats.close()
        c.checkpoints.close()

    @pytest.mark.slow
    def test_pipeline_disabled_still_completes(
        self, tmp_path, tiny_world_configs
    ):
        """PIPELINE_LEARNER=False restores the strictly serial
        dispatch-then-fetch path."""
        c = build(
            tmp_path, tiny_world_configs, run_name="serial_async",
            ASYNC_ROLLOUTS=True, PIPELINE_LEARNER=False,
            MAX_TRAINING_STEPS=4,
        )
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        assert loop.global_step == 4
        assert not loop._inflight
        c.stats.close()
        c.checkpoints.close()

    @pytest.mark.slow
    def test_pipelined_fused_groups(self, tmp_path, tiny_world_configs):
        """Pipelined pump + fused groups: steps, cadences and the final
        checkpoint all land; nothing is left inflight."""
        c = build(
            tmp_path, tiny_world_configs, run_name="pipelined_run",
            ASYNC_ROLLOUTS=True, FUSED_LEARNER_STEPS=2,
            MAX_TRAINING_STEPS=8,
        )
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        assert loop.global_step == 8
        assert not loop._inflight
        assert c.checkpoints.latest_step() == 8
        assert c.stats.latest("Loss/total_loss") is not None
        c.stats.close()
        c.checkpoints.close()

    def test_async_chunk_autotune(self, tmp_path, tiny_world_configs):
        """One clean chunk measurement sizes async dispatches to the
        ASYNC_CHUNK_SECONDS budget (shared across streams)."""
        c = build(
            tmp_path, tiny_world_configs, run_name="tune_run",
            ASYNC_ROLLOUTS=True, ASYNC_CHUNK_SECONDS=2.0,
        )
        loop = TrainingLoop(c)
        # Not warmed (compile chunk): no tuning.
        loop._maybe_tune_chunk(4, dt=4.0, warmed=False)
        assert loop._tuned_chunk_moves is None
        assert loop._producer_chunk_moves() == 4
        # 4 moves took 4s -> 1s/move -> 2 moves fit the 2s target.
        loop._maybe_tune_chunk(4, dt=4.0, warmed=True)
        assert loop._tuned_chunk_moves == 2
        assert loop._producer_chunk_moves() == 2
        # First accurate measurement wins; later ones don't retune.
        loop._maybe_tune_chunk(2, dt=0.1, warmed=True)
        assert loop._tuned_chunk_moves == 2
        c.stats.close()
        c.checkpoints.close()

    def test_worker_clamp(self, monkeypatch):
        """Stream counts clamp to cores-2 and the per-device budget
        (reference clamps actors to cores-2, setup.py:106-151)."""
        import os as os_mod

        from alphatriangle_tpu.training.setup import (
            clamp_self_play_workers,
        )

        monkeypatch.setattr(os_mod, "cpu_count", lambda: 4)
        assert clamp_self_play_workers(1) == 1
        assert clamp_self_play_workers(2) == 2
        assert clamp_self_play_workers(8) == 2  # cores-2 wins (cpu backend)
        monkeypatch.setattr(os_mod, "cpu_count", lambda: 64)
        import jax as jax_mod

        cap = 4 * jax_mod.local_device_count()
        assert clamp_self_play_workers(10_000) == min(62, cap)
        # Accelerator host: producer threads are dispatch-bound, so a
        # 1-core TPU VM frontend still gets the full per-device budget.
        monkeypatch.setattr(os_mod, "cpu_count", lambda: 1)
        assert clamp_self_play_workers(8) == 1  # cpu backend: host-bound
        monkeypatch.setattr(jax_mod, "default_backend", lambda: "tpu")
        assert clamp_self_play_workers(8) == 8
        assert clamp_self_play_workers(10_000) == cap

    def test_producer_error_surfaces(
        self, tmp_path, tiny_world_configs, monkeypatch
    ):
        """A PERSISTENT producer crash fails the run (after bounded
        respawns) instead of silently starving the learner — the fault
        is patched at class level so respawned engines crash too."""
        from alphatriangle_tpu.rl.self_play import SelfPlayEngine

        c = build(
            tmp_path, tiny_world_configs, run_name="crash_run",
            ASYNC_ROLLOUTS=True,
            # No pre-start auto-tune chunk: it runs play_moves on the
            # consumer thread, outside producer supervision, and the
            # class-level fault would fail the run before any respawn.
            ASYNC_CHUNK_SECONDS=None,
            PRODUCER_MAX_RESTARTS=1,
            PRODUCER_RESTART_BACKOFF_S=0.01,
        )

        def boom(self, num_moves):
            raise RuntimeError("producer crashed")

        monkeypatch.setattr(SelfPlayEngine, "play_moves", boom)
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.ERROR
        # The stream was respawned the configured number of times
        # before the run gave up.
        assert loop.producer_restarts == 1
        c.stats.close()
        c.checkpoints.close()

    @pytest.mark.slow
    def test_producer_respawn_recovers(
        self, tmp_path, tiny_world_configs, monkeypatch
    ):
        """A TRANSIENT producer crash is healed by supervision: the
        stream respawns (fresh engine, shared compiled programs) and
        the run completes (VERDICT r4 item 8; improves on reference
        `worker_manager.py:153-159`, which only removes dead actors)."""
        from alphatriangle_tpu.rl.self_play import SelfPlayEngine

        c = build(
            tmp_path, tiny_world_configs, run_name="respawn_run",
            ASYNC_ROLLOUTS=True,
            ASYNC_CHUNK_SECONDS=None,  # as in test_producer_error_surfaces
            PRODUCER_MAX_RESTARTS=3,
            PRODUCER_RESTART_BACKOFF_S=0.01,
        )

        real = SelfPlayEngine.play_moves
        fails = {"left": 2}

        def flaky(self, num_moves):
            if fails["left"] > 0:
                fails["left"] -= 1
                raise RuntimeError("transient device fault")
            return real(self, num_moves)

        monkeypatch.setattr(SelfPlayEngine, "play_moves", flaky)
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        assert loop.producer_restarts == 2
        assert loop.global_step == 8
        c.stats.close()
        c.checkpoints.close()


class TestRunnerResume:
    @pytest.mark.slow
    def test_run_training_and_resume(self, tmp_path, tiny_world_configs):
        """VERDICT #10 bar: run, 'kill', rerun -> resumes from latest."""
        env_cfg, model_cfg, mcts_cfg = tiny_world_configs
        pc = PersistenceConfig(ROOT_DATA_DIR=str(tmp_path), RUN_NAME="resume_run")
        tc = make_train_cfg("resume_run", str(tmp_path), MAX_TRAINING_STEPS=4)
        rc = run_training(
            train_config=tc,
            env_config=env_cfg,
            model_config=model_cfg,
            mcts_config=mcts_cfg,
            persistence_config=pc,
            use_tensorboard=False,
            log_level="WARNING",
        )
        assert rc == 0

        # Second session, auto-resume on, longer horizon: must continue
        # from step 4, not restart.
        tc2 = make_train_cfg(
            "fresh_name", str(tmp_path),
            MAX_TRAINING_STEPS=6, AUTO_RESUME_LATEST=True,
        )
        pc2 = PersistenceConfig(ROOT_DATA_DIR=str(tmp_path), RUN_NAME="fresh_name")
        rc = run_training(
            train_config=tc2,
            env_config=env_cfg,
            model_config=model_cfg,
            mcts_config=mcts_cfg,
            persistence_config=pc2,
            use_tensorboard=False,
            log_level="WARNING",
        )
        assert rc == 0
        # The resumed run continued in the original run dir.
        from alphatriangle_tpu.stats import CheckpointManager

        mgr = CheckpointManager(
            PersistenceConfig(ROOT_DATA_DIR=str(tmp_path), RUN_NAME="resume_run")
        )
        assert mgr.latest_step() == 6
        # Counters persisted across sessions.
        import json

        meta = json.loads(
            (
                mgr.config.get_checkpoint_dir() / "step_00000006.meta.json"
            ).read_text()
        )
        assert meta["episodes_played"] > 0
