"""CLI tests (reference `cli.py:141-208` override plumbing).

The train command's end-to-end path is covered by
tests/test_training_loop.py through `run_training`; here we pin that
CLI flags land in the right config fields, and that the auxiliary
commands work without a training run.
"""

import json

import pytest

from alphatriangle_tpu import cli


class TestTrainOverrides:
    def _capture(self, monkeypatch):
        captured = {}

        def fake_run_training(**kwargs):
            captured.update(kwargs)
            return 0

        monkeypatch.setattr(
            "alphatriangle_tpu.training.runner.run_training", fake_run_training
        )
        return captured

    def test_flags_map_to_config_fields(self, monkeypatch):
        captured = self._capture(monkeypatch)
        rc = cli.main(
            [
                "train",
                "--run-name", "cli_run",
                "--seed", "123",
                "--max-steps", "20",
                "--self-play-batch", "8",
                "--batch-size", "16",
                "--buffer-capacity", "500",
                "--min-buffer", "32",
                "--rollout-chunk", "2",
                "--no-per",
                "--no-auto-resume",
                "--profile",
                "--root-dir", "/tmp/cli_test_root",
                "--no-tensorboard",
                "--log-level", "WARNING",
            ]
        )
        assert rc == 0
        tc = captured["train_config"]
        assert tc.RUN_NAME == "cli_run"
        assert tc.RANDOM_SEED == 123
        assert tc.MAX_TRAINING_STEPS == 20
        assert tc.SELF_PLAY_BATCH_SIZE == 8
        assert tc.BATCH_SIZE == 16
        assert tc.BUFFER_CAPACITY == 500
        assert tc.MIN_BUFFER_SIZE_TO_TRAIN == 32
        assert tc.ROLLOUT_CHUNK_MOVES == 2
        assert tc.USE_PER is False
        assert tc.AUTO_RESUME_LATEST is False
        assert tc.PROFILE_WORKERS is True
        pc = captured["persistence_config"]
        assert pc.ROOT_DATA_DIR == "/tmp/cli_test_root"
        assert pc.RUN_NAME == "cli_run"
        assert captured["use_tensorboard"] is False
        assert captured["log_level"] == "WARNING"

    def test_search_and_preset_flags(self, monkeypatch):
        captured = self._capture(monkeypatch)
        rc = cli.main(
            [
                "train",
                "--preset", "2",
                "--max-steps", "50",
                "--gumbel",
                "--fast-sims", "16",
                "--full-search-prob", "0.5",
                "--fused-learner-steps", "4",
                "--async-rollouts",
                "--replay-ratio", "2.0",
                "--no-tensorboard",
            ]
        )
        assert rc == 0
        tc = captured["train_config"]
        assert tc.MAX_TRAINING_STEPS == 50
        # Derived schedule lengths re-derive from the overridden horizon.
        assert tc.LR_SCHEDULER_T_MAX == 50
        assert tc.FUSED_LEARNER_STEPS == 4
        assert tc.ASYNC_ROLLOUTS is True
        assert tc.REPLAY_RATIO == 2.0
        mc = captured["mcts_config"]
        assert mc.max_simulations == 200  # preset 2
        assert mc.root_selection == "gumbel"
        assert mc.fast_simulations == 16
        assert mc.full_search_prob == 0.5
        assert captured["model_config"].USE_TRANSFORMER is False

    def test_keep_checkpoints_flag(self, monkeypatch):
        captured = self._capture(monkeypatch)
        rc = cli.main(
            [
                "train",
                "--max-steps", "5",
                "--keep-checkpoints", "99",
                "--no-tensorboard",
            ]
        )
        assert rc == 0
        assert captured["persistence_config"].KEEP_LAST_CHECKPOINTS == 99

    def test_full_search_prob_without_fast_sims_errors(self, monkeypatch):
        self._capture(monkeypatch)
        with pytest.raises(SystemExit):
            cli.main(
                ["train", "--full-search-prob", "0.5", "--no-tensorboard"]
            )

    def test_defaults_leave_config_alone(self, monkeypatch):
        captured = self._capture(monkeypatch)
        assert cli.main(["train", "--run-name", "r"]) == 0
        tc = captured["train_config"]
        assert tc.USE_PER is True
        assert tc.AUTO_RESUME_LATEST is True
        assert captured["persistence_config"] is None

    def test_distributed_flags(self, monkeypatch):
        captured = self._capture(monkeypatch)
        assert (
            cli.main(
                [
                    "train", "--run-name", "r",
                    "--coordinator", "host0:1234",
                    "--num-processes", "2",
                    "--process-id", "1",
                ]
            )
            == 0
        )
        dc = captured["distributed_config"]
        assert dc.ENABLED and dc.COORDINATOR_ADDRESS == "host0:1234"
        assert (dc.NUM_PROCESSES, dc.PROCESS_ID) == (2, 1)

        captured = self._capture(monkeypatch)
        assert cli.main(["train", "--run-name", "r", "--distributed"]) == 0
        dc = captured["distributed_config"]
        assert dc.ENABLED and dc.COORDINATOR_ADDRESS is None

        captured = self._capture(monkeypatch)
        assert cli.main(["train", "--run-name", "r"]) == 0
        assert captured["distributed_config"] is None

    def test_invalid_override_fails_fast(self, monkeypatch):
        self._capture(monkeypatch)
        with pytest.raises(Exception):
            # BATCH_SIZE > BUFFER_CAPACITY violates config validation.
            cli.main(
                ["train", "--batch-size", "64", "--buffer-capacity", "32"]
            )


class TestAuxCommands:
    def test_devices(self, capsys):
        assert cli.main(["devices"]) == 0
        out = capsys.readouterr().out
        assert "backend: cpu" in out

    def test_analyze_phase_timers(self, tmp_path, capsys):
        (tmp_path / "phase_timers.json").write_text(
            json.dumps(
                {
                    "rollout": {
                        "total_seconds": 12.5, "count": 10, "mean_ms": 1250.0
                    },
                    "train": {
                        "total_seconds": 2.0, "count": 40, "mean_ms": 50.0
                    },
                }
            )
        )
        assert cli.main(["analyze", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "rollout" in out and "1250.00" in out
        # Sorted by total time: rollout line precedes train.
        assert out.index("rollout") < out.index("train")

    def test_analyze_missing_dir(self, tmp_path, capsys):
        assert cli.main(["analyze", str(tmp_path / "nope")]) == 1

    def test_play_scripted(self, capsys):
        from alphatriangle_tpu.env.native import native_available

        if not native_available():
            pytest.skip("native engine unavailable")
        assert cli.main(["play", "--script", "v;q"]) == 0
        out = capsys.readouterr().out
        assert "engine=native" in out
        assert "valid placements:" in out

    def test_requires_subcommand(self):
        with pytest.raises(SystemExit):
            cli.main([])
