"""League subsystem (alphatriangle_tpu/league/): pool persistence +
Elo consistency, matchmaking distribution, the trajectory emitter on a
real PolicyService (staleness tags, flight-family pinning), the
staleness guard, and source-agnostic replay-ring ingest of an
externally-built harvest (PER max-priority init, spill interchange)."""

import json
import logging

import jax
import numpy as np
import pytest

from alphatriangle_tpu.env.engine import TriangleEnv
from alphatriangle_tpu.features.core import get_feature_extractor
from alphatriangle_tpu.league import (
    LIVE_ID,
    LeaguePool,
    Matchmaker,
    TrajectoryEmitter,
    apply_staleness_guard,
    fit_elo,
    pairwise_win_fraction,
)
from alphatriangle_tpu.mcts import BatchedMCTS
from alphatriangle_tpu.nn.network import NeuralNetwork
from alphatriangle_tpu.serving import PolicyService, serve_program_name

SLOTS = 6


@pytest.fixture(scope="module")
def league_world(tiny_env_config, tiny_model_config):
    from alphatriangle_tpu.config import AlphaTriangleMCTSConfig

    env = TriangleEnv(tiny_env_config)
    fe = get_feature_extractor(env, tiny_model_config)
    net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=3)
    mcts_cfg = AlphaTriangleMCTSConfig(
        max_simulations=4, max_depth=3, mcts_batch_size=4
    )
    mcts = BatchedMCTS(env, fe, net.model, mcts_cfg, net.support)
    return env, fe, net, mcts


def make_service(league_world, **kw):
    env, fe, net, mcts = league_world
    return PolicyService(env, fe, net, mcts, slots=SLOTS, **kw)


class TestPoolRatings:
    def test_elo_update_direction_and_zero_sum(self, tmp_path):
        pool = LeaguePool(tmp_path / "league.jsonl")
        pool.add_member("a", "/ckpt/a", 1)
        ra, rb = pool.record_result(LIVE_ID, "a", 1.0)
        assert ra > 0 > rb  # winner up, loser down from 0/0
        assert ra + rb == pytest.approx(0.0)  # K-factor update is zero-sum
        # A loss moves them back toward each other.
        ra2, rb2 = pool.record_result(LIVE_ID, "a", 0.0)
        assert ra2 < ra and rb2 > rb

    def test_replay_reconstructs_state_crash_safe(self, tmp_path):
        path = tmp_path / "league.jsonl"
        pool = LeaguePool(path)
        pool.add_member("a", "/ckpt/a", 1)
        pool.add_member("b", "/ckpt/b", 2)
        pool.record_result(LIVE_ID, "a", 0.75)
        pool.record_result(LIVE_ID, "b", 0.25)
        pool.maybe_promote("/ckpt/live", 5, min_games=2, win_rate_gate=0.4)
        # Torn tail: a crashed writer's partial line must not poison
        # the replay (the MetricsLedger read contract).
        with path.open("a") as f:
            f.write('{"kind": "resu')
        fresh = LeaguePool(path)
        assert fresh.member_ids() == pool.member_ids()
        for m in [LIVE_ID, *pool.member_ids()]:
            assert fresh.rating(m) == pytest.approx(pool.rating(m))
        assert fresh.promotions == pool.promotions == 1
        assert fresh.games == pool.games

    def test_ratings_monotonically_consistent_with_results(self, tmp_path):
        """The smoke's gate, as a property: replaying league.jsonl's
        result events through the incremental update reproduces the
        persisted rating events exactly, in order."""
        pool = LeaguePool(tmp_path / "league.jsonl", elo_k=24.0)
        pool.add_member("a", "/ckpt/a", 1)
        pool.add_member("b", "/ckpt/b", 2)
        rng = np.random.default_rng(0)
        for _ in range(10):
            pool.record_result(
                LIVE_ID, ["a", "b"][rng.integers(2)], float(rng.random())
            )
        records = [
            json.loads(line)
            for line in (tmp_path / "league.jsonl").read_text().splitlines()
        ]
        shadow = LeaguePool(tmp_path / "empty.jsonl", elo_k=24.0)
        for r in records:
            if r["kind"] == "result":
                shadow._fold_result(r["a"], r["b"], r["score_a"], persist=False)
            elif r["kind"] == "rating":
                assert shadow.ratings[r["member_id"]] == pytest.approx(
                    r["elo"], abs=1e-3
                )

    def test_promotion_gate_and_window_reset(self, tmp_path):
        pool = LeaguePool(tmp_path / "league.jsonl")
        pool.add_member("a", "/ckpt/a", 1)
        pool.record_result(LIVE_ID, "a", 1.0)
        # Not enough games yet.
        assert pool.maybe_promote("/c", 7, min_games=2, win_rate_gate=0.6) is None
        pool.record_result(LIVE_ID, "a", 0.9)
        member = pool.maybe_promote("/c", 7, min_games=2, win_rate_gate=0.6)
        assert member == "step_00000007"
        assert member in pool.members
        # Promotion seeds the member at the live rating and resets the
        # live evidence window.
        assert pool.rating(member) == pytest.approx(pool.rating(LIVE_ID))
        assert pool.games[LIVE_ID] == 0 and pool.win_rate(LIVE_ID) is None
        # Same step never promotes twice.
        pool.record_result(LIVE_ID, "a", 1.0)
        pool.record_result(LIVE_ID, "a", 1.0)
        assert pool.maybe_promote("/c", 7, min_games=2, win_rate_gate=0.6) is None

    def test_losing_live_net_never_promotes(self, tmp_path):
        pool = LeaguePool(tmp_path / "league.jsonl")
        pool.add_member("a", "/ckpt/a", 1)
        for _ in range(5):
            pool.record_result(LIVE_ID, "a", 0.2)
        assert pool.maybe_promote("/c", 9, min_games=2, win_rate_gate=0.55) is None
        assert pool.promotions == 0

    def test_fit_elo_ranks_dominance(self):
        # a beats b beats c (clipped winrates) -> elo order a > b > c.
        wins = np.array(
            [[0.0, 0.8, 0.9], [0.2, 0.0, 0.8], [0.1, 0.2, 0.0]]
        )
        elo = fit_elo(wins)
        assert elo[0] > elo[1] > elo[2]
        assert elo.mean() == pytest.approx(0.0)

    def test_pairwise_win_fraction_modes(self):
        a, b = [2.0, 0.0], [1.0, 1.0]
        # Paired: (2>1)=win, (0<1)=loss -> 0.5. Cross: 2 beats both,
        # 0 loses both -> 0.5 too; asymmetric sample splits them.
        assert pairwise_win_fraction(a, b, paired=True) == pytest.approx(0.5)
        assert pairwise_win_fraction([3.0], [1.0, 2.0]) == pytest.approx(1.0)
        assert pairwise_win_fraction([], [1.0]) == pytest.approx(0.5)


class TestMatchmaker:
    def _pool(self, tmp_path, ratings):
        pool = LeaguePool(tmp_path / "league.jsonl")
        for i, (mid, elo) in enumerate(ratings.items()):
            pool.add_member(mid, f"/ckpt/{mid}", i, elo=elo)
        return pool

    def test_probabilities_floor_and_proximity(self, tmp_path):
        pool = self._pool(
            tmp_path, {"near": 10.0, "mid": 300.0, "far": 1500.0}
        )
        mm = Matchmaker(pool, temperature=200.0, exploration_floor=0.15)
        probs = mm.probabilities(live_rating=0.0)
        assert sum(probs.values()) == pytest.approx(1.0)
        assert probs["near"] > probs["mid"] > probs["far"]
        # Exploration floor: even the 1500-gap member keeps at least
        # floor/N mass (KataGo-style anti-starvation).
        assert probs["far"] >= 0.15 / 3 - 1e-12

    def test_sampling_histogram_tracks_distribution(self, tmp_path):
        pool = self._pool(tmp_path, {"near": 0.0, "far": 2000.0})
        mm = Matchmaker(pool, temperature=100.0, exploration_floor=0.2, seed=5)
        for _ in range(200):
            mm.sample_opponent(live_rating=0.0)
        mix = mm.opponent_mix()
        assert mix["near"] + mix["far"] == 200
        # near gets ~0.8+0.1, far ~0.1 of the mass.
        assert mix["near"] > mix["far"]
        assert mix["far"] > 0  # the floor keeps it in rotation

    def test_empty_pool_raises(self, tmp_path):
        pool = LeaguePool(tmp_path / "league.jsonl")
        mm = Matchmaker(pool)
        with pytest.raises(RuntimeError, match="empty"):
            mm.sample_opponent()


@pytest.mark.slow
class TestTrajectoryEmitter:
    """Service-driving coverage (builds a net + MCTS, plays real games
    through PolicyService) — excluded from the tier-1 wall-time budget
    like the megastep smokes; `make league-smoke` drives the same
    machinery end to end in CI."""
    def test_harvest_rows_match_play(self, league_world):
        """Drive one session move by move; the drained harvest must
        carry one row per move with the dispatch rewards discounted
        into value targets and normalized policy targets."""
        env, fe, net, mcts = league_world
        service = make_service(league_world)
        emitter = TrajectoryEmitter(env, fe, gamma=0.5)
        service.emitter = emitter
        s = service.open_session(jax.random.PRNGKey(0))
        rewards, moves = [], 0
        for i in range(12):
            service.request_move(s.sid)
            (r,) = service.dispatch(rng=jax.random.PRNGKey(100 + i))
            rewards.append(r["reward"])
            moves += 1
            if r["done"]:
                break
        service.close_session(s.sid)
        result = emitter.drain()
        assert result is not None and result.num_experiences == moves
        assert emitter.episodes_emitted == 1
        assert result.context["source"] == "league"
        # Per-row staleness tags: no reloads happened -> all 0.
        assert result.context["row_versions"] == [0] * moves
        # Discounted MC returns over the exact served rewards.
        expected = np.zeros(moves, dtype=np.float32)
        acc = 0.0
        for t in range(moves - 1, -1, -1):
            acc = rewards[t] + 0.5 * acc
            expected[t] = acc
        np.testing.assert_allclose(result.value_target, expected, rtol=1e-5)
        # Policy targets are distributions in the ingest layout.
        np.testing.assert_allclose(
            result.policy_target.sum(axis=1), 1.0, atol=1e-4
        )
        grids, others = fe.extract_batch(service.sessions.states)
        assert result.grid.shape[1:] == np.asarray(grids).shape[1:]
        assert result.other_features.shape[1] == np.asarray(others).shape[1]

    def test_staleness_tags_follow_weight_reloads(self, league_world):
        env, fe, net, mcts = league_world
        service = make_service(league_world)
        emitter = TrajectoryEmitter(env, fe)
        service.emitter = emitter
        s = service.open_session(jax.random.PRNGKey(1))
        service.request_move(s.sid)
        service.dispatch(rng=jax.random.PRNGKey(0))
        service.reload_weights()  # the hot-reload counter ticks
        service.request_move(s.sid)
        service.dispatch(rng=jax.random.PRNGKey(1))
        service.close_session(s.sid)
        result = emitter.drain()
        assert result.context["row_versions"] == [0, 1]
        assert result.episode_start_versions == [0]

    def test_emitter_off_by_default_and_sink(self, league_world):
        env, fe, net, mcts = league_world
        service = make_service(league_world)
        assert service.emitter is None  # serve-only behavior preserved
        seen = []
        emitter = TrajectoryEmitter(env, fe, sink=seen.append)
        service.emitter = emitter
        s = service.open_session(jax.random.PRNGKey(2))
        service.request_move(s.sid)
        service.dispatch(rng=jax.random.PRNGKey(0))
        service.close_session(s.sid)
        assert len(seen) == 1 and seen[0].num_experiences == 1
        assert emitter.drain() is None  # sink consumed it

    def test_league_play_reuses_serve_flight_family(
        self, league_world, tmp_path
    ):
        """Satellite pin: league games through the service seal
        `serve/b<B>` flight records — `cli doctor` postmortems and
        `cli watch`'s dispatch line work unchanged in flywheel runs."""
        from alphatriangle_tpu.arena import play_service
        from alphatriangle_tpu.telemetry.flight import (
            FlightRecorder,
            read_flight,
        )

        env, fe, net, mcts = league_world
        service = make_service(league_world)
        service.flight = FlightRecorder(tmp_path / "flight.jsonl")
        service.emitter = TrajectoryEmitter(env, fe)
        play_service(service, games=2, max_moves=4, seed=11)
        seals = [
            r
            for r in read_flight(tmp_path / "flight.jsonl")
            if r.get("phase") == "seal"
        ]
        assert seals, "league dispatches must seal flight records"
        assert {r["family"] for r in seals} == {"serve"}
        assert {r["program"] for r in seals} == {serve_program_name(SLOTS)}
        assert all(r["ok"] for r in seals)


class TestStalenessGuard:
    def _harvest(self, versions, n_actions=12):
        from alphatriangle_tpu.rl.types import SelfPlayResult

        n = len(versions)
        policy = np.full((n, n_actions), 1.0 / n_actions, np.float32)
        return SelfPlayResult(
            grid=np.zeros((n, 1, 3, 4), np.float32),
            other_features=np.zeros((n, 5), np.float32),
            policy_target=policy,
            value_target=np.arange(n, dtype=np.float32),
            episode_scores=[1.0],
            episode_lengths=[n],
            episode_start_versions=[versions[0]],
            num_episodes=1,
            context={"source": "league", "row_versions": list(versions)},
        )

    def test_fresh_rows_pass_untouched(self):
        result = self._harvest([5, 5, 6])
        kept, dropped = apply_staleness_guard(result, clock=6, window=2)
        assert kept is result and dropped == 0

    def test_stale_rows_drop_and_count(self, caplog):
        import alphatriangle_tpu.league.emitter as emitter_mod

        emitter_mod._stale_warned = False
        result = self._harvest([0, 1, 7, 8])
        with caplog.at_level(logging.WARNING):
            kept, dropped = apply_staleness_guard(result, clock=9, window=3)
        assert dropped == 2
        assert kept.num_experiences == 2
        # Only the fresh rows' tags and value targets survive, aligned.
        assert kept.context["row_versions"] == [7, 8]
        np.testing.assert_array_equal(kept.value_target, [2.0, 3.0])
        assert any("Staleness guard" in r.message for r in caplog.records)
        # Warn-once: a second guarded drop stays quiet.
        caplog.clear()
        with caplog.at_level(logging.WARNING):
            apply_staleness_guard(self._harvest([0]), clock=9, window=3)
        assert not any("Staleness guard" in r.message for r in caplog.records)

    def test_all_stale_returns_none(self):
        kept, dropped = apply_staleness_guard(
            self._harvest([0, 0]), clock=10, window=1
        )
        assert kept is None and dropped == 2

    def test_window_off_and_none_passthrough(self):
        result = self._harvest([0])
        assert apply_staleness_guard(result, 100, -1) == (result, 0)
        assert apply_staleness_guard(None, 100, 4) == (None, 0)


@pytest.mark.slow
class TestSourceAgnosticIngest:
    """Satellite: the replay ring ingests an externally-built (league)
    harvest exactly like a self-play one — PER max-priority init,
    validation, and checkpoint/spill interchange with self-play runs.
    Service-driving (slow-marked); the pure-scatter case below stays
    in tier-1."""

    def _league_harvest(self, league_world):
        env, fe, net, mcts = league_world
        service = make_service(league_world)
        emitter = TrajectoryEmitter(env, fe)
        service.emitter = emitter
        from alphatriangle_tpu.arena import play_service

        play_service(service, games=3, max_moves=5, seed=21)
        result = emitter.drain()
        assert result is not None and result.num_experiences >= 3
        return result

    def test_device_ring_ingest_with_per_max_priority(
        self, league_world, tiny_train_config
    ):
        from alphatriangle_tpu.rl.device_buffer import DeviceReplayBuffer

        result = self._league_harvest(league_world)
        cfg = tiny_train_config.model_copy(
            update={
                "BUFFER_CAPACITY": 64,
                "USE_PER": True,
                "PER_BETA_ANNEAL_STEPS": 10,
            }
        )
        buf = DeviceReplayBuffer(
            cfg,
            grid_shape=result.grid.shape[1:],
            other_dim=result.other_features.shape[1],
            action_dim=result.policy_target.shape[1],
        )
        # Pre-load self-play-like rows and depress their priorities so
        # max-priority init on the league rows is observable.
        rng = np.random.default_rng(3)
        pol = rng.random((8, result.policy_target.shape[1])).astype(np.float32)
        pol /= pol.sum(axis=1, keepdims=True)
        first = buf.add_dense(
            rng.integers(-1, 2, (8, *result.grid.shape[1:])).astype(np.float32),
            rng.random((8, result.other_features.shape[1]), dtype=np.float32),
            pol,
            rng.normal(size=8).astype(np.float32),
        )
        buf.update_priorities(np.asarray(first), np.full(8, 1e-3, np.float32))
        max_p = buf.tree.max_priority
        slots = buf.add_dense(
            result.grid,
            result.other_features,
            result.policy_target,
            result.value_target,
            policy_weight=result.policy_weight,
        )
        assert len(slots) == result.num_experiences
        prios = np.asarray(buf.get_state()["priorities"])
        for s in np.asarray(slots):
            assert prios[int(s)] == pytest.approx(max_p)

    def test_spill_interchange_with_self_play_host_buffer(
        self, league_world, tiny_train_config
    ):
        """A ring fed by league rows spills/restores interchangeably
        with the host buffer a pure self-play run would write."""
        from alphatriangle_tpu.rl.buffer import ExperienceBuffer
        from alphatriangle_tpu.rl.device_buffer import DeviceReplayBuffer

        result = self._league_harvest(league_world)
        cfg = tiny_train_config.model_copy(
            update={"BUFFER_CAPACITY": 32, "USE_PER": True,
                    "PER_BETA_ANNEAL_STEPS": 10}
        )
        kw = dict(
            grid_shape=result.grid.shape[1:],
            other_dim=result.other_features.shape[1],
            action_dim=result.policy_target.shape[1],
        )
        dev = DeviceReplayBuffer(cfg, **kw)
        dev.add_dense(
            result.grid,
            result.other_features,
            result.policy_target,
            result.value_target,
        )
        state = dev.get_state()
        host = ExperienceBuffer(cfg, action_dim=kw["action_dim"])
        host.set_state(state)
        assert len(host) == len(dev)
        rt = DeviceReplayBuffer(cfg, **kw)
        rt.set_state(host.get_state())
        for k, v in dev.get_state()["storage"].items():
            np.testing.assert_array_equal(
                np.asarray(v), np.asarray(rt.get_state()["storage"][k]), k
            )

class TestRingScatterExternalBlock:
    def test_ring_scatter_with_positions_on_external_block(self):
        """The pure scatter itself is source-agnostic: an
        externally-built block (league layout, one invalid row) lands
        with per-row positions + keep mask for PER max-priority init.
        Pure jitted numpy — cheap, so it stays in tier-1."""
        import jax.numpy as jnp

        from alphatriangle_tpu.rl.device_buffer import ring_scatter

        cap, n, a = 8, 5, 12
        storage = {
            "grid": jnp.zeros((cap + 1, 1, 3, 4), jnp.int8),
            "other_features": jnp.zeros((cap + 1, 5)),
            "policy_target": jnp.zeros((cap + 1, a)),
            "value_target": jnp.zeros(cap + 1),
            "policy_weight": jnp.zeros(cap + 1),
        }
        policy = jnp.full((n, a), 1.0 / a)
        policy = policy.at[2].set(0.0)  # not a distribution -> trash slot
        block = {
            "grid": jnp.ones((n, 1, 3, 4)),
            "other": jnp.ones((n, 5)),
            "policy": policy,
            "ret": jnp.arange(n, dtype=jnp.float32),
            "pw": jnp.ones(n),
            "mask": jnp.ones(n, dtype=bool),
        }
        new_storage, cursor, written, positions, keep = ring_scatter(
            storage, jnp.int32(0), (block,), cap, with_positions=True
        )
        assert int(written) == 4 and int(cursor) == 4
        keep = np.asarray(keep)
        assert keep.tolist() == [True, True, False, True, True]
        pos = np.asarray(positions)
        # Valid rows land in ring slots 0..3; the invalid row's
        # position points at the trash slot (index cap).
        assert pos[keep].tolist() == [0, 1, 2, 3]
        assert pos[2] == cap
        np.testing.assert_array_equal(
            np.asarray(new_storage["value_target"])[:4], [0.0, 1.0, 3.0, 4.0]
        )
