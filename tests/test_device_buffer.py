"""Device-resident replay ring (rl/device_buffer.py).

Covers: host/device ingest parity (content, order, slots, ring wrap),
on-device row validation, PER bookkeeping via ingest counts,
sample+gather training equivalence against the host path, snapshot
round trips in both directions, and the training loop running end to
end in device-replay mode (sync + overlapped).
"""

import numpy as np
import pytest

from alphatriangle_tpu.rl.buffer import ExperienceBuffer
from alphatriangle_tpu.rl.device_buffer import DeviceReplayBuffer


GRID_SHAPE = (1, 3, 4)
OTHER_DIM = 5
ACTION_DIM = 12


def _cfg(tiny_train_config, **updates):
    return tiny_train_config.model_copy(update=updates)


def _dev_buffer(cfg, seed=0):
    return DeviceReplayBuffer(
        cfg,
        grid_shape=GRID_SHAPE,
        other_dim=OTHER_DIM,
        action_dim=ACTION_DIM,
        seed=seed,
    )


def _rows(n, rng, value=None):
    """n valid experience rows (grids in {-1,0,1}, normalized policy)."""
    grid = rng.integers(-1, 2, size=(n, *GRID_SHAPE)).astype(np.float32)
    other = rng.random((n, OTHER_DIM), dtype=np.float32)
    policy = rng.random((n, ACTION_DIM), dtype=np.float32) + 0.01
    policy /= policy.sum(axis=1, keepdims=True)
    val = (
        np.full(n, value, np.float32)
        if value is not None
        else rng.normal(size=n).astype(np.float32)
    )
    pw = (rng.random(n) > 0.3).astype(np.float32)
    return grid, other, policy, val, pw


class TestIngestParity:
    def test_add_dense_matches_host_buffer(self, tiny_train_config):
        cfg = _cfg(tiny_train_config, BUFFER_CAPACITY=32, USE_PER=True,
                   PER_BETA_ANNEAL_STEPS=100)
        rng = np.random.default_rng(1)
        host = ExperienceBuffer(cfg, action_dim=ACTION_DIM)
        dev = _dev_buffer(cfg)
        for n in (5, 11, 7):
            rows = _rows(n, rng)
            s_host = host.add_dense(*rows[:4], policy_weight=rows[4])
            s_dev = dev.add_dense(*rows[:4], policy_weight=rows[4])
            np.testing.assert_array_equal(s_host, s_dev)
        assert len(host) == len(dev)
        hs, ds = host.get_state(), dev.get_state()
        assert hs["pos"] == ds["pos"] and hs["size"] == ds["size"]
        for k in hs["storage"]:
            np.testing.assert_array_equal(
                hs["storage"][k], ds["storage"][k], err_msg=k
            )
        np.testing.assert_allclose(hs["priorities"], ds["priorities"])

    def test_ring_wraparound(self, tiny_train_config):
        cfg = _cfg(tiny_train_config, BUFFER_CAPACITY=8, USE_PER=False)
        rng = np.random.default_rng(2)
        host = ExperienceBuffer(cfg, action_dim=ACTION_DIM)
        dev = _dev_buffer(cfg)
        for n in (6, 5, 4):  # wraps twice
            rows = _rows(n, rng)
            host.add_dense(*rows[:4], policy_weight=rows[4])
            dev.add_dense(*rows[:4], policy_weight=rows[4])
        assert len(dev) == 8 and dev._pos == host._pos
        hs, ds = host.get_state(), dev.get_state()
        for k in hs["storage"]:
            np.testing.assert_array_equal(
                hs["storage"][k], ds["storage"][k], err_msg=k
            )

    def test_single_ingest_larger_than_capacity(self, tiny_train_config):
        """One add of 20 rows into an 8-slot ring keeps the newest 8 in
        the same slots the host ring's last-write-wins produces."""
        cfg = _cfg(tiny_train_config, BUFFER_CAPACITY=8, USE_PER=False)
        rng = np.random.default_rng(7)
        host = ExperienceBuffer(cfg, action_dim=ACTION_DIM)
        dev = _dev_buffer(cfg)
        rows = _rows(20, rng)
        host.add_dense(*rows[:4], policy_weight=rows[4])
        dev.add_dense(*rows[:4], policy_weight=rows[4])
        assert len(dev) == 8 and dev._pos == host._pos == 20 % 8
        hs, ds = host.get_state(), dev.get_state()
        for k in hs["storage"]:
            np.testing.assert_array_equal(
                hs["storage"][k], ds["storage"][k], err_msg=k
            )

    def test_invalid_rows_dropped(self, tiny_train_config):
        cfg = _cfg(tiny_train_config, BUFFER_CAPACITY=16, USE_PER=False)
        rng = np.random.default_rng(3)
        dev = _dev_buffer(cfg)
        grid, other, policy, val, pw = _rows(6, rng)
        grid[1, 0, 0, 0] = np.nan  # non-finite feature
        policy[3] *= 3.0  # not a distribution
        val[4] = np.inf  # non-finite return
        slots = dev.add_dense(grid, other, policy, val, policy_weight=pw)
        assert len(dev) == 3 and len(slots) == 3
        state = dev.get_state()
        keep = [0, 2, 5]
        np.testing.assert_array_equal(
            state["storage"]["grid"], grid[keep].astype(np.int8)
        )
        np.testing.assert_allclose(
            state["storage"]["value_target"], val[keep]
        )

    def test_sample_returns_indices_only(self, tiny_train_config):
        cfg = _cfg(
            tiny_train_config,
            BUFFER_CAPACITY=32,
            MIN_BUFFER_SIZE_TO_TRAIN=8,
            USE_PER=True,
            PER_BETA_ANNEAL_STEPS=10,
        )
        rng = np.random.default_rng(4)
        dev = _dev_buffer(cfg)
        assert dev.sample(4, current_train_step=0) is None  # not ready
        rows = _rows(12, rng)
        dev.add_dense(*rows[:4], policy_weight=rows[4])
        s = dev.sample(4, current_train_step=0)
        assert s is not None and "batch" not in s
        assert s["indices"].shape == (4,) and (s["indices"] < 12).all()
        assert s["weights"].shape == (4,) and (s["weights"] <= 1.0).all()
        # PER priority updates shift sampling mass (inherited machinery).
        dev.update_priorities(np.array([0]), np.array([100.0]))
        hits = sum(
            0 in dev.sample(4, current_train_step=1)["indices"]
            for _ in range(50)
        )
        assert hits > 25


class TestTrainEquivalence:
    def test_train_steps_from_matches_host_path(
        self, tiny_env_config, tiny_model_config, tiny_train_config
    ):
        """K fused device-gathered steps == K fused host-staged steps
        on the same rows (identical final params + per-step outputs)."""
        import jax

        from alphatriangle_tpu.nn.network import NeuralNetwork
        from alphatriangle_tpu.rl.trainer import Trainer

        cfg = _cfg(
            tiny_train_config,
            BUFFER_CAPACITY=64,
            MIN_BUFFER_SIZE_TO_TRAIN=8,
            USE_PER=False,
            FUSED_LEARNER_STEPS=3,
        )
        rng = np.random.default_rng(5)
        grid_shape = (
            tiny_model_config.GRID_INPUT_CHANNELS,
            tiny_env_config.ROWS,
            tiny_env_config.COLS,
        )
        other_dim = tiny_model_config.OTHER_NN_INPUT_FEATURES_DIM
        action_dim = tiny_env_config.action_dim
        dev = DeviceReplayBuffer(
            cfg,
            grid_shape=grid_shape,
            other_dim=other_dim,
            action_dim=action_dim,
        )
        n = 32
        grid = rng.integers(-1, 2, size=(n, *grid_shape)).astype(np.float32)
        other = rng.random((n, other_dim), dtype=np.float32)
        policy = rng.random((n, action_dim), dtype=np.float32) + 0.01
        policy /= policy.sum(axis=1, keepdims=True)
        val = rng.normal(size=n).astype(np.float32)
        pw = (rng.random(n) > 0.5).astype(np.float32)
        dev.add_dense(grid, other, policy, val, policy_weight=pw)

        samples = [dev.sample(cfg.BATCH_SIZE) for _ in range(3)]
        host_batches = []
        for s in samples:
            i = s["indices"]
            host_batches.append(
                {
                    "grid": grid[i].astype(np.int8).astype(np.float32),
                    "other_features": other[i],
                    "policy_target": policy[i],
                    "value_target": val[i],
                    "policy_weight": pw[i],
                    "weights": s["weights"],
                }
            )

        net_a = NeuralNetwork(tiny_model_config, tiny_env_config, seed=7)
        net_b = NeuralNetwork(tiny_model_config, tiny_env_config, seed=7)
        tr_a = Trainer(net_a, cfg)
        tr_b = Trainer(net_b, cfg)
        outs_host = tr_a.train_steps(host_batches)
        outs_dev = tr_b.train_steps_from(dev, samples)
        assert len(outs_host) == len(outs_dev) == 3
        for (m_h, td_h), (m_d, td_d) in zip(outs_host, outs_dev):
            for key in m_h:
                np.testing.assert_allclose(
                    m_h[key], m_d[key], rtol=1e-5, err_msg=key
                )
            np.testing.assert_allclose(td_h, td_d, rtol=1e-5)
        pa = jax.device_get(tr_a.state.params)
        pb = jax.device_get(tr_b.state.params)
        jax.tree_util.tree_map(
            lambda x, y: np.testing.assert_allclose(x, y, rtol=1e-5), pa, pb
        )
        assert tr_a.global_step == tr_b.global_step == 3

    def test_pipelined_begin_finish(
        self, tiny_env_config, tiny_model_config, tiny_train_config
    ):
        from alphatriangle_tpu.nn.network import NeuralNetwork
        from alphatriangle_tpu.rl.trainer import Trainer

        cfg = _cfg(
            tiny_train_config,
            BUFFER_CAPACITY=64,
            MIN_BUFFER_SIZE_TO_TRAIN=8,
            USE_PER=False,
        )
        rng = np.random.default_rng(6)
        grid_shape = (
            tiny_model_config.GRID_INPUT_CHANNELS,
            tiny_env_config.ROWS,
            tiny_env_config.COLS,
        )
        dev = DeviceReplayBuffer(
            cfg,
            grid_shape=grid_shape,
            other_dim=tiny_model_config.OTHER_NN_INPUT_FEATURES_DIM,
            action_dim=tiny_env_config.action_dim,
        )
        n = 16
        grid = rng.integers(-1, 2, size=(n, *grid_shape)).astype(np.float32)
        other = rng.random(
            (n, tiny_model_config.OTHER_NN_INPUT_FEATURES_DIM),
            dtype=np.float32,
        )
        policy = rng.random((n, tiny_env_config.action_dim), dtype=np.float32)
        policy /= policy.sum(axis=1, keepdims=True)
        dev.add_dense(grid, other, policy, np.zeros(n, np.float32))
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=8)
        tr = Trainer(net, cfg)
        # Two groups in flight (K=2 then K=1), fetched oldest-first.
        h1 = tr.train_steps_from_begin(dev, [dev.sample(4), dev.sample(4)])
        h2 = tr.train_steps_from_begin(dev, [dev.sample(4)])
        assert tr.train_steps_from_begin(dev, []) is None
        outs1 = tr.train_steps_finish(h1)
        outs2 = tr.train_steps_finish(h2)
        assert len(outs1) == 2 and len(outs2) == 1
        assert outs1[0][1].shape == (4,)  # per-step TD rows
        assert tr.global_step == 3
        lrs = [m["learning_rate"] for m, _ in outs1 + outs2]
        assert lrs == [float(tr.schedule(i)) for i in (1, 2, 3)]


class TestSelfPlayIntegration:
    def test_play_chunk_device_matches_host_harvest(
        self,
        tiny_env_config,
        tiny_model_config,
        tiny_train_config,
        tiny_mcts_config,
    ):
        """Same seed, two engines: the device payload ingested into the
        ring equals the host harvest's rows, and stats agree."""
        from alphatriangle_tpu.env.engine import TriangleEnv
        from alphatriangle_tpu.features.core import get_feature_extractor
        from alphatriangle_tpu.nn.network import NeuralNetwork
        from alphatriangle_tpu.rl.self_play import SelfPlayEngine

        cfg = _cfg(tiny_train_config, BUFFER_CAPACITY=512, USE_PER=False)
        env = TriangleEnv(tiny_env_config)
        extractor = get_feature_extractor(env, tiny_model_config)
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=3)
        mk = lambda: SelfPlayEngine(  # noqa: E731
            env, extractor, net, tiny_mcts_config, cfg, seed=11
        )
        host_eng, dev_eng = mk(), mk()
        result = host_eng.play_moves(8)
        dev = DeviceReplayBuffer(
            cfg,
            grid_shape=(
                tiny_model_config.GRID_INPUT_CHANNELS,
                tiny_env_config.ROWS,
                tiny_env_config.COLS,
            ),
            other_dim=extractor.other_dim,
            action_dim=tiny_env_config.action_dim,
        )
        stats, payload = dev_eng.play_moves_device(8)
        added = dev.ingest_payload(payload)
        assert added == result.num_experiences == len(dev)
        assert stats.num_episodes == result.num_episodes
        assert stats.episode_scores == result.episode_scores
        assert stats.total_simulations == result.total_simulations
        assert stats.num_experiences == 0  # stats-only harvest
        state = dev.get_state()
        np.testing.assert_array_equal(
            state["storage"]["grid"], result.grid.astype(np.int8)
        )
        np.testing.assert_allclose(
            state["storage"]["policy_target"], result.policy_target,
            rtol=1e-6,
        )
        np.testing.assert_allclose(
            state["storage"]["value_target"], result.value_target, rtol=1e-6
        )
        np.testing.assert_array_equal(
            state["storage"]["policy_weight"], result.policy_weight
        )


class TestSnapshotRoundTrip:
    @pytest.mark.parametrize("direction", ["dev_to_host", "host_to_dev"])
    def test_round_trip(self, tiny_train_config, direction):
        cfg = _cfg(
            tiny_train_config,
            BUFFER_CAPACITY=16,
            USE_PER=True,
            PER_BETA_ANNEAL_STEPS=50,
        )
        rng = np.random.default_rng(9)
        src: ExperienceBuffer = (
            _dev_buffer(cfg) if direction == "dev_to_host"
            else ExperienceBuffer(cfg, action_dim=ACTION_DIM)
        )
        rows = _rows(20, rng)  # wraps the 16-slot ring
        src.add_dense(*rows[:4], policy_weight=rows[4])
        src.update_priorities(np.arange(4), np.array([1.0, 2.0, 3.0, 4.0]))
        snap = src.get_state()
        dst: ExperienceBuffer = (
            ExperienceBuffer(cfg, action_dim=ACTION_DIM)
            if direction == "dev_to_host"
            else _dev_buffer(cfg)
        )
        dst.set_state(snap)
        assert len(dst) == len(src) == 16
        a, b = src.get_state(), dst.get_state()
        # set_state re-orders slots chronologically; compare as sets of
        # rows via lexicographic sort on the value column.
        oa, ob = np.argsort(a["storage"]["value_target"]), np.argsort(
            b["storage"]["value_target"]
        )
        for k in a["storage"]:
            np.testing.assert_allclose(
                a["storage"][k][oa].astype(np.float32),
                b["storage"][k][ob].astype(np.float32),
                err_msg=k,
            )
        s = dst.sample(4, current_train_step=0)
        assert s is not None


class TestLoopIntegration:
    @pytest.mark.parametrize("async_mode", [False, True])
    def test_training_loop_device_replay(
        self,
        tmp_path,
        tiny_env_config,
        tiny_model_config,
        tiny_train_config,
        tiny_mcts_config,
        async_mode,
    ):
        from alphatriangle_tpu.config import MeshConfig, PersistenceConfig
        from alphatriangle_tpu.training.loop import LoopStatus, TrainingLoop
        from alphatriangle_tpu.training.setup import setup_training_components

        cfg = _cfg(
            tiny_train_config,
            DEVICE_REPLAY="on",
            ASYNC_ROLLOUTS=async_mode,
            ASYNC_CHUNK_SECONDS=None,
            FUSED_LEARNER_STEPS=2,
            MAX_TRAINING_STEPS=6,
            MIN_BUFFER_SIZE_TO_TRAIN=8,
            BUFFER_CAPACITY=256,
            CHECKPOINT_SAVE_FREQ_STEPS=4,
            RUN_NAME=f"pytest_devreplay_{async_mode}",
        )
        comps = setup_training_components(
            train_config=cfg,
            env_config=tiny_env_config,
            model_config=tiny_model_config,
            mcts_config=tiny_mcts_config,
            # The device ring lives on ONE chip; pin a 1-device mesh
            # (the test harness exposes 8 virtual CPU devices).
            mesh_config=MeshConfig(DP_SIZE=1),
            persistence_config=PersistenceConfig(
                ROOT_DATA_DIR=str(tmp_path), RUN_NAME=cfg.RUN_NAME
            ),
            use_tensorboard=False,
        )
        assert getattr(comps.buffer, "is_device", False)
        loop = TrainingLoop(comps)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        assert loop.global_step == 6
        assert loop.experiences_added > 0
        ckpts = list(tmp_path.rglob("step_*"))
        assert ckpts, "no checkpoint written"
