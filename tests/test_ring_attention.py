"""Sequence-parallel attention: equivalence with dense attention.

Pins forward AND gradient equality of ring / Ulysses attention against
a plain softmax(QK^T)V reference on the virtual 8-device CPU mesh —
the correctness contract that lets the model swap `attention_fn`
without changing results (`parallel/ring_attention.py`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphatriangle_tpu.config import MeshConfig
from alphatriangle_tpu.parallel import make_sp_attention

B, S, H, D = 4, 32, 4, 16


def dense_attention(q, k, v):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(q.shape[-1])
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(7)
    return tuple(
        jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
        for _ in range(3)
    )


@pytest.fixture(scope="module", params=["sp8", "dp2_sp4"])
def sp_mesh(request):
    if request.param == "sp8":
        return MeshConfig(DP_SIZE=1, SP_SIZE=8).build_mesh()
    return MeshConfig(DP_SIZE=2, SP_SIZE=4).build_mesh()


def _skip_if_invalid(sp_mesh, kind):
    if kind == "ulysses" and H % sp_mesh.shape["sp"]:
        pytest.skip("ulysses needs heads % sp == 0")


class TestEquivalence:
    @pytest.mark.parametrize("kind", ["ring", "ulysses"])
    def test_forward_matches_dense(self, qkv, sp_mesh, kind):
        _skip_if_invalid(sp_mesh, kind)
        q, k, v = qkv
        fn = make_sp_attention(sp_mesh, kind=kind)
        out = fn(q, k, v)
        expected = dense_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    @pytest.mark.parametrize("kind", ["ring", "ulysses"])
    def test_gradients_match_dense(self, qkv, sp_mesh, kind):
        _skip_if_invalid(sp_mesh, kind)
        q, k, v = qkv
        fn = make_sp_attention(sp_mesh, kind=kind)
        w = jnp.asarray(
            np.random.default_rng(3).standard_normal((B, S, H, D)),
            jnp.float32,
        )

        def loss(attn):
            def inner(q, k, v):
                return (attn(q, k, v) * w).sum()

            return inner

        g_sp = jax.grad(loss(fn), argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(loss(dense_attention), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_sp, g_dense):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5
            )

    def test_under_jit_with_sharded_inputs(self, qkv, sp_mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        q, k, v = qkv
        sh = NamedSharding(sp_mesh, P("dp", "sp"))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        fn = jax.jit(make_sp_attention(sp_mesh, kind="ring"))
        out = fn(qs, ks, vs)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(dense_attention(q, k, v)),
            rtol=2e-5,
            atol=2e-5,
        )

    def test_bad_kind_raises(self, sp_mesh):
        with pytest.raises(ValueError, match="kind"):
            make_sp_attention(sp_mesh, kind="nope")

    def test_dropout_rejected(self, qkv, sp_mesh):
        q, k, v = qkv
        fn = make_sp_attention(sp_mesh, kind="ring")
        with pytest.raises(NotImplementedError):
            fn(q, k, v, dropout_rate=0.1, deterministic=False)

    def test_ulysses_head_divisibility_error(self, qkv):
        mesh = MeshConfig(DP_SIZE=1, SP_SIZE=8).build_mesh()
        q, k, v = qkv  # H=4 < sp=8
        fn = make_sp_attention(mesh, kind="ulysses")
        with pytest.raises(ValueError, match="head count"):
            fn(q, k, v)


class TestModelIntegration:
    def test_model_with_sp_attention_matches_dense(
        self, tiny_model_config, tiny_env_config
    ):
        """Same params, same inputs: the transformer with a
        sequence-sharded attention_fn must reproduce the dense model's
        logits exactly (eval mode)."""
        from alphatriangle_tpu.nn.model import AlphaTriangleNet

        # 3x4 board -> 12 tokens; sp=2 divides it; heads=2 divides for
        # ulysses too.
        mesh = MeshConfig(DP_SIZE=4, SP_SIZE=2).build_mesh()
        cfg = tiny_model_config
        dense = AlphaTriangleNet(cfg, tiny_env_config.action_dim)
        rng = np.random.default_rng(11)
        grid = jnp.asarray(
            rng.integers(-1, 2, size=(4, 1, 3, 4)), jnp.float32
        )
        other = jnp.asarray(
            rng.random((4, cfg.OTHER_NN_INPUT_FEATURES_DIM)), jnp.float32
        )
        variables = dense.init(jax.random.PRNGKey(0), grid, other)
        p_dense, v_dense = dense.apply(variables, grid, other, train=False)

        for kind in ["ring", "ulysses"]:
            sp_net = AlphaTriangleNet(
                cfg,
                tiny_env_config.action_dim,
                attention_fn=make_sp_attention(mesh, kind=kind),
            )
            p_sp, v_sp = sp_net.apply(variables, grid, other, train=False)
            np.testing.assert_allclose(
                np.asarray(p_sp), np.asarray(p_dense), rtol=2e-5, atol=2e-5
            )
            np.testing.assert_allclose(
                np.asarray(v_sp), np.asarray(v_dense), rtol=2e-5, atol=2e-5
            )
