"""Live-run console tests (`cli watch` + stats/watch.py) — the
run-dir-tail observability replacing the reference's Ray dashboard
path (`alphatriangle/cli.py:301-326`)."""

import json
import time

from alphatriangle_tpu import cli
from alphatriangle_tpu.stats.watch import (
    WatchState,
    find_latest_run_dir,
    health_line,
    render_frame,
    tail_live_metrics,
)


def tick(step, t, **means):
    return json.dumps({"step": step, "time": t, "means": means})


class TestWatchState:
    def test_rates_from_window(self):
        s = WatchState()
        t0 = time.time() - 60
        assert s.fold_line(
            tick(0, t0, **{"Progress/Episodes_Played": 100.0})
        )
        assert s.fold_line(
            tick(30, t0 + 60, **{"Progress/Episodes_Played": 220.0})
        )
        # 30 steps / 60 s; 120 episodes / 60 s -> 7200 games/h.
        assert abs(s.steps_per_sec - 0.5) < 1e-6
        assert abs(s.games_per_hour - 7200.0) < 1e-3
        assert s.latest_step == 30

    def test_junk_lines_ignored(self):
        s = WatchState()
        assert not s.fold_line("")
        assert not s.fold_line("{torn json")
        assert not s.fold_line('{"no_step": 1}')
        assert s.latest == {}

    def test_single_tick_has_no_rates(self):
        s = WatchState()
        s.fold_line(tick(5, time.time(), **{"Buffer/Size": 10.0}))
        assert s.steps_per_sec is None
        assert s.games_per_hour is None
        assert s.latest["Buffer/Size"] == 10.0

    def test_render_frame_shows_vitals(self):
        s = WatchState()
        t0 = time.time() - 10
        s.fold_line(
            tick(
                0,
                t0,
                **{
                    "Progress/Episodes_Played": 0.0,
                    "Loss/total_loss": 2.5,
                },
            )
        )
        s.fold_line(
            tick(
                20,
                t0 + 10,
                **{
                    "Progress/Episodes_Played": 50.0,
                    "Loss/total_loss": 1.25,
                    "System/Replay_Ratio_Actual": 0.97,
                },
            )
        )
        frame = render_frame(s, "my_run")
        assert "my_run" in frame and "step 20" in frame
        assert "1.2500" in frame  # loss
        assert "0.970" in frame  # replay ratio
        assert "games/h" in frame and "steps/s" in frame


class TestTail:
    def test_incremental_tail_and_torn_line(self, tmp_path):
        live = tmp_path / "live_metrics.jsonl"
        s = WatchState()
        assert tail_live_metrics(live, s, 0) == 0  # not yet created
        live.write_text(tick(1, 1000.0, **{"Buffer/Size": 1.0}) + "\n")
        off = tail_live_metrics(live, s, 0)
        assert s.latest_step == 1 and off == live.stat().st_size
        # Torn write: no newline yet -> held back, then folded.
        with live.open("a") as f:
            f.write(tick(2, 1001.0, **{"Buffer/Size": 2.0})[:10])
        assert tail_live_metrics(live, s, off) == off
        assert s.latest_step == 1
        with live.open("a") as f:
            f.write(tick(2, 1001.0, **{"Buffer/Size": 2.0})[10:] + "\n")
        off = tail_live_metrics(live, s, off)
        assert s.latest_step == 2 and s.latest["Buffer/Size"] == 2.0

    def test_truncation_restarts(self, tmp_path):
        live = tmp_path / "live_metrics.jsonl"
        live.write_text(tick(1, 1.0) + "\n" + tick(2, 2.0) + "\n")
        s = WatchState()
        off = tail_live_metrics(live, s, 0)
        live.write_text(tick(1, 3.0) + "\n")  # fresh run, same dir
        assert tail_live_metrics(live, s, off) == 0

    def test_find_latest_run_dir(self, tmp_path):
        (tmp_path / "runs").mkdir()
        a = tmp_path / "runs" / "old_run"
        b = tmp_path / "runs" / "new_run"
        a.mkdir()
        b.mkdir()
        import os

        os.utime(a, (1, 1))
        assert find_latest_run_dir(tmp_path / "runs") == b
        assert find_latest_run_dir(tmp_path / "missing") is None


class TestCollectorLiveFile:
    def test_ticks_append_jsonl(self, tmp_path):
        from alphatriangle_tpu.config import PersistenceConfig
        from alphatriangle_tpu.stats.collector import StatsCollector

        pc = PersistenceConfig(ROOT_DATA_DIR=str(tmp_path), RUN_NAME="lr")
        col = StatsCollector(pc, use_tensorboard=False)
        col.log_scalar("Buffer/Size", 5.0, step=1)
        col.process_and_log(1)
        col.log_scalar("Buffer/Size", 7.0, step=2)
        col.process_and_log(2)
        col.close()
        live = pc.get_run_base_dir() / "live_metrics.jsonl"
        lines = [
            json.loads(x) for x in live.read_text().splitlines() if x
        ]
        assert [x["step"] for x in lines] == [1, 2]
        assert lines[1]["means"]["Buffer/Size"] == 7.0

    def test_opt_out(self, tmp_path):
        from alphatriangle_tpu.config import PersistenceConfig
        from alphatriangle_tpu.stats.collector import StatsCollector

        pc = PersistenceConfig(ROOT_DATA_DIR=str(tmp_path), RUN_NAME="lr2")
        col = StatsCollector(pc, use_tensorboard=False, use_live_file=False)
        col.log_scalar("Buffer/Size", 5.0, step=1)
        col.process_and_log(1)
        col.close()
        assert not (pc.get_run_base_dir() / "live_metrics.jsonl").exists()


class TestCliWatch:
    def test_once_renders_run(self, tmp_path, capsys):
        run = tmp_path / "AlphaTriangleTPU" / "runs" / "w_run"
        run.mkdir(parents=True)
        (run / "live_metrics.jsonl").write_text(
            tick(7, time.time(), **{"Buffer/Size": 11.0}) + "\n"
        )
        rc = cli.main(
            [
                "watch",
                "--run-name",
                "w_run",
                "--root-dir",
                str(tmp_path),
                "--once",
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "w_run" in out and "step 7" in out

    def test_defaults_to_latest_run(self, tmp_path, capsys):
        runs = tmp_path / "AlphaTriangleTPU" / "runs"
        (runs / "older").mkdir(parents=True)
        newer = runs / "newer"
        newer.mkdir()
        import os

        os.utime(runs / "older", (1, 1))
        (newer / "live_metrics.jsonl").write_text(
            tick(3, time.time()) + "\n"
        )
        rc = cli.main(
            ["watch", "--root-dir", str(tmp_path), "--once"]
        )
        assert rc == 0
        assert "newer" in capsys.readouterr().out

    def test_no_runs_errors(self, tmp_path, capsys):
        rc = cli.main(["watch", "--root-dir", str(tmp_path), "--once"])
        assert rc == 1


class TestHealthLine:
    def test_live_heartbeat(self):
        hb = {
            "time": 1000.0,
            "learner_step": 42,
            "watchdog_deadline_s": 300.0,
        }
        line = health_line(hb, now=1010.0)
        assert "live" in line and "step 42" in line and "10s" in line

    def test_stalled_when_heartbeat_ages_out(self):
        hb = {"time": 1000.0, "watchdog_deadline_s": 100.0}
        line = health_line(hb, now=1350.0)
        assert "STALLED (no heartbeat for 350s)" in line

    def test_stalled_when_watchdog_flagged(self):
        hb = {
            "time": 1000.0,
            "stalled": True,
            "watchdog_deadline_s": 300.0,
        }
        line = health_line(hb, now=1010.0)
        assert "STALLED" in line and "watchdog" in line

    def test_no_heartbeat_no_line(self):
        assert health_line(None) is None
        assert health_line({"not": "a heartbeat"}) is None
        # Frame without a heartbeat stays at its pre-telemetry shape.
        frame = render_frame(WatchState(), "r")
        assert "health" not in frame

    def test_frame_includes_stall_verdict(self):
        s = WatchState()
        s.fold_line(tick(5, time.time(), **{"Buffer/Size": 1.0}))
        hb = {"time": time.time() - 9999.0, "watchdog_deadline_s": 300.0}
        frame = render_frame(s, "r", health=hb)
        assert "STALLED (no heartbeat for" in frame

    def test_cli_watch_renders_stall(self, tmp_path, capsys):
        run = tmp_path / "AlphaTriangleTPU" / "runs" / "h_run"
        run.mkdir(parents=True)
        (run / "live_metrics.jsonl").write_text(
            tick(7, time.time(), **{"Buffer/Size": 11.0}) + "\n"
        )
        (run / "health.json").write_text(
            json.dumps(
                {
                    "time": time.time() - 5000.0,
                    "learner_step": 7,
                    "watchdog_deadline_s": 300.0,
                }
            )
        )
        rc = cli.main(
            [
                "watch",
                "--run-name",
                "h_run",
                "--root-dir",
                str(tmp_path),
                "--once",
            ]
        )
        assert rc == 0
        assert "STALLED (no heartbeat for" in capsys.readouterr().out


class TestRateRobustness:
    def test_learner_only_tick_does_not_flap_games_rate(self):
        # Ticks without Progress/Episodes_Played (learner-dominated)
        # must not null the games/h headline.
        s = WatchState()
        t0 = time.time() - 90
        s.fold_line(tick(0, t0, **{"Progress/Episodes_Played": 0.0}))
        s.fold_line(tick(10, t0 + 60, **{"Progress/Episodes_Played": 120.0}))
        s.fold_line(tick(12, t0 + 90, **{"Loss/total_loss": 1.0}))
        assert abs(s.games_per_hour - 7200.0) < 1e-3
        assert s.latest_step == 12
