"""Fit-driven autotuner tests (alphatriangle_tpu/autotune/).

Everything here is cheap: the feasibility oracle is always a fake (the
real `estimate_fit` oracle compiles programs and belongs to
benchmarks/tune_smoke.py), predictions are pure math, and the cli-level
tests monkeypatch the oracle or rely on the free ring-math prune. The
one "gate" test pins the analytic throughput model against the
checked-in CPU smoke reference summary — the model must predict the
observed throughput within a checked-in factor or the objective the
search maximizes has drifted from reality.
"""

import json
from pathlib import Path

import pytest

from alphatriangle_tpu.autotune import (
    Calibration,
    Candidate,
    SearchSpace,
    build_tuned_preset,
    calibration_from_summary,
    divisibility_gate,
    ledger_tune_outcome,
    predict_throughput,
    prune_dominated,
    run_search,
    write_tuned_preset,
)
from alphatriangle_tpu.autotune.search import materialize_candidate
from alphatriangle_tpu.config import (
    TUNED_PRESET_SCHEMA,
    AlphaTriangleMCTSConfig,
    EnvConfig,
    ModelConfig,
    TrainConfig,
    expected_other_features_dim,
    load_tuned_preset,
)

REFERENCE = (
    Path(__file__).resolve().parent.parent
    / "benchmarks"
    / "perf_reference_cpu_smoke.json"
)

# The model must land within this factor of the reference's observed
# throughput (both directions). Calibrated from the same summary it
# predicts, the model currently lands within ~10%; the factor leaves
# room for FLOPs-accounting drift without letting the objective decouple
# from reality entirely.
CALIBRATION_FACTOR = 3.0


def _smoke_world():
    """The perf-smoke world (benchmarks/perf_smoke.py tiny_configs),
    i.e. the configuration the checked-in reference was measured on."""
    env_cfg = EnvConfig(
        ROWS=3,
        COLS=4,
        PLAYABLE_RANGE_PER_ROW=[(0, 4), (0, 4), (0, 4)],
        NUM_SHAPE_SLOTS=1,
        MAX_SHAPE_TRIANGLES=3,
        LINE_MIN_LENGTH=3,
    )
    model_cfg = ModelConfig(
        GRID_INPUT_CHANNELS=1,
        CONV_FILTERS=[4],
        CONV_KERNEL_SIZES=[3],
        CONV_STRIDES=[1],
        NUM_RESIDUAL_BLOCKS=0,
        RESIDUAL_BLOCK_FILTERS=4,
        USE_TRANSFORMER=False,
        FC_DIMS_SHARED=[16],
        POLICY_HEAD_DIMS=[16],
        VALUE_HEAD_DIMS=[16],
        OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(env_cfg),
        NUM_VALUE_ATOMS=11,
        COMPUTE_DTYPE="float32",
    )
    mcts_cfg = AlphaTriangleMCTSConfig(max_simulations=4, max_depth=4)
    return env_cfg, model_cfg, mcts_cfg


class TestThroughputModelCalibration:
    """Gate: the analytic model vs the checked-in observed reference."""

    def test_reference_exists_and_calibrates(self):
        summary = json.loads(REFERENCE.read_text())
        cal = calibration_from_summary(summary)
        assert cal is not None
        # mfu and moves/s+games/h are all present in the reference, so
        # both calibrated terms must have been picked up.
        assert cal.efficiency == pytest.approx(summary["mfu"])
        assert cal.moves_per_game == pytest.approx(
            summary["moves_per_sec"] * 3600.0 / summary["games_per_hour"]
        )

    def test_model_predicts_reference_within_factor(self):
        summary = json.loads(REFERENCE.read_text())
        cal = calibration_from_summary(summary)
        env_cfg, model_cfg, mcts_cfg = _smoke_world()
        # The reference run's shapes: B=4, T=4, lbatch=8; its observed
        # dispatches/iteration is 3.0 = 2 + ceil(B*T/lbatch/K) at K=2.
        cand = Candidate(
            geometry="plan",
            sp_batch=4,
            capacity=2000,
            chunk=4,
            fused_k=2,
            dp=1,
        )
        pred = predict_throughput(
            cand,
            env_cfg,
            model_cfg,
            mcts_cfg,
            lbatch=8,
            calibration=cal,
            peak_tflops=summary["peak_bf16_tflops"],
            megastep=False,
        )
        for metric in ("moves_per_sec", "games_per_hour"):
            observed = summary[metric]
            predicted = pred[metric]
            assert predicted > 0
            assert (
                observed / CALIBRATION_FACTOR
                <= predicted
                <= observed * CALIBRATION_FACTOR
            ), (
                f"{metric}: predicted {predicted:.1f} vs observed "
                f"{observed:.1f} drifted past {CALIBRATION_FACTOR}x"
            )
        assert pred["dispatches_per_iteration"] == pytest.approx(
            summary["dispatches_per_iteration"]
        )

    def test_model_monotone_in_b_t_k(self):
        """The dominance prune's contract: games/h never decreases when
        B, T or K grows with the other axes fixed."""
        env_cfg, model_cfg, mcts_cfg = _smoke_world()
        cal = Calibration()

        def gph(b, t, k):
            return predict_throughput(
                Candidate("plan", b, 2000, t, k, 1),
                env_cfg,
                model_cfg,
                mcts_cfg,
                lbatch=8,
                calibration=cal,
            )["games_per_hour"]

        assert gph(8, 4, 2) >= gph(4, 4, 2)
        assert gph(4, 8, 2) >= gph(4, 4, 2)
        assert gph(4, 4, 4) >= gph(4, 4, 2)

    def test_capacity_does_not_change_prediction(self):
        """Ring size costs memory, not time — 'spend HBM, not chip
        windows' depends on capacity being absent from the objective."""
        env_cfg, model_cfg, mcts_cfg = _smoke_world()

        def gph(cap):
            return predict_throughput(
                Candidate("plan", 4, cap, 4, 2, 1),
                env_cfg,
                model_cfg,
                mcts_cfg,
                lbatch=8,
            )["games_per_hour"]

        assert gph(2000) == pytest.approx(gph(200_000))


class TestSpacePruning:
    def test_divisibility_gates(self):
        ok = Candidate("plan", 8, 64, 4, 2, 1)
        assert divisibility_gate(ok, lbatch=4, min_buffer=10) is None
        # dp must divide capacity / lbatch / lanes.
        bad_dp = Candidate("plan", 8, 64, 4, 2, 3)
        reason = divisibility_gate(bad_dp, lbatch=4, min_buffer=10)
        assert reason is not None and "dp 3" in reason
        # sharded evenly: passes.
        good_dp = Candidate("plan", 8, 64, 4, 2, 2)
        assert divisibility_gate(good_dp, lbatch=4, min_buffer=10) is None
        # Learner batch can't exceed the ring.
        tiny_cap = Candidate("plan", 8, 2, 4, 2, 1)
        assert (
            "BATCH_SIZE"
            in divisibility_gate(tiny_cap, lbatch=4, min_buffer=1)
        )
        assert (
            "MIN_BUFFER"
            in divisibility_gate(
                Candidate("plan", 8, 8, 4, 2, 1), lbatch=4, min_buffer=10
            )
        )
        assert (
            divisibility_gate(
                Candidate("plan", 0, 64, 4, 2, 1), lbatch=4, min_buffer=1
            )
            == "non-positive axis"
        )

    def test_prune_dominated(self):
        group = [
            Candidate("plan", b, 64, 4, 2, 1) for b in (16, 8, 4)
        ]
        other = Candidate("plan", 4, 128, 4, 2, 1)  # different group
        statuses = prune_dominated(group + [other], feasible={group[1]})
        assert statuses == {group[2]: "dominated"}


class _CountingOracle:
    """Fake feasibility oracle: fits iff sp_batch <= max_b, counts
    calls so tests can assert how much pruning saved."""

    def __init__(self, max_b: int, bytes_per_lane: int = 1000):
        self.max_b = max_b
        self.bytes_per_lane = bytes_per_lane
        self.calls: list = []

    def __call__(self, cand, env, model, train, limit):
        self.calls.append(cand)
        budget = {"total_bytes": cand.sp_batch * self.bytes_per_lane}
        return cand.sp_batch <= self.max_b, budget, []


class TestRunSearch:
    def _base(self, tiny_env_config, tiny_model_config, tiny_mcts_config):
        train = TrainConfig(
            BATCH_SIZE=4,
            BUFFER_CAPACITY=64,
            MIN_BUFFER_SIZE_TO_TRAIN=8,
            SELF_PLAY_BATCH_SIZE=4,
            ROLLOUT_CHUNK_MOVES=4,
            AUTO_RESUME_LATEST=False,
            RUN_NAME="tune_test",
        )
        return tiny_env_config, tiny_model_config, tiny_mcts_config, train

    def test_dominance_walk_calls_oracle_minimally(
        self, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        env, model, mcts, train = self._base(
            tiny_env_config, tiny_model_config, tiny_mcts_config
        )
        space = SearchSpace(
            geometries=["plan"],
            batches=[4, 8, 16],
            capacities=[64],
            chunks=[4],
            fused_ks=[2],
            dps=[1],
        )
        oracle = _CountingOracle(max_b=8)
        result = run_search(
            space, env, model, mcts, train, 10**9, oracle=oracle
        )
        # B=16 over (1 call), B=8 fits (1 call), B=4 dominated (0).
        assert [c.sp_batch for c in oracle.calls] == [16, 8]
        assert result.best is not None and result.best.sp_batch == 8
        statuses = {r["sp_batch"]: r["status"] for r in result.rows}
        assert statuses == {16: "over", 8: "fit", 4: "dominated"}
        assert result.oracle_calls == 2

    def test_winner_beats_every_feasible_candidate(
        self, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        """Acceptance (b): the emitted preset predicts >= games/h of
        every feasible-but-rejected candidate."""
        env, model, mcts, train = self._base(
            tiny_env_config, tiny_model_config, tiny_mcts_config
        )
        space = SearchSpace(
            geometries=["plan"],
            batches=[4, 8],
            capacities=[64, 128],
            chunks=[4, 8],
            fused_ks=[2],
            dps=[1],
        )
        result = run_search(
            space, env, model, mcts, train, 10**9,
            oracle=_CountingOracle(max_b=8),
        )
        assert result.best is not None
        best_gph = result.best_prediction["games_per_hour"]
        for row in result.rows:
            if row["status"] in ("fit", "dominated"):
                assert (
                    best_gph >= row["predicted"]["games_per_hour"] - 1e-9
                )

    def test_ring_math_prunes_without_oracle(
        self, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        """A limit below the ring's own bytes ends the search with zero
        oracle calls — the infeasible-space exit is free."""
        env, model, mcts, train = self._base(
            tiny_env_config, tiny_model_config, tiny_mcts_config
        )
        space = SearchSpace(
            geometries=["plan"],
            batches=[4, 8],
            capacities=[64],
            chunks=[4],
            fused_ks=[2],
            dps=[1],
        )

        def exploding_oracle(*a):
            raise AssertionError("oracle must not run under ring prune")

        result = run_search(
            space, env, model, mcts, train, 16, oracle=exploding_oracle
        )
        assert result.best is None
        assert result.oracle_calls == 0
        assert {r["status"] for r in result.rows} == {"ring-over"}
        assert result.feasible_rows() == []

    def test_gated_candidates_never_reach_oracle(
        self, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        env, model, mcts, train = self._base(
            tiny_env_config, tiny_model_config, tiny_mcts_config
        )
        space = SearchSpace(
            geometries=["plan"],
            batches=[6],  # 6 % dp(4) != 0 -> gate
            capacities=[64],
            chunks=[4],
            fused_ks=[2],
            dps=[4],
        )
        oracle = _CountingOracle(max_b=99)
        result = run_search(
            space, env, model, mcts, train, 10**9, oracle=oracle
        )
        assert oracle.calls == []
        assert {r["status"] for r in result.rows} == {"gate"}

    def test_megastep_mode_materializes_fused_config(
        self, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        env, model, mcts, train = self._base(
            tiny_env_config, tiny_model_config, tiny_mcts_config
        )
        cand = Candidate("plan", 8, 128, 4, 2, 1)
        _env, _model, tuned = materialize_candidate(
            cand, env, model, train, "megastep"
        )
        assert tuned.FUSED_MEGASTEP is True
        assert tuned.DEVICE_REPLAY == "on"
        assert tuned.SELF_PLAY_BATCH_SIZE == 8
        assert tuned.BUFFER_CAPACITY == 128
        assert tuned.FUSED_LEARNER_STEPS == 2


class TestTunedPresetArtifact:
    def _result_and_configs(
        self, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        train = TrainConfig(
            BATCH_SIZE=4,
            BUFFER_CAPACITY=64,
            MIN_BUFFER_SIZE_TO_TRAIN=8,
            SELF_PLAY_BATCH_SIZE=8,
            ROLLOUT_CHUNK_MOVES=4,
            AUTO_RESUME_LATEST=False,
            RUN_NAME="tuned_rt",
        )
        space = SearchSpace(
            geometries=["plan"],
            batches=[8],
            capacities=[64],
            chunks=[4],
            fused_ks=[2],
            dps=[1],
        )
        result = run_search(
            space,
            tiny_env_config,
            tiny_model_config,
            tiny_mcts_config,
            train,
            10**9,
            oracle=_CountingOracle(max_b=8),
        )
        assert result.best is not None
        return result, tiny_env_config, tiny_model_config, train

    def test_roundtrip(
        self,
        tmp_path,
        tiny_env_config,
        tiny_model_config,
        tiny_mcts_config,
    ):
        result, env, model, train = self._result_and_configs(
            tiny_env_config, tiny_model_config, tiny_mcts_config
        )
        payload = build_tuned_preset(
            result,
            env,
            model,
            tiny_mcts_config,
            train,
            scale="cpu",
            mode="sync",
            backend="cpu",
            device_kind="cpu",
            limit_bytes=10**9,
            limit_source="flag",
            calibration=Calibration(),
            run_name="tuned_rt",
        )
        assert payload["schema"] == TUNED_PRESET_SCHEMA
        path = write_tuned_preset(payload, tmp_path / "tuned_preset.json")
        bundle = load_tuned_preset(path)
        assert bundle["train"].SELF_PLAY_BATCH_SIZE == 8
        assert bundle["train"].BUFFER_CAPACITY == 64
        assert bundle["env"].ROWS == env.ROWS
        assert (
            bundle["model"].OTHER_NN_INPUT_FEATURES_DIM
            == model.OTHER_NN_INPUT_FEATURES_DIM
        )
        assert bundle["mcts"].max_simulations == (
            tiny_mcts_config.max_simulations
        )
        assert bundle["tuned"]["candidate"]["sp_batch"] == 8

    def test_schema_mismatch_is_a_clear_error(self, tmp_path):
        path = tmp_path / "tuned_preset.json"
        path.write_text(
            json.dumps(
                {"schema": "alphatriangle.tuned_preset.v999", "configs": {}}
            )
        )
        with pytest.raises(ValueError, match="v999"):
            load_tuned_preset(path)

    def test_unreadable_and_invalid_presets(self, tmp_path):
        with pytest.raises(ValueError, match="unreadable"):
            load_tuned_preset(tmp_path / "missing.json")
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        with pytest.raises(ValueError, match="invalid JSON"):
            load_tuned_preset(bad)
        nodict = tmp_path / "list.json"
        nodict.write_text("[1, 2]")
        with pytest.raises(ValueError):
            load_tuned_preset(nodict)

    def test_ledger_tune_outcome(
        self,
        tmp_path,
        tiny_env_config,
        tiny_model_config,
        tiny_mcts_config,
    ):
        result, env, model, train = self._result_and_configs(
            tiny_env_config, tiny_model_config, tiny_mcts_config
        )
        payload = build_tuned_preset(
            result,
            env,
            model,
            tiny_mcts_config,
            train,
            scale="cpu",
            mode="sync",
            backend="cpu",
            device_kind="cpu",
            limit_bytes=10**9,
            limit_source="flag",
            calibration=Calibration(),
            run_name="tuned_rt",
        )
        run_dir = tmp_path / "run"
        run_dir.mkdir()
        predicted = payload["predicted"]["games_per_hour"]
        ledger = run_dir / "metrics.jsonl"
        ledger.write_text(
            json.dumps(
                {
                    "kind": "util",
                    "step": 4,
                    "moves_per_sec": 10.0,
                    "games_per_hour": predicted / 2.0,
                }
            )
            + "\n"
        )
        record = ledger_tune_outcome(run_dir, payload)
        assert record is not None
        assert record["observed_over_predicted"] == pytest.approx(0.5)
        lines = ledger.read_text().splitlines()
        assert json.loads(lines[-1])["kind"] == "tune_outcome"
        # The calibration loop reads it back as an outcome scale.
        from alphatriangle_tpu.autotune import calibration_from_targets

        cal = calibration_from_targets([str(ledger)])
        assert cal.outcome_scale == pytest.approx(0.5)

    def test_ledger_tune_outcome_without_ledger(
        self,
        tmp_path,
        tiny_env_config,
        tiny_model_config,
        tiny_mcts_config,
    ):
        result, env, model, train = self._result_and_configs(
            tiny_env_config, tiny_model_config, tiny_mcts_config
        )
        payload = build_tuned_preset(
            result,
            env,
            model,
            tiny_mcts_config,
            train,
            scale="cpu",
            mode="sync",
            backend="cpu",
            device_kind="cpu",
            limit_bytes=10**9,
            limit_source="flag",
            calibration=Calibration(),
            run_name="tuned_rt",
        )
        empty = tmp_path / "empty_run"
        empty.mkdir()
        assert ledger_tune_outcome(empty, payload) is None


class TestCliTune:
    """cmd_tune end to end with the oracle faked out (the real oracle
    compiles programs; benchmarks/tune_smoke.py covers it)."""

    def test_happy_path_emits_consumable_preset(
        self, monkeypatch, tmp_path
    ):
        from alphatriangle_tpu import cli as cli_mod
        from alphatriangle_tpu.autotune import search as search_mod

        def fake_default_oracle(mcts, mode, device_replay=None, progress=None):
            def oracle(cand, env, model, train, limit):
                return True, {"total_bytes": 12345}, []

            return oracle

        monkeypatch.setattr(
            search_mod, "default_oracle", fake_default_oracle
        )
        out = tmp_path / "tuned_preset.json"
        rc = cli_mod.main(
            [
                "tune",
                "cpu",
                "--smoke",
                "--limit-gb",
                "8",
                "--out",
                str(out),
                "--root-dir",
                str(tmp_path),
                "--run-name",
                "tune_unit",
            ]
        )
        assert rc == 0
        payload = json.loads(out.read_text())
        assert payload["schema"] == TUNED_PRESET_SCHEMA
        assert payload["limit_source"] == "flag"
        bundle = load_tuned_preset(out)
        assert bundle["train"].RUN_NAME == "tune_unit"
        # Acceptance (b) at the artifact level: the winner's predicted
        # games/h tops every candidate the search scored as feasible.
        best = payload["predicted"]["games_per_hour"]
        for row in payload["search"]["rows"]:
            if row["status"] in ("fit", "dominated") and row["predicted"]:
                assert best >= row["predicted"]["games_per_hour"] - 1e-9

    def test_infeasible_space_exits_1(self, tmp_path):
        """A byte limit below the replay ring's own size: every
        candidate dies in the free ring prune (no compiles) and the
        command exits FIT_OVER."""
        from alphatriangle_tpu import cli as cli_mod

        rc = cli_mod.main(
            [
                "tune",
                "cpu",
                "--smoke",
                "--limit-gb",
                "0.000001",
                "--root-dir",
                str(tmp_path),
            ]
        )
        assert rc == 1

    def test_unknown_limit_exits_2(self, monkeypatch, tmp_path):
        from alphatriangle_tpu import cli as cli_mod
        from alphatriangle_tpu.telemetry import health as health_mod
        from alphatriangle_tpu.telemetry import memory as memory_mod

        monkeypatch.delenv(memory_mod.BYTES_LIMIT_ENV, raising=False)
        # resolve_bytes_limit falls through flag -> env -> device stats;
        # blind the device layer so nothing is known.
        monkeypatch.setattr(
            health_mod, "device_memory_stats", lambda: []
        )
        rc = cli_mod.main(
            ["tune", "cpu", "--smoke", "--root-dir", str(tmp_path)]
        )
        assert rc == 2


class TestPerfTolerance:
    """Satellite: historical ledgers without the newer fields still
    summarize and compare instead of being skipped."""

    def test_kindless_legacy_util_records_summarize(self):
        from alphatriangle_tpu.telemetry.perf import summarize_utilization

        legacy = [
            {
                "step": i,
                "moves_per_sec": 10.0 + i,
                "learner_steps_per_sec": 1.0,
                "window_s": 2.0,
            }
            for i in range(4)
        ]
        summary = summarize_utilization(legacy)
        assert summary is not None
        assert summary["ticks"] == 4
        assert summary["moves_per_sec"] == pytest.approx(11.5)
        # Fields the era predates surface as None, not a crash.
        assert summary["mfu"] is None
        assert summary["mem_bytes_limit"] is None

    def test_load_comparable_reads_legacy_ledger(self, tmp_path):
        from alphatriangle_tpu.telemetry.perf import (
            compare_summaries,
            load_comparable,
        )

        ledger = tmp_path / "metrics.jsonl"
        ledger.write_text(
            "\n".join(
                json.dumps(
                    {"step": i, "moves_per_sec": 5.0, "games_per_hour": 99.0}
                )
                for i in range(3)
            )
            + "\n"
        )
        summary, label = load_comparable(str(ledger))
        assert summary is not None, label
        assert summary["games_per_hour"] == pytest.approx(99.0)
        # And a modern summary compares against it: missing metrics are
        # "n/a" rows, never a skipped run.
        modern = json.loads(REFERENCE.read_text())
        rows, regressions = compare_summaries(modern, summary)
        statuses = {m: s for m, _a, _b, _r, s in rows}
        assert statuses.get("mfu") == "n/a"

    def test_fit_json_schema_tag(self):
        """`cli fit --json` output leads with a schema tag so scripts
        can gate on it (satellite: machine-readable fit)."""
        import inspect

        from alphatriangle_tpu import cli as cli_mod

        src = inspect.getsource(cli_mod.cmd_fit)
        assert "alphatriangle.fit.v1" in src
