"""Serve-fleet control plane (alphatriangle_tpu/serving/router.py +
fleet.py, docs/SERVING.md "Fleet").

The router tests drive every routing edge case — all-replicas-unhealthy
shedding, retry-exhaustion surfacing the last error, hedge
cancel-on-first-win, capped backoff math — with fake replica handles,
an injectable clock and ZERO subprocesses; the FleetSupervisor tests
script a replica death through a fake popen and assert the death ->
verdict -> respawn -> re-admission chain lands in fleet.jsonl exactly
as `make fleet-smoke` reads it back from real children
(tests/test_supervise.py style). JAX never loads on these paths — the
contract benchmarks/fleet_smoke.py pins with an import guard.
"""

import json
import sys
import time

import pytest

from alphatriangle_tpu.serving.fleet import FLEET_FILENAME, FleetSupervisor
from alphatriangle_tpu.serving.router import (
    REJECT_NO_HEALTHY,
    REJECT_QUEUE_FULL,
    REJECT_RETRIES_EXHAUSTED,
    ReplicaError,
    ReplicaRouter,
)
from alphatriangle_tpu.supervise.faults import (
    FAULT_STATE_DIR_ENV,
    FAULTS_ENV,
    SITE_FAULTS,
    fault_point,
)
from alphatriangle_tpu.supervise.policy import (
    WEDGE_EXIT_CODE,
    RecoveryPolicy,
)
from alphatriangle_tpu.telemetry.health import (
    PROBE_DISPATCH_OVERDUE,
    PROBE_LIVE,
    PROBE_MISSING,
    PROBE_UNHEALTHY,
    probe_run,
)
from alphatriangle_tpu.telemetry.perf import (
    COMPARE_METRICS,
    LOWER_IS_BETTER,
    summarize_fleet,
)

# --- fakes (router handle protocol, no subprocesses) ---------------------


class FakeClock:
    """Monotonic clock advanced only by `sleep` — the router's polling
    loops and backoff waits move time deterministically."""

    def __init__(self, t: float = 0.0):
        self.t = t
        self.sleeps: list[float] = []

    def __call__(self) -> float:
        return self.t

    def sleep(self, s: float) -> None:
        self.sleeps.append(s)
        self.t += s


class FakePending:
    """Pre-resolved (or never-resolving) future."""

    def __init__(self, value=None, error=None, done=True):
        self.value = value
        self.error = error
        self._done = done
        self.cancelled = False

    def done(self) -> bool:
        return self._done

    def wait(self, timeout=None) -> bool:
        return self._done

    def cancel(self) -> None:
        self.cancelled = True
        if not self._done:
            self.error = ReplicaError("cancelled")
            self._done = True


class ClockPending(FakePending):
    """Resolves once the fake clock reaches `ready_at`."""

    def __init__(self, clock: FakeClock, ready_at: float, value=None):
        super().__init__(value=value, done=False)
        self._clock = clock
        self._ready_at = ready_at

    def done(self) -> bool:
        if not self._done and self._clock.t >= self._ready_at:
            self._done = True
        return self._done


class FakeReplica:
    """Router handle protocol: each submit pops the next scripted
    outcome (a pending, or an exception to raise from submit)."""

    def __init__(
        self, name, *, routable=True, queue_depth=0, bucket=8, outcomes=None
    ):
        self.name = name
        self.routable = routable
        self.queue_depth = queue_depth
        self.bucket = bucket
        self.outcomes = list(outcomes or [])
        self.submits: list[dict] = []

    def submit(self, payload: dict):
        self.submits.append(payload)
        outcome = (
            self.outcomes.pop(0)
            if self.outcomes
            else FakePending(value={"ok": True})
        )
        if isinstance(outcome, Exception):
            raise outcome
        return outcome


def make_router(replicas, clock=None, **kw):
    clock = clock or FakeClock()
    defaults = dict(
        timeout_s=10.0,
        retries=2,
        backoff_base_s=0.1,
        backoff_max_s=2.0,
        poll_s=0.01,
        clock=clock,
        sleep=clock.sleep,
    )
    defaults.update(kw)
    return ReplicaRouter(replicas, **defaults), clock


class TestRouter:
    def test_all_replicas_unhealthy_sheds_with_distinct_code(self):
        events = []
        router, _ = make_router(
            [FakeReplica("r0", routable=False), FakeReplica("r1", routable=False)],
            on_event=events.append,
        )
        res = router.route({"kind": "episode"})
        assert not res.ok
        assert res.rejection == REJECT_NO_HEALTHY
        assert router.stats.shed_unhealthy == 1
        assert router.stats.completed == 0
        assert [e["event"] for e in events] == ["shed"]
        assert events[0]["rejection"] == REJECT_NO_HEALTHY

    def test_bounded_admission_sheds_queue_full(self):
        router, _ = make_router([FakeReplica("r0")], max_inflight=0)
        res = router.route({"kind": "episode"})
        assert res.rejection == REJECT_QUEUE_FULL
        assert router.stats.shed_queue_full == 1

    def test_least_queue_depth_wins_and_exclusion_falls_back(self):
        deep = FakeReplica("r0", queue_depth=3)
        shallow = FakeReplica("r1", queue_depth=1)
        router, _ = make_router([deep, shallow])
        res = router.route({"kind": "episode"})
        assert res.ok and res.replica == "r1"
        assert not deep.submits
        # Exclusion prefers the untried replica; with everything tried
        # the pick falls back rather than shedding.
        assert router._pick(exclude=["r1"]) is deep
        assert router._pick(exclude=["r0", "r1"]) is shallow

    def test_retry_lands_on_a_different_replica(self):
        failing = FakeReplica(
            "r0",
            queue_depth=0,
            outcomes=[FakePending(error=ReplicaError("r0 died"))],
        )
        backup = FakeReplica("r1", queue_depth=5)
        router, clock = make_router([failing, backup])
        res = router.route({"kind": "episode"})
        assert res.ok
        assert res.replica == "r1"  # excluded the failed replica
        assert res.attempts == 2
        assert router.stats.retries == 1
        assert router.stats.backoff_sleeps == [0.1]

    def test_retry_exhaustion_surfaces_last_error(self):
        only = FakeReplica(
            "r0",
            outcomes=[
                FakePending(error=ReplicaError(f"boom-{k}"))
                for k in (1, 2, 3)
            ],
        )
        events = []
        router, _ = make_router([only], retries=2, on_event=events.append)
        res = router.route({"kind": "episode"})
        assert not res.ok
        assert res.rejection == REJECT_RETRIES_EXHAUSTED
        assert res.attempts == 3
        assert "boom-3" in str(res.error)  # the LAST error, not the first
        assert router.stats.exhausted == 1
        # Capped exponential backoff between attempts.
        assert router.stats.backoff_sleeps == [0.1, 0.2]
        assert events[-1]["event"] == "exhausted"
        assert "boom-3" in events[-1]["error"]

    def test_backoff_curve_doubles_then_caps(self):
        router, _ = make_router(
            [], backoff_base_s=0.5, backoff_max_s=1.7
        )
        assert [router.backoff_delay(k) for k in (1, 2, 3, 4)] == [
            0.5,
            1.0,
            1.7,
            1.7,
        ]

    def test_hedge_win_cancels_the_straggling_primary(self):
        clock = FakeClock()
        straggler_pending = FakePending(done=False)
        straggler = FakeReplica("r0", outcomes=[straggler_pending])
        fast = FakeReplica(
            "r1",
            queue_depth=9,  # primary pick must still be r0
            outcomes=[FakePending(value={"ok": True, "kind": "episode"})],
        )
        events = []
        router, _ = make_router(
            [straggler, fast],
            clock=clock,
            hedge_after_s=0.05,
            on_event=events.append,
        )
        res = router.route({"kind": "episode"})
        assert res.ok and res.hedged and res.hedge_won
        assert res.replica == "r1"
        assert straggler_pending.cancelled  # cancel-on-first-win
        assert router.stats.hedges == 1
        assert router.stats.hedge_wins == 1
        assert [e["event"] for e in events] == ["hedge", "hedge-win"]

    def test_primary_win_cancels_the_hedge(self):
        clock = FakeClock()
        primary = FakeReplica(
            "r0", outcomes=[ClockPending(clock, 0.2, value={"ok": True})]
        )
        hedge_pending = FakePending(done=False)
        backup = FakeReplica(
            "r1", queue_depth=9, outcomes=[hedge_pending]
        )
        router, _ = make_router(
            [primary, backup], clock=clock, hedge_after_s=0.05
        )
        res = router.route({"kind": "episode"})
        assert res.ok and res.replica == "r0"
        assert res.hedged and not res.hedge_won
        assert hedge_pending.cancelled
        assert router.stats.hedges == 1
        assert router.stats.hedge_wins == 0

    def test_timeout_cancels_and_counts(self):
        clock = FakeClock()
        stuck_pending = FakePending(done=False)
        stuck = FakeReplica("r0", outcomes=[stuck_pending])
        router, _ = make_router(
            [stuck], clock=clock, timeout_s=0.1, retries=0
        )
        res = router.route({"kind": "episode"})
        assert not res.ok
        assert res.rejection == REJECT_RETRIES_EXHAUSTED
        assert isinstance(res.error, TimeoutError)
        assert stuck_pending.cancelled
        assert router.stats.timeouts == 1


# --- the shared liveness probe (cli health --probe / fleet admission) ----


def write_health(run_dir, *, time_s, stalled=False, deadline_s=10.0):
    (run_dir / "health.json").write_text(
        json.dumps(
            {
                "time": time_s,
                "pid": 4242,
                "stalled": stalled,
                "watchdog_deadline_s": deadline_s,
            }
        )
    )


class TestProbeRun:
    NOW = 1_000.0

    def test_missing_heartbeat(self, tmp_path):
        out = probe_run(tmp_path, now=self.NOW)
        assert out["code"] == PROBE_MISSING
        assert out["verdict"] == "missing"

    def test_live(self, tmp_path):
        write_health(tmp_path, time_s=self.NOW - 1.0)
        out = probe_run(tmp_path, now=self.NOW)
        assert out["code"] == PROBE_LIVE
        assert out["verdict"] == "live"
        assert out["heartbeat_age_s"] == pytest.approx(1.0)
        assert out["pid"] == 4242

    def test_stale_heartbeat(self, tmp_path):
        write_health(tmp_path, time_s=self.NOW - 100.0, deadline_s=10.0)
        out = probe_run(tmp_path, now=self.NOW)
        assert out["code"] == PROBE_UNHEALTHY
        assert out["verdict"] == "stale"

    def test_fresh_but_stalled(self, tmp_path):
        write_health(tmp_path, time_s=self.NOW - 1.0, stalled=True)
        out = probe_run(tmp_path, now=self.NOW)
        assert out["code"] == PROBE_UNHEALTHY
        assert out["verdict"] == "stalled"

    def test_unsealed_intent_past_deadline(self, tmp_path):
        write_health(tmp_path, time_s=self.NOW - 1.0)
        (tmp_path / "flight.jsonl").write_text(
            json.dumps(
                {
                    "kind": "flight",
                    "phase": "intent",
                    "seq": 7,
                    "program": "serve/b8",
                    "family": "serve",
                    "time": self.NOW - 50.0,
                    "deadline_s": 5.0,
                }
            )
            + "\n"
        )
        out = probe_run(tmp_path, now=self.NOW)
        assert out["code"] == PROBE_DISPATCH_OVERDUE
        assert out["verdict"] == "dispatch-overdue"
        assert out["overdue"][0]["program"] == "serve/b8"
        assert "serve/b8" in out["reason"]

    def test_sealed_intent_is_not_overdue(self, tmp_path):
        write_health(tmp_path, time_s=self.NOW - 1.0)
        intent = {
            "kind": "flight",
            "phase": "intent",
            "seq": 7,
            "program": "serve/b8",
            "family": "serve",
            "time": self.NOW - 50.0,
            "deadline_s": 5.0,
        }
        seal = {
            "kind": "flight",
            "phase": "seal",
            "seq": 7,
            "ok": True,
            "program": "serve/b8",
            "family": "serve",
            "time": self.NOW - 49.0,
        }
        (tmp_path / "flight.jsonl").write_text(
            json.dumps(intent) + "\n" + json.dumps(seal) + "\n"
        )
        out = probe_run(tmp_path, now=self.NOW)
        assert out["code"] == PROBE_LIVE

    def test_previous_incarnation_wedge_does_not_gate_respawn(
        self, tmp_path
    ):
        # The predecessor died wedged (unsealed intent, its pid); the
        # respawned process heartbeats under a NEW pid. Its probe must
        # come up live — the old confession is doctor evidence for the
        # death, not a permanent eviction of the replacement.
        write_health(tmp_path, time_s=self.NOW - 1.0)  # pid 4242
        (tmp_path / "flight.jsonl").write_text(
            json.dumps(
                {
                    "kind": "flight",
                    "phase": "intent",
                    "seq": 7,
                    "program": "serve/b8",
                    "family": "serve",
                    "time": self.NOW - 50.0,
                    "deadline_s": 5.0,
                    "pid": 1111,
                }
            )
            + "\n"
        )
        out = probe_run(tmp_path, now=self.NOW)
        assert out["code"] == PROBE_LIVE
        assert out["overdue"] == []
        # Same pid -> still overdue (the CURRENT process is wedged).
        (tmp_path / "flight.jsonl").write_text(
            json.dumps(
                {
                    "kind": "flight",
                    "phase": "intent",
                    "seq": 8,
                    "program": "serve/b8",
                    "family": "serve",
                    "time": self.NOW - 50.0,
                    "deadline_s": 5.0,
                    "pid": 4242,
                }
            )
            + "\n"
        )
        out = probe_run(tmp_path, now=self.NOW)
        assert out["code"] == PROBE_DISPATCH_OVERDUE


# --- serve quarantine arm + serve-dispatch fault site --------------------


def test_serve_wedge_quarantines_onto_smaller_bucket():
    policy = RecoveryPolicy(
        max_restarts=8,
        circuit_breaker_deaths=99,
        backoff_base_s=1.0,
        quarantine_after=1,
        clock=lambda: 1000.0,
    )
    a = policy.decide(
        verdict="dispatch-hung",
        exit_code=WEDGE_EXIT_CODE,
        family="serve",
        progress_step=5,
    )
    assert a.kind == "restart"
    assert a.overrides == {
        "SERVE_SLOTS__scale": 0.5,
        "TELEMETRY__BEACONS": True,
    }


class TestServeDispatchFaultSite:
    def test_site_registered(self):
        assert SITE_FAULTS["serve-dispatch"] == ("hang-serve", "crash-serve")

    def test_crash_serve_fires_once_per_state_dir(self, tmp_path, monkeypatch):
        monkeypatch.setenv(FAULTS_ENV, "crash-serve@after=2")
        monkeypatch.setenv(FAULT_STATE_DIR_ENV, str(tmp_path))
        fault_point("serve-dispatch", 1)  # below threshold: no-op
        with pytest.raises(RuntimeError, match="injected serve-dispatch"):
            fault_point("serve-dispatch", 2)
        fault_point("serve-dispatch", 3)  # sentinel claimed: fires once
        assert (tmp_path / "crash-serve.fired").exists()

    def test_unarmed_site_is_a_cheap_no_op(self, monkeypatch):
        monkeypatch.delenv(FAULTS_ENV, raising=False)
        fault_point("serve-dispatch", 10**6)


# --- FleetSupervisor lifecycle with scripted children --------------------


class FakeProc:
    """Subprocess stand-in: stdout lines are pre-scripted (a list is a
    valid line iterable for the handle's reader thread)."""

    _pids = iter(range(50_000, 60_000))

    def __init__(self, stdout_lines):
        self.stdout = list(stdout_lines)
        self.stdin = self
        self.pid = next(FakeProc._pids)
        self.returncode = None

    # stdin protocol (unused unless the test submits requests)
    def write(self, line):
        pass

    def flush(self):
        pass

    def close(self):
        pass

    def poll(self):
        return self.returncode

    def wait(self, timeout=None):
        return self.returncode


def fleet_popen(calls):
    def popen(argv, **kw):
        calls.append(list(argv))
        name = argv[argv.index("--name") + 1]
        return FakeProc(
            [json.dumps({"kind": "ready", "name": name, "pid": 1}) + "\n"]
        )

    return popen


def write_wedge_evidence(run_dir, family="serve", program="serve/b8"):
    """The artifacts a replica's watchdog 113 leaves behind: a wedge
    report plus a ring where the program sealed once before hanging."""
    now = time.time()
    records = [
        {"kind": "flight", "phase": "intent", "seq": 1, "program": program,
         "family": family, "time": now},
        {"kind": "flight", "phase": "seal", "seq": 1, "ok": True,
         "program": program, "family": family, "wall_s": 1.0, "time": now},
        {"kind": "flight", "phase": "intent", "seq": 2, "program": program,
         "family": family, "time": now},
    ]
    (run_dir / "flight.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in records)
    )
    (run_dir / "wedge_report.json").write_text(
        json.dumps(
            {"kind": "wedge", "time": now, "program": program,
             "family": family, "seq": 2, "elapsed_s": 99.0,
             "deadline_s": 5.0}
        )
    )


def fleet_events(run_dir):
    out = []
    for line in (run_dir / FLEET_FILENAME).read_text().splitlines():
        rec = json.loads(line)
        if rec.get("kind") == "fleet":
            out.append(rec)
    return out


class TestFleetSupervisor:
    def make_fleet(self, tmp_path, calls, clock):
        return FleetSupervisor(
            tmp_path / "fleet",
            replicas=1,
            slots=8,
            sims=2,
            popen=fleet_popen(calls),
            now=clock,
            sleep=lambda s: None,
            probe_deadline_s=10.0,
            policy_factory=lambda: RecoveryPolicy(
                max_restarts=8,
                circuit_breaker_deaths=99,
                backoff_base_s=3.0,
                backoff_max_s=30.0,
                quarantine_after=1,
                clock=clock,
            ),
        )

    def test_death_verdict_respawn_readmission_chain(self, tmp_path):
        clock = FakeClock(t=1_000.0)
        calls: list = []
        fleet = self.make_fleet(tmp_path, calls, clock)
        h = fleet.handles[0]

        # Spawn (driving the internals directly keeps the monitor
        # thread out of the test), then the probe admits the replica.
        fleet._spawn(h, "spawn")
        assert h.ready.wait(2.0)
        assert calls[0][calls[0].index("--slots") + 1] == "8"
        write_health(h.run_dir, time_s=clock.t - 0.5)
        fleet._probe(h)
        assert h.routable
        assert fleet.readmissions == 1

        # The replica wedges in serve dispatch and dies by watchdog 113.
        write_wedge_evidence(h.run_dir, family="serve", program="serve/b8")
        h.served_moves = 24  # progress since spawn: streak stays 1
        h.proc.returncode = 113
        fleet.poll_once()
        assert fleet.deaths == 1
        assert not h.routable
        death = [e for e in fleet_events(fleet.run_dir) if e["event"] == "death"][0]
        assert death["rc"] == 113
        assert death["verdict"] == "dispatch-hung"
        assert death["family"] == "serve"
        assert death["program"] == "serve/b8"
        assert death["action"] == "restart"
        assert death["overrides"] == {
            "SERVE_SLOTS__scale": 0.5,
            "TELEMETRY__BEACONS": True,
        }
        assert death["progress_moves"] == 24

        # Before the backoff expires: no respawn yet.
        clock.t += 1.0
        fleet.poll_once()
        assert fleet.respawns == 0

        # Past the backoff: respawn onto the DEGRADED (halved) bucket.
        clock.t += 3.0
        fleet.poll_once()
        assert fleet.respawns == 1
        assert h.ready.wait(2.0)
        assert calls[1][calls[1].index("--slots") + 1] == "4"
        assert h.bucket == 4

        # Fresh heartbeat from the new incarnation -> re-admission.
        write_health(h.run_dir, time_s=clock.t - 0.5)
        fleet.poll_once()
        assert h.routable
        assert fleet.readmissions == 2

        chain = [e["event"] for e in fleet_events(fleet.run_dir)]
        # replica-ready is ledgered by the reader THREAD the moment the
        # child prints its ready line, so its position among the
        # main-thread lifecycle events is timing-dependent: assert one
        # per incarnation, then pin the lifecycle order without them.
        assert chain.count("replica-ready") == 2
        lifecycle = [e for e in chain if e != "replica-ready"]
        assert lifecycle == ["spawn", "readmit", "death", "respawn", "readmit"]
        assert fleet.summary()["buckets"] == {"r0": 4}

    def test_stale_heartbeat_evicts_until_it_recovers(self, tmp_path):
        clock = FakeClock(t=1_000.0)
        calls: list = []
        fleet = self.make_fleet(tmp_path, calls, clock)
        h = fleet.handles[0]
        fleet._spawn(h, "spawn")
        assert h.ready.wait(2.0)
        write_health(h.run_dir, time_s=clock.t - 0.5)
        fleet._probe(h)
        assert h.routable

        clock.t += 100.0  # heartbeat goes stale: evict from admission
        fleet.poll_once()
        assert not h.routable
        assert fleet.evictions == 1
        evict = [e for e in fleet_events(fleet.run_dir) if e["event"] == "evict"][0]
        assert evict["code"] == PROBE_UNHEALTHY

        write_health(h.run_dir, time_s=clock.t - 0.5)  # recovered
        fleet.poll_once()
        assert h.routable
        assert fleet.readmissions == 2

    def test_quarantine_and_ladder_cannot_drift(self, tmp_path):
        """Drift regression (fleet.py `_effective_slots`): quarantine's
        `SERVE_SLOTS__scale` multiplier and the micro-batcher both
        derive their rungs from serving/buckets.py, so one quarantine
        strike must land EXACTLY one `walk_down` on the shared ladder —
        a shape `cli warm`/PolicyService precompiled — for any base
        slot count and any explicit `--buckets` spec."""
        from alphatriangle_tpu.serving.buckets import (
            BucketLadder,
            default_rungs,
        )

        clock = FakeClock(t=1_000.0)
        for slots in (1, 3, 5, 8, 16, 64):
            fleet = FleetSupervisor(
                tmp_path / f"fleet_b{slots}",
                replicas=1,
                slots=slots,
                popen=fleet_popen([]),
                now=clock,
                sleep=lambda s: None,
            )
            name = fleet.handles[0].name
            # The implicit ladder under a bare --slots knob is the
            # halving ladder — the legacy 0.5-multiplier bucket set.
            assert fleet.ladder.rungs == default_rungs(slots)
            # Healthy replica: the base rung itself.
            assert fleet._effective_slots(name) == slots
            # One strike (scale 0.5) == one forced walk-down, exactly.
            fleet._overrides[name] = {"SERVE_SLOTS__scale": 0.5}
            assert fleet._effective_slots(name) == fleet.ladder.walk_down(
                slots
            )
            # Two strikes (0.25) keep agreeing, and the degraded bucket
            # is always a rung the ladder owns (a warmable shape).
            fleet._overrides[name] = {"SERVE_SLOTS__scale": 0.25}
            two = fleet._effective_slots(name)
            assert two == fleet.ladder.walk_down(slots, strikes=2)
            assert two in fleet.ladder
            # summary() advertises the shared rung set (cli watch's
            # fleet line reads it).
            assert fleet.summary()["rungs"] == list(fleet.ladder.rungs)

        # An explicit --buckets spec flows into quarantine too: the
        # strike snaps DOWN onto the CUSTOM rungs, not powers of two.
        fleet = FleetSupervisor(
            tmp_path / "fleet_custom",
            replicas=1,
            slots=48,
            ladder="12,48,96",
            popen=fleet_popen([]),
            now=clock,
            sleep=lambda s: None,
        )
        name = fleet.handles[0].name
        assert fleet.ladder == BucketLadder((12, 48, 96))
        fleet._overrides[name] = {"SERVE_SLOTS__scale": 0.5}
        # 48 * 0.5 = 24 is NOT a rung: rung_at_or_below snaps to 12 —
        # the same answer as one walk_down from the base rung.
        assert fleet._effective_slots(name) == 12
        assert fleet._effective_slots(name) == fleet.ladder.walk_down(48)


# --- perf fold (cli perf / cli compare fleet rows) -----------------------


def test_summarize_fleet_folds_lifecycle_and_storm():
    events = [
        {"kind": "fleet", "event": "fleet-start", "replicas": 2},
        {"kind": "fleet", "event": "death", "replica": "r0"},
        {"kind": "fleet", "event": "respawn", "replica": "r0"},
        {"kind": "fleet", "event": "readmit", "replica": "r0"},
        {"kind": "fleet", "event": "retry", "replica": "r1"},
        {"kind": "fleet", "event": "shed", "rejection": "queue-full"},
        {"kind": "fleet", "event": "replica-reloaded", "recompiles": 0},
        {"kind": "fleet", "event": "replica-reloaded", "recompiles": 0},
        {"kind": "util", "moves_per_sec": 10.0},  # ignored: not fleet
        {
            "kind": "fleet",
            "event": "storm-summary",
            "requests": 32,
            "completed": 30,
            "shed": 2,
            "lost": 0,
            "requests_per_sec": 4.5,
            "move_latency_ms_p50": 12.0,
            "move_latency_ms_p95": 80.0,
        },
        {"kind": "fleet", "event": "fleet-stop", "gaveup": []},
    ]
    out = summarize_fleet(events)
    assert out["fleet_deaths"] == 1
    assert out["fleet_respawns"] == 1
    assert out["fleet_readmissions"] == 1
    assert out["fleet_retries"] == 1
    assert out["fleet_sheds"] == 1
    assert out["fleet_reload_recompiles"] == 0
    assert out["fleet_requests"] == 32
    assert out["fleet_lost"] == 0
    assert out["fleet_move_latency_ms_p95"] == 80.0
    assert out["fleet_requests_per_sec"] == 4.5
    assert out["fleet_gaveup"] == []
    assert summarize_fleet([{"kind": "util"}]) is None
    # The compare rows exist and latency gates in the right direction.
    assert "fleet_move_latency_ms_p95" in COMPARE_METRICS
    assert "fleet_requests_per_sec" in COMPARE_METRICS
    assert "fleet_move_latency_ms_p95" in LOWER_IS_BETTER


def test_router_events_keep_the_fleet_ledger_kind(tmp_path):
    """Router shed payloads carry the REQUEST's kind ("episode"); the
    sink must rename it so the record keeps kind="fleet" and stays
    visible to summarize_fleet (regression: sheds vanished from perf)."""
    fleet = FleetSupervisor(tmp_path / "fleet", replicas=0)
    fleet.router_event(
        {"event": "shed", "kind": "episode", "rejection": "queue-full"}
    )
    events = fleet_events(tmp_path / "fleet")
    assert events[-1]["event"] == "shed"
    assert events[-1]["kind"] == "fleet"
    assert events[-1]["request_kind"] == "episode"
    assert summarize_fleet(events)["fleet_sheds"] == 1


def test_fleet_control_plane_is_jax_free():
    """router/fleet must be importable without JAX (the smoke pins this
    in a blocked subprocess; here we pin the imported module set)."""
    for name in (
        "alphatriangle_tpu.serving.router",
        "alphatriangle_tpu.serving.fleet",
        "alphatriangle_tpu.serving",
    ):
        mod = sys.modules.get(name)
        assert mod is not None, f"{name} should be imported by this test"
        assert not getattr(mod, "jax", None), name
