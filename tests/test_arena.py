"""Arena-play helpers + run-config reloading (alphatriangle_tpu/arena.py,
config/run_configs.py) — the shared core under `cli eval` and
benchmarks/elo_ladder.py."""

import json

import numpy as np
import pytest

from alphatriangle_tpu.arena import greedy_mcts_policy, play
from alphatriangle_tpu.config.run_configs import (
    load_run_configs,
    load_run_configs_or_default,
)
from alphatriangle_tpu.env.engine import TriangleEnv
from alphatriangle_tpu.features.core import get_feature_extractor
from alphatriangle_tpu.mcts import BatchedMCTS, GumbelMCTS
from alphatriangle_tpu.nn.network import NeuralNetwork


@pytest.fixture(scope="module")
def arena_world(tiny_env_config, tiny_model_config, tiny_mcts_config):
    env = TriangleEnv(tiny_env_config)
    fe = get_feature_extractor(env, tiny_model_config)
    net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
    mcts = BatchedMCTS(env, fe, net.model, tiny_mcts_config, net.support)
    return env, fe, net, mcts, tiny_mcts_config


class TestArenaPlay:
    def test_paired_hands_are_deterministic(self, arena_world):
        """Same seed + same policy => identical scores (the paired-
        comparison property every arena consumer leans on)."""
        env, _, net, mcts, _ = arena_world
        policy = greedy_mcts_policy(net, mcts)
        s1, l1, d1 = play(env, policy, games=4, max_moves=5, seed=3)
        s2, l2, d2 = play(env, policy, games=4, max_moves=5, seed=3)
        np.testing.assert_array_equal(s1, s2)
        np.testing.assert_array_equal(l1, l2)
        assert s1.shape == (4,)

    def test_policy_reads_live_variables(self, arena_world):
        """greedy_mcts_policy closes over the net, not a weights
        snapshot — a set_weights between plays must be visible (the
        property the one-compile Elo ladder depends on)."""
        env, _, net, mcts, _ = arena_world
        policy = greedy_mcts_policy(net, mcts)
        s1, _, _ = play(env, policy, games=4, max_moves=5, seed=3)
        import jax

        original = net.variables
        try:
            # Perturb every weight; play again with the SAME policy fn.
            net.set_weights(
                jax.tree_util.tree_map(lambda x: x + 0.5, original)
            )
            s2, _, _ = play(env, policy, games=4, max_moves=5, seed=3)
            # A snapshotting regression would reproduce s1 exactly.
            assert not np.array_equal(s1, s2)
        finally:
            net.set_weights(original)  # module-scoped fixture

    def test_gumbel_policy_mode(self, arena_world):
        env, fe, net, _, mcts_cfg = arena_world
        gm = GumbelMCTS(
            env, fe, net.model, mcts_cfg, net.support, exploit=True
        )
        policy = greedy_mcts_policy(net, gm, use_gumbel=True)
        scores, _, _ = play(env, policy, games=4, max_moves=5, seed=1)
        assert scores.shape == (4,)

    def test_termination_check_interval_preserves_paired_hands(
        self, arena_world
    ):
        """The every-8-moves termination check (vs the old per-move
        `states.done` host sync) is a pure dispatch-count optimization:
        stepping all-done lanes is a frozen no-op, so scores/lengths/
        done are bit-identical at any check interval (fixed seed)."""
        env, _, net, mcts, _ = arena_world
        policy = greedy_mcts_policy(net, mcts)
        every_move = play(
            env, policy, games=4, max_moves=12, seed=5,
            termination_check_every=1,
        )
        deferred = play(
            env, policy, games=4, max_moves=12, seed=5,
            termination_check_every=8,
        )
        for a, b in zip(every_move, deferred):
            np.testing.assert_array_equal(a, b)

    def test_play_service_matches_direct_play(self, arena_world):
        """Arena traffic through the policy service's queue/dispatch
        path (the `cli eval` / elo_ladder route) reproduces direct
        greedy-MCTS arena play exactly — the acceptance bar for
        serving and eval sharing one code path."""
        from alphatriangle_tpu.arena import play_service
        from alphatriangle_tpu.serving import PolicyService

        env, fe, net, mcts, _ = arena_world
        direct = play(
            env, greedy_mcts_policy(net, mcts), games=4, max_moves=10,
            seed=3,
        )
        service = PolicyService(env, fe, net, mcts, slots=4)
        served = play_service(service, games=4, max_moves=10, seed=3)
        for a, b in zip(direct, served):
            np.testing.assert_array_equal(a, b)
        assert service.sessions.live_count == 0  # all retired
        assert service.sessions.retired_total == 4


class TestRunConfigs:
    def test_roundtrip(self, tmp_path, tiny_env_config, tiny_model_config):
        (tmp_path / "configs.json").write_text(
            json.dumps(
                {
                    "env": tiny_env_config.model_dump(),
                    "model": tiny_model_config.model_dump(),
                }
            )
        )
        loaded = load_run_configs(tmp_path)
        assert loaded is not None
        assert loaded["env"] == tiny_env_config
        assert loaded["model"] == tiny_model_config

    def test_missing_falls_back_to_defaults(self, tmp_path):
        assert load_run_configs(tmp_path) is None
        env, model = load_run_configs_or_default(tmp_path)
        assert env.ROWS == 8 and env.COLS == 15  # flagship defaults
        assert model.OTHER_NN_INPUT_FEATURES_DIM > 0

    def test_corrupt_dump_falls_back(self, tmp_path):
        (tmp_path / "configs.json").write_text("{not json")
        assert load_run_configs(tmp_path) is None
        env, _ = load_run_configs_or_default(tmp_path)
        assert env.ROWS == 8
