"""Learner tests (reference matrix: `tests/rl/test_trainer.py:135-270`)
plus the multi-device dp-sharding correctness story from VERDICT.md #3:
an 8-virtual-device train step keeps replicas bit-identical and matches
the single-device result."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphatriangle_tpu.config import MeshConfig, TrainConfig
from alphatriangle_tpu.nn.network import NeuralNetwork
from alphatriangle_tpu.rl.trainer import (
    Trainer,
    make_lr_schedule,
    make_optimizer,
    project_to_support,
)

B, A = 8, 12


@pytest.fixture(scope="module")
def network(tiny_model_config, tiny_env_config):
    return NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)


def make_batch(n=B, seed=0, weights=None):
    rng = np.random.default_rng(seed)
    policy = rng.random((n, A)).astype(np.float32)
    policy /= policy.sum(axis=1, keepdims=True)
    return {
        "grid": rng.integers(-1, 2, size=(n, 1, 3, 4)).astype(np.float32),
        "other_features": rng.random((n, 14), dtype=np.float32),
        "policy_target": policy,
        "value_target": rng.uniform(-5, 5, n).astype(np.float32),
        "weights": (
            np.ones(n, dtype=np.float32) if weights is None else weights
        ),
    }


class TestSchedules:
    def test_cosine_endpoints(self):
        cfg = TrainConfig(
            MAX_TRAINING_STEPS=1000,
            LR_SCHEDULER_TYPE="CosineAnnealingLR",
            LEARNING_RATE=1e-3,
            LR_SCHEDULER_ETA_MIN=1e-6,
            RUN_NAME="t",
        )
        sched = make_lr_schedule(cfg)
        assert float(sched(0)) == pytest.approx(1e-3)
        assert float(sched(1000)) == pytest.approx(1e-6, rel=1e-3)

    def test_step_lr_staircase(self):
        cfg = TrainConfig(
            LR_SCHEDULER_TYPE="StepLR",
            LR_SCHEDULER_STEP_SIZE=10,
            LR_SCHEDULER_GAMMA=0.5,
            LEARNING_RATE=1e-3,
            RUN_NAME="t",
        )
        sched = make_lr_schedule(cfg)
        assert float(sched(9)) == pytest.approx(1e-3)
        assert float(sched(10)) == pytest.approx(5e-4)
        assert float(sched(25)) == pytest.approx(2.5e-4)

    def test_optimizer_types(self):
        for opt_type in ["Adam", "AdamW", "SGD"]:
            cfg = TrainConfig(OPTIMIZER_TYPE=opt_type, RUN_NAME="t")
            opt = make_optimizer(cfg)
            params = {"w": jnp.ones(3)}
            state = opt.init(params)
            grads = {"w": jnp.ones(3)}
            updates, _ = opt.update(grads, state, params)
            assert jnp.all(jnp.isfinite(updates["w"]))


class TestProjection:
    def test_exact_atom_is_one_hot(self):
        # support [-10, 10], 51 atoms => atom spacing 0.4; -10 is atom 0.
        out = project_to_support(jnp.array([-10.0, 10.0, 0.0]), 51, -10, 10)
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-6)
        assert out[0, 0] == 1.0
        assert out[1, 50] == 1.0
        assert out[2, 25] == 1.0

    def test_between_atoms_two_hot(self):
        # 11 atoms on [-1, 1] => spacing 0.2; 0.15 sits 3/4 between atoms 5,6.
        out = project_to_support(jnp.array([0.15]), 11, -1, 1)
        assert out[0, 5] == pytest.approx(0.25, abs=1e-5)
        assert out[0, 6] == pytest.approx(0.75, abs=1e-5)
        assert out[0].sum() == pytest.approx(1.0)

    def test_out_of_range_clipped(self):
        out = project_to_support(jnp.array([-100.0, 100.0]), 11, -1, 1)
        assert out[0, 0] == 1.0
        assert out[1, 10] == 1.0


class TestTrainStep:
    def test_params_change_and_metrics(self, network, tiny_train_config):
        trainer = Trainer(network, tiny_train_config)
        before = jax.tree_util.tree_map(np.asarray, trainer.state.params)
        out = trainer.train_step(make_batch())
        assert out is not None
        metrics, td = out
        assert td.shape == (B,)
        assert np.all(np.isfinite(td)) and np.all(td >= 0)
        for key in ["total_loss", "policy_loss", "value_loss", "entropy"]:
            assert np.isfinite(metrics[key])
        after = trainer.state.params
        changed = jax.tree_util.tree_map(
            lambda a, b: not np.allclose(a, np.asarray(b)), before, after
        )
        assert any(jax.tree_util.tree_leaves(changed))
        assert trainer.global_step == 1

    def test_empty_batch_returns_none(self, network, tiny_train_config):
        trainer = Trainer(network, tiny_train_config)
        assert trainer.train_step(make_batch(0)) is None

    def test_zero_weights_leave_only_entropy_grads(
        self, network, tiny_train_config
    ):
        """IS weights gate the policy/value losses but NOT the entropy
        regularizer, which the reference keeps as an unweighted mean
        (`trainer.py:253-256`)."""
        trainer = Trainer(network, tiny_train_config)
        # Step off the freshly-initialized params first: at init the
        # policy is exactly uniform (entropy = ln(A), its maximum), a
        # stationary point where the entropy gradient is mathematically
        # ZERO — the zero-weight assertion below needs a non-degenerate
        # policy to have anything to regularize.
        assert trainer.train_step(make_batch()) is not None
        out = trainer.train_step(
            make_batch(weights=np.zeros(B, dtype=np.float32))
        )
        assert out is not None
        metrics = out[0]
        # Weighted terms vanish...
        assert metrics["policy_loss"] == pytest.approx(0.0, abs=1e-12)
        assert metrics["value_loss"] == pytest.approx(0.0, abs=1e-12)
        # ...but the entropy bonus still produces a gradient.
        ent_w = tiny_train_config.ENTROPY_BONUS_WEIGHT
        assert metrics["total_loss"] == pytest.approx(
            -ent_w * metrics["entropy"], abs=1e-9
        )
        if ent_w > 0:
            assert metrics["grad_norm"] > 0.0

    def test_lr_follows_schedule(self, network, tiny_train_config):
        trainer = Trainer(network, tiny_train_config)
        lr0 = trainer.get_current_lr()
        for _ in range(3):
            trainer.train_step(make_batch())
        assert trainer.get_current_lr() < lr0  # cosine decays

    def test_sync_to_network_bumps_version(self, network, tiny_train_config):
        trainer = Trainer(network, tiny_train_config)
        v0 = network.weights_version
        trainer.train_step(make_batch())
        assert trainer.sync_to_network() == v0 + 1
        # The wrapper now evaluates with the trained params.
        np.testing.assert_array_equal(
            np.asarray(jax.tree_util.tree_leaves(network.params)[0]),
            np.asarray(jax.tree_util.tree_leaves(trainer.state.params)[0]),
        )


class TestPolicyWeightMask:
    def test_zero_policy_weight_rows_drop_policy_loss(
        self, network, tiny_train_config
    ):
        """Rows with policy_weight 0 (fast PCR searches) contribute no
        policy CE or entropy; the value head still trains on them."""
        trainer = Trainer(network, tiny_train_config)
        batch = make_batch()
        batch["policy_weight"] = np.zeros(B, dtype=np.float32)
        out = trainer.train_step(batch)
        assert out is not None
        metrics = out[0]
        assert metrics["policy_loss"] == pytest.approx(0.0, abs=1e-12)
        assert metrics["entropy"] == pytest.approx(0.0, abs=1e-12)
        assert metrics["value_loss"] > 0.0

    def test_mixed_weights_match_subset(self, tiny_model_config, tiny_env_config, tiny_train_config):
        """policy_loss with half the rows masked equals the IS-weighted
        mean over all rows with masked rows as zeros."""
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        trainer = Trainer(net, tiny_train_config)
        batch = make_batch()
        pw = np.zeros(B, dtype=np.float32)
        pw[: B // 2] = 1.0
        batch["policy_weight"] = pw
        metrics, _ = trainer.train_step(batch)

        net2 = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        trainer2 = Trainer(net2, tiny_train_config)
        full_metrics, _ = trainer2.train_step(make_batch())
        # Same data, same params: the masked run's policy loss must be
        # strictly less than the unmasked run's (half the rows zeroed).
        assert 0.0 < metrics["policy_loss"] < full_metrics["policy_loss"]

    def test_absent_key_defaults_to_ones(self, network, tiny_train_config):
        trainer = Trainer(network, tiny_train_config)
        out = trainer.train_step(make_batch())  # no policy_weight key
        assert out is not None and out[0]["policy_loss"] > 0.0


class TestFusedSteps:
    """`train_steps` (FUSED_LEARNER_STEPS) must be a pure dispatch
    optimization: K fused steps == K sequential steps."""

    def test_fused_matches_sequential(
        self, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        batches = [make_batch(seed=i) for i in range(3)]
        net_a = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        net_b = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        tr_seq = Trainer(net_a, tiny_train_config)
        tr_fused = Trainer(net_b, tiny_train_config)

        seq = [tr_seq.train_step(b) for b in batches]
        fused = tr_fused.train_steps(batches)

        assert len(fused) == 3
        assert tr_fused.global_step == 3
        for (m_s, td_s), (m_f, td_f) in zip(seq, fused):
            np.testing.assert_allclose(td_s, td_f, rtol=1e-5, atol=1e-6)
            for key in m_s:
                assert m_s[key] == pytest.approx(
                    m_f[key], rel=1e-4, abs=1e-6
                ), key
        p_seq = jax.tree_util.tree_leaves(tr_seq.state.params)
        p_fused = jax.tree_util.tree_leaves(tr_fused.state.params)
        for a, b in zip(p_seq, p_fused):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            )

    def test_single_batch_delegates(self, network, tiny_train_config):
        trainer = Trainer(network, tiny_train_config)
        out = trainer.train_steps([make_batch()])
        assert len(out) == 1
        assert trainer.global_step == 1

    def test_empty_list(self, network, tiny_train_config):
        trainer = Trainer(network, tiny_train_config)
        assert trainer.train_steps([]) == []
        assert trainer.global_step == 0

    def test_host_step_mirrors_device_step(self, network, tiny_train_config):
        trainer = Trainer(network, tiny_train_config)
        trainer.train_step(make_batch())
        trainer.train_steps([make_batch(seed=1), make_batch(seed=2)])
        assert trainer.global_step == 3
        assert int(trainer.state.step) == 3


class TestTensorParallel:
    """Real mdl-axis tensor parallelism: transformer params shard
    Megatron-style over the mesh's mdl axis, results match the
    replicated learner, and the eval wrapper receives whole tensors."""

    def _tx_config(self, tiny_model_config):
        return tiny_model_config.model_copy(
            update={
                "USE_TRANSFORMER": True,
                "TRANSFORMER_LAYERS": 1,
                "TRANSFORMER_DIM": 8,
                "TRANSFORMER_HEADS": 2,
                "TRANSFORMER_FC_DIM": 16,
            }
        )

    def test_tp_matches_replicated(
        self, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        from jax.sharding import PartitionSpec as P

        from alphatriangle_tpu.config import MeshConfig

        mc = self._tx_config(tiny_model_config)
        batch = make_batch(16, seed=3)

        net_rep = NeuralNetwork(mc, tiny_env_config, seed=0)
        tr_rep = Trainer(
            net_rep,
            tiny_train_config,
            mesh=MeshConfig(DP_SIZE=8).build_mesh(),
        )
        net_tp = NeuralNetwork(mc, tiny_env_config, seed=0)
        tr_tp = Trainer(
            net_tp,
            tiny_train_config,
            mesh=MeshConfig(DP_SIZE=4, MDL_SIZE=2).build_mesh(),
        )
        assert tr_tp.tp_size == 2

        # Transformer QKV kernels sharded on heads; MLP Dense_0 on
        # columns; everything else replicated.
        def spec_of(substr):
            flat = jax.tree_util.tree_flatten_with_path(
                tr_tp.state.params
            )[0]
            for path, leaf in flat:
                name = "/".join(str(k.key) for k in path)
                if substr in name:
                    return name, leaf.sharding.spec
            raise AssertionError(f"no param matching {substr}")

        _, qspec = spec_of("query/kernel")
        assert qspec == P(None, "mdl", None)
        _, d0spec = spec_of("TransformerEncoderLayer_0/Dense_0/kernel")
        assert d0spec == P(None, "mdl")
        # The top-level shared-FC Dense_0 is NOT a transformer MLP and
        # stays replicated.
        flat = jax.tree_util.tree_flatten_with_path(tr_tp.state.params)[0]
        for path, leaf in flat:
            name = "/".join(str(k.key) for k in path)
            if name == "Dense_0/kernel":
                assert leaf.sharding.spec == P()
        _, convspec = spec_of("ConvBlock_0/Conv_0/kernel")
        assert convspec == P()

        out_rep = tr_rep.train_step(dict(batch))
        out_tp = tr_tp.train_step(dict(batch))
        m_rep, td_rep = out_rep
        m_tp, td_tp = out_tp
        np.testing.assert_allclose(td_rep, td_tp, rtol=1e-4, atol=1e-5)
        for key in m_rep:
            assert m_rep[key] == pytest.approx(
                m_tp[key], rel=1e-3, abs=1e-5
            ), key

        # Weight sync gathers shards: the eval wrapper gets whole,
        # single-device tensors and still evaluates.
        tr_tp.sync_to_network()
        leaves = jax.tree_util.tree_leaves(net_tp.variables["params"])
        assert all(
            len(leaf.sharding.device_set) == 1 for leaf in leaves
        )
        policy, value = net_tp.evaluate_features(
            np.asarray(batch["grid"]), np.asarray(batch["other_features"])
        )
        assert np.all(np.isfinite(np.asarray(policy)))

    def test_indivisible_widths_fall_back_to_replication(
        self, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        """Widths that don't divide the mdl axis replicate (never
        crash, never shard unevenly)."""
        from jax.sharding import PartitionSpec as P

        from alphatriangle_tpu.config import MeshConfig

        mc = self._tx_config(tiny_model_config).model_copy(
            update={"TRANSFORMER_HEADS": 1}  # 1 head % mdl=2 != 0
        )
        net = NeuralNetwork(mc, tiny_env_config, seed=0)
        tr = Trainer(
            net,
            tiny_train_config,
            mesh=MeshConfig(DP_SIZE=4, MDL_SIZE=2).build_mesh(),
        )
        flat = jax.tree_util.tree_flatten_with_path(tr.state.params)[0]
        for path, leaf in flat:
            name = "/".join(str(k.key) for k in path)
            if "query/kernel" in name:
                assert leaf.sharding.spec == P()
        assert tr.train_step(make_batch(16)) is not None


class TestPipelinedSteps:
    """`train_steps_begin`/`train_steps_finish`: the overlapped loop's
    double-buffered dispatch path must be bit-equivalent to serial
    `train_step` calls, even with two groups in flight."""

    def test_two_inflight_groups_match_sequential(
        self, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        batches = [make_batch(seed=i) for i in range(4)]
        net_a = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        net_b = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        tr_seq = Trainer(net_a, tiny_train_config)
        tr_pipe = Trainer(net_b, tiny_train_config)

        seq = [tr_seq.train_step(b) for b in batches]
        # Dispatch BOTH groups before fetching either (pipeline depth 2).
        h1 = tr_pipe.train_steps_begin(batches[:2])
        h2 = tr_pipe.train_steps_begin(batches[2:])
        piped = tr_pipe.train_steps_finish(h1) + tr_pipe.train_steps_finish(
            h2
        )

        assert tr_pipe.global_step == 4
        assert int(tr_pipe.state.step) == 4
        for (m_s, td_s), (m_p, td_p) in zip(seq, piped):
            np.testing.assert_allclose(td_s, td_p, rtol=1e-5, atol=1e-6)
            for key in m_s:
                assert m_s[key] == pytest.approx(
                    m_p[key], rel=1e-4, abs=1e-6
                ), key
        for a, b in zip(
            jax.tree_util.tree_leaves(tr_seq.state.params),
            jax.tree_util.tree_leaves(tr_pipe.state.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            )

    def test_single_batch_group(self, network, tiny_train_config):
        """A 1-batch group rides the per-step program but still follows
        the begin/finish contract."""
        trainer = Trainer(network, tiny_train_config)
        handle = trainer.train_steps_begin([make_batch()])
        assert handle is not None and handle["k"] == 1
        assert trainer.global_step == 1  # dispatch advances the clock
        outs = trainer.train_steps_finish(handle)
        assert len(outs) == 1
        metrics, td = outs[0]
        assert np.isfinite(metrics["total_loss"])
        assert td.shape == (B,)

    def test_begin_empty_returns_none(self, network, tiny_train_config):
        trainer = Trainer(network, tiny_train_config)
        assert trainer.train_steps_begin([]) is None
        assert trainer.global_step == 0

    def test_lr_labels_per_step(self, network, tiny_train_config):
        """Per-step learning rates in a fetched group match the
        schedule at each step's own index, not the group end."""
        trainer = Trainer(network, tiny_train_config)
        h = trainer.train_steps_begin([make_batch(seed=i) for i in range(3)])
        outs = trainer.train_steps_finish(h)
        for i, (m, _) in enumerate(outs):
            assert m["learning_rate"] == pytest.approx(
                float(trainer.schedule(i + 1))
            )


class TestBatchNormPath:
    def test_batch_stats_updated(self, tiny_model_config, tiny_env_config):
        bn_cfg = tiny_model_config.model_copy(update={"NORM_TYPE": "batch"})
        net = NeuralNetwork(bn_cfg, tiny_env_config, seed=0)
        cfg = TrainConfig(
            BATCH_SIZE=4, BUFFER_CAPACITY=100, MIN_BUFFER_SIZE_TO_TRAIN=10,
            USE_PER=False, MAX_TRAINING_STEPS=10, RUN_NAME="bn",
        )
        trainer = Trainer(net, cfg)
        assert trainer.state.batch_stats
        before = jax.tree_util.tree_map(np.asarray, trainer.state.batch_stats)
        trainer.train_step(make_batch())
        changed = jax.tree_util.tree_map(
            lambda a, b: not np.allclose(a, np.asarray(b)),
            before,
            trainer.state.batch_stats,
        )
        assert any(jax.tree_util.tree_leaves(changed))


class TestMultiDevice:
    """VERDICT #3 'Done =' criteria: dp-sharded batch, params change,
    replicas stay bit-identical, and the result matches single-device."""

    def test_8dev_step_matches_single_device(
        self, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        assert len(jax.devices()) == 8
        mesh = MeshConfig(DP_SIZE=8, MDL_SIZE=1).build_mesh()
        batch = make_batch(16, seed=7)

        net1 = NeuralNetwork(tiny_model_config, tiny_env_config, seed=3)
        t_single = Trainer(net1, tiny_train_config)
        t_single.train_step(batch)
        single_params = jax.tree_util.tree_map(
            np.asarray, t_single.state.params
        )

        net8 = NeuralNetwork(tiny_model_config, tiny_env_config, seed=3)
        t_mesh = Trainer(net8, tiny_train_config, mesh=mesh)
        out = t_mesh.train_step(batch)
        assert out is not None

        # Replicas bit-identical across all 8 devices (the grad
        # all-reduce actually ran and agreed).
        leaf = jax.tree_util.tree_leaves(t_mesh.state.params)[0]
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        assert len(shards) == 8
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)

        # Multi-device result matches the single-device step.
        mesh_params = jax.tree_util.tree_map(np.asarray, t_mesh.state.params)
        flat_s = jax.tree_util.tree_leaves(single_params)
        flat_m = jax.tree_util.tree_leaves(mesh_params)
        for a, b in zip(flat_s, flat_m, strict=True):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)

    def test_custom_axis_names(
        self, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        mesh = MeshConfig(DP_SIZE=8, DP_AXIS="data", MDL_AXIS="model").build_mesh()
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        trainer = Trainer(net, tiny_train_config, mesh=mesh)
        assert trainer.dp_size == 8
        out = trainer.train_step(make_batch(16))
        assert out is not None
        with pytest.raises(ValueError, match="not divisible"):
            trainer.train_step(make_batch(12))

    def test_set_state_does_not_alias_caller(
        self, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        trainer = Trainer(net, tiny_train_config)
        trainer.train_step(make_batch())
        saved = trainer.state
        trainer.set_state(saved)
        trainer.train_step(make_batch(seed=1))
        # The caller's snapshot must survive the donated step.
        assert all(
            np.isfinite(np.asarray(leaf)).all()
            for leaf in jax.tree_util.tree_leaves(saved.params)
        )

    def test_indivisible_batch_raises(
        self, tiny_model_config, tiny_env_config, tiny_train_config
    ):
        mesh = MeshConfig(DP_SIZE=8, MDL_SIZE=1).build_mesh()
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        trainer = Trainer(net, tiny_train_config, mesh=mesh)
        with pytest.raises(ValueError, match="not divisible"):
            trainer.train_step(make_batch(6))
