"""Roofline attribution plane (telemetry/roofline.py, `cli roofline`;
docs/OBSERVABILITY.md "Roofline & gap attribution").

The reader-side tests here are JAX-free and fast: peak-bandwidth
resolution, machine balance, cost-record extraction, the roofline join
against flight rows, gap forensics over synthetic flight timelines,
and the CLI/legacy degradation contract (pre-roofline run dirs must
render with ZERO new fields — the same tolerance bar as the beacon and
device-stats suites). The compile-cache capture leg (real
`cost_analysis()` on compiled programs, sidecar round-trips, torn-file
recovery) needs JAX and lives at the bottom. Real-run integration is
`make roofline-smoke`, not here, to keep tier-1 fast.
"""

import json

import pytest

from alphatriangle_tpu.cli import main as cli_main
from alphatriangle_tpu.telemetry.flight import FLIGHT_FILENAME
from alphatriangle_tpu.telemetry.ledger import MetricsLedger, read_ledger
from alphatriangle_tpu.telemetry.perf import (
    COMPARE_METRICS,
    LOWER_IS_BETTER,
    UtilizationMeter,
    summarize_utilization,
)
from alphatriangle_tpu.telemetry.roofline import (
    COST_PRECAPTURE_ENV,
    GAP_CATEGORIES,
    PEAK_HBM_GBPS_ENV,
    attribute_gaps,
    cost_precapture_enabled,
    cost_flops_by_family,
    load_trace_spans,
    machine_balance_flops_per_byte,
    peak_hbm_gbps_info,
    program_cost_record,
    roofline_rows,
    summarize_roofline,
)

from tests.test_ledger import FakeClock, synthetic_run


class FakeCompiled:
    """Stands in for jax.stages.Compiled: cost_analysis only."""

    def __init__(self, stats):
        self._stats = stats

    def cost_analysis(self):
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


def _cost(program, flops, bytes_accessed, transcendentals=0.0):
    return {
        "kind": "cost",
        "category": "program",
        "component": f"program/{program}",
        "program": program,
        "key": "k",
        "backend": "cpu",
        "origin": "compile",
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "transcendentals": transcendentals,
        "time": 100.0,
    }


def _intent(seq, t_mono, program="megastep/t4_k2", **kw):
    return {
        "kind": "flight", "phase": "intent", "seq": seq,
        "program": program, "family": "megastep",
        "t_mono": float(t_mono), "time": kw.pop("time", 100.0 + t_mono),
        **kw,
    }


def _seal(seq, t_mono, program="megastep/t4_k2", wall_s=1.0, **kw):
    return {
        "kind": "flight", "phase": "seal", "seq": seq,
        "program": program, "family": "megastep", "ok": True,
        "wall_s": wall_s, "t_mono": float(t_mono),
        "time": kw.pop("time", 100.0 + t_mono), **kw,
    }


class TestPeakHbm:
    def test_table_lookup(self, monkeypatch):
        monkeypatch.delenv(PEAK_HBM_GBPS_ENV, raising=False)
        assert peak_hbm_gbps_info("TPU v4") == (1228.0, "table")
        assert peak_hbm_gbps_info("TPU v5e") == (819.0, "table")
        assert peak_hbm_gbps_info("TPU v5p") == (2765.0, "table")

    def test_prefix_fallback_matches_runtime_variants(self, monkeypatch):
        monkeypatch.delenv(PEAK_HBM_GBPS_ENV, raising=False)
        assert peak_hbm_gbps_info("TPU v5litepod-8") == (819.0, "table")
        assert peak_hbm_gbps_info("TPU v4 megacore") == (1228.0, "table")

    def test_unknown_is_explicit_not_guessed(self, monkeypatch):
        monkeypatch.delenv(PEAK_HBM_GBPS_ENV, raising=False)
        assert peak_hbm_gbps_info("Quantum Q1") == (None, "unknown")
        assert peak_hbm_gbps_info("") == (None, "unknown")

    def test_env_override_wins_with_provenance(self, monkeypatch):
        monkeypatch.setenv(PEAK_HBM_GBPS_ENV, "42.5")
        assert peak_hbm_gbps_info("TPU v4") == (42.5, "env")
        assert peak_hbm_gbps_info("cpu") == (42.5, "env")

    def test_bad_env_values_ignored(self, monkeypatch):
        monkeypatch.setenv(PEAK_HBM_GBPS_ENV, "not-a-number")
        assert peak_hbm_gbps_info("TPU v4") == (1228.0, "table")
        monkeypatch.setenv(PEAK_HBM_GBPS_ENV, "-3")
        assert peak_hbm_gbps_info("TPU v4") == (1228.0, "table")


class TestCostPrecaptureKnob:
    def test_default_on_suite_off(self, monkeypatch):
        # conftest turns it off for the whole suite (and subprocess
        # children); the default everywhere else is on.
        assert not cost_precapture_enabled()
        monkeypatch.delenv(COST_PRECAPTURE_ENV, raising=False)
        assert cost_precapture_enabled()
        monkeypatch.setenv(COST_PRECAPTURE_ENV, "0")
        assert not cost_precapture_enabled()
        monkeypatch.setenv(COST_PRECAPTURE_ENV, "1")
        assert cost_precapture_enabled()


class TestMachineBalance:
    def test_v4_balance(self):
        # 275 TFLOP/s over 1228 GB/s ~= 224 FLOPs/byte.
        balance = machine_balance_flops_per_byte(275.0, 1228.0)
        assert balance == pytest.approx(275e12 / 1228e9)

    def test_unknown_peaks_yield_none(self):
        assert machine_balance_flops_per_byte(None, 1228.0) is None
        assert machine_balance_flops_per_byte(275.0, None) is None
        assert machine_balance_flops_per_byte(0.0, 1228.0) is None


class TestProgramCostRecord:
    def test_dict_shape(self):
        rec = program_cost_record(
            "megastep/t4_k2",
            FakeCompiled(
                {"flops": 1e9, "bytes accessed": 2e6, "transcendentals": 7.0}
            ),
            backend="cpu",
            key="abc",
        )
        assert rec["kind"] == "cost"
        assert rec["program"] == "megastep/t4_k2"
        assert rec["component"] == "program/megastep/t4_k2"
        assert rec["flops"] == 1e9
        assert rec["bytes_accessed"] == 2e6
        assert rec["transcendentals"] == 7.0
        assert rec["origin"] == "compile"

    def test_legacy_list_of_dicts_shape(self):
        rec = program_cost_record(
            "p", FakeCompiled([{"flops": 5.0, "bytes accessed": 2.0}])
        )
        assert rec["flops"] == 5.0
        assert rec["bytes_accessed"] == 2.0

    def test_degrades_to_none(self):
        assert program_cost_record("p", object()) is None
        assert program_cost_record("p", FakeCompiled(RuntimeError())) is None
        assert program_cost_record("p", FakeCompiled({})) is None
        assert program_cost_record("p", FakeCompiled("bogus")) is None


class TestCostFlopsByFamily:
    def test_hottest_program_per_family_wins(self):
        records = [
            _cost("megastep/t4_k2", 1e9, 1e6),
            _cost("megastep/t8_k2", 4e9, 1e6),
            _cost("self_play_chunk/t4", 2e8, 1e6),
            _cost("learner_step/b8", 0.0, 1e6),  # non-positive: skipped
        ]
        fams = cost_flops_by_family(records)
        assert fams["megastep"] == 4e9
        assert fams["rollout"] == 2e8
        assert "learner" not in fams

    def test_non_cost_rows_skipped(self):
        assert cost_flops_by_family([{"kind": "util"}, "torn", None]) == {}


class TestRooflineRows:
    def _flight_row(self, program, p50=0.5, total=5.0, count=10):
        return {
            "program": program, "family": "megastep", "count": count,
            "errors": 0, "wall_s_p50": p50, "wall_s_p95": p50,
            "wall_s_total": total,
        }

    def test_compute_bound_join(self):
        # balance = 1e12 / 1e9 = 1000 FLOPs/byte; intensity 2000 is
        # compute-bound, ceiling = peak FLOP/s.
        [row] = roofline_rows(
            [_cost("megastep/t4_k2", 2e9, 1e6)],
            [self._flight_row("megastep/t4_k2", p50=0.5)],
            peak_tflops=1.0,
            peak_hbm_gbps=1.0,
        )
        assert row["intensity"] == pytest.approx(2000.0)
        assert row["bound"] == "compute"
        assert row["achieved_tflops"] == pytest.approx(2e9 / 0.5 / 1e12)
        assert row["roofline_tflops"] == pytest.approx(1.0)
        assert row["roofline_fraction"] == pytest.approx(0.004)

    def test_memory_bound_ceiling_is_bandwidth(self):
        # intensity 0.5 < balance 1000: ceiling = 0.5 * 1 GB/s = 5e8.
        [row] = roofline_rows(
            [_cost("megastep/t4_k2", 5e5, 1e6)],
            [self._flight_row("megastep/t4_k2", p50=0.001)],
            peak_tflops=1.0,
            peak_hbm_gbps=1.0,
        )
        assert row["bound"] == "memory"
        assert row["roofline_tflops"] == pytest.approx(5e8 / 1e12)
        assert row["roofline_fraction"] == pytest.approx(
            (5e5 / 0.001) / 5e8
        )

    def test_missing_cost_record_degrades_to_na_row(self):
        # A legacy run's flight ring without cost sidecars still rows.
        [row] = roofline_rows(
            [], [self._flight_row("serve/b4")], peak_tflops=1.0,
            peak_hbm_gbps=1.0,
        )
        assert row["program"] == "serve/b4"
        assert row["flops"] is None
        assert row["intensity"] is None
        assert row["bound"] is None
        assert row["roofline_fraction"] is None

    def test_unknown_peaks_classify_nothing(self):
        [row] = roofline_rows(
            [_cost("megastep/t4_k2", 2e9, 1e6)],
            [self._flight_row("megastep/t4_k2")],
        )
        assert row["intensity"] == pytest.approx(2000.0)
        assert row["bound"] is None
        assert row["roofline_fraction"] is None


class TestAttributeGaps:
    def test_too_few_records_is_none(self):
        assert attribute_gaps([]) is None
        assert attribute_gaps([_intent(1, 0.0)]) is None
        assert attribute_gaps([{"kind": "flight"}, {"no": "stamp"}]) is None

    def test_dispatch_and_gap_cover_the_timeline(self):
        records = [
            _intent(1, 0.0), _seal(1, 1.0),
            _intent(2, 2.0), _seal(2, 3.0),
        ]
        a = attribute_gaps(records)
        assert a["wall_s"] == pytest.approx(3.0)
        assert a["dispatch_s"] == pytest.approx(2.0)
        assert a["gap_s"] == pytest.approx(1.0)
        assert a["chip_idle_fraction"] == pytest.approx(1.0 / 3.0)
        assert a["attributed_fraction"] == pytest.approx(1.0)
        assert a["dispatches"] == 2
        assert a["unsealed"] == 0
        # No spans: the whole gap lands in "other", nothing dropped.
        assert a["gaps"]["other"] == pytest.approx(1.0)
        assert set(a["gaps"]) == set(GAP_CATEGORIES)

    def test_span_overlap_attributes_gap_categories(self):
        # mono->wall offset is exactly +100 in the helpers; the gap is
        # mono [1, 2] == wall [101, 102]. A 0.6s fetch span inside it
        # claims 0.6, the residual 0.4 lands in "other".
        records = [
            _intent(1, 0.0), _seal(1, 1.0),
            _intent(2, 2.0), _seal(2, 3.0),
        ]
        spans = [("fetch", 101.2, 101.8)]
        a = attribute_gaps(records, spans=spans)
        assert a["gaps"]["fetch"] == pytest.approx(0.6)
        assert a["gaps"]["other"] == pytest.approx(0.4)
        assert a["attributed_fraction"] == pytest.approx(1.0)

    def test_overclaimed_gap_scales_proportionally(self):
        # Two overlapping span categories claim 1.5s of a 1.0s gap:
        # both scale by 2/3, "other" gets nothing, total stays 1.0.
        records = [
            _intent(1, 0.0), _seal(1, 1.0),
            _intent(2, 2.0), _seal(2, 3.0),
        ]
        spans = [("fetch", 101.0, 102.0), ("ingest", 101.5, 102.0)]
        a = attribute_gaps(records, spans=spans)
        assert a["gaps"]["fetch"] == pytest.approx(1.0 * (1.0 / 1.5))
        assert a["gaps"]["ingest"] == pytest.approx(0.5 * (1.0 / 1.5))
        assert a["gaps"]["other"] == pytest.approx(0.0)
        assert sum(a["gaps"].values()) == pytest.approx(a["gap_s"])

    def test_unsealed_intent_counted_not_attributed(self):
        records = [
            _intent(1, 0.0), _seal(1, 1.0),
            _intent(2, 2.0),  # died in flight
        ]
        a = attribute_gaps(records)
        assert a["unsealed"] == 1
        assert a["dispatches"] == 1

    def test_overlapping_dispatches_merge(self):
        # Pipelined programs (overlapped loop): two in-flight intervals
        # overlapping [0,2] and [1,3] are 3s busy, not 4.
        records = [
            _intent(1, 0.0), _intent(2, 1.0),
            _seal(1, 2.0), _seal(2, 3.0),
        ]
        a = attribute_gaps(records)
        assert a["dispatch_s"] == pytest.approx(3.0)
        assert a["gap_s"] == pytest.approx(0.0)
        assert a["chip_idle_fraction"] == pytest.approx(0.0)


class TestLoadTraceSpans:
    def test_reads_categorized_complete_events(self, tmp_path):
        trace = tmp_path / "trace.json"
        trace.write_text(json.dumps({
            "traceEvents": [
                {"ph": "X", "name": "fetch_results", "ts": 1_000_000,
                 "dur": 500_000},
                {"ph": "X", "name": "checkpoint", "ts": 2_000_000,
                 "dur": 100_000},
                {"ph": "X", "name": "mystery_phase", "ts": 0, "dur": 1},
                {"ph": "B", "name": "fetch", "ts": 0},
                {"ph": "X", "name": "fold", "ts": 5, "dur": 0},
            ]
        }))
        spans = load_trace_spans(trace)
        assert spans == [
            ("fetch", 1.0, 1.5),
            ("checkpoint", 2.0, 2.1),
        ]

    def test_missing_or_corrupt_trace_degrades_to_empty(self, tmp_path):
        assert load_trace_spans(tmp_path / "ghost.json") == []
        bad = tmp_path / "trace.json"
        bad.write_text("{torn")
        assert load_trace_spans(bad) == []


class TestSummarizeRoofline:
    def test_none_when_no_evidence(self):
        assert summarize_roofline([], []) is None

    def test_full_summary_schema(self, monkeypatch):
        monkeypatch.setenv(PEAK_HBM_GBPS_ENV, "1.0")
        records = [
            _intent(1, 0.0), _seal(1, 1.0),
            _intent(2, 2.0), _seal(2, 3.0),
        ]
        s = summarize_roofline(
            [_cost("megastep/t4_k2", 2e9, 1e6)],
            records,
            device_kind="cpu",
            peak_tflops=1.0,
        )
        assert s["schema"] == "alphatriangle.roofline.v1"
        assert s["peak_hbm_gbps"] == 1.0
        assert s["peak_hbm_source"] == "env"
        assert s["machine_balance_flops_per_byte"] == pytest.approx(1000.0)
        [row] = s["programs"]
        assert row["bound"] == "compute"
        assert s["attribution"]["chip_idle_fraction"] == pytest.approx(
            1.0 / 3.0
        )

    def test_flight_only_run_still_attributes(self, monkeypatch):
        # Cost records absent (legacy sidecars lost): gap forensics
        # still works, rows degrade instead of vanishing.
        monkeypatch.delenv(PEAK_HBM_GBPS_ENV, raising=False)
        records = [_intent(1, 0.0), _seal(1, 1.0), _intent(2, 2.0),
                   _seal(2, 3.0)]
        s = summarize_roofline([], records, device_kind="cpu")
        assert s is not None
        assert s["attribution"]["dispatches"] == 2
        [row] = s["programs"]
        assert row["flops"] is None


class TestChipIdleGauge:
    """UtilizationMeter.tick's live counterpart of attribute_gaps."""

    def _meter(self, clock):
        return UtilizationMeter(
            forward_flops=1_000_000,
            train_step_flops=50_000_000,
            device_kind="cpu",
            buffer_capacity=1000,
            clock=clock,
        )

    def test_idle_fraction_from_consecutive_counters(self):
        clock = FakeClock()
        meter = self._meter(clock)
        assert meter.tick(step=0, dispatch_wall_s=0.0) is None
        clock.advance(2.0)
        rec = meter.tick(step=10, dispatch_wall_s=1.5)
        assert rec["chip_idle_fraction"] == pytest.approx(0.25)

    def test_legacy_wiring_emits_no_field(self):
        clock = FakeClock()
        meter = self._meter(clock)
        meter.tick(step=0)
        clock.advance(2.0)
        rec = meter.tick(step=10)
        assert "chip_idle_fraction" not in rec

    def test_counter_appearing_mid_run_waits_one_tick(self):
        # Flight recorder attached late: the first tick that carries
        # the counter has no baseline, so no delta is invented.
        clock = FakeClock()
        meter = self._meter(clock)
        meter.tick(step=0)
        clock.advance(2.0)
        rec = meter.tick(step=10, dispatch_wall_s=1.0)
        assert "chip_idle_fraction" not in rec
        clock.advance(2.0)
        rec = meter.tick(step=20, dispatch_wall_s=2.0)
        assert rec["chip_idle_fraction"] == pytest.approx(0.5)

    def test_clamped_to_unit_interval(self):
        # Pipelined dispatch can exceed the window (overlap) — clamp,
        # never a negative idle fraction.
        clock = FakeClock()
        meter = self._meter(clock)
        meter.tick(step=0, dispatch_wall_s=0.0)
        clock.advance(1.0)
        rec = meter.tick(step=10, dispatch_wall_s=5.0)
        assert rec["chip_idle_fraction"] == 0.0

    def test_summary_folds_mean_and_max(self):
        clock = FakeClock()
        meter = self._meter(clock)
        records = []
        walls = [0.0, 1.0, 1.5, 3.5]
        for i, w in enumerate(walls):
            rec = meter.tick(step=i * 10, dispatch_wall_s=w)
            if rec is not None:
                records.append(rec)
            clock.advance(2.0)
        s = summarize_utilization(records)
        # idle fractions: 0.5, 0.75, 0.0
        assert s["chip_idle_fraction"] == pytest.approx(
            (0.5 + 0.75 + 0.0) / 3
        )
        assert s["chip_idle_fraction_max"] == pytest.approx(0.75)

    def test_compare_gates_idle_lower_is_better(self):
        assert "chip_idle_fraction" in COMPARE_METRICS
        assert "chip_idle_fraction" in LOWER_IS_BETTER


class TestLegacyRooflineTolerance:
    """Run dirs from BEFORE the roofline plane existed (no
    `kind:"cost"` records, no dispatch-wall counter on util ticks)
    must keep reading exactly as they always did: no roofline_* keys
    invented, no idle line printed, compare still clean — even though
    such runs may well carry a flight.jsonl."""

    def test_perf_json_has_no_roofline_fields(self, tmp_path, capsys):
        run = synthetic_run(tmp_path)
        rc = cli_main(["perf", str(run), "--json"])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        assert not [k for k in summary if k.startswith("roofline_")]
        assert "chip_idle_fraction" not in summary

    def test_perf_with_flight_but_no_cost_stays_legacy(
        self, tmp_path, capsys
    ):
        # PR-18-era run: flight ring present, zero cost records. The
        # perf fold is gated on cost records, so even the attribution
        # (computable from flight alone) must NOT appear.
        run = synthetic_run(tmp_path)
        lines = [
            _intent(1, 0.0), _seal(1, 1.0),
            _intent(2, 2.0), _seal(2, 3.0),
        ]
        (run / FLIGHT_FILENAME).write_text(
            "".join(json.dumps(r) + "\n" for r in lines)
        )
        rc = cli_main(["perf", str(run), "--json"])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        assert not [k for k in summary if k.startswith("roofline_")]
        for row in summary.get("programs") or []:
            assert "intensity" not in row
            assert "bound" not in row
        capsys.readouterr()
        rc = cli_main(["perf", str(run)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "roofline" not in out
        assert "intensity" not in out

    def test_cli_roofline_exits_2_on_legacy_run(self, tmp_path, capsys):
        run = synthetic_run(tmp_path)
        rc = cli_main(["roofline", str(run)])
        err = capsys.readouterr().err
        assert rc == 2
        assert "no cost records or flight timeline" in err

    def test_cli_roofline_renders_cost_run(self, tmp_path, capsys):
        run = synthetic_run(tmp_path)
        led = MetricsLedger(run / "metrics.jsonl")
        led.append(_cost("megastep/t4_k2", 2e9, 1e6))
        lines = [
            _intent(1, 0.0), _seal(1, 1.0),
            _intent(2, 2.0), _seal(2, 3.0),
        ]
        (run / FLIGHT_FILENAME).write_text(
            "".join(json.dumps(r) + "\n" for r in lines)
        )
        rc = cli_main(["roofline", str(run), "--json"])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["schema"] == "alphatriangle.roofline.v1"
        assert summary["attribution"]["dispatches"] == 2
        [row] = summary["programs"]
        assert row["flops"] == 2e9
        capsys.readouterr()
        assert cli_main(["roofline", str(run)]) == 0
        out = capsys.readouterr().out
        assert "megastep/t4_k2" in out
        assert "idle" in out

    def test_torn_cost_ledger_line_skipped(self, tmp_path, capsys):
        run = synthetic_run(tmp_path)
        led = MetricsLedger(run / "metrics.jsonl")
        led.append(_cost("megastep/t4_k2", 2e9, 1e6))
        with (run / "metrics.jsonl").open("a") as fh:
            fh.write('{"kind": "cost", "program": "torn')  # SIGKILL
        recs = read_ledger(run / "metrics.jsonl", kinds={"cost"})
        assert len(recs) == 1
        rc = cli_main(["perf", str(run), "--json"])
        assert rc == 0

    def test_compare_legacy_vs_roofline_reference_clean(
        self, tmp_path, capsys
    ):
        """A reference regenerated WITH the new fields must not regress
        a legacy run: chip_idle_fraction is gated on both sides
        carrying it, roofline_* keys are not in COMPARE_METRICS."""
        run = synthetic_run(tmp_path)
        rc = cli_main(["perf", str(run), "--json"])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        ref = dict(
            summary,
            chip_idle_fraction=0.05,
            roofline_attributed_fraction=0.99,
            roofline_chip_idle_fraction=0.04,
        )
        ref_path = tmp_path / "ref_roofline.json"
        ref_path.write_text(json.dumps(ref))
        assert cli_main(["compare", str(run), str(ref_path)]) == 0

    def test_watch_renders_no_idle_line_on_legacy(self):
        from alphatriangle_tpu.stats.watch import idle_line

        assert idle_line({}) is None
        assert idle_line({"mfu": 0.5, "steps_per_sec": 1.0}) is None

    def test_watch_idle_line_flags_host_bound(self):
        from alphatriangle_tpu.stats.watch import idle_line

        line = idle_line({"chip_idle_fraction": 0.12})
        assert "12.0%" in line
        assert "HOST-BOUND" not in line
        assert "HOST-BOUND?" in idle_line({"chip_idle_fraction": 0.61})


class TestAutotuneCostAnchor:
    def test_cost_anchored_efficiency(self):
        from alphatriangle_tpu.autotune.model import (
            cost_anchored_efficiency,
        )

        # 2e9 FLOPs over 0.5s on a 1-TFLOP peak: 0.4% efficiency.
        eff = cost_anchored_efficiency(
            {"megastep": 2e9}, {"megastep": 0.5}, 1.0
        )
        assert eff == pytest.approx(2e9 / 0.5 / 1e12)

    def test_anchor_requires_both_sides(self):
        from alphatriangle_tpu.autotune.model import (
            cost_anchored_efficiency,
        )

        assert cost_anchored_efficiency({}, {"megastep": 0.5}, 1.0) is None
        assert cost_anchored_efficiency({"megastep": 2e9}, {}, 1.0) is None
        assert (
            cost_anchored_efficiency({"megastep": 2e9}, {"megastep": 0.5},
                                     None)
            is None
        )

    def test_implausible_ratio_rejected(self):
        from alphatriangle_tpu.autotune.model import (
            cost_anchored_efficiency,
        )

        # Above-peak implied efficiency means clock skew or a torn
        # record — never anchor on it.
        assert (
            cost_anchored_efficiency({"megastep": 2e12}, {"megastep": 0.5},
                                     1.0)
            is None
        )

    def test_calibration_round_trips_cost_flops(self):
        from alphatriangle_tpu.autotune.model import Calibration

        cal = Calibration(cost_flops={"megastep": 2e9})
        assert cal.as_dict()["cost_flops"] == {"megastep": 2e9}

    def test_merge_calibrations_means_cost_flops(self):
        from alphatriangle_tpu.autotune.model import (
            Calibration,
            merge_calibrations,
        )

        a = Calibration(cost_flops={"megastep": 2e9, "serve": 1e6})
        b = Calibration(cost_flops={"megastep": 4e9})
        merged = merge_calibrations([a, b])
        assert merged.cost_flops["megastep"] == pytest.approx(3e9)
        assert merged.cost_flops["serve"] == pytest.approx(1e6)


class TestCostSidecarCapture:
    """The JAX-dependent writer leg: real cost_analysis() capture on
    compiled programs, `.cost.json` sidecar round-trips, and torn-file
    recovery (the same degradation bar as the `.mem.json` tests)."""

    def test_capture_on_compile_and_sidecar_on_hit(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from alphatriangle_tpu.compile_cache import reset_compile_cache

        cache = reset_compile_cache(cache_dir=str(tmp_path / "aot"))
        try:
            fn = cache.wrap("costtest", jax.jit(lambda x: x @ x + 1.0))
            fn(jnp.ones((16, 16), jnp.float32))
            [rec] = cache.cost_summary()
            assert rec["program"] == "costtest"
            assert rec["origin"] == "compile"
            assert rec["flops"] and rec["flops"] > 0
            sidecars = list((tmp_path / "aot").glob("*.cost.json"))
            assert len(sidecars) == 1
            assert json.loads(sidecars[0].read_text())["kind"] == "cost"

            # Fresh cache object, same dir: the AOT hit re-attributes
            # from the persisted sidecar without re-analyzing.
            cache2 = reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            fn2 = cache2.wrap("costtest", jax.jit(lambda x: x @ x + 1.0))
            fn2(jnp.ones((16, 16), jnp.float32))
            assert cache2.hits == 1
            [rec2] = cache2.cost_summary()
            assert rec2["origin"] == "sidecar"
            assert rec2["flops"] == rec["flops"]
        finally:
            reset_compile_cache()

    def test_torn_sidecar_recaptured_on_hit(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from alphatriangle_tpu.compile_cache import reset_compile_cache

        cache = reset_compile_cache(cache_dir=str(tmp_path / "aot"))
        try:
            fn = cache.wrap("torntest", jax.jit(lambda x: x @ x))
            fn(jnp.ones((8, 8), jnp.float32))
            [sidecar] = list((tmp_path / "aot").glob("*.cost.json"))
            sidecar.write_text('{"kind": "cost", "torn')  # SIGKILL mid-write

            cache2 = reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            fn2 = cache2.wrap("torntest", jax.jit(lambda x: x @ x))
            fn2(jnp.ones((8, 8), jnp.float32))
            assert cache2.hits == 1
            [rec] = cache2.cost_summary()
            # Degraded to a fresh analysis of the reloaded executable,
            # never an exception.
            assert rec["origin"] == "compile"
            assert rec["flops"] and rec["flops"] > 0
        finally:
            reset_compile_cache()

    def test_legacy_artifact_without_sidecar_recaptured(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from alphatriangle_tpu.compile_cache import reset_compile_cache

        cache = reset_compile_cache(cache_dir=str(tmp_path / "aot"))
        try:
            fn = cache.wrap("legacytest", jax.jit(lambda x: x + 1.0))
            fn(jnp.ones(8, jnp.float32))
            for sidecar in (tmp_path / "aot").glob("*.cost.json"):
                sidecar.unlink()

            cache2 = reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            fn2 = cache2.wrap("legacytest", jax.jit(lambda x: x + 1.0))
            fn2(jnp.ones(8, jnp.float32))
            assert cache2.hits == 1
            assert len(cache2.cost_summary()) == 1
        finally:
            reset_compile_cache()

    def test_analyze_captures_cost_for_cpu_bypassed_program(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from alphatriangle_tpu.compile_cache import reset_compile_cache

        cache = reset_compile_cache(cache_dir=str(tmp_path / "aot"))
        try:
            fn = cache.wrap("bypassed", jax.jit(lambda x: x @ x),
                            cpu_aot=False)
            assert not fn.aot_active
            assert fn.analyze(jnp.ones((8, 8), jnp.float32)) is not None
            [rec] = cache.cost_summary()
            assert rec["program"] == "bypassed"
            # The cost sidecar persists even though the executable
            # never touches the artifact path (analyze's persist flag
            # guards only the .mem.json side).
            assert list((tmp_path / "aot").glob("*.cost.json"))
            assert list((tmp_path / "aot").glob("*.jaxexe")) == []
        finally:
            reset_compile_cache()
