"""The round-5 sweep script's window-critical logic, driven for real.

benchmarks/tpu_round5.sh runs unattended in rare healthy-chip windows;
a logic bug there silently wastes the round's one shot at hardware
numbers. These tests copy the script into a sandbox with a stub
bench.py and assert the behaviors the orchestration depends on:
resume-skip, BENCH_SECTIONS filtering, and the refusal to record
CPU-fallback or wedge-truncated partial rows.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

STUB_BENCH = """\
import json, os, sys
mode = os.environ.get("STUB_MODE", "tpu")
label_hint = os.environ.get("BENCH_CONFIG", "") or os.environ.get(
    "BENCH_RECIPE", ""
)
if mode == "tpu":
    print(json.dumps({
        "metric": "self_play_games_per_hour", "value": 1234.0,
        "unit": "games/hour", "vs_baseline": 0.12,
        "extra": {"backend": "tpu", "hint": label_hint},
    }))
elif mode == "cpu":
    print(json.dumps({
        "metric": "self_play_games_per_hour", "value": 99.0,
        "unit": "games/hour", "vs_baseline": 0.01,
        "extra": {"backend": "cpu"},
    }))
elif mode == "partial":
    print(json.dumps({
        "metric": "self_play_games_per_hour", "value": 777.0,
        "unit": "games/hour", "vs_baseline": 0.08,
        "extra": {"backend": "tpu", "partial": "self_play"},
    }))
elif mode == "silent":
    pass
sys.exit(0)
"""


@pytest.fixture()
def sandbox(tmp_path):
    bench_dir = tmp_path / "benchmarks"
    bench_dir.mkdir()
    script = (REPO / "benchmarks" / "tpu_round5.sh").read_text()
    (bench_dir / "tpu_round5.sh").write_text(script)
    (tmp_path / "bench.py").write_text(STUB_BENCH)
    return tmp_path


def run_sweep(sandbox, env=None, sections=None):
    full_env = {
        "PATH": "/usr/bin:/bin",
        "HOME": str(sandbox),
        "STUB_MODE": "tpu",
    }
    # The stub must run under THIS python; the script calls `python`.
    bindir = sandbox / "bin"
    bindir.mkdir(exist_ok=True)
    link = bindir / "python"
    if not link.exists():
        link.symlink_to(sys.executable)
    full_env["PATH"] = f"{bindir}:{full_env['PATH']}"
    if sections is not None:
        full_env["BENCH_SECTIONS"] = sections
    full_env.update(env or {})
    proc = subprocess.run(
        ["bash", str(sandbox / "benchmarks" / "tpu_round5.sh")],
        cwd=sandbox,
        env=full_env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    out = sandbox / "benchmarks" / "tpu_r5_results.jsonl"
    rows = (
        [json.loads(x) for x in out.read_text().splitlines() if x.strip()]
        if out.exists()
        else []
    )
    return proc, rows


def labels(rows):
    return [r["label"] for r in rows]


class TestSweepScript:
    def test_sections_filter_limits_to_named(self, sandbox):
        proc, rows = run_sweep(
            sandbox, sections="flagship_gumbel_pcr preset2"
        )
        assert proc.returncode == 0, proc.stderr
        assert labels(rows) == ["flagship_gumbel_pcr", "preset2"]
        # Per-section env vars reach the bench child (the stub echoes
        # BENCH_CONFIG/BENCH_RECIPE back as extra.hint).
        assert rows[1]["result"]["extra"]["hint"] == "2"

    def test_full_sweep_records_every_section(self, sandbox):
        proc, rows = run_sweep(sandbox)
        assert proc.returncode == 0, proc.stderr
        assert "sweep complete" in proc.stderr
        got = labels(rows)
        # Priority prefix the orchestrator depends on.
        assert got[:4] == [
            "flagship_gumbel_pcr", "flagship_puct", "preset2", "preset4",
        ]
        assert "flagship_profile" in got

    def test_resume_skips_recorded_sections(self, sandbox):
        run_sweep(sandbox, sections="flagship_gumbel_pcr")
        proc, rows = run_sweep(
            sandbox, sections="flagship_gumbel_pcr preset2"
        )
        assert proc.returncode == 0, proc.stderr
        assert "already recorded" in proc.stderr
        assert labels(rows) == ["flagship_gumbel_pcr", "preset2"]

    def test_cpu_fallback_aborts_without_recording(self, sandbox):
        proc, rows = run_sweep(sandbox, env={"STUB_MODE": "cpu"})
        assert proc.returncode == 1
        assert "backend != tpu" in proc.stderr
        assert rows == []

    def test_partial_row_aborts_without_recording(self, sandbox):
        proc, rows = run_sweep(sandbox, env={"STUB_MODE": "partial"})
        assert proc.returncode == 1
        assert "partial" in proc.stderr
        assert rows == []

    def test_no_json_aborts(self, sandbox):
        proc, rows = run_sweep(sandbox, env={"STUB_MODE": "silent"})
        assert proc.returncode == 1
        assert "no JSON" in proc.stderr
        assert rows == []
