"""Telemetry subsystem tests: span tracer, health/watchdog, anomaly
detector, the health/trace CLI surfaces, and a CPU smoke run proving a
tiny training session emits a loadable trace.json + advancing
health.json heartbeat (docs/OBSERVABILITY.md acceptance bar)."""

import json
import threading
import time

import numpy as np
import pytest

from alphatriangle_tpu import cli
from alphatriangle_tpu.config import PersistenceConfig, TelemetryConfig
from alphatriangle_tpu.stats.collector import StatsCollector
from alphatriangle_tpu.telemetry import (
    AnomalyDetector,
    HealthMonitor,
    RunTelemetry,
    SpanTracer,
    Watchdog,
    health_verdict,
    read_health,
    summarize_trace_file,
)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


class TestSpanTracer:
    def test_spans_export_chrome_trace(self, tmp_path):
        tr = SpanTracer()
        with tr.span("rollout", chunk=3):
            time.sleep(0.002)
        with tr.span("train"):
            pass
        tr.instant("stall_marker")
        n = tr.export(tmp_path / "trace.json")
        assert n == 3
        data = json.loads((tmp_path / "trace.json").read_text())
        events = data["traceEvents"]
        spans = [e for e in events if e.get("ph") == "X"]
        assert len(spans) == 2
        for ev in spans:
            assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(ev)
        # Real duration in microseconds.
        rollout = next(e for e in spans if e["name"] == "rollout")
        assert rollout["dur"] >= 2000
        assert rollout["args"] == {"chunk": 3}
        assert any(e["ph"] == "i" for e in events)
        # Thread metadata names the recording thread.
        meta = [e for e in events if e["ph"] == "M"]
        assert meta and meta[0]["args"]["name"]

    def test_ring_bounds_memory(self, tmp_path):
        tr = SpanTracer(capacity=8)
        for i in range(20):
            with tr.span(f"s{i}"):
                pass
        assert tr.recorded == 20
        assert tr.export(tmp_path / "t.json") == 8
        names = [
            e["name"]
            for e in json.loads((tmp_path / "t.json").read_text())[
                "traceEvents"
            ]
            if e["ph"] == "X"
        ]
        assert names == [f"s{i}" for i in range(12, 20)]

    def test_threads_recorded_separately(self, tmp_path):
        tr = SpanTracer()

        def work():
            with tr.span("worker_phase"):
                pass

        t = threading.Thread(target=work, name="producer-0")
        t.start()
        t.join()
        with tr.span("main_phase"):
            pass
        tr.export(tmp_path / "t.json")
        events = json.loads((tmp_path / "t.json").read_text())["traceEvents"]
        tids = {e["tid"] for e in events if e["ph"] == "X"}
        assert len(tids) == 2
        meta_names = {
            e["args"]["name"] for e in events if e["ph"] == "M"
        }
        assert "producer-0" in meta_names

    def test_disabled_records_nothing(self, tmp_path):
        tr = SpanTracer(enabled=False)
        with tr.span("x"):
            pass
        tr.instant("y")
        assert tr.recorded == 0
        assert tr.export(tmp_path / "t.json") == 0

    def test_summary_and_file_summary_agree(self, tmp_path):
        tr = SpanTracer()
        for _ in range(3):
            with tr.span("rollout"):
                pass
        s = tr.summary()
        assert s["rollout"]["count"] == 3
        tr.export(tmp_path / "t.json")
        rows = summarize_trace_file(tmp_path / "t.json")
        assert rows[0]["name"] == "rollout" and rows[0]["count"] == 3


class TestHealthMonitor:
    def test_heartbeat_roundtrip(self, tmp_path):
        clock = FakeClock(100.0)
        h = HealthMonitor(
            tmp_path / "health.json", deadline_s=60, run_name="r",
            clock=clock,
        )
        h.note_rollout(experiences=32, episodes=2)
        clock.t = 105.0
        h.note_learner_step(7)
        h.note_buffer(500)
        clock.t = 110.0
        h.write()
        payload = read_health(tmp_path / "health.json")
        assert payload["run"] == "r"
        assert payload["learner_step"] == 7
        assert payload["learner_age_s"] == pytest.approx(5.0)
        assert payload["rollout_age_s"] == pytest.approx(10.0)
        assert payload["buffer_size"] == 500
        assert payload["episodes_played"] == 2
        assert payload["experiences_added"] == 32
        assert not payload["stalled"]
        assert payload["watchdog_deadline_s"] == 60

    def test_last_progress_tracks_newest_beat(self):
        clock = FakeClock(10.0)
        h = HealthMonitor("unused", clock=clock)
        assert h.last_progress() == 10.0  # start counts as progress
        clock.t = 20.0
        h.note_rollout()
        clock.t = 30.0
        h.note_learner_step(1)
        assert h.last_progress() == 30.0

    def test_verdict(self):
        base = {"time": 1000.0, "watchdog_deadline_s": 100.0}
        ok, age, _ = health_verdict(base, now=1050.0)
        assert ok and age == pytest.approx(50.0)
        ok, age, reason = health_verdict(base, now=1200.0)
        assert not ok and "no heartbeat" in reason
        ok, _, reason = health_verdict(
            {**base, "stalled": True}, now=1050.0
        )
        assert not ok and "stall" in reason
        # Explicit deadline override wins.
        ok, _, _ = health_verdict(base, now=1050.0, deadline_s=10.0)
        assert not ok

    def test_read_health_missing_or_torn(self, tmp_path):
        assert read_health(tmp_path / "nope.json") is None
        (tmp_path / "torn.json").write_text('{"time": 1')
        assert read_health(tmp_path / "torn.json") is None


class TestWatchdog:
    def test_stall_fires_once_then_recovers_and_rearms(self, tmp_path):
        clock = FakeClock(0.0)
        h = HealthMonitor(
            tmp_path / "health.json", deadline_s=10.0, clock=clock
        )
        calls: list[float] = []
        wd = Watchdog(
            h, deadline_s=10.0, on_stall=calls.append, clock=clock
        )
        assert not wd.check()
        # Frozen progress past the deadline: fires exactly once.
        clock.t = 11.0
        assert wd.check()
        assert wd.check()  # still stalled, no second fire
        assert len(calls) == 1 and calls[0] == pytest.approx(11.0)
        h.write()
        assert read_health(h.path)["stalled"] is True
        # Progress resumes: recovers cleanly...
        h.note_learner_step(1)
        assert not wd.check()
        h.write()
        payload = read_health(h.path)
        assert payload["stalled"] is False
        assert payload["stall_count"] == 1
        # ...and a second stall re-arms the dump.
        clock.t = 30.0
        assert wd.check()
        assert len(calls) == 2 and wd.stall_count == 2

    def test_on_stall_failure_does_not_kill_watchdog(self, tmp_path):
        clock = FakeClock(0.0)
        h = HealthMonitor(
            tmp_path / "health.json", deadline_s=5.0, clock=clock
        )

        def boom(age):
            raise RuntimeError("hook failed")

        wd = Watchdog(h, deadline_s=5.0, on_stall=boom, clock=clock)
        clock.t = 6.0
        assert wd.check()  # must not raise
        assert wd.stall_count == 1

    def test_thread_start_stop(self, tmp_path):
        h = HealthMonitor(tmp_path / "health.json", deadline_s=1000.0)
        wd = Watchdog(h, deadline_s=1000.0, poll_s=0.01)
        wd.start()
        assert any(
            t.name == "telemetry-watchdog" for t in threading.enumerate()
        )
        wd.stop()
        assert not any(
            t.name == "telemetry-watchdog" and t.is_alive()
            for t in threading.enumerate()
        )


class TestRunTelemetryStall:
    def test_stall_dumps_stacks_metric_and_trace(self, tmp_path):
        clock = FakeClock(0.0)
        pc = PersistenceConfig(ROOT_DATA_DIR=str(tmp_path), RUN_NAME="s")
        stats = StatsCollector(pc, use_tensorboard=False)
        cfg = TelemetryConfig(WATCHDOG_DEADLINE_S=10.0)
        t = RunTelemetry(
            cfg, run_dir=tmp_path, stats=stats, run_name="s", clock=clock
        )
        with t.tracer.span("rollout"):
            pass
        t.on_learner_step(3, {"Loss/total_loss": 1.0})
        clock.t = 20.0
        assert t.watchdog.check()
        # Exactly one stack dump, containing this (main) thread.
        stacks = (tmp_path / "stall_stacks.txt").read_text()
        assert stacks.count("=== stall at") == 1
        assert "MainThread" in stacks or "Current thread" in stacks
        # Health/stall metric (value = stall age) queued for the tick.
        means = stats.process_and_log(3)
        assert means["Health/stall"] == pytest.approx(20.0)
        # Span buffer flushed (stall marker included).
        data = json.loads((tmp_path / "trace.json").read_text())
        names = [e["name"] for e in data["traceEvents"]]
        assert "rollout" in names and "watchdog_stall" in names
        assert read_health(tmp_path / "health.json")["stalled"] is True
        # Recovery clears the flag; a second frozen window fires again.
        t.on_learner_step(4, {})
        assert not t.watchdog.check()
        clock.t = 40.0
        assert t.watchdog.check()
        assert (tmp_path / "stall_stacks.txt").read_text().count(
            "=== stall at"
        ) == 2
        t.close(4)
        stats.close()

    def test_disabled_is_inert(self, tmp_path):
        t = RunTelemetry(
            TelemetryConfig(ENABLED=False), run_dir=tmp_path
        )
        assert t.watchdog is None
        with t.tracer.span("x"):
            pass
        t.on_rollout(1, 1)
        assert t.on_learner_step(1, {"Loss/total_loss": float("nan")}) == []
        t.on_tick(1, 0)
        t.start()
        t.close(1)
        assert not (tmp_path / "health.json").exists()
        assert not (tmp_path / "trace.json").exists()


class TestAnomalyDetector:
    def test_quiet_on_noisy_stationary_series(self):
        det = AnomalyDetector(z_threshold=6.0, warmup=20)
        rng = np.random.default_rng(0)
        fired = []
        for step, v in enumerate(2.0 + 0.1 * rng.standard_normal(500)):
            fired += det.observe("Loss/total_loss", float(v), step)
        assert fired == []

    def test_spike_fires_at_the_right_step(self):
        det = AnomalyDetector(z_threshold=6.0, warmup=20)
        rng = np.random.default_rng(1)
        series = 2.0 + 0.1 * rng.standard_normal(300)
        series[150] = 10.0  # injected loss spike
        fired = []
        for step, v in enumerate(series):
            fired += det.observe("Loss/total_loss", float(v), step)
        assert [a.step for a in fired] == [150]
        a = fired[0]
        assert a.kind == "spike" and a.zscore > 6.0
        assert a.window  # recent context travels with the anomaly
        assert "sigma" in a.describe()

    def test_grad_norm_explosion(self):
        det = AnomalyDetector(z_threshold=6.0, warmup=20)
        fired = []
        for step in range(100):
            v = 0.5 if step != 80 else 500.0
            fired += det.observe("Loss/Grad_Norm", v, step)
        assert [a.step for a in fired] == [80]

    def test_nonfinite_fires_and_does_not_poison_baseline(self):
        det = AnomalyDetector(z_threshold=6.0, warmup=10)
        fired = []
        for step in range(30):
            v = float("nan") if step == 20 else 1.0
            fired += det.observe("Loss/total_loss", v, step)
        kinds = [(a.kind, a.step) for a in fired]
        assert kinds == [("nonfinite", 20)]  # no trailing spike

    def test_entropy_collapse_latches_and_rearms(self):
        det = AnomalyDetector(warmup=5, entropy_floor=0.01)
        fired = []
        series = [1.0] * 10 + [0.001] * 5 + [1.0] * 5 + [0.0] * 3
        for step, v in enumerate(series):
            fired += det.observe("Loss/Entropy", v, step)
        collapses = [a for a in fired if a.kind == "collapse"]
        # Once per excursion: steps 10 and 20.
        assert [a.step for a in collapses] == [10, 20]

    def test_constant_series_never_spikes(self):
        det = AnomalyDetector(z_threshold=6.0, warmup=5)
        fired = []
        for step in range(200):
            fired += det.observe("LearningRate", 3e-4, step)
        assert fired == []


def _build_components(tmp_path, cfgs, run_name, telemetry_config=None, **kw):
    from alphatriangle_tpu.training import setup_training_components
    from tests.test_training_loop import make_train_cfg

    env_cfg, model_cfg, mcts_cfg = cfgs
    tc = make_train_cfg(run_name, str(tmp_path), **kw)
    pc = PersistenceConfig(ROOT_DATA_DIR=str(tmp_path), RUN_NAME=run_name)
    return setup_training_components(
        train_config=tc,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        persistence_config=pc,
        telemetry_config=telemetry_config,
        use_tensorboard=False,
    )


class TestTrainingSmoke:
    """Acceptance bar: a tiny CPU training session with telemetry on
    emits a Chrome-loadable trace.json and an advancing heartbeat, and
    the health CLI gates on it."""

    def test_cpu_run_emits_trace_and_heartbeat(
        self,
        tmp_path,
        tiny_env_config,
        tiny_model_config,
        tiny_mcts_config,
    ):
        from alphatriangle_tpu.training import LoopStatus, TrainingLoop

        c = _build_components(
            tmp_path,
            (tiny_env_config, tiny_model_config, tiny_mcts_config),
            run_name="telemetry_smoke",
            MAX_TRAINING_STEPS=4,
        )
        assert c.telemetry is not None and c.telemetry.enabled
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        run_dir = c.persistence_config.get_run_base_dir()

        # Heartbeat: learner step advanced from 0 to the horizon.
        payload = read_health(run_dir / "health.json")
        assert payload is not None
        assert payload["learner_step"] == 4
        assert payload["buffer_size"] > 0
        assert payload["episodes_played"] >= 0
        assert payload["stalled"] is False

        # Chrome trace: the loop phases appear as complete events with
        # ph/ts/tid/dur fields.
        data = json.loads((run_dir / "trace.json").read_text())
        events = data["traceEvents"]
        spans = [e for e in events if e.get("ph") == "X"]
        assert spans
        for ev in spans:
            assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(ev)
        names = {e["name"] for e in spans}
        assert {"rollout", "sample", "train", "checkpoint"} <= names
        assert "weight_sync" in names

        # The watchdog thread shut down with the loop.
        assert not any(
            t.name == "telemetry-watchdog" and t.is_alive()
            for t in threading.enumerate()
        )

        # CLI verdicts: live now, stale once the heartbeat ages out.
        rc = cli.main(
            ["health", "telemetry_smoke", "--root-dir", str(tmp_path)]
        )
        assert rc == 0
        stale = dict(payload, time=payload["time"] - 10_000)
        (run_dir / "health.json").write_text(json.dumps(stale))
        rc = cli.main(
            ["health", "telemetry_smoke", "--root-dir", str(tmp_path)]
        )
        assert rc == 1
        # Span-trace summary renders from the same run.
        rc = cli.main(
            ["trace", "telemetry_smoke", "--root-dir", str(tmp_path)]
        )
        assert rc == 0

        c.stats.close()
        c.checkpoints.close()

    def test_telemetry_opt_out(
        self,
        tmp_path,
        tiny_env_config,
        tiny_model_config,
        tiny_mcts_config,
    ):
        from alphatriangle_tpu.training import LoopStatus, TrainingLoop

        c = _build_components(
            tmp_path,
            (tiny_env_config, tiny_model_config, tiny_mcts_config),
            run_name="no_telemetry",
            MAX_TRAINING_STEPS=2,
            telemetry_config=TelemetryConfig(ENABLED=False),
        )
        loop = TrainingLoop(c)
        assert loop.run() == LoopStatus.COMPLETED
        run_dir = c.persistence_config.get_run_base_dir()
        assert not (run_dir / "health.json").exists()
        assert not (run_dir / "trace.json").exists()
        c.stats.close()
        c.checkpoints.close()
