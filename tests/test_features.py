"""Feature-extraction contract tests.

Pins the 30-dim layout of the reference extractor
(`alphatriangle/features/extractor.py:33-147`) against the jnp pipeline:
grid encoding, shape-feature table semantics, scalar grid features, and
host/device agreement.
"""

import jax
import numpy as np
import pytest

from alphatriangle_tpu.config import (
    EnvConfig,
    ModelConfig,
    expected_other_features_dim,
)
from alphatriangle_tpu.env import GameState, TriangleEnv
from alphatriangle_tpu.features import (
    build_shape_feature_table,
    extract_state_features,
    get_feature_extractor,
)
from alphatriangle_tpu.features.grid_features import (
    bumpiness_np,
    column_heights_np,
    count_holes_np,
)


@pytest.fixture(scope="module")
def env(tiny_env_config) -> TriangleEnv:
    return TriangleEnv(tiny_env_config)


@pytest.fixture(scope="module")
def extractor(env, tiny_model_config):
    return get_feature_extractor(env, tiny_model_config)


def test_other_features_dim_matches_formula(extractor, tiny_env_config):
    assert extractor.other_dim == expected_other_features_dim(tiny_env_config)


def test_extract_shapes_and_dtypes(env, extractor, tiny_model_config, tiny_env_config):
    state = env.reset(jax.random.PRNGKey(0))
    grid, other = extractor.extract(state)
    assert grid.shape == (
        tiny_model_config.GRID_INPUT_CHANNELS,
        tiny_env_config.ROWS,
        tiny_env_config.COLS,
    )
    assert other.shape == (extractor.other_dim,)
    assert grid.dtype == np.float32
    assert other.dtype == np.float32


def test_grid_encoding_values(tiny_model_config):
    # Board with a death column: row windows exclude the last column.
    cfg = EnvConfig(
        ROWS=3,
        COLS=4,
        PLAYABLE_RANGE_PER_ROW=[(0, 3), (0, 3), (0, 3)],
        NUM_SHAPE_SLOTS=1,
        MAX_SHAPE_TRIANGLES=3,
    )
    model = ModelConfig(
        **{
            **tiny_model_config.model_dump(),
            "OTHER_NN_INPUT_FEATURES_DIM": expected_other_features_dim(cfg),
        }
    )
    gs = GameState(cfg, initial_seed=3)
    # Play one valid action so something is occupied.
    action = gs.valid_actions()[0]
    gs.step(action)
    feats = extract_state_features(gs, model)
    grid = feats["grid"][0]
    death = gs.get_grid_data_np()["death"]
    occupied = gs.get_grid_data_np()["occupied"]
    assert np.all(grid[death] == -1.0)
    assert np.all(grid[occupied & ~death] == 1.0)
    assert np.all(grid[~occupied & ~death] == 0.0)


def test_shape_feature_table_semantics(env, tiny_env_config):
    table = build_shape_feature_table(env.bank, tiny_env_config)
    assert table.shape == (env.bank.n_shapes + 1, 7)
    # Zero row for empty slots.
    assert np.all(table[-1] == 0.0)
    for s, cells in enumerate(env.bank.shapes):
        n = len(cells)
        ups = sum(1 for r, c in cells if (r + c) % 2 == 0)
        assert table[s, 0] == pytest.approx(min(n / 5.0, 1.0))
        assert table[s, 1] == pytest.approx(ups / n)
        assert table[s, 2] == pytest.approx((n - ups) / n)
        # Fractions sum to 1.
        assert table[s, 1] + table[s, 2] == pytest.approx(1.0)
    # All features normalized into [0, 1].
    assert table.min() >= 0.0 and table.max() <= 1.0


def test_grid_scalar_features_numpy_twins():
    rng = np.random.default_rng(7)
    occupied = rng.random((6, 5)) < 0.4
    death = np.zeros((6, 5), dtype=bool)
    death[:, 4] = True
    heights = column_heights_np(occupied, death)
    # Manual check, reference semantics: height = last occupied row + 1.
    for c in range(5):
        occ_rows = [r for r in range(6) if occupied[r, c] and not death[r, c]]
        assert heights[c] == (max(occ_rows) + 1 if occ_rows else 0)
    holes = count_holes_np(occupied, death, heights)
    expected_holes = sum(
        1
        for c in range(5)
        for r in range(heights[c])
        if not occupied[r, c] and not death[r, c]
    )
    assert holes == expected_holes
    assert bumpiness_np(heights) == sum(
        abs(int(heights[i]) - int(heights[i + 1])) for i in range(4)
    )


def test_jnp_matches_numpy_grid_features(env, extractor):
    from alphatriangle_tpu.features.grid_features import (
        bumpiness,
        column_heights,
        count_holes,
    )

    rng = np.random.default_rng(11)
    occupied = rng.random((env.rows, env.cols)) < 0.5
    death = env.geometry.death
    h_np = column_heights_np(occupied, death)
    h_j = np.asarray(column_heights(occupied, death))
    assert np.array_equal(h_np, h_j)
    assert count_holes_np(occupied, death, h_np) == int(
        count_holes(occupied, death, h_j)
    )
    assert bumpiness_np(h_np) == float(bumpiness(h_j))


def test_batched_extraction_matches_single(env, extractor):
    keys = jax.random.split(jax.random.PRNGKey(5), 8)
    states = env.reset_batch(keys)
    grids, others = extractor.extract_batch(states)
    assert grids.shape[0] == 8 and others.shape[0] == 8
    for i in range(8):
        single = jax.tree_util.tree_map(lambda a, i=i: a[i], states)
        g, o = extractor.extract(single)
        np.testing.assert_allclose(np.asarray(g), np.asarray(grids[i]), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(o), np.asarray(others[i]), rtol=1e-6)


def test_host_wrapper_matches_device_path(env, tiny_model_config):
    gs = GameState(env.cfg, initial_seed=9)
    for _ in range(3):
        acts = gs.valid_actions()
        if not acts:
            break
        gs.step(acts[0])
    feats = extract_state_features(gs, tiny_model_config)
    fe = get_feature_extractor(env, tiny_model_config)
    g, o = fe.extract(gs._state)
    np.testing.assert_allclose(feats["grid"], np.asarray(g))
    np.testing.assert_allclose(feats["other_features"], np.asarray(o))
    assert np.all(np.isfinite(feats["other_features"]))


def test_explicit_features_after_play(env, tiny_model_config):
    gs = GameState(env.cfg, initial_seed=1)
    while not gs.is_over() and gs.current_step < 10:
        gs.step(gs.valid_actions()[0])
    feats = extract_state_features(gs, tiny_model_config)
    other = feats["other_features"]
    slots = env.num_slots
    explicit = other[slots * 7 + slots :]
    grid_data = gs.get_grid_data_np()
    h = column_heights_np(grid_data["occupied"], grid_data["death"])
    assert explicit[0] == pytest.approx(np.clip(gs.game_score() / 100.0, -5, 5))
    assert explicit[1] == pytest.approx(h.mean() / env.rows)
    assert explicit[2] == pytest.approx(h.max() / env.rows)
    assert explicit[5] == pytest.approx(min(gs.current_step / 1000.0, 1.0))
