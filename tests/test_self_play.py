"""Self-play engine tests (reference `worker.py:166-513` semantics).

The n-step window math is validated against an independent per-game
deque implementation fed the engine's own recorded rewards / root
values / done flags — the vectorized (B, n) window must emit exactly
the same multiset of value targets."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphatriangle_tpu.config import TrainConfig
from alphatriangle_tpu.env.engine import TriangleEnv
from alphatriangle_tpu.features.core import get_feature_extractor
from alphatriangle_tpu.nn.network import NeuralNetwork
from alphatriangle_tpu.rl import ExperienceBuffer, SelfPlayEngine


@pytest.fixture(scope="module")
def world(tiny_env_config, tiny_model_config, tiny_mcts_config):
    env = TriangleEnv(tiny_env_config)
    fe = get_feature_extractor(env, tiny_model_config)
    net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
    return env, fe, net, tiny_mcts_config


def make_engine(world, **cfg_kw):
    env, fe, net, mcts_cfg = world
    base = dict(
        BATCH_SIZE=4,
        BUFFER_CAPACITY=5000,
        MIN_BUFFER_SIZE_TO_TRAIN=8,
        USE_PER=False,
        N_STEP_RETURNS=3,
        GAMMA=0.9,
        MAX_EPISODE_MOVES=50,
        SELF_PLAY_BATCH_SIZE=4,
        MAX_TRAINING_STEPS=100,
        RUN_NAME="sp_test",
    )
    base.update(cfg_kw)
    tc = TrainConfig(**base)
    return SelfPlayEngine(env, fe, net, mcts_cfg, tc, seed=7), tc


class TestBasics:
    def test_produces_valid_experiences(self, world):
        engine, tc = make_engine(world)
        result = engine.play_moves(10)
        assert result.num_experiences > 0
        np.testing.assert_allclose(
            result.policy_target.sum(axis=1), 1.0, atol=1e-4
        )
        assert np.all(np.isfinite(result.value_target))
        assert result.grid.shape[1:] == (1, 3, 4)

    def test_buffer_accepts_harvest(self, world):
        engine, tc = make_engine(world)
        buf = ExperienceBuffer(tc)
        result = engine.play_moves(8)
        buf.add_dense(
            result.grid,
            result.other_features,
            result.policy_target,
            result.value_target,
        )
        assert len(buf) == result.num_experiences
        assert buf.sample(4) is not None or len(buf) < 8

    def test_episode_stats_consistent(self, world):
        engine, _ = make_engine(world)
        result = engine.play_moves(25)
        assert result.num_episodes == len(result.episode_scores)
        assert result.num_episodes == len(result.episode_lengths)
        assert result.num_episodes > 0  # tiny board games end fast
        assert all(s >= 0 for s in result.episode_lengths)
        assert result.total_simulations > 0

    @pytest.mark.slow
    def test_truncation_counted(self, world):
        """Games hitting MAX_EPISODE_MOVES are counted as truncated;
        natural game-overs are not."""
        engine, _ = make_engine(world, MAX_EPISODE_MOVES=3)
        result = engine.play_moves(9)
        assert result.num_episodes > 0
        # A 3-move cap on the tiny board truncates most episodes.
        assert 0 < result.num_truncated <= result.num_episodes

        natural, _ = make_engine(world, MAX_EPISODE_MOVES=500)
        r2 = natural.play_moves(25)
        assert r2.num_episodes > 0 and r2.num_truncated == 0

    def test_harvest_clears(self, world):
        engine, _ = make_engine(world)
        engine.play_moves(6)
        r2 = engine.harvest()
        assert r2.num_experiences == 0
        assert r2.num_episodes == 0

    @pytest.mark.slow
    def test_staleness_tag_tracks_weights_version(self, world):
        env, fe, net, mcts_cfg = world
        engine, _ = make_engine(world)
        v0 = net.weights_version
        result = engine.play_moves(2)
        assert result.trainer_step_at_episode_start == v0
        net.weights_version += 1
        result = engine.play_moves(2)
        assert result.trainer_step_at_episode_start == v0 + 1
        # A mid-window sync must NOT relabel earlier experiences fresh:
        # the tag is the oldest version seen during the window.
        engine.play_move()
        net.weights_version += 5
        engine.play_move()
        assert engine.harvest().trainer_step_at_episode_start == v0 + 1


def make_pcr_engine(world, prob=0.5, record_fast=False):
    """Engine with playout cap randomization on (shared by the PCR
    behavior tests and the n-step deque cross-check)."""
    env, fe, net, mcts_cfg = world
    pcr_cfg = type(mcts_cfg)(
        **{
            **mcts_cfg.model_dump(),
            "fast_simulations": max(2, mcts_cfg.max_simulations // 4),
            "full_search_prob": prob,
            "pcr_record_fast_rows": record_fast,
        }
    )
    return make_engine((env, fe, net, pcr_cfg))


class TestSharedCompile:
    def test_streams_share_chunk_programs(self, world):
        e1, tc = make_engine(world)
        env, fe, net, mcts_cfg = world
        e2 = type(e1)(
            env, fe, net, mcts_cfg, tc, seed=99, share_compiled=e1
        )
        assert e2._chunk_fn is e1._chunk_fn
        # Both streams advance independently through the shared program
        # and BOTH produce experiences (6 moves > n_step=3 guarantees
        # matured emissions per stream).
        e1.play_chunk(6)
        e2.play_chunk(6)
        r1, r2 = e1.harvest(), e2.harvest()
        assert r1.num_experiences > 0 and r2.num_experiences > 0
        # Different seeds -> different games (not a shared-carry alias).
        assert not np.array_equal(r1.grid, r2.grid)

    def test_mismatched_configs_rejected(self, world):
        e1, tc = make_engine(world)
        env, fe, net, mcts_cfg = world
        other_tc = tc.model_copy(update={"SELF_PLAY_BATCH_SIZE": 8})
        with pytest.raises(ValueError, match="identically-configured"):
            type(e1)(
                env, fe, net, mcts_cfg, other_tc, seed=1, share_compiled=e1
            )


class TestPlayoutCapRandomization:
    """KataGo-style PCR (config/mcts_config.py): fast moves carry
    policy weight 0; accounting reflects the sims actually run."""


    def test_default_drops_fast_rows(self, world):
        """KataGo-faithful default: cheap-search positions advance the
        game but never become training rows."""
        engine, _ = make_pcr_engine(world, prob=0.5)
        engine.play_chunk(24)
        trace = engine.last_trace
        fulls = np.asarray(trace["is_full"])
        assert 0 < fulls.sum() < fulls.size  # both kinds of move ran
        result = engine.harvest()
        assert result.num_experiences > 0
        # Everything that reached replay came from a full search.
        assert np.all(result.policy_weight == 1.0)

    def test_policy_weights_mark_fast_moves(self, world):
        engine, _ = make_pcr_engine(
            world, prob=0.5, record_fast=True
        )
        engine.play_chunk(24)
        trace = engine.last_trace
        assert trace is not None and "is_full" in trace
        fulls = np.asarray(trace["is_full"])
        # 24 Bernoulli(0.5) draws: both kinds appear with prob ~1-6e-8.
        assert 0 < fulls.sum() < fulls.size
        result = engine.harvest()
        pw = result.policy_weight
        assert pw is not None and set(np.unique(pw)) <= {0.0, 1.0}
        assert 0 < pw.sum() < pw.size  # both kinds reached the replay

    def test_sims_accounting_matches_trace(self, world):
        engine, _ = make_pcr_engine(world, prob=0.5)
        engine.play_chunk(10)
        trace = engine.last_trace
        expected = int(np.asarray(trace["sims"]).sum()) * engine.batch_size
        assert engine.harvest().total_simulations == expected
        full = engine.mcts_config.max_simulations
        fast = engine.mcts_config.fast_simulations
        assert set(np.unique(np.asarray(trace["sims"]))) <= {full, fast}

    def test_disabled_by_default(self, world):
        engine, _ = make_engine(world)
        assert engine.mcts_fast is None
        result = engine.play_moves(6)
        assert np.all(result.policy_weight == 1.0)

    def test_buffer_roundtrip_preserves_weights(self, world):
        engine, tc = make_pcr_engine(world, prob=0.5)
        result = engine.play_moves(24)
        buf = ExperienceBuffer(tc, action_dim=result.policy_target.shape[1])
        buf.add_dense(
            result.grid,
            result.other_features,
            result.policy_target,
            result.value_target,
            policy_weight=result.policy_weight,
        )
        sample = buf.sample(8)
        assert sample is not None
        pw = sample["batch"]["policy_weight"]
        assert pw.shape == (8,) and set(np.unique(pw)) <= {0.0, 1.0}


class TestNStepMath:
    def test_window_matches_reference_deque(self, world):
        """Replay the engine's own per-move (reward, root_value, ending)
        trace through a straightforward per-game deque; emitted
        value-target multisets must match exactly."""
        engine, tc = make_engine(world)
        n, gamma = tc.N_STEP_RETURNS, tc.GAMMA
        B = engine.batch_size

        M = 14
        result = engine.play_moves(M)
        tr = engine.last_trace
        assert tr is not None and tr["reward"].shape == (M, B)
        trace = [
            {
                "root_value": tr["root_value"][t],
                "reward": tr["reward"][t],
                "ending": tr["ending"][t],
            }
            for t in range(M)
        ]

        # Reference implementation: per-game deque of pending items.
        expected: list[float] = []
        pending: list[list[list[float]]] = [[] for _ in range(B)]
        for t, mv in enumerate(trace):
            for b in range(B):
                # Mature items added n moves ago (bootstrapped).
                for item in pending[b]:
                    if t - item[2] == n:
                        expected.append(item[0] + item[1] * mv["root_value"][b])
                pending[b] = [i for i in pending[b] if t - i[2] < n]
                # Add this move's item, then fold the reward into all.
                pending[b].append([0.0, 1.0, t])
                for item in pending[b]:
                    item[0] += item[1] * mv["reward"][b]
                    item[1] *= gamma
                if mv["ending"][b]:
                    expected.extend(i[0] for i in pending[b])
                    pending[b] = []

        got = np.sort(result.value_target)
        want = np.sort(np.asarray(expected, np.float32))
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_window_matches_reference_deque_under_pcr(self, world):
        """Same deque cross-check with playout cap randomization: items
        added on fast-search moves are never emitted (dropped at
        maturation AND at episode flush), but their rewards still fold
        into neighbours' returns and maturation bootstraps use whatever
        search ran n moves later."""
        env, fe, net, mcts_cfg = world
        pcr_cfg = type(mcts_cfg)(
            **{
                **mcts_cfg.model_dump(),
                "fast_simulations": max(2, mcts_cfg.max_simulations // 4),
                "full_search_prob": 0.5,
            }
        )
        engine, tc = make_engine((env, fe, net, pcr_cfg))
        n, gamma = tc.N_STEP_RETURNS, tc.GAMMA
        B = engine.batch_size

        M = 20
        result = engine.play_moves(M)
        tr = engine.last_trace
        fulls = np.asarray(tr["is_full"])  # (M,) per lockstep move
        assert 0 < fulls.sum() < M  # both kinds occurred

        expected: list[float] = []
        pending: list[list[list[float]]] = [[] for _ in range(B)]
        for t in range(M):
            rv = tr["root_value"][t]
            rew = tr["reward"][t]
            end = tr["ending"][t]
            for b in range(B):
                for item in pending[b]:
                    if t - item[2] == n and item[3]:  # full-move rows only
                        expected.append(item[0] + item[1] * rv[b])
                pending[b] = [i for i in pending[b] if t - i[2] < n]
                pending[b].append([0.0, 1.0, t, bool(fulls[t])])
                for item in pending[b]:
                    item[0] += item[1] * rew[b]
                    item[1] *= gamma
                if end[b]:
                    expected.extend(
                        i[0] for i in pending[b] if i[3]
                    )
                    pending[b] = []

        got = np.sort(result.value_target)
        want = np.sort(np.asarray(expected, np.float32))
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_one_step_returns(self, world):
        engine, tc = make_engine(world, N_STEP_RETURNS=1, GAMMA=0.5)
        result = engine.play_moves(6)
        assert result.num_experiences > 0
        assert np.all(np.isfinite(result.value_target))
