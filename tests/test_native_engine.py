"""Golden parity tests: native C++ engine vs the JAX engine.

Both engines consume the same precomputed bitboard tables, so every
refill-free transition must agree bit-for-bit: valid masks, placement,
line clears, rewards, scores, termination, forfeit. Refill draws are
the one documented divergence (threefry vs xorshift), so parity runs on
a 2-slot config and compares single steps from states whose hand keeps
at least one shape (no refill fires).

Role of the native engine: host-side consumers (interactive play,
arena evaluation) per the reference's C++ `trianglengin` (its
README.md:14,42); the device path stays JAX.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphatriangle_tpu.config import EnvConfig
from alphatriangle_tpu.env.engine import TriangleEnv
from alphatriangle_tpu.env.native import (
    NativeTriangleEnv,
    native_available,
    native_build_error,
)

pytestmark = pytest.mark.skipif(
    not native_available(),
    reason=f"native engine unavailable: {native_build_error()}",
)


@pytest.fixture(scope="module")
def world():
    cfg = EnvConfig(
        ROWS=4,
        COLS=6,
        PLAYABLE_RANGE_PER_ROW=[(0, 6), (1, 5), (0, 6), (0, 6)],
        NUM_SHAPE_SLOTS=2,
    )
    env = TriangleEnv(cfg)
    native = NativeTriangleEnv(env)
    return env, native


def jax_states_to_native(env, native, states, n):
    """Copy a batched JAX EnvState into a fresh NativeBatch."""
    batch = native.new_batch(n)
    batch.occupied[:] = np.asarray(states.occupied)
    batch.color[:] = np.asarray(states.color).reshape(n, -1)
    batch.shape_idx[:] = np.asarray(states.shape_idx)
    batch.shape_color[:] = np.asarray(states.shape_color)
    batch.score[:] = np.asarray(states.score)
    batch.step_count[:] = np.asarray(states.step_count)
    batch.done[:] = np.asarray(states.done).astype(np.uint8)
    batch.last_cleared[:] = np.asarray(states.last_cleared)
    return batch


def random_playout_states(env, n, moves, seed):
    """Mid-game JAX states reached by uniform-random valid play."""
    states = env.reset_batch(jax.random.split(jax.random.PRNGKey(seed), n))
    rng = np.random.default_rng(seed)
    for _ in range(moves):
        masks = np.asarray(env.valid_mask_batch(states))
        logits = np.where(masks, rng.random(masks.shape), -np.inf)
        actions = np.where(masks.any(axis=1), logits.argmax(axis=1), 0)
        states, _, _ = env.step_batch(
            states, jnp.asarray(actions, dtype=jnp.int32)
        )
    return states


class TestParity:
    N = 32

    def test_valid_masks_match(self, world):
        env, native = world
        for seed in (0, 1):
            for moves in (0, 3, 7):
                states = random_playout_states(env, self.N, moves, seed)
                batch = jax_states_to_native(env, native, states, self.N)
                np.testing.assert_array_equal(
                    native.valid_mask(batch),
                    np.asarray(env.valid_mask_batch(states)),
                )

    def test_step_matches_on_valid_actions(self, world):
        env, native = world
        rng = np.random.default_rng(7)
        states = random_playout_states(env, self.N, 4, seed=2)
        for _ in range(6):
            masks = np.asarray(env.valid_mask_batch(states))
            # Keep the hand non-empty after the step so no refill fires:
            # prefer actions from a slot when the other slot still holds
            # a shape; games with one live slot left are stepped too —
            # there the JAX engine refills, so those games are compared
            # only up to the pre-refill fields (reward/score/board).
            logits = np.where(masks, rng.random(masks.shape), -np.inf)
            actions = np.where(
                masks.any(axis=1), logits.argmax(axis=1), 0
            ).astype(np.int32)
            held = np.asarray(states.shape_idx) >= 0
            will_refill = held.sum(axis=1) == 1

            batch = jax_states_to_native(env, native, states, self.N)
            pre_done = batch.done.copy()
            n_rewards, n_done = native.step(
                batch, actions, refill=False
            )
            states, j_rewards, j_done = env.step_batch(
                states, jnp.asarray(actions)
            )

            np.testing.assert_allclose(
                n_rewards, np.asarray(j_rewards), rtol=1e-6,
                err_msg="rewards diverge",
            )
            np.testing.assert_array_equal(
                batch.occupied, np.asarray(states.occupied)
            )
            np.testing.assert_array_equal(
                batch.color, np.asarray(states.color).reshape(self.N, -1)
            )
            np.testing.assert_allclose(
                batch.score, np.asarray(states.score), rtol=1e-6
            )
            np.testing.assert_array_equal(
                batch.last_cleared, np.asarray(states.last_cleared)
            )
            np.testing.assert_array_equal(
                batch.step_count, np.asarray(states.step_count)
            )
            # done: identical except games whose hand refilled (the JAX
            # draw can unstick what the empty native hand calls stuck).
            same = ~will_refill | (pre_done > 0)
            np.testing.assert_array_equal(
                n_done[same].astype(bool), np.asarray(j_done)[same]
            )

    def test_forfeit_on_invalid_action(self, world):
        env, native = world
        states = random_playout_states(env, self.N, 2, seed=3)
        masks = np.asarray(env.valid_mask_batch(states))
        # Pick an INVALID action for every live game.
        invalid = (~masks).astype(float)
        actions = invalid.argmax(axis=1).astype(np.int32)
        assert not masks[np.arange(self.N), actions].any()

        batch = jax_states_to_native(env, native, states, self.N)
        pre_occ = batch.occupied.copy()
        pre_score = batch.score.copy()
        n_rewards, n_done = native.step(batch, actions, refill=False)
        states2, j_rewards, j_done = env.step_batch(
            states, jnp.asarray(actions)
        )
        np.testing.assert_allclose(n_rewards, np.asarray(j_rewards), rtol=1e-6)
        assert n_done.all() and np.asarray(j_done).all()
        np.testing.assert_array_equal(batch.occupied, pre_occ)
        np.testing.assert_array_equal(batch.score, pre_score)

    def test_done_games_freeze(self, world):
        env, native = world
        batch = native.new_batch(4)
        batch.done[:] = 1
        pre = batch.occupied.copy()
        rewards, done = native.step(
            batch, np.zeros(4, np.int32), refill=False
        )
        assert (rewards == 0).all() and done.all()
        np.testing.assert_array_equal(batch.occupied, pre)
        assert not native.valid_mask(batch).any()


@pytest.mark.slow
def test_asan_ubsan_fuzz(world, tmp_path):
    """Random-playout fuzz of the C++ engine under ASAN+UBSan, plus
    occ/color-sync invariants — sanitizer coverage the reference's
    prebuilt C++ wheels never had (SURVEY.md §5)."""
    import struct
    import subprocess

    env, native = world
    src_dir = (
        __import__("pathlib").Path(
            __import__("alphatriangle_tpu.env.native", fromlist=["x"]).__file__
        ).parent
    )
    dump = tmp_path / "tables.bin"
    with dump.open("wb") as f:
        f.write(
            struct.pack(
                "<7i",
                native.rows,
                native.cols,
                native.num_slots,
                native.n_shapes,
                native.num_words,
                native._lines.shape[0],
                env.cfg.NUM_COLORS,
            )
        )
        f.write(np.ascontiguousarray(native._fp, np.uint32).tobytes())
        f.write(np.ascontiguousarray(native._lines, np.uint32).tobytes())

    binary = tmp_path / "fuzz"
    compile_proc = subprocess.run(
        [
            "g++", "-O1", "-g", "-std=c++17",
            "-fsanitize=address,undefined",
            "-fno-sanitize-recover=all",
            str(src_dir / "fuzz_main.cpp"),
            str(src_dir / "engine.cpp"),
            "-o", str(binary),
        ],
        capture_output=True,
        text=True,
        timeout=180,
    )
    if compile_proc.returncode != 0:
        pytest.skip(f"sanitizer build unavailable: {compile_proc.stderr[-300:]}")
    run = subprocess.run(
        [str(binary), str(dump)], capture_output=True, text=True, timeout=300
    )
    assert run.returncode == 0, f"fuzz failed:\n{run.stdout}\n{run.stderr}"
    assert "FUZZ_OK" in run.stdout


class TestNativeRollout:
    def test_full_games_terminate_with_refills(self, world):
        """Self-contained native rollout: uniform-random play with
        in-engine refills reaches termination with sane scores."""
        _, native = world
        batch = native.new_batch(16, seed=5)
        rng = np.random.default_rng(5)
        for _ in range(200):
            if batch.done.all():
                break
            masks = native.valid_mask(batch)
            logits = np.where(masks, rng.random(masks.shape), -np.inf)
            actions = np.where(
                masks.any(axis=1), logits.argmax(axis=1), 0
            ).astype(np.int32)
            native.step(batch, actions, refill=True)
        assert batch.done.all()
        assert (batch.score > 0).all()
        assert (batch.step_count > 0).all()
