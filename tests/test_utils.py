"""SumTree + helper tests (reference analog: buffer/sumtree unit tests)."""

import numpy as np
import pytest

from alphatriangle_tpu.utils import (
    SumTree,
    dense_policy_from_mapping,
    format_eta,
    is_point_in_polygon,
    mapping_from_dense_policy,
    set_random_seeds,
)


class TestSumTree:
    def test_add_and_total(self):
        t = SumTree(8)
        for i in range(5):
            t.add(float(i + 1), f"item{i}")
        assert t.total_priority == pytest.approx(15.0)
        assert len(t) == 5

    def test_ring_wraparound(self):
        t = SumTree(4)
        for i in range(6):
            t.add(1.0, i)
        assert len(t) == 4
        assert t.total_priority == pytest.approx(4.0)
        assert sorted(d for d in t.data) == [2, 3, 4, 5]

    def test_update_propagates(self):
        t = SumTree(4)
        idx = t.add(1.0, "a")
        t.add(2.0, "b")
        t.update(idx, 5.0)
        assert t.total_priority == pytest.approx(7.0)
        assert t.max_priority == pytest.approx(5.0)

    def test_get_leaf_selects_proportionally(self):
        t = SumTree(4)
        t.add(1.0, "low")
        t.add(99.0, "high")
        idx, prio, data = t.get_leaf(50.0)
        assert data == "high"
        assert prio == pytest.approx(99.0)
        idx, prio, data = t.get_leaf(0.5)
        assert data == "low"

    def test_batched_matches_scalar(self):
        rng = np.random.default_rng(1)
        t = SumTree(33)  # non-power-of-two capacity
        prios = rng.uniform(0.1, 5.0, size=33)
        for i, p in enumerate(prios):
            t.add(float(p), i)
        values = rng.uniform(0, t.total_priority, size=64)
        slots, got_prios = t.get_leaves(values)
        for v, s, p in zip(values, slots, got_prios):
            si, pi, _ = t.get_leaf(float(v))
            assert si == s
            assert pi == pytest.approx(p)

    def test_sample_batch_distribution(self):
        rng = np.random.default_rng(2)
        t = SumTree(16)
        t.add(90.0, "hot")
        for i in range(15):
            t.add(1.0, f"cold{i}")
        slots, _ = t.sample_batch(512, rng)
        hot_frac = float(np.mean(slots == 0))
        assert hot_frac > 0.7  # 90/105 ≈ 0.857 expected

    def test_update_batch_duplicate_indices_last_wins(self):
        t = SumTree(4)
        t.add(1.0, "a")
        t.update_batch(np.array([0, 0]), np.array([3.0, 7.0]))
        assert t.total_priority == pytest.approx(7.0)

    def test_rejects_bad_priorities(self):
        t = SumTree(4)
        with pytest.raises(ValueError):
            t.add(-1.0, "bad")
        with pytest.raises(ValueError):
            t.add(float("nan"), "bad")

    def test_empty_sample_raises(self):
        t = SumTree(4)
        with pytest.raises(ValueError):
            t.sample_batch(2, np.random.default_rng(0))


def test_format_eta():
    assert format_eta(None) == "N/A"
    assert format_eta(-5) == "N/A"
    assert format_eta(3661) == "01:01:01"
    assert format_eta(90061) == "1d 01:01:01"


def test_set_random_seeds_returns_key():
    key = set_random_seeds(7)
    assert key.shape == (2,) or key.dtype.name == "key<fry>" or key.size >= 1


def test_dense_policy_roundtrip():
    mapping = {0: 0.25, 3: 0.75}
    dense = dense_policy_from_mapping(mapping, 5)
    assert dense.sum() == pytest.approx(1.0)
    assert mapping_from_dense_policy(dense) == {0: 0.25, 3: 0.75}


def test_point_in_polygon():
    square = [(0, 0), (2, 0), (2, 2), (0, 2)]
    assert is_point_in_polygon((1, 1), square)
    assert not is_point_in_polygon((3, 3), square)
    assert is_point_in_polygon((0, 0), square)  # vertex counts as inside


class TestFlops:
    """Analytic FLOP accounting (utils/flops.py) used by bench MFU."""

    def test_forward_flops_positive_and_scales(self):
        from alphatriangle_tpu.config import (
            EnvConfig,
            ModelConfig,
            expected_other_features_dim,
        )
        from alphatriangle_tpu.utils.flops import (
            forward_flops,
            train_step_flops,
        )

        env = EnvConfig()
        feat = expected_other_features_dim(env)
        small = ModelConfig(
            OTHER_NN_INPUT_FEATURES_DIM=feat, TRANSFORMER_LAYERS=2
        )
        big = ModelConfig(
            OTHER_NN_INPUT_FEATURES_DIM=feat, TRANSFORMER_LAYERS=4
        )
        f_small = forward_flops(small, env, env.action_dim)
        f_big = forward_flops(big, env, env.action_dim)
        assert 0 < f_small < f_big
        # Two extra layers add exactly the per-layer cost.
        s = env.ROWS * env.COLS
        d, m = small.TRANSFORMER_DIM, small.TRANSFORMER_FC_DIM
        per_layer = 8 * s * d * d + 4 * s * s * d + 4 * s * d * m
        assert f_big - f_small == 2 * per_layer
        # Train step: 3x forward without remat, 4x with.
        assert train_step_flops(small, env, env.action_dim, 8) == (
            3 * 8 * f_small
        )
        remat = small.model_copy(update={"REMAT": True})
        assert train_step_flops(remat, env, env.action_dim, 8) == (
            4 * 8 * f_small
        )

    def test_peak_table_and_mfu(self):
        from alphatriangle_tpu.utils.flops import mfu, peak_bf16_tflops

        assert peak_bf16_tflops("TPU v5 lite") == 394.0
        assert peak_bf16_tflops("TPU v5litepod-8") == 394.0
        assert peak_bf16_tflops("cpu") is None
        assert mfu(394e12 / 2, "TPU v5 lite") == 0.5
        assert mfu(1.0, "unknown-chip") is None


class TestCompileCacheGate:
    """The persistent-cache gate must never enable for a CPU backend
    (XLA:CPU AOT reloads log SIGILL-risk feature mismatches) — including
    the auto-on-a-cpu-only-host path where no platform is pinned."""

    def _calls(self, monkeypatch, env_platforms=None):
        import types

        from alphatriangle_tpu.utils import helpers

        recorded = []
        # Stub the module's jax view: jax.config is read-only property
        # soup, and the conftest pins jax_platforms=cpu process-wide —
        # a stub lets each case control exactly what the gate sees.
        config = types.SimpleNamespace(
            jax_platforms="",
            update=lambda k, v: recorded.append((k, v)),
        )
        monkeypatch.setattr(
            helpers, "jax", types.SimpleNamespace(config=config)
        )
        if env_platforms is None:
            monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        else:
            monkeypatch.setenv("JAX_PLATFORMS", env_platforms)
        return helpers, recorded

    def test_resolved_cpu_backend_skips(self, monkeypatch):
        helpers, calls = self._calls(monkeypatch)
        helpers.enable_persistent_compilation_cache(backend="cpu")
        assert calls == []

    def test_resolved_tpu_backend_enables(self, monkeypatch):
        helpers, calls = self._calls(monkeypatch)
        helpers.enable_persistent_compilation_cache(backend="tpu")
        assert any(k == "jax_compilation_cache_dir" for k, _ in calls)

    def test_unpinned_auto_defers(self, monkeypatch):
        # No pinned platform and no resolved backend: must NOT enable —
        # the run may resolve to XLA:CPU (the SIGILL-risk path).
        helpers, calls = self._calls(monkeypatch)
        helpers.enable_persistent_compilation_cache()
        assert calls == []

    def test_pinned_cpu_skips(self, monkeypatch):
        helpers, calls = self._calls(monkeypatch, env_platforms="cpu")
        helpers.enable_persistent_compilation_cache()
        assert calls == []

    def test_pinned_tpu_enables(self, monkeypatch):
        helpers, calls = self._calls(monkeypatch, env_platforms="tpu")
        helpers.enable_persistent_compilation_cache()
        assert any(k == "jax_compilation_cache_dir" for k, _ in calls)

    def test_opt_out_env_wins(self, monkeypatch):
        helpers, calls = self._calls(monkeypatch)
        monkeypatch.setenv("ALPHATRIANGLE_NO_COMPILE_CACHE", "1")
        helpers.enable_persistent_compilation_cache(backend="tpu")
        assert calls == []
