"""Golden behavior contracts for the TPU-native triangle engine.

These pin the reconstructed rules (SURVEY.md §2b trianglengin row) so
every later layer builds against stable semantics: shape enumeration,
line geometry, placement legality, clearing, refill, termination, and
the GameState parity surface.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphatriangle_tpu.config import EnvConfig
from alphatriangle_tpu.env import (
    GameState,
    TriangleEnv,
    build_geometry,
    build_shape_bank,
    enumerate_shapes,
)
from alphatriangle_tpu.env.shapes import _is_up, _neighbors


# --- shape bank ------------------------------------------------------------


def test_enumerate_shape_counts():
    # Fixed polyiamonds (translation-only dedupe): 2, 3, 6, 14, 36.
    sizes = [len([s for s in enumerate_shapes(n, n)]) for n in range(1, 6)]
    assert sizes == [2, 3, 6, 14, 36]
    assert len(enumerate_shapes(1, 5)) == 61


def test_shapes_connected_and_canonical():
    for shape in enumerate_shapes(1, 4):
        cells = set(shape)
        # Connectivity via flood fill.
        seen = {shape[0]}
        frontier = [shape[0]]
        while frontier:
            cur = frontier.pop()
            for nb in _neighbors(*cur):
                if nb in cells and nb not in seen:
                    seen.add(nb)
                    frontier.append(nb)
        assert seen == cells
        # Canonical: min row 0, min col in {0, 1}.
        assert min(r for r, _ in shape) == 0
        assert min(c for _, c in shape) in (0, 1)


def test_bank_arrays_consistent(tiny_env_config):
    bank = build_shape_bank(tiny_env_config)
    assert bank.n_shapes == 2 + 3 + 6  # sizes 1..3
    assert bank.max_tris == tiny_env_config.MAX_SHAPE_TRIANGLES
    for i in range(bank.n_shapes):
        n = int(bank.n_tris[i])
        assert bank.tri_valid[i, :n].all() and not bank.tri_valid[i, n:].any()
        for j in range(n):
            r, c = int(bank.tri_r[i, j]), int(bank.tri_c[i, j])
            assert bool(bank.tri_up[i, j]) == _is_up(r, c)


# --- geometry --------------------------------------------------------------


def test_death_mask_from_playable_ranges():
    cfg = EnvConfig()
    geo = build_geometry(cfg)
    assert geo.death.shape == (cfg.ROWS, cfg.COLS)
    for r, (lo, hi) in enumerate(cfg.PLAYABLE_RANGE_PER_ROW):
        assert not geo.death[r, lo:hi].any()
        assert geo.death[r, :lo].all() and geo.death[r, hi:].all()


def test_line_masks_properties(tiny_env_config):
    geo = build_geometry(tiny_env_config)
    assert geo.n_lines > 0
    for mask in geo.line_masks:
        n = int(mask.sum())
        assert n >= tiny_env_config.LINE_MIN_LENGTH
        assert not (mask & geo.death).any()  # lines live on playable cells
    # On the 3x4 all-playable board the 3 horizontal lines cover all cells.
    horizontal = [m for m in geo.line_masks if len(set(np.nonzero(m)[0])) == 1]
    assert len(horizontal) == 3


def test_line_masks_default_board():
    geo = build_geometry(EnvConfig())
    # Default 8x15 board: 8 horizontal lines plus diagonals both ways.
    assert geo.n_lines >= 8


# --- engine: reset / placement / clearing ----------------------------------


@pytest.fixture(scope="module")
def tiny_env(tiny_env_config):
    return TriangleEnv(tiny_env_config)


def _hand(env, state, shape_ids):
    """Test helper: inject specific shapes into the hand."""
    return state.replace(
        shape_idx=jnp.asarray(shape_ids, dtype=jnp.int32),
        shape_color=jnp.zeros(env.num_slots, dtype=jnp.int8),
    )


def test_reset_deterministic_and_empty(tiny_env):
    s1 = tiny_env.reset(jax.random.PRNGKey(7))
    s2 = tiny_env.reset(jax.random.PRNGKey(7))
    assert not np.asarray(s1.occupied).any()
    assert float(s1.score) == 0.0 and int(s1.step_count) == 0
    assert not bool(s1.done)
    assert (np.asarray(s1.shape_idx) >= 0).all()
    np.testing.assert_array_equal(np.asarray(s1.shape_idx), np.asarray(s2.shape_idx))


def test_valid_mask_matches_manual_check(tiny_env):
    # Canonical anchors always have even parity: the up single occupies
    # its origin cell, the down single occupies (r, c+1).
    state = tiny_env.reset(jax.random.PRNGKey(0))
    for sid, dc in ((0, 0), (1, 1)):
        st = _hand(tiny_env, state, [sid])
        mask = np.asarray(tiny_env.valid_action_mask(st))
        assert mask.shape == (tiny_env.action_dim,)
        for a in range(tiny_env.action_dim):
            r = (a % 12) // 4
            c = a % 4
            expected = (r + c) % 2 == 0 and c + dc < 4
            assert mask[a] == expected, (sid, r, c)


def test_place_and_score(tiny_env):
    state = tiny_env.reset(jax.random.PRNGKey(0))
    state = _hand(tiny_env, state, [0])  # up single at (0,0)
    state, reward, done = tiny_env.step(state, jnp.int32(0))
    assert float(reward) == tiny_env.cfg.REWARD_PER_PLACED_TRIANGLE
    assert float(state.score) == float(reward)
    occ = tiny_env.unpack_grid_np(np.asarray(state.occupied))
    assert occ[0, 0] and occ.sum() == 1
    assert int(state.step_count) == 1 and not bool(done)
    assert int(state.last_cleared) == 0


def test_fill_row_clears_line(tiny_env):
    # Place singles across row 0; the 4-cell horizontal line clears.
    state = tiny_env.reset(jax.random.PRNGKey(0))
    total = 0.0
    for c in range(4):
        # Cover cell (0, c): even cells via the up single anchored there,
        # odd cells via the down single anchored one column left.
        sid, action = (0, c) if c % 2 == 0 else (1, c - 1)
        state = _hand(tiny_env, state, [sid])
        state, reward, done = tiny_env.step(state, jnp.int32(action))
        total += float(reward)
    assert int(state.last_cleared) == 4
    # Last reward: 1 placed + 4 cleared * 2.0.
    assert float(reward) == pytest.approx(1.0 + 4 * 2.0)
    assert not tiny_env.unpack_grid_np(np.asarray(state.occupied))[
        0
    ].any()  # row cleared
    assert float(state.score) == pytest.approx(total)


def test_full_board_clears_everything(tiny_env):
    # Occupy all but (0,0); placing the last up triangle fills every
    # horizontal line simultaneously and the whole board clears.
    state = tiny_env.reset(jax.random.PRNGKey(0))
    occ = np.ones((3, 4), dtype=bool)
    occ[0, 0] = False
    state = state.replace(occupied=jnp.asarray(tiny_env.pack_grid_np(occ)))
    state = _hand(tiny_env, state, [0])
    state, reward, done = tiny_env.step(state, jnp.int32(0))
    assert int(state.last_cleared) == 12
    assert not np.asarray(state.occupied).any()
    assert float(reward) == pytest.approx(1.0 + 12 * 2.0)
    assert not bool(done)


def test_invalid_action_forfeits(tiny_env):
    state = tiny_env.reset(jax.random.PRNGKey(0))
    state = _hand(tiny_env, state, [0])
    before = np.asarray(state.occupied).copy()
    # Action 1 has odd parity: invalid for the up single triangle.
    state, reward, done = tiny_env.step(state, jnp.int32(1))
    assert bool(done)
    assert float(reward) == tiny_env.cfg.PENALTY_GAME_OVER
    np.testing.assert_array_equal(np.asarray(state.occupied), before)
    # Stepping a finished game is a no-op with zero reward.
    state2, reward2, done2 = tiny_env.step(state, jnp.int32(0))
    assert bool(done2) and float(reward2) == 0.0


def test_stuck_game_over_with_penalty():
    # No clearable lines (LINE_MIN_LENGTH > board) on a 2x2 board: filling
    # the last cell leaves a full board, and the fresh hand cannot fit.
    cfg = EnvConfig(
        ROWS=2,
        COLS=2,
        PLAYABLE_RANGE_PER_ROW=[(0, 2), (0, 2)],
        NUM_SHAPE_SLOTS=1,
        MAX_SHAPE_TRIANGLES=1,
        LINE_MIN_LENGTH=99,
    )
    env = TriangleEnv(cfg)
    state = env.reset(jax.random.PRNGKey(0))
    occ = np.ones((2, 2), dtype=bool)
    occ[0, 0] = False
    state = state.replace(
        occupied=jnp.asarray(env.pack_grid_np(occ)),
        shape_idx=jnp.asarray([0], dtype=jnp.int32),
    )
    state, reward, done = env.step(state, jnp.int32(0))
    assert bool(done)
    assert float(reward) == pytest.approx(1.0 + cfg.PENALTY_GAME_OVER)
    # Penalty is not part of the score.
    assert float(state.score) == pytest.approx(1.0)


def test_hand_refills_only_when_empty():
    cfg = EnvConfig(
        ROWS=3,
        COLS=4,
        PLAYABLE_RANGE_PER_ROW=[(0, 4)] * 3,
        NUM_SHAPE_SLOTS=2,
        MAX_SHAPE_TRIANGLES=1,
    )
    env = TriangleEnv(cfg)
    state = env.reset(jax.random.PRNGKey(3))
    state = state.replace(shape_idx=jnp.asarray([0, 1], dtype=jnp.int32))
    # Consume slot 0 (up single at (0,0)).
    state, _, _ = env.step(state, jnp.int32(0))
    hand = np.asarray(state.shape_idx)
    assert hand[0] == -1 and hand[1] == 1  # no refill yet
    # Consume slot 1: down single anchored at (0,0) occupies cell (0,1).
    state, _, _ = env.step(state, jnp.int32(12 + 0))
    hand = np.asarray(state.shape_idx)
    assert (hand >= 0).all()  # refilled


# --- batched episodes under jit --------------------------------------------


def test_batched_random_episodes(tiny_env):
    batch = 8
    keys = jax.random.split(jax.random.PRNGKey(42), batch)
    state = tiny_env.reset_batch(keys)
    rng = np.random.default_rng(0)
    total_steps = 0
    for _ in range(200):
        mask = np.asarray(tiny_env.valid_mask_batch(state))
        if not mask.any():
            break
        # Random valid action per live game (0 for finished ones).
        actions = np.zeros(batch, dtype=np.int32)
        for b in range(batch):
            valid = np.flatnonzero(mask[b])
            if len(valid):
                actions[b] = rng.choice(valid)
        state, rewards, dones = tiny_env.step_batch(state, jnp.asarray(actions))
        assert np.isfinite(np.asarray(rewards)).all()
        total_steps += 1
        if np.asarray(dones).all():
            break
    assert np.asarray(state.done).all(), "random play should end within 200 moves"
    assert total_steps > 2


def test_reset_where_done(tiny_env):
    batch = 4
    keys = jax.random.split(jax.random.PRNGKey(1), batch)
    state = tiny_env.reset_batch(keys)
    done = np.zeros(batch, dtype=bool)
    done[1] = True
    state = state.replace(
        done=jnp.asarray(done),
        score=jnp.full((batch,), 5.0, dtype=jnp.float32),
    )
    out = tiny_env.reset_where_done_jit(state, jax.random.PRNGKey(9))
    scores = np.asarray(out.score)
    assert scores[1] == 0.0  # replaced
    assert (scores[[0, 2, 3]] == 5.0).all()  # untouched
    assert not np.asarray(out.done).any()


# --- GameState parity wrapper ----------------------------------------------


def test_game_state_surface(tiny_env_config):
    gs = GameState(tiny_env_config, initial_seed=11)
    assert not gs.is_over()
    assert gs.get_game_over_reason() is None
    assert gs.game_score() == 0.0
    assert gs.current_step == 0
    grid = gs.get_grid_data_np()
    assert set(grid) == {"occupied", "death", "color_id"}
    assert grid["occupied"].shape == (3, 4)
    assert grid["occupied"].dtype == bool
    shapes = gs.get_shapes()
    assert len(shapes) == tiny_env_config.NUM_SHAPE_SLOTS
    for sh in shapes:
        assert sh is not None
        assert 1 <= len(sh.triangles) <= tiny_env_config.MAX_SHAPE_TRIANGLES
        mn_r, mn_c, mx_r, mx_c = sh.bbox()
        assert mn_r <= mx_r and mn_c <= mx_c
        for r, c, up in sh.triangles:
            assert up == ((r + c) % 2 == 0)


def test_game_state_full_episode(tiny_env_config):
    rng = np.random.default_rng(5)
    gs = GameState(tiny_env_config, initial_seed=2)
    rewards = []
    for _ in range(100):
        if gs.is_over():
            break
        acts = gs.valid_actions()
        assert acts, "live game must expose valid actions"
        reward, done = gs.step(int(rng.choice(acts)))
        rewards.append(reward)
    assert gs.is_over()
    assert gs.get_game_over_reason() is not None
    # Score equals the gains; the final reward carries the game-over penalty.
    expected = sum(rewards) - tiny_env_config.PENALTY_GAME_OVER
    assert gs.game_score() == pytest.approx(expected)
    assert gs.current_step == len(rewards)


def test_game_state_copy_independent(tiny_env_config):
    gs = GameState(tiny_env_config, initial_seed=3)
    clone = gs.copy()
    act = gs.valid_actions()[0]
    gs.step(act)
    assert clone.current_step == 0
    assert gs.current_step == 1
