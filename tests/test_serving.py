"""Policy-serving subsystem (alphatriangle_tpu/serving/): session
slot-array semantics, continuous-batching dispatch, SLO ledger wiring,
hot weight reload, and the `cli serve --smoke` front end."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphatriangle_tpu.env.engine import TriangleEnv
from alphatriangle_tpu.features.core import get_feature_extractor
from alphatriangle_tpu.mcts import BatchedMCTS
from alphatriangle_tpu.nn.network import NeuralNetwork
from alphatriangle_tpu.serving import (
    PolicyService,
    SessionSlots,
    build_serve_telemetry,
    run_simulated_load,
    serve_program_name,
)

SLOTS = 8


@pytest.fixture(scope="module")
def serve_world(tiny_env_config, tiny_model_config):
    from alphatriangle_tpu.config import AlphaTriangleMCTSConfig

    env = TriangleEnv(tiny_env_config)
    fe = get_feature_extractor(env, tiny_model_config)
    net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
    # A deliberately small search (4 sims, depth 3): these tests pin
    # queue/slot/swap semantics, not search quality — and ONE search
    # instance module-wide means every PolicyService shares the jitted
    # program (and the serve/b8 executable).
    mcts_cfg = AlphaTriangleMCTSConfig(
        max_simulations=4, max_depth=3, mcts_batch_size=4
    )
    mcts = BatchedMCTS(env, fe, net.model, mcts_cfg, net.support)
    return env, fe, net, mcts


def make_service(serve_world, **kw):
    env, fe, net, mcts = serve_world
    return PolicyService(env, fe, net, mcts, slots=SLOTS, **kw)


class TestSessionSlots:
    def test_admit_retire_churn_reuses_lowest_slots(self, serve_world):
        env = serve_world[0]
        slots = SessionSlots(env, 4)
        keys = jax.random.split(jax.random.PRNGKey(0), 4)
        a, b, c, d = slots.admit_many(keys)
        assert [s.slot for s in (a, b, c, d)] == [0, 1, 2, 3]
        with pytest.raises(RuntimeError):
            slots.admit(jax.random.PRNGKey(9))
        slots.retire(b.sid)
        slots.retire(d.sid)
        # Freed lanes re-freeze (inert padding for search and engine).
        done = np.asarray(slots.states.done)
        assert done[1] and done[3]
        e = slots.admit(jax.random.PRNGKey(5))
        assert e.slot == 1  # lowest free slot first
        assert slots.admitted_total == 5 and slots.retired_total == 2
        assert slots.live_count == 3 and slots.free_count == 1

    def test_masked_step_leaves_unmasked_lanes_untouched(self, serve_world):
        env = serve_world[0]
        slots = SessionSlots(env, 4)
        slots.admit_many(jax.random.split(jax.random.PRNGKey(1), 4))
        before = jax.tree_util.tree_map(np.asarray, slots.states)
        # Step only lanes 0 and 2 with a valid action each.
        masks = np.asarray(env.valid_mask_batch(slots.states))
        actions = masks.argmax(axis=1)
        mask = np.array([True, False, True, False])
        slots.step(actions, mask)
        after = jax.tree_util.tree_map(np.asarray, slots.states)
        np.testing.assert_array_equal(
            before.step_count[[1, 3]], after.step_count[[1, 3]]
        )
        np.testing.assert_array_equal(
            before.occupied[[1, 3]], after.occupied[[1, 3]]
        )
        assert (after.step_count[[0, 2]] == before.step_count[[0, 2]] + 1).all()


def drive_session(
    service,
    reset_key,
    dispatch_keys,
    churn=False,
    seed=7,
    switch=None,
):
    """Admit ONE tracked session (slot 0) and drive it to completion
    with a fixed dispatch-key sequence; optionally churn other
    sessions around it. `switch=(i, rung)` forces a ladder rung switch
    after dispatch i (serving/buckets.py). Returns the tracked
    session's (actions, scores) trajectory."""
    tracked = service.open_session(reset_key)
    assert tracked.slot == 0
    others = []
    if churn:
        others = service.open_sessions(
            jax.random.split(jax.random.PRNGKey(seed), 3)
        )
        for o in others:
            service.request_move(o.sid)
    actions, scores = [], []
    for i, key in enumerate(dispatch_keys):
        service.request_move(tracked.sid)
        results = service.dispatch(rng=key)
        mine = next(r for r in results if r["sid"] == tracked.sid)
        actions.append(mine["action"])
        scores.append(mine["score"])
        if churn:
            # Real churn: retire + replace neighbours mid-stream.
            for r in results:
                if r["sid"] == tracked.sid:
                    continue
                if r["done"] or i % 2:
                    service.close_session(r["sid"])
                else:
                    service.request_move(r["sid"])
            n_fresh = min(2, service.sessions.free_count)
            if n_fresh:
                fresh = service.open_sessions(
                    jax.random.split(
                        jax.random.PRNGKey(1000 + seed + i), n_fresh
                    )
                )
                for o in fresh:
                    service.request_move(o.sid)
        if switch is not None and i == switch[0]:
            service._switch_rung(switch[1], "test")
        if mine["done"]:
            break
    service.close_session(tracked.sid)
    for s in list(service.sessions.live_sessions()):
        service.close_session(s.sid)
    return actions, scores


class TestPolicyService:
    def test_padded_slots_never_leak_into_real_sessions(self, serve_world):
        """Lane isolation: a session pinned to slot 0 plays the exact
        same game whether the other lanes are empty padding or a
        churning crowd of admits/retires — the property that makes
        partial-batch padding correct."""
        reset_key = jax.random.PRNGKey(42)
        dispatch_keys = [jax.random.PRNGKey(100 + i) for i in range(10)]
        solo = drive_session(
            make_service(serve_world), reset_key, dispatch_keys,
            churn=False,
        )
        crowded = drive_session(
            make_service(serve_world), reset_key, dispatch_keys,
            churn=True,
        )
        assert solo == crowded

    def test_lane_isolation_holds_with_tree_reuse(self, serve_world):
        """Subtree reuse carries per-lane trees across dispatches
        (docs/KERNELS.md): a slot-0 session must still play the exact
        same game solo vs inside a churning crowd — admits/retires
        invalidate ONLY their own lanes' carried trees — and the
        carried visits must actually register on the reuse counter."""
        from alphatriangle_tpu.config import AlphaTriangleMCTSConfig

        env, fe, net, _mcts = serve_world
        # 8 sims / depth 4 (vs the fixture's 4/3): the promoted child
        # needs expanded edges of its own before the reuse counter can
        # register carried visits.
        reuse_cfg = AlphaTriangleMCTSConfig(
            max_simulations=8, max_depth=4, mcts_batch_size=4,
            tree_reuse=True,
        )
        mcts = BatchedMCTS(env, fe, net.model, reuse_cfg, net.support)
        reset_key = jax.random.PRNGKey(42)
        dispatch_keys = [jax.random.PRNGKey(100 + i) for i in range(10)]
        solo_service = PolicyService(env, fe, net, mcts, slots=SLOTS)
        solo = drive_session(
            solo_service, reset_key, dispatch_keys, churn=False
        )
        crowded = drive_session(
            PolicyService(env, fe, net, mcts, slots=SLOTS),
            reset_key, dispatch_keys, churn=True,
        )
        assert solo == crowded
        assert solo_service.reused_visits_total > 0

    def test_dispatch_serves_queue_and_reports_latency(self, serve_world):
        service = make_service(serve_world)
        sessions = service.open_sessions(
            jax.random.split(jax.random.PRNGKey(3), 5)
        )
        for s in sessions:
            service.request_move(s.sid)
        assert service.queue_depth == 5
        results = service.dispatch()
        assert service.queue_depth == 0
        assert {r["sid"] for r in results} == {s.sid for s in sessions}
        for r in results:
            assert r["latency_ms"] >= r["queue_wait_ms"] >= 0.0
        assert service.dispatch_count == 1
        assert service.requests_total == 5
        stats = service.serve_stats(drain=False)
        assert stats["serve_move_latency_ms_p95"] is not None
        assert stats["serve_batch_fill"] == pytest.approx(5 / SLOTS)
        for s in sessions:
            service.close_session(s.sid)

    def test_double_request_rejected(self, serve_world):
        service = make_service(serve_world)
        s = service.open_session(jax.random.PRNGKey(0))
        service.request_move(s.sid)
        with pytest.raises(RuntimeError):
            service.request_move(s.sid)
        service.dispatch()
        service.close_session(s.sid)

    def test_hot_weight_swap_mid_stream(self, serve_world):
        """reload_weights between dispatches changes play without a
        recompile: the swapped run diverges from the unswapped one
        after the swap point, and the compile cache records no new
        event for the serve program."""
        from alphatriangle_tpu.compile_cache import get_compile_cache

        env, fe, net, mcts = serve_world
        reset_key = jax.random.PRNGKey(11)
        dispatch_keys = [jax.random.PRNGKey(500 + i) for i in range(8)]
        original = net.get_weights()
        try:
            baseline = drive_session(
                make_service(serve_world), reset_key, dispatch_keys
            )

            service = make_service(serve_world)
            tracked = service.open_session(reset_key)
            # Load the executable first (a fresh service instance
            # deserializes once — a legitimate cache event); THEN pin
            # that the weight swap itself causes no compile activity.
            service.warm()
            events_before = len(get_compile_cache().stats()["events"])
            actions = []
            for i, key in enumerate(dispatch_keys):
                if i == 2:
                    perturbed = jax.tree_util.tree_map(
                        lambda x: x + 0.5, net.variables
                    )
                    assert service.reload_weights(perturbed) == 1
                service.request_move(tracked.sid)
                results = service.dispatch(rng=key)
                mine = next(
                    r for r in results if r["sid"] == tracked.sid
                )
                actions.append(mine["action"])
                if mine["done"]:
                    break
            assert actions[:2] == baseline[0][:2]
            assert actions != baseline[0]  # the swap changed play
            assert (
                len(get_compile_cache().stats()["events"])
                == events_before
            )
            service.close_session(tracked.sid)
        finally:
            net.set_weights(original)  # module-scoped fixture

    def test_loadgen_churn_and_ledger_records(
        self, serve_world, tiny_env_config, tiny_model_config, tmp_path
    ):
        """>slots sessions through the batcher with telemetry: churn
        completes, and the run dir gains util records carrying the
        serve latency fields plus a heartbeat with the serve view."""
        env, fe, net, mcts = serve_world
        telemetry = build_serve_telemetry(
            tmp_path, "serve_test", tiny_env_config, tiny_model_config
        )
        service = PolicyService(
            env, fe, net, mcts, slots=SLOTS, telemetry=telemetry
        )
        total = SLOTS + 5  # > slots: churn by construction
        stats = run_simulated_load(
            service,
            total_sessions=total,
            max_moves=15,
            seed=1,
            tick_every=2,
            max_dispatches=200,
        )
        telemetry.close(step=service.dispatch_count)
        assert stats["sessions_served"] == total
        assert service.sessions.retired_total == total
        records = [
            json.loads(line)
            for line in (tmp_path / "metrics.jsonl").read_text().splitlines()
        ]
        lat = [
            r
            for r in records
            if r.get("kind") == "util"
            and isinstance(
                r.get("serve_move_latency_ms_p95"), (int, float)
            )
        ]
        assert lat, "no util record carried serve latency fields"
        assert lat[-1]["serve_sessions_retired"] == total
        health = json.loads((tmp_path / "health.json").read_text())
        # The heartbeat carries the serve view. (The latency
        # percentiles ride only on ticks whose window served requests
        # — the final drain tick may be empty — so assert on the
        # always-present occupancy/rate fields.)
        assert "serve_requests_per_sec" in (
            health.get("utilization") or {}
        )
        assert "serve_queue_depth" in (health.get("utilization") or {})

    def test_warm_and_analyze(self, serve_world):
        """The serve program AOT-warms and yields a memory record
        named serve/b<B> (the `cli warm` / `cli fit --serve` rows)."""
        service = make_service(serve_world)
        assert service.warm() is True
        record = service.analyze()
        assert record is not None
        assert record["program"] == serve_program_name(SLOTS)
        from alphatriangle_tpu.telemetry.memory import serve_budget_bytes

        assert serve_budget_bytes(record) > 0


class TestBucketLadder:
    """The serve-shape ladder micro-batcher (serving/buckets.py +
    PolicyService._maybe_walk): rung walking under load, lane isolation
    and carried-tree invalidation across switches, zero recompiles."""

    def test_ladder_and_quarantine_share_rungs(self):
        """serving/buckets.py is the single rung-set definition: the
        default ladder reproduces the legacy halving buckets exactly."""
        from alphatriangle_tpu.serving import BucketLadder, default_rungs

        assert default_rungs(8) == (1, 2, 4, 8)
        ladder = BucketLadder.from_spec("16,4,8,4", base=16)
        assert ladder.rungs == (4, 8, 16)
        assert ladder.rung_for(5) == 8
        assert ladder.rung_for(99) == 16  # clamped to the top
        assert ladder.rung_at_or_below(15) == 8
        assert ladder.walk_down(16) == 8

    def test_storm_walks_up_down_without_recompiling(self, serve_world):
        """The acceptance storm: a burst against a 2-slot base rung
        walks the micro-batcher up, the drain walks it back down, no
        request is lost, each wave is exactly one program dispatch,
        and — every rung having been warmed up front — no switch ever
        touches the compiler (compile-cache event count pinned)."""
        from alphatriangle_tpu.compile_cache import get_compile_cache

        env, fe, net, mcts = serve_world
        service = PolicyService(
            env, fe, net, mcts, slots=2, ladder="2,4,8", sustain=2
        )
        assert service.ladder.rungs == (2, 4, 8)
        assert service.max_slots == 8
        service.warm()

        def serve_events() -> int:
            return sum(
                1
                for e in get_compile_cache().stats()["events"]
                if str(e.get("program", "")).startswith("serve/b")
            )

        events_after_warm = serve_events()
        rungs_seen = []
        stats = run_simulated_load(
            service,
            total_sessions=20,
            concurrency=8,
            max_moves=6,
            seed=3,
            reload_hook=lambda svc, _d: rungs_seen.append(
                svc.sessions.slots
            ),
        )
        assert stats["sessions_served"] == 20  # zero lost requests
        assert service.rung_switches >= 2
        assert max(rungs_seen) > 2  # walked up under the burst
        assert rungs_seen[-1] < max(rungs_seen)  # and back down on drain
        assert serve_events() == events_after_warm  # zero recompiles
        assert service.dispatch_count == stats["dispatches"]

    def test_lane_isolation_across_rung_switch(self, serve_world):
        """A mid-stream rung switch migrates live sessions into the new
        slot array; the tracked slot-0 session must still play the
        exact same game solo vs inside a churning crowd — migration
        (SessionSlots.migrate) preserves every lane's state."""
        env, fe, net, mcts = serve_world
        reset_key = jax.random.PRNGKey(42)
        dispatch_keys = [jax.random.PRNGKey(100 + i) for i in range(10)]
        solo = drive_session(
            PolicyService(env, fe, net, mcts, slots=SLOTS, ladder="8,16"),
            reset_key, dispatch_keys, churn=False, switch=(3, 16),
        )
        crowded = drive_session(
            PolicyService(env, fe, net, mcts, slots=SLOTS, ladder="8,16"),
            reset_key, dispatch_keys, churn=True, switch=(3, 16),
        )
        assert solo == crowded

    def test_rung_switch_invalidates_carried_trees(self, serve_world):
        """A promoted subtree's static shape belongs to its bucket:
        switching rungs must drop every carried tree (`_carry_ok` all
        False at the new width) while live sessions keep identity."""
        from alphatriangle_tpu.config import AlphaTriangleMCTSConfig

        env, fe, net, _mcts = serve_world
        reuse_cfg = AlphaTriangleMCTSConfig(
            max_simulations=8, max_depth=4, mcts_batch_size=4,
            tree_reuse=True,
        )
        mcts = BatchedMCTS(env, fe, net.model, reuse_cfg, net.support)
        service = PolicyService(
            env, fe, net, mcts, slots=SLOTS, ladder="8,16"
        )
        sessions = service.open_sessions(
            jax.random.split(jax.random.PRNGKey(5), 3)
        )
        for _ in range(2):
            for s in sessions:
                service.request_move(s.sid)
            service.dispatch()
        assert service._carry_ok.any()  # trees are being carried
        service._switch_rung(16, "test")
        assert service.sessions.slots == 16
        assert service._carry_ok.shape == (16,)
        assert not service._carry_ok.any()  # all invalidated
        # Identity preserved: same sids, slots re-packed lowest-first.
        live = sorted(service.sessions.live_sessions(), key=lambda s: s.slot)
        assert [s.sid for s in live] == [s.sid for s in sessions]
        # And the service still serves at the new rung.
        for s in sessions:
            service.request_move(s.sid)
        results = service.dispatch()
        assert len(results) == 3
        for s in sessions:
            service.close_session(s.sid)


class TestConcurrentDrain:
    def test_serve_stats_drain_races_dispatch_without_losing_requests(
        self, serve_world
    ):
        """Regression: `serve_stats(drain=True)` from the telemetry
        thread while the service thread dispatches. Before the snapshot
        moved under the service lock, a drain landing mid-dispatch
        could read the window lists and reset them around a concurrent
        append — silently losing that dispatch's requests from the SLO
        window. Invariant: every served request shows up in exactly one
        drained window."""
        import threading

        service = make_service(serve_world)
        sessions = service.open_sessions(
            jax.random.split(jax.random.PRNGKey(21), 4)
        )
        drained: list[dict] = []
        done = threading.Event()

        def drainer():
            while not done.is_set():
                drained.append(service.serve_stats(drain=True))

        t = threading.Thread(target=drainer, daemon=True)
        t.start()
        try:
            for i in range(20):
                for s in sessions:
                    service.request_move(s.sid)
                service.dispatch(rng=jax.random.PRNGKey(700 + i))
        finally:
            done.set()
            t.join(timeout=10.0)
        drained.append(service.serve_stats(drain=True))
        assert (
            sum(s["serve_window_requests"] for s in drained)
            == service.requests_total
            == 80
        )
        for s in sessions:
            service.close_session(s.sid)

    def test_emitter_drain_races_session_close_without_losing_episodes(
        self,
    ):
        """Regression (league/emitter.py): `drain()` swapping the
        finished list while `on_session_close` appends must not drop
        episodes — the publication seam is lock-guarded. Driven with
        synthetic open-row state, no env/extractor needed."""
        import threading

        from alphatriangle_tpu.league.emitter import TrajectoryEmitter

        emitter = TrajectoryEmitter(None, None)
        total = 200

        def rows():
            return {
                "grid": [np.zeros((2, 2), dtype=np.float32)],
                "other": [np.zeros(3, dtype=np.float32)],
                "policy": [np.full(4, 0.25, dtype=np.float32)],
                "reward": [1.0],
                "version": [0],
            }

        for sid in range(total):
            emitter._open[sid] = rows()
        harvested = []
        done = threading.Event()

        def drainer():
            while not done.is_set():
                harvested.append(emitter.drain())

        t = threading.Thread(target=drainer, daemon=True)
        t.start()
        try:
            for sid in range(total):
                emitter.on_session_close(
                    sid, {"score": 1.0, "done": True}
                )
        finally:
            done.set()
            t.join(timeout=10.0)
        harvested.append(emitter.drain())
        episodes = sum(
            r.num_episodes for r in harvested if r is not None
        )
        assert episodes == total == emitter.episodes_emitted


class TestServeSummary:
    def test_perf_summary_carries_serve_fields(self):
        from alphatriangle_tpu.telemetry.perf import summarize_utilization

        records = [
            {
                "kind": "util",
                "step": i,
                "window_s": 1.0,
                "serve_move_latency_ms_p50": 5.0 + i,
                "serve_move_latency_ms_p95": 9.0 + i,
                "serve_queue_wait_ms_p50": 1.0,
                "serve_queue_wait_ms_p95": 2.0,
                "serve_requests_per_sec": 100.0,
                "serve_requests_total": 100 * (i + 1),
                "serve_sessions": 4,
                "serve_batch_fill": 0.5,
                "serve_weight_reloads": i,
            }
            for i in range(3)
        ]
        summary = summarize_utilization(records)
        assert summary["serve_move_latency_ms_p95"] == 11.0  # worst window
        assert summary["serve_move_latency_ms_p50"] == 6.0  # mean
        assert summary["serve_requests_total"] == 300
        assert summary["serve_weight_reloads"] == 2

    def test_compare_gates_serve_latency_lower_is_better(self):
        from alphatriangle_tpu.telemetry.perf import compare_summaries

        base = {
            "serve_move_latency_ms_p95": 10.0,
            "serve_requests_per_sec": 100.0,
        }
        slow = {
            "serve_move_latency_ms_p95": 25.0,
            "serve_requests_per_sec": 100.0,
        }
        rows, regressions = compare_summaries(slow, base, threshold=0.5)
        assert regressions == ["serve_move_latency_ms_p95"]
        fast = {"serve_move_latency_ms_p95": 4.0}
        rows, regressions = compare_summaries(
            fast, base, threshold=0.5,
            metrics=("serve_move_latency_ms_p95",),
        )
        assert not regressions
        assert rows[0][4] == "improved"
        # --metrics restricts the compared set.
        assert len(rows) == 1


class TestServeCli:
    @pytest.mark.slow
    def test_cli_serve_smoke_exit_0(
        self, tmp_path, tiny_env_config, tiny_model_config, capsys
    ):
        """`cli serve --smoke` end to end on the tiny world: warm +
        pre-flight + churn traffic + SLO ledger, exit 0.

        Marked slow (the megastep precedent): it compiles its own
        serve search program, and `make serve-smoke` runs the bigger
        sibling of this exact path in CI; tier-1 keeps the in-process
        service tests above."""
        from alphatriangle_tpu.cli import main as cli_main
        from alphatriangle_tpu.config import PersistenceConfig

        root = str(tmp_path)
        pc = PersistenceConfig(ROOT_DATA_DIR=root, RUN_NAME="tiny_src")
        run_dir = pc.get_run_base_dir()
        run_dir.mkdir(parents=True)
        (run_dir / "configs.json").write_text(
            json.dumps(
                {
                    "env": tiny_env_config.model_dump(),
                    "model": tiny_model_config.model_dump(),
                }
            )
        )
        rc = cli_main(
            [
                "serve",
                "--smoke",
                "--run-name", "tiny_src",
                "--root-dir", root,
                "--slots", "8",
                "--sessions", "12",
                "--sims", "4",
                "--max-moves", "20",
                "--tick-every", "3",
            ]
        )
        assert rc == 0
        report = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1]
        )
        assert report["sessions_served"] >= 12
        serve_dir = PersistenceConfig(
            ROOT_DATA_DIR=root, RUN_NAME="serve_tiny_src"
        ).get_run_base_dir()
        assert (serve_dir / "metrics.jsonl").exists()
        assert (serve_dir / "health.json").exists()
        # And `cli perf --json` summarizes the SLO fields (the full
        # compare gate lives in `make serve-smoke`).
        rc = cli_main(
            ["perf", "serve_tiny_src", "--root-dir", root, "--json"]
        )
        assert rc == 0
        summary = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1]
        )
        assert isinstance(
            summary.get("serve_move_latency_ms_p95"), (int, float)
        )
        assert isinstance(
            summary.get("serve_requests_per_sec"), (int, float)
        )
