"""graftlint (alphatriangle_tpu/analysis/, docs/ANALYSIS.md).

Every rule is pinned by one fixture true positive AND one near-miss
true negative, so the analyzer's precision is a test contract. The
engine tests pin the pragma/baseline semantics and the exit-code
contract (0 clean / 1 findings-or-stale-baseline / 2 parse error);
the CLI tests drive `cli lint` exactly as the Makefile and
tpu_watch.sh preflight do, including the no-jax import guard.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from alphatriangle_tpu.analysis import (
    LINT_SCHEMA,
    RULE_NAMES,
    run_lint,
    write_baseline,
)
from alphatriangle_tpu.cli import main as cli_main

REPO = Path(__file__).resolve().parent.parent


def lint_tree(tmp_path, files, **kw):
    """Write {relpath: source} under a fresh root and lint it."""
    root = tmp_path / "pkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_lint(root, **kw)


def rules_hit(report):
    return {f.rule for f in report.findings}


# --- rule: use-after-donation ---------------------------------------------


DONATION_BAD = """
    import jax

    class Trainer:
        def __init__(self, cache, impl):
            self._step = cache.wrap(
                "learner_step", jax.jit(impl, donate_argnums=(0,))
            )

        def bad(self, state, batch):
            new_state, metrics = self._step(state, batch)
            return state.params, metrics
"""

DONATION_GOOD = """
    import jax

    class Trainer:
        def __init__(self, cache, impl):
            self._step = cache.wrap(
                "learner_step", jax.jit(impl, donate_argnums=(0,))
            )

        def good(self, state, batch):
            state, metrics = self._step(state, batch)
            return state.params, metrics

        def also_good(self, state, batch):
            out, metrics = self._step(state, batch)
            return batch, metrics
"""


class TestUseAfterDonation:
    def test_true_positive(self, tmp_path):
        r = lint_tree(tmp_path, {"rl/t.py": DONATION_BAD})
        hits = [f for f in r.findings if f.rule == "use-after-donation"]
        assert len(hits) == 1
        assert "`state`" in hits[0].message
        assert hits[0].context == "Trainer.bad"

    def test_true_negative_rebind_and_other_arg(self, tmp_path):
        r = lint_tree(tmp_path, {"rl/t.py": DONATION_GOOD})
        assert "use-after-donation" not in rules_hit(r)

    def test_direct_jit_assignment(self, tmp_path):
        src = """
            import jax

            def run(buf, rows):
                ingest = jax.jit(_impl, donate_argnums=(0,))
                out = ingest(buf, rows)
                return buf.shape
        """
        r = lint_tree(tmp_path, {"rl/u.py": src})
        assert "use-after-donation" in rules_hit(r)

    def test_lambda_factory_not_treated_as_donating(self, tmp_path):
        # A factory RETURNING donating programs is not itself one —
        # calling it must not count as a donation site.
        src = """
            import jax

            def build(cache):
                factory = lambda t: cache.wrap(
                    "x", jax.jit(_impl, donate_argnums=(0,))
                )
                prog = factory(4)
                return factory, prog
        """
        r = lint_tree(tmp_path, {"rl/v.py": src})
        assert "use-after-donation" not in rules_hit(r)


# --- rule: host-sync-in-hot-path ------------------------------------------


class TestHostSyncInHotPath:
    def test_item_true_positive_in_hot_module(self, tmp_path):
        src = """
            def loop(metrics):
                return metrics.item()
        """
        r = lint_tree(tmp_path, {"rl/hot.py": src})
        assert "host-sync-in-hot-path" in rules_hit(r)

    def test_same_code_cold_module_is_clean(self, tmp_path):
        src = """
            def loop(metrics):
                return metrics.item()
        """
        r = lint_tree(tmp_path, {"stats/cold.py": src})
        assert "host-sync-in-hot-path" not in rules_hit(r)

    def test_shape_only_transfer(self, tmp_path):
        src = """
            import numpy as np

            def f(batch):
                n = int(np.asarray(batch["v"]).shape[0])
                ok = np.asarray(batch["v"])  # real conversion: not flagged
                return n, ok
        """
        r = lint_tree(tmp_path, {"serving/s.py": src})
        hits = [f for f in r.findings if f.rule == "host-sync-in-hot-path"]
        assert len(hits) == 1
        assert "shape" in hits[0].message

    def test_fragmented_attribute_fetch_and_shallow_negative(self, tmp_path):
        src = """
            import numpy as np

            class S:
                def retire(self, slot):
                    score = float(np.asarray(self.states.score[slot]))
                    local = np.asarray(self.buf)  # depth-1 attr: not flagged
                    return score, local
        """
        r = lint_tree(tmp_path, {"serving/t.py": src})
        hits = [f for f in r.findings if f.rule == "host-sync-in-hot-path"]
        assert len(hits) == 1
        assert "self.states.score" in hits[0].message

    def test_device_get_flagged_and_pragma_allows(self, tmp_path):
        src = """
            import jax

            def fetch(out):
                a = jax.device_get(out)
                b = jax.device_get(out)  # graftlint: allow(host-sync-in-hot-path) the one deliberate fetch
                return a, b
        """
        r = lint_tree(tmp_path, {"mcts/m.py": src})
        hits = [f for f in r.findings if f.rule == "host-sync-in-hot-path"]
        assert len(hits) == 1
        assert hits[0].line == 5
        assert r.suppressed_pragma == 1

    def test_io_callback_true_positive_beacon_callback_sanctioned(
        self, tmp_path
    ):
        # The device-telemetry plane's beacons use jax.debug.callback
        # (unordered, fire-and-forget) — sanctioned in hot programs.
        # io_callback blocks the program on the host round-trip: flagged.
        src = """
            import jax
            from jax.experimental import io_callback

            def wave_body(k, carry):
                jax.debug.callback(lambda i: None, k, ordered=False)
                io_callback(lambda i: i, k, k)
                return carry
        """
        r = lint_tree(tmp_path, {"mcts/beacons.py": src})
        hits = [f for f in r.findings if f.rule == "host-sync-in-hot-path"]
        assert len(hits) == 1
        assert "io_callback" in hits[0].message
        assert hits[0].line == 7

    def test_debug_callback_alone_is_clean(self, tmp_path):
        src = """
            import jax

            def wave_body(k, carry):
                jax.debug.callback(lambda i: None, k, ordered=False)
                return carry
        """
        r = lint_tree(tmp_path, {"rl/beacons.py": src})
        assert "host-sync-in-hot-path" not in rules_hit(r)

    def test_training_loop_and_flywheel_are_hot(self, tmp_path):
        src = """
            def f(x):
                return x.item()
        """
        r = lint_tree(
            tmp_path,
            {"training/loop.py": src, "league/flywheel.py": src,
             "training/setup.py": src},
        )
        hot = [f.path for f in r.findings]
        assert "training/loop.py" in hot
        assert "league/flywheel.py" in hot
        assert "training/setup.py" not in hot


# --- rule: mixed-placement-dispatch ---------------------------------------


MIXED_BAD = """
    import jax
    import numpy as np

    class Runner:
        def __init__(self, cache, fn):
            self._prog = cache.wrap("megastep/t4_k2", fn)

        def bad(self, x, y):
            a = jax.device_put(x)
            b = np.zeros(4)
            return self._prog(a, b)
"""

MIXED_GOOD = """
    import jax
    import numpy as np

    class Runner:
        def __init__(self, cache, fn):
            self._prog = cache.wrap("megastep/t4_k2", fn)

        def good(self, x, y):
            a = jax.device_put(x)
            b = jax.device_put(np.zeros(4))
            return self._prog(a, b)
"""


class TestMixedPlacementDispatch:
    def test_true_positive(self, tmp_path):
        r = lint_tree(tmp_path, {"rl/m.py": MIXED_BAD})
        hits = [
            f for f in r.findings if f.rule == "mixed-placement-dispatch"
        ]
        assert len(hits) == 1
        assert "recompiles" in hits[0].message

    def test_all_committed_is_clean(self, tmp_path):
        r = lint_tree(tmp_path, {"rl/m.py": MIXED_GOOD})
        assert "mixed-placement-dispatch" not in rules_hit(r)


# --- rule: unbracketed-hot-dispatch ---------------------------------------


UNBRACKETED_BAD = """
    class Runner:
        def __init__(self, cache, fn):
            self._mega = cache.wrap("megastep/t4_k2", fn)

        def bad(self, args):
            return self._mega(args)
"""

BRACKETED_GOOD = """
    from ..telemetry.flight import flight_span

    class Runner:
        def __init__(self, cache, fn):
            self._mega = cache.wrap("megastep/t4_k2", fn)
            self._cold = cache.wrap("admit_rows", fn)

        def good_with(self, args):
            with flight_span(self.flight, "megastep", "megastep/t4_k2"):
                return self._mega(args)

        def good_begin(self, args):
            span = self.flight.begin("megastep", "megastep/t4_k2")
            out = self._mega(args)
            span.seal()
            return out

        def cold_family_needs_no_bracket(self, args):
            return self._cold(args)
"""


class TestUnbracketedHotDispatch:
    def test_true_positive(self, tmp_path):
        r = lint_tree(tmp_path, {"rl/d.py": UNBRACKETED_BAD})
        hits = [
            f for f in r.findings if f.rule == "unbracketed-hot-dispatch"
        ]
        assert len(hits) == 1
        assert "'megastep'" in hits[0].message

    def test_bracketed_and_cold_family_clean(self, tmp_path):
        r = lint_tree(tmp_path, {"rl/d.py": BRACKETED_GOOD})
        assert "unbracketed-hot-dispatch" not in rules_hit(r)

    @pytest.mark.parametrize(
        "name", ["self_play_chunk/t64", "learner_step", "serve/b64"]
    )
    def test_all_instrumented_families_covered(self, tmp_path, name):
        src = UNBRACKETED_BAD.replace("megastep/t4_k2", name)
        r = lint_tree(tmp_path, {"rl/d.py": src})
        assert "unbracketed-hot-dispatch" in rules_hit(r)


# --- rule: debug-artifact --------------------------------------------------


class TestDebugArtifact:
    def test_true_positives(self, tmp_path):
        src = """
            import jax

            def f(x):
                jax.debug.print("x={}", x)
                breakpoint()
                return x
        """
        r = lint_tree(tmp_path, {"nn/dbg.py": src})
        hits = [f for f in r.findings if f.rule == "debug-artifact"]
        assert len(hits) == 2

    def test_logger_debug_is_clean(self, tmp_path):
        src = """
            import logging

            logger = logging.getLogger(__name__)

            def f(x):
                logger.debug("x=%s", x)
                return x
        """
        r = lint_tree(tmp_path, {"nn/dbg.py": src})
        assert "debug-artifact" not in rules_hit(r)

    def test_pdb_import(self, tmp_path):
        r = lint_tree(tmp_path, {"rl/p.py": "import pdb\n"})
        assert "debug-artifact" in rules_hit(r)


# --- rule: untracked-rng ---------------------------------------------------


class TestUntrackedRng:
    def test_global_np_random_in_device_module(self, tmp_path):
        src = """
            import numpy as np

            def noise(shape):
                return np.random.randint(0, 4, shape)
        """
        r = lint_tree(tmp_path, {"mcts/r.py": src})
        assert "untracked-rng" in rules_hit(r)

    def test_seeded_generator_and_cold_module_clean(self, tmp_path):
        seeded = """
            import numpy as np

            def gen(seed):
                return np.random.default_rng(seed)
        """
        cold = """
            import numpy as np

            def noise(shape):
                return np.random.randint(0, 4, shape)
        """
        r = lint_tree(tmp_path, {"rl/g.py": seeded, "stats/c.py": cold})
        assert "untracked-rng" not in rules_hit(r)

    def test_stdlib_random_import(self, tmp_path):
        r = lint_tree(tmp_path, {"env/e.py": "import random\n"})
        assert "untracked-rng" in rules_hit(r)


# --- rule: untrapped-exit --------------------------------------------------


class TestUntrappedExit:
    def test_true_positive_hot_path(self, tmp_path):
        src = """
            import sys

            def bail(metrics):
                if metrics["loss"] != metrics["loss"]:
                    sys.exit(1)
        """
        r = lint_tree(tmp_path, {"rl/bail.py": src})
        hits = [f for f in r.findings if f.rule == "untrapped-exit"]
        assert len(hits) == 1
        assert "sys.exit" in hits[0].message

    def test_true_positive_training_os_exit(self, tmp_path):
        src = """
            import os

            def hard_stop():
                os._exit(3)
        """
        r = lint_tree(tmp_path, {"training/stop.py": src})
        assert "untrapped-exit" in rules_hit(r)

    def test_true_negative_cold_module(self, tmp_path):
        # Same code in a cold dir: CLI-ish exits outside the hot path /
        # training loop are not this rule's business.
        src = """
            import sys

            def bail():
                sys.exit(1)
        """
        r = lint_tree(tmp_path, {"stats/report.py": src})
        assert "untrapped-exit" not in rules_hit(r)

    def test_whitelist_sanctioned_exiters(self, tmp_path):
        # The dispatch watchdog (os._exit is the point — the thread that
        # would run shutdown is the wedged one) and the supervisor own
        # process lifecycle; they stay clean even if their dirs are ever
        # promoted into the hot-path set.
        src = """
            import os, sys

            def die():
                os._exit(113)

            def give_up():
                sys.exit(115)
        """
        r = lint_tree(
            tmp_path,
            {"supervise/supervisor.py": src, "telemetry/flight.py": src},
        )
        assert "untrapped-exit" not in rules_hit(r)


# --- engine: pragmas, baseline, exit codes --------------------------------


ONE_PER_RULE = {
    "training/exit.py": """
        import sys

        def f(step):
            sys.exit(1)
    """,
    "rl/donation.py": DONATION_BAD,
    "rl/mixed.py": MIXED_BAD,
    "rl/dispatch.py": UNBRACKETED_BAD,
    "serving/sync.py": """
        def f(x):
            return x.item()
    """,
    "nn/dbg.py": """
        def f(x):
            breakpoint()
            return x
    """,
    "mcts/rng.py": """
        import numpy as np

        def f():
            return np.random.rand(3)
    """,
}


class TestEngine:
    def test_one_violation_per_rule_tree(self, tmp_path):
        r = lint_tree(tmp_path, ONE_PER_RULE)
        assert rules_hit(r) == set(RULE_NAMES)
        assert r.exit_code == 1

    def test_rule_selector(self, tmp_path):
        r = lint_tree(tmp_path, ONE_PER_RULE, rule_names=["debug-artifact"])
        assert rules_hit(r) == {"debug-artifact"}
        assert r.rules == ["debug-artifact"]

    def test_unknown_rule_raises(self, tmp_path):
        with pytest.raises(ValueError, match="unknown rule"):
            lint_tree(tmp_path, ONE_PER_RULE, rule_names=["nope"])

    def test_parse_error_exit_2(self, tmp_path):
        r = lint_tree(tmp_path, {"rl/broken.py": "def f(:\n"})
        assert r.exit_code == 2
        assert r.parse_errors and r.parse_errors[0]["path"] == "rl/broken.py"

    def test_clean_tree_exit_0(self, tmp_path):
        r = lint_tree(tmp_path, {"rl/ok.py": "X = 1\n"})
        assert r.exit_code == 0

    def test_baseline_suppresses_then_stales(self, tmp_path):
        r = lint_tree(tmp_path, {"serving/sync.py": ONE_PER_RULE["serving/sync.py"]})
        assert r.exit_code == 1
        baseline = tmp_path / "baseline.json"
        write_baseline(baseline, r.findings)

        # Same tree + baseline: suppressed, clean.
        r2 = run_lint(tmp_path / "pkg", baseline_path=baseline)
        assert r2.exit_code == 0
        assert r2.suppressed_baseline == 1

        # Finding fixed but baseline kept: the entry is STALE -> dirty.
        (tmp_path / "pkg" / "serving" / "sync.py").write_text(
            "def f(x):\n    return x\n"
        )
        r3 = run_lint(tmp_path / "pkg", baseline_path=baseline)
        assert r3.exit_code == 1
        assert len(r3.stale_baseline) == 1
        assert not r3.findings

    def test_baseline_survives_line_drift(self, tmp_path):
        r = lint_tree(tmp_path, {"serving/sync.py": ONE_PER_RULE["serving/sync.py"]})
        baseline = tmp_path / "baseline.json"
        write_baseline(baseline, r.findings)
        # Prepend lines: finding moves, key (scope+text) does not.
        p = tmp_path / "pkg" / "serving" / "sync.py"
        p.write_text("# header\n# more header\n" + p.read_text())
        r2 = run_lint(tmp_path / "pkg", baseline_path=baseline)
        assert r2.exit_code == 0
        assert r2.suppressed_baseline == 1

    def test_corrupt_baseline_raises(self, tmp_path):
        bad = tmp_path / "b.json"
        bad.write_text("{not json")
        with pytest.raises(ValueError, match="not valid JSON"):
            lint_tree(tmp_path, ONE_PER_RULE, baseline_path=bad)


# --- cli lint --------------------------------------------------------------


class TestCliLint:
    def make_tree(self, tmp_path, files):
        root = tmp_path / "pkg"
        for rel, src in files.items():
            p = root / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(src))
        return root

    def test_exit_1_on_seeded_tree_and_json_schema(self, tmp_path, capsys):
        root = self.make_tree(tmp_path, ONE_PER_RULE)
        rc = cli_main(["lint", str(root), "--json"])
        out = json.loads(capsys.readouterr().out.strip())
        assert rc == 1
        assert list(out)[0] == "schema"
        assert out["schema"] == LINT_SCHEMA
        assert {f["rule"] for f in out["findings"]} == set(RULE_NAMES)

    def test_rule_selector_and_exit_codes(self, tmp_path, capsys):
        root = self.make_tree(tmp_path, ONE_PER_RULE)
        assert cli_main(["lint", str(root), "--rule", "debug-artifact"]) == 1
        capsys.readouterr()
        assert cli_main(["lint", str(root), "--rule", "nope"]) == 2
        clean = self.make_tree(tmp_path / "c", {"rl/ok.py": "X = 1\n"})
        capsys.readouterr()
        assert cli_main(["lint", str(clean)]) == 0

    def test_parse_error_exit_2(self, tmp_path, capsys):
        root = self.make_tree(tmp_path, {"rl/broken.py": "def f(:\n"})
        assert cli_main(["lint", str(root)]) == 2

    def test_write_baseline_roundtrip(self, tmp_path, capsys):
        root = self.make_tree(tmp_path, ONE_PER_RULE)
        baseline = tmp_path / "lb.json"
        assert (
            cli_main(
                ["lint", str(root), "--baseline", str(baseline),
                 "--write-baseline"]
            )
            == 0
        )
        capsys.readouterr()
        assert (
            cli_main(["lint", str(root), "--baseline", str(baseline)]) == 0
        )
        out = capsys.readouterr().out
        assert "baselined" in out

    def test_repo_package_lints_clean(self, capsys):
        """THE acceptance gate: the shipped package + checked-in
        baseline produce a clean verdict."""
        rc = cli_main(
            [
                "lint",
                str(REPO / "alphatriangle_tpu"),
                "--baseline",
                str(REPO / "lint_baseline.json"),
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "clean" in out

    def test_cli_lint_never_imports_jax(self):
        """Subprocess import guard: the lint path (CLI + analysis +
        telemetry.flight's family table) must stay JAX-free, exactly
        like `cli mem`/`cli doctor` — it runs in the tpu_watch.sh
        preflight beside a possibly-wedged chip."""
        code = (
            "import builtins, sys\n"
            "real = builtins.__import__\n"
            "def guard(name, *a, **k):\n"
            "    if name == 'jax' or name.startswith('jax.'):\n"
            "        raise AssertionError('cli lint imported ' + name)\n"
            "    return real(name, *a, **k)\n"
            "builtins.__import__ = guard\n"
            "from alphatriangle_tpu.cli import main\n"
            "sys.exit(main(['lint', '--json']))\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            cwd=str(REPO),
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        verdict = json.loads(proc.stdout.strip().splitlines()[-1])
        assert verdict["schema"] == LINT_SCHEMA
        assert verdict["exit_code"] == 0
