"""Subprocess driver for the 8-way dp-sharded megastep dryrun.

Launched by tests/test_megastep_sharded.py in its own process so it can
force 8 virtual CPU devices before JAX initialises (the conftest
process is already pinned to its own device count). Runs two phases:

1. a single-device megastep run to step 4 (checkpoint + buffer spill),
2. a dp=8 sharded megastep run that resumes from that single-device
   checkpoint and continues to step 8.

Phase 2 asserts the ISSUE's acceptance criteria in-process — one mesh
dispatch per iteration, params bit-identical on all 8 shards after the
fused K-step groups, per-shard device/host PER priority reconciliation
— and prints marker lines (RESUME_STEP / GAUGE / MEGA_DP_OK) the
parent test asserts on.
"""

import os
import sys
import tempfile

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault(
    "ALPHATRIANGLE_AOT_CACHE_DIR",
    tempfile.mkdtemp(prefix="mega_dp_aot_"),
)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_enable_async_dispatch", False)


def _configs(run_name: str, dp: int, max_steps: int):
    from alphatriangle_tpu.config import (
        AlphaTriangleMCTSConfig,
        EnvConfig,
        MeshConfig,
        ModelConfig,
        TrainConfig,
        expected_other_features_dim,
    )

    env_cfg = EnvConfig(
        ROWS=3,
        COLS=4,
        PLAYABLE_RANGE_PER_ROW=[(0, 4), (0, 4), (0, 4)],
        NUM_SHAPE_SLOTS=1,
        MAX_SHAPE_TRIANGLES=3,
        LINE_MIN_LENGTH=3,
    )
    model_cfg = ModelConfig(
        GRID_INPUT_CHANNELS=1,
        CONV_FILTERS=[4],
        CONV_KERNEL_SIZES=[3],
        CONV_STRIDES=[1],
        NUM_RESIDUAL_BLOCKS=0,
        RESIDUAL_BLOCK_FILTERS=4,
        USE_TRANSFORMER=False,
        TRANSFORMER_DIM=8,
        TRANSFORMER_HEADS=2,
        TRANSFORMER_LAYERS=0,
        TRANSFORMER_FC_DIM=16,
        FC_DIMS_SHARED=[8],
        POLICY_HEAD_DIMS=[8],
        VALUE_HEAD_DIMS=[8],
        NUM_VALUE_ATOMS=11,
        OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(env_cfg),
        COMPUTE_DTYPE="float32",
        NORM_TYPE="group",
    )
    mcts_cfg = AlphaTriangleMCTSConfig(
        max_simulations=8,
        max_depth=5,
        cpuct=1.0,
        dirichlet_alpha=0.3,
        dirichlet_epsilon=0.25,
        discount=1.0,
        mcts_batch_size=4,
    )
    train_cfg = TrainConfig(
        RUN_NAME=run_name,
        AUTO_RESUME_LATEST=False,
        MAX_TRAINING_STEPS=max_steps,
        SELF_PLAY_BATCH_SIZE=8,
        ROLLOUT_CHUNK_MOVES=2,
        BATCH_SIZE=8,
        BUFFER_CAPACITY=2000,
        MIN_BUFFER_SIZE_TO_TRAIN=16,
        USE_PER=True,
        PER_BETA_ANNEAL_STEPS=8,
        N_STEP_RETURNS=2,
        WORKER_UPDATE_FREQ_STEPS=2,
        CHECKPOINT_SAVE_FREQ_STEPS=2,
        MAX_EPISODE_MOVES=30,
        RANDOM_SEED=5,
        FUSED_MEGASTEP=True,
        DEVICE_REPLAY="on",
        FUSED_LEARNER_STEPS=2,
    )
    return env_cfg, model_cfg, mcts_cfg, train_cfg, MeshConfig(DP_SIZE=dp)


def _build(workdir: str, run_name: str, dp: int, max_steps: int):
    from alphatriangle_tpu.config import PersistenceConfig
    from alphatriangle_tpu.training import setup_training_components

    env_cfg, model_cfg, mcts_cfg, train_cfg, mesh_cfg = _configs(
        run_name, dp, max_steps
    )
    pc = PersistenceConfig(ROOT_DATA_DIR=workdir, RUN_NAME=run_name)
    return setup_training_components(
        train_config=train_cfg,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        mesh_config=mesh_cfg,
        persistence_config=pc,
        use_tensorboard=False,
    )


def main() -> None:
    workdir = sys.argv[1]
    run_name = "mega_dp8"

    import json

    import numpy as np

    from alphatriangle_tpu.training import LoopStatus, TrainingLoop

    assert len(jax.devices()) == 8, jax.devices()

    # --- phase 1: single-device megastep run to step 4 -------------------
    c1 = _build(workdir, run_name, dp=1, max_steps=4)
    assert not getattr(c1.buffer, "is_sharded", False)
    assert c1.megastep is not None and not c1.megastep.sharded
    loop1 = TrainingLoop(c1)
    status = loop1.run()
    assert status == LoopStatus.COMPLETED, status
    assert loop1.global_step == 4
    c1.stats.close()
    c1.checkpoints.close()
    print(f"BASE_STEP={loop1.global_step}", flush=True)

    # --- phase 2: dp=8 sharded run resumes the same checkpoints ---------
    c2 = _build(workdir, run_name, dp=8, max_steps=8)
    assert getattr(c2.buffer, "is_sharded", False), type(c2.buffer)
    assert c2.megastep is not None and c2.megastep.sharded
    assert c2.megastep.dp == 8
    loop2 = TrainingLoop(c2)
    loaded = c2.checkpoints.restore(c2.trainer.state, buffer=c2.buffer)
    assert loaded.train_state is not None, "no checkpoint to resume"
    assert loaded.buffer_loaded, "no buffer spill to resume"
    c2.trainer.set_state(loaded.train_state)
    c2.trainer.sync_to_network()
    loop2.set_initial_state(
        loaded.global_step,
        int(loaded.counters.get("episodes_played", 0)),
        int(loaded.counters.get("total_simulations", 0)),
    )
    print(f"RESUME_STEP={loaded.global_step}", flush=True)
    assert loaded.global_step == 4

    status = loop2.run()
    assert status == LoopStatus.COMPLETED, status
    assert loop2.global_step == 8

    runner = c2.megastep
    # One mesh-level dispatch per megastep iteration; the embedded
    # learner never dispatched standalone programs.
    assert runner.dispatch_count == loop2.megastep_iterations > 0
    assert c2.trainer.dispatch_count == 0
    print("DISPATCH_OK", flush=True)

    # Params bit-identical across all 8 shards after the K-step groups.
    for leaf in jax.tree_util.tree_leaves(c2.trainer.state.params):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        assert len(shards) == 8, len(shards)
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)
    print("PARAMS_OK", flush=True)

    # Per-shard PER reconciliation: the device priority slice of every
    # shard matches its host SumTree mirror.
    buf = c2.buffer
    prios = np.asarray(runner._priorities)
    assert buf.trees is not None
    for k, tree in enumerate(buf.trees):
        sz = int(buf._sizes[k])
        assert sz > 0, f"shard {k} never ingested"
        dev = prios[k * buf.stride : k * buf.stride + sz]
        host = tree.tree[np.arange(sz) + tree._cap2]
        np.testing.assert_allclose(dev, host, rtol=1e-4, atol=1e-6)
    print("PER_OK", flush=True)

    run_dir = c2.persistence_config.get_run_base_dir()
    records = [
        json.loads(line)
        for line in (run_dir / "metrics.jsonl").read_text().splitlines()
    ]
    dpi = [
        r["dispatches_per_iteration"]
        for r in records
        if r.get("kind") == "util"
        and isinstance(r.get("dispatches_per_iteration"), (int, float))
    ]
    assert dpi, "no util records with dispatches_per_iteration"
    print(f"GAUGE={dpi[-1]}", flush=True)
    assert abs(dpi[-1] - 1.0) < 1e-9

    assert c2.checkpoints.latest_step() == 8
    c2.stats.close()
    c2.checkpoints.close()
    print("MEGA_DP_OK", flush=True)


if __name__ == "__main__":
    main()
