"""Multi-host (DCN) scaffolding tests.

The 2-process smoke test launches tests/distributed_driver.py twice
(jax.distributed over localhost, 2 virtual CPU devices per process ->
a 4x1 global mesh) and asserts the real multi-host contract: identical
replicated params and global loss on every process, Orbax checkpoint
written once, meta.json / singleton file writes on process 0 only.
Covers SURVEY.md §2c's DCN row (the reference scales across hosts via
Ray actors; here via jax.distributed + GSPMD over a global mesh).
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

from alphatriangle_tpu.parallel.distributed import (
    DistributedConfig,
    initialize_distributed,
    is_primary,
    process_info,
)

REPO_ROOT = Path(__file__).resolve().parent.parent


class TestConfig:
    def test_explicit_fields_must_come_together(self):
        with pytest.raises(ValueError, match="together"):
            DistributedConfig(ENABLED=True, COORDINATOR_ADDRESS="x:1")
        cfg = DistributedConfig(
            ENABLED=True,
            COORDINATOR_ADDRESS="x:1",
            NUM_PROCESSES=2,
            PROCESS_ID=0,
        )
        assert cfg.NUM_PROCESSES == 2

    def test_disabled_is_noop_single_process(self):
        assert initialize_distributed(None) is False
        assert initialize_distributed(DistributedConfig()) is False
        assert is_primary()
        assert process_info() == (0, 1)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_train_step(tmp_path):
    port = _free_port()
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                str(REPO_ROOT / "tests" / "distributed_driver.py"),
                str(pid),
                f"localhost:{port}",
                str(tmp_path),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=REPO_ROOT,
            env={**os.environ, "PYTHONPATH": str(REPO_ROOT)},
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=280)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed driver timed out")
        outs.append(out)
    for pid, out in enumerate(outs):
        assert procs[pid].returncode == 0, f"proc {pid} failed:\n{out}"
        assert "DIST_OK" in out

    def field(out: str, key: str) -> str:
        return next(
            line.split("=", 1)[1]
            for line in out.splitlines()
            if line.startswith(key + "=")
        )

    # Replicated state + global loss agree across processes.
    assert field(outs[0], "LOSS") == field(outs[1], "LOSS")
    assert field(outs[0], "PARAM_SUM") == field(outs[1], "PARAM_SUM")
    # Ring attention over the cross-process sp axis agrees too.
    assert field(outs[0], "SP_LOSS") == field(outs[1], "SP_LOSS")
    # Tensor parallelism with mdl shards on different hosts agrees
    # across processes. (TP_LOSS is not compared to SP_LOSS: the SP
    # attention kernel disables attention-weight dropout, the dense
    # one doesn't, so the two runs draw different dropout masks.)
    assert field(outs[0], "TP_LOSS") == field(outs[1], "TP_LOSS")
    assert field(outs[0], "PRIMARY") == "1"
    assert field(outs[1], "PRIMARY") == "0"

    # One checkpoint, one meta.json (written by process 0 only).
    ckpt_dir = (
        tmp_path / "AlphaTriangleTPU" / "runs" / "dist_smoke" / "checkpoints"
    )
    assert (ckpt_dir / "step_00000001").is_dir()
    assert (ckpt_dir / "step_00000001.meta.json").is_file()
