"""Test fixtures: a deliberately tiny world on a virtual 8-device CPU mesh.

Mirrors the reference's fixture strategy (`tests/conftest.py:30-125`):
small board, small net, small buffer — plus the JAX twist: tests run on
CPU with `xla_force_host_platform_device_count=8` so multi-device
sharding paths are exercised without TPU hardware.
"""

import os
import tempfile

# AOT executable cache (compile_cache.py): point the process-wide cache
# at a fresh per-run directory BEFORE any package import can build it.
# Within one pytest process programs compile once and reuse in-memory
# executables; what this prevents is DESERIALIZING artifacts a previous
# process left behind — XLA:CPU reloads of the donating learner/rollout
# programs can silently misbehave (see the persistent-cache note
# below), and a stale shared /tmp cache made the suite's pass/fail
# depend on what ran on the machine earlier. test_compile_cache builds
# its own explicit cache dirs and is unaffected.
os.environ["ALPHATRIANGLE_AOT_CACHE_DIR"] = tempfile.mkdtemp(
    prefix="at_test_aot_"
)

# Skip the setup-time cost pre-capture (telemetry/roofline.py): it
# lower+compiles the learner/megastep program purely for
# `cost_analysis()`, seconds of pure overhead in every throwaway
# training run the suite (and its subprocess drivers — children
# inherit this) spins up. The capture path itself is covered by
# tests/test_roofline.py and `make roofline-smoke`.
os.environ["ALPHATRIANGLE_COST_PRECAPTURE"] = "0"

# Must happen before jax import anywhere in the test process. Force CPU
# even when the ambient environment points at a real accelerator (e.g.
# JAX_PLATFORMS=axon): tests exercise sharding on virtual CPU devices and
# must not contend for the TPU with a training/bench process.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Accelerator site hooks (e.g. the axon TPU plugin's sitecustomize) can
# force jax_platforms at interpreter startup, overriding the env var;
# re-assert CPU at the config layer before any backend initializes.
jax.config.update("jax_platforms", "cpu")

# Do NOT enable the XLA persistent compilation cache here. It used to
# be on (jax_compilation_cache_dir=/tmp/jax_test_cache) to speed up
# repeat suite runs, but XLA:CPU persistent-cache RELOADS are broken in
# this image: a reloaded learner-step executable (donated train state)
# runs without error and returns its inputs UNCHANGED — params stop
# updating, silently (reproduced deterministically: cold run passes,
# warm run fails test_params_change_and_metrics; and serializing the
# reloaded executable fails with "Symbols not found"). This is the same
# hazard utils/helpers.enable_persistent_compilation_cache documents
# and guards by skipping the CPU backend — the test override bypassed
# that guard. The repo's own AOT executable cache (compile_cache.py)
# is unaffected (deserialize_executable round-trips correctly on CPU,
# counter-proven in test_compile_cache) and keeps covering the
# expensive programs across processes.

# XLA:CPU's async dispatch can deadlock when one thread blocks on an
# in-flight program while another enqueues programs sharing its buffers
# (the device-replay producer/consumer topology reproduces it at
# flagship program sizes; see rl/device_buffer.py). Latched at CPU
# client creation, so it must be set here, before any backend touch.
jax.config.update("jax_cpu_enable_async_dispatch", False)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from alphatriangle_tpu.config import (  # noqa: E402
    AlphaTriangleMCTSConfig,
    EnvConfig,
    ModelConfig,
    TrainConfig,
)

rng = np.random.default_rng(0)


@pytest.fixture(scope="session")
def tiny_env_config() -> EnvConfig:
    """3x4 board, 1 slot, tiny shapes => action_dim 12."""
    return EnvConfig(
        ROWS=3,
        COLS=4,
        PLAYABLE_RANGE_PER_ROW=[(0, 4), (0, 4), (0, 4)],
        NUM_SHAPE_SLOTS=1,
        MAX_SHAPE_TRIANGLES=3,
        LINE_MIN_LENGTH=3,
    )


@pytest.fixture(scope="session")
def tiny_model_config(tiny_env_config: EnvConfig) -> ModelConfig:
    from alphatriangle_tpu.config import expected_other_features_dim

    return ModelConfig(
        GRID_INPUT_CHANNELS=1,
        CONV_FILTERS=[4],
        CONV_KERNEL_SIZES=[3],
        CONV_STRIDES=[1],
        NUM_RESIDUAL_BLOCKS=0,
        RESIDUAL_BLOCK_FILTERS=4,
        USE_TRANSFORMER=False,
        TRANSFORMER_DIM=8,
        TRANSFORMER_HEADS=2,
        TRANSFORMER_LAYERS=0,
        TRANSFORMER_FC_DIM=16,
        FC_DIMS_SHARED=[8],
        POLICY_HEAD_DIMS=[8],
        VALUE_HEAD_DIMS=[8],
        NUM_VALUE_ATOMS=11,
        OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(tiny_env_config),
        COMPUTE_DTYPE="float32",
        NORM_TYPE="group",
    )


@pytest.fixture(scope="session")
def tiny_train_config() -> TrainConfig:
    return TrainConfig(
        BATCH_SIZE=4,
        BUFFER_CAPACITY=100,
        MIN_BUFFER_SIZE_TO_TRAIN=10,
        USE_PER=False,
        AUTO_RESUME_LATEST=False,
        RANDOM_SEED=42,
        SELF_PLAY_BATCH_SIZE=4,
        ROLLOUT_CHUNK_MOVES=4,
        NUM_SELF_PLAY_WORKERS=1,
        MAX_TRAINING_STEPS=200,
        N_STEP_RETURNS=3,
        GAMMA=0.99,
        MAX_EPISODE_MOVES=50,
        RUN_NAME="pytest_run",
    )


@pytest.fixture(scope="session")
def tiny_per_train_config() -> TrainConfig:
    return TrainConfig(
        BATCH_SIZE=4,
        BUFFER_CAPACITY=64,
        MIN_BUFFER_SIZE_TO_TRAIN=8,
        USE_PER=True,
        PER_BETA_ANNEAL_STEPS=100,
        AUTO_RESUME_LATEST=False,
        MAX_TRAINING_STEPS=100,
        RUN_NAME="pytest_per_run",
    )


@pytest.fixture(scope="session")
def tiny_mcts_config() -> AlphaTriangleMCTSConfig:
    return AlphaTriangleMCTSConfig(
        max_simulations=8,
        max_depth=5,
        cpuct=1.0,
        dirichlet_alpha=0.3,
        dirichlet_epsilon=0.25,
        discount=1.0,
        mcts_batch_size=4,
    )


@pytest.fixture(scope="session")
def random_state_type(tiny_model_config, tiny_env_config):
    """A random StateType dict with the right shapes."""
    return {
        "grid": rng.random(
            (
                tiny_model_config.GRID_INPUT_CHANNELS,
                tiny_env_config.ROWS,
                tiny_env_config.COLS,
            ),
            dtype=np.float32,
        ),
        "other_features": rng.random(
            (tiny_model_config.OTHER_NN_INPUT_FEATURES_DIM,), dtype=np.float32
        ),
    }
