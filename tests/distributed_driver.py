"""Subprocess driver for the 2-process CPU multi-host smoke test.

Launched twice by tests/test_distributed.py (process_id 0 and 1); each
process owns 2 virtual CPU devices, so the global (dp, mdl) mesh is
4x1 across processes. Exercises the real multi-host path end to end:
`initialize_distributed` -> global mesh -> `Trainer.train_step` on a
process-local batch shard (assembled into global arrays by
`shard_batch`) -> process-0-gated checkpoint save.

Prints PARAM_SUM / LOSS lines the parent asserts on: both processes
must see identical replicated params and the same global loss.
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    process_id = int(sys.argv[1])
    coordinator = sys.argv[2]
    workdir = sys.argv[3]

    from alphatriangle_tpu.parallel.distributed import (
        DistributedConfig,
        initialize_distributed,
        is_primary,
        process_info,
    )

    multi = initialize_distributed(
        DistributedConfig(
            ENABLED=True,
            COORDINATOR_ADDRESS=coordinator,
            NUM_PROCESSES=2,
            PROCESS_ID=process_id,
        )
    )
    assert multi, "initialize_distributed reported single-process"
    idx, count = process_info()
    assert (idx, count) == (process_id, 2)
    assert len(jax.devices()) == 4, jax.devices()

    import numpy as np

    from alphatriangle_tpu.config import (
        EnvConfig,
        MeshConfig,
        ModelConfig,
        PersistenceConfig,
        TrainConfig,
        expected_other_features_dim,
    )
    from alphatriangle_tpu.nn.network import NeuralNetwork
    from alphatriangle_tpu.rl import Trainer
    from alphatriangle_tpu.stats.persistence import CheckpointManager

    env_cfg = EnvConfig(
        ROWS=3, COLS=4, PLAYABLE_RANGE_PER_ROW=[(0, 4), (0, 4), (0, 4)],
        NUM_SHAPE_SLOTS=1,
    )
    model_cfg = ModelConfig(
        GRID_INPUT_CHANNELS=1,
        CONV_FILTERS=[4],
        CONV_KERNEL_SIZES=[3],
        CONV_STRIDES=[1],
        NUM_RESIDUAL_BLOCKS=0,
        USE_TRANSFORMER=False,
        FC_DIMS_SHARED=[16],
        POLICY_HEAD_DIMS=[16],
        VALUE_HEAD_DIMS=[16],
        NUM_VALUE_ATOMS=11,
        OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(env_cfg),
    )
    train_cfg = TrainConfig(
        BATCH_SIZE=8,  # global; 4 rows per process
        MAX_TRAINING_STEPS=10,
        USE_PER=False,
        RUN_NAME="dist_smoke",
    )
    mesh = MeshConfig().build_mesh()  # 4 global devices -> (dp=4, mdl=1)
    assert mesh.devices.size == 4

    net = NeuralNetwork(model_cfg, env_cfg, seed=0)
    trainer = Trainer(net, train_cfg, mesh=mesh)

    # Deterministic process-local half of the global batch (4 rows each).
    rng = np.random.default_rng(100 + process_id)
    b = train_cfg.BATCH_SIZE // 2
    policy = rng.random((b, env_cfg.action_dim)).astype(np.float32)
    policy /= policy.sum(axis=1, keepdims=True)
    batch = {
        "grid": rng.integers(
            -1, 2, size=(b, 1, env_cfg.ROWS, env_cfg.COLS)
        ).astype(np.float32),
        "other_features": rng.random(
            (b, model_cfg.OTHER_NN_INPUT_FEATURES_DIM)
        ).astype(np.float32),
        "policy_target": policy,
        "value_target": rng.uniform(-5, 5, b).astype(np.float32),
        "weights": np.ones(b, np.float32),
    }

    losses = [trainer.train_step(batch)[0]["total_loss"] for _ in range(2)]
    param_sum = sum(
        float(np.asarray(leaf).sum())
        for leaf in jax.tree_util.tree_leaves(trainer.state.params)
    )
    print(f"LOSS={losses[0]:.6f},{losses[1]:.6f}", flush=True)
    print(f"PARAM_SUM={param_sum:.6f}", flush=True)

    # Process-0 gating: every process calls save (Orbax-style collective
    # discipline); only process 0 may write meta.json / prune.
    mgr = CheckpointManager(
        PersistenceConfig(ROOT_DATA_DIR=workdir, RUN_NAME="dist_smoke")
    )
    mgr.save(1, trainer.state)
    mgr.wait_until_finished()
    print(f"PRIMARY={int(is_primary())}", flush=True)
    print("DIST_OK", flush=True)


if __name__ == "__main__":
    main()
