"""Subprocess driver for the 2-process CPU multi-host smoke test.

Launched twice by tests/test_distributed.py (process_id 0 and 1); each
process owns 2 virtual CPU devices, so the global (dp, mdl) mesh is
4x1 across processes. Exercises the real multi-host path end to end:
`initialize_distributed` -> global mesh -> `Trainer.train_step` on a
process-local batch shard (assembled into global arrays by
`shard_batch`) -> process-0-gated checkpoint save.

Prints PARAM_SUM / LOSS lines the parent asserts on: both processes
must see identical replicated params and the same global loss.
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    process_id = int(sys.argv[1])
    coordinator = sys.argv[2]
    workdir = sys.argv[3]

    from alphatriangle_tpu.parallel.distributed import (
        DistributedConfig,
        initialize_distributed,
        is_primary,
        process_info,
    )

    multi = initialize_distributed(
        DistributedConfig(
            ENABLED=True,
            COORDINATOR_ADDRESS=coordinator,
            NUM_PROCESSES=2,
            PROCESS_ID=process_id,
        )
    )
    assert multi, "initialize_distributed reported single-process"
    idx, count = process_info()
    assert (idx, count) == (process_id, 2)
    assert len(jax.devices()) == 4, jax.devices()

    import numpy as np

    from alphatriangle_tpu.config import (
        EnvConfig,
        MeshConfig,
        ModelConfig,
        PersistenceConfig,
        TrainConfig,
        expected_other_features_dim,
    )
    from alphatriangle_tpu.nn.network import NeuralNetwork
    from alphatriangle_tpu.rl import Trainer
    from alphatriangle_tpu.stats.persistence import CheckpointManager

    env_cfg = EnvConfig(
        ROWS=3, COLS=4, PLAYABLE_RANGE_PER_ROW=[(0, 4), (0, 4), (0, 4)],
        NUM_SHAPE_SLOTS=1,
    )
    model_cfg = ModelConfig(
        GRID_INPUT_CHANNELS=1,
        CONV_FILTERS=[4],
        CONV_KERNEL_SIZES=[3],
        CONV_STRIDES=[1],
        NUM_RESIDUAL_BLOCKS=0,
        USE_TRANSFORMER=False,
        FC_DIMS_SHARED=[16],
        POLICY_HEAD_DIMS=[16],
        VALUE_HEAD_DIMS=[16],
        NUM_VALUE_ATOMS=11,
        OTHER_NN_INPUT_FEATURES_DIM=expected_other_features_dim(env_cfg),
    )
    train_cfg = TrainConfig(
        BATCH_SIZE=8,  # global; 4 rows per process
        MAX_TRAINING_STEPS=10,
        USE_PER=False,
        RUN_NAME="dist_smoke",
    )
    mesh = MeshConfig().build_mesh()  # 4 global devices -> (dp=4, mdl=1)
    assert mesh.devices.size == 4

    net = NeuralNetwork(model_cfg, env_cfg, seed=0)
    trainer = Trainer(net, train_cfg, mesh=mesh)

    # Deterministic process-local half of the global batch (4 rows each).
    rng = np.random.default_rng(100 + process_id)
    b = train_cfg.BATCH_SIZE // 2
    policy = rng.random((b, env_cfg.action_dim)).astype(np.float32)
    policy /= policy.sum(axis=1, keepdims=True)
    batch = {
        "grid": rng.integers(
            -1, 2, size=(b, 1, env_cfg.ROWS, env_cfg.COLS)
        ).astype(np.float32),
        "other_features": rng.random(
            (b, model_cfg.OTHER_NN_INPUT_FEATURES_DIM)
        ).astype(np.float32),
        "policy_target": policy,
        "value_target": rng.uniform(-5, 5, b).astype(np.float32),
        "weights": np.ones(b, np.float32),
    }

    losses = [trainer.train_step(batch)[0]["total_loss"] for _ in range(2)]
    param_sum = sum(
        float(np.asarray(leaf).sum())
        for leaf in jax.tree_util.tree_leaves(trainer.state.params)
    )
    print(f"LOSS={losses[0]:.6f},{losses[1]:.6f}", flush=True)
    print(f"PARAM_SUM={param_sum:.6f}", flush=True)

    # Process-0 gating: every process calls save (Orbax-style collective
    # discipline); only process 0 may write meta.json / prune.
    mgr = CheckpointManager(
        PersistenceConfig(ROOT_DATA_DIR=workdir, RUN_NAME="dist_smoke")
    )
    mgr.save(1, trainer.state)
    mgr.wait_until_finished()
    print(f"PRIMARY={int(is_primary())}", flush=True)

    # Phase 2: sequence parallelism under jax.distributed — ring
    # attention over a (dp=2, mdl=1, sp=2) global mesh whose sp axis
    # GENUINELY crosses the process boundary: jax.devices() orders
    # [p0d0, p0d1, p1d0, p1d1], and a plain reshape would pair sp
    # within each process (leaving only the grad-reduce cross-host).
    # Interleave so each sp pair is (p0 device, p1 device) and the
    # ring ppermute itself rides the inter-process link.
    from alphatriangle_tpu.parallel import make_sp_attention

    devs = jax.devices()
    assert [d.process_index for d in devs] == [0, 0, 1, 1], devs
    sp_mesh = MeshConfig(DP_SIZE=2, SP_SIZE=2).build_mesh(
        devices=[devs[0], devs[2], devs[1], devs[3]]
    )
    sp_axis_procs = {
        frozenset(d.process_index for d in row)
        for row in sp_mesh.devices.reshape(2, 2)
    }
    assert sp_axis_procs == {frozenset({0, 1})}, sp_mesh.devices
    sp_model_cfg = model_cfg.model_copy(
        update={
            "USE_TRANSFORMER": True,
            "TRANSFORMER_DIM": 8,
            "TRANSFORMER_HEADS": 2,
            "TRANSFORMER_LAYERS": 1,
            "TRANSFORMER_FC_DIM": 16,
        }
    )
    sp_net = NeuralNetwork(
        sp_model_cfg,
        env_cfg,
        seed=0,
        attention_fn=make_sp_attention(sp_mesh, kind="ring"),
    )
    sp_trainer = Trainer(sp_net, train_cfg, mesh=sp_mesh)
    # With sp crossing processes, every dp batch shard is replicated
    # onto devices of BOTH processes — so both must supply identical
    # local data (make_array_from_process_local_data fills replicas
    # from each process's own buffer). Shared seed, not 100+pid.
    rng2 = np.random.default_rng(4242)
    policy2 = rng2.random((b, env_cfg.action_dim)).astype(np.float32)
    policy2 /= policy2.sum(axis=1, keepdims=True)
    sp_batch = {
        "grid": rng2.integers(
            -1, 2, size=(b, 1, env_cfg.ROWS, env_cfg.COLS)
        ).astype(np.float32),
        "other_features": rng2.random(
            (b, model_cfg.OTHER_NN_INPUT_FEATURES_DIM)
        ).astype(np.float32),
        "policy_target": policy2,
        "value_target": rng2.uniform(-5, 5, b).astype(np.float32),
        "weights": np.ones(b, np.float32),
    }
    sp_metrics, _ = sp_trainer.train_step(sp_batch)
    assert np.isfinite(sp_metrics["total_loss"]), sp_metrics
    print(f"SP_LOSS={sp_metrics['total_loss']:.6f}", flush=True)

    # Phase 3: tensor parallelism under jax.distributed — a
    # (dp=2, mdl=2) mesh whose mdl pairs GENUINELY cross the process
    # boundary (same interleave trick as phase 2), so the Megatron
    # param shards live on different hosts and `sync_to_network`'s
    # on-device all-gather must ride the inter-process link.
    tp_mesh = MeshConfig(DP_SIZE=2, MDL_SIZE=2).build_mesh(
        devices=[devs[0], devs[2], devs[1], devs[3]]
    )
    mdl_axis_procs = {
        frozenset(d.process_index for d in row)
        for row in tp_mesh.devices.reshape(2, 2)
    }
    assert mdl_axis_procs == {frozenset({0, 1})}, tp_mesh.devices
    tp_net = NeuralNetwork(sp_model_cfg, env_cfg, seed=0)
    tp_trainer = Trainer(tp_net, train_cfg, mesh=tp_mesh)
    assert tp_trainer.tp_size == 2
    from jax.sharding import PartitionSpec as P

    qkv = [
        leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            tp_trainer.state.params
        )[0]
        if "query" in "/".join(str(k.key) for k in path)
        and str(path[-1].key) == "kernel"
    ]
    assert qkv and qkv[0].sharding.spec == P(None, "mdl", None)
    tp_metrics, _ = tp_trainer.train_step(sp_batch)
    assert np.isfinite(tp_metrics["total_loss"]), tp_metrics
    print(f"TP_LOSS={tp_metrics['total_loss']:.6f}", flush=True)
    # The multi-host gather: every process ends up with whole,
    # locally-addressable tensors for the eval wrapper.
    tp_trainer.sync_to_network()
    for leaf in jax.tree_util.tree_leaves(tp_net.variables["params"]):
        assert len(leaf.sharding.device_set) == 1
        assert np.all(np.isfinite(np.asarray(leaf)))
    # The synced weights must be COPIES: the next train step donates
    # the live state buffers, and an aliasing sync would leave the
    # eval wrapper holding deleted arrays.
    tp_metrics2, _ = tp_trainer.train_step(sp_batch)
    assert np.isfinite(tp_metrics2["total_loss"])
    for leaf in jax.tree_util.tree_leaves(tp_net.variables["params"]):
        assert np.all(np.isfinite(np.asarray(leaf)))
    print("DIST_OK", flush=True)


if __name__ == "__main__":
    main()
