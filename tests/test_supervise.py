"""Self-healing supervisor (alphatriangle_tpu/supervise/, docs/ROBUSTNESS.md).

The policy tests drive the whole verdict->action matrix with a fake
clock and zero subprocesses; the Supervisor tests script child deaths
through an injectable popen/sleep pair and assert the death->verdict->
restart chain lands in supervisor.jsonl exactly as `make chaos-smoke`
sees it from real children. JAX is never needed on these paths (the
jax-free contract itself is pinned by benchmarks/chaos_smoke.py's
import guard, and re-checked here via sys.modules).
"""

import json
import signal
import sys
import time

import pytest

from alphatriangle_tpu.supervise import (
    OVERRIDES_ENV,
    RecoveryPolicy,
    Supervisor,
    latest_committed_step,
)
from alphatriangle_tpu.supervise.faults import parse_spec
from alphatriangle_tpu.supervise.policy import (
    PREEMPT_EXIT_CODE,
    SUPERVISOR_GIVEUP_EXIT_CODE,
    WEDGE_EXIT_CODE,
)


def make_policy(**kw):
    defaults = dict(
        max_restarts=8,
        circuit_breaker_deaths=3,
        backoff_base_s=5.0,
        backoff_max_s=300.0,
        quarantine_after=2,
        clock=lambda: 1000.0,
    )
    defaults.update(kw)
    return RecoveryPolicy(**defaults)


class TestRecoveryPolicy:
    def test_backoff_doubles_without_progress_and_caps(self):
        policy = make_policy(backoff_base_s=5.0, backoff_max_s=18.0,
                             circuit_breaker_deaths=10)
        delays = [
            policy.decide(verdict="clean", exit_code=1).delay_s
            for _ in range(4)
        ]
        assert delays == [5.0, 10.0, 18.0, 18.0]

    def test_checkpoint_progress_resets_the_streak(self):
        policy = make_policy()
        a1 = policy.decide(verdict="clean", exit_code=1, progress_step=2)
        a2 = policy.decide(verdict="clean", exit_code=1, progress_step=4)
        a3 = policy.decide(verdict="clean", exit_code=1, progress_step=6)
        assert [a.delay_s for a in (a1, a2, a3)] == [5.0, 5.0, 5.0]
        assert all(a.kind == "restart" for a in (a1, a2, a3))

    def test_preemption_resets_the_streak(self):
        policy = make_policy()
        policy.decide(verdict="clean", exit_code=1)
        policy.decide(verdict="clean", exit_code=1)
        a = policy.decide(verdict="preempted", exit_code=PREEMPT_EXIT_CODE)
        assert a.kind == "restart"
        assert a.delay_s == 5.0  # streak back to 1

    def test_circuit_breaker_on_no_progress(self):
        policy = make_policy(circuit_breaker_deaths=2)
        assert policy.decide(verdict="clean", exit_code=1).kind == "restart"
        assert policy.decide(verdict="clean", exit_code=1).kind == "restart"
        a = policy.decide(verdict="clean", exit_code=1)
        assert a.kind == "give-up"
        assert "circuit breaker" in a.reason

    def test_restart_budget_exhaustion(self):
        policy = make_policy(max_restarts=2, circuit_breaker_deaths=99)
        step = iter(range(2, 100, 2))
        for _ in range(2):
            a = policy.decide(
                verdict="clean", exit_code=1, progress_step=next(step)
            )
            assert a.kind == "restart"
        a = policy.decide(verdict="clean", exit_code=1, progress_step=next(step))
        assert a.kind == "give-up"
        assert "budget" in a.reason

    def test_second_wedge_on_family_quarantines(self):
        policy = make_policy(quarantine_after=2, circuit_breaker_deaths=99)
        a1 = policy.decide(
            verdict="dispatch-hung",
            exit_code=WEDGE_EXIT_CODE,
            family="megastep",
            progress_step=2,
        )
        # The first wedge arms progress beacons so a repeat names its
        # phase; quarantine waits for the second.
        assert a1.overrides == {"TELEMETRY__BEACONS": True}
        assert "beacons" in a1.reason
        a2 = policy.decide(
            verdict="dispatch-hung",
            exit_code=WEDGE_EXIT_CODE,
            family="megastep",
            progress_step=4,
        )
        assert a2.overrides == {
            "FUSED_MEGASTEP": False,
            "TELEMETRY__BEACONS": True,
        }
        assert "quarantined" in a2.reason
        # A later unrelated death keeps the quarantine (overrides
        # accumulate; a sick megastep stays off).
        a3 = policy.decide(verdict="clean", exit_code=1, progress_step=6)
        assert a3.overrides == {
            "FUSED_MEGASTEP": False,
            "TELEMETRY__BEACONS": True,
        }

    def test_wedge_by_exit_code_alone_counts(self):
        # Evidence can be thin (e.g. verdict unreadable): the watchdog's
        # 113 still counts toward quarantine.
        policy = make_policy(quarantine_after=1, circuit_breaker_deaths=99)
        a = policy.decide(
            verdict="clean", exit_code=WEDGE_EXIT_CODE, family="rollout",
            progress_step=2,
        )
        assert a.overrides == {
            "ASYNC_ROLLOUTS": False,
            "TELEMETRY__BEACONS": True,
        }

    def test_oom_ladder_halves_then_forces_k1(self):
        policy = make_policy(circuit_breaker_deaths=99)
        a1 = policy.decide(verdict="oom", exit_code=1, progress_step=2)
        assert a1.overrides == {"SELF_PLAY_BATCH_SIZE__scale": 0.5}
        a2 = policy.decide(verdict="oom", exit_code=1, progress_step=4)
        assert a2.overrides == {
            "SELF_PLAY_BATCH_SIZE__scale": 0.25,
            "FUSED_LEARNER_STEPS": 1,
        }


class TestParseSpec:
    def test_good_spec(self):
        assert parse_spec("hang-dispatch@after=6,sigterm@step=3") == {
            "hang-dispatch": 6,
            "sigterm": 3,
        }

    def test_malformed_entries_skipped_not_raised(self):
        assert parse_spec("nonsense, sigkill@step=x, crash@step=7,") == {
            "crash": 7
        }
        assert parse_spec("") == {}


class TestLatestCommittedStep:
    def test_markers_win(self, tmp_path):
        ckpts = tmp_path / "checkpoints"
        for step in (2, 4, 6):
            (ckpts / f"step_{step:08d}").mkdir(parents=True)
            (ckpts / f"step_{step:08d}.meta.json").write_text(
                json.dumps({"global_step": step})
            )
        # Only 2 and 4 committed: 6 is a torn save.
        for step in (2, 4):
            (ckpts / f"step_{step:08d}.commit").write_text(
                json.dumps({"global_step": step})
            )
        assert latest_committed_step(tmp_path) == 4

    def test_legacy_run_without_markers_falls_back_to_meta(self, tmp_path):
        ckpts = tmp_path / "checkpoints"
        (ckpts / "step_00000003").mkdir(parents=True)
        (ckpts / "step_00000003.meta.json").write_text("{\"global_step\": 3}")
        (ckpts / "step_00000005").mkdir()
        (ckpts / "step_00000005.meta.json").write_text("{torn")
        assert latest_committed_step(tmp_path) == 3

    def test_empty(self, tmp_path):
        assert latest_committed_step(tmp_path) is None


class FakeChild:
    def __init__(self, rc, on_wait=None):
        self.rc = rc
        self._on_wait = on_wait

    def wait(self):
        if self._on_wait is not None:
            self._on_wait()
        return self.rc

    def poll(self):
        return self.rc

    def send_signal(self, signum):
        pass


def scripted_popen(script):
    """`script` is a list of (rc, on_wait) per spawn; returns (popen,
    calls) where calls records each spawn's argv + env."""
    calls = []

    def popen(argv, env=None):
        rc, on_wait = script[len(calls)]
        calls.append({"argv": list(argv), "env": dict(env or {})})
        return FakeChild(rc, on_wait)

    return popen, calls


def events_of(run_dir):
    path = run_dir / "supervisor.jsonl"
    out = []
    for line in path.read_text().splitlines():
        rec = json.loads(line)
        if rec.get("kind") == "supervisor":
            out.append(rec)
    return out


def write_wedge_evidence(run_dir, family="megastep", program="megastep/t4"):
    """The artifacts a real watchdog 113 leaves: a wedge report plus a
    ring where the program sealed once before hanging (so classify_run
    says dispatch-hung, not compile-hung)."""
    now = time.time()
    (run_dir / "flight.jsonl").write_text(
        json.dumps(
            {"kind": "flight", "phase": "intent", "seq": 1,
             "program": program, "family": family, "time": now}
        )
        + "\n"
        + json.dumps(
            {"kind": "flight", "phase": "seal", "seq": 1, "ok": True,
             "program": program, "family": family, "wall_s": 1.0,
             "time": now}
        )
        + "\n"
        + json.dumps(
            {"kind": "flight", "phase": "intent", "seq": 2,
             "program": program, "family": family, "time": now}
        )
        + "\n"
    )
    (run_dir / "wedge_report.json").write_text(
        json.dumps(
            {"kind": "wedge", "time": now, "program": program,
             "family": family, "seq": 2, "elapsed_s": 99.0,
             "deadline_s": 5.0}
        )
    )


class TestSupervisor:
    def test_wedge_death_restart_chain(self, tmp_path):
        run_dir = tmp_path / "run"
        run_dir.mkdir()
        sleeps = []
        popen, calls = scripted_popen(
            [
                (113, lambda: write_wedge_evidence(run_dir)),
                (0, None),
            ]
        )
        policy = make_policy(backoff_base_s=7.0, quarantine_after=1,
                             clock=time.monotonic)
        sup = Supervisor(
            ["train-child"],
            run_dir,
            policy,
            popen=popen,
            sleep=sleeps.append,
        )
        assert sup.run() == 0

        assert len(calls) == 2
        # The quarantine override (and the wedge's beacon directive)
        # reaches the second child via env.
        overrides = json.loads(calls[1]["env"][OVERRIDES_ENV])
        assert overrides == {
            "FUSED_MEGASTEP": False,
            "TELEMETRY__BEACONS": True,
        }
        assert OVERRIDES_ENV not in calls[0]["env"]
        assert sleeps == [7.0]

        chain = [(e["event"], e.get("verdict")) for e in events_of(run_dir)]
        assert chain == [
            ("spawn", None),
            ("death", "dispatch-hung"),
            ("spawn", None),
            ("complete", None),
        ]
        death = [e for e in events_of(run_dir) if e["event"] == "death"][0]
        assert death["rc"] == 113
        assert death["program"] == "megastep/t4"
        assert death["action"] == "restart"
        assert death["delay_s"] == 7.0
        # The dead attempt's report is archived, not left to pollute the
        # next death's diagnosis.
        assert not (run_dir / "wedge_report.json").exists()
        assert (run_dir / "wedge_report.json.attempt1").exists()

    def test_progress_step_read_from_commit_markers(self, tmp_path):
        run_dir = tmp_path / "run"
        ckpts = run_dir / "checkpoints"
        ckpts.mkdir(parents=True)
        (ckpts / "step_00000004").mkdir()
        (ckpts / "step_00000004.commit").write_text("{\"global_step\": 4}")
        popen, _ = scripted_popen([(1, None), (0, None)])
        sup = Supervisor(
            ["c"], run_dir, make_policy(clock=time.monotonic),
            popen=popen, sleep=lambda s: None,
        )
        assert sup.run() == 0
        death = [e for e in events_of(run_dir) if e["event"] == "death"][0]
        assert death["progress_step"] == 4
        # Empty flight ring + nonzero exit -> never-started.
        assert death["verdict"] == "never-started"

    def test_give_up_returns_115(self, tmp_path):
        run_dir = tmp_path / "run"
        run_dir.mkdir()
        popen, calls = scripted_popen([(1, None), (1, None)])
        policy = make_policy(circuit_breaker_deaths=1, clock=time.monotonic)
        sup = Supervisor(
            ["c"], run_dir, policy, popen=popen, sleep=lambda s: None
        )
        assert sup.run() == SUPERVISOR_GIVEUP_EXIT_CODE
        assert len(calls) == 2
        events = [e["event"] for e in events_of(run_dir)]
        assert events[-1] == "give-up"

    def test_forwarded_signal_ends_the_loop(self, tmp_path):
        run_dir = tmp_path / "run"
        run_dir.mkdir()
        sup_holder = {}

        def on_wait():
            sup_holder["sup"]._forward_signal(signal.SIGTERM, None)

        popen, calls = scripted_popen([(PREEMPT_EXIT_CODE, on_wait)])
        sup = Supervisor(
            ["c"], run_dir, make_policy(clock=time.monotonic),
            popen=popen, sleep=lambda s: None,
        )
        sup_holder["sup"] = sup
        assert sup.run() == PREEMPT_EXIT_CODE
        assert len(calls) == 1  # no restart after a forwarded SIGTERM
        events = [e["event"] for e in events_of(run_dir)]
        assert "forward-signal" in events
        assert events[-1] == "terminated"

    def test_supervise_module_is_jax_free(self):
        """The package import graph must not pull jax (the chaos smoke
        pins this in a blocked subprocess; here we pin the already-
        imported module set for fast feedback)."""
        mods = [
            m
            for m, mod in sys.modules.items()
            if m.startswith("alphatriangle_tpu.supervise")
            and mod is not None
        ]
        assert mods, "supervise modules should be imported by this test"
        for name in mods:
            mod = sys.modules[name]
            assert not getattr(mod, "jax", None), name


@pytest.mark.parametrize(
    "codes",
    [
        {"WEDGE_EXIT_CODE": 113, "PREEMPT_EXIT_CODE": 114,
         "SUPERVISOR_GIVEUP_EXIT_CODE": 115},
    ],
)
def test_exit_code_registry(codes):
    """The exit codes tpu_watch.sh branches on are a public contract."""
    assert WEDGE_EXIT_CODE == codes["WEDGE_EXIT_CODE"]
    assert PREEMPT_EXIT_CODE == codes["PREEMPT_EXIT_CODE"]
    assert SUPERVISOR_GIVEUP_EXIT_CODE == codes["SUPERVISOR_GIVEUP_EXIT_CODE"]
