"""dp-sharded device replay ring (rl/sharded_device_buffer.py).

The multi-chip zero-copy data path: dp-sharded rollout lanes scatter
into per-device ring shards (shard_map ingest), the learner gathers its
dp-sharded batch rows device-locally. No reference counterpart (its
buffer is one host object fed by actor RPC); this composes the two
device-resident halves this repo already has.
"""

import jax
import numpy as np
import pytest

from alphatriangle_tpu.config import MeshConfig, TrainConfig
from alphatriangle_tpu.env.engine import TriangleEnv
from alphatriangle_tpu.features.core import get_feature_extractor
from alphatriangle_tpu.nn.network import NeuralNetwork
from alphatriangle_tpu.rl import ExperienceBuffer, SelfPlayEngine
from alphatriangle_tpu.rl.sharded_device_buffer import (
    ShardedDeviceReplayBuffer,
)
from alphatriangle_tpu.rl.trainer import Trainer

DP = 8


@pytest.fixture(scope="module")
def mesh():
    return MeshConfig(DP_SIZE=DP).build_mesh()


@pytest.fixture(scope="module")
def world(tiny_env_config, tiny_model_config, tiny_mcts_config):
    env = TriangleEnv(tiny_env_config)
    fe = get_feature_extractor(env, tiny_model_config)
    net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
    return env, fe, net, tiny_mcts_config


def _cfg(**kw):
    base = dict(
        BATCH_SIZE=16,
        BUFFER_CAPACITY=64 * DP,
        MIN_BUFFER_SIZE_TO_TRAIN=16,
        USE_PER=True,
        PER_BETA_ANNEAL_STEPS=10,
        N_STEP_RETURNS=2,
        GAMMA=0.9,
        MAX_EPISODE_MOVES=50,
        SELF_PLAY_BATCH_SIZE=DP,
        MAX_TRAINING_STEPS=100,
        RUN_NAME="sharded_ring_test",
    )
    base.update(kw)
    return TrainConfig(**base)


def _buffer(world, mesh, tc=None):
    env, fe, _, _ = world
    tc = tc or _cfg()
    return ShardedDeviceReplayBuffer(
        tc,
        grid_shape=(1, env.rows, env.cols),
        other_dim=fe.other_dim,
        action_dim=env.action_dim,
        mesh=mesh,
        dp_axis="dp",
    ), tc


def _rows(n, world, seed=0):
    env, fe, _, _ = world
    rng = np.random.default_rng(seed)
    policy = rng.random((n, env.action_dim)).astype(np.float32)
    policy /= policy.sum(axis=1, keepdims=True)
    return {
        "grid": rng.integers(-1, 2, size=(n, 1, env.rows, env.cols)).astype(
            np.float32
        ),
        "other_features": rng.random((n, fe.other_dim)).astype(np.float32),
        "policy_target": policy,
        "value_target": rng.uniform(-3, 3, n).astype(np.float32),
    }


class TestShardedIngest:
    def test_storage_spans_every_device(self, world, mesh):
        buf, _ = _buffer(world, mesh)
        shards = buf.storage["value_target"].addressable_shards
        assert len({s.device for s in shards}) == DP

    def test_add_dense_stripes_and_counts(self, world, mesh):
        buf, _ = _buffer(world, mesh)
        rows = _rows(4 * DP, world)
        slots = buf.add_dense(**rows)
        assert len(buf) == 4 * DP
        assert len(slots) == 4 * DP
        # Every shard got exactly 4 rows.
        assert all(int(s) == 4 for s in buf._sizes)
        # Encoded slots decode into in-range local positions.
        assert ((slots % buf.stride) < buf.cap_local).all()

    def test_ragged_add_pads_with_masked_rows(self, world, mesh):
        buf, _ = _buffer(world, mesh)
        rows = _rows(DP + 3, world)
        slots = buf.add_dense(**rows)
        assert len(slots) == DP + 3
        assert len(buf) == DP + 3

    def test_row_content_roundtrip(self, world, mesh):
        buf, _ = _buffer(world, mesh)
        rows = _rows(2 * DP, world)
        slots = buf.add_dense(**rows)
        host = jax.device_get(buf.storage)
        # add_dense stripes rows contiguously per shard: shard k holds
        # rows [k*2, k*2+2) of the source block.
        got = host["value_target"][slots]
        np.testing.assert_allclose(got, rows["value_target"], atol=1e-6)
        got_grid = host["grid"][slots].astype(np.float32)
        np.testing.assert_array_equal(got_grid, rows["grid"])

    def test_invalid_rows_hit_trash(self, world, mesh):
        buf, _ = _buffer(world, mesh)
        rows = _rows(DP, world)
        rows["value_target"][0] = np.nan
        buf.add_dense(**rows)
        assert len(buf) == DP - 1

    def test_engine_payload_ingest_matches_harvest(self, world, mesh):
        env, fe, net, mcts_cfg = world
        tc = _cfg()
        # Twin engines, same seed: one harvests to host, one keeps the
        # payload on device for the sharded ingest. Identical games, so
        # the ring must hold exactly the harvested rows.
        fetch = SelfPlayEngine(
            env, fe, net, mcts_cfg, tc, seed=3, mesh=mesh
        )
        device = SelfPlayEngine(
            env, fe, net, mcts_cfg, tc, seed=3, mesh=mesh
        )
        harvested = fetch.play_moves(10)
        stats, payload = device.play_moves_device(10)
        buf, _ = _buffer(world, mesh, tc)
        count = buf.ingest_payload(payload)
        assert count == harvested.num_experiences
        host = jax.device_get(buf.storage)
        ring_vals = []
        for k in range(DP):
            base = k * buf.stride
            ring_vals.append(
                host["value_target"][base : base + int(buf._sizes[k])]
            )
        np.testing.assert_allclose(
            np.sort(np.concatenate(ring_vals)),
            np.sort(harvested.value_target),
            atol=1e-5,
        )


class TestSampling:
    def test_stratified_sample_shape_and_encoding(self, world, mesh):
        buf, tc = _buffer(world, mesh)
        buf.add_dense(**_rows(8 * DP, world))
        out = buf.sample(16, current_train_step=0)
        assert out is not None
        idx, w = out["indices"], out["weights"]
        assert idx.shape == (16,) and w.shape == (16,)
        # Shard-major: entries [k*2, k*2+2) come from shard k.
        shard_of = idx // buf.stride
        expect = np.repeat(np.arange(DP), 2)
        np.testing.assert_array_equal(shard_of, expect)
        assert w.max() == pytest.approx(1.0)

    def test_not_ready_until_every_shard_can_fill(self, world, mesh):
        buf, _ = _buffer(
            world, mesh, _cfg(MIN_BUFFER_SIZE_TO_TRAIN=DP)
        )
        # DP+3 rows pad to 2 per shard-slice, so the trailing shards
        # get only padding (0 valid rows) — a 2*DP batch needs 2 rows
        # in EVERY shard and must refuse until they exist.
        buf.add_dense(**_rows(DP + 3, world))
        assert buf.sample(2 * DP, current_train_step=0) is None
        buf.add_dense(**_rows(2 * DP, world, seed=1))
        assert buf.sample(2 * DP, current_train_step=0) is not None

    def test_batch_must_divide_dp(self, world, mesh):
        buf, _ = _buffer(world, mesh)
        buf.add_dense(**_rows(4 * DP, world))
        with pytest.raises(ValueError, match="divide"):
            buf.sample(12, current_train_step=0)

    def test_priority_update_routes_to_shards(self, world, mesh):
        buf, _ = _buffer(world, mesh)
        buf.add_dense(**_rows(2 * DP, world))
        out = buf.sample(2 * DP, current_train_step=0)
        td = np.linspace(0.1, 5.0, 2 * DP)
        buf.update_priorities(out["indices"], td)
        assert buf.trees is not None
        totals = [t.total_priority for t in buf.trees]
        assert all(t > 0 for t in totals)
        # A huge TD on one known row must move ITS shard's total.
        target = out["indices"][0]
        k = int(target // buf.stride)
        before = buf.trees[k].total_priority
        buf.update_priorities(
            np.asarray([target]), np.asarray([100.0])
        )
        assert buf.trees[k].total_priority > before


class TestLearnerPath:
    def test_fused_steps_from_sharded_ring(self, world, mesh):
        env, fe, net, _ = world
        tc = _cfg()
        buf, _ = _buffer(world, mesh, tc)
        buf.add_dense(**_rows(8 * DP, world))
        trainer = Trainer(net, tc, mesh=mesh)
        samples = [
            buf.sample(tc.BATCH_SIZE, current_train_step=trainer.global_step)
            for _ in range(2)
        ]
        results = trainer.train_steps_from(buf, samples)
        assert len(results) == 2
        for metrics, td in results:
            assert np.isfinite(metrics["total_loss"])
            assert td.shape == (tc.BATCH_SIZE,)
            assert np.all(np.isfinite(td))
        # Replicas identical after dp-sharded updates.
        leaf = jax.tree_util.tree_leaves(trainer.state.params)[0]
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)

    def test_gathered_rows_match_host_gather(self, world, mesh):
        """The sharded device gather must feed the learner the exact
        rows the indices name (bit-parity with a host-side gather)."""
        env, fe, net, _ = world
        tc = _cfg()
        buf, _ = _buffer(world, mesh, tc)
        rows = _rows(4 * DP, world)
        buf.add_dense(**rows)
        out = buf.sample(tc.BATCH_SIZE, current_train_step=0)
        host = jax.device_get(buf.storage)
        expect = host["value_target"][out["indices"]]
        # Independent check through the trainer's gather program: run
        # one fused step and verify the TD errors correspond to the
        # sampled rows by recomputing on host-gathered values. Cheaper:
        # gather via the storage directly (the trainer program uses the
        # same local-slot arithmetic).
        local = out["indices"] % buf.stride
        shard = out["indices"] // buf.stride
        manual = np.array(
            [
                host["value_target"][s * buf.stride + sl]
                for s, sl in zip(shard, local)
            ]
        )
        np.testing.assert_array_equal(manual, expect)


class TestPersistence:
    def test_roundtrip_sharded_to_sharded(self, world, mesh):
        buf, tc = _buffer(world, mesh)
        rows = _rows(4 * DP, world)
        buf.add_dense(**rows)
        out = buf.sample(2 * DP, current_train_step=0)
        buf.update_priorities(
            out["indices"], np.linspace(0.5, 2.0, 2 * DP)
        )
        snap = buf.get_state()
        assert snap["size"] == 4 * DP
        fresh, _ = _buffer(world, mesh, tc)
        fresh.set_state(snap)
        assert len(fresh) == 4 * DP
        a = np.sort(
            np.asarray(snap["storage"]["value_target"], np.float32)
        )
        host = jax.device_get(fresh.storage)
        got = []
        for k in range(DP):
            base = k * fresh.stride
            got.append(
                host["value_target"][base : base + int(fresh._sizes[k])]
            )
        np.testing.assert_allclose(
            np.sort(np.concatenate(got)), a, atol=1e-6
        )

    def test_host_snapshot_restores_into_sharded(self, world, mesh):
        env, fe, _, _ = world
        tc = _cfg()
        host_buf = ExperienceBuffer(tc, action_dim=env.action_dim)
        rows = _rows(3 * DP, world)
        host_buf.add_dense(**rows)
        snap = host_buf.get_state()
        buf, _ = _buffer(world, mesh, tc)
        buf.set_state(snap)
        assert len(buf) == 3 * DP

    def test_sharded_snapshot_restores_into_host(self, world, mesh):
        env, fe, _, _ = world
        buf, tc = _buffer(world, mesh)
        rows = _rows(3 * DP, world)
        buf.add_dense(**rows)
        snap = buf.get_state()
        host_buf = ExperienceBuffer(tc, action_dim=env.action_dim)
        host_buf.set_state(snap)
        assert len(host_buf) == 3 * DP


class TestSetupWiring:
    def _components(self, tmp_path, cfgs, **tc_kw):
        from alphatriangle_tpu.config import PersistenceConfig
        from alphatriangle_tpu.training import setup_training_components

        env_cfg, model_cfg, mcts_cfg = cfgs
        tc = _cfg(RUN_NAME="sharded_setup", **tc_kw)
        return setup_training_components(
            train_config=tc,
            env_config=env_cfg,
            model_config=model_cfg,
            mcts_config=mcts_cfg,
            mesh_config=MeshConfig(DP_SIZE=DP),
            persistence_config=PersistenceConfig(
                ROOT_DATA_DIR=str(tmp_path), RUN_NAME="sharded_setup"
            ),
            use_tensorboard=False,
        )

    def test_forced_on_dp_mesh_builds_sharded_ring(
        self, tmp_path, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        c = self._components(
            tmp_path,
            (tiny_env_config, tiny_model_config, tiny_mcts_config),
            DEVICE_REPLAY="on",
        )
        assert isinstance(c.buffer, ShardedDeviceReplayBuffer)
        assert c.self_play.mesh is not None  # rollouts sharded too
        c.stats.close()
        c.checkpoints.close()

    def test_forced_on_indivisible_capacity_raises(
        self, tmp_path, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        with pytest.raises(ValueError, match="DEVICE_REPLAY"):
            self._components(
                tmp_path,
                (tiny_env_config, tiny_model_config, tiny_mcts_config),
                DEVICE_REPLAY="on",
                BUFFER_CAPACITY=64 * DP + 1,
            )


class TestLoopEndToEnd:
    @pytest.mark.slow
    def test_overlapped_loop_on_sharded_ring(
        self, tmp_path, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        """The full multi-chip data path through the REAL TrainingLoop:
        dp-sharded rollout lanes -> per-device shard_map ingest ->
        device-local gather -> dp-sharded fused learner, overlapped
        producers + pipelined learner, on the virtual 8-device mesh."""
        from alphatriangle_tpu.config import PersistenceConfig
        from alphatriangle_tpu.training import (
            LoopStatus,
            TrainingLoop,
            setup_training_components,
        )

        tc = _cfg(
            RUN_NAME="sharded_loop",
            DEVICE_REPLAY="on",
            ASYNC_ROLLOUTS=True,
            ASYNC_CHUNK_SECONDS=None,
            MAX_TRAINING_STEPS=3,
            MIN_BUFFER_SIZE_TO_TRAIN=16,
            ROLLOUT_CHUNK_MOVES=4,
            FUSED_LEARNER_STEPS=2,
            CHECKPOINT_SAVE_FREQ_STEPS=100,
        )
        c = setup_training_components(
            train_config=tc,
            env_config=tiny_env_config,
            model_config=tiny_model_config,
            mcts_config=tiny_mcts_config,
            mesh_config=MeshConfig(DP_SIZE=DP),
            persistence_config=PersistenceConfig(
                ROOT_DATA_DIR=str(tmp_path), RUN_NAME="sharded_loop"
            ),
            use_tensorboard=False,
        )
        assert isinstance(c.buffer, ShardedDeviceReplayBuffer)
        assert c.self_play.mesh is not None
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        assert loop.global_step == 3
        # Replicas still identical after the full overlapped run.
        leaf = jax.tree_util.tree_leaves(c.trainer.state.params)[0]
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)
        c.stats.close()
        c.checkpoints.close()

def test_indivisible_selfplay_batch_falls_back_to_host(
    tmp_path, tiny_env_config, tiny_model_config, tiny_mcts_config
):
    # An unsharded rollout engine's payload lanes would crash the
    # shard_map ingest; the gate must route to the host buffer.
    from alphatriangle_tpu.config import PersistenceConfig
    from alphatriangle_tpu.training import setup_training_components

    c = setup_training_components(
        train_config=_cfg(
            RUN_NAME="sharded_fallback",
            DEVICE_REPLAY="auto",
            SELF_PLAY_BATCH_SIZE=DP + 1,
        ),
        env_config=tiny_env_config,
        model_config=tiny_model_config,
        mcts_config=tiny_mcts_config,
        mesh_config=MeshConfig(DP_SIZE=DP),
        persistence_config=PersistenceConfig(
            ROOT_DATA_DIR=str(tmp_path), RUN_NAME="sharded_fallback"
        ),
        use_tensorboard=False,
    )
    assert not getattr(c.buffer, "is_device", False)
    c.stats.close()
    c.checkpoints.close()
