"""Custom-op tests: every ops/ lowering pair must be numerically
pinned against the other (the Pallas kernels run in interpret mode on
CPU) — exact for the gather, backup and PER index-select ops, and
tolerance + fixed-seed arena equality for the bf16 inference path —
and full searches must be invariant to the backend choice."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphatriangle_tpu.mcts import BatchedMCTS
from alphatriangle_tpu.ops import (
    backup_update,
    gather_rows,
    per_sample,
    subtree_promote,
)


class TestGatherRows:
    @pytest.mark.parametrize("mode", ["einsum", "pallas", "take"])
    def test_matches_numpy(self, mode):
        rng = np.random.default_rng(0)
        stats = rng.random((6, 17, 40)).astype(np.float32)
        idx = rng.integers(0, 17, (6, 5)).astype(np.int32)
        out = np.asarray(gather_rows(stats, idx, mode))
        expect = np.stack([stats[b][idx[b]] for b in range(6)])
        np.testing.assert_array_equal(out, expect)

    def test_unknown_mode_raises(self):
        with pytest.raises(ValueError, match="unknown gather"):
            gather_rows(np.zeros((1, 2, 3)), np.zeros((1, 1), np.int32), "x")

    def test_jittable_under_vmapped_search_shapes(self):
        # Negative-free int32 indices with K not a multiple of 128
        # (flagship 6A = 2160 is; exercise the ragged case too).
        rng = np.random.default_rng(1)
        stats = rng.random((3, 9, 130)).astype(np.float32)
        idx = rng.integers(0, 9, (3, 4)).astype(np.int32)
        for mode in ("einsum", "pallas", "take"):
            out = jax.jit(lambda s, i, m=mode: gather_rows(s, i, m))(
                stats, idx
            )
            np.testing.assert_array_equal(
                np.asarray(out),
                np.stack([stats[b][idx[b]] for b in range(3)]),
            )


class TestPerSample:
    """Stratified PER draw: the Pallas compare-count and XLA
    searchsorted lowerings share one prefix-sum, so index selection is
    bit-identical by construction."""

    # cap off/on the kernel tile boundary, below one tile, K=1.
    @pytest.mark.parametrize("cap,k,b", [(37, 2, 8), (512, 1, 16), (700, 3, 32)])
    def test_pallas_matches_xla_exactly(self, cap, k, b):
        key = jax.random.PRNGKey(3)
        prios = jax.random.uniform(jax.random.PRNGKey(7), (cap + 1,))
        prios = prios.at[cap].set(0.0)  # trash slot
        idx_x, probs_x = per_sample(prios, cap, k, b, key, mode="xla")
        idx_p, probs_p = per_sample(prios, cap, k, b, key, mode="pallas")
        np.testing.assert_array_equal(np.asarray(idx_x), np.asarray(idx_p))
        np.testing.assert_array_equal(
            np.asarray(probs_x), np.asarray(probs_p)
        )
        assert idx_p.dtype == jnp.int32 and probs_p.dtype == jnp.float32

    @pytest.mark.parametrize("mode", ["xla", "pallas"])
    def test_draw_is_stratified_proportional(self, mode):
        """Each selected slot must bound its stratum draw:
        cum[idx-1] <= u < cum[idx] (the SumTree descent invariant)."""
        cap, k, b = 133, 2, 16
        prios = jax.random.uniform(jax.random.PRNGKey(9), (cap,))
        cum = np.cumsum(np.asarray(prios))
        key = jax.random.PRNGKey(11)
        idx, _ = per_sample(prios, cap, k, b, key, mode=mode)
        # Reconstruct the shared stratum draws exactly as per_sample.
        u = np.asarray(
            (
                jnp.arange(b, dtype=jnp.float32)[None, :]
                + jax.random.uniform(key, (k, b))
            )
            / b
            * jnp.cumsum(prios[:cap])[-1]
        )
        idx = np.asarray(idx)
        assert (u[idx > 0] >= cum[idx[idx > 0] - 1]).all()
        assert (u[idx < cap - 1] <= cum[idx[idx < cap - 1]]).all()

    @pytest.mark.parametrize("mode", ["xla", "pallas"])
    def test_zero_priority_never_selected(self, mode):
        """Empty/trash slots have empty cumsum segments."""
        cap = 64
        prios = jnp.zeros((cap,)).at[jnp.array([3, 17, 40])].set(1.0)
        idx, probs = per_sample(
            prios, cap, 4, 8, jax.random.PRNGKey(13), mode=mode
        )
        assert set(np.asarray(idx).ravel()) <= {3, 17, 40}
        assert (np.asarray(probs) > 0).all()

    def test_unknown_mode_raises(self):
        with pytest.raises(ValueError, match="unknown PER sample"):
            per_sample(
                jnp.ones((8,)), 8, 1, 4, jax.random.PRNGKey(0), mode="x"
            )


def _backup_operands(seed=2, batch=3, n=9, a=12, w=4, depth=5):
    """Random edge planes + a random (not necessarily consistent)
    descent record, with duplicate (node, action) hits across members
    and levels so update-order semantics are actually exercised."""
    ks = jax.random.split(jax.random.PRNGKey(seed), 12)
    return dict(
        e_visits=jax.random.uniform(ks[0], (batch, n, a)),
        e_value=jax.random.normal(ks[1], (batch, n, a)),
        children=jnp.full((batch, n, a), -1.0).at[:, 0, :3].set(1.0),
        e_reward=jax.random.normal(ks[2], (batch, n, a)),
        parents=jax.random.randint(ks[3], (batch, w), 0, n),
        actions=jax.random.randint(ks[4], (batch, w), 0, a),
        new_child=jnp.where(
            jax.random.bernoulli(ks[5], 0.5, (batch, w)),
            jax.random.randint(ks[6], (batch, w), 1, n).astype(jnp.float32),
            -1.0,
        ),
        rewards=jax.random.normal(ks[7], (batch, w)),
        rec_node=jax.random.randint(ks[8], (batch, w, depth), -1, 3),
        rec_action=jax.random.randint(ks[9], (batch, w, depth), -1, 4),
        rec_active=jax.random.bernoulli(ks[10], 0.7, (batch, w, depth)),
        returns=jax.random.normal(ks[11], (batch, w, depth)),
    )


class TestBackupUpdate:
    def test_pallas_matches_xla_exactly(self):
        ops = _backup_operands()
        outs_x = backup_update(*ops.values(), mode="xla")
        outs_p = backup_update(*ops.values(), mode="pallas")
        for got, want in zip(outs_p, outs_x):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_xla_matches_numpy_reference(self):
        """The op must reproduce the scatter math `_wave` originally
        spelled, computed here as a sequential numpy loop."""
        ops = _backup_operands(seed=5)
        ev, eq, ch, er = (
            np.asarray(ops[k], np.float64)
            for k in ("e_visits", "e_value", "children", "e_reward")
        )
        parents, actions = np.asarray(ops["parents"]), np.asarray(ops["actions"])
        new_child = np.asarray(ops["new_child"])
        rewards = np.asarray(ops["rewards"])
        rec_node, rec_action = np.asarray(ops["rec_node"]), np.asarray(ops["rec_action"])
        rec_active = np.asarray(ops["rec_active"])
        returns = np.asarray(ops["returns"])
        batch, w = parents.shape
        depth = rec_node.shape[-1]
        for bi in range(batch):
            for j in range(w):
                p, ac = parents[bi, j], actions[bi, j]
                ch[bi, p, ac] = max(ch[bi, p, ac], new_child[bi, j])
                er[bi, p, ac] = rewards[bi, j]
            for lvl in range(depth):
                for j in range(w):
                    nd = max(rec_node[bi, j, lvl], 0)
                    ac = max(rec_action[bi, j, lvl], 0)
                    if rec_active[bi, j, lvl]:
                        ev[bi, nd, ac] += 1.0
                        eq[bi, nd, ac] += returns[bi, j, lvl]
        got = backup_update(*ops.values(), mode="xla")
        for g, want in zip(got, (ev, eq, ch, er)):
            np.testing.assert_allclose(
                np.asarray(g), want.astype(np.float32), atol=1e-5
            )

    def test_unknown_mode_raises(self):
        ops = _backup_operands()
        with pytest.raises(ValueError, match="unknown backup"):
            backup_update(*ops.values(), mode="x")


def _promote_operands(seed=0, batch=3, nodes=8, actions=3):
    """Random forest planes with hand-known topology on lane 0/1 plus a
    random-ish lane: children form a proper forest (each node at most
    one parent, ids increasing away from the root) so the scatter-min
    BFS in `_promotion_plan` and a literal traversal must agree."""
    rng = np.random.default_rng(seed)
    ch = np.full((batch, nodes, actions), -1.0, np.float32)
    # lane 0: 0 -> {1, 2}, 1 -> {3}, 2 -> {4}; action 0 promotes node 1.
    ch[0, 0, 0], ch[0, 0, 1] = 1.0, 2.0
    ch[0, 1, 0] = 3.0
    ch[0, 2, 1] = 4.0
    # lane 1: chosen action has no child (invalid promotion).
    ch[1, 0, 0] = 5.0
    # lane 2: a deeper chain 0 -> 1 -> 2 -> 3 under action 0.
    ch[2, 0, 0] = 1.0
    ch[2, 1, 1] = 2.0
    ch[2, 2, 0] = 3.0
    planes = tuple(
        rng.random((batch, nodes, actions)).astype(np.float32)
        for _ in range(3)
    ) + (ch,) + tuple(
        rng.random((batch, nodes, actions)).astype(np.float32)
        for _ in range(2)
    )
    terminal = rng.random((batch, nodes)) < 0.3
    acts = np.array([0, 1, 0], np.int32)
    return planes, terminal, acts


def _eager_promote(planes, terminal, actions, max_retained):
    """Literal-BFS reference for `subtree_promote` (mirrors the
    reuse-smoke reference): traverse from the chosen child, order
    (depth, node id), truncate at the budget, remap children, zero
    freed rows, broadcast the chosen child over freed state_index."""
    from collections import deque

    ev, eq, er, ch, pr, va = [np.asarray(p, np.float32) for p in planes]
    term = np.asarray(terminal, bool)
    b_n, n, a_dim = ev.shape
    outs = [np.zeros_like(p) for p in (ev, eq, er, ch, pr, va)]
    outs[3][:] = -1.0
    term_out = np.zeros_like(term)
    state_index = np.zeros((b_n, n), np.int32)
    promo_valid = np.zeros(b_n, bool)
    retained = np.zeros(b_n, np.int32)
    for b in range(b_n):
        c0 = int(ch[b, 0, actions[b]])
        if c0 < 0:
            continue
        promo_valid[b] = True
        depth = {c0: 0}
        dq = deque([c0])
        while dq:
            u = dq.popleft()
            for act in range(a_dim):
                v = int(ch[b, u, act])
                if v >= 0 and v not in depth:
                    depth[v] = depth[u] + 1
                    dq.append(v)
        order = sorted(depth, key=lambda u: (depth[u], u))
        rank = {u: r for r, u in enumerate(order)}
        ret = min(len(order), max_retained)
        retained[b] = ret
        for r, u in enumerate(order[:ret]):
            for i, plane in enumerate((ev, eq, er, None, pr, va)):
                if plane is not None:
                    outs[i][b, r] = plane[b, u]
            for act in range(a_dim):
                v = int(ch[b, u, act])
                kept = v >= 0 and v in rank and rank[v] < max_retained
                outs[3][b, r, act] = float(rank[v]) if kept else -1.0
            term_out[b, r] = term[b, u]
        state_index[b, :ret] = order[:ret]
        state_index[b, ret:] = c0
    return outs, term_out, state_index, promo_valid, retained


class TestSubtreePromote:
    """Root promotion for subtree reuse (docs/KERNELS.md): both
    lowerings against a literal-BFS numpy reference, including budget
    truncation and the invalid-promotion lane."""

    @pytest.mark.parametrize("mode", ["xla", "pallas"])
    @pytest.mark.parametrize("max_retained", [8, 3])
    def test_matches_eager_reference(self, mode, max_retained):
        planes, terminal, acts = _promote_operands()
        ref_planes, ref_term, ref_sidx, ref_pv, ref_ret = _eager_promote(
            planes, terminal, acts, max_retained
        )
        got = subtree_promote(
            *[jnp.asarray(p) for p in planes],
            jnp.asarray(terminal),
            jnp.asarray(acts),
            max_retained=max_retained,
            bfs_rounds=4,
            mode=mode,
        )
        refs = list(ref_planes) + [ref_term, ref_sidx, ref_pv, ref_ret]
        for g, want in zip(got, refs):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(want))

    def test_pallas_matches_xla_exactly(self):
        planes, terminal, acts = _promote_operands(seed=9)
        kw = dict(max_retained=5, bfs_rounds=4)
        out_x = subtree_promote(
            *[jnp.asarray(p) for p in planes],
            jnp.asarray(terminal), jnp.asarray(acts), mode="xla", **kw
        )
        out_p = subtree_promote(
            *[jnp.asarray(p) for p in planes],
            jnp.asarray(terminal), jnp.asarray(acts), mode="pallas", **kw
        )
        for g, want in zip(out_p, out_x):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(want))

    def test_unknown_mode_raises(self):
        planes, terminal, acts = _promote_operands()
        with pytest.raises(ValueError, match="unknown subtree_promote"):
            subtree_promote(
                *[jnp.asarray(p) for p in planes],
                jnp.asarray(terminal),
                jnp.asarray(acts),
                max_retained=4,
                bfs_rounds=4,
                mode="x",
            )


class TestSearchGatherInvariance:
    @pytest.mark.slow
    def test_search_identical_across_modes(
        self, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        from alphatriangle_tpu.env.engine import TriangleEnv
        from alphatriangle_tpu.features.core import get_feature_extractor
        from alphatriangle_tpu.nn.network import NeuralNetwork

        env = TriangleEnv(tiny_env_config)
        fe = get_feature_extractor(env, tiny_model_config)
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        roots = env.reset_batch(
            jax.random.split(jax.random.PRNGKey(4), 4)
        )
        outs = {}
        for mode in ("einsum", "pallas", "take"):
            cfg = tiny_mcts_config.model_copy(
                update={"descent_gather": mode}
            )
            mcts = BatchedMCTS(env, fe, net.model, cfg, net.support)
            outs[mode] = np.asarray(
                mcts.search(net.variables, roots, jax.random.PRNGKey(5))
                .visit_counts
            )
        np.testing.assert_array_equal(outs["einsum"], outs["take"])
        np.testing.assert_array_equal(outs["einsum"], outs["pallas"])


def _tiny_net(tiny_env_config, tiny_model_config):
    from alphatriangle_tpu.env.engine import TriangleEnv
    from alphatriangle_tpu.features.core import get_feature_extractor
    from alphatriangle_tpu.nn.network import NeuralNetwork

    env = TriangleEnv(tiny_env_config)
    fe = get_feature_extractor(env, tiny_model_config)
    net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
    return env, fe, net


class TestSearchBackupInvariance:
    def test_search_identical_across_backup_modes(
        self, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        env, fe, net = _tiny_net(tiny_env_config, tiny_model_config)
        roots = env.reset_batch(jax.random.split(jax.random.PRNGKey(4), 4))
        outs = {}
        for mode in ("xla", "pallas"):
            cfg = tiny_mcts_config.model_copy(update={"backup_update": mode})
            mcts = BatchedMCTS(env, fe, net.model, cfg, net.support)
            out = mcts.search(net.variables, roots, jax.random.PRNGKey(5))
            outs[mode] = (
                np.asarray(out.visit_counts),
                np.asarray(out.root_value),
            )
        np.testing.assert_array_equal(outs["xla"][0], outs["pallas"][0])
        np.testing.assert_array_equal(outs["xla"][1], outs["pallas"][1])

    @pytest.mark.slow
    def test_fixed_seed_chunk_bit_identical(
        self,
        tiny_env_config,
        tiny_model_config,
        tiny_mcts_config,
        tiny_train_config,
    ):
        """A whole self-play chunk (search + select + step + n-step
        window) must be bit-identical under either backup backend —
        the rollout-program-level parity pin."""
        from alphatriangle_tpu.rl.self_play import SelfPlayEngine

        env, fe, net = _tiny_net(tiny_env_config, tiny_model_config)
        harvests = {}
        for mode in ("xla", "pallas"):
            engine = SelfPlayEngine(
                env,
                fe,
                net,
                tiny_mcts_config.model_copy(update={"backup_update": mode}),
                tiny_train_config,
                batch_size=4,
                seed=123,
            )
            engine.play_chunk(2)
            result = engine.harvest()
            harvests[mode] = (
                result.policy_target,
                result.value_target,
                np.asarray(engine.states.score),
            )
        for got, want in zip(harvests["pallas"], harvests["xla"]):
            np.testing.assert_array_equal(got, want)


class TestInferencePrecision:
    def test_f32_policy_is_identity(self, tiny_model_config):
        from alphatriangle_tpu.nn.precision import (
            cast_params_for_inference,
            inference_dtype,
        )

        assert inference_dtype(tiny_model_config) == jnp.float32
        variables = {"params": {"w": jnp.ones((2, 2))}}
        assert (
            cast_params_for_inference(variables, tiny_model_config)
            is variables
        )

    def test_bf16_forward_within_tolerance(
        self, tiny_env_config, tiny_model_config
    ):
        """bf16-cast params must give close (not bit-equal: the heads'
        final f32 Dense sees rounded weights) priors and values."""
        from alphatriangle_tpu.nn.precision import cast_params_for_inference

        env, fe, net = _tiny_net(tiny_env_config, tiny_model_config)
        bf16_cfg = tiny_model_config.model_copy(
            update={"INFERENCE_PRECISION": "bfloat16"}
        )
        cast = cast_params_for_inference(net.variables, bf16_cfg)
        leaf = jax.tree_util.tree_leaves(cast["params"])[0]
        assert leaf.dtype == jnp.bfloat16
        states = env.reset_batch(jax.random.split(jax.random.PRNGKey(8), 8))
        grids, others = jax.vmap(fe.extract)(states)
        pol_f32, val_f32 = net.model.apply(
            net.variables, grids, others, train=False
        )
        pol_bf16, val_bf16 = net.model.apply(cast, grids, others, train=False)
        assert pol_bf16.dtype == jnp.float32  # heads stay f32
        p32 = jax.nn.softmax(pol_f32, axis=-1)
        p16 = jax.nn.softmax(pol_bf16, axis=-1)
        np.testing.assert_allclose(
            np.asarray(p16), np.asarray(p32), atol=0.05
        )
        np.testing.assert_allclose(
            np.asarray(val_bf16), np.asarray(val_f32), atol=0.2, rtol=0.1
        )

    def test_fixed_seed_arena_equality(
        self, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        """Paired-hands arena (arena.py): the same fixed-seed games
        played greedily under f32 vs bf16 inference must score within
        tolerance — the Elo-neutrality gate for the precision policy
        (KataGo, arXiv:1902.10565)."""
        from alphatriangle_tpu.arena import greedy_mcts_policy, play
        from alphatriangle_tpu.nn.precision import cast_params_for_inference

        env, fe, net = _tiny_net(tiny_env_config, tiny_model_config)
        cfg = tiny_mcts_config.model_copy(update={"wave_noise_scale": 0.0})
        mcts = BatchedMCTS(env, fe, net.model, cfg, net.support)
        bf16_cfg = tiny_model_config.model_copy(
            update={"INFERENCE_PRECISION": "bfloat16"}
        )

        class _Net:
            def __init__(self, variables):
                self.variables = variables

        scores = {}
        for name, variables in (
            ("f32", net.variables),
            ("bf16", cast_params_for_inference(net.variables, bf16_cfg)),
        ):
            s, _, _ = play(
                env,
                greedy_mcts_policy(_Net(variables), mcts),
                games=4,
                max_moves=8,
                seed=21,
            )
            scores[name] = s
        # Paired hands strip hand luck; a per-game score gap only
        # appears where rounding flips a near-tie move choice.
        assert (
            abs(float(scores["bf16"].mean() - scores["f32"].mean())) <= 3.0
        )

    def test_int8_round_trip_within_per_channel_tolerance(
        self, tiny_env_config, tiny_model_config
    ):
        """Weight-only int8 (nn/precision.py): every floating matrix
        leaf becomes {int8 q, per-channel f32 scale}; dequantization
        must land within one per-channel scale unit of the original
        (0.5 from symmetric rounding + ~0.5 from the bf16 dequant
        target), and the quantized tree must read far fewer bytes."""
        from alphatriangle_tpu.nn.precision import (
            dequantize_params,
            is_quantized_leaf,
            quantize_params_for_inference,
            quantized_param_bytes,
        )

        _env, _fe, net = _tiny_net(tiny_env_config, tiny_model_config)
        q = quantize_params_for_inference(net.variables)
        q_leaves = [
            leaf
            for leaf in jax.tree_util.tree_leaves(
                q, is_leaf=is_quantized_leaf
            )
            if is_quantized_leaf(leaf)
        ]
        assert q_leaves, "no matrix leaf was quantized"
        for leaf in q_leaves:
            assert leaf["q"].dtype == jnp.int8
            assert leaf["scale"].dtype == jnp.float32
        deq = dequantize_params(q)
        flat_orig = jax.tree_util.tree_leaves(net.variables)
        flat_deq = jax.tree_util.tree_leaves(deq)
        checked = 0
        for orig, got in zip(flat_orig, flat_deq):
            if orig.ndim < 2 or not jnp.issubdtype(
                orig.dtype, jnp.floating
            ):
                continue
            scale = jnp.max(
                jnp.abs(orig.astype(jnp.float32)),
                axis=tuple(range(orig.ndim - 1)),
                keepdims=True,
            ) / 127.0
            err = jnp.abs(
                got.astype(jnp.float32) - orig.astype(jnp.float32)
            )
            assert float(jnp.max(err / jnp.maximum(scale, 1e-12))) <= 1.0
            checked += 1
        assert checked == len(q_leaves)
        # The HBM-read win the quantization exists for: int8 weights +
        # per-channel scales must read far fewer bytes than f32.
        f32_bytes = quantized_param_bytes(net.variables)
        int8_bytes = quantized_param_bytes(q)
        assert int8_bytes < f32_bytes / 2

    def test_int8_fixed_seed_arena_within_gate(
        self, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        """The same Elo-neutrality gate as bf16, for the int8 path:
        paired fixed-seed greedy games under f32 vs quantized weights
        must score within tolerance (the search dequantizes marker
        leaves at its evaluate choke point, mcts/search.py)."""
        from alphatriangle_tpu.arena import greedy_mcts_policy, play
        from alphatriangle_tpu.nn.precision import cast_params_for_inference

        env, fe, net = _tiny_net(tiny_env_config, tiny_model_config)
        cfg = tiny_mcts_config.model_copy(update={"wave_noise_scale": 0.0})
        mcts = BatchedMCTS(env, fe, net.model, cfg, net.support)
        int8_cfg = tiny_model_config.model_copy(
            update={"INFERENCE_PRECISION": "int8"}
        )

        class _Net:
            def __init__(self, variables):
                self.variables = variables

        scores = {}
        for name, variables in (
            ("f32", net.variables),
            ("int8", cast_params_for_inference(net.variables, int8_cfg)),
        ):
            s, _, _ = play(
                env,
                greedy_mcts_policy(_Net(variables), mcts),
                games=4,
                max_moves=8,
                seed=21,
            )
            scores[name] = s
        assert (
            abs(float(scores["int8"].mean() - scores["f32"].mean())) <= 3.0
        )
