"""Custom-op tests: the three descent row-gather lowerings must be
numerically identical (the Pallas kernel runs in interpret mode on
CPU), and full searches must be invariant to the choice."""

import jax
import numpy as np
import pytest

from alphatriangle_tpu.mcts import BatchedMCTS
from alphatriangle_tpu.ops import gather_rows


class TestGatherRows:
    @pytest.mark.parametrize("mode", ["einsum", "pallas", "take"])
    def test_matches_numpy(self, mode):
        rng = np.random.default_rng(0)
        stats = rng.random((6, 17, 40)).astype(np.float32)
        idx = rng.integers(0, 17, (6, 5)).astype(np.int32)
        out = np.asarray(gather_rows(stats, idx, mode))
        expect = np.stack([stats[b][idx[b]] for b in range(6)])
        np.testing.assert_array_equal(out, expect)

    def test_unknown_mode_raises(self):
        with pytest.raises(ValueError, match="unknown gather"):
            gather_rows(np.zeros((1, 2, 3)), np.zeros((1, 1), np.int32), "x")

    def test_jittable_under_vmapped_search_shapes(self):
        # Negative-free int32 indices with K not a multiple of 128
        # (flagship 6A = 2160 is; exercise the ragged case too).
        rng = np.random.default_rng(1)
        stats = rng.random((3, 9, 130)).astype(np.float32)
        idx = rng.integers(0, 9, (3, 4)).astype(np.int32)
        for mode in ("einsum", "pallas", "take"):
            out = jax.jit(lambda s, i, m=mode: gather_rows(s, i, m))(
                stats, idx
            )
            np.testing.assert_array_equal(
                np.asarray(out),
                np.stack([stats[b][idx[b]] for b in range(3)]),
            )


class TestSearchGatherInvariance:
    def test_search_identical_across_modes(
        self, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        from alphatriangle_tpu.env.engine import TriangleEnv
        from alphatriangle_tpu.features.core import get_feature_extractor
        from alphatriangle_tpu.nn.network import NeuralNetwork

        env = TriangleEnv(tiny_env_config)
        fe = get_feature_extractor(env, tiny_model_config)
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        roots = env.reset_batch(
            jax.random.split(jax.random.PRNGKey(4), 4)
        )
        outs = {}
        for mode in ("einsum", "pallas", "take"):
            cfg = tiny_mcts_config.model_copy(
                update={"descent_gather": mode}
            )
            mcts = BatchedMCTS(env, fe, net.model, cfg, net.support)
            outs[mode] = np.asarray(
                mcts.search(net.variables, roots, jax.random.PRNGKey(5))
                .visit_counts
            )
        np.testing.assert_array_equal(outs["einsum"], outs["take"])
        np.testing.assert_array_equal(outs["einsum"], outs["pallas"])
