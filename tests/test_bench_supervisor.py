"""The bench supervisor's one-JSON-line contract (bench.py).

The driver records `python bench.py` stdout as the round's benchmark
artifact, so the supervisor must emit exactly one parseable line under
every failure mode — wedged probe, post-probe hang, child crash — and
must never silently relabel a failed accelerator attempt as a
measurement. These tests pin the failure-path plumbing that can't be
exercised on a healthy machine (pure-Python paths; no JAX import in
the supervisor process).
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
BENCH = REPO / "bench.py"

sys.path.insert(0, str(REPO))
import bench  # noqa: E402


class TestParseLastJsonLine:
    def test_picks_last_valid_line(self):
        buf = b'{"a": 1}\nnoise\n{"b": 2}\n'
        assert bench.parse_last_json_line(buf) == {"b": 2}

    def test_skips_trailing_garbage_brace_line(self):
        # An atexit hook printing a '{'-prefixed non-JSON line must not
        # mask the real result emitted just before it.
        buf = b'{"metric": "x", "value": 1}\n{not json\n'
        assert bench.parse_last_json_line(buf) == {"metric": "x", "value": 1}

    def test_truncated_tail_falls_back_to_previous(self):
        # A budget kill can cut the pipe mid-line.
        buf = b'{"metric": "x"}\n{"metric": "y", "val'
        assert bench.parse_last_json_line(buf) == {"metric": "x"}

    def test_no_json_returns_none(self):
        assert bench.parse_last_json_line(b"just logs\n") is None
        assert bench.parse_last_json_line(b"") is None


class TestErrorResult:
    def test_shape_matches_contract(self):
        out = bench.error_result({"backend": "none"})
        assert out["metric"] == "self_play_games_per_hour"
        assert out["value"] == 0.0
        assert out["unit"] == "games/hour"
        assert out["vs_baseline"] == 0.0
        assert out["extra"] == {"backend": "none"}


@pytest.mark.slow
class TestSupervisorErrorPath:
    def test_no_fallback_emits_error_line_fast(self):
        """Probe budget too small to attempt -> immediate error line
        (sweep mode), no JAX ever imported, well under a minute."""
        env = dict(
            os.environ,
            BENCH_INIT_BUDGET="5",  # < 30s floor: zero probe attempts
            BENCH_NO_CPU_FALLBACK="1",
        )
        env.pop("JAX_PLATFORMS", None)  # must not look explicit-cpu
        r = subprocess.run(
            [sys.executable, str(BENCH)],
            capture_output=True,
            text=True,
            timeout=60,
            env=env,
            cwd=REPO,
        )
        assert r.returncode == 0
        lines = [
            ln for ln in r.stdout.splitlines() if ln.strip().startswith("{")
        ]
        assert len(lines) == 1, r.stdout
        out = json.loads(lines[0])
        assert out["value"] == 0.0
        assert out["extra"]["backend"] == "none"
        assert "probe" in out["extra"]["error"]


class TestPartialSnapshots:
    def test_is_final_result(self):
        assert not bench.is_final_result(None)
        assert bench.is_final_result({"metric": "x", "extra": {}})
        assert bench.is_final_result({"metric": "x"})
        assert not bench.is_final_result(
            {"metric": "x", "extra": {"partial": "self_play"}}
        )

    def test_last_partial_wins_over_stream(self):
        # The supervisor keeps the NEWEST snapshot when the child is
        # killed mid-run: later sections' lines supersede earlier ones.
        lines = (
            b'{"metric": "m", "value": 1, "extra": {"partial": "self_play"}}\n'
            b'{"metric": "m", "value": 1, "extra": {"partial": "learner"}}\n'
        )
        parsed = bench.parse_last_json_line(lines)
        assert parsed["extra"]["partial"] == "learner"
        assert not bench.is_final_result(parsed)

    def test_final_line_supersedes_partials(self):
        lines = (
            b'{"metric": "m", "value": 1, "extra": {"partial": "self_play"}}\n'
            b'{"metric": "m", "value": 2, "extra": {}}\n'
        )
        parsed = bench.parse_last_json_line(lines)
        assert bench.is_final_result(parsed)
        assert parsed["value"] == 2


class TestLatestTpuRecord:
    def test_prefers_newest_flagship_row(self, tmp_path):
        bdir = tmp_path / "benchmarks"
        bdir.mkdir()
        old = bdir / "tpu_r4_results.jsonl"
        old.write_text(
            '{"label": "flagship_gumbel_pcr", "result": {"value": 1000.0,'
            ' "extra": {"backend": "tpu"}}}\n'
        )
        new = bdir / "tpu_r5_results.jsonl"
        new.write_text(
            '{"label": "preset2", "result": {"value": 5.0, "extra": {}}}\n'
            '{"label": "flagship_gumbel_pcr", "result": {"value": 2000.0,'
            ' "extra": {"backend": "tpu"}}}\n'
        )
        import os as _os

        _os.utime(old, (1, 1))
        rec = bench.latest_tpu_record(base_dir=str(tmp_path))
        assert "tpu_r5_results.jsonl" in rec and "2,000" in rec

    def test_falls_back_to_static_artifact(self, tmp_path):
        rec = bench.latest_tpu_record(base_dir=str(tmp_path))
        assert "bench_flagship_tpu_20260730" in rec

    def test_skips_cpu_and_zero_value_rows(self, tmp_path):
        bdir = tmp_path / "benchmarks"
        bdir.mkdir()
        (bdir / "tpu_r5_results.jsonl").write_text(
            '{"label": "flagship_gumbel_pcr", "result": {"value": 0.0,'
            ' "extra": {"backend": "cpu"}}}\n'
        )
        (bdir / "tpu_r4_results.jsonl").write_text(
            '{"label": "flagship_puct", "result": {"value": 1500.0,'
            ' "extra": {"backend": "tpu"}}}\n'
        )
        rec = bench.latest_tpu_record(base_dir=str(tmp_path))
        # r5's junk row skipped; r4's real TPU row cited.
        assert "tpu_r4_results.jsonl" in rec and "1,500" in rec

    def test_round_number_ordering_beats_mtime(self, tmp_path):
        import os as _os

        bdir = tmp_path / "benchmarks"
        bdir.mkdir()
        r4 = bdir / "tpu_r4_results_early.jsonl"
        r5 = bdir / "tpu_r5_results.jsonl"
        for p, v in ((r4, 1000.0), (r5, 2000.0)):
            p.write_text(
                '{"label": "flagship_gumbel_pcr", "result": '
                f'{{"value": {v}, "extra": {{"backend": "tpu"}}}}}}\n'
            )
        # Simulate a fresh checkout flattening mtimes the wrong way.
        _os.utime(r5, (1, 1))
        rec = bench.latest_tpu_record(base_dir=str(tmp_path))
        assert "tpu_r5_results.jsonl" in rec and "2,000" in rec
