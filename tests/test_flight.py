"""Dispatch flight recorder + window forensics (telemetry/flight.py,
`cli doctor`).

Everything here is JAX-free and fast: the recorder/watchdog/classifier
are pure host-side machinery, and the crash-path tests run real
subprocesses (SIGKILL mid-dispatch, import-guarded doctor) — the same
evidence chain `benchmarks/tpu_watch.sh` relies on when a chip window
dies. Real-dispatch integration (the four hot sites actually sealing
records) is gated by `make perf-smoke`, not here, to keep tier-1 fast.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

from alphatriangle_tpu.telemetry.flight import (
    DOCTOR_EXIT_CODES,
    FLIGHT_FILENAME,
    WEDGE_EXIT_CODE,
    WEDGE_REPORT_FILENAME,
    WEDGE_STACKS_FILENAME,
    DispatchWatchdog,
    FlightRecorder,
    classify_run,
    family_seconds,
    flight_span,
    program_family,
    read_flight,
    read_wedge_report,
    summarize_flight,
    unsealed_intents,
)

REPO = Path(__file__).resolve().parent.parent


def _flight_line(**fields) -> str:
    return json.dumps({"kind": "flight", **fields}) + "\n"


def _intent(seq, program="megastep/t4_k2", family="megastep", **kw):
    return {
        "kind": "flight", "phase": "intent", "seq": seq,
        "program": program, "family": family, "avals": "B4",
        "expected_s": kw.pop("expected_s", None),
        "deadline_s": kw.pop("deadline_s", 900.0),
        "t_mono": float(seq), "time": kw.pop("time", 100.0 + seq),
        "pid": 1, **kw,
    }


def _seal(seq, program="megastep/t4_k2", family="megastep", **kw):
    return {
        "kind": "flight", "phase": "seal", "seq": seq,
        "program": program, "family": family,
        "wall_s": kw.pop("wall_s", 1.0), "ok": kw.pop("ok", True),
        "t_mono": float(seq) + 1, "time": kw.pop("time", 101.0 + seq),
        **kw,
    }


class TestProgramFamily:
    def test_hot_families(self):
        assert program_family("self_play_chunk/t8") == "rollout"
        assert program_family("learner_step") == "learner"
        assert program_family("learner_fused_from_sharded_ring/s2_dp") == "learner"
        assert program_family("megastep/dp2_t4_k2") == "megastep"
        assert program_family("serve/b64") == "serve"
        assert program_family("reuse/promote_b64") == "reuse"
        assert program_family("warm/xyz") == "warm"


class TestFlightRecorder:
    def test_intent_seal_round_trip(self, tmp_path):
        rec = FlightRecorder(tmp_path / FLIGHT_FILENAME)
        with flight_span(rec, "rollout", "self_play_chunk/t4", avals="B4xT4"):
            pass
        records = read_flight(tmp_path / FLIGHT_FILENAME)
        assert [r["phase"] for r in records] == ["intent", "seal"]
        intent, seal = records
        assert intent["program"] == seal["program"] == "self_play_chunk/t4"
        assert intent["family"] == "rollout"
        assert intent["avals"] == "B4xT4"
        assert intent["seq"] == seal["seq"] == 1
        assert intent["deadline_s"] == rec.first_deadline_s
        assert seal["ok"] is True and seal["wall_s"] >= 0
        assert not unsealed_intents(records)
        assert rec.dispatches == 1

    def test_expected_ewma_calibrates_deadline(self, tmp_path):
        rec = FlightRecorder(
            tmp_path / FLIGHT_FILENAME, min_deadline_s=0.5,
            deadline_factor=10.0,
        )
        rec.begin("learner", "learner_step").seal()
        first_wall = rec.expected_s("learner_step")
        assert first_wall is not None
        rec.begin("learner", "learner_step").seal()
        records = read_flight(tmp_path / FLIGHT_FILENAME)
        second_intent = [r for r in records if r["phase"] == "intent"][1]
        # The record rounds expected_s to 6 decimals.
        assert second_intent["expected_s"] == pytest.approx(
            first_wall, abs=1e-6
        )
        assert second_intent["deadline_s"] == pytest.approx(
            max(0.5, 10.0 * first_wall), abs=1e-3
        )

    def test_new_recorder_inherits_prior_seals(self, tmp_path):
        path = tmp_path / FLIGHT_FILENAME
        path.write_text(
            _flight_line(**_intent(1)) + _flight_line(**_seal(1, wall_s=3.0))
        )
        rec = FlightRecorder(path)
        assert rec.expected_s("megastep/t4_k2") == pytest.approx(3.0)

    def test_error_seal_is_not_torn(self, tmp_path):
        rec = FlightRecorder(tmp_path / FLIGHT_FILENAME)
        with pytest.raises(RuntimeError):
            with flight_span(rec, "learner", "learner_step"):
                raise RuntimeError("boom")
        records = read_flight(tmp_path / FLIGHT_FILENAME)
        seal = records[-1]
        assert seal["phase"] == "seal" and seal["ok"] is False
        assert "boom" in seal["error"]
        assert not unsealed_intents(records)

    def test_span_seal_idempotent(self, tmp_path):
        rec = FlightRecorder(tmp_path / FLIGHT_FILENAME)
        span = rec.begin("serve", "serve/b8")
        span.seal()
        span.seal()
        records = read_flight(tmp_path / FLIGHT_FILENAME)
        assert sum(1 for r in records if r["phase"] == "seal") == 1

    def test_none_recorder_is_noop(self):
        with flight_span(None, "learner", "learner_step") as span:
            assert span is None

    def test_close_writes_overhead_summary(self, tmp_path):
        from alphatriangle_tpu.telemetry.ledger import iter_jsonl_records

        path = tmp_path / FLIGHT_FILENAME
        rec = FlightRecorder(path)
        rec.begin("learner", "learner_step").seal()
        rec.close()
        summaries = [
            r
            for r in iter_jsonl_records(path)
            if r.get("kind") == "flight_overhead"
        ]
        assert len(summaries) == 1
        assert summaries[0]["dispatches"] == 1
        assert summaries[0]["overhead_s"] >= 0

    def test_byte_torn_tail_tolerated(self, tmp_path):
        """Shared-reader regression (the ledger's tolerant tail
        handling must cover the flight ring too): a mid-record SIGKILL
        leaves junk bytes the readers skip without losing the sealed
        history before them."""
        path = tmp_path / FLIGHT_FILENAME
        path.write_text(
            _flight_line(**_intent(1))
            + _flight_line(**_seal(1))
            + _flight_line(**_intent(2))
            + '{"kind": "flight", "phase": "seal", "seq": 2, "wa\x00'
        )
        records = read_flight(path)
        assert len(records) == 3
        torn = unsealed_intents(records)
        assert [t["seq"] for t in torn] == [2]
        # And a fresh recorder over the torn file still seeds from the
        # intact seal.
        rec = FlightRecorder(path)
        assert rec.expected_s("megastep/t4_k2") == pytest.approx(1.0)


class TestSummaries:
    def test_summarize_and_family_seconds(self):
        records = []
        for seq, wall in enumerate([1.0, 2.0, 3.0], 1):
            records.append(_intent(seq, program="learner_step", family="learner"))
            records.append(
                _seal(seq, program="learner_step", family="learner", wall_s=wall)
            )
        records.append(_intent(9, program="serve/b8", family="serve"))
        records.append(
            _seal(9, program="serve/b8", family="serve", ok=False, error="x")
        )
        rows = summarize_flight(records)
        assert [r["program"] for r in rows] == ["learner_step", "serve/b8"]
        top = rows[0]
        assert top["count"] == 3 and top["errors"] == 0
        assert top["wall_s_p50"] == pytest.approx(2.0)
        assert top["wall_s_total"] == pytest.approx(6.0)
        assert rows[1]["errors"] == 1 and rows[1]["count"] == 0
        fams = family_seconds(records)
        assert fams == {"learner": pytest.approx(2.0)}


class TestDispatchWatchdog:
    def _pair(self, tmp_path, **kw):
        clock = {"t": 0.0}
        wd = DispatchWatchdog(
            tmp_path, exit_on_wedge=False, clock=lambda: clock["t"], **kw
        )
        rec = FlightRecorder(
            tmp_path / FLIGHT_FILENAME, watchdog=wd,
            min_deadline_s=5.0, first_deadline_s=10.0,
        )
        return clock, wd, rec

    def test_no_fire_before_deadline(self, tmp_path):
        clock, wd, rec = self._pair(tmp_path)
        rec.begin("learner", "learner_step")
        clock["t"] += 9.0
        assert wd.check() is None

    def test_seal_disarms(self, tmp_path):
        clock, wd, rec = self._pair(tmp_path)
        rec.begin("learner", "learner_step").seal()
        clock["t"] += 1e6
        assert wd.check() is None

    def test_fires_once_with_report_and_stacks(self, tmp_path):
        clock, wd, rec = self._pair(tmp_path)
        hooks = []
        wd.on_wedge = hooks.append
        rec.begin("learner", "learner_step", avals="B8")
        clock["t"] += 11.0
        report = wd.check()
        assert report is not None
        assert report["program"] == "learner_step"
        assert report["elapsed_s"] == pytest.approx(11.0)
        assert report["exit_code"] is None  # exit_on_wedge off
        assert hooks and hooks[0]["program"] == "learner_step"
        on_disk = read_wedge_report(tmp_path / WEDGE_REPORT_FILENAME)
        assert on_disk["program"] == "learner_step"
        assert (tmp_path / WEDGE_STACKS_FILENAME).read_text()
        # Latch: one wedge per process, however long it stays overdue.
        clock["t"] += 100.0
        assert wd.check() is None
        assert wd.wedge_count == 1


class TestClassifyRun:
    def test_never_started(self):
        v = classify_run([])
        assert v["verdict"] == "never-started"
        assert v["exit_code"] == DOCTOR_EXIT_CODES["never-started"] == 2

    def test_clean(self):
        v = classify_run([_intent(1), _seal(1)])
        assert v["verdict"] == "clean" and v["exit_code"] == 0

    def test_compile_hung_on_first_dispatch(self):
        v = classify_run([_intent(1)])
        assert v["verdict"] == "compile-hung" and v["exit_code"] == 3
        assert v["program"] == "megastep/t4_k2"

    def test_dispatch_hung_after_prior_seal(self):
        v = classify_run([_intent(1), _seal(1), _intent(2)])
        assert v["verdict"] == "dispatch-hung" and v["exit_code"] == 4
        assert v["program"] == "megastep/t4_k2"
        assert v["family"] == "megastep"

    def test_wedge_report_is_strongest_evidence(self):
        wedge = {
            "program": "serve/b64", "family": "serve",
            "elapsed_s": 99.0, "deadline_s": 9.0,
        }
        v = classify_run(
            [_intent(1), _seal(1)], wedge=wedge
        )
        assert v["verdict"] == "compile-hung"  # serve/b64 never sealed
        assert v["program"] == "serve/b64"
        assert v["evidence"]["wedge_report"] is True

    def test_oom_precedence_over_hung(self):
        v = classify_run(
            [_intent(1)], utils=[{"kind": "util", "mem_utilization": 0.97}]
        )
        assert v["verdict"] == "oom" and v["exit_code"] == 6
        assert v["program"] == "megastep/t4_k2"

    def test_host_stall_from_stalled_heartbeat(self):
        v = classify_run(
            [_intent(1), _seal(1)],
            health={"time": 200.0, "stalled": True},
        )
        assert v["verdict"] == "host-stall" and v["exit_code"] == 5

    def test_host_stall_from_beating_past_last_seal(self):
        v = classify_run(
            [_intent(1), _seal(1, time=100.0)],
            health={
                "time": 100.0 + 2 * 300.0 + 1,
                "stalled": False,
                "watchdog_deadline_s": 300.0,
            },
        )
        assert v["verdict"] == "host-stall"


# The crash-path child: seals one dispatch, begins a second, announces
# readiness, then sleeps inside the bracket until SIGKILLed.
_CRASH_CHILD = """
import sys, time
from alphatriangle_tpu.telemetry.flight import FlightRecorder, flight_span
rec = FlightRecorder({path!r})
with flight_span(rec, "megastep", "megastep/t4_k2", avals="B4xT4xK2"):
    pass
span = rec.begin("megastep", "megastep/t4_k2", avals="B4xT4xK2")
print("IN_DISPATCH", flush=True)
time.sleep(120)
"""


class TestCrashPath:
    @pytest.fixture()
    def killed_run(self, tmp_path):
        """A real process SIGKILLed mid-dispatch, like a wedge or an
        external kill -9: the flight ring must carry the evidence."""
        path = str(tmp_path / FLIGHT_FILENAME)
        proc = subprocess.Popen(
            [sys.executable, "-c", _CRASH_CHILD.format(path=path)],
            cwd=str(REPO),
            stdout=subprocess.PIPE,
            text=True,
        )
        try:
            line = proc.stdout.readline()
            assert "IN_DISPATCH" in line
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
        return tmp_path

    def test_sigkill_leaves_torn_intent(self, killed_run):
        records = read_flight(killed_run / FLIGHT_FILENAME)
        torn = unsealed_intents(records)
        assert len(torn) == 1
        assert torn[0]["program"] == "megastep/t4_k2"
        v = classify_run(records)
        assert v["verdict"] == "dispatch-hung"
        assert v["program"] == "megastep/t4_k2"

    def test_cli_doctor_names_program_without_jax(self, killed_run):
        """The full postmortem invocation tpu_watch.sh makes: `cli
        doctor` in a subprocess whose import machinery refuses jax,
        exiting nonzero with the hung program named."""
        code = (
            "import builtins, sys\n"
            "real = builtins.__import__\n"
            "def guard(name, *a, **k):\n"
            "    if name == 'jax' or name.startswith('jax.'):\n"
            "        raise AssertionError('cli doctor imported ' + name)\n"
            "    return real(name, *a, **k)\n"
            "builtins.__import__ = guard\n"
            "from alphatriangle_tpu.cli import main\n"
            f"sys.exit(main(['doctor', {str(killed_run)!r}, '--json']))\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            cwd=str(REPO),
            timeout=120,
        )
        assert proc.returncode == DOCTOR_EXIT_CODES["dispatch-hung"], (
            proc.stdout + proc.stderr
        )
        verdict = json.loads(proc.stdout.strip().splitlines()[-1])
        assert verdict["verdict"] == "dispatch-hung"
        assert verdict["program"] == "megastep/t4_k2"
        assert verdict["family"] == "megastep"


class TestWatchIntegration:
    def test_fold_flight_line_and_render(self, tmp_path):
        from alphatriangle_tpu.stats.watch import (
            WatchState,
            last_dispatch_line,
            render_frame,
            tail_flight,
        )

        path = tmp_path / FLIGHT_FILENAME
        now = time.time()
        path.write_text(
            _flight_line(**_intent(1, time=now - 30))
            + _flight_line(**_seal(1, time=now - 20, wall_s=2.0))
        )
        state = WatchState()
        offset = tail_flight(path, state, 0)
        assert offset == path.stat().st_size
        line = last_dispatch_line(state, now=now)
        assert "megastep/t4_k2" in line and "sealed" in line
        # A newer unsealed intent flips the line to in-flight with the
        # deadline visible.
        with path.open("a") as f:
            f.write(
                _flight_line(
                    **_intent(2, time=now - 5, expected_s=2.0, deadline_s=20.0)
                )
            )
        tail_flight(path, state, offset)
        line = last_dispatch_line(state, now=now)
        assert "in flight" in line and "deadline" in line
        assert "OVER DEADLINE" not in line
        line_late = last_dispatch_line(state, now=now + 100)
        assert "OVER DEADLINE" in line_late
        frame = render_frame(state, "runx")
        assert "megastep/t4_k2" in frame

    def test_no_flight_records_renders_nothing(self):
        from alphatriangle_tpu.stats.watch import WatchState, last_dispatch_line

        assert last_dispatch_line(WatchState()) is None


class TestCliIntegration:
    def _run_dir(self, tmp_path):
        now = time.time()
        utils = [
            json.dumps(
                {"kind": "util", "step": i, "time": now - 60 + i,
                 "window_s": 1.0, "learner_steps_per_sec": 1.0,
                 "mfu": 0.01, "tflops_per_sec": 0.01,
                 "device_kind": "cpu", "step_time_ms": 10.0}
            )
            for i in range(1, 4)
        ]
        (tmp_path / "metrics.jsonl").write_text("\n".join(utils) + "\n")
        (tmp_path / FLIGHT_FILENAME).write_text(
            _flight_line(**_intent(1, program="serve/b8", family="serve"))
            + _flight_line(
                **_seal(1, program="serve/b8", family="serve", wall_s=0.5)
            )
        )
        return tmp_path

    def test_cli_perf_json_programs(self, tmp_path, capsys):
        from alphatriangle_tpu.cli import main as cli_main

        run_dir = self._run_dir(tmp_path)
        rc = cli_main(["perf", str(run_dir), "--json"])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        programs = summary["programs"]
        assert programs[0]["program"] == "serve/b8"
        assert programs[0]["wall_s_p50"] == pytest.approx(0.5)

    def test_cli_doctor_clean_run(self, tmp_path, capsys):
        from alphatriangle_tpu.cli import main as cli_main

        run_dir = self._run_dir(tmp_path)
        rc = cli_main(["doctor", str(run_dir)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "clean" in out

    def test_cli_doctor_missing_run_exits_2(self, tmp_path, capsys):
        from alphatriangle_tpu.cli import main as cli_main

        rc = cli_main(["doctor", "no_such_run", "--root-dir", str(tmp_path)])
        assert rc == 2


class TestCalibrationIntegration:
    def test_family_seconds_flow_into_calibration(self, tmp_path):
        from alphatriangle_tpu.autotune.model import (
            Calibration,
            merge_calibrations,
        )

        a = Calibration(family_seconds={"megastep": 2.0, "serve": 0.1})
        b = Calibration(family_seconds={"megastep": 4.0})
        merged = merge_calibrations([a, b])
        assert merged.family_seconds["megastep"] == pytest.approx(3.0)
        assert merged.family_seconds["serve"] == pytest.approx(0.1)
        assert merged.as_dict()["family_seconds"]["megastep"] == pytest.approx(3.0)


class TestWedgeExitCodeContract:
    def test_exit_code_outside_shell_ranges(self):
        """tpu_watch.sh branches on 113; it must stay clear of shell
        (1, 2, 126-165, 255) and doctor (0-6) codes."""
        assert WEDGE_EXIT_CODE == 113
        assert WEDGE_EXIT_CODE not in DOCTOR_EXIT_CODES.values()


class TestLegacyBeaconTolerance:
    """Run dirs from BEFORE the beacon channel existed (no
    beacons.jsonl, wedge reports without `last_beacon`) must classify
    and doctor exactly as they always did — no beacon line invented,
    no `last_beacon` key in the verdict."""

    def test_last_beacon_missing_file_is_none(self, tmp_path):
        from alphatriangle_tpu.telemetry.device_stats import last_beacon

        assert last_beacon(tmp_path) is None
        assert last_beacon(tmp_path / "ghost" / "beacons.jsonl") is None

    def test_classify_legacy_wedge_report_no_beacon_key(self):
        wedge = {
            "program": "megastep/t4_k2",
            "family": "megastep",
            "elapsed_s": 99.0,
            "deadline_s": 5.0,
        }
        v = classify_run([_intent(1), _seal(1), _intent(2)], wedge=wedge)
        assert v["verdict"] == "dispatch-hung"
        assert "last_beacon" not in v
        assert "last beacon" not in v["detail"]

    def test_classify_caller_beacon_fallback(self):
        """When the wedge report predates the beacon field, a caller-
        read beacons.jsonl row still names the phase."""
        wedge = {"program": "megastep/t4_k2", "family": "megastep",
                 "elapsed_s": 99.0, "deadline_s": 5.0}
        row = {"program": "megastep/t4_k2", "phase": "learner_step",
               "index": 7, "monotonic": 12.5}
        v = classify_run(
            [_intent(1), _seal(1), _intent(2)], wedge=wedge, beacon=row
        )
        assert v["last_beacon"] == row
        assert "last beacon" in v["detail"]
        assert "learner_step" in v["detail"]

    def test_cli_doctor_legacy_run_prints_no_beacon(self, tmp_path, capsys):
        from alphatriangle_tpu.cli import main as cli_main

        run = tmp_path / "legacy_run"
        run.mkdir()
        (run / FLIGHT_FILENAME).write_text(
            _flight_line(**_intent(1))
            + _flight_line(**_seal(1))
            + _flight_line(**_intent(2))
        )
        rc = cli_main(["doctor", str(run)])
        out = capsys.readouterr().out
        assert rc == DOCTOR_EXIT_CODES["dispatch-hung"]
        assert "beacon" not in out

    def test_cli_doctor_legacy_json_has_no_beacon_key(self, tmp_path, capsys):
        from alphatriangle_tpu.cli import main as cli_main

        run = tmp_path / "legacy_run_json"
        run.mkdir()
        (run / FLIGHT_FILENAME).write_text(
            _flight_line(**_intent(1))
            + _flight_line(**_seal(1))
            + _flight_line(**_intent(2))
        )
        rc = cli_main(["doctor", str(run), "--json"])
        verdict = json.loads(capsys.readouterr().out)
        assert rc == DOCTOR_EXIT_CODES["dispatch-hung"]
        assert "last_beacon" not in verdict
