"""Gumbel sequential-halving root search (mcts/gumbel.py).

Contract tests: candidate budgeting, improved-policy distribution
validity, valid/selected-action consistency, determinism, and the
self-play integration (policy targets come from completed-Q, actions
from the final-candidate argmax).
"""

import jax
import numpy as np
import pytest

from alphatriangle_tpu.config import TrainConfig
from alphatriangle_tpu.env.engine import TriangleEnv
from alphatriangle_tpu.features.core import get_feature_extractor
from alphatriangle_tpu.mcts import GumbelMCTS
from alphatriangle_tpu.nn.network import NeuralNetwork
from alphatriangle_tpu.rl import SelfPlayEngine

B = 4


@pytest.fixture(scope="module")
def gumbel_world(tiny_env_config, tiny_model_config, tiny_mcts_config):
    env = TriangleEnv(tiny_env_config)
    fe = get_feature_extractor(env, tiny_model_config)
    net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
    cfg = type(tiny_mcts_config)(
        **{
            **tiny_mcts_config.model_dump(),
            "root_selection": "gumbel",
            "gumbel_m": 4,
        }
    )
    mcts = GumbelMCTS(env, fe, net.model, cfg, net.support)
    return env, fe, net, cfg, mcts


def run_search(gumbel_world, seed=0):
    env, fe, net, cfg, mcts = gumbel_world
    keys = jax.random.split(jax.random.PRNGKey(seed), B)
    states = env.reset_batch(keys)
    out = mcts.search(
        net.variables, states, jax.random.PRNGKey(seed + 100)
    )
    return env, states, out


class TestGumbelSearch:
    def test_candidates_bound_visited_actions(self, gumbel_world):
        """Sequential halving only ever visits the m initial
        candidates at the root."""
        _, _, out = run_search(gumbel_world)
        visited = np.asarray(out.visit_counts > 0)
        assert (visited.sum(axis=1) <= 4).all()  # gumbel_m = 4
        assert (visited.sum(axis=1) >= 1).all()

    def test_improved_policy_is_valid_distribution(self, gumbel_world):
        env, states, out = run_search(gumbel_world)
        improved = np.asarray(out.improved_policy)
        valid = np.asarray(jax.vmap(env.valid_action_mask)(states))
        np.testing.assert_allclose(improved.sum(axis=1), 1.0, atol=1e-5)
        assert (improved >= 0).all()
        # No mass outside the valid action set.
        assert (improved[~valid] == 0).all()
        # The improvement operator scores ALL valid actions
        # (completed-Q), but with the paper's c_scale=1.0 sigma spans
        # hundreds of logits, so low-scoring UNVISITED actions can
        # legitimately underflow to exact 0 in float32 softmax. The
        # candidates the search actually visited must carry real mass
        # (their q fed the final scores):
        visited = np.asarray(out.visit_counts) > 0
        best_visited = np.where(
            visited, improved, -1.0
        ).max(axis=1)
        assert (best_visited > 0).all()

    def test_selected_action_is_valid(self, gumbel_world):
        env, states, out = run_search(gumbel_world)
        sel = np.asarray(out.selected_action)
        valid = np.asarray(jax.vmap(env.valid_action_mask)(states))
        done = np.asarray(states.done)
        for b in range(B):
            if not done[b]:
                assert sel[b] >= 0 and valid[b, sel[b]]

    def test_deterministic_given_seed(self, gumbel_world):
        _, _, out1 = run_search(gumbel_world, seed=3)
        _, _, out2 = run_search(gumbel_world, seed=3)
        np.testing.assert_array_equal(
            np.asarray(out1.selected_action), np.asarray(out2.selected_action)
        )
        np.testing.assert_allclose(
            np.asarray(out1.improved_policy),
            np.asarray(out2.improved_policy),
        )

    def test_small_wave_never_plays_unsimulated_action(
        self, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        """Regression: with wave_size < gumbel_m the candidate set must
        clamp to the wave size so the played action always has real
        simulations behind it (candidates outside the wave budget used
        to be halved/selected on sigma(q)=0 without ever being run)."""
        env = TriangleEnv(tiny_env_config)
        fe = get_feature_extractor(env, tiny_model_config)
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        cfg = type(tiny_mcts_config)(
            **{
                **tiny_mcts_config.model_dump(),
                "root_selection": "gumbel",
                "gumbel_m": 8,
                "max_simulations": 8,
                "mcts_batch_size": 2,  # wave_size 2 << gumbel_m
            }
        )
        mcts = GumbelMCTS(env, fe, net.model, cfg, net.support)
        keys = jax.random.split(jax.random.PRNGKey(5), B)
        states = env.reset_batch(keys)
        out = mcts.search(net.variables, states, jax.random.PRNGKey(9))
        sel = np.asarray(out.selected_action)
        visits = np.asarray(out.visit_counts)
        done = np.asarray(states.done)
        for b in range(B):
            if not done[b]:
                assert visits[b, sel[b]] > 0, (b, sel[b], visits[b])

    def test_exploit_mode_is_deterministic_across_rng(
        self, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        """exploit=True (PCR fast searches) zeroes the root Gumbel
        sample: with the descent wave noise also off, the whole search
        is deterministic — selection must not depend on the search rng.
        (With wave noise on, q estimates still vary benignly; the
        contract is no root EXPLORATION noise.)"""
        env = TriangleEnv(tiny_env_config)
        fe = get_feature_extractor(env, tiny_model_config)
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        cfg = type(tiny_mcts_config)(
            **{
                **tiny_mcts_config.model_dump(),
                "root_selection": "gumbel",
                "gumbel_m": 4,
                "wave_noise_scale": 0.0,
            }
        )
        mcts = GumbelMCTS(
            env, fe, net.model, cfg, net.support, exploit=True
        )
        keys = jax.random.split(jax.random.PRNGKey(2), B)
        states = env.reset_batch(keys)
        out1 = mcts.search(net.variables, states, jax.random.PRNGKey(1))
        out2 = mcts.search(net.variables, states, jax.random.PRNGKey(999))
        np.testing.assert_array_equal(
            np.asarray(out1.selected_action), np.asarray(out2.selected_action)
        )

    def test_no_dirichlet_noise_applied(self, gumbel_world):
        """GumbelMCTS zeroes dirichlet_epsilon internally."""
        *_, mcts = gumbel_world
        assert mcts.config.dirichlet_epsilon == 0.0


class TestGumbelSelfPlay:
    def test_end_to_end_rollout(
        self, tiny_env_config, tiny_model_config, tiny_mcts_config
    ):
        env = TriangleEnv(tiny_env_config)
        fe = get_feature_extractor(env, tiny_model_config)
        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        cfg = type(tiny_mcts_config)(
            **{
                **tiny_mcts_config.model_dump(),
                "root_selection": "gumbel",
                "gumbel_m": 4,
            }
        )
        tc = TrainConfig(
            BATCH_SIZE=4,
            BUFFER_CAPACITY=5000,
            MIN_BUFFER_SIZE_TO_TRAIN=8,
            USE_PER=False,
            N_STEP_RETURNS=2,
            MAX_EPISODE_MOVES=30,
            SELF_PLAY_BATCH_SIZE=4,
            MAX_TRAINING_STEPS=100,
            RUN_NAME="gumbel_sp",
        )
        engine = SelfPlayEngine(env, fe, net, cfg, tc, seed=11)
        assert isinstance(engine.mcts, GumbelMCTS)
        result = engine.play_moves(12)
        assert result.num_experiences > 0
        np.testing.assert_allclose(
            result.policy_target.sum(axis=1), 1.0, atol=1e-4
        )
        assert np.all(np.isfinite(result.value_target))
