"""Compile-latency subsystem tests (compile_cache.py, warm.py, cli warm).

Cold-vs-warm is asserted via the cache's hit/miss counters and the
on-disk executable files — never wall clock (CI machines make timing
assertions flaky). The cross-PROCESS reuse property is exercised
in-process by resetting the process-global cache between engines: a
fresh `CompileCache` has no in-memory executables, so a hit can only
come from deserializing the serialized artifact, exactly what a new
process would do.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphatriangle_tpu.compile_cache import (
    CompileCache,
    config_digest,
    get_compile_cache,
    reset_compile_cache,
)


@pytest.fixture(autouse=True)
def no_xla_persistent_cache():
    """Disable the XLA persistent cache for this module (conftest turns
    it on for suite speed). This mirrors the real CPU environment —
    `enable_persistent_compilation_cache` skips CPU — and matters for
    correctness here: an executable that compile() loads FROM the
    persistent cache serializes to a truncated payload on XLA:CPU, so
    with it on, fresh AOT artifacts could never be published (the
    validation round trip in `_serialize` rejects them).

    jax LATCHES cache-used at the first compile of the process, so in
    a full-suite run (where earlier test files already compiled through
    the cache) flipping the config alone does nothing: the latch must
    be reset too (`compilation_cache.reset_cache`)."""
    from jax._src import compilation_cache as _cc

    jax.config.update("jax_enable_compilation_cache", False)
    _cc.reset_cache()
    yield
    jax.config.update("jax_enable_compilation_cache", True)
    _cc.reset_cache()  # re-latch with the cache enabled for later tests


@pytest.fixture()
def fresh_cache(tmp_path):
    """Point the process-global cache at an empty tmp dir; restore the
    default afterwards so other tests keep their shared cache."""
    cache = reset_compile_cache(cache_dir=str(tmp_path / "aot"))
    yield cache
    reset_compile_cache()


def _double(x):
    return x * 2.0


class TestCachedProgram:
    def test_roundtrip_serialize_deserialize_cpu(self, fresh_cache):
        """An executable serialized by one cache instance is
        deserialized (hit) by a fresh instance — the cross-process
        path — and computes the same answer."""
        x = jnp.arange(6.0).reshape(2, 3)
        prog = fresh_cache.wrap("t/double", jax.jit(_double))
        cold = np.asarray(prog(x))
        assert fresh_cache.misses == 1 and fresh_cache.hits == 0
        files = list(fresh_cache.cache_dir.glob("*.jaxexe"))
        assert len(files) == 1  # serialized artifact on disk

        second = CompileCache(cache_dir=str(fresh_cache.cache_dir))
        prog2 = second.wrap("t/double", jax.jit(_double))
        warm = np.asarray(prog2(x))
        assert second.hits == 1 and second.misses == 0
        np.testing.assert_array_equal(cold, warm)

    def test_warm_populates_without_executing(self, fresh_cache):
        calls = []

        def spy(x):
            calls.append(1)
            return x + 1

        prog = fresh_cache.wrap("t/spy", jax.jit(spy))
        x = jnp.ones((3,))
        assert prog.warm(x) is True  # compiles + serializes
        assert fresh_cache.misses == 1
        # warm() traced (to lower) but never executed on real data;
        # the later call reuses the in-memory executable (no new event).
        out = np.asarray(prog(x))
        np.testing.assert_array_equal(out, np.full(3, 2.0))
        assert fresh_cache.misses == 1 and fresh_cache.hits == 0

    def test_shape_mismatch_is_fresh_compile_not_hit(self, fresh_cache):
        prog = fresh_cache.wrap("t/double", jax.jit(_double))
        prog(jnp.ones((2, 3)))
        second = CompileCache(cache_dir=str(fresh_cache.cache_dir))
        prog2 = second.wrap("t/double", jax.jit(_double))
        # Different shape -> different signature -> miss, new artifact.
        prog2(jnp.ones((4, 5)))
        assert second.hits == 0 and second.misses == 1
        assert len(list(second.cache_dir.glob("*.jaxexe"))) == 2
        # Same shape again -> hit against the first artifact.
        prog3 = CompileCache(cache_dir=str(fresh_cache.cache_dir)).wrap(
            "t/double", jax.jit(_double)
        )
        prog3(jnp.ones((2, 3)))

    def test_config_extra_splits_the_key(self, fresh_cache):
        x = jnp.ones((2, 2))
        fresh_cache.wrap("t/double", jax.jit(_double), extra="cfgA")(x)
        second = CompileCache(cache_dir=str(fresh_cache.cache_dir))
        second.wrap("t/double", jax.jit(_double), extra="cfgB")(x)
        # Same avals, different config digest: must NOT reuse.
        assert second.hits == 0 and second.misses == 1

    def test_corrupt_artifact_degrades_to_recompile(self, fresh_cache):
        x = jnp.ones((2, 2))
        fresh_cache.wrap("t/double", jax.jit(_double))(x)
        (artifact,) = fresh_cache.cache_dir.glob("*.jaxexe")
        artifact.write_bytes(b"not a pickle")
        second = CompileCache(cache_dir=str(fresh_cache.cache_dir))
        out = second.wrap("t/double", jax.jit(_double))(x)
        np.testing.assert_array_equal(np.asarray(out), np.full((2, 2), 2.0))
        assert second.deserialize_errors == 1
        assert second.misses == 1 and second.hits == 0

    def test_disabled_cache_delegates_to_jit(self, tmp_path):
        cache = CompileCache(cache_dir=str(tmp_path), enabled=False)
        prog = cache.wrap("t/double", jax.jit(_double))
        out = prog(jnp.ones((2, 2)))
        np.testing.assert_array_equal(np.asarray(out), np.full((2, 2), 2.0))
        assert cache.hits == cache.misses == 0
        assert not list(tmp_path.glob("*.jaxexe"))

    def test_donated_args_work_through_the_aot_path(self, fresh_cache):
        def bump(state, dx):
            return state + dx

        prog = fresh_cache.wrap(
            "t/donate", jax.jit(bump, donate_argnums=(0,))
        )
        state = jnp.zeros((4,))
        for i in range(3):  # state threads through donated calls
            state = prog(state, jnp.ones((4,)))
        np.testing.assert_array_equal(np.asarray(state), np.full(4, 3.0))

    def test_compile_spans_reach_the_tracer(self, fresh_cache):
        from alphatriangle_tpu.telemetry import SpanTracer

        tracer = SpanTracer(enabled=True)
        fresh_cache.set_tracer(tracer)
        fresh_cache.wrap("t/double", jax.jit(_double))(jnp.ones((2,)))
        names = {s[1] for s in tracer._snapshot()}
        assert "compile/t/double" in names

    def test_config_digest_ignores_run_name(self, tiny_train_config):
        a = config_digest(tiny_train_config)
        b = config_digest(
            tiny_train_config.model_copy(update={"RUN_NAME": "other"})
        )
        c = config_digest(
            tiny_train_config.model_copy(update={"GAMMA": 0.5})
        )
        assert a == b
        assert a != c


class TestEngineAndTrainerReuse:
    """The acceptance property: the rollout-chunk and learner programs
    are genuinely reused across cache instances (counter-proven)."""

    def _engine(self, env_cfg, model_cfg, mcts_cfg, train_cfg, seed=0):
        from alphatriangle_tpu.env.engine import TriangleEnv
        from alphatriangle_tpu.features.core import get_feature_extractor
        from alphatriangle_tpu.nn.network import NeuralNetwork
        from alphatriangle_tpu.rl import SelfPlayEngine

        env = TriangleEnv(env_cfg)
        extractor = get_feature_extractor(env, model_cfg)
        net = NeuralNetwork(model_cfg, env_cfg, seed=seed)
        return SelfPlayEngine(
            env, extractor, net, mcts_cfg, train_cfg, seed=seed
        )

    def test_rollout_chunk_cold_then_warm(
        self,
        tmp_path,
        tiny_env_config,
        tiny_model_config,
        tiny_mcts_config,
        tiny_train_config,
    ):
        cache_dir = str(tmp_path / "aot")
        try:
            cold = reset_compile_cache(cache_dir=cache_dir)
            e1 = self._engine(
                tiny_env_config,
                tiny_model_config,
                tiny_mcts_config,
                tiny_train_config,
            )
            e1.play_chunk(2)
            assert cold.misses >= 1 and cold.hits == 0

            warm = reset_compile_cache(cache_dir=cache_dir)
            e2 = self._engine(
                tiny_env_config,
                tiny_model_config,
                tiny_mcts_config,
                tiny_train_config,
            )
            e2.play_chunk(2)  # same shapes -> deserialized executable
            assert warm.hits == 1 and warm.misses == 0
            r = e2.harvest()
            assert r.num_episodes >= 0  # the reused program really ran
        finally:
            reset_compile_cache()

    def test_warm_chunk_then_play_needs_no_more_compiles(
        self,
        tmp_path,
        tiny_env_config,
        tiny_model_config,
        tiny_mcts_config,
        tiny_train_config,
    ):
        try:
            cache = reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            engine = self._engine(
                tiny_env_config,
                tiny_model_config,
                tiny_mcts_config,
                tiny_train_config,
            )
            assert engine.warm_chunk(2) is True
            events_after_warm = len(cache.events)
            engine.play_chunk(2)
            # Dispatch found the warmed executable: no new compile event.
            assert len(cache.events) == events_after_warm
        finally:
            reset_compile_cache()

    def test_trainer_step_cold_then_warm(
        self, tmp_path, tiny_env_config, tiny_model_config, tiny_train_config
    ):
        from alphatriangle_tpu.nn.network import NeuralNetwork
        from alphatriangle_tpu.rl import Trainer

        b = tiny_train_config.BATCH_SIZE
        rng = np.random.default_rng(0)
        batch = {
            "grid": rng.random(
                (b, 1, tiny_env_config.ROWS, tiny_env_config.COLS)
            ).astype(np.float32),
            "other_features": rng.random(
                (b, tiny_model_config.OTHER_NN_INPUT_FEATURES_DIM)
            ).astype(np.float32),
            "policy_target": np.full(
                (b, tiny_env_config.action_dim),
                1.0 / tiny_env_config.action_dim,
                np.float32,
            ),
            "value_target": np.zeros(b, np.float32),
            "weights": np.ones(b, np.float32),
        }
        cache_dir = str(tmp_path / "aot")
        try:
            # Learner programs NEVER ride the AOT artifact path on the
            # CPU backend (trainer wraps with cpu_aot=False): an XLA:CPU
            # deserialized learner executable runs without error but
            # returns the donated train state UNCHANGED — params stop
            # updating silently. This test is the regression lock: no
            # artifacts, no hits, and the second trainer still LEARNS.
            cold = reset_compile_cache(cache_dir=cache_dir)
            net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
            t1 = Trainer(net, tiny_train_config)
            assert t1.aot_enabled is False  # CPU bypass active
            out1 = t1.train_step(dict(batch))
            assert out1 is not None
            assert cold.misses == 0 and cold.hits == 0
            assert not list(Path(cache_dir).glob("learner_step-*.jaxexe"))

            warm = reset_compile_cache(cache_dir=cache_dir)
            net2 = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
            t2 = Trainer(net2, tiny_train_config)
            before = jax.tree_util.tree_map(
                np.asarray, t2.state.params
            )
            out2 = t2.train_step(dict(batch))
            assert out2 is not None
            assert warm.hits == 0 and warm.misses == 0
            # Same seed, same batch, fresh compile: same loss...
            assert out1[0]["total_loss"] == pytest.approx(
                out2[0]["total_loss"], rel=1e-5
            )
            # ...and the step genuinely updated the params (the exact
            # thing a reloaded CPU executable silently failed to do).
            changed = jax.tree_util.tree_map(
                lambda a, b: not np.allclose(a, np.asarray(b)),
                before,
                t2.state.params,
            )
            assert any(jax.tree_util.tree_leaves(changed))
        finally:
            reset_compile_cache()

    def test_fused_steps_warm_covers_dispatch(
        self, tmp_path, tiny_env_config, tiny_model_config, tiny_train_config
    ):
        from alphatriangle_tpu.nn.network import NeuralNetwork
        from alphatriangle_tpu.rl import Trainer

        try:
            cache = reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
            trainer = Trainer(net, tiny_train_config)
            # CPU backend: learner warming reports not-AOT (cpu_aot
            # bypass — reloads corrupt donated state) and records no
            # cache events; the fused path still runs correctly via
            # the plain jitted program.
            assert trainer.warm_steps(3) is False
            assert len(cache.events) == 0
            b = tiny_train_config.BATCH_SIZE
            batch = trainer._zero_batch(b)
            results = trainer.train_steps([dict(batch)] * 3)
            assert len(results) == 3
            assert len(cache.events) == 0  # bypass never touches cache
        finally:
            reset_compile_cache()


class TestBenchPlan:
    def test_plan_matches_bench_scales(self):
        from alphatriangle_tpu.bench_config import resolve_bench_plan

        smoke = resolve_bench_plan(True, "cpu", environ={})
        assert (smoke.scale, smoke.sims, smoke.sp_batch) == ("smoke", 8, 16)
        assert smoke.fused_k == smoke.overlap_k == 4
        assert smoke.device_replay is False

        cpu = resolve_bench_plan(False, "cpu", environ={})
        assert (cpu.scale, cpu.sp_batch, cpu.chunk) == ("cpu", 64, 4)

        tpu = resolve_bench_plan(False, "tpu", environ={})
        assert (tpu.scale, tpu.sp_batch, tpu.lbatch) == ("flagship", 512, 256)
        assert tpu.mcts.root_selection == "gumbel"
        assert (tpu.fused_k, tpu.overlap_k, tpu.device_replay) == (16, 64, True)

    def test_plan_honors_ab_knobs(self):
        from alphatriangle_tpu.bench_config import resolve_bench_plan

        plan = resolve_bench_plan(
            False,
            "tpu",
            environ={"BENCH_RECIPE": "puct", "BENCH_BATCH": "256"},
        )
        assert plan.mcts.root_selection == "puct"
        assert plan.sp_batch == 256

        with pytest.raises(SystemExit):
            resolve_bench_plan(
                False, "tpu", environ={"BENCH_RECIPE": "bogus"}
            )

    def test_preset_plan_builds(self):
        from alphatriangle_tpu.bench_config import resolve_bench_plan

        plan = resolve_bench_plan(
            False, "cpu", environ={"BENCH_CONFIG": "1"}
        )
        assert plan.scale == "baseline_config_1"
        assert plan.sp_batch <= 64  # cpu lane clamp
        assert plan.train.ROLLOUT_CHUNK_MOVES == 4


class TestWarmCLI:
    def test_cli_warm_smoke(
        self,
        tmp_path,
        monkeypatch,
        capsys,
        tiny_env_config,
        tiny_model_config,
        tiny_mcts_config,
        tiny_train_config,
    ):
        """`cli warm` end to end on a tiny plan: compiles the rollout
        chunk + learner programs, serializes them, prints a JSON report,
        and a second invocation is all hits."""
        from alphatriangle_tpu import cli
        from alphatriangle_tpu.bench_config import BenchPlan

        def tiny_plan(smoke, backend, environ=None):
            return BenchPlan(
                env=tiny_env_config,
                model=tiny_model_config,
                mcts=tiny_mcts_config,
                train=tiny_train_config,
                scale="tiny",
                sims=tiny_mcts_config.max_simulations,
                sp_batch=tiny_train_config.SELF_PLAY_BATCH_SIZE,
                chunk=tiny_train_config.ROLLOUT_CHUNK_MOVES,
                lbatch=tiny_train_config.BATCH_SIZE,
                fused_k=2,
                overlap_k=2,
                device_replay=False,
            )

        monkeypatch.setattr(
            "alphatriangle_tpu.bench_config.resolve_bench_plan", tiny_plan
        )
        try:
            reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            rc = cli.main(["warm", "smoke", "--jobs", "2"])
            out = capsys.readouterr().out
            report = json.loads(out.strip().splitlines()[-1])
            assert rc == 0
            # CPU backend: the rollout chunk AOT-warms; the learner
            # programs are deliberately skipped (cpu_aot bypass —
            # reloaded learner executables corrupt donated state).
            statuses = {r["program"]: r["status"] for r in report["programs"]}
            assert len(statuses) >= 3
            aot = [p for p, s in statuses.items() if s == "aot"]
            skipped = [p for p, s in statuses.items() if s == "skipped-cpu"]
            assert aot and all(p.startswith("self_play") for p in aot)
            # The learner family AND the megastep (which embeds learner
            # steps) are CPU-bypassed.
            assert skipped and all(
                p.startswith(("learner", "megastep")) for p in skipped
            )
            assert any(p.startswith("megastep") for p in skipped)
            assert set(statuses.values()) == {"aot", "skipped-cpu"}
            assert report["stats"]["misses"] == len(aot)

            reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            rc2 = cli.main(["warm", "smoke", "--jobs", "2"])
            report2 = json.loads(
                capsys.readouterr().out.strip().splitlines()[-1]
            )
            assert rc2 == 0
            assert report2["stats"]["hits"] == len(aot)
            assert report2["stats"]["misses"] == 0
        finally:
            reset_compile_cache()

    def test_cli_warm_program_filter(
        self,
        tmp_path,
        monkeypatch,
        capsys,
        tiny_env_config,
        tiny_model_config,
        tiny_mcts_config,
        tiny_train_config,
    ):
        from alphatriangle_tpu import cli
        from alphatriangle_tpu.bench_config import BenchPlan

        monkeypatch.setattr(
            "alphatriangle_tpu.bench_config.resolve_bench_plan",
            lambda smoke, backend, environ=None: BenchPlan(
                env=tiny_env_config,
                model=tiny_model_config,
                mcts=tiny_mcts_config,
                train=tiny_train_config,
                scale="tiny",
                sims=8,
                sp_batch=4,
                chunk=4,
                lbatch=4,
                fused_k=2,
                overlap_k=2,
            ),
        )
        try:
            reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            rc = cli.main(
                ["warm", "smoke", "--programs", "self_play", "--jobs", "1"]
            )
            report = json.loads(
                capsys.readouterr().out.strip().splitlines()[-1]
            )
            assert rc == 0
            assert [r["program"] for r in report["programs"]] == [
                "self_play_chunk/t4"
            ]

            # Filtering down to CPU-skipped learner programs leaves
            # nothing warmable: reported, and exit 1 ("nothing warm").
            reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            rc2 = cli.main(
                ["warm", "smoke", "--programs", "learner_step", "--jobs", "1"]
            )
            report2 = json.loads(
                capsys.readouterr().out.strip().splitlines()[-1]
            )
            assert rc2 == 1
            assert [r["status"] for r in report2["programs"]] == [
                "skipped-cpu"
            ]
        finally:
            reset_compile_cache()


class TestGlobalCache:
    def test_global_accessor_is_a_singleton(self):
        try:
            a = reset_compile_cache()
            assert get_compile_cache() is a
        finally:
            reset_compile_cache()
