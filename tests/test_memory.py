"""Memory observability (telemetry/memory.py; docs/OBSERVABILITY.md
"Memory"): estimator math, AOT memory_analysis capture, the leak
detector, live accounting, and the `cli fit`/`cli mem` surfaces —
including the acceptance bar that the static pre-flight estimate lands
within 2x of a real smoke run's observed peak."""

import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphatriangle_tpu.compile_cache import reset_compile_cache
from alphatriangle_tpu.config import PersistenceConfig, TrainConfig
from alphatriangle_tpu.telemetry.anomaly import AnomalyDetector
from alphatriangle_tpu.telemetry.memory import (
    attribution_rows,
    compose_budget,
    estimate_fit,
    fit_verdict,
    fmt_bytes,
    program_memory_record,
    replay_ring_bytes,
    replay_ring_record,
    summarize_device_memory,
    train_state_record,
    tree_bytes,
)
from alphatriangle_tpu.telemetry.perf import (
    LOWER_IS_BETTER,
    UtilizationMeter,
    compare_summaries,
    summarize_utilization,
)


class TestRingBytes:
    def test_matches_allocated_device_ring(
        self, tiny_train_config, tiny_env_config, tiny_model_config
    ):
        """The pure byte math must equal the bytes the single-device
        ring actually allocates (dtype/shape drift here would skew
        every fit estimate)."""
        from alphatriangle_tpu.rl.device_buffer import DeviceReplayBuffer

        grid_shape = (
            tiny_model_config.GRID_INPUT_CHANNELS,
            tiny_env_config.ROWS,
            tiny_env_config.COLS,
        )
        buf = DeviceReplayBuffer(
            tiny_train_config,
            grid_shape=grid_shape,
            other_dim=7,
            action_dim=tiny_env_config.action_dim,
        )
        est = replay_ring_bytes(
            tiny_train_config.BUFFER_CAPACITY,
            grid_shape,
            7,
            tiny_env_config.action_dim,
        )
        assert buf.storage_nbytes() == est
        rec = buf.memory_record()
        assert rec["kind"] == "memory"
        assert rec["category"] == "ring"
        assert rec["total"] == est
        assert rec["location"] == "device"
        assert rec["shards"] == 1

    def test_sharded_ring_counts_per_shard_trash_rows(self):
        # 4 shards => 4 trash rows; the single-shard math has 1.
        one = replay_ring_bytes(1024, (1, 3, 4), 8, 12, shards=1)
        four = replay_ring_bytes(1024, (1, 3, 4), 8, 12, shards=4)
        row = 1 * 3 * 4 + 4 * 8 + 4 * 12 + 4 + 4
        assert four - one == 3 * row


class TestTreeAccounting:
    def test_tree_bytes_exact(self):
        tree = {
            "a": jnp.zeros((4, 5), jnp.float32),
            "b": jnp.zeros(7, jnp.int8),
            "c": None,
        }
        assert tree_bytes(tree) == 4 * 5 * 4 + 7

    def test_train_state_record_splits_params_and_opt(
        self, tiny_env_config, tiny_model_config, tiny_train_config
    ):
        from alphatriangle_tpu.nn.network import NeuralNetwork
        from alphatriangle_tpu.rl import Trainer

        net = NeuralNetwork(tiny_model_config, tiny_env_config, seed=0)
        trainer = Trainer(net, tiny_train_config)
        rec = train_state_record(trainer.state)
        assert rec["category"] == "state"
        assert rec["bytes"]["params"] == tree_bytes(trainer.state.params)
        assert rec["bytes"]["opt_state"] > 0  # adam moments exist
        # total covers params + opt + batch_stats + step/rng leaves
        assert rec["total"] >= sum(
            v for v in rec["bytes"].values() if isinstance(v, int)
        )


class TestProgramCapture:
    def test_capture_on_compile_and_sidecar_on_hit(self, tmp_path):
        """A wrapped program's memory_analysis is recorded at compile
        time, persisted beside the executable, and reloaded from the
        sidecar on a cross-process AOT hit."""
        cache = reset_compile_cache(cache_dir=str(tmp_path / "aot"))
        try:
            fn = cache.wrap("memtest", jax.jit(lambda x: x @ x + 1.0))
            x = jnp.ones((16, 16), jnp.float32)
            np.testing.assert_allclose(fn(x), np.ones((16, 16)) * 17.0)
            recs = cache.memory_summary()
            assert len(recs) == 1
            rec = recs[0]
            assert rec["program"] == "memtest"
            assert rec["bytes"]["argument"] == 16 * 16 * 4
            assert rec["bytes"]["output"] == 16 * 16 * 4
            assert rec["origin"] == "compile"
            sidecars = list((tmp_path / "aot").glob("*.mem.json"))
            assert len(sidecars) == 1
            assert json.loads(sidecars[0].read_text())["program"] == "memtest"

            # Fresh cache object, same dir: the AOT hit re-attributes
            # from the persisted sidecar without re-analyzing.
            cache2 = reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            fn2 = cache2.wrap("memtest", jax.jit(lambda x: x @ x + 1.0))
            fn2(x)
            assert cache2.hits == 1
            [rec2] = cache2.memory_summary()
            assert rec2["origin"] == "sidecar"
            assert rec2["bytes"] == rec["bytes"]
        finally:
            reset_compile_cache()

    def test_analyze_works_on_cpu_bypassed_program(self, tmp_path):
        """cpu_aot=False programs (the learner family on XLA:CPU) never
        touch the AOT artifact path, but `analyze` still produces a
        memory record — compiling fresh for analysis only, executing
        nothing, serializing nothing."""
        calls = []

        def impl(x):
            calls.append(1)
            return x * 2.0

        cache = reset_compile_cache(cache_dir=str(tmp_path / "aot"))
        try:
            fn = cache.wrap("bypassed", jax.jit(impl), cpu_aot=False)
            assert not fn.aot_active
            rec = fn.analyze(jnp.ones(8, jnp.float32))
            assert rec is not None
            assert rec["bytes"]["argument"] == 32
            # Tracing happened (impl ran under trace) but nothing was
            # dispatched and no artifact/sidecar was written.
            assert list((tmp_path / "aot").glob("*.jaxexe")) == []
            assert list((tmp_path / "aot").glob("*.mem.json")) == []
            # A second analyze is a registry hit, no recompile/retrace.
            calls.clear()
            assert fn.analyze(jnp.ones(8, jnp.float32)) == rec
            assert calls == []
        finally:
            reset_compile_cache()


class TestComposeBudget:
    def _records(self):
        return [
            {
                "kind": "memory",
                "category": "state",
                "component": "train_state",
                "bytes": {"params": 100, "opt_state": 200, "batch_stats": 0},
                "total": 308,
            },
            replay_ring_record(5000, 128, location="device"),
            {
                "kind": "memory",
                "category": "program",
                "component": "program/self_play_chunk/t4",
                "program": "self_play_chunk/t4",
                "bytes": {"argument": 700, "output": 50, "temp": 40,
                          "generated_code": 0, "alias": 10},
                "total": 790,
                "transient": 80,
            },
            {
                "kind": "memory",
                "category": "program",
                "component": "program/learner_step",
                "program": "learner_step",
                "bytes": {"argument": 400, "output": 320, "temp": 90,
                          "generated_code": 0, "alias": 300},
                "total": 810,
                "transient": 110,
            },
        ]

    def test_composition(self):
        budget = compose_budget(self._records())
        assert budget["train_state_bytes"] == 308
        assert budget["replay_ring_bytes"] == 5000
        # chunk argument (700) minus shared params (100)
        assert budget["rollout_resident_bytes"] == 600
        # worst transient: learner 110 vs chunk 80
        assert budget["program_transient_bytes"] == 110
        assert budget["total_bytes"] == 308 + 5000 + 600 + 110
        assert budget["programs"] == 2

    def test_host_ring_excluded(self):
        recs = self._records()
        recs[1] = replay_ring_record(5000, 128, location="host")
        assert compose_budget(recs)["replay_ring_bytes"] == 0

    def test_latest_record_wins_and_rows_sorted(self):
        recs = self._records()
        recs.append(dict(recs[0], total=999, bytes={"params": 999}))
        rows = attribution_rows(recs)
        by_name = {r[0]: r[1] for r in rows}
        assert by_name["train_state"] == 999
        assert [r[1] for r in rows] == sorted(
            (r[1] for r in rows), reverse=True
        )

    def test_fit_verdict_codes(self):
        assert fit_verdict(100, 1000)[0] == 0
        assert fit_verdict(2000, 1000)[0] == 1
        assert fit_verdict(100, None)[0] == 2
        assert fit_verdict(100, 0)[0] == 2

    def test_fmt_bytes(self):
        assert fmt_bytes(None) == "—"
        assert fmt_bytes(512) == "512 B"
        assert fmt_bytes(3 * 2**30) == "3.00 GiB"


class TestLeakDetector:
    def test_monotonic_growth_fires_once_and_rearms(self):
        det = AnomalyDetector(
            memory_growth_ticks=4, memory_growth_fraction=0.05
        )
        fired = []
        value = 1000.0
        for step in range(10):
            value *= 1.03  # strictly growing, ~3%/tick
            fired += det.observe_memory(value, step)
        assert len(fired) == 1
        assert fired[0].kind == "memory_growth"
        assert fired[0].metric == "Memory/bytes_in_use"
        assert "leak" in fired[0].describe()
        # Latched: continued growth in the same excursion stays quiet.
        assert det.observe_memory(value * 1.5, 10) == []
        # A release re-arms and restarts the monotonic run.
        assert det.observe_memory(value * 0.5, 11) == []
        v = value * 0.5
        fired2 = []
        for step in range(12, 20):
            v *= 1.05
            fired2 += det.observe_memory(v, step)
        assert len(fired2) == 1

    def test_sawtooth_and_flat_stay_quiet(self):
        det = AnomalyDetector(
            memory_growth_ticks=4, memory_growth_fraction=0.05
        )
        out = []
        for step in range(40):
            # healthy allocator: climbs 3 ticks, releases
            v = 1000 + 100 * (step % 4)
            out += det.observe_memory(v, step)
        assert out == []
        det2 = AnomalyDetector(memory_growth_ticks=4)
        assert all(
            det2.observe_memory(500.0, s) == [] for s in range(20)
        )

    def test_tiny_monotonic_drift_below_fraction_stays_quiet(self):
        det = AnomalyDetector(
            memory_growth_ticks=4, memory_growth_fraction=0.5
        )
        v = 1000.0
        out = []
        for step in range(20):
            v += 1  # monotonic but far below the 50% growth floor
            out += det.observe_memory(v, step)
        assert out == []


class TestLiveAccounting:
    def test_meter_memory_fields_and_high_water(self):
        t = {"now": 0.0}
        meter = UtilizationMeter(device_kind="cpu", clock=lambda: t["now"])

        def dev(in_use, peak=None, limit=1000):
            return [
                {
                    "device": 0,
                    "kind": "cpu",
                    "bytes_in_use": in_use,
                    "peak_bytes_in_use": peak,
                    "bytes_limit": limit,
                }
            ]

        meter.tick(step=0, device_memory=dev(500))
        t["now"] = 1.0
        rec = meter.tick(step=1, device_memory=dev(400))
        # High water remembers the baseline tick's 500 even though the
        # current in-use dropped to 400.
        assert rec["mem_bytes_in_use"] == 400
        assert rec["mem_peak_bytes_in_use"] == 500
        assert rec["mem_bytes_limit"] == 1000
        assert rec["mem_utilization"] == pytest.approx(0.4)
        assert rec["mem_devices"][0]["bytes_in_use"] == 400
        # A backend-reported peak above the high water wins.
        t["now"] = 2.0
        rec = meter.tick(step=2, device_memory=dev(450, peak=900))
        assert rec["mem_peak_bytes_in_use"] == 900

    def test_meter_without_memory_keeps_schema(self):
        t = {"now": 0.0}
        meter = UtilizationMeter(device_kind="cpu", clock=lambda: t["now"])
        meter.tick(step=0)
        t["now"] = 1.0
        rec = meter.tick(step=1)
        assert "mem_bytes_in_use" not in rec

    def test_summarize_device_memory_totals(self):
        rows = [
            {"bytes_in_use": 10, "peak_bytes_in_use": 20, "bytes_limit": 100},
            {"bytes_in_use": 5, "peak_bytes_in_use": None, "bytes_limit": None},
        ]
        totals = summarize_device_memory(rows)
        assert totals == {
            "bytes_in_use": 15,
            "peak_bytes_in_use": 25,  # missing peak falls back to in-use
            "bytes_limit": 100,
        }
        assert summarize_device_memory([]) is None

    def test_cpu_device_memory_synthesized_from_live_arrays(self):
        from alphatriangle_tpu.telemetry.health import device_memory_stats

        anchor = jnp.ones((128, 128), jnp.float32)  # keep alive
        stats = device_memory_stats()
        assert stats, "CPU fallback should synthesize per-device rows"
        row = stats[0]
        assert row.get("source") == "live_arrays"
        assert row["bytes_in_use"] >= anchor.nbytes
        assert row["bytes_limit"] and row["bytes_limit"] > 0
        del anchor

    def test_compare_memory_metrics_lower_is_better(self):
        a = {"mem_peak_bytes_in_use": 2000, "memory_budget_bytes": 100}
        b = {"mem_peak_bytes_in_use": 1000, "memory_budget_bytes": 100}
        rows, regressions = compare_summaries(a, b, threshold=0.1)
        verdicts = {m: status for m, _, _, _, status in rows}
        assert verdicts["mem_peak_bytes_in_use"] == "regression"
        assert "mem_peak_bytes_in_use" in regressions
        assert verdicts["memory_budget_bytes"] == "ok"
        # Shrinking memory is an improvement, not a regression.
        rows, regressions = compare_summaries(b, a, threshold=0.1)
        assert {m: s for m, _, _, _, s in rows}[
            "mem_peak_bytes_in_use"
        ] == "improved"
        assert regressions == []
        assert LOWER_IS_BETTER <= {m for m, *_ in rows}


class TestRenderers:
    def test_watch_memory_line(self):
        from alphatriangle_tpu.stats.watch import WatchState, memory_line, render_frame

        util = {
            "mem_bytes_in_use": 2 * 2**30,
            "mem_peak_bytes_in_use": 3 * 2**30,
            "mem_bytes_limit": 16 * 2**30,
            "mem_utilization": 0.125,
        }
        line = memory_line(util)
        assert "2.00 GiB in use" in line
        assert "peak 3.00 GiB" in line
        assert "limit 16.00 GiB (12.5%)" in line
        assert memory_line({"mfu": 0.5}) is None
        state = WatchState()
        state.util = dict(util, kind="util")
        assert "memory" in render_frame(state, "r")

    def test_cli_health_prints_peak(self, tmp_path, capsys):
        from alphatriangle_tpu.cli import main as cli_main

        run_dir = tmp_path / "AlphaTriangleTPU" / "runs" / "hrun"
        run_dir.mkdir(parents=True)
        import time as _time

        (run_dir / "health.json").write_text(
            json.dumps(
                {
                    "run": "hrun",
                    "time": _time.time(),
                    "watchdog_deadline_s": 300,
                    "learner_step": 3,
                    "device_memory": [
                        {
                            "device": 0,
                            "kind": "TPU v4",
                            "bytes_in_use": 2**30,
                            "peak_bytes_in_use": 2 * 2**30,
                            "bytes_limit": 4 * 2**30,
                        }
                    ],
                }
            )
        )
        rc = cli_main(["health", "hrun", "--root-dir", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "peak 2.00 GiB" in out
        assert "/ 4.00 GiB (25%)" in out


class TestFitCLI:
    def test_cli_fit_tiny_plan_fits_on_cpu(
        self,
        tmp_path,
        monkeypatch,
        capsys,
        tiny_env_config,
        tiny_model_config,
        tiny_mcts_config,
        tiny_train_config,
    ):
        from alphatriangle_tpu import cli
        from alphatriangle_tpu.bench_config import BenchPlan
        from alphatriangle_tpu.rl.megastep import MegastepRunner

        # `cli fit` also analyzes the fused-megastep program; stub it
        # here (its real compile/record path is pinned in
        # tests/test_megastep.py) so this test stays inside the tier-1
        # compile budget while still proving the wiring reaches it.
        monkeypatch.setattr(
            MegastepRunner,
            "analyze_megastep",
            lambda self, t=None, k=None: {
                "kind": "memory",
                "category": "program",
                "component": "program/megastep/t4_k2",
                "program": "megastep/t4_k2",
                "bytes": {"argument": 64, "output": 8, "temp": 8,
                          "generated_code": 0},
                "total": 80,
                "transient": 16,
            },
        )
        monkeypatch.setattr(
            "alphatriangle_tpu.bench_config.resolve_bench_plan",
            lambda smoke, backend, environ=None: BenchPlan(
                env=tiny_env_config,
                model=tiny_model_config,
                mcts=tiny_mcts_config,
                train=tiny_train_config,
                scale="tiny",
                sims=tiny_mcts_config.max_simulations,
                sp_batch=tiny_train_config.SELF_PLAY_BATCH_SIZE,
                chunk=tiny_train_config.ROLLOUT_CHUNK_MOVES,
                lbatch=tiny_train_config.BATCH_SIZE,
                fused_k=2,
                overlap_k=2,
                device_replay=False,
            ),
        )
        try:
            reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            rc = cli.main(["fit", "cpu", "--json"])
            report = json.loads(capsys.readouterr().out.strip())
            # A tiny world against host RAM must fit.
            assert rc == 0
            assert report["exit"] == 0
            assert report["budget"]["total_bytes"] > 0
            assert report["budget"]["programs"] >= 3
            assert report["bytes_limit"] > report["budget"]["total_bytes"]
            categories = {r["category"] for r in report["records"]}
            assert categories == {"state", "ring", "program"}

            # An asserted tiny limit flips the verdict to over-budget.
            reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            rc = cli.main(["fit", "cpu", "--limit-gb", "0.0000001"])
            assert rc == 1
        finally:
            reset_compile_cache()


@pytest.fixture(scope="module")
def memory_smoke_run(
    tmp_path_factory, tiny_env_config, tiny_model_config, tiny_mcts_config
):
    """One tiny end-to-end training run whose ledger carries the full
    memory-observability record set (module-scoped: several tests read
    it)."""
    from alphatriangle_tpu.training import (
        LoopStatus,
        TrainingLoop,
        setup_training_components,
    )

    root = tmp_path_factory.mktemp("memory_run")
    train_cfg = TrainConfig(
        RUN_NAME="mem_smoke",
        AUTO_RESUME_LATEST=False,
        MAX_TRAINING_STEPS=8,
        SELF_PLAY_BATCH_SIZE=4,
        ROLLOUT_CHUNK_MOVES=4,
        BATCH_SIZE=8,
        BUFFER_CAPACITY=2000,
        MIN_BUFFER_SIZE_TO_TRAIN=16,
        USE_PER=True,
        PER_BETA_ANNEAL_STEPS=8,
        N_STEP_RETURNS=2,
        WORKER_UPDATE_FREQ_STEPS=2,
        CHECKPOINT_SAVE_FREQ_STEPS=4,
        MAX_EPISODE_MOVES=30,
        RANDOM_SEED=5,
    )
    # The run's live-memory accounting synthesizes bytes-in-use from
    # jax.live_arrays(): collect cycle-held garbage from earlier test
    # modules first, or their dead engines/rings inflate the observed
    # peak this fixture's 2x acceptance band is measured against.
    import gc

    gc.collect()
    pc = PersistenceConfig(ROOT_DATA_DIR=str(root), RUN_NAME="mem_smoke")
    c = setup_training_components(
        train_config=train_cfg,
        env_config=tiny_env_config,
        model_config=tiny_model_config,
        mcts_config=tiny_mcts_config,
        persistence_config=pc,
        use_tensorboard=False,
    )
    loop = TrainingLoop(c)
    status = loop.run()
    c.stats.close()
    c.checkpoints.close()
    assert status == LoopStatus.COMPLETED
    run_dir = pc.get_run_base_dir()
    records = [
        json.loads(line)
        for line in (run_dir / "metrics.jsonl").read_text().splitlines()
    ]
    return {
        "run_dir": run_dir,
        "records": records,
        "train_cfg": train_cfg,
        "root": root,
    }


class TestSmokeRunLedger:
    def test_ledger_carries_attribution_and_live_memory(
        self, memory_smoke_run
    ):
        records = memory_smoke_run["records"]
        mems = [r for r in records if r["kind"] == "memory"]
        components = {m["component"] for m in mems}
        assert "train_state" in components
        assert "replay_ring" in components
        assert any(c.startswith("program/self_play_chunk") for c in components)
        utils = [r for r in records if r["kind"] == "util"]
        assert utils
        for u in utils:
            assert isinstance(u["mem_bytes_in_use"], int)
            assert u["mem_peak_bytes_in_use"] >= u["mem_bytes_in_use"]
            assert u["mem_devices"]
        # Ring is host-resident on the CPU backend (DEVICE_REPLAY auto)
        ring = next(m for m in mems if m["component"] == "replay_ring")
        assert ring["location"] == "host"
        # Heartbeat carries the trimmed memory fields too.
        health = json.loads(
            (memory_smoke_run["run_dir"] / "health.json").read_text()
        )
        assert health["utilization"]["mem_bytes_in_use"] > 0

    def test_fit_estimate_within_2x_of_observed_peak(
        self,
        memory_smoke_run,
        tiny_env_config,
        tiny_model_config,
        tiny_mcts_config,
    ):
        """Acceptance bar: the static `cli fit` estimate for the smoke
        preset lands within 2x of the run's observed peak_bytes_in_use."""
        utils = [
            r for r in memory_smoke_run["records"] if r["kind"] == "util"
        ]
        observed = max(r["mem_peak_bytes_in_use"] for r in utils)
        report = estimate_fit(
            tiny_env_config,
            tiny_model_config,
            tiny_mcts_config,
            memory_smoke_run["train_cfg"],
            fused_k=1,
            device_replay=False,
        )
        estimate = report["budget"]["total_bytes"]
        assert estimate > 0 and observed > 0
        ratio = estimate / observed
        assert 0.5 <= ratio <= 2.0, (
            f"static estimate {estimate} vs observed peak {observed} "
            f"(ratio {ratio:.2f}) left the 2x band"
        )

    def test_cli_mem_renders_attribution_table(
        self, memory_smoke_run, capsys
    ):
        from alphatriangle_tpu.cli import main as cli_main

        rc = cli_main(
            [
                "mem",
                "mem_smoke",
                "--root-dir",
                str(memory_smoke_run["root"]),
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "train_state" in out
        assert "replay_ring" in out
        assert "program/self_play_chunk" in out
        assert "static budget" in out
        assert "observed:" in out

    def test_cli_mem_json(self, memory_smoke_run, capsys):
        from alphatriangle_tpu.cli import main as cli_main

        rc = cli_main(
            [
                "mem",
                str(memory_smoke_run["run_dir"] / "metrics.jsonl"),
                "--json",
            ]
        )
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert payload["budget"]["total_bytes"] > 0
        assert payload["observed"]["mem_bytes_in_use"] > 0

    def test_cli_mem_never_imports_jax(self, memory_smoke_run):
        """`cli mem` must attribute from artifacts alone: run it in a
        subprocess whose import machinery refuses jax outright."""
        ledger = memory_smoke_run["run_dir"] / "metrics.jsonl"
        code = (
            "import builtins, sys\n"
            "real = builtins.__import__\n"
            "def guard(name, *a, **k):\n"
            "    if name == 'jax' or name.startswith('jax.'):\n"
            "        raise AssertionError('cli mem imported ' + name)\n"
            "    return real(name, *a, **k)\n"
            "builtins.__import__ = guard\n"
            "from alphatriangle_tpu.cli import main\n"
            f"sys.exit(main(['mem', {str(ledger)!r}]))\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            cwd=str(Path(__file__).resolve().parent.parent),
            timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        assert "train_state" in proc.stdout

    def test_cli_mem_missing_run_exits_2(self, tmp_path, capsys):
        from alphatriangle_tpu.cli import main as cli_main

        rc = cli_main(
            ["mem", "no_such_run", "--root-dir", str(tmp_path)]
        )
        assert rc == 2

    def test_cli_mem_ledger_without_memory_records_exits_2(
        self, tmp_path, capsys
    ):
        from alphatriangle_tpu.cli import main as cli_main

        ledger = tmp_path / "metrics.jsonl"
        ledger.write_text(
            json.dumps({"kind": "tick", "step": 1, "means": {}}) + "\n"
        )
        assert cli_main(["mem", str(ledger)]) == 2

    def test_perf_summary_and_compare_pick_up_memory(
        self, memory_smoke_run
    ):
        from alphatriangle_tpu.telemetry.perf import load_comparable

        utils = [
            r for r in memory_smoke_run["records"] if r["kind"] == "util"
        ]
        summary = summarize_utilization(utils)
        assert summary["mem_peak_bytes_in_use"] == max(
            r["mem_peak_bytes_in_use"] for r in utils
        )
        loaded, _ = load_comparable(
            str(memory_smoke_run["run_dir"]), None
        )
        assert loaded["memory_budget_bytes"] > 0
        rows, regressions = compare_summaries(loaded, loaded)
        verdicts = {m: s for m, _, _, _, s in rows}
        assert verdicts["mem_peak_bytes_in_use"] == "ok"
        assert verdicts["memory_budget_bytes"] == "ok"
        assert regressions == []
