"""dp-sharded fused megastep (rl/megastep.py `megastep/dp<D>_t<T>_k<K>`).

PR 8 lifts the single-device gate: the whole Anakin program (rollout +
ring ingest + K learner steps) runs dp-sharded over the mesh — each
shard scatters its harvest into its ring shard, samples its stratum of
the PER batch device-locally, and the embedded learner's gradient
all-reduce keeps params bit-identical on every shard.

Fast tier: setup wiring + host-side reconciliation + the per-shard
sampling kernel (no megastep compile). Slow tier: the dp=2 in-process
end-to-end loop, and the 8-way `--xla_force_host_platform_device_count`
subprocess dryrun (tests/megastep_dp_driver.py) that also covers resume
from a single-device-mode checkpoint.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

from alphatriangle_tpu.config import (
    MeshConfig,
    PersistenceConfig,
    TrainConfig,
)
from alphatriangle_tpu.rl.sharded_device_buffer import (
    ShardedDeviceReplayBuffer,
)
from alphatriangle_tpu.training import (
    LoopStatus,
    TrainingLoop,
    setup_training_components,
)

REPO_ROOT = Path(__file__).resolve().parent.parent

DP = 2


def make_cfg(run_name: str, **kw) -> TrainConfig:
    base = dict(
        RUN_NAME=run_name,
        AUTO_RESUME_LATEST=False,
        MAX_TRAINING_STEPS=8,
        SELF_PLAY_BATCH_SIZE=4,
        ROLLOUT_CHUNK_MOVES=2,
        BATCH_SIZE=8,
        BUFFER_CAPACITY=2000,
        MIN_BUFFER_SIZE_TO_TRAIN=16,
        USE_PER=True,
        PER_BETA_ANNEAL_STEPS=8,
        N_STEP_RETURNS=2,
        WORKER_UPDATE_FREQ_STEPS=2,
        CHECKPOINT_SAVE_FREQ_STEPS=4,
        MAX_EPISODE_MOVES=30,
        RANDOM_SEED=5,
        FUSED_MEGASTEP=True,
        DEVICE_REPLAY="on",
        FUSED_LEARNER_STEPS=2,
    )
    base.update(kw)
    return TrainConfig(**base)


def build(tmp_path, cfgs, run_name="mega_dp", dp=DP, **kw):
    env_cfg, model_cfg, mcts_cfg = cfgs
    return setup_training_components(
        train_config=make_cfg(run_name, **kw),
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        mesh_config=MeshConfig(DP_SIZE=dp),
        persistence_config=PersistenceConfig(
            ROOT_DATA_DIR=str(tmp_path), RUN_NAME=run_name
        ),
        use_tensorboard=False,
    )


@pytest.fixture(scope="module")
def tiny_world_configs(tiny_env_config, tiny_model_config, tiny_mcts_config):
    return tiny_env_config, tiny_model_config, tiny_mcts_config


@pytest.fixture(scope="module")
def shared_components(tmp_path_factory, tiny_world_configs):
    """One dp=2 component build shared by the fast read-mostly tests —
    setup_training_components is the dominant cost here (several
    seconds), and the tier-1 870s budget is razor-thin. Tests that
    mutate buffer state call _reset_buffer first."""
    c = build(
        tmp_path_factory.mktemp("mega_dp_shared"),
        tiny_world_configs,
        run_name="shared",
    )
    yield c
    c.stats.close()
    c.checkpoints.close()


def _reset_buffer(buf) -> None:
    """Zero the host mirrors (trees/cursors/sizes) between tests; the
    device storage contents are irrelevant to the host-side asserts."""
    from alphatriangle_tpu.utils.sumtree import SumTree

    if buf.trees is not None:
        buf.trees = [SumTree(buf.cap_local) for _ in range(buf.dp)]
    buf._cursors[:] = 0
    buf._sizes[:] = 0
    buf._size = 0


class TestShardedWiring:
    def test_setup_builds_sharded_megastep(self, shared_components):
        c = shared_components
        buf = c.buffer
        assert isinstance(buf, ShardedDeviceReplayBuffer)
        assert c.megastep is not None and c.megastep.sharded
        assert c.megastep.dp == DP
        # Per-shard ring geometry: the global capacity splits into
        # dp local rings, each with its own trash row.
        assert buf.cap_local == buf.capacity // DP
        assert buf.stride == buf.cap_local + 1
        # All three participants share one mesh, dp-only.
        assert c.trainer.mesh is buf.mesh
        assert c.self_play.mesh is buf.mesh

    def test_warmup_gate_requires_every_shard(self, shared_components):
        # _megastep_ready: the in-program gather samples each shard's
        # stratum locally, so warmup must run until EVERY shard holds a
        # full per-shard batch — a global row count is not enough.
        c = shared_components
        _reset_buffer(c.buffer)
        loop = TrainingLoop(c)
        need = c.train_config.MIN_BUFFER_SIZE_TO_TRAIN
        assert not loop._megastep_ready(need)
        rows = _rows(need * DP, c)
        c.buffer.add_dense(**rows)
        assert loop._megastep_ready(need)
        # Starve one shard below b_local by rebuilding lopsided.
        c.buffer._sizes[0] = 0
        assert not loop._megastep_ready(need)


def _rows(n, c, seed=0):
    env = c.self_play.env
    rng = np.random.default_rng(seed)
    adim = env.action_dim
    policy = rng.random((n, adim)).astype(np.float32)
    policy /= policy.sum(axis=1, keepdims=True)
    grid_shape = jax.device_get(c.buffer.storage["grid"]).shape[1:]
    other_dim = jax.device_get(c.buffer.storage["other_features"]).shape[1]
    return {
        "grid": rng.integers(-1, 2, size=(n, *grid_shape)).astype(
            np.float32
        ),
        "other_features": rng.random((n, other_dim)).astype(np.float32),
        "policy_target": policy,
        "value_target": rng.uniform(-3, 3, n).astype(np.float32),
    }


class TestHostReconciliation:
    def test_reconcile_ingest_advances_mirrors(self, shared_components):
        buf = shared_components.buffer
        _reset_buffer(buf)
        counts = np.array([3, 5], dtype=np.int64)
        total, slots = buf.reconcile_ingest(counts, max_priority=2.5)
        assert total == 8
        assert len(buf) == 8
        np.testing.assert_array_equal(buf._sizes, counts)
        np.testing.assert_array_equal(
            buf._cursors, counts % buf.cap_local
        )
        # Slots are globally encoded, shard-major.
        np.testing.assert_array_equal(
            slots // buf.stride, np.repeat([0, 1], [3, 5])
        )
        # Every ingested row carries the sampling watermark the
        # device program used — device and host trees agree.
        for k, tree in enumerate(buf.trees):
            sz = int(counts[k])
            leaves = tree.tree[np.arange(sz) + tree._cap2]
            np.testing.assert_allclose(leaves, 2.5)
        assert buf.max_priority == pytest.approx(2.5)

    def test_reconcile_wraps_per_shard_ring(self, shared_components):
        buf = shared_components.buffer
        _reset_buffer(buf)
        cap = buf.cap_local
        buf.reconcile_ingest(
            np.array([cap - 1, 0]), max_priority=1.0
        )
        _, slots = buf.reconcile_ingest(
            np.array([3, 0]), max_priority=1.0
        )
        # 3 rows on a cap-1 cursor: one fills the ring, two wrap.
        local = slots % buf.stride
        np.testing.assert_array_equal(local, [cap - 1, 0, 1])
        assert int(buf._sizes[0]) == cap
        assert int(buf._cursors[0]) == 2


class TestSampleLocal:
    def test_per_stratified_in_range_and_weighted(
        self, shared_components
    ):
        buf = shared_components.buffer
        size, k, b_local = 32, 2, 4
        prios = np.zeros(buf.cap_local + 1, np.float32)
        prios[:size] = np.linspace(1.0, 4.0, size)
        idx, w = jax.device_get(
            buf.sample_local(
                jax.numpy.asarray(prios),
                jax.numpy.int32(size),
                k,
                b_local,
                jax.random.PRNGKey(0),
                jax.numpy.float32(0.4),
            )
        )
        assert idx.shape == (k, b_local) and w.shape == (k, b_local)
        assert (idx >= 0).all() and (idx < size).all()
        # Weights are the UNNORMALIZED (N*p)^-beta — the megastep
        # normalizes by a pmax across shards, not here.
        assert (w > 0).all()


class TestWarmFitWiring:
    def test_warm_and_fit_cover_sharded_family(
        self, tmp_path, tiny_world_configs, monkeypatch
    ):
        """`cli warm` lists the dp-sharded megastep program beside the
        single-device one (skipped-cpu on this backend, like every
        learner-embedding program) and `estimate_fit(megastep=True)`
        analyzes the sharded family with a per-device ring budget
        (cap_local, not the global capacity). Analyze implementations
        are stubbed — this pins the WIRING inside the tier-1 budget."""
        from alphatriangle_tpu.bench_config import BenchPlan
        from alphatriangle_tpu.compile_cache import reset_compile_cache
        from alphatriangle_tpu.rl.megastep import MegastepRunner
        from alphatriangle_tpu.rl.self_play import SelfPlayEngine
        from alphatriangle_tpu.rl.trainer import Trainer
        from alphatriangle_tpu.telemetry.memory import estimate_fit
        from alphatriangle_tpu.warm import warm_bench_programs

        def stub_record(program):
            return {
                "kind": "memory",
                "category": "program",
                "component": f"program/{program}",
                "program": program,
                "bytes": {"argument": 64, "output": 8, "temp": 8,
                          "generated_code": 0},
                "total": 80,
                "transient": 16,
            }

        monkeypatch.setattr(
            SelfPlayEngine,
            "analyze_chunk",
            lambda self, n=None: stub_record("self_play_chunk/t2"),
        )
        monkeypatch.setattr(
            Trainer,
            "analyze_step",
            lambda self, b=None: stub_record("learner_step/b8"),
        )
        monkeypatch.setattr(
            Trainer,
            "analyze_steps",
            lambda self, k, b=None: stub_record("learner_fused/k2"),
        )
        monkeypatch.setattr(
            MegastepRunner,
            "analyze_megastep",
            lambda self, t=None, k=None: stub_record(
                f"megastep/dp{self.dp}_t2_k2"
                if self.sharded
                else "megastep/t2_k2"
            ),
        )

        env_cfg, model_cfg, mcts_cfg = tiny_world_configs
        # dp = the process's full 8-device count: every divisibility
        # condition of the setup gate holds for this geometry.
        ndev = jax.device_count()
        train_cfg = make_cfg(
            "warm_fit_dp", SELF_PLAY_BATCH_SIZE=ndev, MAX_TRAINING_STEPS=2
        )
        plan = BenchPlan(
            env=env_cfg,
            model=model_cfg,
            mcts=mcts_cfg,
            train=train_cfg,
            scale="tiny",
            sims=mcts_cfg.max_simulations,
            sp_batch=train_cfg.SELF_PLAY_BATCH_SIZE,
            chunk=train_cfg.ROLLOUT_CHUNK_MOVES,
            lbatch=train_cfg.BATCH_SIZE,
            fused_k=2,
            overlap_k=2,
            device_replay=False,
        )
        try:
            reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            report = warm_bench_programs(
                plan, jobs=1, programs={"megastep"}
            )
            rows = {r["program"]: r["status"] for r in report["programs"]}
            assert rows == {
                "megastep/t2_k2": "skipped-cpu",
                f"megastep/dp{ndev}_t2_k2": "skipped-cpu",
            }

            fit = estimate_fit(
                env_cfg,
                model_cfg,
                mcts_cfg,
                train_cfg,
                fused_k=2,
                megastep=True,
            )
            programs = {
                str(r.get("program", ""))
                for r in fit["records"]
                if r.get("category") == "program"
            }
            assert f"megastep/dp{ndev}_t2_k2" in programs
            assert any(p.startswith("self_play_chunk") for p in programs)
            # Budget charges each device its cap_local ring slice.
            ring = next(
                r
                for r in fit["records"]
                if r.get("category") == "ring"
                and r.get("location") == "device"
            )
            assert ring["shards"] == ndev
            assert (
                fit["budget"]["replay_ring_bytes"]
                == ring["total"] // ndev
            )
        finally:
            reset_compile_cache()


@pytest.mark.slow
class TestShardedLoopEndToEnd:
    def test_dp2_one_dispatch_params_and_per(
        self, tmp_path, tiny_world_configs, monkeypatch
    ):
        monkeypatch.setenv("ALPHATRIANGLE_PEAK_TFLOPS", "1.0")
        c = build(tmp_path, tiny_world_configs, run_name="dp2_e2e")
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        assert loop.global_step == 8

        runner = c.megastep
        # ONE mesh-level dispatch per iteration; the trainer never
        # launched a standalone program.
        assert runner.dispatch_count == loop.megastep_iterations > 0
        assert c.trainer.dispatch_count == 0

        # Params bit-identical on every shard after the K-step groups
        # (the gradient all-reduce is the megastep's psum axis).
        for leaf in jax.tree_util.tree_leaves(c.trainer.state.params):
            shards = [np.asarray(s.data) for s in leaf.addressable_shards]
            assert len(shards) == DP
            for s in shards[1:]:
                np.testing.assert_array_equal(shards[0], s)

        # Per-shard PER reconciliation: each shard's device priority
        # slice matches its host SumTree mirror exactly.
        buf = c.buffer
        prios = np.asarray(runner._priorities)
        for k, tree in enumerate(buf.trees):
            sz = int(buf._sizes[k])
            assert sz > 0
            dev = prios[k * buf.stride : k * buf.stride + sz]
            host = tree.tree[np.arange(sz) + tree._cap2]
            np.testing.assert_allclose(dev, host, rtol=1e-4, atol=1e-6)

        # Ledger gauge: steady-state dispatches_per_iteration == 1.0.
        run_dir = c.persistence_config.get_run_base_dir()
        records = [
            json.loads(line)
            for line in (run_dir / "metrics.jsonl")
            .read_text()
            .splitlines()
        ]
        dpi = [
            r["dispatches_per_iteration"]
            for r in records
            if r.get("kind") == "util"
            and isinstance(
                r.get("dispatches_per_iteration"), (int, float)
            )
        ]
        assert dpi and dpi[-1] == pytest.approx(1.0)
        assert c.checkpoints.latest_step() == 8
        c.stats.close()
        c.checkpoints.close()


@pytest.mark.slow
def test_eight_way_dryrun_with_single_device_resume(tmp_path):
    """The ISSUE's acceptance dryrun: 8 virtual host-platform devices,
    resume from a single-device-mode checkpoint, one dispatch per
    iteration, identical params on all shards, per-shard PER
    reconciliation. Runs in a subprocess so it can set its own
    --xla_force_host_platform_device_count before JAX initialises."""
    proc = subprocess.run(
        [
            sys.executable,
            str(REPO_ROOT / "tests" / "megastep_dp_driver.py"),
            str(tmp_path),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=REPO_ROOT,
        env={**os.environ, "PYTHONPATH": str(REPO_ROOT)},
        timeout=540,
    )
    out = proc.stdout
    assert proc.returncode == 0, f"driver failed:\n{out}"
    for marker in (
        "BASE_STEP=4",
        "RESUME_STEP=4",
        "DISPATCH_OK",
        "PARAMS_OK",
        "PER_OK",
        "MEGA_DP_OK",
    ):
        assert marker in out, f"missing {marker}:\n{out}"

    def field(key: str) -> str:
        return next(
            line.split("=", 1)[1]
            for line in out.splitlines()
            if line.startswith(key + "=")
        )

    assert float(field("GAUGE")) == pytest.approx(1.0)
