"""Replay buffer tests (port of the reference matrix,
`tests/rl/test_buffer.py:107-416`): add / wraparound / readiness /
sampling gates / priority updates / beta annealing — against the dense
SoA buffer."""

import numpy as np
import pytest

from alphatriangle_tpu.config import TrainConfig
from alphatriangle_tpu.rl import ExperienceBuffer, SelfPlayResult

C, H, W, F, A = 1, 3, 4, 14, 12


def make_dense(n: int, seed: int = 0, value: float | None = None):
    rng = np.random.default_rng(seed)
    grid = rng.integers(-1, 2, size=(n, C, H, W)).astype(np.float32)
    other = rng.random((n, F), dtype=np.float32)
    policy = rng.random((n, A)).astype(np.float32)
    policy /= policy.sum(axis=1, keepdims=True)
    values = (
        np.full(n, value, dtype=np.float32)
        if value is not None
        else rng.random(n).astype(np.float32)
    )
    return grid, other, policy, values


def uniform_cfg(**kw) -> TrainConfig:
    base = dict(
        BATCH_SIZE=4,
        BUFFER_CAPACITY=20,
        MIN_BUFFER_SIZE_TO_TRAIN=8,
        USE_PER=False,
        MAX_TRAINING_STEPS=100,
        RUN_NAME="buf_test",
    )
    base.update(kw)
    return TrainConfig(**base)


def per_cfg(**kw) -> TrainConfig:
    return uniform_cfg(USE_PER=True, PER_BETA_ANNEAL_STEPS=100, **kw)


class TestUniform:
    def test_add_and_len(self):
        buf = ExperienceBuffer(uniform_cfg())
        buf.add_dense(*make_dense(5))
        assert len(buf) == 5
        assert not buf.is_ready()
        buf.add_dense(*make_dense(5))
        assert len(buf) == 10
        assert buf.is_ready()

    def test_capacity_wraparound(self):
        buf = ExperienceBuffer(uniform_cfg())
        idx1 = buf.add_dense(*make_dense(15, value=1.0))
        idx2 = buf.add_dense(*make_dense(15, seed=1, value=2.0))
        assert len(buf) == 20
        assert idx2[-1] == (15 + 15 - 1) % 20  # ring wrapped
        # Slots 0..9 were overwritten by the second batch.
        assert buf._storage["value_target"][idx2[-1]] == 2.0

    def test_sample_none_before_ready(self):
        buf = ExperienceBuffer(uniform_cfg())
        buf.add_dense(*make_dense(4))
        assert buf.sample(4) is None

    def test_sample_shapes_and_weights(self):
        buf = ExperienceBuffer(uniform_cfg())
        buf.add_dense(*make_dense(12))
        out = buf.sample(4)
        assert out is not None
        assert out["batch"]["grid"].shape == (4, C, H, W)
        assert out["batch"]["grid"].dtype == np.float32
        assert out["batch"]["policy_target"].shape == (4, A)
        assert out["batch"]["value_target"].shape == (4,)
        assert np.all(out["weights"] == 1.0)

    def test_sample_larger_than_size(self):
        buf = ExperienceBuffer(uniform_cfg(MIN_BUFFER_SIZE_TO_TRAIN=4))
        buf.add_dense(*make_dense(6))
        assert buf.sample(10) is None

    def test_update_priorities_noop(self):
        buf = ExperienceBuffer(uniform_cfg())
        buf.add_dense(*make_dense(10))
        buf.update_priorities(np.arange(4), np.ones(4))  # no crash


class TestParityTupleAPI:
    def test_tuple_add_without_action_dim_raises(self, random_state_type):
        buf = ExperienceBuffer(uniform_cfg())
        with pytest.raises(ValueError, match="action_dim"):
            buf.add((random_state_type, {0: 1.0}, 0.0))

    def test_add_batch_tuples(self, random_state_type):
        buf = ExperienceBuffer(uniform_cfg(), action_dim=A)
        exp = (random_state_type, {0: 0.5, 3: 0.5}, 1.25)
        buf.add_batch([exp] * 9)
        assert len(buf) == 9
        buf.add(exp)
        assert buf.is_ready()
        out = buf.sample(4)
        assert out is not None
        np.testing.assert_allclose(
            out["batch"]["policy_target"].sum(axis=1), 1.0, rtol=1e-5
        )


class TestPER:
    def test_requires_step(self):
        buf = ExperienceBuffer(per_cfg())
        buf.add_dense(*make_dense(10))
        with pytest.raises(ValueError, match="current_train_step"):
            buf.sample(4)

    def test_sample_and_weights(self):
        buf = ExperienceBuffer(per_cfg())
        buf.add_dense(*make_dense(10))
        out = buf.sample(4, current_train_step=0)
        assert out is not None
        assert out["weights"].shape == (4,)
        assert np.all(out["weights"] > 0) and np.all(out["weights"] <= 1.0)

    def test_priority_update_shifts_sampling(self):
        buf = ExperienceBuffer(per_cfg(BUFFER_CAPACITY=64, MIN_BUFFER_SIZE_TO_TRAIN=8))
        buf.add_dense(*make_dense(64))
        # Crush every priority except slot 7.
        buf.update_priorities(np.arange(64), np.full(64, 1e-6))
        buf.update_priorities(np.array([7]), np.array([100.0]))
        counts = np.zeros(64)
        for _ in range(30):
            out = buf.sample(8, current_train_step=0)
            for i in out["indices"]:
                counts[i] += 1
        assert counts[7] > counts.sum() * 0.5

    def test_beta_annealing(self):
        buf = ExperienceBuffer(per_cfg())
        assert buf.beta(0) == pytest.approx(0.4)
        assert buf.beta(50) == pytest.approx(0.7)
        assert buf.beta(100) == pytest.approx(1.0)
        assert buf.beta(10_000) == pytest.approx(1.0)

    def test_new_items_get_max_priority(self):
        buf = ExperienceBuffer(per_cfg())
        buf.add_dense(*make_dense(8))
        buf.update_priorities(np.arange(8), np.full(8, 5.0))
        max_p = buf.tree.max_priority
        buf.add_dense(*make_dense(2, seed=3))
        leaf = buf.tree.tree[buf.tree._cap2 + 8]
        assert leaf == pytest.approx(max_p)

    def test_mismatched_update_raises(self):
        buf = ExperienceBuffer(per_cfg())
        buf.add_dense(*make_dense(8))
        with pytest.raises(ValueError, match="must match"):
            buf.update_priorities(np.arange(3), np.ones(4))

    def test_nonfinite_adds_dropped(self):
        buf = ExperienceBuffer(per_cfg())
        g, o, p, v = make_dense(6)
        v[2] = np.nan
        o[4, 0] = np.inf
        g[0, 0, 0, 0] = np.nan
        buf.add_dense(g, o, p, v)
        assert len(buf) == 3


class TestPersistence:
    def test_state_roundtrip(self):
        buf = ExperienceBuffer(per_cfg())
        buf.add_dense(*make_dense(12, value=3.0))
        buf.update_priorities(np.arange(12), np.linspace(0.1, 2.0, 12))
        state = buf.get_state()

        buf2 = ExperienceBuffer(per_cfg())
        buf2.set_state(state)
        assert len(buf2) == 12
        np.testing.assert_array_equal(
            buf2._storage["value_target"][:12], buf._storage["value_target"][:12]
        )
        # Priorities survived (reference drops them; we keep them).
        np.testing.assert_allclose(
            buf2.tree.tree[buf2.tree._cap2 : buf2.tree._cap2 + 12],
            buf.tree.tree[buf.tree._cap2 : buf.tree._cap2 + 12],
        )
        out = buf2.sample(4, current_train_step=0)
        assert out is not None

    def test_smaller_snapshot_over_fuller_buffer_clears_stale_leaves(self):
        small = ExperienceBuffer(per_cfg())
        small.add_dense(*make_dense(8))
        snap = small.get_state()

        full = ExperienceBuffer(per_cfg())
        full.add_dense(*make_dense(20, seed=9))
        full.update_priorities(np.arange(20), np.full(20, 10.0))
        full.set_state(snap)
        assert len(full) == 8
        # Stale leaves zeroed: total priority reflects only the 8 slots.
        assert full.tree.total_priority == pytest.approx(
            full.tree.tree[full.tree._cap2 : full.tree._cap2 + 8].sum()
        )
        out = full.sample(4, current_train_step=0)
        assert np.all(out["indices"] < 8)
        # Max-priority watermark restored too: post-restore adds must
        # not inherit the overwritten buffer's max (10.0).
        assert full.tree.max_priority == pytest.approx(1.0)
        full.add_dense(*make_dense(2, seed=5))
        assert full.tree.tree[full.tree._cap2 + 8] == pytest.approx(1.0)

    def test_wrapped_snapshot_restores_chronologically(self):
        """A wrapped ring's snapshot is in slot order; restore must put
        the oldest entry at slot 0 so future ring writes overwrite
        oldest-first, and a capacity shrink must keep the NEWEST rows."""
        src = ExperienceBuffer(uniform_cfg())  # capacity 20
        # 28 adds with value = chronological index: ring wraps, slots
        # hold [20..27, 8..19], pos = 8.
        for i in range(28):
            src.add_dense(*make_dense(1, value=float(i)))
        snap = src.get_state()
        assert snap["pos"] == 8

        same = ExperienceBuffer(uniform_cfg())
        same.set_state(snap)
        np.testing.assert_array_equal(
            same._storage["value_target"][:20], np.arange(8, 28, dtype=np.float32)
        )
        assert same._pos == 0  # full: next write lands on the oldest (8)
        same.add_dense(*make_dense(1, value=99.0))
        assert same._storage["value_target"][0] == 99.0
        assert same._storage["value_target"][1] == 9.0  # second-oldest intact

        shrunk = ExperienceBuffer(uniform_cfg(BUFFER_CAPACITY=10))
        shrunk.set_state(snap)
        assert len(shrunk) == 10
        np.testing.assert_array_equal(
            shrunk._storage["value_target"][:10],
            np.arange(18, 28, dtype=np.float32),  # newest 10 kept
        )
        assert shrunk._pos == 0

        grown = ExperienceBuffer(uniform_cfg(BUFFER_CAPACITY=40))
        grown.set_state(snap)
        assert len(grown) == 20
        assert grown._pos == 20  # next write appends, not overwrites
        np.testing.assert_array_equal(
            grown._storage["value_target"][:20], np.arange(8, 28, dtype=np.float32)
        )

    def test_wrapped_per_snapshot_priorities_follow_rows(self):
        src = ExperienceBuffer(per_cfg())  # capacity 20
        for i in range(25):
            src.add_dense(*make_dense(1, value=float(i)))
        # Priority = value of the row in each slot, so we can track rows.
        vals = src._storage["value_target"][:20].astype(np.float64)
        src.update_priorities(np.arange(20), vals)  # p = (|v|+eps)^alpha
        snap = src.get_state()

        dst = ExperienceBuffer(per_cfg())
        dst.set_state(snap)
        leaves = dst.tree.tree[dst.tree._cap2 : dst.tree._cap2 + 20]
        expect = (
            np.abs(dst._storage["value_target"][:20].astype(np.float64))
            + dst.per_epsilon
        ) ** dst.alpha
        np.testing.assert_allclose(leaves, expect, rtol=1e-6)


class TestSelfPlayResult:
    def test_valid_rows_kept_invalid_dropped(self):
        g, o, p, v = make_dense(5)
        v[1] = np.nan
        p[3] = 0.0  # not a distribution
        res = SelfPlayResult(
            grid=g,
            other_features=o,
            policy_target=p,
            value_target=v,
            episode_scores=[1.0],
            episode_lengths=[5],
            num_episodes=1,
        )
        assert res.num_experiences == 3

    def test_row_count_mismatch_raises(self):
        g, o, p, v = make_dense(4)
        with pytest.raises(ValueError, match="row count"):
            SelfPlayResult(
                grid=g, other_features=o[:3], policy_target=p, value_target=v
            )
