"""Fused-megastep tests (rl/megastep.py; `TrainConfig.FUSED_MEGASTEP`).

The acceptance bars from the megastep issue:
- one host dispatch per steady-state iteration (counter-asserted);
- params actually update across megasteps (the donation/reload
  regression guard from the compile-cache work, extended to the
  megastep program family);
- the counters contract (global_step, episodes, buffer fill) matches
  the sync loop's, PER priorities reconcile between the device array
  and the host SumTree mirror, and the loss decreases (learning
  sanity) at the sync mode's step count;
- checkpoints/resume and telemetry (health, ledger) keep working;
- the megastep program lands in the compile cache with a `.mem.json`
  sidecar and `cli warm`/`cli fit` cover it.
"""

import json

import jax
import numpy as np
import pytest

from alphatriangle_tpu.compile_cache import (
    get_compile_cache,
    reset_compile_cache,
)
from alphatriangle_tpu.config import (
    MeshConfig,
    PersistenceConfig,
    TrainConfig,
)
from alphatriangle_tpu.training import (
    LoopStatus,
    TrainingLoop,
    run_training,
    setup_training_components,
)


@pytest.fixture(scope="module")
def tiny_world_configs(tiny_env_config, tiny_model_config, tiny_mcts_config):
    return tiny_env_config, tiny_model_config, tiny_mcts_config


@pytest.fixture(scope="module", autouse=True)
def _collect_module_garbage():
    """Free cycle-held device arrays (components <-> loop references)
    when this module finishes: test_memory's live-array accounting runs
    next alphabetically and must not see our dead engines/rings."""
    yield
    import gc

    gc.collect()


def make_cfg(run_name: str, **kw) -> TrainConfig:
    base = dict(
        RUN_NAME=run_name,
        AUTO_RESUME_LATEST=False,
        MAX_TRAINING_STEPS=8,
        SELF_PLAY_BATCH_SIZE=4,
        ROLLOUT_CHUNK_MOVES=4,
        BATCH_SIZE=8,
        BUFFER_CAPACITY=2000,
        MIN_BUFFER_SIZE_TO_TRAIN=16,
        USE_PER=True,
        PER_BETA_ANNEAL_STEPS=8,
        N_STEP_RETURNS=2,
        WORKER_UPDATE_FREQ_STEPS=2,
        CHECKPOINT_SAVE_FREQ_STEPS=4,
        MAX_EPISODE_MOVES=30,
        RANDOM_SEED=5,
        FUSED_MEGASTEP=True,
        DEVICE_REPLAY="on",
        FUSED_LEARNER_STEPS=2,
    )
    base.update(kw)
    return TrainConfig(**base)


def build(tmp_path, cfgs, run_name="mega_run", mcts_kw=None, **kw):
    env_cfg, model_cfg, mcts_cfg = cfgs
    if mcts_kw:
        mcts_cfg = mcts_cfg.model_copy(update=mcts_kw)
    tc = make_cfg(run_name, **kw)
    pc = PersistenceConfig(ROOT_DATA_DIR=str(tmp_path), RUN_NAME=run_name)
    return setup_training_components(
        train_config=tc,
        env_config=env_cfg,
        model_config=model_cfg,
        mcts_config=mcts_cfg,
        # The megastep (like the single-device ring it drives) lives on
        # ONE chip; the harness exposes 8 virtual CPU devices.
        mesh_config=MeshConfig(DP_SIZE=1),
        persistence_config=pc,
        use_tensorboard=False,
    )


def _priorities_sides(c):
    """(device priority array, host SumTree mirror leaves) for the
    first `size` ring slots."""
    runner = c.megastep
    tree = c.buffer.tree
    size = len(c.buffer)
    dev = np.asarray(runner._priorities)[:size]
    host = tree.tree[np.arange(size) + tree._cap2]
    return dev, host


class TestConfigValidation:
    def test_megastep_excludes_async(self):
        with pytest.raises(ValueError, match="mutually exclusive"):
            make_cfg("bad", ASYNC_ROLLOUTS=True)

    def test_megastep_needs_device_replay(self):
        with pytest.raises(ValueError, match="device-resident replay"):
            make_cfg("bad", DEVICE_REPLAY="off")

    def test_setup_rejects_non_divisible_dp_mesh(
        self, tmp_path, tiny_world_configs
    ):
        # dp-sharded megastep meshes are accepted now, but only when
        # the ring / batch / lane geometry divides evenly: dp=8 with
        # SELF_PLAY_BATCH_SIZE=4 leaves the rollout lanes unshardable.
        env_cfg, model_cfg, mcts_cfg = tiny_world_configs
        with pytest.raises(Exception, match="divisible by dp"):
            setup_training_components(
                train_config=make_cfg("multi_mesh"),
                env_config=env_cfg,
                model_config=model_cfg,
                mcts_config=mcts_cfg,
                mesh_config=MeshConfig(DP_SIZE=8),
                persistence_config=PersistenceConfig(
                    ROOT_DATA_DIR=str(tmp_path), RUN_NAME="multi_mesh"
                ),
                use_tensorboard=False,
            )


class TestMegastepLoop:
    def test_end_to_end_one_dispatch_per_iteration(
        self, tmp_path, tiny_world_configs, monkeypatch
    ):
        monkeypatch.setenv("ALPHATRIANGLE_PEAK_TFLOPS", "1.0")
        # 2-move chunks keep the fused program's scan short (tier-1
        # compile budget); the loop semantics are chunk-length-free.
        # Every Pallas backend is enabled (interpret mode on CPU), so
        # the one-dispatch contract is asserted with the full kernel
        # library inside the fused program (ops/, docs/KERNELS.md).
        c = build(
            tmp_path,
            tiny_world_configs,
            ROLLOUT_CHUNK_MOVES=2,
            PER_SAMPLE_BACKEND="pallas",
            mcts_kw={"descent_gather": "pallas", "backup_update": "pallas"},
        )
        params0 = jax.device_get(c.trainer.state.params)
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        # Counters contract, same as the sync loop's.
        assert loop.global_step == 8
        assert loop.episodes_played > 0
        assert len(c.buffer) > 0
        assert loop.experiences_added > 0

        # THE acceptance bar: steady state makes exactly ONE device
        # dispatch per iteration — the megastep program itself. The
        # trainer never dispatched on its own; engine/ring dispatches
        # happened only as warmup pairs (rollout + ingest).
        runner = c.megastep
        assert loop.megastep_iterations > 0
        assert runner.dispatch_count == loop.megastep_iterations
        assert c.trainer.dispatch_count == 0
        assert c.self_play.dispatch_count == c.buffer.dispatch_count

        # Donation/reload regression guard extended to the megastep:
        # params must actually change across megasteps.
        params1 = jax.device_get(c.trainer.state.params)
        leaves0 = jax.tree_util.tree_leaves(params0)
        leaves1 = jax.tree_util.tree_leaves(params1)
        assert any(
            not np.allclose(a, b) for a, b in zip(leaves0, leaves1)
        ), "megastep did not update params (donation regression)"

        # PER reconciliation: the device priority array and the host
        # SumTree mirror agree row for row (float32 vs float64 only).
        dev, host = _priorities_sides(c)
        assert dev.size > 0
        np.testing.assert_allclose(dev, host, rtol=1e-4, atol=1e-6)

        # Weight sync cadence (K=2 crossing freq=2 every megastep).
        assert loop.weight_updates == 4
        # Checkpoints: cadence (step 4) + final (step 8).
        assert c.checkpoints.latest_step() == 8

        # Telemetry keeps working: ledger util records carry the
        # dispatches-per-iteration gauge, converged to 1.0 in steady
        # state; health heartbeat exists.
        run_dir = c.persistence_config.get_run_base_dir()
        records = [
            json.loads(line)
            for line in (run_dir / "metrics.jsonl").read_text().splitlines()
        ]
        utils = [r for r in records if r.get("kind") == "util"]
        assert utils
        dpi = [
            r["dispatches_per_iteration"]
            for r in utils
            if isinstance(
                r.get("dispatches_per_iteration"), (int, float)
            )
        ]
        assert dpi, "no dispatches_per_iteration in util records"
        assert dpi[-1] == pytest.approx(1.0)
        assert (run_dir / "health.json").exists()
        c.stats.close()
        c.checkpoints.close()

    @pytest.mark.slow
    def test_one_dispatch_holds_with_tree_reuse(
        self, tmp_path, tiny_world_configs, monkeypatch
    ):
        """Subtree reuse rides INSIDE the fused program: with
        tree_reuse on, steady state is still exactly one device
        dispatch per iteration (the promotion never becomes its own
        dispatch) and the loop's reused-visit counter proves the
        carried trees were actually consumed. Marked slow (a second
        full megastep compile); the in-program reuse accumulation is
        tier-1-covered at engine level and the dispatch accounting by
        the fresh-root one-dispatch test above."""
        monkeypatch.setenv("ALPHATRIANGLE_PEAK_TFLOPS", "1.0")
        c = build(
            tmp_path,
            tiny_world_configs,
            run_name="mega_reuse",
            MAX_TRAINING_STEPS=4,
            ROLLOUT_CHUNK_MOVES=2,
            mcts_kw={"tree_reuse": True},
        )
        loop = TrainingLoop(c)
        status = loop.run()
        assert status == LoopStatus.COMPLETED
        runner = c.megastep
        assert loop.megastep_iterations > 0
        assert runner.dispatch_count == loop.megastep_iterations
        assert c.trainer.dispatch_count == 0
        assert loop.total_reused_visits > 0
        c.stats.close()
        c.checkpoints.close()

    @pytest.mark.slow
    def test_counters_contract_matches_sync(
        self, tmp_path, tiny_world_configs
    ):
        """Same seeds, same step budget: megastep and sync modes both
        complete the run with the same counters contract (global_step,
        episodes played, buffer fill). Marked slow (two full component
        builds + loop runs); the megastep side of the contract is
        tier-1-asserted by the end-to-end test above against the same
        numbers the sync-mode tier-1 test pins."""
        steps = 8
        c_sync = build(
            tmp_path,
            tiny_world_configs,
            run_name="contract_sync",
            FUSED_MEGASTEP=False,
            LEARNER_STEPS_PER_ROLLOUT=2,
            MAX_TRAINING_STEPS=steps,
            PER_BETA_ANNEAL_STEPS=steps,
        )
        loop_sync = TrainingLoop(c_sync)
        assert loop_sync.run() == LoopStatus.COMPLETED
        c_sync.stats.close()
        c_sync.checkpoints.close()

        c_mega = build(
            tmp_path,
            tiny_world_configs,
            run_name="contract_mega",
            MAX_TRAINING_STEPS=steps,
            PER_BETA_ANNEAL_STEPS=steps,
        )
        loop_mega = TrainingLoop(c_mega)
        assert loop_mega.run() == LoopStatus.COMPLETED
        c_mega.stats.close()
        c_mega.checkpoints.close()

        # Same counters contract at identical seeds/budget.
        assert loop_mega.global_step == loop_sync.global_step == steps
        assert loop_mega.episodes_played > 0
        assert loop_sync.episodes_played > 0
        assert len(c_mega.buffer) > 0 and len(c_sync.buffer) > 0
        # PER beta annealed on the same learner-step clock.
        assert c_mega.buffer.beta(steps) == c_sync.buffer.beta(steps)

    @pytest.mark.slow
    def test_learning_sanity_loss_decreases(self, tiny_world_configs):
        """The megastep's learner actually learns: against a FIXED ring
        of synthetic targets (stationary distribution — the live loop's
        loss is a moving-target signal in every mode), repeated
        megasteps must drive the loss down. Marked slow — the tier-1
        end-to-end test already pins that params update; this adds the
        loss-decrease bar on stationary data."""
        from alphatriangle_tpu.env.engine import TriangleEnv
        from alphatriangle_tpu.features.core import get_feature_extractor
        from alphatriangle_tpu.nn.network import NeuralNetwork
        from alphatriangle_tpu.rl import (
            MegastepRunner,
            SelfPlayEngine,
            Trainer,
        )
        from alphatriangle_tpu.rl.device_buffer import DeviceReplayBuffer

        env_cfg, model_cfg, mcts_cfg = tiny_world_configs
        tc = make_cfg(
            "learning_probe",
            MAX_TRAINING_STEPS=100,
            ROLLOUT_CHUNK_MOVES=2,
            BATCH_SIZE=16,
            LEARNING_RATE=3e-3,
        )
        env = TriangleEnv(env_cfg)
        extractor = get_feature_extractor(env, model_cfg)
        net = NeuralNetwork(model_cfg, env_cfg, seed=0)
        engine = SelfPlayEngine(env, extractor, net, mcts_cfg, tc, seed=0)
        trainer = Trainer(net, tc)
        buf = DeviceReplayBuffer(
            tc,
            grid_shape=(
                model_cfg.GRID_INPUT_CHANNELS,
                env_cfg.ROWS,
                env_cfg.COLS,
            ),
            other_dim=extractor.other_dim,
            action_dim=env_cfg.action_dim,
        )
        rng = np.random.default_rng(0)
        n = 512  # dominates the trickle of live rollout rows
        policy = rng.random((n, env_cfg.action_dim)).astype(np.float32)
        policy /= policy.sum(axis=1, keepdims=True)
        buf.add_dense(
            rng.integers(
                -1, 2, size=(n, model_cfg.GRID_INPUT_CHANNELS,
                             env_cfg.ROWS, env_cfg.COLS)
            ).astype(np.float32),
            rng.random((n, extractor.other_dim)).astype(np.float32),
            policy,
            rng.uniform(-2, 2, n).astype(np.float32),
        )
        runner = MegastepRunner(engine, trainer, buf, tc)
        losses = []
        for _ in range(12):
            outs, _added = runner.run_megastep(2, 2)
            losses.extend(m["total_loss"] for m, _td in outs)
        early = float(np.mean(losses[:4]))
        late = float(np.mean(losses[-4:]))
        assert late < early, (
            f"megastep loss did not decrease ({early:.4f} -> {late:.4f})"
        )

    @pytest.mark.slow
    def test_run_training_and_resume(self, tmp_path, tiny_world_configs):
        """Checkpoint + resume work in megastep mode (run, 'kill',
        rerun with a longer horizon -> continues from the saved step).
        Marked slow (two full run_training sessions); the sync-mode
        resume contract is tier-1-covered in test_training_loop and the
        megastep checkpoint cadence in the end-to-end test above."""
        env_cfg, model_cfg, mcts_cfg = tiny_world_configs
        pc = PersistenceConfig(
            ROOT_DATA_DIR=str(tmp_path), RUN_NAME="mega_resume"
        )
        tc = make_cfg(
            "mega_resume", MAX_TRAINING_STEPS=4, CHECKPOINT_SAVE_FREQ_STEPS=2
        )
        rc = run_training(
            train_config=tc,
            env_config=env_cfg,
            model_config=model_cfg,
            mcts_config=mcts_cfg,
            mesh_config=MeshConfig(DP_SIZE=1),
            persistence_config=pc,
            use_tensorboard=False,
            log_level="WARNING",
        )
        assert rc == 0
        tc2 = make_cfg(
            "mega_resume", MAX_TRAINING_STEPS=8, CHECKPOINT_SAVE_FREQ_STEPS=2
        )
        rc = run_training(
            train_config=tc2,
            env_config=env_cfg,
            model_config=model_cfg,
            mcts_config=mcts_cfg,
            mesh_config=MeshConfig(DP_SIZE=1),
            persistence_config=pc,
            use_tensorboard=False,
            log_level="WARNING",
        )
        assert rc == 0
        from alphatriangle_tpu.stats import CheckpointManager

        mgr = CheckpointManager(pc)
        assert mgr.latest_step() == 8


class TestMegastepCompileCache:
    def _runner(self, cfgs, train_cfg):
        from alphatriangle_tpu.env.engine import TriangleEnv
        from alphatriangle_tpu.features.core import get_feature_extractor
        from alphatriangle_tpu.nn.network import NeuralNetwork
        from alphatriangle_tpu.rl import MegastepRunner, SelfPlayEngine, Trainer
        from alphatriangle_tpu.rl.device_buffer import DeviceReplayBuffer

        env_cfg, model_cfg, mcts_cfg = cfgs
        env = TriangleEnv(env_cfg)
        extractor = get_feature_extractor(env, model_cfg)
        net = NeuralNetwork(model_cfg, env_cfg, seed=0)
        engine = SelfPlayEngine(
            env, extractor, net, mcts_cfg, train_cfg, seed=0
        )
        trainer = Trainer(net, train_cfg)
        buffer = DeviceReplayBuffer(
            train_cfg,
            grid_shape=(
                model_cfg.GRID_INPUT_CHANNELS,
                env_cfg.ROWS,
                env_cfg.COLS,
            ),
            other_dim=extractor.other_dim,
            action_dim=env_cfg.action_dim,
        )
        return MegastepRunner(engine, trainer, buffer, train_cfg)

    @pytest.mark.slow
    def test_analyze_registers_record_and_sidecar(
        self, tmp_path, tiny_world_configs
    ):
        """The megastep program lands in the compile cache's memory
        registry with a `.mem.json` sidecar — on CPU too, where the
        executable itself is cpu_aot-bypassed. Marked slow (a real
        megastep compile); the fit/warm WIRING stays tier-1 below."""
        train_cfg = make_cfg("cache_probe", MAX_TRAINING_STEPS=2)
        try:
            cache = reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            runner = self._runner(tiny_world_configs, train_cfg)
            rec = runner.analyze_megastep(2, 1)
            assert rec is not None
            assert rec["program"] == "megastep/t2_k1"
            assert any(
                r.get("program") == "megastep/t2_k1"
                for r in cache.memory_summary()
            )
            sidecars = list((tmp_path / "aot").glob("megastep*.mem.json"))
            assert len(sidecars) == 1
            assert (
                json.loads(sidecars[0].read_text())["program"]
                == "megastep/t2_k1"
            )
        finally:
            reset_compile_cache()

    def test_cli_warm_and_fit_cover_megastep(
        self, tmp_path, tiny_world_configs, monkeypatch
    ):
        """`cli warm` lists the megastep program (skipped-cpu on the
        CPU backend, like the learner family it embeds) and `cli fit`'s
        estimator includes it in its analysis targets. The analyze
        implementations are stubbed here (their real compile/record
        path is covered by the sidecar test above) — this test pins the
        WIRING, inside the tier-1 compile budget."""
        from alphatriangle_tpu.bench_config import BenchPlan
        from alphatriangle_tpu.rl.megastep import MegastepRunner
        from alphatriangle_tpu.rl.self_play import SelfPlayEngine
        from alphatriangle_tpu.rl.trainer import Trainer
        from alphatriangle_tpu.telemetry.memory import estimate_fit
        from alphatriangle_tpu.warm import warm_bench_programs

        def stub_record(program):
            return {
                "kind": "memory",
                "category": "program",
                "component": f"program/{program}",
                "program": program,
                "bytes": {"argument": 64, "output": 8, "temp": 8,
                          "generated_code": 0},
                "total": 80,
                "transient": 16,
            }

        monkeypatch.setattr(
            SelfPlayEngine,
            "analyze_chunk",
            lambda self, n=None: stub_record("self_play_chunk/t4"),
        )
        monkeypatch.setattr(
            Trainer,
            "analyze_step",
            lambda self, b=None: stub_record("learner_step/b8"),
        )
        monkeypatch.setattr(
            Trainer,
            "analyze_steps",
            lambda self, k, b=None: stub_record("learner_fused/k2"),
        )
        monkeypatch.setattr(
            MegastepRunner,
            "analyze_megastep",
            lambda self, t=None, k=None: stub_record("megastep/t4_k2"),
        )

        env_cfg, model_cfg, mcts_cfg = tiny_world_configs
        train_cfg = make_cfg("warm_fit_probe", MAX_TRAINING_STEPS=2)
        plan = BenchPlan(
            env=env_cfg,
            model=model_cfg,
            mcts=mcts_cfg,
            train=train_cfg,
            scale="tiny",
            sims=mcts_cfg.max_simulations,
            sp_batch=train_cfg.SELF_PLAY_BATCH_SIZE,
            chunk=train_cfg.ROLLOUT_CHUNK_MOVES,
            lbatch=train_cfg.BATCH_SIZE,
            fused_k=2,
            overlap_k=2,
            device_replay=False,
        )
        try:
            reset_compile_cache(cache_dir=str(tmp_path / "aot"))
            report = warm_bench_programs(
                plan, jobs=1, programs={"megastep"}
            )
            rows = {r["program"]: r["status"] for r in report["programs"]}
            assert rows == {"megastep/t4_k2": "skipped-cpu"}

            fit = estimate_fit(
                env_cfg,
                model_cfg,
                mcts_cfg,
                train_cfg,
                fused_k=2,
                megastep=True,
            )
            programs = {
                str(r.get("program", ""))
                for r in fit["records"]
                if r.get("category") == "program"
            }
            assert "megastep/t4_k2" in programs
            # The pre-megastep targets are still analyzed too.
            assert any(p.startswith("self_play_chunk") for p in programs)
        finally:
            reset_compile_cache()
