"""Profiling subsystem: device traces + per-phase host timers.

TPU-native equivalent of the reference's worker profiling
(`alphatriangle/rl/self_play/worker.py:99-104,549-566` cProfile dumps +
`time.monotonic()` span logging) and its offline analyzer
(`alphatriangle/analyze_profiles.py:41-78`):

- `jax.profiler` trace of a bounded window of loop iterations (the
  XLA/TPU story the reference's cProfile cannot see) written to
  `runs/<run>/profile_data/`, viewable in TensorBoard's profile plugin.
- `PhaseTimers`: per-phase wall-clock accumulators (rollout / sample /
  train / checkpoint) kept for the WHOLE run, exported as metrics each
  stats tick and dumped to `phase_timers.json` at exit.
- `analyze_profile_dir`: prints a per-phase summary table from the
  dump, replacing the reference's pstats top-N listing.
"""

import json
import logging
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from pathlib import Path

logger = logging.getLogger(__name__)


class PhaseTimers:
    """Accumulates wall-clock seconds per named phase.

    Thread-safe: multiple rollout-producer threads time the same
    "rollout" phase concurrently (training/loop.py), so the
    accumulation is locked (a bare `dict[k] += dt` would lose
    increments across interleaved read-modify-writes).
    """

    def __init__(self) -> None:
        self._total: dict[str, float] = defaultdict(float)
        self._count: dict[str, int] = defaultdict(int)
        self._lock = threading.Lock()

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._total[name] += dt
                self._count[name] += 1

    def metrics(self) -> dict[str, float]:
        """Mean milliseconds per phase, for the stats pipeline."""
        with self._lock:  # keys can be inserted by producer threads
            totals = dict(self._total)
            counts = dict(self._count)
        return {
            f"Profile/{name}_ms": 1000.0 * totals[name] / counts[name]
            for name in totals
            if counts[name]
        }

    def summary(self) -> dict[str, dict[str, float]]:
        with self._lock:
            totals = dict(self._total)
            counts = dict(self._count)
        return {
            name: {
                "total_seconds": totals[name],
                "count": counts[name],
                "mean_ms": 1000.0 * totals[name] / max(counts[name], 1),
            }
            for name in sorted(totals)
        }

    def dump(self, path: Path) -> None:
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(self.summary(), indent=2))


class ProfileSession:
    """Owns one run's profiling: a bounded device-trace window + timers.

    The trace covers iterations [trace_start, trace_stop) — after the
    first iteration so compilation doesn't dominate, and bounded so the
    trace stays a viewable size (the reference bounds its cProfile per
    episode for the same reason, `worker.py:172-173`).

    When a `tracer` (telemetry.SpanTracer) is attached, every `phase`
    also records an individual begin/end span — the per-occurrence
    timeline next to these whole-run means.
    """

    def __init__(
        self,
        enabled: bool,
        profile_dir: Path,
        trace_start: int = 1,
        trace_stop: int = 3,
        tracer=None,
    ) -> None:
        if trace_stop <= trace_start:
            # A window that never closes would silently trace the whole
            # run into an unviewably large dump.
            raise ValueError(
                f"trace_stop={trace_stop} must be > trace_start="
                f"{trace_start}"
            )
        self.enabled = enabled
        self.profile_dir = Path(profile_dir)
        self.timers = PhaseTimers()
        self.tracer = tracer
        self._trace_start = trace_start
        self._trace_stop = trace_stop
        self._tracing = False

    @contextmanager
    def phase(self, name: str):
        with self.timers.phase(name):
            if self.tracer is not None:
                with self.tracer.span(name):
                    yield
            else:
                yield

    def on_iteration(self, iteration: int) -> None:
        """Called at the top of each loop iteration."""
        if not self.enabled:
            return
        if iteration == self._trace_start and not self._tracing:
            import jax

            self.profile_dir.mkdir(parents=True, exist_ok=True)
            logger.info(
                "Profiling: starting jax.profiler trace into %s "
                "(iterations %d-%d).",
                self.profile_dir,
                self._trace_start,
                self._trace_stop - 1,
            )
            jax.profiler.start_trace(str(self.profile_dir))
            self._tracing = True
        elif iteration >= self._trace_stop and self._tracing:
            self._stop_trace()

    def _stop_trace(self) -> None:
        import jax

        # Cleared first: a failing stop_trace must not leave the session
        # retrying forever (and close() must still dump the timers).
        self._tracing = False
        jax.profiler.stop_trace()
        logger.info("Profiling: device trace written to %s.", self.profile_dir)

    def close(self) -> None:
        if self._tracing:
            try:
                self._stop_trace()
            except Exception:
                logger.exception(
                    "jax.profiler.stop_trace failed; dumping phase "
                    "timers anyway."
                )
        if self.enabled:
            self.timers.dump(self.profile_dir / "phase_timers.json")


def analyze_profile_dir(profile_dir: str, top: int = 20) -> int:
    """Print a per-phase summary of a profile run (CLI `analyze`)."""
    root = Path(profile_dir)
    dump = root / "phase_timers.json"
    if dump.exists():
        summary = json.loads(dump.read_text())
        rows = sorted(
            summary.items(),
            key=lambda kv: kv[1]["total_seconds"],
            reverse=True,
        )[:top]
        width = max((len(name) for name, _ in rows), default=5)
        print(f"{'phase':<{width}}  {'total s':>9}  {'count':>7}  {'mean ms':>9}")
        for name, s in rows:
            print(
                f"{name:<{width}}  {s['total_seconds']:>9.2f}  "
                f"{s['count']:>7d}  {s['mean_ms']:>9.2f}"
            )
    else:
        print(f"No phase_timers.json in {root}.")

    traces = sorted(root.glob("**/*.xplane.pb"))
    if traces:
        print(f"\n{len(traces)} device trace(s):")
        for t in traces[:top]:
            print(f"  {t}")
            summarize_xplane_trace(t, top=top)
        print(
            "View with: tensorboard --logdir "
            f"{root} (PROFILE tab)"
        )
    elif not dump.exists():
        return 1
    return 0


def summarize_xplane_trace(path: Path, top: int = 20) -> None:
    """Top ops per plane of a jax.profiler xplane trace, in-terminal.

    The image's tensorboard profile plugin can't load this TF build
    (pywrap converter mismatch), so aggregate the raw XSpace protobuf
    directly: per plane (device core / host), sum event durations by op
    name. This is the table that says where self-play MFU actually goes
    (network matmuls vs tree-op gathers vs dispatch gaps) — the bench's
    BENCH_PROFILE section and the sweep's flagship_profile row feed it.
    Gracefully degrades when the TF tsl protos aren't importable.
    """
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception as exc:
        print(f"  (xplane summary unavailable: {exc})")
        return
    xs = xplane_pb2.XSpace()
    try:
        xs.ParseFromString(path.read_bytes())
    except Exception as exc:
        print(f"  (unreadable trace: {exc})")
        return
    for plane in xs.planes:
        meta = {m.id: m.name for m in plane.event_metadata.values()}
        # Aggregate PER LINE: a device plane carries hierarchical lines
        # ("XLA Modules" spans everything its "XLA Ops" line itemizes),
        # so summing across lines would double-count and crown the
        # module name as the top "op".
        for line in plane.lines:
            if not line.events:
                continue
            total_ps: dict[str, int] = defaultdict(int)
            count: dict[str, int] = defaultdict(int)
            for ev in line.events:
                name = meta.get(ev.metadata_id, f"op#{ev.metadata_id}")
                total_ps[name] += ev.duration_ps
                count[name] += 1
            grand_ps = sum(total_ps.values())
            rows = sorted(
                total_ps.items(), key=lambda kv: kv[1], reverse=True
            )
            line_name = line.name or f"line#{line.id}"
            print(
                f"\n  plane {plane.name} / {line_name}: "
                f"{len(line.events)} events, {grand_ps / 1e12:.3f}s "
                "summed op time"
            )
            print(f"    {'op':<52} {'total ms':>10} {'count':>8} {'%':>6}")
            for name, ps in rows[:top]:
                pct = 100.0 * ps / max(grand_ps, 1)
                label = name if len(name) <= 52 else name[:49] + "..."
                print(
                    f"    {label:<52} {ps / 1e9:>10.2f} "
                    f"{count[name]:>8d} {pct:>5.1f}%"
                )
